"""The five BASELINE.md benchmark configs, end to end.

Usage:
    python benches/run_all.py            # run everything, update BENCH.md
    python benches/run_all.py 1 4       # run selected configs

Configs (BASELINE.md "Targets"):
  1. 4-replica in-process net, f=1, 100 heights — the reference-equivalent
     pure-host baseline (unsigned, NullVerifier trust model).
  2. 16 replicas, 1k heights, round-robin scheduler.
  3. 64 replicas, adversarial mq reorder + timer timeouts (multi-round).
  4. 256 validators, Ed25519 batch-verify offload on the TPU: sustained
     device votes/s and the per-round (2 x 256^2 votes) verify latency,
     plus projected heights/s at 10k-height scale.
  5. 256 validators + Shamir k-of-n payload reconstruction per committed
     block on the TPU kernels.

Every config prints one JSON line; the suite is deterministic (seeded)
except for wall-clock rates. Caps vs the BASELINE config text (e.g. config
3 runs 20 heights, not unbounded) are stated in the JSON — nothing is
silently truncated.
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _sim_metrics(sim, res, wall: float) -> dict:
    snap = sim.tracer.snapshot()
    lat = snap["histograms"].get("replica.height.latency", {})
    rounds = snap["histograms"].get("replica.commit.rounds", {})
    return {
        "completed": res.completed,
        "steps": res.steps,
        "wall_s": round(wall, 3),
        "msgs_per_s": round(res.steps / wall, 1) if wall > 0 else None,
        "virtual_time": round(res.virtual_time, 3),
        "p50_height_latency_virtual": round(lat.get("p50", 0.0), 6),
        "mean_rounds_per_height": round(rounds.get("mean", 1.0), 3),
    }


def config_1() -> dict:
    from hyperdrive_tpu.harness import Simulation

    t0 = time.perf_counter()
    sim = Simulation(n=4, target_height=100, seed=1001, timeout=20.0, delivery_cost=0.001)
    res = sim.run()
    wall = time.perf_counter() - t0
    res.assert_safety()
    return {
        "config": "1: 4 replicas, f=1, 100 heights, pure-host",
        **_sim_metrics(sim, res, wall),
    }


def config_2() -> dict:
    from hyperdrive_tpu.harness import Simulation

    t0 = time.perf_counter()
    sim = Simulation(n=16, target_height=1000, seed=1002, timeout=20.0, delivery_cost=0.001)
    res = sim.run(max_steps=5_000_000)
    wall = time.perf_counter() - t0
    res.assert_safety()
    return {
        "config": "2: 16 replicas, f=5, 1k heights, round-robin",
        **_sim_metrics(sim, res, wall),
    }


def config_3() -> dict:
    from hyperdrive_tpu.harness import Simulation

    heights = 20
    # Bare quorum online (f = 21 offline). Replicas 1..21 are the offline
    # set: with round-robin proposer = (h + r) % 64, most heights' round-0
    # proposer is offline, so heights genuinely span multiple rounds
    # through propose timeouts, under adversarial reorder.
    offline = set(range(1, 22))
    t0 = time.perf_counter()
    sim = Simulation(
        n=64, target_height=heights, seed=1003, reorder=True, offline=offline,
        timeout=20.0, delivery_cost=0.001,
    )
    res = sim.run(max_steps=5_000_000)
    wall = time.perf_counter() - t0
    res.assert_safety()
    return {
        "config": "3: 64 replicas, adversarial reorder + timeouts (2f+1 online)",
        "cap": f"{heights} heights (BASELINE text is open-ended)",
        **_sim_metrics(sim, res, wall),
    }


def config_4() -> dict:
    import numpy as np
    import jax
    import jax.numpy as jnp

    from hyperdrive_tpu.crypto import ed25519 as host_ed
    from hyperdrive_tpu.crypto.keys import KeyRing
    from hyperdrive_tpu.messages import Prevote
    from hyperdrive_tpu.ops.ed25519_jax import Ed25519BatchHost, make_verify_fn
    from hyperdrive_tpu.ops.tally import pack_values, quorum_flags, tally_counts

    n_val, rounds = 256, 64
    batch = n_val * rounds

    ring = KeyRing.deterministic(n_val, namespace=b"bench4")
    value = b"\x2a" * 32
    base = []
    for v in range(n_val):
        pv = Prevote(height=1, round=0, value=value, sender=ring[v].public)
        d = pv.digest()
        base.append((ring[v].public, d, host_ed.sign(ring[v].seed, d)))
    items = base * rounds

    host = Ed25519BatchHost(buckets=(batch,))
    t0 = time.perf_counter()
    arrays, prevalid, _ = host.pack(items)
    pack_s = time.perf_counter() - t0
    assert prevalid.all()

    fn = make_verify_fn(jit=True)
    dev = tuple(jnp.asarray(a) for a in arrays)
    assert bool(np.asarray(fn(*dev)).all())  # compile + warm
    # block_until_ready is unreliable over the axon tunnel; time the
    # in-order device stream and materialize the LAST result inside the
    # timed region (TPU executes enqueued programs in order, so the final
    # transfer bounds the whole pipeline).
    iters = 8
    t0 = time.perf_counter()
    outs = [fn(*dev) for _ in range(iters)]
    final = np.asarray(outs[-1])  # materialization = the completion barrier
    dt = time.perf_counter() - t0
    if not bool(final.all()):
        raise RuntimeError("verification kernel rejected valid signatures")
    votes_per_s = batch * iters / dt

    # Per-round latency: one height of vote traffic for one replica =
    # 2 phases x 256 votes = 512 signatures, verified as one small launch.
    round_items = base * 2
    host_small = Ed25519BatchHost(buckets=(512,))
    arrays_r, pv_r, _ = host_small.pack(round_items)
    dev_r = tuple(jnp.asarray(a) for a in arrays_r)
    _ = np.asarray(fn(*dev_r))  # compile + warm
    t0 = time.perf_counter()
    for _ in range(16):
        ok_r = np.asarray(fn(*dev_r))  # per-launch: full round trip
    round_latency = (time.perf_counter() - t0) / 16

    return {
        "config": "4: 256 validators, Ed25519 TPU batch-verify offload",
        "device": str(jax.devices()[0]),
        "votes_per_s_device": round(votes_per_s, 1),
        "host_pack_s_per_16k": round(pack_s, 3),
        "host_pack_sigs_per_s": round(batch / pack_s, 1),
        "round_verify_latency_s": round(round_latency, 5),
        "projected_heights_per_s": round(votes_per_s / (2 * n_val), 2),
        "target_votes_per_s": 50_000.0,
        "vs_target": round(votes_per_s / 50_000.0, 3),
        "note": "10k-height figure projected from sustained votes/s; "
        "full 10k-height sim is host-state-machine-bound",
    }


def config_5() -> dict:
    import secrets as pysecrets

    from hyperdrive_tpu.crypto import shamir as host_shamir
    from hyperdrive_tpu.ops.shamir import BatchReconstructor

    n, f = 256, 85
    k = 2 * f + 1  # reconstruction quorum
    payload = pysecrets.token_bytes(31 * 64)  # 64 blocks per committed value

    blocks = host_shamir.split_payload(payload, k, n, tag=b"bench5")
    subset = [shares[:k] for shares in blocks]

    rec = BatchReconstructor()
    out = rec.reconstruct_payload_shares(subset)  # compile + correctness
    assert out == payload

    iters = 10
    t0 = time.perf_counter()
    for _ in range(iters):
        out = rec.reconstruct_payload_shares(subset)
    dt = time.perf_counter() - t0
    blocks_per_s = len(blocks) * iters / dt
    return {
        "config": "5: 256 validators, Shamir 171-of-256 payload reconstruction",
        "k": k,
        "n": n,
        "blocks": len(blocks),
        "blocks_per_s": round(blocks_per_s, 1),
        "payload_bytes_per_s": round(blocks_per_s * host_shamir.BLOCK_BYTES, 1),
        "per_commit_latency_s": round(dt / iters, 5),
    }


CONFIGS = {1: config_1, 2: config_2, 3: config_3, 4: config_4, 5: config_5}


def main():
    which = [int(a) for a in sys.argv[1:]] or sorted(CONFIGS)
    results = []
    for i in which:
        r = CONFIGS[i]()
        results.append(r)
        print(json.dumps(r))
    if which == sorted(CONFIGS):
        write_bench_md(results)


def write_bench_md(results):
    lines = [
        "# BENCH — measured results for the five BASELINE.md configs",
        "",
        f"Run on: {time.strftime('%Y-%m-%d %H:%M:%S')}; "
        "host = single-core container, device = jax.devices()[0].",
        "",
    ]
    for r in results:
        lines.append(f"## {r['config']}")
        lines.append("")
        for key, v in r.items():
            if key == "config":
                continue
            lines.append(f"- {key}: {v}")
        lines.append("")
    with open(os.path.join(REPO, "BENCH.md"), "w") as fh:
        fh.write("\n".join(lines))


if __name__ == "__main__":
    main()
