"""The five BASELINE.md benchmark configs, end to end.

Usage:
    python benches/run_all.py            # run everything, update BENCH.md
    python benches/run_all.py 1 4       # run selected configs

Configs (BASELINE.md "Targets"):
  1. 4-replica in-process net, f=1, 100 heights — the reference-equivalent
     pure-host baseline (unsigned, NullVerifier trust model).
  2. 16 replicas, 1k heights, round-robin scheduler.
  3. 64 replicas, adversarial mq reorder + timer timeouts (multi-round).
  4. 256 validators, Ed25519 batch-verify offload on the TPU, measured
     end to end: signed burst runs (dedup, redundant, and device-tally
     vote-grid variants) plus the 512-signature round-window latency
     through the native host path, the device path, and the adaptive
     router.
  5. 256 validators + Shamir k-of-n payload reconstruction per committed
     block on the TPU kernels.
  6. The reference's four CI harness scenarios (its only quantitative
     perf-adjacent data), measured in this harness against its budgets.
  7. 512 validators: sustained wire pipeline (+1024 probe), paired signed
     e2e, grid memory budgets at 512 and 1024.
  8. Fused-settle regime sweep: the adversarial-reorder negative
     (windows collapse to 1-2 messages) and all-online storms at 512
     (below the sync floor -> routed to host) and 1024 (above it ->
     the fused settle is chosen and must win).
  9. Engine wire-format e2e: the grouped 69 B/lane challenge format vs
     the per-lane 100 B/lane path on the transfer-heaviest (redundant)
     signed run — the byte-ratio lift measured inside the engine.
 10. Columnar settle fast path + double-buffered settle: the host-side
     automaton insert-leg speedup (columnar vs object path, paired
     trials), engine digest-parity proof with every fast path toggled
     off, and the router-hysteresis upkeep counters. Pure host + tiny
     signed sims — regenerable on a CPU-only container.

Every config prints one JSON line; the suite is deterministic (seeded)
except for wall-clock rates. Caps vs the BASELINE config text (e.g. config
3 runs 20 heights, not unbounded) are stated in the JSON — nothing is
silently truncated.
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: Whether config 4's verifier uses the RLC fast path (set from the
#: measured kernel comparison; the per-signature kernel remains the
#: fallback and the correctness anchor either way). BENCH_r07.json's
#: paired medians — 2.56x at 16384 lanes, 3.57x at 65536 — flip this on.
RLC_DEFAULT = True


def _sim_metrics(sim, res, wall: float) -> dict:
    snap = sim.tracer.snapshot()
    lat = snap["histograms"].get("replica.height.latency", {})
    rounds = snap["histograms"].get("replica.commit.rounds", {})
    out = {
        "completed": res.completed,
        "steps": res.steps,
        "wall_s": round(wall, 3),
        "msgs_per_s": round(res.steps / wall, 1) if wall > 0 else None,
        "virtual_time": round(res.virtual_time, 3),
        "p50_height_latency_virtual": round(lat.get("p50", 0.0), 6),
        "mean_rounds_per_height": round(rounds.get("mean", 1.0), 3),
        # The full metric registry rides along so BENCH_r*.json is a
        # self-contained record: a regression diff never needs a re-run
        # to ask "what did sim.settle.* look like that day".
        "tracer_snapshot": snap,
        # The uniform registry view (tracer absorbed + devtel launch
        # series when the run pipelines): the same shape the obs CLI's
        # ``metrics`` subcommand and the quick-bench sentinel export.
        "metrics_snapshot": sim.metrics_snapshot(),
    }
    if len(sim.obs):
        from hyperdrive_tpu.obs.report import phase_summary

        # Per-phase commit-latency anatomy over the recorder's retained
        # window (the ring keeps the most recent obs_capacity events).
        out["commit_anatomy"] = phase_summary(sim.obs.snapshot())
    return out


def config_1() -> dict:
    from hyperdrive_tpu.harness import Simulation

    t0 = time.perf_counter()
    sim = Simulation(n=4, target_height=100, seed=1001, timeout=20.0,
                     delivery_cost=0.001, observe=True)
    res = sim.run()
    wall = time.perf_counter() - t0
    res.assert_safety()
    return {
        "config": "1: 4 replicas, f=1, 100 heights, pure-host",
        **_sim_metrics(sim, res, wall),
    }


def config_2() -> dict:
    from hyperdrive_tpu.harness import Simulation

    t0 = time.perf_counter()
    sim = Simulation(n=16, target_height=1000, seed=1002, timeout=20.0,
                     delivery_cost=0.001, observe=True)
    res = sim.run(max_steps=5_000_000)
    wall = time.perf_counter() - t0
    res.assert_safety()

    # The batched driving mode (superstep delivery + fast-lane buffering +
    # one rule cascade per verified window): same network, same safety
    # assertions, the per-message host overhead amortized away.
    t0 = time.perf_counter()
    bsim = Simulation(n=16, target_height=1000, seed=1002, timeout=20.0,
                      delivery_cost=0.001, burst=True)
    bres = bsim.run(max_steps=5_000_000)
    bwall = time.perf_counter() - t0
    bres.assert_safety()
    assert bres.completed, f"burst variant stalled at {bres.heights}"

    return {
        "config": "2: 16 replicas, f=5, 1k heights, round-robin",
        **_sim_metrics(sim, res, wall),
        "burst_steps": bres.steps,
        "burst_wall_s": round(bwall, 3),
        "burst_msgs_per_s": round(bres.steps / bwall, 1),
    }


def config_3() -> dict:
    from hyperdrive_tpu.harness import Simulation

    heights = 20
    # Bare quorum online (f = 21 offline). Replicas 1..21 are the offline
    # set: with round-robin proposer = (h + r) % 64, most heights' round-0
    # proposer is offline, so heights genuinely span multiple rounds
    # through propose timeouts, under adversarial reorder.
    offline = set(range(1, 22))
    t0 = time.perf_counter()
    sim = Simulation(
        n=64, target_height=heights, seed=1003, reorder=True, offline=offline,
        timeout=20.0, delivery_cost=0.001, observe=True,
    )
    res = sim.run(max_steps=5_000_000)
    wall = time.perf_counter() - t0
    res.assert_safety()
    return {
        "config": "3: 64 replicas, adversarial reorder + timeouts (2f+1 online)",
        "cap": f"{heights} heights (BASELINE text is open-ended)",
        **_sim_metrics(sim, res, wall),
    }


def _wall_tracer():
    """A wall-clock tracer installed on every replica so commit latency
    histograms measure real time (the sim default is virtual time)."""
    from hyperdrive_tpu.utils import Tracer

    return Tracer(time_fn=time.perf_counter, threadsafe=False)


def _run_signed_burst(ver, heights: int, dedup: bool, seed: int,
                      device_tally: bool = False,
                      max_steps: int = 50_000_000,
                      record: bool = True) -> dict:
    from hyperdrive_tpu.harness import Simulation

    def build(h, rec):
        return Simulation(
            n=256,
            target_height=h,
            seed=seed,
            timeout=20.0,
            sign=True,
            burst=True,
            batch_verifier=ver,
            dedup_verify=dedup,
            device_tally=device_tally,
            record=rec,
        )

    # 2-height warm pass: compiles whatever this mode launches (the fused
    # verify+scatter+tally kernel in device-tally mode) outside the timed
    # region, mirroring ver.warmup() for the plain verify kernels.
    build(2, False).run(max_steps=max_steps)
    sim = build(heights, record)
    wall_tr = _wall_tracer()
    for r in sim.replicas:
        r.tracer = wall_tr
    t0 = time.perf_counter()
    res = sim.run(max_steps=max_steps)
    wall = time.perf_counter() - t0
    res.assert_safety()
    assert res.completed, f"stalled at {res.heights}"
    snap = wall_tr.snapshot()
    lat = snap["histograms"].get("replica.height.latency", {})
    launch = sim.tracer.snapshot()["histograms"].get("sim.verify.launch", {})
    verified = int(launch.get("count", 0) * launch.get("mean", 0.0))
    return {
        "completed": res.completed,
        "heights": heights,
        "steps": res.steps,
        "wall_s": round(wall, 2),
        "heights_per_s": round(heights / wall, 3),
        "msgs_per_s": round(res.steps / wall, 1),
        "signatures_verified": verified,
        "votes_verified_per_s": round(verified / wall, 1),
        "p50_height_latency_s": round(lat.get("p50", 0.0), 4),
        "p95_height_latency_s": round(lat.get("p95", 0.0), 4),
    }


def _run_signed_burst_paired(ver, heights: int, seed: int, block: int = 20,
                             max_steps: int = 50_000_000,
                             modes: "dict[str, dict] | None" = None,
                             n: int = 256, after_warmup=None):
    """The mode comparison (dedup vs device-tally vs ...), PAIRED: the
    modes run in alternating ``block``-height segments (order rotating
    each round) so tunnel-latency drift — measured at ±15% over minutes
    on this chip, enough to invert the comparison all by itself — hits
    every leg equally. ``modes``: name -> extra Simulation kwargs;
    defaults to the dedup/device-tally pair. Returns name -> report with
    the keys of :func:`_run_signed_burst` (plus settle-pipeline telemetry
    for device-tally modes)."""
    from hyperdrive_tpu.harness import Simulation

    if modes is None:
        modes = {"dedup": {}, "tally": {"device_tally": True}}

    def build(extra, h, rec):
        kwargs = dict(
            n=n, target_height=h, seed=seed, timeout=20.0, sign=True,
            burst=True, batch_verifier=ver, dedup_verify=True,
            record=rec,
        )
        kwargs.update(extra)  # a mode may override batch_verifier etc.
        return Simulation(**kwargs)

    # Warm every mode's kernels outside the timed blocks.
    for extra in modes.values():
        build(extra, 2, False).run(max_steps=max_steps)
    if after_warmup is not None:
        # E.g. reset verifier-side accounting so per-run stats describe
        # the timed blocks only, not the warm passes.
        after_warmup()

    acc = {
        m: {"wall": 0.0, "steps": 0, "verified": 0, "heights": 0,
            "completed": True, "tracer": _wall_tracer(),
            "sync_count": 0, "sync_p50s": [], "cascade_p50s": [],
            "routed_count": 0, "block_walls": []}
        for m in modes
    }
    names = list(modes)
    n_blocks = heights // block
    # Position balance: the order rotation only equalizes leg positions
    # (cache warmth, within-round drift) when every leg leads the same
    # number of rounds.
    assert n_blocks % len(names) == 0, (
        f"{n_blocks} blocks over {len(names)} modes leaves the rotation "
        "unbalanced; pick heights/block so n_blocks is a multiple"
    )
    for b in range(n_blocks):
        order = names[b % len(names):] + names[: b % len(names)]
        for mode in order:
            a = acc[mode]
            sim = build(modes[mode], block, True)
            for r in sim.replicas:
                r.tracer = a["tracer"]
            t0 = time.perf_counter()
            res = sim.run(max_steps=max_steps)
            block_wall = time.perf_counter() - t0
            a["wall"] += block_wall
            a["block_walls"].append(block_wall)
            res.assert_safety()
            a["completed"] = a["completed"] and res.completed
            assert res.completed, f"mode {mode} stalled at {res.heights}"
            a["steps"] += res.steps
            a["heights"] += block
            hists = sim.tracer.snapshot()["histograms"]
            launch = hists.get("sim.verify.launch", {})
            a["verified"] += int(
                launch.get("count", 0) * launch.get("mean", 0.0)
            )
            sync = hists.get("sim.fused.sync.latency", {})
            if sync.get("count"):
                a["sync_count"] += int(sync["count"])
                a["sync_p50s"].append(float(sync.get("p50", 0.0)))
            casc = hists.get("sim.fused.cascade.latency", {})
            if casc.get("count"):
                a["cascade_p50s"].append(float(casc.get("p50", 0.0)))
            routed = hists.get("sim.settle.host_routed", {})
            a["routed_count"] += int(routed.get("count", 0))

    def report(a) -> dict:
        import numpy as np

        lat = a["tracer"].snapshot()["histograms"].get(
            "replica.height.latency", {}
        )
        out = {
            "completed": a["completed"],
            "heights": a["heights"],
            "paired_blocks": n_blocks,
            "steps": a["steps"],
            "wall_s": round(a["wall"], 2),
            "heights_per_s": round(a["heights"] / a["wall"], 3),
            # Per-block rate median: the drift-robust figure the paired
            # gates compare (one outlier block cannot move it).
            "block_heights_per_s_p50": round(
                block / float(np.median(a["block_walls"])), 3
            ),
            "msgs_per_s": round(a["steps"] / a["wall"], 1),
            "signatures_verified": a["verified"],
            "votes_verified_per_s": round(a["verified"] / a["wall"], 1),
            "p50_height_latency_s": round(lat.get("p50", 0.0), 4),
            "p95_height_latency_s": round(lat.get("p95", 0.0), 4),
        }
        if a["sync_count"] or a["routed_count"]:
            out["fused_syncs"] = a["sync_count"]
            out["fused_syncs_per_height"] = round(
                a["sync_count"] / max(a["heights"], 1), 2
            )
            out["host_routed_settles"] = a["routed_count"]
        if a["sync_p50s"]:
            out["fused_sync_p50_ms"] = round(
                float(np.median(a["sync_p50s"])) * 1e3, 1
            )
        if a["cascade_p50s"]:
            out["fused_cascade_p50_ms"] = round(
                float(np.median(a["cascade_p50s"])) * 1e3, 1
            )
        return out

    return {m: report(a) for m, a in acc.items()}


def config_4() -> dict:
    """256 replicas, Ed25519 batch-verify offload — measured end to end.

    Three measurements, no projections:
      (a) dedup run, 100 heights: each broadcast verified once per chip —
          one chip performing one replica's verification load, the per-chip
          work of a deployment where every validator owns a chip;
      (b) redundant run, 20 heights: the single chip re-verifies every
          broadcast for all 256 receivers (256x the per-chip load);
      (c) the 512-signature round window: 48 PAIRED host/routed reps
          (leg order alternating, no device launches inside the loop) for
          the router-overhead comparison, a separate 16-rep device-only
          loop for the device latency, and an 8-rep paired loop at a
          4096-signature storm where the router must beat the host by
          taking the device — the latency half of the north star.
    """
    import numpy as np
    import jax
    import jax.numpy as jnp

    from hyperdrive_tpu.crypto import ed25519 as host_ed
    from hyperdrive_tpu.crypto.keys import KeyRing
    from hyperdrive_tpu.messages import Prevote
    from hyperdrive_tpu.ops.ed25519_jax import TpuBatchVerifier
    from hyperdrive_tpu.verifier import AdaptiveVerifier, HostVerifier

    ver = TpuBatchVerifier(buckets=(1024, 4096, 16384), rlc=RLC_DEFAULT)
    t0 = time.perf_counter()
    ver.warmup()
    warm_s = time.perf_counter() - t0

    # Calibration FIRST (it feeds the routed e2e mode below): the
    # adaptive crossover from paired host/device probes, and the device
    # sync floor.
    ring = KeyRing.deterministic(256, namespace=b"bench4")
    value = b"\x2a" * 32
    round_items = []
    for v in range(256):
        pv = Prevote(height=1, round=0, value=value, sender=ring[v].public)
        d = pv.digest()
        round_items.append((ring[v].public, d, host_ed.sign(ring[v].seed, d)))
    round_items = round_items * 2

    hv = HostVerifier()
    assert np.asarray(hv.verify_signatures(round_items)).all()
    assert np.asarray(ver.verify_signatures(round_items)).all()  # warm 1024
    adaptive = AdaptiveVerifier(device=ver, host=hv)
    adaptive.verify_signatures(round_items)  # triggers calibration

    tiny = jax.jit(lambda a: a + 1)
    zed = jnp.zeros(8, jnp.int32)
    np.asarray(tiny(zed))  # compile
    floor_ts = []
    for _ in range(9):
        t0 = time.perf_counter()
        np.asarray(tiny(zed))
        floor_ts.append(time.perf_counter() - t0)
    sync_floor = float(np.median(floor_ts))

    # (a)+(a')+(a''') paired three ways: host-counter dedup, the fused
    # device vote-grid pipeline (quorum counts from masked reductions
    # over device-resident vote tensors fused into the verification
    # launch), and the CROSSOVER-ROUTED device-tally mode — settles whose
    # windows sit below the measured adaptive crossover are handled on
    # host (the device grid re-engages at windows the device actually
    # wins), in alternating 10-height blocks (see the helper's note on
    # tunnel drift).
    # The "host" leg shares the rotation, the recorder setting, and the
    # drift pairing with the other legs — it is the baseline the
    # within-15% routing gate compares against (the standalone
    # host_engine_run below keeps the recorder off and measures the raw
    # automaton ceiling; the two measure different things).
    paired = _run_signed_burst_paired(
        ver, heights=120, seed=1004, block=10,
        modes={
            "dedup": {},
            "tally": {"device_tally": True},
            "routed": {
                "device_tally": True,
                "fused_min_window": int(adaptive.crossover),
            },
            "host": {"batch_verifier": HostVerifier()},
        },
    )
    dedup, grid_run, routed_run, host_paired = (
        paired["dedup"], paired["tally"], paired["routed"], paired["host"]
    )

    # Height pipelining (ROADMAP item 5): the SAME device-tally path,
    # sequential vs pipelined through the async devsched queue —
    # speculative settle, commits gated on the coalesced launch's
    # future. Settle windows fill ~25% of a verify bucket, and a padded
    # launch costs by BUCKET, not fill, so coalescing the settles of
    # several heights into one launch pays the sync floor once per
    # pipeline slot instead of once per settle. Paired 10-height blocks
    # like the mode comparison above; the gate reads the per-block
    # MEDIANS so one drifty block cannot manufacture (or mask) the 2x.
    pipe_paired = _run_signed_burst_paired(
        ver, heights=120, seed=1004, block=10,
        modes={
            "tally_seq": {"device_tally": True},
            "tally_pipelined": {
                "device_tally": True, "pipeline_heights": True,
            },
        },
    )
    pipe_seq, pipe_run = (
        pipe_paired["tally_seq"], pipe_paired["tally_pipelined"]
    )
    pipe_speedup = round(
        pipe_run["block_heights_per_s_p50"]
        / pipe_seq["block_heights_per_s_p50"], 2
    )
    height_pipelining = {
        "sequential": pipe_seq,
        "pipelined": pipe_run,
        "speedup_block_p50": pipe_speedup,
        "speedup_aggregate": round(
            pipe_run["heights_per_s"] / pipe_seq["heights_per_s"], 2
        ),
        "pipelined_2x_sequential": bool(pipe_speedup >= 2.0),
    }

    redundant = _run_signed_burst(ver, heights=20, dedup=False, seed=1044)

    # (a'') the host-engine ceiling: the same signed 256-replica network
    # with aggregated HOST verification and no replay recorder — zero
    # device round trips, so the number measures the consensus automaton
    # itself (the e2e dedup/device-tally runs above are bounded by the
    # tunnel's ~100 ms sync per settle, not by the host engine).
    from hyperdrive_tpu.harness import Simulation
    from hyperdrive_tpu.verifier import HostVerifier

    hsim = Simulation(
        n=256, target_height=30, seed=1004, timeout=20.0, sign=True,
        burst=True, batch_verifier=HostVerifier(), dedup_verify=True,
        record=False,
    )
    t0 = time.perf_counter()
    hres = hsim.run(max_steps=50_000_000)
    hwall = time.perf_counter() - t0
    hres.assert_safety()
    assert hres.completed
    host_engine = {
        "completed": True,
        "heights": 30,
        "steps": hres.steps,
        "wall_s": round(hwall, 2),
        "heights_per_s": round(30 / hwall, 3),
        "msgs_per_s": round(hres.steps / hwall, 1),
    }

    # (c) one round window (2 phases x 256 votes = 512 signatures):
    # methodology per the docstring — paired host/routed reps, separate
    # device-only loop, then the 4096 storm. (Items + calibration were
    # built above, before the e2e runs.)
    # The routed-vs-host comparison is PAIRED per rep (median of per-rep
    # differences cancels common-mode drift) and runs with NO device
    # launches inside the loop: below the crossover the router never
    # touches the device, and interleaving unrelated device RPCs was
    # measured to tax whichever leg follows them by ~1ms on this
    # single-core host — contaminating exactly the sub-1% comparison the
    # paired loop exists to make. The device's own 512-window latency is
    # characterized in a separate loop below.
    #
    # Both comparisons presuppose the calibrated crossover lies in
    # (512, 4096]: then the 512 window routes to the host (device-free
    # paired loop) and the 4096 storm routes to the device. Calibration is
    # machine-dependent, so the premise is checked and RECORDED — if it
    # fails, routed_beats_pure_host reports False rather than publishing a
    # comparison whose legs did not measure what the names claim.
    def paired_reps(items, n_reps):
        host_t: list = []
        routed_t: list = []
        for rep in range(n_reps):
            legs = (
                [(hv, host_t), (adaptive, routed_t)]
                if rep % 2
                else [(adaptive, routed_t), (hv, host_t)]
            )
            for backend, sink in legs:
                t0 = time.perf_counter()
                backend.verify_signatures(items)
                sink.append(time.perf_counter() - t0)
        return np.array(host_t), np.array(routed_t)

    crossover_premise_ok = 512 < adaptive.crossover <= 4096

    host_times, routed_times = paired_reps(round_items, 48)
    p50_host = float(np.median(host_times))
    p50_routed = float(np.median(routed_times))
    diffs_512 = routed_times - host_times
    paired_diff_512 = float(np.median(diffs_512))
    # The measurement's own resolution: the median absolute deviation of
    # the paired differences. "Routed never hurts" asks whether the diff
    # is distinguishable from zero at this resolution — a fixed 1%-of-
    # host threshold alone (0.5-0.8 ms here) sits BELOW the tunnel's
    # rep-to-rep jitter and flips the verdict on sub-millisecond noise.
    mad_512 = float(np.median(np.abs(diffs_512 - paired_diff_512)))

    dev_times = []
    for _ in range(16):
        t0 = time.perf_counter()
        ver.verify_signatures(round_items)
        dev_times.append(time.perf_counter() - t0)
    p50_dev = float(np.median(dev_times))

    # Second latency point, above the crossover: a 4096-signature storm
    # (eight round windows arriving at once). Here the router must take
    # the device and beat the host outright — the two points together are
    # the adaptive claim: routed ~= min(host, device) at every scale.
    storm = round_items * 8
    ver.verify_signatures(storm)  # warm the 4096 bucket
    storm_host, storm_routed = paired_reps(storm, 8)
    p50_storm_host = float(np.median(storm_host))
    p50_storm_routed = float(np.median(storm_routed))

    # Sub-crossover analysis (measured, not argued): the device sync
    # floor — measured above as a minimal launch + result fetch with
    # effectively no input, no signature math — bounds ANY device path
    # from below on this tunnel-attached chip. If floor_sigs =
    # floor * host_rate exceeds 512, no kernel or input-packing
    # improvement can put the device ahead on a single round window: the
    # host finishes before one empty device round trip returns.
    host_rate_512 = len(round_items) / p50_host
    floor_sigs = int(sync_floor * host_rate_512)

    return {
        "config": "4: 256 validators, Ed25519 TPU batch-verify offload",
        "cap": (
            "e2e runs are 120 heights (dedup / device-tally / crossover-"
            "routed / host, measured as PAIRED alternating 10-height "
            "blocks with a balanced rotation so tunnel drift cannot bias "
            "the comparison) and 20 heights (redundant); the full "
            "BASELINE 10k-height depth is dedup_run_deep — rates are "
            "sustained and height-invariant once warm; nothing here is "
            "projected"
        ),
        "device": str(jax.devices()[0]),
        "warmup_s": round(warm_s, 1),
        "rlc": RLC_DEFAULT,
        "dedup_run": dedup,
        "redundant_run": redundant,
        "device_tally_run": grid_run,
        "device_tally_routed_run": routed_run,
        "host_paired_run": host_paired,
        "host_engine_run": host_engine,
        "height_pipelining": height_pipelining,
        # The settle-pipeline verdict (VERDICT r3 #2): every fused settle
        # pays exactly ONE blocking device sync (mask + counts in one
        # transfer, fused_sync_p50_ms ~= device_sync_floor_ms), and the
        # host insert+cascade that DEPENDS on that data costs
        # fused_cascade_p50_ms < the sync — so no overlap schedule can
        # hide the sync behind host work at this window size; the fix is
        # not to pay it: the crossover router keeps sub-crossover settles
        # on host, and the routed device-tally mode must land within 15%
        # of the host leg measured under the SAME recorder + pairing.
        "routed_tally_within_15pct_of_host": bool(
            routed_run["heights_per_s"]
            >= 0.85 * host_paired["heights_per_s"]
        ),
        "round512_p50_latency_host_native_s": round(p50_host, 5),
        "round512_p50_latency_device_s": round(p50_dev, 5),
        "round512_p50_latency_routed_s": round(p50_routed, 5),
        "round512_paired_p50_routed_minus_host_s": round(paired_diff_512, 6),
        "storm4096_p50_latency_host_native_s": round(p50_storm_host, 5),
        "storm4096_p50_latency_routed_s": round(p50_storm_routed, 5),
        # The north-star latency claim, measured at both scales: below the
        # crossover the router matches the pure-host baseline (paired
        # difference indistinguishable from zero at the measurement's own
        # resolution, or under 1% of host), above it the router does not
        # lose to the host (and typically wins ~2x; a slow-device session
        # can tie, which still satisfies "never hurts").
        "crossover_premise_ok": crossover_premise_ok,
        "round512_paired_diff_mad_s": round(mad_512, 6),
        "routed_beats_pure_host": bool(
            crossover_premise_ok
            and paired_diff_512 <= max(0.01 * p50_host, 2 * mad_512)
            and p50_storm_routed <= 1.02 * p50_storm_host
        ),
        "adaptive_crossover_sigs": adaptive.crossover,
        "adaptive_calibration": {
            k: round(float(v), 4 if k == "device_overhead_s" else 1)
            for k, v in (adaptive.rates or {}).items()
        },
        "device_sync_floor_ms": round(sync_floor * 1e3, 1),
        "sync_floor_equivalent_sigs": floor_sigs,
        "sub_crossover_note": (
            (
                "negative result, by measurement: the minimal device "
                "round trip (empty launch + 32-byte fetch, no crypto) "
                f"costs {sync_floor * 1e3:.0f} ms on this tunnel-attached "
                f"chip — the host verifies {floor_sigs} signatures in "
                "that time, so for any window below that no device path "
                "(regardless of kernel, donation, or pre-packed device-"
                "resident inputs) can win; the adaptive crossover sits at "
                "the floor, and a sub-512 crossover requires a locally "
                "attached chip, not a better kernel"
            )
            if floor_sigs >= 512
            else (
                "sync floor does NOT preclude a sub-512 crossover on this "
                f"chip (floor {sync_floor * 1e3:.0f} ms = {floor_sigs} "
                "host-verified signatures < 512) — the device path is "
                "latency-viable at round-window scale here"
            )
        ),
    }


def config_5() -> dict:
    """256 replicas, Shamir payloads end to end: every proposed value
    carries a 171-of-256 share bundle, validators check the bundle against
    the value commitment, and every commit reconstructs the payload via
    the ADAPTIVE router (commit-sized batches land on the cached-weight
    host leg; the device kernel is measured standalone and in the
    commit16 device leg below) — measured through the full consensus
    harness, plus the standalone kernel reconstruct throughput."""
    import secrets as pysecrets

    from hyperdrive_tpu.crypto import shamir as host_shamir
    from hyperdrive_tpu.harness import Simulation
    from hyperdrive_tpu.ops.shamir import BatchReconstructor

    heights = 10
    blocks_per_payload = 16
    sim = Simulation(
        n=256,
        target_height=heights,
        seed=1005,
        timeout=20.0,
        burst=True,
        payload_bytes=31 * blocks_per_payload,
    )
    # (No device warmup for the e2e run: the adaptive default routes
    # 16-block commits to the cached-weight host leg, so the run launches
    # no reconstruct kernel — e2e_p50_reconstruct_s measures the ROUTED
    # path, not the r3 device path.)
    t0 = time.perf_counter()
    res = sim.run(max_steps=20_000_000)
    wall = time.perf_counter() - t0
    res.assert_safety()
    assert res.completed, f"stalled at {res.heights}"
    for i in range(sim.n):
        assert set(sim.reconstructed[i]) >= set(range(1, heights + 1))
    recon = sim.tracer.snapshot()["histograms"].get("sim.reconstruct.latency", {})

    # Standalone kernel throughput at the r1 scale (64 blocks/launch).
    n, f = 256, 85
    k = 2 * f + 1
    payload = pysecrets.token_bytes(31 * 64)
    blocks = host_shamir.split_payload(payload, k, n, tag=b"bench5")
    subset = [shares[:k] for shares in blocks]
    rec = BatchReconstructor()
    out = rec.reconstruct_payload_shares(subset)  # compile + correctness
    assert out == payload
    iters = 10
    t0 = time.perf_counter()
    for _ in range(iters):
        out = rec.reconstruct_payload_shares(subset)
    dt = time.perf_counter() - t0
    blocks_per_s = len(blocks) * iters / dt

    # Adaptive per-commit routing (VERDICT r3 #5): calibrate on the
    # 64-block standalone batch (host and device both timed, outputs
    # cross-checked, crossover solved), then measure the COMMIT shape —
    # a 16-block, 496-byte payload, k = 171 — through host-only,
    # device-only, and the routed reconstructor. The gate: routing must
    # never lose to the host at commit scale.
    from hyperdrive_tpu.ops.shamir import AdaptiveReconstructor

    adaptive = AdaptiveReconstructor(device=rec, calibrate_at=64)
    assert adaptive.reconstruct_payload_shares(subset) == payload
    assert adaptive.calibrated

    commit_payload = pysecrets.token_bytes(31 * blocks_per_payload - 1)
    commit_blocks = host_shamir.split_payload(
        commit_payload, k, n, tag=b"bench5c"
    )
    commit_subset = [shares[:k] for shares in commit_blocks]

    import numpy as np

    def p50(fn, reps=9):
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            got = fn()
            ts.append(time.perf_counter() - t0)
            assert got == commit_payload
        return float(np.median(ts))

    p50_commit_host_naive = p50(
        lambda: host_shamir.reconstruct_payload(
            [list(s) for s in commit_subset]
        )
    )
    adaptive.host_reconstruct(commit_subset)  # populate the weight cache
    p50_commit_host = p50(
        lambda: adaptive.host_reconstruct(commit_subset)
    )
    rec.reconstruct_payload_shares(commit_subset)  # warm the 16-block shape
    p50_commit_dev = p50(
        lambda: rec.reconstruct_payload_shares(commit_subset)
    )
    p50_commit_routed = p50(
        lambda: adaptive.reconstruct_payload_shares(commit_subset)
    )

    return {
        "config": "5: 256 validators, Shamir 171-of-256 payloads on committed blocks",
        "k": k,
        "n": n,
        "e2e_heights": heights,
        "e2e_wall_s": round(wall, 2),
        "e2e_heights_per_s": round(heights / wall, 3),
        "e2e_payload_bytes_per_height": 31 * blocks_per_payload,
        "e2e_reconstructs": recon.get("count", 0),
        "e2e_p50_reconstruct_s": round(recon.get("p50", 0.0), 5),
        "kernel_blocks_per_launch": len(blocks),
        "kernel_blocks_per_s": round(blocks_per_s, 1),
        "kernel_payload_bytes_per_s": round(
            blocks_per_s * host_shamir.BLOCK_BYTES, 1
        ),
        "kernel_per_commit_latency_s": round(dt / iters, 5),
        # Host legs: "naive" recomputes the k = 171 Lagrange inverses per
        # block (the oracle's shape); "cached" reuses them per contributor
        # set — the regime steady-state commits actually see, and the
        # baseline the routing gate compares against.
        "commit16_p50_host_naive_s": round(p50_commit_host_naive, 6),
        "commit16_p50_host_cached_s": round(p50_commit_host, 6),
        "commit16_p50_device_s": round(p50_commit_dev, 6),
        "commit16_p50_routed_s": round(p50_commit_routed, 6),
        "reconstruct_crossover_blocks": adaptive.crossover_blocks,
        "reconstruct_calibration": {
            kk: round(float(v), 6 if kk.endswith("overhead_s") else 1)
            for kk, v in (adaptive.rates or {}).items()
        },
        "routed_commit_not_worse_than_host": bool(
            p50_commit_routed <= 1.05 * p50_commit_host
        ),
    }


def config_6() -> dict:
    """The reference's four CI harness scenarios, measured here.

    The ONLY quantitative perf-adjacent data the reference publishes are
    its test budgets (BASELINE.md table): n=10 honest to height 30 under
    15 s, n=7 (bare 2f+1) under 35 s, n=10 with f killed mid-run under
    30 s, n=10 with f Byzantine proposers under 45 s — all with 1 ms
    lock-step delivery pacing on CI hardware. Same scenarios, same pacing
    cost, this harness; budgets from replica/replica_test.go:384-672."""
    from hyperdrive_tpu.harness import Simulation

    def timed(label, budget_s, **kw):
        t0 = time.perf_counter()
        sim = Simulation(target_height=30, timeout=20.0,
                         delivery_cost=0.001, **kw)
        res = sim.run(max_steps=2_000_000)
        wall = time.perf_counter() - t0
        res.assert_safety()
        assert res.completed, f"{label} stalled at {res.heights}"
        return {
            f"{label}_wall_s": round(wall, 3),
            f"{label}_reference_budget_s": budget_s,
        }

    out = {
        "config": "6: the reference CI harness scenarios, measured",
        "note": (
            "the reference paces its harness with a REAL 1 ms sleep per "
            "delivery (replica_test.go:291), which dominates its budgets; "
            "this harness charges the same 1 ms to a virtual clock and "
            "never sleeps, so wall_s here measures pure engine throughput "
            "— the budget columns are context, not a like-for-like race"
        ),
    }
    out.update(timed("n10_honest", 15, n=10, seed=1061))
    out.update(timed("n7_bare_quorum", 35, n=7, seed=1062))
    # f = 3 of 10 killed partway through the run (step chosen well before
    # the honest completion point so the kills actually bite).
    out.update(timed("n10_f_killed", 30, n=10, seed=1063,
                     kill_at_step={1: 2000, 4: 2500, 7: 3000}))
    # f Byzantine proposers: propose garbage whenever it is their turn.
    bad = {i: (lambda h, r: bytes([0xBB]) * 32) for i in (2, 5, 8)}
    out.update(timed("n10_f_byzantine", 45, n=10, seed=1064,
                     byzantine_proposer=bad))
    return out


def config_7() -> dict:
    """512 validators — the >256 operating point (VERDICT r3 weak #5).

    Three measurements:
      (a) the sustained unique-signature wire pipeline at a 512-entry
          validator table: 512 validators x 128 rounds = 65,536 fresh
          signatures per launch, pack || transfer || verify, no input
          reuse (bench.py's methodology at double the validator set);
      (b) a paired signed 512-replica e2e: host-counter dedup vs the
          crossover-routed device-tally mode, alternating blocks;
      (c) the grid memory budget at this scale (computed from the live
          grid's dtypes, not hand-derived).
    Sharded-consensus CORRECTNESS at 512 and 1024 validators (signed)
    runs in the test suite on the 8-device CPU mesh
    (tests/test_harness.py::test_device_tally_sharded_at_scale).
    """
    from hyperdrive_tpu.ops.ed25519_jax import TpuBatchVerifier
    from hyperdrive_tpu.verifier import AdaptiveVerifier, HostVerifier

    # (a) the sustained pipeline, through bench.py's OWN harness (one
    # methodology for the 256-validator headline and this 512-validator
    # point — a fix to one cannot silently leave the other stale; REPO
    # is already on sys.path from module import).
    from bench import run_sustained

    validators, rounds = 512, 128
    pipe = run_sustained(
        validators=validators, rounds=rounds, full_wire=False,
        namespace=b"bench7",
    )

    # Session drift is the dominant error bar on every sustained scalar
    # (PARITY quotes 2x across sessions), so the trial spread rides NEXT
    # TO the headline number instead of in a prose note readers must
    # find.
    def spread(trials):
        return [round(min(trials), 1), round(max(trials), 1)]

    pipe["sustained_votes_per_s_spread"] = spread(pipe["sustained_trials"])

    # (a') a 1024-validator probe through the same harness: the wire
    # cost per lane is validator-count-invariant (the table AND the
    # dense-grid index are resident; the launch ships only R + s), so
    # the sustained rate should hold as the set doubles again — this
    # records that it does. Shorter (2 launches per trial): it is a
    # scale point, not the headline.
    probe_1024 = run_sustained(
        validators=1024, rounds=64, iters=2, trials=3, full_wire=False,
        namespace=b"bench7x1024",
    )
    pipe["sustained_1024v_votes_per_s"] = probe_1024["sustained_votes_per_s"]
    pipe["sustained_1024v_trials"] = probe_1024["sustained_trials"]
    pipe["sustained_1024v_votes_per_s_spread"] = spread(
        probe_1024["sustained_trials"]
    )
    # Measured by run_sustained from its live table (coords + encodings
    # + valid mask) — layout changes keep the artifact true.
    pipe["table_bytes_1024v"] = probe_1024["table_bytes"]

    # (b) paired e2e at n=512: dedup vs crossover-routed device tally.
    from hyperdrive_tpu.crypto.keys import KeyRing
    from hyperdrive_tpu.messages import Prevote

    ver = TpuBatchVerifier(buckets=(1024, 4096), rlc=RLC_DEFAULT)
    ver.warmup()
    hv = HostVerifier()
    # 1024 UNIQUE signatures (two distinct rounds per validator): a
    # duplicated probe would trip the device verifier's dedup fast path
    # and calibrate its leg on half the pack/transfer work the host leg
    # does — an asymmetric, non-representative crossover.
    ring = KeyRing.deterministic(512, namespace=b"bench7cal")
    probe = []
    for r in (0, 1):
        value = bytes([0x2A + r]) * 32
        for v in range(512):
            pv = Prevote(height=1, round=r, value=value,
                         sender=ring[v].public)
            d = pv.digest()
            probe.append((ring[v].public, d, ring[v].sign_digest(d)))
    adaptive = AdaptiveVerifier(device=ver, host=hv, calibrate_at=1024)
    adaptive.verify_signatures(probe)
    # 40 heights / 4 paired blocks per leg (VERDICT r4 #5: the 8-height
    # sample was too thin to earn the comparison).
    paired = _run_signed_burst_paired(
        ver, heights=40, seed=1007, block=10, n=512,
        modes={
            "dedup": {},
            "routed": {
                "device_tally": True,
                "fused_min_window": int(adaptive.crossover),
            },
        },
    )

    # (c) grid memory: derived from a LIVE grid's array nbytes (so a
    # dtype or layout change shows up here instead of a stale constant),
    # scaled by the exact (n * V) proportionality of the [n,2,R,V,...]
    # shapes. r_slots=4 matches Simulation's grid construction.
    from hyperdrive_tpu.ops.votegrid import VoteGrid

    probe_grid = VoteGrid(1, 8, r_slots=4, buckets=(64,))
    probe_lanes = 1 * 2 * 4 * 8
    lane_bytes = (
        probe_grid._values.nbytes + probe_grid._present.nbytes
    ) / probe_lanes

    def grid_bytes(n_rep, v):
        return int(n_rep * 2 * 4 * v * lane_bytes)

    return {
        "config": "7: 512 validators — sustained wire pipeline, paired e2e, grid budget",
        **pipe,
        "e2e_dedup_run": paired["dedup"],
        "e2e_routed_tally_run": paired["routed"],
        "adaptive_crossover_sigs": adaptive.crossover,
        "grid_bytes_sim_512": grid_bytes(512, 512),
        "grid_bytes_per_device_8way": grid_bytes(512, 512) // 8,
        "grid_bytes_deployment_n1_v512": grid_bytes(1, 512),
        "grid_bytes_sim_1024": grid_bytes(1024, 1024),
        "grid_bytes_per_device_8way_1024": grid_bytes(1024, 1024) // 8,
        "grid_bytes_deployment_n1_v1024": grid_bytes(1, 1024),
        "sharded_consensus_correctness": (
            "tests/test_harness.py::test_device_tally_sharded_at_scale "
            "(8-device CPU mesh, CheckedTallyView; 512 unsigned + 512 "
            "signed + 1024 signed, commits identical to host runs)"
        ),
    }


def config_8() -> dict:
    """Fused-settle regime sweep (VERDICT r4 #3): where the fused device
    settle WINS end to end, and where it cannot — both measured.

    Settle-window physics first (measured on the 8-device CPU probe and
    re-measured here): the lockstep burst engine settles once per
    superstep, so a settle window is ONE broadcast phase ~= n dedup'd
    signatures; adversarial reorder serializes deliveries and collapses
    windows to p50 = 1-2 messages. Config 4 measures the tunnel sync
    floor at ~880 host-equivalent signatures. Therefore:

      (a) the config-3-style multi-round adversarial regime (reorder +
          offline proposers, 256 validators): windows are 1-2 sigs,
          three orders of magnitude under the floor — no device path
          can engage, and the crossover router correctly sends every
          settle to host (fused_syncs = 0 IS the win). Published as the
          measured negative.
      (b) all-online signed storm at 512: windows = 512 < floor; the
          routed leg stays on host and must track the host leg, the
          always-fused leg pays the sync per settle and documents the
          cost of ignoring the router.
      (c) all-online signed storm at 1024: windows ~= 1024 > floor —
          the first e2e consensus regime on this tunnel where the fused
          settle is chosen AND should win outright (fused_syncs > 0 in
          the winning leg, or the negative is published with numbers).
    """
    import numpy as np
    import jax
    import jax.numpy as jnp

    from hyperdrive_tpu.crypto import ed25519 as host_ed
    from hyperdrive_tpu.crypto.keys import KeyRing
    from hyperdrive_tpu.harness import Simulation
    from hyperdrive_tpu.messages import Prevote
    from hyperdrive_tpu.ops.ed25519_jax import TpuBatchVerifier
    from hyperdrive_tpu.verifier import HostVerifier

    ver = TpuBatchVerifier(buckets=(1024, 2048), rlc=RLC_DEFAULT)
    ver.warmup()
    hv = HostVerifier()

    # The router threshold, from first principles ON THIS SESSION: the
    # sync floor (minimal launch + fetch) converted to host-equivalent
    # signatures at the host's measured 1024-unique-signature rate.
    ring = KeyRing.deterministic(1024, namespace=b"bench8")
    probe = []
    for v in range(1024):
        pv = Prevote(height=1, round=0, value=b"\x55" * 32,
                     sender=ring[v].public)
        d = pv.digest()
        probe.append((ring[v].public, d, host_ed.sign(ring[v].seed, d)))
    assert np.asarray(hv.verify_signatures(probe)).all()
    host_ts = []
    for _ in range(5):
        t0 = time.perf_counter()
        hv.verify_signatures(probe)
        host_ts.append(time.perf_counter() - t0)
    host_rate = len(probe) / float(np.median(host_ts))
    tiny = jax.jit(lambda a: a + 1)
    zed = jnp.zeros(8, jnp.int32)
    np.asarray(tiny(zed))
    floor_ts = []
    for _ in range(9):
        t0 = time.perf_counter()
        np.asarray(tiny(zed))
        floor_ts.append(time.perf_counter() - t0)
    sync_floor = float(np.median(floor_ts))
    floor_sigs = int(sync_floor * host_rate)

    def window_stats(sim):
        h = sim.tracer.snapshot()["histograms"].get("sim.verify.launch", {})
        return {
            "settles": int(h.get("count", 0)),
            "window_p50": h.get("p50"),
            "window_p95": h.get("p95"),
            "window_mean": round(float(h.get("mean", 0.0)), 1),
        }

    # (a) the adversarial multi-round regime, short (it is host-bound by
    # construction): routed device-tally vs host, serial legs.
    adv = {}
    for name, extra in (
        ("host", {"batch_verifier": HostVerifier()}),
        ("routed", {"batch_verifier": ver, "device_tally": True,
                    "fused_min_window": floor_sigs}),
    ):
        sim = Simulation(
            n=256, target_height=2, seed=1008, sign=True, burst=True,
            reorder=True, offline=set(range(1, 86)), dedup_verify=True,
            record=False, **extra,
        )
        t0 = time.perf_counter()
        res = sim.run(max_steps=50_000_000)
        wall = time.perf_counter() - t0
        res.assert_safety()
        assert res.completed, f"adversarial {name} stalled at {res.heights}"
        hists = sim.tracer.snapshot()["histograms"]
        adv[name] = {
            "wall_s": round(wall, 2),
            "heights_per_s": round(2 / wall, 4),
            **window_stats(sim),
            "fused_syncs": int(
                hists.get("sim.fused.sync.latency", {}).get("count", 0)
            ),
            "host_routed_settles": int(
                hists.get("sim.settle.host_routed", {}).get("count", 0)
            ),
        }

    # (b) + (c): paired all-online storms. Three legs each — host
    # baseline, always-fused, crossover-routed — in balanced rotating
    # blocks so tunnel drift hits every leg equally.
    def storm(n, heights, block, seed):
        return _run_signed_burst_paired(
            ver, heights=heights, seed=seed, block=block, n=n,
            modes={
                "host": {"batch_verifier": HostVerifier()},
                "fused": {"device_tally": True},
                "routed": {"device_tally": True,
                           "fused_min_window": floor_sigs},
            },
        )

    storm512 = storm(512, 6, 2, 1081)
    storm1024 = storm(1024, 6, 2, 1082)

    f1024, h1024 = storm1024["fused"], storm1024["host"]
    r1024 = storm1024["routed"]
    # Two distinct claims, both published: does the ALWAYS-fused leg beat
    # host (it pays a sync even for sub-floor settles), and does the
    # ROUTED leg — which fuses the above-floor windows and hosts the
    # rest — win with fused settles actually chosen (fused_syncs > 0)?
    # The second is the e2e "fused settle is chosen and wins" claim
    # (VERDICT r4 #3); the first documents the price of ignoring the
    # router.
    fused_always_wins_1024 = bool(
        f1024.get("fused_syncs", 0) > 0
        and f1024["heights_per_s"] >= h1024["heights_per_s"]
    )
    routed_fused_wins_1024 = bool(
        r1024.get("fused_syncs", 0) > 0
        and r1024["heights_per_s"] >= h1024["heights_per_s"]
    )
    return {
        "config": "8: fused-settle regime sweep — adversarial negative, "
                  "512/1024 all-online storms",
        "device": str(jax.devices()[0]),
        "sync_floor_ms": round(sync_floor * 1e3, 1),
        "host_sigs_per_s_unique1024": round(host_rate, 1),
        "floor_equivalent_sigs": floor_sigs,
        "adversarial_256": adv,
        "adversarial_routed_over_host_wall": round(
            adv["routed"]["wall_s"] / adv["host"]["wall_s"], 2
        ),
        "adversarial_note": (
            "negative result, by measurement: adversarial reorder "
            "serializes deliveries, so settle windows collapse to "
            f"p50={adv['host']['window_p50']} messages — no device path "
            "can engage below the sync floor. The router protects the "
            "unfused device-tally path too (tiny settles dispatch on "
            "host with the grid poisoned): fused_syncs="
            f"{adv['routed']['fused_syncs']}, host_routed="
            f"{adv['routed']['host_routed_settles']}, routed/host wall "
            f"= {adv['routed']['wall_s'] / adv['host']['wall_s']:.2f}x"
        ),
        "storm512": storm512,
        "storm1024": storm1024,
        "fused_always_wins_at_1024": fused_always_wins_1024,
        "routed_with_fused_syncs_wins_at_1024": routed_fused_wins_1024,
        "window_physics_note": (
            "a lockstep settle window is one broadcast phase ~= n "
            "dedup'd signatures, so the fused settle can only win where "
            f"n exceeds the session's ~{floor_sigs}-signature sync "
            "floor: 512-validator windows route to host by measurement, "
            "1024-validator windows cross the floor"
        ),
    }


def config_9() -> dict:
    """Engine wire-format e2e (VERDICT r4 #2's bench leg): the grouped
    69 B/lane challenge format vs the per-lane 100 B/lane path, measured
    INSIDE the engine on the transfer-heaviest signed e2e regime.

    The redundant (no-dedup) 256-replica signed run makes the single
    chip re-verify every broadcast for all 256 receivers — settle
    windows of ~65k lanes, the most transfer-bound regime the harness
    has. Both legs run the SAME TpuWireVerifier code with the same
    resident table; the 100 B leg only pins M_GROUP_CAP = 0 so every
    chunk takes the per-lane digest-rows path. Paired alternating
    blocks; the byte ratio (100/69 ~= 1.45) is the expected ceiling of
    the lift when fully transfer-bound.
    """
    import numpy as np

    from hyperdrive_tpu.crypto.keys import KeyRing
    from hyperdrive_tpu.ops.ed25519_wire import (
        TpuWireVerifier,
        ValidatorTable,
    )

    seed = 1009
    ring = KeyRing.deterministic(256, namespace=b"sim-%d" % seed)
    table = ValidatorTable([ring[i].public for i in range(256)])

    def make_wv(group: bool) -> TpuWireVerifier:
        wv = TpuWireVerifier(buckets=(4096,), table=table, backend="xla")
        if not group:
            wv.host.M_GROUP_CAP = 0  # pin the per-lane 100 B/lane path
        return wv

    wv69, wv100 = make_wv(True), make_wv(False)
    wv69.warmup()
    wv100.warmup()
    paired = _run_signed_burst_paired(
        None, heights=8, seed=seed, block=4, n=256,
        modes={
            "wire69": {"batch_verifier": wv69, "dedup_verify": False},
            "wire100": {"batch_verifier": wv100, "dedup_verify": False},
        },
        # Stats must describe the timed blocks, not the warm passes.
        after_warmup=lambda: (wv69.reset_stats(), wv100.reset_stats()),
    )
    r69, r100 = paired["wire69"], paired["wire100"]
    lift = r69["votes_verified_per_s"] / max(
        r100["votes_verified_per_s"], 1e-9
    )
    return {
        "config": "9: engine wire format e2e — grouped 69 B/lane vs "
                  "per-lane 100 B/lane, redundant signed 256-replica run",
        "wire69_run": r69,
        "wire100_run": r100,
        "engine_bytes_per_lane_grouped": round(wv69.bytes_per_lane(), 2),
        "engine_bytes_per_lane_perlane": round(wv100.bytes_per_lane(), 2),
        "lanes_grouped": int(wv69.stats["lanes_grouped"]),
        "lanes_perlane": int(wv100.stats["lanes_chal"]),
        "e2e_throughput_lift_69_over_100": round(float(np.float64(lift)), 3),
        "byte_ratio_ceiling": round(100 / 69, 3),
        "note": (
            "both legs are the engine's own verify_signatures path with "
            "a resident ValidatorTable; only the digest wire format "
            "differs. The lift approaches the byte ratio exactly to the "
            "degree the regime is transfer-bound: "
            + (
                "this run IS transfer-bound (lift tracks the byte ratio)"
                if lift >= 1.15
                else (
                    "this session it is NOT — the 256-replica automaton "
                    "insert + native pack dominate the redundant settle, "
                    "so the ~31% byte saving vanishes into host time and "
                    "the lift is ~1.0; the sustained pipeline (config 7 "
                    "/ bench.py), where transfer IS the bottleneck, is "
                    "where the byte-ratio lift appears (1.5-1.8x "
                    "measured r4; engine format = bench format either "
                    "way)"
                )
            )
        ),
    }


def config_10() -> dict:
    """Columnar settle fast path + double-buffered settle — the engine-
    path artifact a CPU-only container can regenerate honestly.

    Three measurements, no device required:
      (a) the automaton INSERT leg: `bench.run_insert_leg` — the columnar
          `ingest_insert_cols` path vs the object path (per-replica
          filter comprehension + `ingest_insert`), paired trials,
          median ratio is the headline;
      (b) whole-run commit-digest parity on a signed 4-replica network:
          the default run (columnar + pipelined settle ON) against the
          same seed with every fast path toggled off — commits and step
          counts must be identical, and the tracer must show the fast
          paths actually engaged;
      (c) router hysteresis: a run whose every settle host-routes
          (fused_min_window is huge) must disengage the vote grid and
          skip upkeep for the tail of the run, with commits unchanged.
    """
    import jax

    from bench import run_insert_leg
    from hyperdrive_tpu.harness import Simulation

    leg = run_insert_leg()

    def run(**kw):
        sim = Simulation(n=4, target_height=6, seed=11, burst=True,
                         sign=True, **kw)
        res = sim.run(max_steps=2_000_000)
        res.assert_safety()
        assert res.completed, f"stalled at {res.heights}"
        return sim, res

    sim_c, res_c = run()
    sim_o, res_o = run(columnar_ingest=False, pipeline_verify=False)
    assert res_c.commits == res_o.commits, "columnar changed commits"
    assert res_c.steps == res_o.steps
    snap_c = sim_c.tracer.snapshot()["counters"]
    assert snap_c.get("replica.ingest.fastpath_rows", 0) > 0
    assert snap_c.get("sim.settle.pipelined", 0) > 0

    sim_h, res_h = run(device_tally=True, fused_min_window=10_000,
                       route_hysteresis=4)
    assert res_h.commits == res_o.commits, "hysteresis changed commits"
    snap_h = sim_h.tracer.snapshot()["counters"]

    return {
        "config": "10: columnar settle fast path + double-buffered "
                  "settle (host engine-path artifact)",
        "device": str(jax.devices()[0]),
        **leg,
        "commit_digest_parity": True,
        "fastpath_rows": int(
            snap_c.get("replica.ingest.fastpath_rows", 0)
        ),
        "pipelined_settles": int(snap_c.get("sim.settle.pipelined", 0)),
        "hysteresis_disengaged": int(
            snap_h.get("sim.settle.grid_disengaged", 0)
        ),
        "hysteresis_upkeep_skipped": int(
            snap_h.get("sim.settle.grid_upkeep_skipped", 0)
        ),
        "note": (
            "insert_leg_speedup_median is the CPU-measured host-side "
            "lift of the columnar settle path over the object path on "
            "the lockstep window shape; commit_digest_parity asserts "
            "the default (columnar + pipelined) run and the "
            "all-fast-paths-off run produce identical commits and step "
            "counts, and the hysteresis run keeps commits identical "
            "while dropping vote-grid upkeep "
            "(columnar/object state equality is property-tested in "
            "tests/test_columnar_parity.py)"
        ),
    }


CONFIGS = {1: config_1, 2: config_2, 3: config_3, 4: config_4, 5: config_5,
           6: config_6, 7: config_7, 8: config_8, 9: config_9,
           10: config_10}

RESULTS_DIR = os.path.join(REPO, "benches", "results")


def _run_config(i: int) -> dict:
    """Run one config, retrying once on transient device/tunnel failures
    (the axon remote-compile channel can drop mid-run; a retry on a fresh
    attempt is the difference between losing a 20-minute suite and not)."""
    try:
        return CONFIGS[i]()
    except Exception as e:  # noqa: BLE001 — classify, then retry or re-raise
        transient = "remote_compile" in str(e) or "INTERNAL" in str(e)
        if not transient:
            raise
        print(f"# config {i}: transient device failure, retrying: {e}",
              file=sys.stderr)
        time.sleep(10.0)
        return CONFIGS[i]()


def main():
    which = [int(a) for a in sys.argv[1:]] or sorted(CONFIGS)
    os.makedirs(RESULTS_DIR, exist_ok=True)
    for i in which:
        r = _run_config(i)
        # Stamp and persist each config as it lands so a later crash (or a
        # partial re-run of one config) never loses completed measurements,
        # and so a merged BENCH.md can say when each section was measured.
        r["measured_at"] = time.strftime("%Y-%m-%d %H:%M:%S")
        path = os.path.join(RESULTS_DIR, f"config_{i}.json")
        # Merge-preserve keys other tools contributed to this config (the
        # 10k deep run writes dedup_run_deep into config 4): a re-run of
        # the base config must not silently drop a 2.5-hour measurement.
        if os.path.exists(path):
            with open(path) as fh:
                old = json.load(fh)
            for k, v in old.items():
                r.setdefault(k, v)
        with open(path, "w") as fh:
            json.dump(r, fh, indent=1)
        if i == 4 and "height_pipelining" in r:
            # The pipelining comparison doubles as a standalone artifact
            # (BENCH_r05.json's sibling): the paired sequential/pipelined
            # medians plus provenance, committed at the repo root.
            r06 = dict(r["height_pipelining"])
            r06["device"] = r.get("device")
            r06["rlc"] = r.get("rlc")
            r06["measured_at"] = r["measured_at"]
            with open(os.path.join(REPO, "BENCH_r06.json"), "w") as fh:
                json.dump(r06, fh, indent=1)
        print(json.dumps(r))
    results = []
    for i in sorted(CONFIGS):
        path = os.path.join(RESULTS_DIR, f"config_{i}.json")
        if os.path.exists(path):
            with open(path) as fh:
                results.append(json.load(fh))
    if len(results) == len(CONFIGS):
        write_bench_md(results)


def write_bench_md(results):
    lines = [
        "# BENCH — measured results for the BASELINE.md configs",
        "",
        "host = single-core container, device = jax.devices()[0]. Each "
        "section records its own measured_at (sections persist in "
        "benches/results/ and merge across partial re-runs).",
        "",
        "All numbers are measured with `HD_SANITIZE` unset/`0`: the "
        "consensus",
        "sanitizer (ANALYSIS.md) recounts quorums from host logs on "
        "every commit",
        "and cross-checks device tallies, which is exactly the host "
        "work the hot",
        "path exists to avoid. The test suite turns it on; benchmarks "
        "must not.",
        "",
        "Artifacts are metrics-carrying (OBSERVABILITY.md): each "
        "sim-config row",
        "in `benches/results/config_*.json` embeds the run's full",
        "`tracer_snapshot` (counter/histogram registry) and, for "
        "observed sims,",
        "`commit_anatomy` — the per-phase commit-latency breakdown "
        "from the",
        "flight recorder — and `bench.py`'s single JSON line carries "
        "the same",
        "pair from a fixed-seed 4-replica host sim. Diff the "
        "artifact, not a",
        "re-run.",
        "",
    ]
    # Headline = MEDIAN of the checked-in artifact's trials, computed
    # from the artifacts at generation time so the preamble can never
    # drift from the sections below. Fastest-window figures stay in the
    # per-config trial spreads where they belong.
    head = []
    r05_path = os.path.join(REPO, "BENCH_r05.json")
    if os.path.exists(r05_path):
        with open(r05_path) as fh:
            r05 = json.load(fh)
        # The r05 artifact wraps bench.py's JSON line under "parsed".
        r05 = r05.get("parsed", r05)
        trials = r05.get("sustained_trials", [])
        head.append(
            f"256 validators: {r05['value'] / 1e3:.1f}k votes/s "
            f"sustained (median of {len(trials)} trials, BENCH_r05.json; "
            f"spread {min(trials) / 1e3:.1f}-{max(trials) / 1e3:.1f}k)"
            if trials else
            f"256 validators: {r05['value'] / 1e3:.1f}k votes/s "
            "sustained (BENCH_r05.json)"
        )
    r06_path = os.path.join(REPO, "BENCH_r06.json")
    if os.path.exists(r06_path):
        with open(r06_path) as fh:
            r06 = json.load(fh)
        head.append(
            "height pipelining: "
            f"{r06['speedup_block_p50']}x device-tally heights/s "
            f"({r06['sequential']['block_heights_per_s_p50']} -> "
            f"{r06['pipelined']['block_heights_per_s_p50']} per-block "
            f"p50, paired blocks on {r06.get('device', '?')}, "
            "BENCH_r06.json)"
        )
    by_num = {}
    for r in results:
        try:
            by_num[int(str(r.get("config", "")).split(":")[0])] = r
        except ValueError:
            pass
    r7 = by_num.get(7)
    if r7 and "sustained_votes_per_s" in r7:
        t512 = r7.get("sustained_trials", [])
        head.append(
            f"512 validators: {r7['sustained_votes_per_s'] / 1e3:.1f}k "
            f"(median of {len(t512)} trials"
            + (f"; spread {min(t512) / 1e3:.1f}-{max(t512) / 1e3:.1f}k"
               if t512 else "") + ", config 7)"
        )
    if r7 and "sustained_1024v_votes_per_s" in r7:
        t1k = r7.get("sustained_1024v_trials", [])
        head.append(
            "1024 validators: "
            f"{r7['sustained_1024v_votes_per_s'] / 1e3:.1f}k (median"
            + (f"; spread {min(t1k) / 1e3:.1f}-{max(t1k) / 1e3:.1f}k"
               if t1k else "") + ", config 7 probe)"
        )
    r07_path = os.path.join(REPO, "BENCH_r07.json")
    if os.path.exists(r07_path):
        with open(r07_path) as fh:
            r07 = json.load(fh)
        kern = r07.get("kernels", {})
        ratios = ", ".join(
            f"{k} lanes {v['p50_ladder_over_msm']:.2f}x"
            for k, v in sorted(kern.items(), key=lambda kv: int(kv[0]))
        )
        if ratios:
            head.append(
                f"RLC-MSM batch verify: {ratios} over the per-signature "
                "ladder (paired per-trial medians, BENCH_r07.json; "
                "benches/msm_bench.py)"
            )
        certs = r07.get("certificates", {}).get("1024")
        if certs:
            head.append(
                "quorum certificates: "
                f"{certs['certificate_bytes']} B commit proof at 1024 "
                f"validators vs {certs['sigset_bytes'] / 1e3:.1f} KB of "
                f"re-gossiped signatures ({certs['ratio']:.0f}x, O(1) "
                "re-verify; BENCH_r07.json)"
            )
    if head:
        lines += [
            "Headline sustained-verification rates (medians of the "
            "checked-in artifacts):",
            "",
            *[f"- {h}" for h in head],
            "",
        ]
    for r in results:
        lines.append(f"## {r['config']}")
        lines.append("")
        for key, v in r.items():
            if key == "config":
                continue
            lines.append(f"- {key}: {v}")
        lines.append("")
    with open(os.path.join(REPO, "BENCH.md"), "w") as fh:
        fh.write("\n".join(lines))


if __name__ == "__main__":
    main()
