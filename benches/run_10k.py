"""BASELINE config 4 at depth: 256 replicas, N heights (default 10,000 —
the full BASELINE scale; ~2.5h of EXCLUSIVE chip time at the measured
1.11 heights/s sustained rate — any concurrent TPU user serializes
launches and poisons the measurement), Ed25519 batch-verify offload in
dedup mode (one chip carrying one replica's verification load, the
per-chip work of a real deployment).

Usage: python benches/run_10k.py [heights]

Merges the result into benches/results/config_4.json as
``dedup_run_deep`` and regenerates BENCH.md.
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import run_all  # noqa: E402  (benches/ sibling)


def main():
    from hyperdrive_tpu.ops.ed25519_jax import TpuBatchVerifier

    heights = int(sys.argv[1]) if len(sys.argv) > 1 else 10_000
    ver = TpuBatchVerifier(buckets=(1024, 4096, 16384), rlc=run_all.RLC_DEFAULT)
    ver.warmup()
    # ~132k steps/height at n=256: budget steps to the requested depth.
    # record=False: the replay recorder would hold every delivery in
    # memory (~12 GB at 1k heights) and throttle the measurement.
    run = run_all._run_signed_burst(
        ver, heights=heights, dedup=True, seed=1004,
        max_steps=200_000 * heights, record=False,
    )

    path = os.path.join(run_all.RESULTS_DIR, "config_4.json")
    with open(path) as fh:
        r = json.load(fh)
    run["measured_at"] = time.strftime("%Y-%m-%d %H:%M:%S")
    run["note"] = (
        "replay recorder disabled (record=False: a 10k-height dump would "
        "serialize 1.3B deliveries — the replay workflow isn't meaningful "
        "at this depth; in-memory recording itself is now broadcast-"
        "compact and near-free). The round-2 depth decay was diagnosed to "
        "two growing structures: the then-per-delivery recorder and the "
        "virtual clock's timeout heap, which accumulated ~255 stale "
        "propose-timeouts per height because the happy path never drains "
        "the queue (VirtualClock.prune now drops timeouts below every "
        "live replica's height once the heap passes 64k entries); with "
        "both fixed, a 300-height probe shows no rate decay beyond +-5% "
        "noise"
    )
    r["dedup_run_deep"] = run
    r["cap"] = (
        f"dedup mode additionally measured at {heights} heights "
        "(dedup_run_deep) with its own measured_at; the device-tally and "
        "redundant variants run 100/20 heights — rates are sustained and "
        "height-invariant once warm; nothing here is projected"
    )
    with open(path, "w") as fh:
        json.dump(r, fh, indent=1)

    results = []
    for i in sorted(run_all.CONFIGS):
        p = os.path.join(run_all.RESULTS_DIR, f"config_{i}.json")
        with open(p) as fh:
            results.append(json.load(fh))
    run_all.write_bench_md(results)
    print(json.dumps(run))


if __name__ == "__main__":
    main()
