"""Open-loop overload bench: latency-vs-offered-load, sim + real sockets.

Produces the BENCH_r08 artifact (graceful-degradation evidence for the
backpressure spine, ROBUSTNESS.md "Overload doctrine"):

- **sim sweep** — the deterministic harness under escalating
  behavior-neutral duplicate storms, three runs per rate: unloaded
  baseline, storm with the admission gate, storm without it. The
  committed chain is asserted digest-identical across all three (the
  plateau is exact: offered load never bends the chain or sheds a
  certificate); the wall-clock curves show what the storm *costs*, and
  the gated ``admission_benefit_per_s_ratio_series`` (ungated wall /
  gated wall) pins the gate's overhead-vs-savings balance so a
  regression that makes admission more expensive than the Process work
  it sheds fails the sentinel.

- **real-socket sweep** — a live :class:`~hyperdrive_tpu.transport.
  TcpNode` with the admission gate on its wire ingress, fed by the
  open-loop :class:`~hyperdrive_tpu.load.generator.TcpLoadGenerator`
  past saturation. The storm is duplicates (shed); interleaved unique
  probe prevotes measure *admitted-work* delivery latency
  (send-schedule time -> replica inbox time). Per rate: offered /
  admitted / shed-by-class and probe p50/p95/p99; the gated series is
  p99 normalized to the lowest rate's p99 — bounded blowup, not
  collapse.

Both gated series are machine-portable ratios, nominated in the
artifact's ``benchdiff_gate`` list; the CI overload-soak job diffs a
fresh ``--quick`` run against the committed BENCH_r08.json with
``python -m hyperdrive_tpu.obs benchdiff``.

Usage::

    python benches/overload_bench.py [-o BENCH_r08.json] [--quick]
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from hyperdrive_tpu.harness.sim import Simulation  # noqa: E402
from hyperdrive_tpu.load.backpressure import (  # noqa: E402
    SHED_DUPLICATES,
    AdmissionGate,
    BackpressureController,
)
from hyperdrive_tpu.load.generator import (  # noqa: E402
    LoadProfile,
    TcpLoadGenerator,
)
from hyperdrive_tpu.load.schedule import PoissonSchedule  # noqa: E402
from hyperdrive_tpu.messages import Prevote  # noqa: E402
from hyperdrive_tpu.obs.metrics import Registry  # noqa: E402
from hyperdrive_tpu.transport import (  # noqa: E402
    TcpNode,
    encode_frame,
)

SEED = 23


def _quantile(sorted_vals, q):
    if not sorted_vals:
        return None
    return sorted_vals[min(len(sorted_vals) - 1, int(q * len(sorted_vals)))]


# ------------------------------------------------------------------ sim

def _sim(seed, target, load=None):
    extra = {} if load is None else {"load": load}
    return Simulation(
        n=4,
        target_height=target,
        seed=seed,
        timeout=1.0,
        delivery_cost=1e-3,
        certificates=True,
        observe=True,
        **extra,
    )


def _timed_run(sim):
    w0 = time.perf_counter()
    res = sim.run()
    return res, time.perf_counter() - w0


def sim_sweep(rates, target, trials):
    """The deterministic-harness sweep, three runs per (trial, rate):
    unloaded baseline, storm with the admission gate, storm without it
    (raw Process-dedup path). Virtual committed throughput under the
    behavior-neutral storm is *exactly* flat — the loaded chain equals
    the unloaded chain digest-for-digest, asserted per rate — so the
    wall-clock curves carry the degradation story: how much wall each
    offered rate costs, and how much of that cost the admission gate
    sheds before it reaches the Process (the gated
    ``admission_benefit_per_s_ratio_series``, gated >= ~1)."""
    out = {
        "rates": list(rates),
        "trials": trials,
        "digest_equal": [],
        "certs_intact": [],
        "injected": [],
        "shed": [],
        "unloaded_commits_per_s": [],
        "gated_commits_per_s": {},
        "ungated_commits_per_s": {},
        "admission_benefit_per_s_ratio_series": [],
    }
    for t in range(trials):
        base_sim = _sim(SEED + t, target)
        base, base_wall = _timed_run(base_sim)
        out["unloaded_commits_per_s"].append(round(target / base_wall, 2))
        for rate in rates:
            gated = _sim(
                SEED + t, target,
                load=LoadProfile(rate=rate, seed=SEED + t),
            )
            gres, gwall = _timed_run(gated)
            ungated = _sim(
                SEED + t, target,
                load=LoadProfile(rate=rate, seed=SEED + t,
                                 admission=False),
            )
            ures, uwall = _timed_run(ungated)
            out["gated_commits_per_s"].setdefault(str(rate), []).append(
                round(target / gwall, 2)
            )
            out["ungated_commits_per_s"].setdefault(str(rate), []).append(
                round(target / uwall, 2)
            )
            out["admission_benefit_per_s_ratio_series"].append(
                round(uwall / gwall, 4)
            )
            if t == 0:
                snap = gated.overload_snapshot()
                out["digest_equal"].append(
                    gres.commit_digest() == base.commit_digest()
                    and ures.commit_digest() == base.commit_digest()
                )
                out["certs_intact"].append(
                    all(
                        set(bc.certs) == set(lc.certs)
                        for bc, lc in zip(
                            base_sim.certifiers, gated.certifiers
                        )
                    )
                )
                out["injected"].append(snap["injected"])
                out["shed"].append(snap["shed"])
    return out


# ----------------------------------------------------------- real socket

class _ProbeSink:
    """A TcpNode 'replica' that timestamps every delivered prevote by
    its value — the receive side of the latency probes."""

    def __init__(self):
        self.recv = {}

    def propose(self, msg, stop):
        pass

    def prevote(self, msg, stop):
        self.recv.setdefault(msg.value, time.monotonic())

    def precommit(self, msg, stop):
        pass


def _probe_frames(n_arrivals, probe_every):
    """The storm frame list: one shared duplicate prevote everywhere,
    a unique probe prevote every ``probe_every``-th slot."""
    dup = encode_frame(
        Prevote(height=5, round=0, value=b"\x11" * 32, sender=b"\x22" * 32)
    )
    frames = []
    probe_slots = {}
    for k in range(n_arrivals):
        if k % probe_every == 0:
            value = k.to_bytes(32, "little")
            frames.append(
                encode_frame(
                    Prevote(
                        height=5, round=0, value=value, sender=b"\x33" * 32
                    )
                )
            )
            probe_slots[k] = value
        else:
            frames.append(dup)
    return frames, probe_slots


def socket_sweep(rates, duration, probe_every=16):
    out = {
        "rates": list(rates),
        "duration_s": duration,
        "offered": [],
        "sent": [],
        "admitted": [],
        "shed": [],
        "behind_max_s": [],
        "probe_p50_s": [],
        "probe_p95_s": [],
        "probe_p99_s": [],
        "probes_delivered": [],
        "p99_latency_ratio_series": [],
        "shed_classes_ok": True,
    }
    for i, rate in enumerate(rates):
        registry = Registry()
        ctrl = BackpressureController(registry=registry, threadsafe=True)
        ctrl.floor = SHED_DUPLICATES
        ctrl.poll()
        gate = AdmissionGate(ctrl, registry=registry, threadsafe=True)
        node = TcpNode(admission=gate, registry=registry, seed=SEED)
        sink = _ProbeSink()
        node.add_replica(sink)
        node.start()
        try:
            schedule = PoissonSchedule(rate, seed=SEED + i)
            arrivals = schedule.arrivals(duration)
            frames, probe_slots = _probe_frames(len(arrivals), probe_every)
            gen = TcpLoadGenerator(
                [("127.0.0.1", node.port)], frames, schedule,
                duration=duration,
            )
            gen.start()
            gen.join(duration + 10.0)
            time.sleep(0.3)  # let the read loop drain the tail
            lats = []
            for k, value in probe_slots.items():
                t_recv = sink.recv.get(value)
                if t_recv is not None and gen.t0 is not None:
                    lats.append(max(0.0, t_recv - (gen.t0 + arrivals[k])))
            lats.sort()
            snap = gate.snapshot()
            out["offered"].append(len(arrivals))
            out["sent"].append(gen.sent)
            out["admitted"].append(snap["admitted"])
            out["shed"].append(snap["shed"])
            out["behind_max_s"].append(round(gen.behind_max, 4))
            out["probe_p50_s"].append(_quantile(lats, 0.50))
            out["probe_p95_s"].append(_quantile(lats, 0.95))
            out["probe_p99_s"].append(_quantile(lats, 0.99))
            out["probes_delivered"].append(len(lats))
            # The pinned gate may shed ONLY behavior-neutral classes.
            if set(snap["shed"]) - {"duplicate", "stale_height"}:
                out["shed_classes_ok"] = False
        finally:
            node.stop()
    base_p99 = out["probe_p99_s"][0] if out["probe_p99_s"] else None
    if base_p99:
        out["p99_latency_ratio_series"] = [
            round(p / base_p99, 4)
            for p in out["probe_p99_s"]
            if p is not None
        ]
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("-o", "--output", default="BENCH_r08.json")
    ap.add_argument("--quick", action="store_true",
                    help="CI-sized sweep (shorter, fewer trials)")
    ns = ap.parse_args(argv)

    if ns.quick:
        sim_rates, target, trials = [1000.0, 8000.0], 6, 2
        sock_rates, duration = [1000.0, 4000.0, 12000.0], 0.6
    else:
        sim_rates, target, trials = [1000.0, 4000.0, 16000.0], 8, 3
        sock_rates, duration = [1000.0, 4000.0, 12000.0, 24000.0], 1.0

    doc = {
        "measured_at": datetime.datetime.now().strftime(
            "%Y-%m-%d %H:%M:%S"
        ),
        "benchdiff_gate": [
            "overload.sim.admission_benefit_per_s_ratio_series",
            "overload.real.p99_latency_ratio_series",
        ],
        "overload": {
            "sim": sim_sweep(sim_rates, target, trials),
            "real": socket_sweep(sock_rates, duration),
        },
    }
    ok = (
        all(doc["overload"]["sim"]["digest_equal"])
        and all(doc["overload"]["sim"]["certs_intact"])
        and doc["overload"]["real"]["shed_classes_ok"]
    )
    doc["graceful_degradation_ok"] = ok
    with open(ns.output, "w") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True)
        fh.write("\n")
    print(json.dumps({
        "artifact": ns.output,
        "graceful_degradation_ok": ok,
        "sim_admission_benefit": doc["overload"]["sim"][
            "admission_benefit_per_s_ratio_series"
        ],
        "real_p99_ratio": doc["overload"]["real"][
            "p99_latency_ratio_series"
        ],
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
