"""Merkle proof bench: incremental device hash-tree update vs full
rebuild, plus O(log n) proof-serving throughput for light clients.

Produces the BENCH_r14 artifact (the perf evidence for the
device-Merkleized state, README "Trustless reads"):

- **merkle.update_speedup** (gated) — incremental O(k log n) update
  (``update_tree_np``: one [K] leaf recompute plus one [K]
  gather-combine-scatter per level) against the full O(n) rebuild
  (``build_tree_np``) at n = 2^16 leaves across dirty fractions, on
  the host twin — the path the proof-serving replica and host
  executor actually pay per block. Every leg asserts the incremental
  tree is BIT-IDENTICAL to a rebuild of the same state — a speedup
  that drifts the root is a bug, not a result. Acceptance floor:
  >= 5x at <= 1% dirty. The jitted device twin rides along as
  ``update_speedup_device`` (informational): on CPU-emulated devices
  XLA's full rebuild is a single streamed pass whose constant factor
  beats log-n dependent scatter launches, so the asymptotic win only
  shows on the device series for sub-0.1% dirty sets; the fused
  drain (exec/device.py) already picks full-vs-incremental per block
  on exactly that tradeoff.

- **proof.serve_per_s** (gated) — ``ProofBasis.prove`` +
  ``encode_proof`` throughput on the frozen O(n) snapshot the serving
  replica answers from (pure numpy indexing, no tree hashing on the
  read path), over a Poisson-sized request batch with seeded account
  draws. Acceptance floor: >= 10k proofs/s at n = 2^16. Absolute
  rows gate by benchdiff's noise bound against the committed
  artifact, so this series assumes CI runners of the same class.

- **proof_bytes / verify_us** (informational) — wire frame size and
  client-side ``verify_inclusion`` cost vs n in {2^10, 2^13, 2^16}:
  both must grow with depth (log n), not n.

- **consensus_p99_ratio_shed** (floored in-script, not
  benchdiff-gated: p99 of a timing loop is too noisy for an 8% drift
  bound) — p99 commit-to-commit interval of a jax-free TenantShard
  consensus loop with an open-loop Poisson query storm riding the
  same thread THROUGH the AdmissionGate pinned at its shed floor,
  over the storm-free baseline. This is the overload doctrine's
  promise measured directly: when pressure rises, reads are the
  first prey and consensus p99 must not move (floor <= 2x, which is
  microseconds of classify-and-drop per gap). The always-serve ratio
  (gate at ACCEPT, every query answered inline on the consensus
  thread — the single-core worst case a real deployment avoids by
  shedding exactly as the gated row does) rides along as
  ``consensus_p99_ratio_serve``, informational.

Every timed wall is a best-of-``reps`` minimum: the measurement boxes
are single-core and preemption inflates individual runs by 2-3x, and
the minimum is the run the machine actually executed without
interference.

Usage::

    python benches/proof_bench.py [-o BENCH_r14.json] [--quick]
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", ".jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "2.0")

from hyperdrive_tpu.exec import ExecutionConfig  # noqa: E402
from hyperdrive_tpu.exec.ledger import (  # noqa: E402
    BlockSource,
    HostLedgerExecutor,
)
from hyperdrive_tpu.load import LoadProfile, PoissonSchedule  # noqa: E402
from hyperdrive_tpu.load.generator import LoadRuntime  # noqa: E402
from hyperdrive_tpu.ops.merkle import verify_inclusion  # noqa: E402
from hyperdrive_tpu.parallel.service import (  # noqa: E402
    STATUS_COMMITTED,
    encode_proof,
)

SEED = 31

#: Update-leg tree size (leaves) and dirty fractions. 2^16 is the
#: acceptance-criterion size; the fractions bracket the <= 1% floor.
UPDATE_LEAVES = 65536
DIRTY_FRACS = (0.0005, 0.01, 0.05)

#: Proof-size/verify-cost/serving sweep (accounts).
PROOF_SIZES = (1024, 8192, 65536)

#: Consensus-interference leg: committed heights per run and the
#: open-loop proof-request rate ridden on the consensus thread.
CONSENSUS_HEIGHTS = 80
SERVE_STORM_RATE = 20_000.0


def bench_update(frac: float, reps: int, iters: int) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from hyperdrive_tpu.ops.merkle import (
        build_tree_jax,
        build_tree_np,
        update_tree_jax,
        update_tree_np,
    )

    n = UPDATE_LEAVES
    rng = np.random.default_rng(SEED)
    bal = rng.integers(0, 1 << 30, size=n, dtype=np.int32)
    stk = rng.integers(0, 1 << 20, size=n, dtype=np.int32)
    k = max(1, int(n * frac))
    dirty = np.sort(rng.choice(n, size=k, replace=False)).astype(np.int32)
    bal2 = bal.copy()
    bal2[dirty] += 1

    # Parity before timing: the incremental tree must be bit-identical
    # to a full rebuild of the post-update state, on both twins.
    ref = build_tree_np(bal2, stk)
    host_tree = build_tree_np(bal, stk)
    update_tree_np(host_tree, bal2, stk, dirty)
    build_j = jax.jit(build_tree_jax)
    update_j = jax.jit(update_tree_jax)
    db, db2 = jnp.asarray(bal), jnp.asarray(bal2)
    ds, di = jnp.asarray(stk), jnp.asarray(dirty)
    tree = build_j(db, ds)
    updated = update_j(tree, db2, ds, di)
    for twin, got_tree in (("host", host_tree), ("device", updated)):
        for got, want in zip(got_tree, ref):
            if not np.array_equal(np.asarray(got), want):
                raise SystemExit(
                    f"UPDATE PARITY BROKEN at frac={frac}: {twin} "
                    f"incremental tree diverges from a full rebuild"
                )

    walls = {}
    # Host twin: re-updating with the already-applied state recomputes
    # identical nodes (clean-leaf idempotency), so iterating in place
    # is sound for timing.
    for label, fn in (
        ("full", lambda: build_tree_np(bal2, stk)),
        ("incremental",
         lambda: update_tree_np(host_tree, bal2, stk, dirty)),
    ):
        best = None
        for _ in range(reps):
            t0 = time.perf_counter()
            for _ in range(iters):
                fn()
            wall = (time.perf_counter() - t0) / iters
            best = wall if best is None else min(best, wall)
        walls[label] = best
    for label, fn in (
        ("full_dev", lambda: build_j(db2, ds)),
        ("incremental_dev", lambda: update_j(tree, db2, ds, di)),
    ):
        fn()[-1].block_until_ready()  # compiled + warm
        best = None
        for _ in range(reps):
            t0 = time.perf_counter()
            for _ in range(iters):
                out = fn()
            out[-1].block_until_ready()
            wall = (time.perf_counter() - t0) / iters
            best = wall if best is None else min(best, wall)
        walls[label] = best
    return {
        "dirty_frac": frac,
        "dirty_leaves": k,
        "full_us": round(walls["full"] * 1e6, 1),
        "incremental_us": round(walls["incremental"] * 1e6, 1),
        "speedup": round(walls["full"] / walls["incremental"], 3),
        "device_speedup": round(
            walls["full_dev"] / walls["incremental_dev"], 3
        ),
    }


def _basis(accounts: int):
    cfg = ExecutionConfig(
        accounts=accounts,
        txs_per_block=256,
        stake_every=4,
        stake_accounts=min(64, accounts // 4),
        seed=SEED,
        amount_cap=64,
        initial_balance=1_000_000,
    )
    ex = HostLedgerExecutor(cfg, source=BlockSource(cfg))
    ex.advance_to(2)
    return ex, ex.proof_basis()


def bench_proof_cost(accounts: int, reps: int, iters: int) -> dict:
    ex, basis = _basis(accounts)
    proof = basis.prove(accounts // 2)
    frame = encode_proof(1, STATUS_COMMITTED, proof)
    root = ex.roots[basis.height]
    assert verify_inclusion(
        root, proof.account, proof.balance, proof.stake, proof
    )
    best = None
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(iters):
            verify_inclusion(
                root, proof.account, proof.balance, proof.stake, proof
            )
        wall = (time.perf_counter() - t0) / iters
        best = wall if best is None else min(best, wall)
    return {
        "accounts": accounts,
        "depth": len(proof.siblings),
        "proof_bytes": len(frame),
        "verify_us": round(best * 1e6, 2),
    }


def bench_serve(accounts: int, reps: int, horizon: float) -> dict:
    import random

    _, basis = _basis(accounts)
    # Poisson-sized batch: the open-loop arrival process fixes the
    # request count; seeded draws pick the accounts. Serving is
    # CPU-bound, so the wall measures replica capacity.
    count = len(PoissonSchedule(40_000.0, seed=SEED).arrivals(horizon))
    rng = random.Random(SEED)
    targets = [rng.randrange(accounts) for _ in range(count)]
    best = None
    for _ in range(reps):
        t0 = time.perf_counter()
        for rid, account in enumerate(targets):
            encode_proof(rid, STATUS_COMMITTED, basis.prove(account))
        wall = time.perf_counter() - t0
        best = wall if best is None else min(best, wall)
    return {
        "accounts": accounts,
        "requests": count,
        "serve_per_s": round(count / best, 1),
    }


def _consensus_run(heights: int, basis, storm: str | None) -> tuple:
    from hyperdrive_tpu.load import (
        SHED_LOW_PRIORITY,
        AdmissionGate,
        BackpressureController,
    )
    from hyperdrive_tpu.load.frames import QueryFrame
    from hyperdrive_tpu.parallel.service import (
        ShardVerifyService,
        TenantShard,
    )
    from hyperdrive_tpu.verifier import NullVerifier

    svc = ShardVerifyService(NullVerifier(), max_depth=0)
    shard = TenantShard(
        "bench", n_validators=4, target_height=heights, sign=False
    ).attach_local(svc)
    rt = gate = None
    if storm is not None:
        rt = LoadRuntime(LoadProfile(rate=SERVE_STORM_RATE, seed=SEED))
        ctrl = BackpressureController()
        if storm == "shed":
            ctrl.floor = SHED_LOW_PRIORITY
        ctrl.poll()
        gate = AdmissionGate(ctrl)
    commit_t = []
    ncommits = served = 0
    t0 = time.perf_counter()
    while not shard.done:
        shard.pump(max_inflight=2)
        svc.drain()
        if rt is not None:
            for _ in range(rt.due(time.perf_counter() - t0)):
                account = served * 7919 % basis.accounts
                if gate.admit(QueryFrame(account=account)):
                    encode_proof(
                        served, STATUS_COMMITTED, basis.prove(account)
                    )
                    served += 1
        if len(shard.commits) > ncommits:
            now = time.perf_counter()
            commit_t.extend([now] * (len(shard.commits) - ncommits))
            ncommits = len(shard.commits)
    gaps = sorted(b - a for a, b in zip(commit_t, commit_t[1:]))
    p99 = gaps[min(len(gaps) - 1, int(0.99 * len(gaps)))]
    shed = gate.shed.get("query", 0) if gate is not None else 0
    return p99, served, shed


def bench_consensus(heights: int, reps: int) -> dict:
    _, basis = _basis(PROOF_SIZES[-1])
    p99 = {}
    served = shed = 0
    for storm in (None, "shed", "serve"):
        best = None
        for _ in range(reps):
            p, s, d = _consensus_run(heights, basis, storm)
            best = p if best is None else min(best, p)
            served = max(served, s)
            shed = max(shed, d)
        p99[storm] = best
    return {
        "heights": heights,
        "baseline_p99_us": round(p99[None] * 1e6, 1),
        "shed_p99_us": round(p99["shed"] * 1e6, 1),
        "serve_p99_us": round(p99["serve"] * 1e6, 1),
        "proofs_served": served,
        "queries_shed": shed,
        "p99_ratio_shed": round(p99["shed"] / p99[None], 3),
        "p99_ratio_serve": round(p99["serve"] / p99[None], 3),
    }


def run_bench(quick: bool) -> dict:
    reps = 2 if quick else 3
    iters = 5 if quick else 20
    verify_iters = 200 if quick else 2000
    horizon = 0.1 if quick else 1.0
    heights = 24 if quick else CONSENSUS_HEIGHTS

    update_rows = []
    for frac in DIRTY_FRACS:
        row = bench_update(frac, reps, iters)
        print(
            f"update n={UPDATE_LEAVES} frac={frac:<7g} "
            f"k={row['dirty_leaves']:5d} full={row['full_us']:9.1f}us "
            f"incr={row['incremental_us']:8.1f}us "
            f"speedup={row['speedup']:.2f}x "
            f"(device {row['device_speedup']:.2f}x)"
        )
        update_rows.append(row)
    for row in update_rows:
        if row["dirty_frac"] <= 0.01 and row["speedup"] < 5.0:
            raise SystemExit(
                f"incremental update speedup {row['speedup']}x at "
                f"{row['dirty_frac'] * 100:g}% dirty is below the 5x "
                f"acceptance floor (n={UPDATE_LEAVES})"
            )

    cost_rows = []
    for accounts in PROOF_SIZES:
        row = bench_proof_cost(accounts, reps, verify_iters)
        print(
            f"proof  n={accounts:6d} depth={row['depth']:2d} "
            f"bytes={row['proof_bytes']:4d} "
            f"verify={row['verify_us']:.2f}us"
        )
        cost_rows.append(row)

    serve_rows = []
    for accounts in PROOF_SIZES:
        row = bench_serve(accounts, reps, horizon)
        print(
            f"serve  n={accounts:6d} requests={row['requests']:6d} "
            f"rate={row['serve_per_s']:12.1f}/s"
        )
        serve_rows.append(row)
    for row in serve_rows:
        if row["serve_per_s"] < 10_000:
            raise SystemExit(
                f"proof serving {row['serve_per_s']}/s at "
                f"n={row['accounts']} is below the 10k proofs/s "
                f"acceptance floor"
            )

    consensus = bench_consensus(heights, reps)
    print(
        f"consensus p99 baseline={consensus['baseline_p99_us']:.1f}us "
        f"shed-storm={consensus['shed_p99_us']:.1f}us "
        f"(ratio {consensus['p99_ratio_shed']:.2f}x, "
        f"{consensus['queries_shed']} shed) "
        f"serve-inline={consensus['serve_p99_us']:.1f}us "
        f"(ratio {consensus['p99_ratio_serve']:.2f}x, "
        f"{consensus['proofs_served']} served)"
    )
    if consensus["p99_ratio_shed"] > 2.0:
        raise SystemExit(
            f"consensus p99 ratio {consensus['p99_ratio_shed']}x under "
            f"a SHED query storm exceeds the 2x acceptance ceiling — "
            f"the gate is not protecting the consensus path"
        )

    return {
        "benchdiff_gate": [
            "merkle.update_speedup",
            "proof.serve_per_s",
        ],
        "measured_at": datetime.datetime.now().strftime(
            "%Y-%m-%d %H:%M:%S"
        ),
        "merkle": {
            "seed": SEED,
            "leaves": UPDATE_LEAVES,
            "dirty_fracs": list(DIRTY_FRACS),
            "update_speedup": [r["speedup"] for r in update_rows],
            "update_speedup_device": [
                r["device_speedup"] for r in update_rows
            ],
            "update_full_us": [r["full_us"] for r in update_rows],
            "update_incremental_us": [
                r["incremental_us"] for r in update_rows
            ],
        },
        "proof": {
            "sizes": list(PROOF_SIZES),
            "depth": [r["depth"] for r in cost_rows],
            "proof_bytes": [r["proof_bytes"] for r in cost_rows],
            "verify_us": [r["verify_us"] for r in cost_rows],
            "serve_per_s": [r["serve_per_s"] for r in serve_rows],
            "serve_requests": [r["requests"] for r in serve_rows],
            "consensus_heights": consensus["heights"],
            "consensus_baseline_p99_us": consensus["baseline_p99_us"],
            "consensus_shed_p99_us": consensus["shed_p99_us"],
            "consensus_serve_p99_us": consensus["serve_p99_us"],
            "consensus_p99_ratio_shed": consensus["p99_ratio_shed"],
            "consensus_p99_ratio_serve": consensus["p99_ratio_serve"],
            "consensus_proofs_served": consensus["proofs_served"],
            "consensus_queries_shed": consensus["queries_shed"],
        },
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("-o", "--output", default="BENCH_r14.json")
    ap.add_argument(
        "--quick",
        action="store_true",
        help="CI mode: fewer iters and best-of-2 walls (series shapes "
        "unchanged, so benchdiff compares cleanly)",
    )
    ns = ap.parse_args(argv)
    doc = run_bench(ns.quick)
    with open(ns.output, "w") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True)
        fh.write("\n")
    print(f"wrote {ns.output}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
