"""Round-7 bench: RLC-MSM batch verification vs the per-signature
ladder, plus the O(1) quorum-certificate size sweep.

Usage:
    python benches/msm_bench.py [--lanes 16384 65536] [--trials 5]
        [-o BENCH_r07.json]

The kernel comparison is PAIRED the way every r05/r06 artifact is:
each trial times BOTH legs back to back — the 64-window per-signature
ladder (``verify_kernel``) and the RLC batch equation whose two
Pippenger MSMs reduce the whole batch in one combined check
(``rlc_kernel`` → ``ops/msm.py``) — with the leg order alternating per
trial so drift cannot rank them by when they ran. The headline is the
per-trial ladder/msm wall ratio's median at each lane count.

The certificate sweep measures marshalled ``QuorumCertificate`` bytes
at 256/512/1024 validators (constant but for the n/8 signer bitmap)
against the 64(2f+1)-byte signature set a commit proof would otherwise
re-gossip, and re-verifies a freshly minted certificate to time the
O(1) check. A culprit-isolation leg plants one forged lane in an
otherwise honest batch and asserts the RLC path's fallback mask equals
the ladder's exactly — the artifact's ``culprit_parity`` flag.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", os.path.join(REPO, ".jax_cache"))
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "2.0")


def _signed_items(n: int, distinct_keys: int = 32):
    from hyperdrive_tpu.crypto.keys import KeyPair

    kps = [
        KeyPair.deterministic(i.to_bytes(4, "little"))
        for i in range(distinct_keys)
    ]
    items = []
    for i in range(n):
        kp = kps[i % distinct_keys]
        d = hashlib.sha256(b"r07-%d" % i).digest()
        items.append((kp.public, d, kp.sign_digest(d)))
    return items


def kernel_comparison(lanes: list, trials: int) -> dict:
    """Paired ladder vs RLC-MSM wall times at each lane count."""
    import numpy as np

    import jax.numpy as jnp

    from hyperdrive_tpu.ops.ed25519_jax import (
        Ed25519BatchHost,
        make_rlc_fn,
        make_verify_fn,
        rlc_scalars,
    )
    from hyperdrive_tpu.ops.msm import msm_plan

    base = 256
    host = Ed25519BatchHost(buckets=(base,))
    arrays, prevalid, _ = host.pack(_signed_items(base))
    vfn, rfn = make_verify_fn(), make_rlc_fn()

    out = {}
    for n in lanes:
        reps = n // base
        arrs = tuple(np.tile(a, (reps, 1)) for a in arrays)
        pv = np.tile(prevalid, reps)
        m_nib, z_nib, c_nib = rlc_scalars(arrs[5], arrs[6], pv, b"r07")
        dev = [jnp.asarray(a) for a in arrs]
        dm, dz, dc = (jnp.asarray(x) for x in (m_nib, z_nib, c_nib))

        t0 = time.time()
        np.asarray(vfn(*dev))
        warm_ladder = time.time() - t0
        t0 = time.time()
        assert bool(rfn(*dev[:5], dm, dz, dc))
        warm_msm = time.time() - t0

        rows = []
        for t in range(trials):
            legs = {}
            for leg in ("ladder", "msm") if t % 2 == 0 else ("msm", "ladder"):
                t0 = time.time()
                if leg == "ladder":
                    np.asarray(vfn(*dev))
                else:
                    bool(rfn(*dev[:5], dm, dz, dc))
                legs[leg] = time.time() - t0
            rows.append(legs)
            print(f"  lanes={n} trial={t} {legs}", file=sys.stderr)
        ratios = sorted(r["ladder"] / r["msm"] for r in rows)
        out[str(n)] = {
            "trials": rows,
            "p50_ladder_over_msm": ratios[len(ratios) // 2],
            "warmup_s": {"ladder": warm_ladder, "msm": warm_msm},
            "msm_plan_64w": msm_plan(n, 64),
        }
    return out


def culprit_parity(n: int = 64) -> dict:
    """One forged lane: the RLC reject must isolate the exact culprit
    the ladder isolates (fallback re-verify), masks bit-identical."""
    import numpy as np

    from hyperdrive_tpu.ops.ed25519_jax import TpuBatchVerifier

    items = _signed_items(n)
    # Forge with a WELL-FORMED signature (same key, wrong digest): it
    # survives host prevalidation, so the reject must come from the RLC
    # combined equation and the per-signature fallback must isolate it.
    from hyperdrive_tpu.crypto.keys import KeyPair

    kp = KeyPair.deterministic((n - 1).to_bytes(4, "little"))
    wrong = kp.sign_digest(hashlib.sha256(b"r07-forged").digest())
    items[-1] = (items[-1][0], items[-1][1], wrong)

    ladder = TpuBatchVerifier(buckets=(n,), rlc=False)
    rlc = TpuBatchVerifier(buckets=(n,), rlc=True)
    m_l = np.asarray(ladder.verify_signatures(items))
    m_r = np.asarray(rlc.verify_signatures(items))
    return {
        "masks_equal": bool((m_l == m_r).all()),
        "culprit_isolated": bool(m_l[:-1].all() and not m_l[-1]),
        "rlc_fallbacks": rlc.rlc_fallbacks,
        "transcript_bytes": len(rlc.last_transcript),
    }


def certificate_sweep() -> dict:
    """Marshalled certificate bytes vs validator count, one O(1)
    re-verify timed per size."""
    from hyperdrive_tpu.certificates import (
        Certifier,
        certificate_size,
        marshal_certificate,
    )
    from hyperdrive_tpu.codec import Writer

    rows = {}
    for n in (256, 512, 1024):
        f = (n - 1) // 3
        validators = [
            hashlib.sha256(b"v%d" % i).digest() for i in range(n)
        ]
        c = Certifier(validators, f, transcript_source=lambda: b"\x07" * 32)
        cert = c.observe_commit(1, 0, b"r07-value", validators[: 2 * f + 1])
        w = Writer()
        marshal_certificate(cert, w)
        t0 = time.time()
        ok = c.verify(cert)
        verify_s = time.time() - t0
        assert ok and len(w.data()) == certificate_size(n)
        rows[str(n)] = {
            "certificate_bytes": len(w.data()),
            "sigset_bytes": 64 * (2 * f + 1),
            "ratio": 64 * (2 * f + 1) / len(w.data()),
            "o1_verify_s": verify_s,
        }
    return rows


def pipelined_cert_digest_check() -> dict:
    """Pipelined and sequential schedules must mint identical commit
    AND certificate chains (the r06 guarantee extended to certs)."""
    from hyperdrive_tpu.harness.sim import Simulation

    kw = dict(
        n=4, target_height=6, seed=7, sign=True, burst=True,
        certificates=True,
    )
    seq = Simulation(**kw).run()
    pipe = Simulation(pipeline_heights=True, **kw).run()
    return {
        "commit_digests_equal": seq.commit_digest() == pipe.commit_digest(),
        "cert_digests_equal": seq.cert_digests == pipe.cert_digests,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--lanes", type=int, nargs="+", default=[16384, 65536])
    ap.add_argument("--trials", type=int, default=5)
    ap.add_argument("--skip-kernels", action="store_true",
                    help="certificate + parity legs only (no big compiles)")
    ap.add_argument("-o", "--out", default=os.path.join(REPO, "BENCH_r07.json"))
    args = ap.parse_args(argv)

    result = {
        "measured_at": time.strftime("%Y-%m-%d %H:%M:%S"),
        "certificates": certificate_sweep(),
        "pipelined": pipelined_cert_digest_check(),
        "culprit": culprit_parity(),
    }
    if not args.skip_kernels:
        result["kernels"] = kernel_comparison(args.lanes, args.trials)
    with open(args.out, "w") as fh:
        json.dump(result, fh, indent=1)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
