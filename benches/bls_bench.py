"""BLS12-381 aggregation bench: device MSM/aggregation against the host
fold, aggregate-certificate size + verify cost against committee size,
and the paired EdDSA-batch-vs-BLS-aggregate verification economics.

Produces the BENCH_r10 artifact (the evidence for ISSUE 13's
first-class BLS device path):

- **device vs host aggregation** — the committee-width masked G1 sum
  (the aggregate-pubkey / aggregate-signature inner loop) on the
  fixed-shape device tree (ops/g1.py aggregate_kernel) against the
  serial host fold (crypto/bls.py aggregate_signatures). The gated
  ``device_vs_host_agg_speedup`` ratio series divides the runner's
  speed out; the 4096-lane entry is the headline — the device tree
  must WIN there (the host fold is O(n) bigint inversions; the tree is
  log2(n) branch-free vectorized levels).

- **certificate economics** — wire size per committee size plus the
  gated ``bls_sig_overhead_bytes`` series (the constant-48-byte wire
  invariant; exact ints, zero noise bound) and the light-client verify
  wall: one pairing + n G2 pubkey adds, no transcript trust, against
  the EdDSA path's n per-signature checks.

- **batched launcher** — B independent masked sums through ONE vmapped
  G1SumLauncher launch (the overlay's per-level merge shape) vs B
  sequential device calls.

Wall-clock rows are informational; the gated series are the exact-int
certificate sizes and the device/host ratio (both machine-portable).
Quick and full mode compute every GATED series over the same committee
sizes, so the CI diff of a fresh --quick run against the committed
full artifact cannot flake on series shape.

Usage::

    python benches/bls_bench.py [-o BENCH_r10.json] [--quick]
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", ".jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "2.0")

#: Committee sizes for every GATED series — identical in quick and full
#: mode (see module docstring).
AGG_SIZES = (256, 1024, 4096)

#: EdDSA batch-verify legs (informational wall rows): quick mode skips
#: the 4096-signature ladder run.
EDDSA_QUICK = (256, 1024)
EDDSA_FULL = (256, 1024, 4096)

SEED = 31


def _derive_points(n):
    """n distinct G1 points by a doubling/adding chain — aggregation-
    shaped inputs without paying n scalar multiplications."""
    from hyperdrive_tpu.crypto import bls

    pts, p = [], bls.G1_GEN
    for i in range(n):
        p = bls.g1_double(p) if i % 3 else bls.g1_add(p, bls.G1_GEN)
        pts.append(p)
    return pts


def _timed(fn, *args, repeat=3):
    best = None
    out = None
    for _ in range(repeat):
        t0 = time.time()
        out = fn(*args)
        dt = time.time() - t0
        best = dt if best is None else min(best, dt)
    return out, best


def bench_aggregation(doc):
    """Device tree vs host fold at each committee width."""
    from hyperdrive_tpu.crypto import bls
    from hyperdrive_tpu.ops import g1 as g1k

    host_wall, dev_wall, speedup, match = [], [], [], []
    for n in AGG_SIZES:
        pts = _derive_points(n)
        h, th = _timed(bls.aggregate_signatures, pts)
        # First device call pays the (cached) compile; time steady state.
        g1k.aggregate_points(pts, width=n)
        d, td = _timed(g1k.aggregate_points, pts, n)
        host_wall.append(round(th, 4))
        dev_wall.append(round(td, 4))
        speedup.append(round(th / td, 4))
        match.append(d == h)
        print(f"  agg n={n}: host {th:.3f}s device {td:.3f}s "
              f"speedup {th / td:.2f}x match={d == h}")
    doc["host_agg_wall_s"] = host_wall
    doc["device_agg_wall_s"] = dev_wall
    doc["device_vs_host_agg_speedup"] = speedup
    doc["device_agg_matches_host"] = all(match)
    return all(match)


def bench_certificates(doc):
    """Exact wire sizes + the light-client verify wall per size. The
    committee shares two keypairs (pubkey values may repeat across the
    whitelist; the pairing economics are identical), so the bench pays
    two keygens instead of 4096."""
    from hyperdrive_tpu.certificates import (
        Certifier, certificate_size, verify_bls_certificate,
    )
    from hyperdrive_tpu.crypto import bls

    class _CachedSigner:
        # Mint-side setup only (the mint wall is not a reported
        # series): every counted signer shares one of two keys and
        # signs the same commit message, so sign once per (key, msg)
        # instead of paying ~quorum G1 scalar-mults per size.
        def __init__(self, kp):
            self._kp, self._sigs = kp, {}
            self.pk_bytes = kp.pk_bytes

        def sign(self, msg):
            if msg not in self._sigs:
                self._sigs[msg] = self._kp.sign(msg)
            return self._sigs[msg]

    kp0 = _CachedSigner(bls.bls_keypair_from_identity(b"bls-bench-0"))
    kp1 = _CachedSigner(bls.bls_keypair_from_identity(b"bls-bench-1"))
    size_plain, size_bls, verify_wall, verify_ok = [], [], [], []
    for n in AGG_SIZES:
        ids = [bytes([i & 0xFF, i >> 8]) * 16 for i in range(n)]
        keyring = {s: (kp0 if i % 2 else kp1) for i, s in enumerate(ids)}
        quorum = 2 * ((n - 1) // 3) + 1
        c = Certifier(ids, (n - 1) // 3,
                      transcript_source=lambda: b"\x5a" * 32,
                      bls_keyring=keyring)
        cert = c.observe_commit(3, 0, b"block", ids[:quorum])
        pks = c.bls_pubkeys()
        ok, tw = _timed(
            verify_bls_certificate, cert, pks, quorum, repeat=1
        )
        size_plain.append(certificate_size(n))
        size_bls.append(certificate_size(n, with_bls=True))
        verify_wall.append(round(tw, 4))
        verify_ok.append(bool(ok))
        print(f"  cert n={n}: {size_bls[-1]}B wire "
              f"({size_plain[-1]}B plain) light-client verify {tw:.2f}s "
              f"ok={ok}")
    doc["cert_size_bytes_plain"] = size_plain
    doc["cert_size_bytes_with_bls"] = size_bls
    # The wire invariant worth gating: the aggregate costs a constant
    # 48 bytes at every committee size. A constant series has zero MAD,
    # so the benchdiff bound collapses to the 8% floor and ANY growth
    # trips the sentinel (the raw size series' cross-size spread would
    # swallow a regression in its noise bound).
    doc["bls_sig_overhead_bytes"] = [
        b - p for b, p in zip(size_bls, size_plain)
    ]
    doc["lightclient_verify_wall_s"] = verify_wall
    return all(verify_ok)


def bench_eddsa_pair(doc, sizes):
    """The path BLS replaces: verifying a quorum's worth of individual
    Ed25519 signatures through the device batch verifier, as signatures
    per second, against the BLS side's signers-per-second (committee
    size over the one light-client verify)."""
    import hashlib

    from hyperdrive_tpu.crypto.keys import KeyPair
    from hyperdrive_tpu.ops.ed25519_jax import TpuBatchVerifier

    kp = KeyPair.deterministic(b"bls-bench-eddsa")
    verifier = TpuBatchVerifier(buckets=(256,))
    walls, per_s = [], []
    for n in sizes:
        items = []
        for i in range(n):
            digest = hashlib.sha256(b"m%d" % i).digest()
            items.append((kp.public, digest, kp.sign_digest(digest)))
        verifier.verify_signatures(items[:8])  # absorb compile
        masks, tw = _timed(verifier.verify_signatures, items, repeat=1)
        assert all(masks)
        walls.append(round(tw, 4))
        per_s.append(round(n / tw, 1))
        print(f"  eddsa n={n}: batch verify {tw:.3f}s "
              f"({n / tw:,.0f} sigs/s)")
    doc["eddsa_batch_sizes"] = list(sizes)
    doc["eddsa_batch_verify_wall_s"] = walls
    doc["eddsa_batch_verify_per_s"] = per_s
    doc["bls_signers_per_s"] = [
        round(n / t, 1)
        for n, t in zip(AGG_SIZES, doc["lightclient_verify_wall_s"])
    ]


def bench_launcher(doc):
    """B masked sums in one vmapped launch vs B sequential calls."""
    from hyperdrive_tpu.devsched.queue import DeviceWorkQueue
    from hyperdrive_tpu.ops import g1 as g1k

    width, batch = 256, 8
    pts = _derive_points(width)
    payloads = [pts[i::batch] for i in range(batch)]

    def batched():
        queue = DeviceWorkQueue()
        launcher = g1k.G1SumLauncher(width=width)
        futs = [queue.submit(launcher, p, generation=0) for p in payloads]
        queue.drain()
        return [f.result() for f in futs]

    def sequential():
        return [g1k.aggregate_points(p, width=width) for p in payloads]

    batched()  # absorb the vmapped compile
    got_b, tb = _timed(batched)
    got_s, ts = _timed(sequential)
    assert got_b == got_s
    doc["launcher"] = {
        "batch": batch,
        "width": width,
        "batched_wall_s": round(tb, 4),
        "sequential_wall_s": round(ts, 4),
        "batch_speedup": round(ts / tb, 4),
    }
    print(f"  launcher: {batch}x{width} batched {tb:.3f}s "
          f"sequential {ts:.3f}s ({ts / tb:.2f}x)")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("-o", "--out", default="BENCH_r10.json")
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args(argv)

    bls: dict = {"sizes": list(AGG_SIZES), "seed": SEED}
    print("aggregation (device tree vs host fold):")
    agg_ok = bench_aggregation(bls)
    print("certificates (wire size + light-client verify):")
    cert_ok = bench_certificates(bls)
    print("paired EdDSA batch verify:")
    bench_eddsa_pair(bls, EDDSA_QUICK if args.quick else EDDSA_FULL)
    print("batched G1-sum launcher:")
    bench_launcher(bls)

    doc = {
        "bls_ok": bool(agg_ok and cert_ok),
        "benchdiff_gate": [
            "bls.device_vs_host_agg_speedup",
            "bls.bls_sig_overhead_bytes",
        ],
        "measured_at": datetime.datetime.now().strftime(
            "%Y-%m-%d %H:%M:%S"
        ),
        "bls": bls,
    }
    with open(args.out, "w") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True)
        fh.write("\n")
    print(f"wrote {args.out} (bls_ok={doc['bls_ok']})")
    return 0 if doc["bls_ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
