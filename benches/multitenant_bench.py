"""Multi-tenant continuous-batching bench: ONE shared verify service
against M per-tenant queues.

Produces the BENCH_r11 artifact (the serving evidence for ROADMAP item
2: M shard-consensus instances funneling verify windows into one
continuously-batching :class:`ShardVerifyService`):

- **shared-vs-dedicated speedup** (gated) — at each M, the same
  M-tenant workload (full committee precommit windows, real Ed25519,
  the device batch verifier) runs twice: through ONE shared service
  (every wave coalesces all M windows into one launch) and through M
  dedicated per-tenant services (M launches per wave — the per-launch
  dispatch+pad bill paid M times). Aggregate votes/s ratio per M;
  the artifact refuses to save if sharing loses at M >= 4.

- **fairness p99 speedup** (gated) — a firehose tenant saturates the
  shared queue with wide windows while a small victim tenant commits
  alongside; the victim's p99 commit latency under the
  DeficitRoundRobin drain policy vs the FIFO drain. DRR caps rows per
  launch, so the victim rides small launches instead of waiting on the
  firehose's coalesced slab — the ratio is the fairness win, and the
  DRR leg must also hold the starvation bound it promises.

- **digest neutrality** (ride-along assert) — at every M, each
  tenant's shared-service commit digest is byte-identical to its
  dedicated-queue run: continuous batching changes scheduling, never
  results.

Wall-clock seconds ride along informationally; the gated series are
paired ratios on the same machine, so they are runner-portable.

Usage::

    python benches/multitenant_bench.py [-o BENCH_r11.json] [--quick]
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR", os.path.join(REPO, ".jax_cache")
)

from hyperdrive_tpu.devsched import DeficitRoundRobin  # noqa: E402
from hyperdrive_tpu.parallel.service import (  # noqa: E402
    ShardVerifyService,
    TenantShard,
)

SEED = 37
#: Rows per tenant window (full committee width). Pads to the device
#: verifier's 64-lane bucket, so dedicated queues pay the whole bucket
#: per tenant while the shared service fills it across tenants.
VALIDATORS = 16
#: Same M series in quick and full mode — the gated series must be
#: shape-identical to the committed artifact on any runner; quick mode
#: trims heights, never the series.
M_SERIES = (1, 2, 4, 8, 16)
FULL_HEIGHTS = 4
QUICK_HEIGHTS = 2
FAIRNESS_REPS = 3

#: Fairness leg shape: the firehose's window alone overflows the DRR
#: row budget (progress guarantee gives it solo launches), the victim's
#: fits many times over.
FIRE_VALIDATORS = 48
VICTIM_VALIDATORS = 4
VICTIM_HEIGHTS = 10
DRR_KW = dict(capacity_rows=16, quantum_rows=4, starve_after=3)


def _verifier():
    from hyperdrive_tpu.ops.ed25519_jax import TpuBatchVerifier

    return TpuBatchVerifier()


def _drive_waves(services, tenants, heights: int) -> float:
    """Lock-step waves: every tenant submits one window, then every
    service drains once. With one shared service that is one coalesced
    launch per wave; with per-tenant services it is M launches. Returns
    the wall seconds across all waves."""
    t0 = time.perf_counter()
    for _ in range(heights):
        for t in tenants:
            t.pump(max_inflight=1)
        for svc in services:
            svc.drain()
    return time.perf_counter() - t0


def _run_m(m: int, heights: int, verifier) -> dict:
    # Shared leg: one service, one launch per wave for all m tenants.
    shared_svc = ShardVerifyService(verifier, max_depth=0)
    shared = [
        TenantShard(
            f"tenant-{i}", n_validators=VALIDATORS, target_height=heights
        ).attach_local(shared_svc)
        for i in range(m)
    ]
    # Warmup wave (compile + caches) outside the timed window, for both
    # legs identically: one extra height beyond the measured target.
    for t in shared:
        t.target_height += 1
    _drive_waves([shared_svc], shared, 1)
    for t in shared:
        # The warmup commit may carry a one-time XLA compile; keep it
        # out of the reported latency quantiles like it is kept out of
        # the walls.
        t.commit_latencies.clear()
    shared_wall = _drive_waves([shared_svc], shared, heights)
    assert all(t.done and not t.rejected for t in shared)

    # Dedicated leg: the same workload, one service (queue) per tenant.
    # The verifier object is shared so both legs use the same compiled
    # kernels — the difference under test is the launch schedule.
    dedicated_svcs = [
        ShardVerifyService(verifier, max_depth=0) for _ in range(m)
    ]
    dedicated = [
        TenantShard(
            f"tenant-{i}", n_validators=VALIDATORS,
            target_height=heights + 1,
        ).attach_local(svc)
        for i, svc in enumerate(dedicated_svcs)
    ]
    _drive_waves(dedicated_svcs, dedicated, 1)
    dedicated_wall = _drive_waves(dedicated_svcs, dedicated, heights)
    assert all(t.done and not t.rejected for t in dedicated)

    digest_equal = all(
        a.commit_digest() == b.commit_digest()
        for a, b in zip(shared, dedicated)
    )
    rows = m * heights * VALIDATORS
    lat = sorted(
        x for t in shared for x in t.commit_latencies
    )
    return {
        "m": m,
        "shared_wall_s": round(shared_wall, 4),
        "dedicated_wall_s": round(dedicated_wall, 4),
        "shared_votes_per_s": round(rows / shared_wall, 1),
        "dedicated_votes_per_s": round(rows / dedicated_wall, 1),
        "speedup": round(dedicated_wall / shared_wall, 4),
        "shared_launches": shared_svc.queue.launches,
        "dedicated_launches": sum(
            s.queue.launches for s in dedicated_svcs
        ),
        "digest_equal": digest_equal,
        "p50_s": round(lat[len(lat) // 2], 4),
        "p99_s": round(lat[min(len(lat) - 1, int(0.99 * len(lat)))], 4),
    }


def _fairness_rep(policy, verifier) -> float:
    """One saturated run; returns the VICTIM's p99 commit latency."""
    svc = ShardVerifyService(verifier, max_depth=0, policy=policy)
    fire = TenantShard(
        "firehose", n_validators=FIRE_VALIDATORS,
        target_height=VICTIM_HEIGHTS * 2,
    ).attach_local(svc)
    victim = TenantShard(
        "victim", n_validators=VICTIM_VALIDATORS,
        target_height=VICTIM_HEIGHTS,
    ).attach_local(svc)
    guard = 0
    while not victim.done:
        fire.pump(max_inflight=4)
        victim.pump(max_inflight=1)
        svc.drain()
        guard += 1
        if guard > 100 * VICTIM_HEIGHTS:
            raise SystemExit("fairness leg stalled")
    assert not victim.rejected
    lat = sorted(victim.commit_latencies)
    return lat[min(len(lat) - 1, int(0.99 * len(lat)))]


def run_bench(quick: bool) -> dict:
    heights = QUICK_HEIGHTS if quick else FULL_HEIGHTS
    verifier = _verifier()
    rows = []
    for m in M_SERIES:
        r = _run_m(m, heights, verifier)
        rows.append(r)
        print(
            f"M={m:3d} shared={r['shared_votes_per_s']:8.1f} votes/s "
            f"({r['shared_launches']} launches)  "
            f"dedicated={r['dedicated_votes_per_s']:8.1f} votes/s "
            f"({r['dedicated_launches']} launches)  "
            f"speedup={r['speedup']:.2f}x"
        )
        if not r["digest_equal"]:
            raise SystemExit(
                f"DIGEST MISMATCH at M={m}: shared service diverged "
                f"from dedicated queues"
            )
        if m >= 4 and r["speedup"] < 1.0:
            raise SystemExit(
                f"shared service LOST to dedicated queues at M={m} "
                f"({r['speedup']:.2f}x) — continuous batching is not "
                f"paying for itself; artifact refused"
            )

    fifo_p99, drr_p99, fairness = [], [], []
    deferrals, forced = [], []
    for rep in range(FAIRNESS_REPS):
        f99 = _fairness_rep(None, verifier)
        policy = DeficitRoundRobin(**DRR_KW)
        d99 = _fairness_rep(policy, verifier)
        if policy.max_deferrals > policy.starve_after:
            raise SystemExit(
                f"starvation bound violated: max_deferrals="
                f"{policy.max_deferrals} > starve_after="
                f"{policy.starve_after}"
            )
        fifo_p99.append(round(f99, 4))
        drr_p99.append(round(d99, 4))
        fairness.append(round(f99 / d99, 4))
        deferrals.append(policy.deferred_total)
        forced.append(policy.forced_total)
        print(
            f"fairness rep={rep} victim p99: fifo={f99:.4f}s "
            f"drr={d99:.4f}s speedup={f99 / d99:.2f}x "
            f"(deferred={policy.deferred_total} "
            f"forced={policy.forced_total})"
        )

    doc = {
        "benchdiff_gate": [
            "multitenant.shared_vs_dedicated_speedup_series",
            "multitenant.fairness_p99_speedup_series",
        ],
        "measured_at": datetime.datetime.now().strftime(
            "%Y-%m-%d %H:%M:%S"
        ),
        "multitenant_ok": all(r["digest_equal"] for r in rows),
        "multitenant": {
            "seed": SEED,
            "validators": VALIDATORS,
            "heights": heights,
            "tenants_series": [r["m"] for r in rows],
            "shared_vs_dedicated_speedup_series": [
                r["speedup"] for r in rows
            ],
            "shared_votes_per_s": [r["shared_votes_per_s"] for r in rows],
            "dedicated_votes_per_s": [
                r["dedicated_votes_per_s"] for r in rows
            ],
            "shared_wall_s": [r["shared_wall_s"] for r in rows],
            "dedicated_wall_s": [r["dedicated_wall_s"] for r in rows],
            "shared_launches": [r["shared_launches"] for r in rows],
            "dedicated_launches": [r["dedicated_launches"] for r in rows],
            "digest_equal": [r["digest_equal"] for r in rows],
            "commit_latency_p50_s": [r["p50_s"] for r in rows],
            "commit_latency_p99_s": [r["p99_s"] for r in rows],
            "fairness": {
                "fire_validators": FIRE_VALIDATORS,
                "victim_validators": VICTIM_VALIDATORS,
                "victim_heights": VICTIM_HEIGHTS,
                "drr": DRR_KW,
                "fifo_victim_p99_s": fifo_p99,
                "drr_victim_p99_s": drr_p99,
                "deferred_total": deferrals,
                "forced_total": forced,
            },
            "fairness_p99_speedup_series": fairness,
        },
    }
    return doc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("-o", "--output", default="BENCH_r11.json")
    ap.add_argument(
        "--quick",
        action="store_true",
        help="CI mode: same M series, fewer heights per leg",
    )
    ns = ap.parse_args(argv)
    doc = run_bench(ns.quick)
    with open(ns.output, "w") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True)
        fh.write("\n")
    print(f"wrote {ns.output}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
