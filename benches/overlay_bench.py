"""Aggregation-overlay bench: commit latency and message complexity
vs committee size, overlay against the all-to-all baseline.

Produces the BENCH_r09 artifact (the scaling evidence for the
Byzantine-resilient aggregation overlay, ROBUSTNESS.md "Aggregation
doctrine"):

- **virtual commit latency** — the sim's clock advances one
  ``delivery_cost`` per network message (per overlay FRAME, however
  many constituent votes its mask carries), so virtual time per
  committed height IS the message-complexity curve, deterministic and
  machine-portable: all-to-all pays O(n^2) votes per height, the
  overlay O(n log n) frames. The gated ``latency_vs_n_growth`` series
  is the overlay's latency ratio across consecutive 4x committee
  steps — ~4-6 per step for n log n (vs 16 for n^2) — so aggregation
  quietly degrading back toward all-to-all fan-out fails the CI
  sentinel on any runner.

- **digest neutrality** — at every size both legs run, the bench
  asserts the overlay's committed chain is byte-identical to the
  all-to-all baseline's (aggregation changes the transport, never the
  agreed values).

- **mega-committee leg** (full mode only) — one SIGNED run at
  n = 4096 through the overlay: Ed25519 verification batched per
  aggregation level through the DeviceWorkQueue, each vote verified
  once network-wide. All-to-all at that size would be ~16.7M vote
  deliveries per height; the bench does not attempt it.

Wall-clock seconds ride along as informational rows (absolute wall is
not gated — the virtual-time ratios are the portable signal).

Usage::

    python benches/overlay_bench.py [-o BENCH_r09.json] [--quick]
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from hyperdrive_tpu.harness.sim import Simulation  # noqa: E402
from hyperdrive_tpu.overlay import OverlayConfig  # noqa: E402

SEED = 29
TARGET = 2
DELIVERY_COST = 1e-3

#: Committee sizes per mode. Baseline (all-to-all) stops earlier than
#: the overlay: n^2 Python deliveries per height get prohibitive right
#: where the overlay is just warming up — which is the point.
QUICK_SIZES = (16, 64, 256, 1024)
FULL_SIZES = (16, 64, 256, 1024, 4096)
QUICK_BASELINE_MAX = 256
FULL_BASELINE_MAX = 1024

#: Above this size: batched constituent ingest, no ScenarioRecord (the
#: record would hold millions of delivered-vote tuples), and signed
#: consensus so the mega-committee leg exercises the device-batched
#: verify path the overlay exists to feed.
MEGA = 4096


def _run(n: int, overlay: bool, sign: bool = False):
    kw: dict = {}
    if overlay:
        kw["overlay"] = OverlayConfig(coalesce_ingest=(n >= 1024))
    if n >= MEGA:
        kw["record"] = False
    sim = Simulation(
        n=n,
        seed=SEED,
        target_height=TARGET,
        delivery_cost=DELIVERY_COST,
        sign=sign,
        **kw,
    )
    t0 = time.perf_counter()
    res = sim.run(max_steps=200_000_000)
    wall = time.perf_counter() - t0
    if not res.completed:
        raise SystemExit(
            f"overlay bench run n={n} overlay={overlay} stalled at "
            f"heights={res.heights[:8]}..."
        )
    heights = min(res.heights)
    out = {
        "n": n,
        "wall_s": round(wall, 3),
        "vt_per_commit": round(res.virtual_time / heights, 4),
        "deliveries_per_commit": round(res.steps / heights, 1),
        "digest": res.commit_digest(up_to=TARGET),
    }
    if overlay:
        snap = sim.overlay_snapshot()
        out["frames_per_commit"] = round(snap["frames"] / heights, 1)
        out["verify_rows"] = snap["verify_rows"]
        out["demoted"] = snap["scores"]["demoted"]
    return out


def run_bench(quick: bool) -> dict:
    sizes = QUICK_SIZES if quick else FULL_SIZES
    baseline_max = QUICK_BASELINE_MAX if quick else FULL_BASELINE_MAX
    base_rows = []
    ov_rows = []
    digest_equal = []
    for n in sizes:
        sign = n >= MEGA
        ov = _run(n, overlay=True, sign=sign)
        print(
            f"overlay    n={n:5d} vt/commit={ov['vt_per_commit']:10.3f} "
            f"frames/commit={ov['frames_per_commit']:10.1f} "
            f"wall={ov['wall_s']:.1f}s" + (" [signed]" if sign else "")
        )
        ov_rows.append(ov)
        if n <= baseline_max:
            base = _run(n, overlay=False)
            print(
                f"all-to-all n={n:5d} vt/commit={base['vt_per_commit']:10.3f} "
                f"deliveries/commit={base['deliveries_per_commit']:10.1f} "
                f"wall={base['wall_s']:.1f}s"
            )
            base_rows.append(base)
            eq = ov["digest"] == base["digest"]
            digest_equal.append(eq)
            if not eq:
                raise SystemExit(
                    f"DIGEST MISMATCH at n={n}: overlay chain diverged "
                    f"from the all-to-all baseline"
                )
    growth = [
        round(b["vt_per_commit"] / a["vt_per_commit"], 4)
        for a, b in zip(ov_rows, ov_rows[1:])
    ]
    print(f"latency_vs_n_growth (per 4x committee step): {growth}")
    doc = {
        "benchdiff_gate": ["overlay.latency_vs_n_growth"],
        "measured_at": datetime.datetime.now().strftime(
            "%Y-%m-%d %H:%M:%S"
        ),
        "aggregation_ok": all(digest_equal),
        "overlay": {
            "seed": SEED,
            "target_height": TARGET,
            "sizes": [r["n"] for r in ov_rows],
            "baseline_sizes": [r["n"] for r in base_rows],
            "vt_per_commit": [r["vt_per_commit"] for r in ov_rows],
            "vt_per_commit_all_to_all": [
                r["vt_per_commit"] for r in base_rows
            ],
            "deliveries_per_commit": [
                r["deliveries_per_commit"] for r in ov_rows
            ],
            "deliveries_per_commit_all_to_all": [
                r["deliveries_per_commit"] for r in base_rows
            ],
            "frames_per_commit": [r["frames_per_commit"] for r in ov_rows],
            "latency_vs_n_growth": growth,
            "digest_equal": digest_equal,
            "signed_mega_committee": next(
                (
                    {
                        "n": r["n"],
                        "verify_rows": r["verify_rows"],
                        "wall_s": r["wall_s"],
                        "demoted": r["demoted"],
                    }
                    for r in ov_rows
                    if r["n"] >= MEGA
                ),
                None,
            ),
            "wall_s": [r["wall_s"] for r in ov_rows],
            "wall_s_all_to_all": [r["wall_s"] for r in base_rows],
        },
    }
    return doc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("-o", "--output", default="BENCH_r09.json")
    ap.add_argument(
        "--quick",
        action="store_true",
        help="CI mode: committees up to 1024, no signed 4096 leg",
    )
    ns = ap.parse_args(argv)
    doc = run_bench(ns.quick)
    with open(ns.output, "w") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True)
        fh.write("\n")
    print(f"wrote {ns.output}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
