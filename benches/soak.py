"""Randomized scenario soak: safety + replay determinism under random
network conditions, beyond the fixed-seed fuzz suite. CPU-only.

Usage: python benches/soak.py [seconds]   (default 20 minutes)

Each iteration draws a fresh scenario — replica count, kills, offline
sets, Byzantine proposers, reorder/drops, signed/burst modes — runs it to
completion or stall, asserts cross-replica safety, and (for a sample of
completed runs) dumps + reloads + replays the record and asserts commit
equality. Found in its first minute of existence: Timeout deliveries
broke ScenarioRecord loading (fixed with a regression test in
tests/test_harness.py). Exits nonzero on the first violation with the
scenario seed in the assertion for reproduction."""

import os
import random
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The container's sitecustomize force-registers the TPU plugin, so the env
# var alone doesn't stick — pin the platform through jax.config (same as
# tests/conftest.py), and reuse the persistent compile cache so the device
# verifier draws don't pay the ladder compile on every soak process.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update(
    "jax_compilation_cache_dir",
    os.environ.get(
        "HD_JAX_CACHE",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), ".jax_cache"),
    ),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)

from hyperdrive_tpu.harness import Simulation  # noqa: E402

DEADLINE = time.time() + (float(sys.argv[1]) if len(sys.argv) > 1 else 1200.0)
master = random.Random(os.getpid() ^ int(time.time()))

N_CHOICES = [4, 5, 7, 10, 16]
#: Validator-table slots for the challenge-path draws: padding every
#: scenario's table to the largest replica count keeps the chalwire
#: kernel shapes identical across scenarios (one ladder compile per
#: bucket for the whole soak). Derived, so growing N_CHOICES cannot
#: silently stop the padding.
PAD_SLOTS = max(N_CHOICES)

runs = 0
_DEVICE_VER = None
while time.time() < DEADLINE:
    seed = master.randrange(1 << 30)
    rng = random.Random(seed)
    n = rng.choice(N_CHOICES)
    f = (n - 1) // 3
    kills = {}
    if rng.random() < 0.3 and f:
        for r in rng.sample(range(n), rng.randint(1, f)):
            kills[r] = rng.randint(100, 3000)
    offline = set()
    if rng.random() < 0.3 and f and not kills:
        offline = set(rng.sample(range(n), rng.randint(1, f)))
    byz = {}
    if rng.random() < 0.3 and f:
        byz = {
            i: (lambda h, r, i=i: bytes([i + 1]) * 32)
            for i in rng.sample(range(n), rng.randint(1, f))
        }
    burst = rng.random() < 0.5
    reorder = rng.random() < 0.5
    drop_rate = rng.choice([0.0, 0.0, 0.05])
    sign = rng.random() < 0.3
    # Device-tally draws run the vote grid through random scenarios with
    # CheckedTallyView asserting device==host on every consulted count.
    device_tally = burst and rng.random() < 0.25
    tally_check = None
    if device_tally:
        from hyperdrive_tpu.ops.votegrid import CheckedTallyView

        tally_check = CheckedTallyView
    # Signed burst draws sometimes verify through the device kernel with
    # deduplication — with device_tally that exercises the FUSED
    # verify+merge+tally launch under random faults (XLA backend on CPU;
    # one shared instance so kernels compile once per soak process).
    batch_verifier = None
    dedup_verify = False
    fused_min_window = 0
    small_window_host = None
    chal_table_pubs = None
    if sign and burst and rng.random() < 0.5:
        if rng.random() < 0.3:
            # Challenge-path draw: the wire verifier with the scenario's
            # validator set resident (every settle window rides the
            # chalwire kernels — device SHA-512 + mod-L + ladder). The
            # table is PADDED to PAD_SLOTS so kernel shapes are stable
            # across scenarios and the ladder compiles once per bucket
            # for the whole soak (pad slots are never indexed).
            from hyperdrive_tpu.crypto.keys import KeyRing
            from hyperdrive_tpu.ops.ed25519_wire import (
                TpuWireVerifier,
                ValidatorTable,
            )

            ring = KeyRing.deterministic(n, namespace=b"sim-%d" % seed)
            pubs = [ring[i].public for i in range(n)]
            # Pad slots use a non-canonical y (the encoding of p itself),
            # which always fails decompression — bytes(32) would NOT do:
            # y=0 decompresses to a valid curve point, so zero-padded
            # slots would be live table entries.
            from hyperdrive_tpu.crypto.ed25519 import P as _P

            pad = _P.to_bytes(32, "little")
            table = ValidatorTable(pubs + [pad] * (PAD_SLOTS - n))
            batch_verifier = TpuWireVerifier(
                buckets=(64, 256), table=table, backend="xla"
            )
            chal_table_pubs = pubs  # checked against sim.ring below
        else:
            if _DEVICE_VER is None:
                from hyperdrive_tpu.ops.ed25519_jax import TpuBatchVerifier

                _DEVICE_VER = TpuBatchVerifier(
                    buckets=(64, 256), backend="xla"
                )
            batch_verifier = _DEVICE_VER
        dedup_verify = True
        # Crossover settle routing: random thresholds leave a MIX of
        # fused and host-routed settles (grid poison soundness under
        # random faults), and occasionally force every tiny window
        # through the device verifier.
        if device_tally and rng.random() < 0.5:
            fused_min_window = rng.choice([3, n, 4 * n, 10_000])
        if rng.random() < 0.2:
            small_window_host = False
    # Payload draws run Shamir share bundles through commits; the
    # adaptive reconstructor default routes them host-side — pin the
    # device kernel on a slice so both commit paths soak.
    payload_bytes = 0
    reconstructor = None
    if rng.random() < 0.15 and not byz:
        payload_bytes = rng.choice([31, 62, 124])
        if rng.random() < 0.3:
            from hyperdrive_tpu.ops.shamir import BatchReconstructor

            reconstructor = BatchReconstructor()
    kwargs = dict(
        n=n,
        target_height=rng.randint(3, 12),
        seed=seed,
        reorder=reorder,
        drop_rate=drop_rate,
        kill_at_step=kills or None,
        offline=offline or None,
        byzantine_proposer=byz or None,
        sign=sign,
        burst=burst,
        batch_verifier=batch_verifier,
        dedup_verify=dedup_verify,
        device_tally=device_tally,
        tally_check=tally_check,
        fused_min_window=fused_min_window,
        small_window_host=small_window_host,
        payload_bytes=payload_bytes,
        reconstructor=reconstructor,
    )
    try:
        sim = Simulation(**kwargs)
        if chal_table_pubs is not None:
            # The chal draw rebuilds the sim's keyring from the shared
            # namespace convention (harness/sim.py derivation). If that
            # convention ever drifts, the verifier would silently route
            # every chunk through the full wire path and the chalwire
            # coverage this draw exists for would vanish — fail loudly
            # instead.
            assert [sim.ring[i].public for i in range(n)] == \
                chal_table_pubs, "soak table no longer matches sim ring"
        res = sim.run(max_steps=400_000)
        res.assert_safety()  # safety must hold, completed or stalled
        # Shared-superstep differential: when the fast path was eligible,
        # a slice of draws re-runs the scenario on the per-delivery path
        # and asserts the trajectories are delivery-for-delivery equal —
        # the same equality the unit differential defines (steps, clock,
        # commits, burst boundaries, recorded delivery stream).
        if sim._shared_mode and rng.random() < 0.2:
            slow = Simulation(**kwargs, shared_superstep=False)
            sres = slow.run(max_steps=400_000)
            assert sres.steps == res.steps, "shared/slow step divergence"
            assert sres.virtual_time == res.virtual_time, (
                "shared/slow clock divergence"
            )
            assert sres.commits == res.commits, "shared/slow commit divergence"
            assert sres.record.bursts == res.record.bursts, (
                "shared/slow burst-boundary divergence"
            )
            assert sres.record.messages == res.record.messages, (
                "shared/slow record divergence"
            )
    except AssertionError as e:
        raise AssertionError(f"seed={seed}: {e}") from None
    if res.completed and rng.random() < 0.3:
        import tempfile

        from hyperdrive_tpu.harness import ScenarioRecord

        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, "s.dump")
            res.record.dump(p)
            # Payload runs replay with the payload path live so the
            # Propose.payload serde surface stays under soak.
            replay_kwargs = (
                dict(payload_bytes=payload_bytes, reconstructor=reconstructor)
                if payload_bytes
                else {}
            )
            replayed = Simulation.replay(
                ScenarioRecord.load(p), **replay_kwargs
            )
            assert replayed.commits == res.commits, (seed, "replay divergence")
    runs += 1

print(f"soak ok: {runs} randomized scenarios, safety + replay held")
