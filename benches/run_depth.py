"""1,000-height depth probe for config 4's dedup mode.

Answers one question with a measurement (round-3 verdict item: the 10k
deep artifact predated the round-3 engine changes and disagreed with the
shallow paired rate by 40%): is the dedup rate HEIGHT-INVARIANT on the
final code? One 256-replica signed dedup run to 1,000 heights
(record=False, like the deep run), with every replica's commit
wall-clocked in order — the per-window rates over the first / middle /
last 100 heights expose any depth decay directly, inside ONE run, so
tunnel drift between separate shallow and deep runs cannot fake a decay
(drift within the ~7-minute run is reported as the window spread).

Writes ``dedup_run_deep_r4`` into benches/results/config_4.json and
marks the round-3 ``dedup_run_deep`` artifact as superseded.

Usage: python benches/run_depth.py [heights]
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from hyperdrive_tpu.utils import Tracer  # noqa: E402


class _DepthTracer(Tracer):
    """Tracer that also timestamps every height commit, in order."""

    def __init__(self):
        super().__init__(time_fn=time.perf_counter, threadsafe=False)
        self.marks: list[float] = []

    def observe(self, name: str, value) -> None:
        super().observe(name, value)
        if name == "replica.height.latency":
            self.marks.append(time.perf_counter())


def main() -> None:
    heights = int(sys.argv[1]) if len(sys.argv) > 1 else 1000
    n = 256

    from hyperdrive_tpu.harness import Simulation
    from hyperdrive_tpu.ops.ed25519_jax import TpuBatchVerifier

    ver = TpuBatchVerifier(buckets=(1024, 4096, 16384))
    ver.warmup()

    def build(h, rec):
        return Simulation(
            n=n, target_height=h, seed=1004, timeout=20.0, sign=True,
            burst=True, batch_verifier=ver, dedup_verify=True, record=rec,
        )

    build(2, False).run(max_steps=50_000_000)  # warm pass

    sim = build(heights, False)
    tr = _DepthTracer()
    for r in sim.replicas:
        r.tracer = tr
    t0 = time.perf_counter()
    res = sim.run(max_steps=5_000_000_000)
    wall = time.perf_counter() - t0
    res.assert_safety()
    assert res.completed, f"stalled at {res.heights}"

    # marks[h*n + (n-1)] = the wall time the LAST replica committed
    # observed-height h+1 (lockstep: all replicas commit each height in
    # one settle pass, so the marks arrive height-ordered). The final
    # height's observation can be cut short by run completion, so
    # segment over the heights actually observed.
    observed = len(tr.marks) // n
    assert observed >= heights - 1, (len(tr.marks), heights)
    height_done = [tr.marks[h * n + (n - 1)] - t0 for h in range(observed)]

    def window_rate(lo, hi):
        t_lo = height_done[lo - 1] if lo > 0 else 0.0
        return (hi - lo) / (height_done[hi - 1] - t_lo)

    # Windows tile the WHOLE observed range — the last (possibly ragged)
    # window is included, because a decay confined to the final heights
    # is exactly what a depth probe must not silently drop.
    win = min(100, max(observed // 3, 1))
    windows = {}
    prev_lo = 0
    for lo in range(0, observed, win):
        hi = min(lo + win, observed)
        if hi - lo < max(win // 4, 1) and windows:
            # Merge a tiny tail into the previous window's span.
            windows.popitem()
            windows[f"h{prev_lo + 1}-{hi}"] = round(
                window_rate(prev_lo, hi), 3
            )
            break
        windows[f"h{lo + 1}-{hi}"] = round(window_rate(lo, hi), 3)
        prev_lo = lo
    rates = list(windows.values())
    spread = (max(rates) - min(rates)) / (sum(rates) / len(rates))
    # Decay is DIRECTIONAL: later windows slower than earlier ones. The
    # symmetric spread alone mislabels tunnel drift (a slow first window
    # with a flat tail) as decay; compare the last third's median rate
    # against the first third's.
    third = max(len(rates) // 3, 1)
    head = sorted(rates[:third])[third // 2]
    tail = sorted(rates[-third:])[third // 2]

    out = {
        "completed": True,
        "heights": heights,
        "steps": res.steps,
        "wall_s": round(wall, 2),
        "heights_per_s": round(heights / wall, 3),
        "msgs_per_s": round(res.steps / wall, 1),
        "window_rates_heights_per_s": windows,
        "window_spread_frac": round(spread, 4),
        "head_third_median_heights_per_s": round(head, 3),
        "tail_third_median_heights_per_s": round(tail, 3),
        "height_invariant": bool(tail >= 0.85 * head),
        "measured_at": time.strftime("%Y-%m-%d %H:%M:%S"),
        "note": (
            "rate measured per 100-height window INSIDE one run "
            "(record=False), tail window included; height_invariant "
            "compares the last third's median rate against the first "
            "third's (decay is directional — the symmetric spread also "
            "reported includes the tunnel's drift, which can make the "
            "START of a run slow without any depth effect)"
        ),
    }
    print(json.dumps(out))

    path = os.path.join(REPO, "benches", "results", "config_4.json")
    with open(path) as fh:
        cfg = json.load(fh)
    cfg["dedup_run_deep_r4"] = out
    old = cfg.get("dedup_run_deep")
    if old and "status" not in old:
        old["status"] = (
            "superseded: measured 2026-07-30 22:36 on pre-round-3-router "
            "code; dedup_run_deep_r4 is the depth evidence for the final "
            "engine (the 10k-height, 1.3B-delivery endurance fact this "
            "artifact established still stands)"
        )
    with open(path, "w") as fh:
        json.dump(cfg, fh, indent=1)


if __name__ == "__main__":
    main()
