"""Campaign bench: honest-latency-under-storm and the reputation loop.

Produces the BENCH_r15 artifact (adversarial-economy evidence for the
campaign layer, ROBUSTNESS.md "Adversarial economy"):

- **storm sweep** — the exact admission+verify service loop the storm
  engine drives (campaign/families.py run_storm), timed per wave over
  three arms: an honest-only baseline, the full forged-signature storm
  with the reputation loop on, and the same storm with the loop off
  (control). Every forged row passes the gate's cheap checks and dies
  at batch verify; per-signer verdicts feed back through
  ``note_verify``. Two gated series:

  * ``honest_p99_latency_ratio_series`` — per-trial p99 of honest
    per-wave service time under the storm (reputation on) over the
    unloaded baseline's p99. The wave-0 transient (attackers not yet
    demoted) is <1% of waves by construction, so p99 reads the steady
    state: demoted attackers shed pre-verify and honest service cost
    stays bounded (the acceptance bound is <=2x).
  * ``reputation_speedup_series`` — total storm service wall with the
    loop OFF over wall with it ON. The loop's receipt: rows that shed
    at the gate never reach the verifier, so the control arm pays the
    full forged verify bill every wave and the gated arm pays it once.

- **capture evidence** (ungated) — one budgeted capture campaign
  through ``run_campaign`` at bench scale: wall seconds, adversary
  seats vs the passive baseline, zero proportionality violations.

Both gated series are machine-portable ratios, nominated in the
artifact's ``benchdiff_gate`` list; the CI campaign-soak job diffs a
fresh ``--quick`` run against the committed BENCH_r15.json with
``python -m hyperdrive_tpu.obs benchdiff``.

Usage::

    python benches/campaign_bench.py [-o BENCH_r15.json] [--quick]
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from hyperdrive_tpu.campaign import CampaignConfig  # noqa: E402
from hyperdrive_tpu.campaign.runner import run_campaign  # noqa: E402
from hyperdrive_tpu.crypto.keys import KeyRing  # noqa: E402
from hyperdrive_tpu.load.backpressure import (  # noqa: E402
    AdmissionGate,
    BackpressureController,
    SignerReputation,
)
from hyperdrive_tpu.messages import Prevote  # noqa: E402
from hyperdrive_tpu.verifier import HostVerifier  # noqa: E402

SEED = 15


def _quantile(sorted_vals, q):
    if not sorted_vals:
        return None
    return sorted_vals[min(len(sorted_vals) - 1, int(q * len(sorted_vals)))]


def _forge(sig: bytes) -> bytes:
    return bytes([sig[0] ^ 0xFF]) + sig[1:]


def _wave_frames(ring, k, a, wave_votes, attack_rate, waves, storm):
    """Pre-generated per-wave frame lists (signing stays OUTSIDE the
    timed service loop; the service loop is admit + verify + feedback,
    the path the storm actually loads)."""
    import hashlib

    out = []
    for w in range(waves):
        height = w + 1
        value = hashlib.shake_256(
            b"campaign-bench-value" + w.to_bytes(8, "little")
        ).digest(32)
        frames = []
        for i in range(a, k):
            for r in range(wave_votes):
                msg = Prevote(height, r, value, ring[i].public)
                frames.append(
                    (r, i, msg.with_signature(
                        ring[i].sign_digest(msg.digest())
                    ))
                )
        if storm:
            for j in range(a):
                for r in range(wave_votes * attack_rate):
                    msg = Prevote(height, r, value, ring[j].public)
                    frames.append(
                        (r, j, msg.with_signature(
                            _forge(ring[j].sign_digest(msg.digest()))
                        ))
                    )
        frames.sort(key=lambda f: (f[0], f[1]))
        out.append([msg for _, _, msg in frames])
    return out


def _storm_arm(wave_frames, k, a, wave_votes, attack_rate, reputation):
    """Run the admission+verify service loop over pre-signed waves,
    timing each wave. Returns (per-wave seconds, failed-row total,
    demotions). Mirrors run_storm's loop exactly — same controller
    thresholds, same feedback — minus the summary bookkeeping."""
    honest_rows = (k - a) * wave_votes
    storm_rows = honest_rows + a * wave_votes * attack_rate
    rep = SignerReputation() if reputation else None
    ctrl = BackpressureController(
        depth_low_priority=honest_rows * 2,
        depth_critical=storm_rows * 4,
        hysteresis=2,
    )
    gate = AdmissionGate(ctrl, reputation=rep)
    verifier = HostVerifier()
    wave_s = []
    failed_total = 0
    for frames in wave_frames:
        t0 = time.perf_counter()
        batch = []
        for msg in frames:
            if gate.admit(msg, peer=msg.sender):
                batch.append((msg.sender, msg.digest(), msg.signature))
        ctrl.note_depth(len(batch))
        mask = verifier.verify_signatures(batch)
        per_signer: dict = {}
        for (sender, _, _), ok in zip(batch, mask):
            good, bad = per_signer.get(sender, (0, 0))
            per_signer[sender] = (
                (good + 1, bad) if ok else (good, bad + 1)
            )
        for sender, (good, bad) in per_signer.items():
            if good:
                gate.note_verify(sender, True, good)
            if bad:
                failed_total += bad
                gate.note_verify(sender, False, bad)
        ctrl.note_drain(len(batch), 0.0)
        if rep is not None:
            rep.rehabilitate(1)
        wave_s.append(time.perf_counter() - t0)
    return wave_s, failed_total, (rep.demotions if rep else 0)


def storm_sweep(k, a, wave_votes, attack_rate, waves, trials):
    out = {
        "committee": k,
        "attackers": a,
        "wave_votes": wave_votes,
        "attack_rate": attack_rate,
        "waves": waves,
        "trials": trials,
        "baseline_p99_s": [],
        "storm_p99_s": [],
        "honest_p99_latency_ratio_series": [],
        "reputation_speedup_series": [],
        "failed_rows_reputation": [],
        "failed_rows_control": [],
        "demotions": [],
    }
    for t in range(trials):
        ring = KeyRing.deterministic(
            k, namespace=b"campaign-bench-%d" % (SEED + t)
        )
        honest_only = _wave_frames(
            ring, k, a, wave_votes, attack_rate, waves, storm=False
        )
        storm = _wave_frames(
            ring, k, a, wave_votes, attack_rate, waves, storm=True
        )
        base_s, _, _ = _storm_arm(
            honest_only, k, a, wave_votes, attack_rate, reputation=True
        )
        rep_s, rep_failed, demotions = _storm_arm(
            storm, k, a, wave_votes, attack_rate, reputation=True
        )
        ctl_s, ctl_failed, _ = _storm_arm(
            storm, k, a, wave_votes, attack_rate, reputation=False
        )
        base_p99 = _quantile(sorted(base_s), 0.99)
        rep_p99 = _quantile(sorted(rep_s), 0.99)
        out["baseline_p99_s"].append(round(base_p99, 6))
        out["storm_p99_s"].append(round(rep_p99, 6))
        out["honest_p99_latency_ratio_series"].append(
            round(rep_p99 / base_p99, 4)
        )
        out["reputation_speedup_series"].append(
            round(sum(ctl_s) / sum(rep_s), 4)
        )
        out["failed_rows_reputation"].append(rep_failed)
        out["failed_rows_control"].append(ctl_failed)
        out["demotions"].append(demotions)
    return out


def capture_evidence(validators, committee, epochs, grind_width):
    cfg = CampaignConfig(
        family="capture",
        seed=SEED,
        validators=validators,
        committee_size=committee,
        epochs=epochs,
        attackers=committee // 4,
        sybils=min(16, validators // 2),
        grind_width=grind_width,
    )
    t0 = time.perf_counter()
    outcome = run_campaign(cfg)
    wall = time.perf_counter() - t0
    return {
        "validators": validators,
        "committee": committee,
        "epochs": epochs,
        "grind_width": grind_width,
        "wall_s": round(wall, 4),
        "adv_seats": outcome.summary["seats_total"],
        "passive_seats": outcome.summary["passive_total"],
        "violations": len(outcome.violations),
        "digest": outcome.digest[:8].hex(),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("-o", "--output", default="BENCH_r15.json")
    ap.add_argument("--quick", action="store_true",
                    help="CI-sized sweep (fewer trials, smaller capture)")
    ns = ap.parse_args(argv)

    # The storm sweep is identical in both modes: waves stay >=120 so
    # the wave-0 transient (attackers not yet demoted) is <1% of waves
    # and p99 reads steady state, and trials stay >=3 so the gated
    # series are long enough for benchdiff's median comparison.
    k, a, waves = 32, 8, 120
    if ns.quick:
        trials = 3
        cap = dict(validators=128, committee=16, epochs=8, grind_width=4)
    else:
        trials = 5
        cap = dict(validators=256, committee=32, epochs=8, grind_width=8)

    doc = {
        "measured_at": datetime.datetime.now().strftime(
            "%Y-%m-%d %H:%M:%S"
        ),
        "benchdiff_gate": [
            "campaign.storm.honest_p99_latency_ratio_series",
            "campaign.storm.reputation_speedup_series",
        ],
        "campaign": {
            "storm": storm_sweep(
                k, a, wave_votes=2, attack_rate=8,
                waves=waves, trials=trials,
            ),
            "capture": capture_evidence(**cap),
        },
    }
    storm = doc["campaign"]["storm"]
    ok = (
        all(r <= 2.0 for r in storm["honest_p99_latency_ratio_series"])
        and all(s > 1.0 for s in storm["reputation_speedup_series"])
        and doc["campaign"]["capture"]["violations"] == 0
    )
    doc["adversarial_economy_ok"] = ok
    with open(ns.output, "w") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True)
        fh.write("\n")
    print(json.dumps({
        "artifact": ns.output,
        "adversarial_economy_ok": ok,
        "honest_p99_ratio": storm["honest_p99_latency_ratio_series"],
        "reputation_speedup": storm["reputation_speedup_series"],
        "failed_rows": {
            "reputation": storm["failed_rows_reputation"],
            "control": storm["failed_rows_control"],
        },
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
