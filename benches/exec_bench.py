"""Execution-layer bench: device-vectorized block apply vs the host
reference executor, plus end-to-end committed-tx/s through the
pipelined sim.

Produces the BENCH_r12 artifact (the perf evidence for the
device-vectorized execution layer, README "Execution layer"):

- **apply_speedup** (gated) — raw block-apply throughput, one padded
  segment-sum/scatter-add launch (ops/ledger.py) against the two-pass
  Python reference (exec/ledger.py), at 1k/16k/64k-tx blocks. Block
  generation is pre-cached outside the timed region and the jitted
  kernel is warmed per bucket, so the series measures the apply path
  itself. Every timed height asserts ROOT EQUALITY between the two
  executors — a speedup that drifts the ledger is a bug, not a result.
  The acceptance floor is >= 2x at >= 16k-tx blocks.

- **e2e_speedup** (gated) — committed-tx/s through the full pipelined
  sim (burst delivery, signed votes through the batch verifier,
  settles through the shared device-work queue), device executor vs
  host executor, same seed. The two chains must be byte-identical
  including the root extension (the commit value carries the state
  root) — the bench exits nonzero on any divergence.

Both gated series are ratios, so the runner's absolute speed divides
out (the benchdiff sentinel's machine-portability rule). Absolute tx/s
rows ride along informationally.

Usage::

    python benches/exec_bench.py [-o BENCH_r12.json] [--quick]
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", ".jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "2.0")

from hyperdrive_tpu.exec import ExecutionConfig  # noqa: E402
from hyperdrive_tpu.exec.device import DeviceLedgerExecutor  # noqa: E402
from hyperdrive_tpu.exec.ledger import (  # noqa: E402
    BlockSource,
    HostLedgerExecutor,
)
from hyperdrive_tpu.harness.sim import Simulation  # noqa: E402

SEED = 31

#: Apply-leg block sizes: identical in both modes so the quick CI run
#: and the committed full artifact compare series of equal shape.
APPLY_SIZES = (1024, 16384, 65536)

#: E2E-leg block sizes (txs per committed height).
E2E_SIZES = (1024, 4096, 16384)


def _apply_cfg(txs: int) -> ExecutionConfig:
    return ExecutionConfig(
        accounts=4096,
        txs_per_block=txs,
        stake_every=4,
        stake_accounts=64,
        seed=SEED,
        amount_cap=64,
        initial_balance=1_000_000,
    )


def _time_apply(ex, first: int, last: int) -> float:
    t0 = time.perf_counter()
    ex.advance_to(last)
    return time.perf_counter() - t0


def bench_apply(txs: int, reps: int) -> dict:
    cfg = _apply_cfg(txs)
    source = BlockSource(cfg)
    # Pre-derive every block the legs will touch — including the
    # device-padded column cache, which is block MATERIALIZATION shared
    # across replicas in real runs, not apply work — so the series
    # measures APPLY. (reps + warmup <= the source's LRU, so nothing
    # regenerates inside the timed region.)
    total = reps + 1
    assert total <= BlockSource.CACHE
    for h in range(1, total + 1):
        DeviceLedgerExecutor._device_cols(source.block(h))
    host = HostLedgerExecutor(cfg, source=source)
    dev = DeviceLedgerExecutor(cfg, source=source)
    # Warmup height 1: compiles the bucket's kernel on the device side.
    host.advance_to(1)
    dev.advance_to(1)
    host_s = _time_apply(host, 2, total)
    dev_s = _time_apply(dev, 2, total)
    if host.roots != dev.roots or host.applied_total != dev.applied_total:
        raise SystemExit(
            f"APPLY PARITY BROKEN at {txs}-tx blocks: device roots "
            f"diverge from the host reference"
        )
    n_txs = reps * txs
    return {
        "txs_per_block": txs,
        "blocks": reps,
        "host_tx_s": round(n_txs / host_s, 1),
        "device_tx_s": round(n_txs / dev_s, 1),
        "speedup": round(host_s / dev_s, 3),
        "applied": host.applied_total,
    }


def _e2e_run(txs: int, device: bool, target: int) -> tuple:
    cfg = ExecutionConfig(
        accounts=1024,
        txs_per_block=txs,
        stake_every=4,
        stake_accounts=16,
        seed=SEED,
        amount_cap=64,
        initial_balance=1_000_000,
        device=device,
    )
    # Warm the bucket's kernel outside the timed region (a one-off
    # compile per (bucket, accounts) shape, not committed-tx/s) —
    # symmetric for both executors, on a throwaway source.
    warm = (DeviceLedgerExecutor if device else HostLedgerExecutor)(cfg)
    warm.advance_to(1)
    sim = Simulation(
        n=4,
        target_height=target,
        seed=SEED,
        sign=True,
        burst=True,
        pipeline_heights=True,
        execution=cfg,
    )
    t0 = time.perf_counter()
    res = sim.run(max_steps=5_000_000)
    wall = time.perf_counter() - t0
    if not res.completed:
        raise SystemExit(
            f"e2e run txs={txs} device={device} stalled at "
            f"heights={res.heights}"
        )
    heights = min(res.heights)
    return res.commits, round(heights * txs / wall, 1), wall


def bench_e2e(txs: int, target: int) -> dict:
    host_commits, host_tx_s, host_wall = _e2e_run(txs, False, target)
    dev_commits, dev_tx_s, dev_wall = _e2e_run(txs, True, target)
    if host_commits != dev_commits:
        raise SystemExit(
            f"E2E DIGEST MISMATCH at {txs}-tx blocks: device-executor "
            f"chain (root-extended) diverges from the host-executor run"
        )
    return {
        "txs_per_block": txs,
        "host_committed_tx_s": host_tx_s,
        "device_committed_tx_s": dev_tx_s,
        "speedup": round(dev_tx_s / host_tx_s, 3),
        "host_wall_s": round(host_wall, 3),
        "device_wall_s": round(dev_wall, 3),
    }


def run_bench(quick: bool) -> dict:
    reps = 2 if quick else 5
    target = 4 if quick else 6
    apply_rows = []
    for txs in APPLY_SIZES:
        row = bench_apply(txs, reps)
        print(
            f"apply txs={txs:6d} host={row['host_tx_s']:12.1f}tx/s "
            f"device={row['device_tx_s']:12.1f}tx/s "
            f"speedup={row['speedup']:.2f}x"
        )
        apply_rows.append(row)
    for row in apply_rows:
        if row["txs_per_block"] >= 16384 and row["speedup"] < 2.0:
            raise SystemExit(
                f"apply speedup {row['speedup']}x at "
                f"{row['txs_per_block']}-tx blocks is below the 2x "
                f"acceptance floor"
            )
    e2e_rows = []
    for txs in E2E_SIZES:
        row = bench_e2e(txs, target)
        print(
            f"e2e   txs={txs:6d} host={row['host_committed_tx_s']:12.1f}tx/s "
            f"device={row['device_committed_tx_s']:12.1f}tx/s "
            f"speedup={row['speedup']:.2f}x digest=identical"
        )
        e2e_rows.append(row)
    return {
        "benchdiff_gate": ["exec.apply_speedup", "exec.e2e_speedup"],
        "measured_at": datetime.datetime.now().strftime(
            "%Y-%m-%d %H:%M:%S"
        ),
        "exec": {
            "seed": SEED,
            "apply_sizes": list(APPLY_SIZES),
            "apply_blocks_per_leg": reps,
            "apply_speedup": [r["speedup"] for r in apply_rows],
            "apply_host_tx_s": [r["host_tx_s"] for r in apply_rows],
            "apply_device_tx_s": [r["device_tx_s"] for r in apply_rows],
            "e2e_sizes": list(E2E_SIZES),
            "e2e_target_height": target,
            "e2e_speedup": [r["speedup"] for r in e2e_rows],
            "e2e_host_tx_s": [
                r["host_committed_tx_s"] for r in e2e_rows
            ],
            "e2e_device_tx_s": [
                r["device_committed_tx_s"] for r in e2e_rows
            ],
            "e2e_digest_identical": True,
            "e2e_wall_s": [r["device_wall_s"] for r in e2e_rows],
        },
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("-o", "--output", default="BENCH_r12.json")
    ap.add_argument(
        "--quick",
        action="store_true",
        help="CI mode: fewer blocks per apply leg, shorter e2e chains "
        "(series shapes unchanged, so benchdiff compares cleanly)",
    )
    ns = ap.parse_args(argv)
    doc = run_bench(ns.quick)
    with open(ns.output, "w") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True)
        fh.write("\n")
    print(f"wrote {ns.output}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
