"""Execution-layer bench: device-vectorized block apply vs the host
reference executor, plus end-to-end committed-tx/s through the
speculative execution pipeline.

Produces the BENCH_r13 artifact (the perf evidence for the
sub-second-finality execution pipeline, README "Execution layer"):

- **apply_speedup** (gated) — raw block-apply throughput, one fused
  apply+digest+chain-fold launch (ops/ledger.py) against the two-pass
  Python reference (exec/ledger.py), at 1k/16k/64k-tx blocks. The
  numpy block columns are pre-derived outside the timed region (shared
  workload synthesis); each executor pays its OWN ingest — list
  materialization for the host walk, pack+transfer for the device —
  because that is what each path pays per block in a real run. Every
  leg asserts ROOT EQUALITY between the two executors at every height:
  a speedup that drifts the ledger is a bug, not a result. Acceptance
  floor: >= 2x at >= 16k-tx blocks.

- **e2e_speedup** (gated) — committed-tx/s of the device-resident
  SPECULATIVE PIPELINE (speculate at proposal, confirm at drain, roots
  chained on device, fused verify+apply drain) against the lock-step
  settle-then-execute HOST BASELINE — the architecture this series
  replaced, in which every height serializes consensus, host apply,
  and a host root fold before the next proposal. That serial pipeline
  is exactly what BENCH_r12 showed eating the kernel win (device e2e
  0.95-1.2x despite a 3x apply kernel), so the gate measures the thing
  this change is for. A like-for-like row (host executor through the
  same pipeline) rides along informationally. All three chains must be
  digest-identical — byte-equal commit values, root extension included,
  on every common height — or the bench exits nonzero.

- **e2e_tx_per_s** (gated) — the device pipeline's absolute committed
  tx/s; the acceptance floor is >= 1M tx/s at every size. Absolute
  rows gate by benchdiff's MAD noise bound against the committed
  artifact rather than by a portable ratio, so this is the one series
  that assumes CI runners of the same class.

Every timed wall is a best-of-``reps`` minimum: the measurement boxes
are single-core and preemption inflates individual runs by 2-3x, and
the minimum is the run the machine actually executed without
interference.

Usage::

    python benches/exec_bench.py [-o BENCH_r13.json] [--quick]
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", ".jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "2.0")

from hyperdrive_tpu.exec import ExecutionConfig  # noqa: E402
from hyperdrive_tpu.exec.device import DeviceLedgerExecutor  # noqa: E402
from hyperdrive_tpu.exec.ledger import (  # noqa: E402
    BlockSource,
    HostLedgerExecutor,
)
from hyperdrive_tpu.harness.sim import Simulation  # noqa: E402

SEED = 31

#: Apply-leg block sizes: identical in both modes so the quick CI run
#: and the committed full artifact compare series of equal shape.
APPLY_SIZES = (1024, 16384, 65536)

#: E2E-leg block sizes (txs per committed height).
E2E_SIZES = (16384, 32768, 65536)

#: Heights the e2e sims drive to (the pipeline overshoots by its
#: proposal window; committed-tx/s counts what actually committed).
E2E_TARGET = 8


def _apply_cfg(txs: int) -> ExecutionConfig:
    return ExecutionConfig(
        accounts=4096,
        txs_per_block=txs,
        stake_every=4,
        stake_accounts=64,
        seed=SEED,
        amount_cap=64,
        initial_balance=1_000_000,
    )


def bench_apply(txs: int, blocks: int, reps: int) -> dict:
    cfg = _apply_cfg(txs)
    source = BlockSource(cfg)
    # Pre-derive the numpy block columns — workload synthesis, shared
    # by every replica in real runs — outside the timed region. Each
    # executor's own ingest (host list walk, device pack+transfer)
    # stays INSIDE it. blocks + warmup <= the source's LRU, so nothing
    # regenerates while timing.
    total = blocks + 1
    assert total <= BlockSource.CACHE
    for h in range(1, total + 1):
        source.block(h)
    last = None
    walls = {}
    for cls in (HostLedgerExecutor, DeviceLedgerExecutor):
        best = None
        for _ in range(reps):
            ex = cls(cfg, source=source)
            # Warmup height 1: compiles the bucket's kernel (device)
            # and touches the allocator (host) outside the timing.
            ex.advance_to(1)
            t0 = time.perf_counter()
            ex.advance_to(total)
            wall = time.perf_counter() - t0
            best = wall if best is None else min(best, wall)
        if last is not None and (
            last.roots != ex.roots
            or last.applied_total != ex.applied_total
        ):
            raise SystemExit(
                f"APPLY PARITY BROKEN at {txs}-tx blocks: device roots "
                f"diverge from the host reference"
            )
        last = ex
        walls[cls.device] = best
    n_txs = blocks * txs
    return {
        "txs_per_block": txs,
        "blocks": blocks,
        "host_tx_s": round(n_txs / walls[False], 1),
        "device_tx_s": round(n_txs / walls[True], 1),
        "speedup": round(walls[False] / walls[True], 3),
        "applied": last.applied_total,
    }


def _e2e_cfg(txs: int, device: bool) -> ExecutionConfig:
    return ExecutionConfig(
        accounts=1024,
        txs_per_block=txs,
        stake_every=4,
        stake_accounts=16,
        seed=SEED,
        amount_cap=64,
        initial_balance=1_000_000,
        device=device,
    )


def _e2e_run(txs: int, device: bool, pipelined: bool) -> tuple:
    cfg = _e2e_cfg(txs, device)
    # Warm the bucket's kernel outside the timed region (a one-off
    # compile per (bucket, accounts) shape, not committed-tx/s) —
    # symmetric for both executors, on a throwaway source.
    warm = (DeviceLedgerExecutor if device else HostLedgerExecutor)(cfg)
    warm.advance_to(1)
    sim = Simulation(
        n=4,
        target_height=E2E_TARGET,
        seed=SEED,
        sign=True,
        burst=True,
        pipeline_heights=pipelined,
        execution=cfg,
    )
    t0 = time.perf_counter()
    res = sim.run(max_steps=5_000_000)
    wall = time.perf_counter() - t0
    if not res.completed:
        raise SystemExit(
            f"e2e run txs={txs} device={device} pipelined={pipelined} "
            f"stalled at heights={res.heights}"
        )
    return res.commits, min(res.heights), wall


def _chain(commits) -> dict:
    """Replica 0's height -> commit value map (every replica commits
    the same chain; the per-replica equality is the sim's own
    assertion)."""
    return commits[0]


def bench_e2e(txs: int, reps: int) -> dict:
    legs = {
        # (device, pipelined) -> label
        (False, False): "host_seq",
        (False, True): "host_pipe",
        (True, True): "device_pipe",
    }
    walls = {}
    heights = {}
    chains = {}
    for (device, pipelined), label in legs.items():
        best = None
        for _ in range(reps):
            commits, h, wall = _e2e_run(txs, device, pipelined)
            best = wall if best is None else min(best, wall)
        walls[label] = best
        heights[label] = h
        chains[label] = _chain(commits)
    # Digest identity, root extension included: the pipelined chains
    # must be byte-equal to each other AND to the sequential baseline
    # on every height the baseline committed.
    if chains["host_pipe"] != chains["device_pipe"]:
        raise SystemExit(
            f"E2E DIGEST MISMATCH at {txs}-tx blocks: device-pipeline "
            f"chain diverges from the host-executor pipeline run"
        )
    for h, v in chains["host_seq"].items():
        if chains["device_pipe"].get(h, v) != v:
            raise SystemExit(
                f"E2E DIGEST MISMATCH at {txs}-tx blocks, height {h}: "
                f"pipelined chain diverges from the sequential baseline"
            )
    tx_s = {
        label: heights[label] * txs / walls[label] for label in walls
    }
    return {
        "txs_per_block": txs,
        "host_seq_tx_s": round(tx_s["host_seq"], 1),
        "host_pipe_tx_s": round(tx_s["host_pipe"], 1),
        "device_tx_s": round(tx_s["device_pipe"], 1),
        "speedup": round(tx_s["device_pipe"] / tx_s["host_seq"], 3),
        "pipe_speedup": round(tx_s["device_pipe"] / tx_s["host_pipe"], 3),
        "host_seq_wall_s": round(walls["host_seq"], 3),
        "device_wall_s": round(walls["device_pipe"], 3),
    }


def run_bench(quick: bool) -> dict:
    blocks = 2 if quick else 5
    reps = 2 if quick else 3
    apply_rows = []
    for txs in APPLY_SIZES:
        row = bench_apply(txs, blocks, reps)
        print(
            f"apply txs={txs:6d} host={row['host_tx_s']:12.1f}tx/s "
            f"device={row['device_tx_s']:12.1f}tx/s "
            f"speedup={row['speedup']:.2f}x"
        )
        apply_rows.append(row)
    for row in apply_rows:
        if row["txs_per_block"] >= 16384 and row["speedup"] < 2.0:
            raise SystemExit(
                f"apply speedup {row['speedup']}x at "
                f"{row['txs_per_block']}-tx blocks is below the 2x "
                f"acceptance floor"
            )
    e2e_rows = []
    for txs in E2E_SIZES:
        row = bench_e2e(txs, reps)
        print(
            f"e2e   txs={txs:6d} seq-host={row['host_seq_tx_s']:11.1f}tx/s "
            f"pipe-dev={row['device_tx_s']:11.1f}tx/s "
            f"speedup={row['speedup']:.2f}x "
            f"(like-for-like {row['pipe_speedup']:.2f}x) digest=identical"
        )
        e2e_rows.append(row)
    for row in e2e_rows:
        if row["speedup"] < 2.0:
            raise SystemExit(
                f"e2e speedup {row['speedup']}x at "
                f"{row['txs_per_block']}-tx blocks is below the 2x "
                f"acceptance floor (device pipeline vs sequential host "
                f"baseline)"
            )
        if row["device_tx_s"] < 1_000_000:
            raise SystemExit(
                f"device pipeline {row['device_tx_s']} committed-tx/s "
                f"at {row['txs_per_block']}-tx blocks is below the "
                f"1M tx/s acceptance floor"
            )
    return {
        "benchdiff_gate": [
            "exec.apply_speedup",
            "exec.e2e_speedup",
            "exec.e2e_tx_per_s",
        ],
        "measured_at": datetime.datetime.now().strftime(
            "%Y-%m-%d %H:%M:%S"
        ),
        "exec": {
            "seed": SEED,
            "apply_sizes": list(APPLY_SIZES),
            "apply_blocks_per_leg": blocks,
            "apply_speedup": [r["speedup"] for r in apply_rows],
            # *_tx_per_s, not *_tx_s: benchdiff infers direction from
            # the leaf name, and a bare "_s" suffix reads as a wall
            # time (lower-is-better) — these are throughputs.
            "apply_host_tx_per_s": [r["host_tx_s"] for r in apply_rows],
            "apply_device_tx_per_s": [
                r["device_tx_s"] for r in apply_rows
            ],
            "e2e_sizes": list(E2E_SIZES),
            "e2e_target_height": E2E_TARGET,
            "e2e_speedup": [r["speedup"] for r in e2e_rows],
            "e2e_pipe_speedup": [r["pipe_speedup"] for r in e2e_rows],
            "e2e_tx_per_s": [r["device_tx_s"] for r in e2e_rows],
            "e2e_host_seq_tx_per_s": [
                r["host_seq_tx_s"] for r in e2e_rows
            ],
            "e2e_host_pipe_tx_per_s": [
                r["host_pipe_tx_s"] for r in e2e_rows
            ],
            "e2e_digest_identical": True,
            "e2e_wall_s": [r["device_wall_s"] for r in e2e_rows],
        },
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("-o", "--output", default="BENCH_r13.json")
    ap.add_argument(
        "--quick",
        action="store_true",
        help="CI mode: fewer blocks per apply leg, best-of-2 walls "
        "(series shapes unchanged, so benchdiff compares cleanly)",
    )
    ns = ap.parse_args(argv)
    doc = run_bench(ns.quick)
    with open(ns.output, "w") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True)
        fh.write("\n")
    print(f"wrote {ns.output}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
