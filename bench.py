"""Benchmark: SUSTAINED votes verified per second on one TPU chip.

The north-star metric (BASELINE.md): batched Ed25519 verification of
consensus votes — 256 validators' signatures over vote digests, verified
in wide batches fused with the quorum tally — target >= 50k votes/sec on
one v5e chip.

Round-4 headline: the sustained UNIQUE-signature pipeline. Every timed
launch consumes a fresh batch of distinct signatures; the host packs
batch k+1 while the device verifies batch k. No input reuse — this is
the rate a deployment's mq drain loop could sustain (reference hot
path: /root/reference/process/process.go:574-579), not a kernel ceiling
fed by a pre-packed buffer.

Data path (ops/ed25519_wire.py + ops/sha512_jax.py): point decompression
AND the challenge hash run ON DEVICE; the host only range-checks and
marshals bytes. The consensus validator set is known, so A ships as a
4-byte index into a device-resident pubkey table, and the signing digests
are per-ROUND data (the sender is excluded from them), so the wire
carries R 32 + s 32 + idx 4 = 68 B/lane. On this tunnel-attached chip
(~4-13 MB/s H2D across sessions, BENCH.md) the pipeline is
TRANSFER-bound, so bytes/lane — not kernel speed and not host speed —
set the sustained rate; the host-hashed 100 B/lane path, the full-wire
(128 B/lane) rate, the device-only ceiling, and the host pack rates are
reported alongside so the bottleneck is visible.

:func:`run_sustained` is the ONE harness: bench.py's 256-validator
headline and BENCH.md config 7's 512-validator operating point both call
it, so the methodology cannot drift between them.

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

from hyperdrive_tpu.crypto.keys import KeyRing
from hyperdrive_tpu.messages import Prevote
from hyperdrive_tpu.ops.ed25519_pallas import resolve_backend
from hyperdrive_tpu.ops.ed25519_wire import (
    Ed25519WireHost,
    ValidatorTable,
    make_challenge_round_fn,
    make_semiwire_verify_fn,
    make_wire_verify_fn,
)
from hyperdrive_tpu.ops.tally import pack_values, tally_counts, quorum_flags

N_VALIDATORS = 256
# In-flight (height, round) pairs per launch: 256 rounds x 256 validators
# = 65,536 signatures/launch (the round-3 sweep's operating point — past
# 256 rounds gains flatten under 3%/doubling while launch latency
# doubles).
ROUNDS = 256
TARGET_VOTES_PER_SEC = 50_000.0

#: Timed launches per trial. Every launch gets its own fresh signature
#: batch within a trial (pack || transfer || verify overlap); batches are
#: re-used ACROSS trials but re-packed in full each time, so no packed
#: tensor ever crosses a trial boundary.
ITERS = 4
TRIALS = 3


def _verify_fns(backend: str):
    if backend == "pallas":
        from hyperdrive_tpu.ops.ed25519_pallas import (
            make_pallas_semiwire_verify_fn,
            make_pallas_wire_verify_fn,
        )

        return make_pallas_semiwire_verify_fn(), make_pallas_wire_verify_fn()
    return make_semiwire_verify_fn(), make_wire_verify_fn()


def _build_batches(ring, validators, rounds, iters, namespace: bytes):
    """``iters`` batches of validators*rounds UNIQUE signatures: every
    validator signs one prevote per (round, iter) — every digest
    distinct ACROSS rounds, so no dedup/caching anywhere in the pipeline
    can shortcut the work. Within a round all validators sign the same
    digest (the sender is excluded from it — that is the consensus wire
    format, and what lets the 68 B/lane path ship digests per round).
    Signing is the signers' cost, not the verifier's: generated here,
    untimed, through the native signer."""
    batches = []
    tallies = []
    m_rounds = []
    for it in range(iters):
        items = []
        values = []
        m_round = np.zeros((rounds, 32), dtype=np.uint8)
        ns_byte = bytes([sum(namespace) % 256])  # actually varies per namespace
        for r in range(rounds):
            value = bytes([it, r % 256, r // 256]) + ns_byte + b"\x2a" * 28
            values.append(value)
            digest = Prevote(
                height=1 + it, round=r, value=value, sender=ring[0].public
            ).digest()
            m_round[r] = np.frombuffer(digest, dtype=np.uint8)
            for v in range(validators):
                items.append(
                    (ring[v].public, digest, ring[v].sign_digest(digest))
                )
        vote_vals = jnp.asarray(
            np.repeat(pack_values(values)[:, None, :], validators, axis=1)
        )
        target_vals = jnp.asarray(pack_values(values))
        batches.append(items)
        tallies.append((vote_vals, target_vals))
        m_rounds.append(jnp.asarray(m_round))
    return batches, tallies, m_rounds


def _timed_trials(launch_fn, batch, iters, trials):
    """Timed pipelines of ``iters`` launches; returns votes/s rates. The
    last launch's mask is materialized inside the timed region (the
    device executes enqueued programs in order, so that transfer bounds
    the whole pipeline); np.asarray is the completion barrier —
    block_until_ready is unreliable over the axon tunnel. EVERY launch's
    mask is then checked after the clock stops: the published rate must
    never cover unverified work, and the post-timing fetches cost the
    trials nothing."""
    rates = []
    for _ in range(trials):
        t0 = time.perf_counter()
        oks = [launch_fn(k) for k in range(iters)]
        np.asarray(oks[-1])
        dt = time.perf_counter() - t0
        for ok in oks:
            if not bool(np.asarray(ok).all()):
                raise RuntimeError("pipeline rejected valid signatures")
        rates.append(batch * iters / dt)
    return rates


def run_sustained(validators: int = N_VALIDATORS, rounds: int = ROUNDS,
                  iters: int = ITERS, trials: int = TRIALS,
                  backend: str | None = None,
                  full_wire: bool = True,
                  namespace: bytes = b"bench") -> dict:
    """The sustained unique-signature pipeline measurement (the shared
    harness — see module doc). Returns the full self-describing record;
    raises if any launch rejects a valid signature."""
    backend = resolve_backend(backend)
    semi_verify, full_verify = _verify_fns(backend)
    batch = validators * rounds

    @jax.jit
    def step(idx, r_rows, s_rows, k_rows, tnax, tay, tnat, tvalid,
             vote_vals, target_vals, f):
        ok = semi_verify(idx, r_rows, s_rows, k_rows, tnax, tay, tnat,
                         tvalid)
        counts = tally_counts(
            vote_vals, ok.reshape(rounds, validators), target_vals
        )
        flags = quorum_flags(counts, f)
        return ok, counts, flags

    # 68 B/lane challenge leg: digests broadcast round->lanes on device,
    # A gathered from the resident table, k = SHA-512(R||A||M) mod L
    # in-launch (ops/sha512_jax.py). A separate executable from the
    # ladder (see ed25519_wire.make_chalwire_verify_fn for why); k stays
    # device-resident between the two enqueued launches.
    chal_leg = make_challenge_round_fn(validators)

    def step_chal(idx, r_rows, s_rows, m_round, tnax, tay, tnat, tvalid,
                  trows, vote_vals, target_vals, f):
        k_rows = chal_leg(idx, r_rows, m_round, trows)
        return step(idx, r_rows, s_rows, k_rows, tnax, tay, tnat, tvalid,
                    vote_vals, target_vals, f)

    @jax.jit
    def step_full(a_rows, r_rows, s_rows, k_rows, vote_vals, target_vals,
                  f):
        ok = full_verify(a_rows, r_rows, s_rows, k_rows)
        counts = tally_counts(
            vote_vals, ok.reshape(rounds, validators), target_vals
        )
        flags = quorum_flags(counts, f)
        return ok, counts, flags

    ring = KeyRing.deterministic(validators, namespace=namespace)
    table = ValidatorTable([ring[v].public for v in range(validators)])
    tbl = table.arrays()
    tbl_chal = table.arrays_chal()
    host = Ed25519WireHost(buckets=(batch,))
    f = jnp.int32(validators // 3)

    t0 = time.perf_counter()
    batches, tallies, m_rounds = _build_batches(
        ring, validators, rounds, iters, namespace
    )
    gen_s = time.perf_counter() - t0

    # Warmup / compile + correctness gate on batch 0 (all paths).
    rows0, prevalid0, n0 = host.pack_wire_indexed(batches[0], table)
    assert n0 == batch and prevalid0.all()
    dev0 = tuple(jnp.asarray(r) for r in rows0)
    ok, counts, flags = step(*dev0, *tbl, *tallies[0], f)
    if not bool(np.asarray(ok).all()):
        raise RuntimeError("verification kernel rejected valid signatures")
    assert bool(np.asarray(flags["quorum_matching"]).all())
    crows0, cpre0, _ = host.pack_wire_challenge(
        batches[0], table, with_m=False
    )
    assert cpre0.all()
    ok_c, _, flags_c = step_chal(
        jnp.asarray(crows0[0]), jnp.asarray(crows0[1]),
        jnp.asarray(crows0[2]), m_rounds[0], *tbl_chal, *tallies[0], f
    )
    if not bool(np.asarray(ok_c).all()):
        raise RuntimeError("challenge kernel rejected valid signatures")
    assert bool(np.asarray(flags_c["quorum_matching"]).all())
    if full_wire:
        fw0, fpv0, _ = host.pack_wire(batches[0])
        fdev0 = tuple(jnp.asarray(r) for r in fw0)
        assert fpv0.all()
        ok_f, _, _ = step_full(*fdev0, *tallies[0], f)
        assert bool(np.asarray(ok_f).all())

    # --- Headline: sustained challenge-on-device pipeline, fresh
    # signatures every launch (pack -> enqueue -> pack next while the
    # device works), 68 B/lane.
    def launch_chal(k):
        (idx, rr, ss, _), prevalid, _ = host.pack_wire_challenge(
            batches[k], table, with_m=False
        )
        if not prevalid.all():
            raise RuntimeError(f"batch {k}: packer rejected lanes")
        ok, counts, flags = step_chal(
            jnp.asarray(idx), jnp.asarray(rr), jnp.asarray(ss),
            m_rounds[k], *tbl_chal, *tallies[k], f
        )
        return ok

    sustained = _timed_trials(launch_chal, batch, iters, trials)

    out = {
        "backend": backend,
        "batch": batch,
        "validators": validators,
        "iters": iters,
        "unique_signatures": True,
        "bytes_per_lane": 68,
        "sustained_votes_per_s": round(float(np.median(sustained)), 1),
        "sustained_trials": [round(r, 1) for r in sustained],
        "siggen_seconds_untimed": round(gen_s, 1),
        "device": str(jax.devices()[0]),
        # Resident-table footprint, summed from the live arrays so layout
        # changes keep the record true.
        "table_bytes": int(sum(
            np.asarray(a).nbytes for a in table.arrays_chal()
        )),
    }

    # --- Secondary: host-hashed indexed path (k packed on host,
    # 100 B/lane) — the round-3 operating point, kept for the delta.
    def launch_indexed(k):
        rows, prevalid, _ = host.pack_wire_indexed(batches[k], table)
        if not prevalid.all():
            raise RuntimeError(f"batch {k}: packer rejected lanes")
        ok, counts, flags = step(
            *(jnp.asarray(r) for r in rows), *tbl, *tallies[k], f
        )
        return ok

    hosthash = _timed_trials(launch_indexed, batch, iters, trials)
    out["sustained_hosthash_votes_per_s"] = round(
        float(np.median(hosthash)), 1
    )
    out["hosthash_bytes_per_lane"] = 100

    # --- Secondary: full-wire path (arbitrary pubkeys, 128 B/lane).
    if full_wire:
        def launch_full(k):
            rows, prevalid, _ = host.pack_wire(batches[k])
            if not prevalid.all():
                raise RuntimeError(f"batch {k}: packer rejected lanes")
            ok, counts, flags = step_full(
                *(jnp.asarray(r) for r in rows), *tallies[k], f
            )
            return ok

        full_rates = _timed_trials(launch_full, batch, iters, trials)
        out["sustained_full_wire_votes_per_s"] = round(
            float(np.median(full_rates)), 1
        )
        out["full_wire_bytes_per_lane"] = 128

    # --- Device ceiling: same pipelining, pre-packed device-resident
    # inputs reused (no per-launch transfer).
    device_only = _timed_trials(
        lambda k: step(*dev0, *tbl, *tallies[0], f)[0],
        batch, iters, trials,
    )
    out["device_only_votes_per_s"] = round(
        float(np.median(device_only)), 1
    )

    # --- Pack-only rates (the host leg in isolation; chal = no hashing).
    t0 = time.perf_counter()
    host.pack_wire_challenge(batches[min(1, iters - 1)], table,
                             with_m=False)
    pack_s = time.perf_counter() - t0
    out["chal_pack_sigs_per_s"] = round(batch / pack_s, 1)
    out["chal_pack_seconds"] = round(pack_s, 3)
    t0 = time.perf_counter()
    host.pack_wire_indexed(batches[min(1, iters - 1)], table)
    pack_s = time.perf_counter() - t0
    out["wire_pack_sigs_per_s"] = round(batch / pack_s, 1)
    out["wire_pack_seconds"] = round(pack_s, 3)
    return out


def main():
    backend = sys.argv[1] if len(sys.argv) > 1 else None
    try:
        r = run_sustained(backend=backend)
    except RuntimeError as e:
        print(json.dumps({
            "metric": "sustained votes verified/sec/chip @256 validators",
            "value": 0.0, "unit": "votes/s", "vs_baseline": 0.0,
            "error": str(e),
        }))
        sys.exit(1)
    votes_per_sec = r.pop("sustained_votes_per_s")
    print(json.dumps({
        "metric": "sustained votes verified/sec/chip @256 validators",
        "value": votes_per_sec,
        "unit": "votes/s",
        "vs_baseline": round(votes_per_sec / TARGET_VOTES_PER_SEC, 4),
        **r,
    }))


if __name__ == "__main__":
    main()
