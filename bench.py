"""Benchmark: votes verified per second on one TPU chip, 256 validators.

The north-star metric (BASELINE.md): batched Ed25519 verification of
consensus votes — 256 validators' signatures over vote digests, verified
in wide batches fused with the quorum tally — target >= 50k votes/sec on
one v5e chip.

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

from hyperdrive_tpu.crypto import ed25519 as host_ed
from hyperdrive_tpu.crypto.keys import KeyRing
from hyperdrive_tpu.messages import Prevote
from hyperdrive_tpu.ops.ed25519_jax import Ed25519BatchHost, make_verify_fn
from hyperdrive_tpu.ops.ed25519_pallas import (
    make_pallas_verify_fn,
    resolve_backend,
)
from hyperdrive_tpu.ops.tally import pack_values, tally_counts, quorum_flags

N_VALIDATORS = 256
# In-flight (height, round) pairs per launch. Measured Pallas-backend
# sweep on v5e (8-iter pipeline): 128 rounds (32k sigs) -> 489k/s,
# 256 (64k) -> 532k/s, 512 (128k) -> 565k/s, 1024 (256k) -> 580k/s.
# Gains flatten under 3% per doubling past 256 rounds while per-launch
# latency doubles; 256 rounds (0.12 s/launch) is the shipped operating
# point. (XLA-fallback sweep peaked at 64.4-66k/s around 128-256 rounds.)
ROUNDS = 256
BATCH = N_VALIDATORS * ROUNDS  # 65536 signatures per device launch
TARGET_VOTES_PER_SEC = 50_000.0


def build_batch():
    """256 validators each sign one prevote per round; rounds tile the
    batch so packing cost stays small while the device sees 4096 distinct
    (pubkey, digest, signature) lanes."""
    ring = KeyRing.deterministic(N_VALIDATORS, namespace=b"bench")
    value = b"\x2a" * 32
    items = []
    base_msgs = []
    for v in range(N_VALIDATORS):
        pv = Prevote(height=1, round=0, value=value, sender=ring[v].public)
        digest = pv.digest()
        sig = host_ed.sign(ring[v].seed, digest)
        base_msgs.append((ring[v].public, digest, sig))
    for r in range(ROUNDS):
        items.extend(base_msgs)

    host = Ed25519BatchHost(buckets=(BATCH,))
    arrays, prevalid, n = host.pack(items)
    assert n == BATCH and prevalid.all()

    vote_vals = jnp.asarray(
        np.broadcast_to(
            pack_values([value])[0], (ROUNDS, N_VALIDATORS, 8)
        ).copy()
    )
    target_vals = jnp.asarray(pack_values([value] * ROUNDS))
    return tuple(jnp.asarray(a) for a in arrays), vote_vals, target_vals


# Kernel backend: the Pallas ladder on TPU (7.5x), the XLA kernel elsewhere.
# `python bench.py xla` forces the fallback so its published figure stays
# reproducible with this same harness.
BACKEND = resolve_backend(sys.argv[1] if len(sys.argv) > 1 else None)
_verify = make_pallas_verify_fn() if BACKEND == "pallas" else make_verify_fn()


@jax.jit
def step(ax, ay, at, rx, ry, s_nib, k_nib, vote_vals, target_vals, f):
    ok = _verify(ax, ay, at, rx, ry, s_nib, k_nib)
    counts = tally_counts(vote_vals, ok.reshape(ROUNDS, N_VALIDATORS), target_vals)
    flags = quorum_flags(counts, f)
    return ok, counts, flags


def main():
    t0 = time.time()
    arrays, vote_vals, target_vals = build_batch()
    f = jnp.int32(N_VALIDATORS // 3)
    pack_s = time.time() - t0

    # Warmup / compile. (np.asarray, not block_until_ready: the latter is
    # unreliable over the axon tunnel — materializing is the only honest
    # completion barrier.)
    ok, counts, flags = step(*arrays, vote_vals, target_vals, f)
    if not bool(np.asarray(ok).all()):
        print(
            json.dumps(
                {
                    "metric": "votes verified/sec/chip @256 validators",
                    "value": 0.0,
                    "unit": "votes/s",
                    "vs_baseline": 0.0,
                    "error": "verification kernel rejected valid signatures",
                }
            )
        )
        sys.exit(1)
    assert bool(np.asarray(flags["quorum_matching"]).all())

    # Steady state: dispatch the in-order stream, materialize the last
    # result inside the timed region (the device executes enqueued programs
    # in order, so the final transfer bounds the pipeline). Three timed
    # trials so the reported rate carries its own variance instead of a
    # single 8-iter sample.
    iters = 8
    trials = 3
    rates = []
    for _ in range(trials):
        t0 = time.perf_counter()
        last = None
        for _ in range(iters):
            ok, counts, flags = step(*arrays, vote_vals, target_vals, f)
            last = ok
        final = np.asarray(last)  # materialization = the completion barrier
        dt = time.perf_counter() - t0
        if not bool(final.all()):
            raise RuntimeError("verification kernel rejected valid signatures")
        rates.append(BATCH * iters / dt)

    votes_per_sec = float(np.median(rates))
    print(
        json.dumps(
            {
                "metric": "votes verified/sec/chip @256 validators",
                "value": round(votes_per_sec, 1),
                "unit": "votes/s",
                "vs_baseline": round(votes_per_sec / TARGET_VOTES_PER_SEC, 4),
                "backend": BACKEND,
                "batch": BATCH,
                "iters": iters,
                "trial_rates": [round(r, 1) for r in rates],
                "host_pack_seconds": round(pack_s, 2),
                "device": str(jax.devices()[0]),
            }
        )
    )


if __name__ == "__main__":
    main()
