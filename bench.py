"""Benchmark: SUSTAINED votes verified per second on one TPU chip.

The north-star metric (BASELINE.md): batched Ed25519 verification of
consensus votes — 256 validators' signatures over vote digests, verified
in wide batches fused with the quorum tally — target >= 50k votes/sec on
one v5e chip.

Round-4 headline: the sustained UNIQUE-signature pipeline. Every timed
launch consumes a fresh batch of distinct signatures; the host packs
batch k+1 while the device verifies batch k. No input reuse — this is
the rate a deployment's mq drain loop could sustain (reference hot
path: /root/reference/process/process.go:574-579), not a kernel ceiling
fed by a pre-packed buffer.

Data path (ops/ed25519_wire.py + ops/sha512_jax.py): point decompression
AND the challenge hash run ON DEVICE; the host only range-checks and
marshals bytes. The consensus validator set is known, so A comes from a
device-resident pubkey table, and the signing digests are per-ROUND data
(the sender is excluded from them). Round 5 takes the last step to the
Ed25519 TRANSFER FLOOR: in the dense verification grid the lane ->
validator mapping is TOPOLOGY (lane = round * V + validator), so the
index tensor is uploaded once beside the table and each launch ships
exactly the signature bytes — R 32 + s 32 = 64 B/lane, nothing else.
(Wrong topology cannot pass silently: the index selects A, and a wrong A
fails verification; every launch's mask is checked.) On this
tunnel-attached chip (~4-13 MB/s H2D across sessions, BENCH.md) the
pipeline is TRANSFER-bound, so bytes/lane — not kernel speed and not
host speed — set the sustained rate; the per-launch-index 68 B/lane
path, the host-hashed 100 B/lane path, the full-wire (128 B/lane) rate,
the device-only ceiling, and the host pack rates are reported alongside
so the bottleneck is visible.

:func:`run_sustained` is the ONE harness: bench.py's 256-validator
headline and BENCH.md config 7's 512-validator operating point both call
it, so the methodology cannot drift between them.

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

from hyperdrive_tpu.crypto.keys import KeyRing
from hyperdrive_tpu.messages import Prevote
from hyperdrive_tpu.ops.ed25519_pallas import resolve_backend
from hyperdrive_tpu.ops.ed25519_wire import (
    Ed25519WireHost,
    ValidatorTable,
    make_challenge_round_fn,
    make_semiwire_verify_fn,
    make_wire_verify_fn,
)
from hyperdrive_tpu.ops.tally import pack_values, tally_counts, quorum_flags

N_VALIDATORS = 256
# In-flight (height, round) pairs per launch: 256 rounds x 256 validators
# = 65,536 signatures/launch (the round-3 sweep's operating point — past
# 256 rounds gains flatten under 3%/doubling while launch latency
# doubles).
ROUNDS = 256
TARGET_VOTES_PER_SEC = 50_000.0

#: Timed launches per trial. Every launch gets its own fresh signature
#: batch within a trial (pack || transfer || verify overlap); batches are
#: re-used ACROSS trials but re-packed in full each time, so no packed
#: tensor ever crosses a trial boundary.
ITERS = 4
TRIALS = 3


def _verify_fns(backend: str):
    if backend == "pallas":
        from hyperdrive_tpu.ops.ed25519_pallas import (
            make_pallas_semiwire_verify_fn,
            make_pallas_wire_verify_fn,
        )

        return make_pallas_semiwire_verify_fn(), make_pallas_wire_verify_fn()
    return make_semiwire_verify_fn(), make_wire_verify_fn()


def _build_batches(ring, validators, rounds, iters, namespace: bytes):
    """``iters`` batches of validators*rounds UNIQUE signatures: every
    validator signs one prevote per (round, iter) — every digest
    distinct ACROSS rounds, so no dedup/caching anywhere in the pipeline
    can shortcut the work. Within a round all validators sign the same
    digest (the sender is excluded from it — that is the consensus wire
    format, and what lets the 68 B/lane path ship digests per round).
    Signing is the signers' cost, not the verifier's: generated here,
    untimed, through the native signer."""
    batches = []
    tallies = []
    m_rounds = []
    for it in range(iters):
        items = []
        values = []
        m_round = np.zeros((rounds, 32), dtype=np.uint8)
        ns_byte = bytes([sum(namespace) % 256])  # actually varies per namespace
        for r in range(rounds):
            value = bytes([it, r % 256, r // 256]) + ns_byte + b"\x2a" * 28
            values.append(value)
            digest = Prevote(
                height=1 + it, round=r, value=value, sender=ring[0].public
            ).digest()
            m_round[r] = np.frombuffer(digest, dtype=np.uint8)
            for v in range(validators):
                items.append(
                    (ring[v].public, digest, ring[v].sign_digest(digest))
                )
        vote_vals = jnp.asarray(
            np.repeat(pack_values(values)[:, None, :], validators, axis=1)
        )
        target_vals = jnp.asarray(pack_values(values))
        batches.append(items)
        tallies.append((vote_vals, target_vals))
        m_rounds.append(jnp.asarray(m_round))
    return batches, tallies, m_rounds


def _timed_trials(launch_fn, batch, iters, trials):
    """Timed pipelines of ``iters`` launches; returns votes/s rates. The
    last launch's mask is materialized inside the timed region (the
    device executes enqueued programs in order, so that transfer bounds
    the whole pipeline); np.asarray is the completion barrier —
    block_until_ready is unreliable over the axon tunnel. EVERY launch's
    mask is then checked after the clock stops: the published rate must
    never cover unverified work, and the post-timing fetches cost the
    trials nothing."""
    return _timed_trials_multi({"leg": launch_fn}, batch, iters,
                               trials)["leg"]


def _timed_trials_multi(legs, batch, iters, trials):
    """PAIRED trials across wire-format legs: every trial times each
    leg's pipeline back-to-back, leg order rotating per trial. The
    tunnel's H2D bandwidth drifts on the minutes scale (measured: a
    sequential-leg run once ranked 100 B/lane above 64 B/lane purely by
    WHEN each leg ran), so sequential per-leg trial blocks can assign
    different bandwidth regimes to different legs; pairing makes the
    cross-leg RATIOS the session-invariant claim. Same per-launch mask
    checks as the single-leg form (which is this with one leg).

    Leg positions fully balance only when ``trials`` is a multiple of
    the leg count; with fewer trials some legs never lead a trial. The
    PUBLISHED cross-format claims are the per-trial paired ratios —
    within-trial comparisons seconds apart, which drift on the minutes
    scale cannot split — so residual cross-trial positional skew enters
    the per-leg medians, not the ratios."""
    names = list(legs)
    rates = {n: [] for n in names}
    for t in range(trials):
        order = names[t % len(names):] + names[: t % len(names)]
        for n in order:
            fn = legs[n]
            t0 = time.perf_counter()
            oks = [fn(k) for k in range(iters)]
            np.asarray(oks[-1])
            dt = time.perf_counter() - t0
            for ok in oks:
                if not bool(np.asarray(ok).all()):
                    raise RuntimeError(
                        "pipeline rejected valid signatures"
                    )
            rates[n].append(batch * iters / dt)
    return rates


def run_sustained(validators: int = N_VALIDATORS, rounds: int = ROUNDS,
                  iters: int = ITERS, trials: int = TRIALS,
                  backend: str | None = None,
                  full_wire: bool = True,
                  namespace: bytes = b"bench") -> dict:
    """The sustained unique-signature pipeline measurement (the shared
    harness — see module doc). Returns the full self-describing record;
    raises if any launch rejects a valid signature."""
    backend = resolve_backend(backend)
    semi_verify, full_verify = _verify_fns(backend)
    batch = validators * rounds

    @jax.jit
    def step(idx, r_rows, s_rows, k_rows, tnax, tay, tnat, tvalid,
             vote_vals, target_vals, f):
        ok = semi_verify(idx, r_rows, s_rows, k_rows, tnax, tay, tnat,
                         tvalid)
        counts = tally_counts(
            vote_vals, ok.reshape(rounds, validators), target_vals
        )
        flags = quorum_flags(counts, f)
        return ok, counts, flags

    # 68 B/lane challenge leg: digests broadcast round->lanes on device,
    # A gathered from the resident table, k = SHA-512(R||A||M) mod L
    # in-launch (ops/sha512_jax.py). A separate executable from the
    # ladder (see ed25519_wire.make_chalwire_verify_fn for why); k stays
    # device-resident between the two enqueued launches.
    chal_leg = make_challenge_round_fn(validators)

    def step_chal(idx, r_rows, s_rows, m_round, tnax, tay, tnat, tvalid,
                  trows, vote_vals, target_vals, f):
        k_rows = chal_leg(idx, r_rows, m_round, trows)
        return step(idx, r_rows, s_rows, k_rows, tnax, tay, tnat, tvalid,
                    vote_vals, target_vals, f)

    @jax.jit
    def step_full(a_rows, r_rows, s_rows, k_rows, vote_vals, target_vals,
                  f):
        ok = full_verify(a_rows, r_rows, s_rows, k_rows)
        counts = tally_counts(
            vote_vals, ok.reshape(rounds, validators), target_vals
        )
        flags = quorum_flags(counts, f)
        return ok, counts, flags

    ring = KeyRing.deterministic(validators, namespace=namespace)
    table = ValidatorTable([ring[v].public for v in range(validators)])
    tbl = table.arrays()
    tbl_chal = table.arrays_chal()
    host = Ed25519WireHost(buckets=(batch,))
    f = jnp.int32(validators // 3)

    t0 = time.perf_counter()
    batches, tallies, m_rounds = _build_batches(
        ring, validators, rounds, iters, namespace
    )
    gen_s = time.perf_counter() - t0

    # Warmup / compile + correctness gate on batch 0 (all paths).
    rows0, prevalid0, n0 = host.pack_wire_indexed(batches[0], table)
    assert n0 == batch and prevalid0.all()
    dev0 = tuple(jnp.asarray(r) for r in rows0)
    ok, counts, flags = step(*dev0, *tbl, *tallies[0], f)
    if not bool(np.asarray(ok).all()):
        raise RuntimeError("verification kernel rejected valid signatures")
    assert bool(np.asarray(flags["quorum_matching"]).all())
    crows0, cpre0, _ = host.pack_wire_challenge(
        batches[0], table, with_m=False
    )
    assert cpre0.all()
    ok_c, _, flags_c = step_chal(
        jnp.asarray(crows0[0]), jnp.asarray(crows0[1]),
        jnp.asarray(crows0[2]), m_rounds[0], *tbl_chal, *tallies[0], f
    )
    if not bool(np.asarray(ok_c).all()):
        raise RuntimeError("challenge kernel rejected valid signatures")
    assert bool(np.asarray(flags_c["quorum_matching"]).all())
    if full_wire:
        fw0, fpv0, _ = host.pack_wire(batches[0])
        fdev0 = tuple(jnp.asarray(r) for r in fw0)
        assert fpv0.all()
        ok_f, _, _ = step_full(*fdev0, *tallies[0], f)
        assert bool(np.asarray(ok_f).all())

    # --- Headline: sustained challenge-on-device pipeline at the
    # Ed25519 transfer floor — 64 B/lane. The dense grid's lane ->
    # validator mapping is topology, so the index tensor lives on device
    # beside the table (uploaded once, below); each launch ships exactly
    # the signature bytes (R || s) plus the per-round digests. The
    # topology claim is CHECKED: the host packer's own index must equal
    # the resident one, and a wrong index would select the wrong A and
    # fail verification anyway (every launch's mask is asserted).
    idx_np = np.tile(np.arange(validators, dtype=np.int32), rounds)
    if not np.array_equal(np.asarray(crows0[0]), idx_np):
        raise RuntimeError("dense-grid topology does not match the packer")
    idx_dev = jnp.asarray(idx_np)

    def launch_chal64(k):
        (_, rr, ss, _), prevalid, _ = host.pack_wire_challenge(
            batches[k], table, with_m=False, _idx=idx_np
        )
        if not prevalid.all():
            raise RuntimeError(f"batch {k}: packer rejected lanes")
        ok, counts, flags = step_chal(
            idx_dev, jnp.asarray(rr), jnp.asarray(ss),
            m_rounds[k], *tbl_chal, *tallies[k], f
        )
        return ok

    # --- Secondary legs, defined up front: the wire-format comparison
    # is measured PAIRED (every trial runs all legs back-to-back, order
    # rotating — see _timed_trials_multi) so tunnel drift cannot rank
    # the formats by when they happened to run.
    def launch_chal(k):
        # 68 B/lane: the index ships per launch (non-dense lane layouts,
        # where the index is real data — the round-4 operating point).
        (idx, rr, ss, _), prevalid, _ = host.pack_wire_challenge(
            batches[k], table, with_m=False
        )
        if not prevalid.all():
            raise RuntimeError(f"batch {k}: packer rejected lanes")
        ok, counts, flags = step_chal(
            jnp.asarray(idx), jnp.asarray(rr), jnp.asarray(ss),
            m_rounds[k], *tbl_chal, *tallies[k], f
        )
        return ok

    def launch_indexed(k):
        # 100 B/lane: k = SHA-512(R||A||M) mod L packed on HOST.
        rows, prevalid, _ = host.pack_wire_indexed(batches[k], table)
        if not prevalid.all():
            raise RuntimeError(f"batch {k}: packer rejected lanes")
        ok, counts, flags = step(
            *(jnp.asarray(r) for r in rows), *tbl, *tallies[k], f
        )
        return ok

    legs = {
        "chal64": launch_chal64,
        "chal68": launch_chal,
        "hosthash": launch_indexed,
    }
    if full_wire:
        def launch_full(k):
            # 128 B/lane: arbitrary pubkeys, A ships as its encoding.
            rows, prevalid, _ = host.pack_wire(batches[k])
            if not prevalid.all():
                raise RuntimeError(f"batch {k}: packer rejected lanes")
            ok, counts, flags = step_full(
                *(jnp.asarray(r) for r in rows), *tallies[k], f
            )
            return ok

        legs["full"] = launch_full

    paired = _timed_trials_multi(legs, batch, iters, trials)
    sustained = paired["chal64"]
    sustained68 = paired["chal68"]
    hosthash = paired["hosthash"]

    out = {
        "backend": backend,
        "batch": batch,
        "validators": validators,
        "iters": iters,
        "unique_signatures": True,
        "bytes_per_lane": 64,
        "sustained_votes_per_s": round(float(np.median(sustained)), 1),
        "sustained_trials": [round(r, 1) for r in sustained],
        "sustained_68_votes_per_s": round(
            float(np.median(sustained68)), 1
        ),
        "sustained_68_trials": [round(r, 1) for r in sustained68],
        "siggen_seconds_untimed": round(gen_s, 1),
        "device": str(jax.devices()[0]),
        # Resident-table footprint, summed from the live arrays so layout
        # changes keep the record true. The resident index is its OWN
        # key: it scales with the grid shape (4 * V * rounds), not the
        # validator table, and folding it in would make table_bytes
        # incomparable across rounds settings.
        "table_bytes": int(sum(
            np.asarray(a).nbytes for a in table.arrays_chal()
        )),
        "resident_index_bytes": int(idx_np.nbytes),
    }

    out["sustained_hosthash_votes_per_s"] = round(
        float(np.median(hosthash)), 1
    )
    out["hosthash_bytes_per_lane"] = 100
    # Per-trial paired ratios: the session-invariant byte-ratio claim
    # (each ratio compares legs measured seconds apart in one trial).
    out["paired_64_over_100_ratios"] = [
        round(a / b, 3) for a, b in zip(sustained, hosthash)
    ]
    if full_wire:
        full_rates = paired["full"]
        out["sustained_full_wire_votes_per_s"] = round(
            float(np.median(full_rates)), 1
        )
        out["full_wire_bytes_per_lane"] = 128

    # --- Device ceiling: same pipelining, pre-packed device-resident
    # inputs reused (no per-launch transfer).
    device_only = _timed_trials(
        lambda k: step(*dev0, *tbl, *tallies[0], f)[0],
        batch, iters, trials,
    )
    out["device_only_votes_per_s"] = round(
        float(np.median(device_only)), 1
    )

    # --- Pack-only rates (the host leg in isolation; chal = no hashing).
    t0 = time.perf_counter()
    host.pack_wire_challenge(batches[min(1, iters - 1)], table,
                             with_m=False)
    pack_s = time.perf_counter() - t0
    out["chal_pack_sigs_per_s"] = round(batch / pack_s, 1)
    out["chal_pack_seconds"] = round(pack_s, 3)
    t0 = time.perf_counter()
    host.pack_wire_indexed(batches[min(1, iters - 1)], table)
    pack_s = time.perf_counter() - t0
    out["wire_pack_sigs_per_s"] = round(batch / pack_s, 1)
    out["wire_pack_seconds"] = round(pack_s, 3)
    return out


def run_insert_leg(validators: int = N_VALIDATORS, replicas: int = 64,
                   rounds: int = 2, trials: int = 5,
                   inject_slowdown: float = 0.0):
    """Host-side automaton INSERT leg: columnar settle fast path
    (``Process.ingest_insert_cols`` over a shared ``WindowColumns`` view)
    against the object path (per-replica keep/allowed filter comprehension
    + ``Process.ingest_insert``), exactly the two code paths
    ``Replica.ingest_insert_window[_cols]`` dispatches between.

    Pure host Python — no device required — so it is the engine-path
    metric a CPU-only container can still regenerate honestly. The window
    is the lockstep settle shape: ``rounds`` full (propose + V prevotes +
    V precommits) rounds, ingested by ``replicas`` fresh processes per
    trial (the redundant-settle regime where the columnar view's one-pass
    extraction amortizes across every replica). Ratios are PAIRED per
    trial; the headline is their median.
    """
    import hashlib

    from hyperdrive_tpu.batch import WindowColumns
    from hyperdrive_tpu.messages import Precommit, Propose
    from hyperdrive_tpu.process import Process
    from hyperdrive_tpu.types import INVALID_ROUND

    senders = [hashlib.sha256(b"ins-%d" % i).digest()
               for i in range(validators)]
    allowed = set(senders)
    window = []
    for r in range(rounds):
        v = hashlib.sha256(b"insv-%d" % r).digest()
        window.append(Propose(height=1, round=r, valid_round=INVALID_ROUND,
                              value=v, sender=senders[r % validators]))
        window.extend(Prevote(height=1, round=r, value=v, sender=s)
                      for s in senders)
        window.extend(Precommit(height=1, round=r, value=v, sender=s)
                      for s in senders)
    keep = [True] * len(window)
    cols = WindowColumns.from_messages(window)
    f = (validators - 1) // 3
    total = replicas * len(window)

    def leg_obj():
        t0 = time.perf_counter()
        for _ in range(replicas):
            p = Process(senders[0], f=f)
            batch = [m for j, m in enumerate(window)
                     if keep[j] and m.sender in allowed]
            p.ingest_insert(batch)
        return total / (time.perf_counter() - t0)

    def leg_col():
        t0 = time.perf_counter()
        for _ in range(replicas):
            p = Process(senders[0], f=f)
            p.ingest_insert_cols(cols, keep, allowed)
            if inject_slowdown:
                # Sentinel self-test hook (tests/test_benchdiff.py):
                # deliberately tax the columnar leg so the paired-ratio
                # gate must flag the run.
                time.sleep(inject_slowdown)
        return total / (time.perf_counter() - t0)

    leg_obj(), leg_col()  # warm allocator + bytecode caches
    obj_rates, col_rates, ratios = [], [], []
    for _ in range(trials):
        a = leg_obj()
        b = leg_col()
        obj_rates.append(a)
        col_rates.append(b)
        ratios.append(b / a)
    return {
        "window_rows": len(window),
        "replicas": replicas,
        "validators": validators,
        "trials": trials,
        "object_rows_per_s": round(float(np.median(obj_rates)), 1),
        "columnar_rows_per_s": round(float(np.median(col_rates)), 1),
        "insert_leg_paired_ratios": [round(r, 3) for r in ratios],
        "insert_leg_speedup_median": round(float(np.median(ratios)), 3),
        "insert_leg_speedup_min": round(min(ratios), 3),
    }


def run_quick(sim_trials: int = 3, insert_trials: int = 7,
              heights: int = 8, inject_slowdown: float = 0.0) -> dict:
    """The pinned quick bench: the CI perf sentinel's input.

    Pure host — the pipelined consensus sim rides the HostVerifier leg
    and the insert leg never touches a device — so any CPU runner can
    regenerate it. The artifact nominates its own regression gates via
    ``benchdiff_gate`` (see obs/benchdiff.py), and only MACHINE-PORTABLE
    series are gated: the insert leg's paired columnar/object speedup
    ratios divide the runner's speed out, while the absolute sim wall
    series stays informational (a committed baseline from one machine
    must not fail a differently-sized CI runner). The metrics-registry
    snapshot of the last sim run is embedded whole, so registry-visible
    regressions (occupancy collapse, queue-wait blowup, launch-count
    drift — all deterministic under the virtual clock) diff exactly.
    """
    from hyperdrive_tpu.harness import Simulation

    kw = dict(
        n=4, target_height=heights, seed=7, sign=True, burst=True,
        observe=True, pipeline_heights=True,
    )
    sim = None
    wall = []
    for _ in range(sim_trials):
        sim = Simulation(**kw)
        t0 = time.perf_counter()
        res = sim.run()
        wall.append(time.perf_counter() - t0)
        if not res.completed:
            raise RuntimeError("quick-bench sim failed to complete")
    snap = sim.metrics_snapshot()

    insert = run_insert_leg(
        validators=32, replicas=24, rounds=2, trials=insert_trials,
        inject_slowdown=inject_slowdown,
    )
    # The gated series: paired per-trial ratios under a speedup name so
    # the sentinel compares them in the higher-is-better direction. Only
    # the SERIES is gated — its bound adapts to the run's own scatter
    # (median absolute deviation), where a scalar median would hold the
    # default threshold against micro-benchmark timer noise.
    insert["speedup_series"] = insert["insert_leg_paired_ratios"]

    return {
        "schema": "hyperdrive-quick-bench-v1",
        "benchdiff_gate": [
            "insert.speedup_series",
        ],
        "insert": insert,
        "consensus": {
            "heights": heights,
            "replicas": kw["n"],
            "seed": kw["seed"],
            "sim_trials": sim_trials,
            "sim_wall_s": [round(w, 4) for w in wall],
            "journal_digest": sim.obs.digest(),
            "registry_digest": sim.registry.digest(),
        },
        "metrics_snapshot": snap,
    }


def _main_quick(argv) -> int:
    import argparse

    p = argparse.ArgumentParser(prog="python bench.py --quick")
    p.add_argument("-o", "--output", default=None)
    p.add_argument("--sim-trials", type=int, default=3)
    p.add_argument("--insert-trials", type=int, default=7)
    p.add_argument("--heights", type=int, default=8)
    p.add_argument("--inject-slowdown", type=float, default=0.0)
    ns = p.parse_args(argv)
    out = run_quick(
        sim_trials=ns.sim_trials, insert_trials=ns.insert_trials,
        heights=ns.heights, inject_slowdown=ns.inject_slowdown,
    )
    blob = json.dumps(out, indent=1, sort_keys=True)
    if ns.output:
        with open(ns.output, "w") as fh:
            fh.write(blob + "\n")
        print(json.dumps({
            "quick": ns.output,
            "journal_digest": out["consensus"]["journal_digest"],
        }))
    else:
        print(blob)
    return 0


def main():
    if "--quick" in sys.argv[1:]:
        args = [a for a in sys.argv[1:] if a != "--quick"]
        sys.exit(_main_quick(args))
    backend = sys.argv[1] if len(sys.argv) > 1 else None
    try:
        r = run_sustained(backend=backend)
    except RuntimeError as e:
        print(json.dumps({
            "metric": "sustained votes verified/sec/chip @256 validators",
            "value": 0.0, "unit": "votes/s", "vs_baseline": 0.0,
            "error": str(e),
        }))
        sys.exit(1)
    votes_per_sec = r.pop("sustained_votes_per_s")
    print(json.dumps({
        "metric": "sustained votes verified/sec/chip @256 validators",
        "value": votes_per_sec,
        "unit": "votes/s",
        "vs_baseline": round(votes_per_sec / TARGET_VOTES_PER_SEC, 4),
        **r,
        **_consensus_metrics(),
    }))


def _consensus_metrics() -> dict:
    """Tracer snapshot + commit anatomy from a small observed host sim.

    The headline number above is the wire pipeline alone; this rider
    makes the artifact self-describing about the consensus side too — a
    fixed-seed 4-replica run whose metric registry and per-phase
    commit-latency breakdown (OBSERVABILITY.md) land in the same JSON
    line, so artifact diffs catch regressions in either half.
    """
    try:
        from hyperdrive_tpu.harness import Simulation
        from hyperdrive_tpu.obs.report import phase_summary

        sim = Simulation(n=4, target_height=5, seed=91, timeout=20.0,
                         delivery_cost=0.001, observe=True)
        res = sim.run()
        if not res.completed:
            return {}
        return {
            "tracer_snapshot": sim.tracer.snapshot(),
            "commit_anatomy": phase_summary(sim.obs.snapshot()),
            # The uniform registry view (tracer series absorbed +
            # devtel/launch series when pipelining): what the obs CLI's
            # ``metrics`` subcommand and the quick bench also export.
            "metrics_snapshot": sim.metrics_snapshot(),
        }
    except Exception as e:  # the rider must never sink the headline run
        return {"consensus_metrics_error": str(e)}


if __name__ == "__main__":
    main()
