"""Crash-restart checkpointing for consensus processes.

The reference's checkpoint format is the surge marshal of the whole
``Process`` — identity, f, and the full State including message logs and
once-flags — with the contract "State should be saved after every method
call" (reference: process/process.go:183-223, process/state.go:18-20).
This module provides the file layer around this framework's equivalent
(:meth:`hyperdrive_tpu.process.Process.marshal`): a versioned, checksummed
envelope with atomic replace, so a replica killed mid-write never sees a
torn checkpoint.
"""

from __future__ import annotations

import os
import zlib

from hyperdrive_tpu.analysis.sanitizer import maybe_wire_reader
from hyperdrive_tpu.codec import Reader, SerdeError, Writer
from hyperdrive_tpu.process import Process

__all__ = [
    "save_process",
    "restore_process",
    "checkpoint_bytes",
    "restore_bytes",
    "CheckpointStore",
]

_MAGIC = 0x48594350  # "HYCP"
_VERSION = 1

#: Generous budget for one Process: state grows with logged votes per round.
_MAX_BYTES = 1 << 28


def checkpoint_bytes(proc: Process) -> bytes:
    """Serialize a Process into a self-validating envelope."""
    body = Writer(rem=_MAX_BYTES)
    proc.marshal(body)
    payload = body.data()
    head = Writer(rem=64)
    head.u32(_MAGIC)
    head.u32(_VERSION)
    head.u64(len(payload))
    head.u32(zlib.crc32(payload) & 0xFFFFFFFF)
    return head.data() + payload


def restore_bytes(proc: Process, data: bytes) -> None:
    """Restore ``proc`` in place from :func:`checkpoint_bytes` output.

    Raises :class:`~hyperdrive_tpu.codec.SerdeError` on any corruption —
    wrong magic, unsupported version, truncated payload, or checksum
    mismatch — without touching ``proc``.
    """
    head = Reader(data, rem=_MAX_BYTES + 64)
    if head.u32() != _MAGIC:
        raise SerdeError("not a process checkpoint (bad magic)")
    version = head.u32()
    if version != _VERSION:
        raise SerdeError(f"unsupported checkpoint version {version}")
    size = head.u64()
    crc = head.u32()
    payload = data[20:]
    if len(payload) != size:
        raise SerdeError(
            f"checkpoint truncated: header says {size} bytes, got {len(payload)}"
        )
    if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
        raise SerdeError("checkpoint checksum mismatch")
    proc.unmarshal_into(maybe_wire_reader(
        "process.checkpoint", payload, rem=_MAX_BYTES
    ))


def save_process(proc: Process, path: str) -> None:
    """Atomically write a checkpoint: write to a sibling temp file, fsync,
    rename. A crash at any point leaves either the old or the new
    checkpoint intact, never a torn one."""
    data = checkpoint_bytes(proc)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as fh:
        fh.write(data)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


def restore_process(proc: Process, path: str) -> None:
    """Restore ``proc`` in place from a checkpoint file."""
    with open(path, "rb") as fh:
        restore_bytes(proc, fh.read())


class CheckpointStore:
    """Latest-checkpoint-per-key store over the same self-validating
    envelope the file layer writes.

    The chaos engine's stand-in for each replica's checkpoint file:
    :meth:`save` snapshots a Process after every handled delivery (the
    reference's "save after every method call" contract), :meth:`latest`
    / :meth:`restore` hand the newest envelope back on crash-restart, and
    :meth:`dump` writes each entry to ``<dir>/replica_<key>.ckpt`` for
    post-mortem inspection alongside a ScenarioRecord dump.
    """

    def __init__(self) -> None:
        self._latest: dict[object, bytes] = {}

    def save(self, key, proc: Process) -> None:
        self._latest[key] = checkpoint_bytes(proc)

    def latest(self, key) -> "bytes | None":
        return self._latest.get(key)

    def restore(self, key, proc: Process) -> bool:
        """Restore ``proc`` from the newest checkpoint under ``key``;
        returns False (proc untouched) when none was ever saved."""
        data = self._latest.get(key)
        if data is None:
            return False
        restore_bytes(proc, data)
        return True

    def __len__(self) -> int:
        return len(self._latest)

    def dump(self, dirpath: str) -> list[str]:
        os.makedirs(dirpath, exist_ok=True)
        paths = []
        for key in sorted(self._latest, key=str):
            path = os.path.join(dirpath, f"replica_{key}.ckpt")
            with open(path, "wb") as fh:
                fh.write(self._latest[key])
            paths.append(path)
        return paths
