"""Tracing and metrics: counters, latency histograms, spans, profiler hook.

The reference has **no** observability (SURVEY.md §5: zap loggers are
configured but never called; `TraceLogs` is a consensus data structure, not
tracing). This module is the greenfield subsystem the survey calls for:

- :class:`Counter` / :class:`Histogram` — cheap process-local metrics.
- :class:`Tracer` — a named registry of both, with ``span()`` context
  timing, injectable everywhere a reference Options struct carried a
  logger. The :data:`NULL_TRACER` singleton makes every call a no-op so
  un-instrumented hot paths pay one attribute check.
- :func:`profile` — wraps ``jax.profiler.trace`` when JAX is importable so
  device traces (XLA ops, fusion, HBM traffic) land in TensorBoard format.

Time sources are injectable: the deterministic harness passes its
VirtualClock so round latencies are measured in simulated seconds, exactly
reproducible across record/replay.
"""

from __future__ import annotations

import bisect
import contextlib
import threading
import time
from typing import Callable, Iterator, Optional

__all__ = ["Counter", "Histogram", "Tracer", "NullTracer", "NULL_TRACER", "profile"]


class Counter:
    """A monotonically increasing count."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n


class Histogram:
    """Fixed-bucket histogram with exact quantiles over a bounded sample.

    Buckets follow a log-ish layout suited to latencies (seconds) and batch
    sizes. The most recent ``max_samples`` raw observations are kept for
    exact quantile queries; bucket counts never drop.
    """

    __slots__ = ("buckets", "counts", "total", "sum", "_samples", "_max_samples")

    DEFAULT_BUCKETS = (
        1e-6, 1e-5, 1e-4, 1e-3, 3e-3,
        1e-2, 3e-2, 0.1, 0.3, 1.0,
        3.0, 10.0, 30.0, 100.0, 1000.0,
    )

    def __init__(self, buckets=DEFAULT_BUCKETS, max_samples: int = 4096):
        self.buckets = tuple(buckets)
        self.counts = [0] * (len(self.buckets) + 1)
        self.total = 0
        self.sum = 0.0
        self._samples: list[float] = []
        self._max_samples = max_samples

    def observe(self, v: float) -> None:
        self.counts[bisect.bisect_left(self.buckets, v)] += 1
        self.total += 1
        self.sum += v
        if len(self._samples) < self._max_samples:
            self._samples.append(v)
        else:
            # Reservoir-less ring overwrite: cheap, recent-biased. This
            # observation is number ``total`` (post-increment), so it
            # lands in slot ``total - 1`` — keeping the retained window
            # exactly the most recent ``max_samples`` observations. (The
            # previous ``total % max`` indexing lagged the write slot by
            # one, so the oldest sample survived a full extra lap.)
            self._samples[(self.total - 1) % self._max_samples] = v

    def quantile(self, q: float) -> float:
        """Exact quantile over the retained sample window (0 if empty)."""
        if not self._samples:
            return 0.0
        s = sorted(self._samples)
        idx = min(len(s) - 1, max(0, int(q * (len(s) - 1))))
        return s[idx]

    @property
    def mean(self) -> float:
        return self.sum / self.total if self.total else 0.0


class Tracer:
    """Named registry of counters and histograms with span timing.

    ``time_fn`` defaults to ``time.perf_counter``; the simulator injects its
    virtual clock so traces are deterministic.
    """

    def __init__(self, time_fn: Optional[Callable[[], float]] = None,
                 threadsafe: bool = True):
        self.counters: dict[str, Counter] = {}
        self.histograms: dict[str, Histogram] = {}
        self._time = time_fn or time.perf_counter
        # One tracer is typically shared by many replicas, and replicas may
        # run on their own threads (Replica.run): updates lock by default.
        # A single-threaded driver (the simulator) passes threadsafe=False:
        # the per-call lock acquisition is the dominant cost of counting on
        # the hot path, and the GIL already serializes one-thread use.
        self._lock = threading.Lock() if threadsafe else None

    # ------------------------------------------------------------- recording

    def count(self, name: str, n: int = 1) -> None:
        lock = self._lock
        if lock is None:
            c = self.counters.get(name)
            if c is None:
                c = self.counters[name] = Counter()
            c.inc(n)
            return
        with lock:
            c = self.counters.get(name)
            if c is None:
                c = self.counters[name] = Counter()
            c.inc(n)

    def observe(self, name: str, v: float) -> None:
        lock = self._lock
        if lock is None:
            h = self.histograms.get(name)
            if h is None:
                h = self.histograms[name] = Histogram()
            h.observe(v)
            return
        with lock:
            h = self.histograms.get(name)
            if h is None:
                h = self.histograms[name] = Histogram()
            h.observe(v)

    @contextlib.contextmanager
    def span(self, name: str) -> Iterator[None]:
        """Time a block into histogram ``name`` (seconds)."""
        t0 = self._time()
        try:
            yield
        finally:
            self.observe(name, self._time() - t0)

    def now(self) -> float:
        return self._time()

    # ------------------------------------------------------------- reporting

    def snapshot(self) -> dict:
        """A JSON-friendly view of everything recorded."""
        out: dict = {"counters": {}, "histograms": {}}
        for name, c in sorted(self.counters.items()):
            out["counters"][name] = c.value
        for name, h in sorted(self.histograms.items()):
            out["histograms"][name] = {
                "count": h.total,
                "mean": h.mean,
                "p50": h.quantile(0.50),
                "p95": h.quantile(0.95),
                "p99": h.quantile(0.99),
            }
        return out

    def render(self) -> str:
        """Human-readable table of the snapshot."""
        snap = self.snapshot()
        lines = []
        if snap["counters"]:
            width = max(len(k) for k in snap["counters"])
            lines.append("counters:")
            for k, v in snap["counters"].items():
                lines.append(f"  {k:<{width}}  {v}")
        if snap["histograms"]:
            lines.append("histograms (count / mean / p50 / p95 / p99):")
            width = max(len(k) for k in snap["histograms"])
            for k, h in snap["histograms"].items():
                lines.append(
                    f"  {k:<{width}}  {h['count']:>8}  {h['mean']:.6g}  "
                    f"{h['p50']:.6g}  {h['p95']:.6g}  {h['p99']:.6g}"
                )
        return "\n".join(lines)


class NullTracer(Tracer):
    """All recording is a no-op; reporting returns empty structures."""

    def __init__(self):
        super().__init__(time_fn=lambda: 0.0)

    def count(self, name: str, n: int = 1) -> None:
        pass

    def observe(self, name: str, v: float) -> None:
        pass

    @contextlib.contextmanager
    def span(self, name: str) -> Iterator[None]:
        yield


#: Shared no-op tracer — the default everywhere a tracer is injectable.
NULL_TRACER = NullTracer()


@contextlib.contextmanager
def profile(log_dir: str) -> Iterator[None]:
    """Capture a JAX/XLA device profile into ``log_dir`` (TensorBoard
    format). No-ops cleanly when the profiler is unavailable (e.g. pure
    host runs)."""
    try:
        import jax

        ctx = jax.profiler.trace(log_dir)
    except Exception:
        yield
        return
    with ctx:
        yield
