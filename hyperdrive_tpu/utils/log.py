"""Structured logging for the framework.

The reference injects a ``zap.Logger`` through every Options struct but
never writes a single log line (SURVEY.md §5 — verified against the whole
repo). This framework keeps the injectable-logger capability and actually
uses it: the replica driver logs commits, height resyncs, signatory
rotations, and caught equivocations.

Loggers are stdlib :mod:`logging` with a key=value structured suffix so
output is grep-able without a dependency. A library must not configure the
root logger; :func:`get_logger` attaches a ``NullHandler`` and leaves
configuration (level, sinks) to the application — mirroring the
reference's "logger comes from the embedding app" stance.
"""

from __future__ import annotations

import logging

__all__ = ["get_logger", "kv"]


def get_logger(name: str = "hyperdrive_tpu") -> logging.Logger:
    logger = logging.getLogger(name)
    if not any(isinstance(h, logging.NullHandler) for h in logger.handlers):
        logger.addHandler(logging.NullHandler())
    return logger


def kv(**fields) -> str:
    """Render key=value pairs for a structured log suffix. Bytes are
    hex-abbreviated so 32-byte hashes stay readable."""
    parts = []
    for k, v in fields.items():
        if isinstance(v, (bytes, bytearray)):
            v = v.hex()[:16]
        parts.append(f"{k}={v}")
    return " ".join(parts)
