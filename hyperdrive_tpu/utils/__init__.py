"""Cross-cutting utilities: tracing/metrics, logging, checkpointing."""

from hyperdrive_tpu.utils.log import get_logger, kv
from hyperdrive_tpu.utils.trace import (
    NULL_TRACER,
    Counter,
    Histogram,
    NullTracer,
    Tracer,
    profile,
)

__all__ = [
    "get_logger",
    "kv",
    "NULL_TRACER",
    "Counter",
    "Histogram",
    "NullTracer",
    "Tracer",
    "profile",
]
