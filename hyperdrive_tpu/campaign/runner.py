"""Campaign runner: run, check, record, replay.

``run_campaign`` drives one family engine, feeds the summary through
the chaos monitor's campaign checks, and wraps the outcome in a
:class:`~hyperdrive_tpu.campaign.record.CampaignRecord`. Violations
are collected, not raised — the CLI and the soak legs decide whether
a violation dumps artifacts, raises, or both.

``replay_campaign`` is the determinism proof: re-run the record's
config from scratch and require the fresh summary digest to equal the
recorded one bit-for-bit. The chaos soak's ``--campaign-every`` leg
and the campaign-soak CI job call exactly this.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from hyperdrive_tpu.chaos.monitor import InvariantViolation, InvariantMonitor
from hyperdrive_tpu.obs.recorder import NULL_BOUND

from hyperdrive_tpu.campaign import CampaignConfig
from hyperdrive_tpu.campaign.families import ENGINES
from hyperdrive_tpu.campaign.record import CampaignRecord

__all__ = ["CampaignOutcome", "run_campaign", "replay_campaign"]


@dataclass
class CampaignOutcome:
    config: CampaignConfig
    summary: dict
    record: CampaignRecord
    #: ``(kind, detail)`` per check that failed; empty = clean run.
    violations: list = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.violations

    @property
    def digest(self) -> bytes:
        return self.record.digest


def _checks_for(config: CampaignConfig, summary: dict):
    mon = InvariantMonitor
    if config.family == "storm":
        yield lambda: mon.check_storm_hygiene(summary)
    if config.family in ("capture", "coincidence"):
        yield lambda: mon.check_campaign_proportionality(
            summary["trajectory"], grind_width=config.grind_width
        )
    if config.family == "coincidence":
        yield lambda: mon.check_campaign_economy(summary)


def run_campaign(
    config: CampaignConfig,
    *,
    registry=None,
    obs=NULL_BOUND,
) -> CampaignOutcome:
    """Run one campaign and judge it. Deterministic in ``config``:
    registry and obs observe the run but never feed the summary, so
    the outcome digest is a pure function of the config."""
    config.validate()
    summary = ENGINES[config.family](config, registry, obs)
    violations = []
    for check in _checks_for(config, summary):
        try:
            check()
        except InvariantViolation as err:
            violations.append((err.kind, str(err)))
            if obs is not NULL_BOUND:
                obs.emit("campaign.violation", -1, -1, err.kind)
    record = CampaignRecord.capture(config, summary)
    if registry is not None:
        registry.count("campaign.runs", label=config.family)
        if violations:
            registry.count("campaign.violations", len(violations))
    if obs is not NULL_BOUND:
        obs.emit(
            "campaign.done", -1, -1,
            "%s %s violations=%d"
            % (config.family, record.digest[:8].hex(), len(violations)),
        )
    return CampaignOutcome(
        config=config,
        summary=summary,
        record=record,
        violations=violations,
    )


def replay_campaign(
    record: CampaignRecord, *, registry=None, obs=NULL_BOUND
) -> tuple[bool, CampaignOutcome]:
    """Re-run a recorded campaign from its config alone and compare
    digests. ``(True, outcome)`` iff the fresh trajectory is
    bit-identical to the recorded one."""
    outcome = run_campaign(record.config, registry=registry, obs=obs)
    return outcome.digest == record.digest, outcome
