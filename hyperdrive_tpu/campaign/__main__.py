"""CLI: seeded attack campaigns as first-class workloads.

    python -m hyperdrive_tpu.campaign run [--family F] [--seed N] ...
    python -m hyperdrive_tpu.campaign replay DUMP.bin

``run`` executes the selected families (default: all three) at the
configured scale, judges each through the chaos monitor's campaign
checks, and — on any violation — dumps a replayable CampaignRecord
plus the obs journal next to it, with a one-line reproduce command.
``replay`` re-runs a dump's config from scratch and asserts the fresh
trajectory digest matches the recorded one bit-for-bit.

Exit status: 0 clean, 1 violations (run) or digest mismatch (replay).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from hyperdrive_tpu.campaign import FAMILIES, CampaignConfig
from hyperdrive_tpu.campaign.record import CampaignRecord
from hyperdrive_tpu.campaign.runner import replay_campaign, run_campaign


def _build_config(args, family: str) -> CampaignConfig:
    return CampaignConfig(
        family=family,
        seed=args.seed,
        validators=args.validators,
        committee_size=args.committee,
        epochs=args.epochs,
        epoch_length=args.epoch_length,
        attackers=args.attackers,
        waves=args.waves,
        wave_votes=args.wave_votes,
        attack_rate=args.attack_rate,
        sybils=args.sybils,
        budget_milli=args.budget_milli,
        grind_width=args.grind_width,
        reputation=not args.no_reputation,
    )


def _dump(outcome, out_dir: str, label: str) -> str:
    os.makedirs(out_dir, exist_ok=True)
    base = os.path.join(
        out_dir,
        "campaign-%s-%s" % (label, outcome.digest[:8].hex()),
    )
    outcome.record.dump(base + ".bin")
    with open(base + ".json", "w") as fh:
        json.dump(outcome.summary, fh, indent=1, sort_keys=True)
    return base + ".bin"


def _cmd_run(args) -> int:
    families = FAMILIES if args.family == "all" else (args.family,)
    from hyperdrive_tpu.obs.metrics import Registry
    from hyperdrive_tpu.obs.recorder import Recorder

    rc = 0
    results = []
    for family in families:
        config = _build_config(args, family)
        registry = Registry()
        recorder = Recorder()
        outcome = run_campaign(
            config, registry=registry, obs=recorder.scoped(-1)
        )
        results.append(outcome)
        status = "ok" if outcome.ok else "VIOLATION"
        print(
            "campaign %-11s seed=%d validators=%d digest=%s %s"
            % (
                family,
                config.seed,
                config.validators,
                outcome.digest[:8].hex(),
                status,
            )
        )
        if args.json:
            print(json.dumps(outcome.summary, sort_keys=True))
        if not outcome.ok:
            rc = 1
            for kind, detail in outcome.violations:
                print("  [%s] %s" % (kind, detail))
            path = _dump(outcome, args.out, family)
            recorder.save(
                os.path.splitext(path)[0] + ".journal.json",
                meta={"family": family, "seed": config.seed},
            )
            print(
                "  dumped %s\n  reproduce: python -m "
                "hyperdrive_tpu.campaign replay %s" % (path, path)
            )
        elif args.dump_ok:
            path = _dump(outcome, args.dump_ok, family)
            print("  dumped %s" % path)
    return rc


def _cmd_replay(args) -> int:
    record = CampaignRecord.load_file(args.dump)
    ok, outcome = replay_campaign(record)
    status = "digest-identical" if ok else "DIGEST MISMATCH"
    print(
        "replay %-11s seed=%d recorded=%s fresh=%s %s"
        % (
            record.config.family,
            record.config.seed,
            record.digest[:8].hex(),
            outcome.digest[:8].hex(),
            status,
        )
    )
    if args.json:
        print(json.dumps(outcome.summary, sort_keys=True))
    if ok and not outcome.ok:
        # Identical trajectory that still violates: the record was
        # dumped FROM a violating run, and replay reproduced it.
        for kind, detail in outcome.violations:
            print("  [%s] %s" % (kind, detail))
    return 0 if ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m hyperdrive_tpu.campaign",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    sub = ap.add_subparsers(dest="cmd", required=True)

    run = sub.add_parser("run", help="run seeded attack campaigns")
    run.add_argument(
        "--family",
        choices=FAMILIES + ("all",),
        default="all",
        help="campaign family (default: all three)",
    )
    run.add_argument("--seed", type=int, default=0)
    run.add_argument("--validators", type=int, default=1024)
    run.add_argument("--committee", type=int, default=64)
    run.add_argument("--epochs", type=int, default=8)
    run.add_argument("--epoch-length", type=int, default=4)
    run.add_argument("--attackers", type=int, default=16)
    run.add_argument("--waves", type=int, default=6)
    run.add_argument("--wave-votes", type=int, default=2)
    run.add_argument("--attack-rate", type=int, default=8)
    run.add_argument("--sybils", type=int, default=16)
    run.add_argument("--budget-milli", type=int, default=200)
    run.add_argument("--grind-width", type=int, default=8)
    run.add_argument(
        "--no-reputation",
        action="store_true",
        help="disable the admission reputation loop (bench control)",
    )
    run.add_argument(
        "--out",
        default="campaign-failures",
        help="violation dump directory",
    )
    run.add_argument(
        "--dump-ok",
        default=None,
        metavar="DIR",
        help="also dump records for CLEAN runs (CI replay cross-check)",
    )
    run.add_argument("--json", action="store_true")
    run.set_defaults(fn=_cmd_run)

    rp = sub.add_parser(
        "replay", help="re-run a dump, assert digest identity"
    )
    rp.add_argument("dump", help="CampaignRecord .bin path")
    rp.add_argument("--json", action="store_true")
    rp.set_defaults(fn=_cmd_replay)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
