"""CampaignRecord: the replayable dump of one attack campaign.

Same contract as the harness ScenarioRecord: the record carries the
full :class:`~hyperdrive_tpu.campaign.CampaignConfig` (as its u64
trailer), the outcome digest the live run produced, and the canonical
summary blob — everything :func:`~hyperdrive_tpu.campaign.runner
.replay_campaign` needs to re-derive the identical trajectory and
prove it, and everything ``obs report --campaign`` needs to decode a
dump without importing the campaign engines.

The file format rides the wire-codec machinery (``@wire_codec`` /
``@wire_entry``), so HD_SANITIZE=1 runs parse dumps under the same
byte-budget reader the network decoders use.
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass

from hyperdrive_tpu.analysis.annotations import wire_codec, wire_entry
from hyperdrive_tpu.analysis.sanitizer import maybe_wire_reader
from hyperdrive_tpu.codec import Reader, SerdeError, Writer

from hyperdrive_tpu.campaign import CampaignConfig

__all__ = ["CampaignRecord", "summary_digest", "MAGIC", "VERSION"]

#: "HYDC" — distinct from ScenarioRecord's magic so a mixed-up file
#: fails loudly at the first u32, not at trailer parse.
MAGIC = 0x48594443
VERSION = 1

_MAX_RECORD = 1 << 20


def summary_digest(summary: dict) -> bytes:
    """Digest of a campaign summary: sha256 of its canonical JSON.

    Canonical = sorted keys, no whitespace — the same dict always maps
    to the same bytes, so live-vs-replay digest equality is exactly
    trajectory equality (seat trajectories, shed counts, reputation
    state, per-epoch roots all live in the summary).
    """
    blob = json.dumps(
        summary, sort_keys=True, separators=(",", ":")
    ).encode()
    return hashlib.sha256(blob).digest()


@wire_codec(tag="campaign.record", max_bytes=_MAX_RECORD)
@dataclass(frozen=True)
class CampaignRecord:
    config: CampaignConfig
    digest: bytes
    summary: dict

    # -- wire ---------------------------------------------------------

    def marshal(self, w: Writer) -> None:
        w.u32(MAGIC)
        w.u16(VERSION)
        ints = self.config.as_ints()
        w.u16(len(ints))
        for v in ints:
            w.u64(int(v))
        w.bytes32(self.digest)
        w.raw(
            json.dumps(
                self.summary, sort_keys=True, separators=(",", ":")
            ).encode()
        )

    @classmethod
    def unmarshal(cls, r: Reader) -> "CampaignRecord":
        if r.u32() != MAGIC:
            raise SerdeError("not a campaign record (bad magic)")
        version = r.u16()
        if version != VERSION:
            raise SerdeError(f"unsupported campaign record v{version}")
        n = r.u16()
        ints = tuple(r.u64() for _ in range(n))
        config = CampaignConfig.from_ints(ints)
        digest = r.bytes32()
        summary = json.loads(r.raw().decode())
        if not isinstance(summary, dict):
            raise SerdeError("campaign summary must be a JSON object")
        rec = cls(config=config, digest=digest, summary=summary)
        if summary_digest(summary) != digest:
            raise SerdeError(
                "campaign record digest does not match its summary"
            )
        return rec

    # -- files --------------------------------------------------------

    def dump(self, path) -> None:
        w = Writer(rem=_MAX_RECORD)
        self.marshal(w)
        with open(path, "wb") as f:
            f.write(w.data())

    @classmethod
    @wire_entry
    def load(cls, payload: bytes, *, obs=None) -> "CampaignRecord":
        r = maybe_wire_reader(
            "campaign.record", payload, obs=obs, rem=_MAX_RECORD
        )
        return cls.unmarshal(r)

    @classmethod
    def load_file(cls, path, *, obs=None) -> "CampaignRecord":
        with open(path, "rb") as f:
            payload = f.read()
        return cls.load(payload, obs=obs)

    # -- convenience --------------------------------------------------

    @classmethod
    def capture(
        cls, config: CampaignConfig, summary: dict
    ) -> "CampaignRecord":
        return cls(
            config=config,
            digest=summary_digest(summary),
            summary=summary,
        )
