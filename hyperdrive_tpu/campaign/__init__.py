"""Attack campaigns: the adversarial economy as a first-class workload.

Tendermint-BFT's safety argument (PAPER.md, arXiv:1807.04938) assumes
less than one third of *stake* is Byzantine. The repo grew the defenses
that protect that assumption one PR at a time — stake-driven elections
(epochs.py), overload shedding (load/), overlay contribution scoring
(overlay/score.py), batch signature verification (verifier.py) — but
nothing ever composed them against the economic attacks they were built
to resist. This package does: seeded, replayable, multi-epoch attack
campaigns, run as probe-style workloads against the REAL subsystems
(the admission gate, the host ledger executor, the epoch schedule, the
aggregation topology) at 1024+ validators, with the chaos monitor's
:class:`~hyperdrive_tpu.chaos.monitor.InvariantViolation` as the only
failure currency.

Three campaign families (families.py):

- **storm** — signed-vote storms: forged-but-well-formed Ed25519
  signatures at open-loop rates that pass every cheap admission check
  and die only at batch verify, exercising the
  :class:`~hyperdrive_tpu.load.backpressure.SignerReputation` feedback
  loop that moves repeat forgers from the expensive post-verify shed to
  the cheap pre-verify one.
- **capture** — validator-set capture: an adversary with a fixed stake
  budget drives grinding / splitting / delegation-churn transaction
  workloads through the real ``exec/`` ledger across >= 8 epochs,
  trying to exceed its proportional committee share; the
  arXiv:2004.12990 proportionality bound is enforced over the WHOLE
  campaign trajectory, grinding allowance included.
- **coincidence** — everything at once: the capture attempt, plus a
  partition slicing the aggregation tree along a level boundary, plus
  the signature storm overloading admission.

Every campaign is a pure function of its :class:`CampaignConfig`; a
:class:`~hyperdrive_tpu.campaign.record.CampaignRecord` (riding the
ScenarioRecord wire machinery) captures the config and the outcome
digest, and replay re-derives the identical trajectory bit-for-bit —
the ``--campaign-every`` chaos-soak leg and the campaign-soak CI job
both assert exactly that. Everything here is host-side and stdlib+
numpy only: no jax import anywhere on the campaign path.
"""

from __future__ import annotations

from dataclasses import dataclass, replace

__all__ = [
    "FAMILIES",
    "CampaignConfig",
    "CampaignRecord",
    "CampaignOutcome",
    "run_campaign",
    "replay_campaign",
]

#: The closed family vocabulary, in CLI order. Index IS the wire
#: encoding (CampaignRecord stores the index), so order is append-only.
FAMILIES = ("storm", "capture", "coincidence")


@dataclass(frozen=True)
class CampaignConfig:
    """One campaign's full parameterization.

    Every field is an integer (or the family name, wire-encoded as its
    :data:`FAMILIES` index), so :meth:`as_ints` round-trips the whole
    config through the record's length-prefixed u64 trailer — the same
    forward-compatible shape ScenarioRecord's execution trailer uses.
    """

    family: str = "storm"
    seed: int = 0
    #: Validator-pool size (the ``n`` every subsystem is sized to).
    validators: int = 1024
    #: Committee size: the active signer set in a storm, the elected
    #: committee in a capture.
    committee_size: int = 64
    #: Capture/coincidence: epochs the campaign spans (>= 8 for the
    #: acceptance trajectory) and heights per epoch.
    epochs: int = 8
    epoch_length: int = 4
    #: Storm: forging signers (a suffix of the committee), open-loop
    #: waves, honest votes per signer per wave, and the forged-frame
    #: multiplier per attacker per wave.
    attackers: int = 16
    waves: int = 6
    wave_votes: int = 2
    attack_rate: int = 8
    #: Capture: adversary (sybil) accounts and their share of genesis
    #: stake in milli (200 = 20%), and the number of candidate
    #: boundary-block plans the grinder evaluates per epoch.
    sybils: int = 16
    budget_milli: int = 200
    grind_width: int = 8
    #: Storm: reputation loop on. The bench's no-reputation control
    #: flips this to measure the loop's post-verify-cost cut.
    reputation: bool = True

    def validate(self) -> None:
        if self.family not in FAMILIES:
            raise ValueError(f"unknown campaign family {self.family!r}")
        if self.validators < 4:
            raise ValueError("campaign needs >= 4 validators")
        if not 3 <= self.committee_size <= self.validators:
            raise ValueError(
                f"committee_size {self.committee_size} out of range "
                f"[3, {self.validators}]"
            )
        if not 0 < self.attackers < self.committee_size:
            raise ValueError(
                "attackers must leave at least one honest signer"
            )
        if not 0 < self.sybils <= self.validators // 2:
            raise ValueError("sybils must be in (0, validators/2]")
        if not 0 < self.budget_milli < 334:
            raise ValueError(
                "budget_milli must stay under the 1/3 Byzantine-stake "
                "assumption (got %d)" % self.budget_milli
            )
        if self.epochs < 1 or self.epoch_length < 1:
            raise ValueError("epochs and epoch_length must be >= 1")
        if self.waves < 1 or self.wave_votes < 1 or self.attack_rate < 1:
            raise ValueError("storm knobs must be >= 1")
        if self.grind_width < 1:
            raise ValueError("grind_width must be >= 1")

    def as_ints(self) -> tuple:
        """The config as a fixed-order u64 tuple (record trailer)."""
        return (
            FAMILIES.index(self.family),
            self.seed,
            self.validators,
            self.committee_size,
            self.epochs,
            self.epoch_length,
            self.attackers,
            self.waves,
            self.wave_votes,
            self.attack_rate,
            self.sybils,
            self.budget_milli,
            self.grind_width,
            1 if self.reputation else 0,
        )

    @classmethod
    def from_ints(cls, ints) -> "CampaignConfig":
        """Rebuild from :meth:`as_ints` output. Extra trailing ints are
        ignored (same forward-compatibility rule as the execution
        trailer: a future field extends the tuple, old readers skip)."""
        vals = list(ints)
        if len(vals) < 14:
            raise ValueError(
                f"campaign config trailer too short: {len(vals)} ints"
            )
        if not 0 <= int(vals[0]) < len(FAMILIES):
            raise ValueError(
                f"unknown campaign family index {int(vals[0])}"
            )
        return cls(
            family=FAMILIES[int(vals[0])],
            seed=int(vals[1]),
            validators=int(vals[2]),
            committee_size=int(vals[3]),
            epochs=int(vals[4]),
            epoch_length=int(vals[5]),
            attackers=int(vals[6]),
            waves=int(vals[7]),
            wave_votes=int(vals[8]),
            attack_rate=int(vals[9]),
            sybils=int(vals[10]),
            budget_milli=int(vals[11]),
            grind_width=int(vals[12]),
            reputation=bool(vals[13]),
        )

    def with_family(self, family: str) -> "CampaignConfig":
        return replace(self, family=family)


def __getattr__(name):
    # Lazy re-exports: importing the package stays cheap (and jax-free)
    # until a campaign actually runs — the same idiom exec/__init__.py
    # uses for its executor classes.
    if name == "CampaignRecord":
        from hyperdrive_tpu.campaign.record import CampaignRecord

        return CampaignRecord
    if name in ("CampaignOutcome", "run_campaign", "replay_campaign"):
        from hyperdrive_tpu.campaign import runner

        return getattr(runner, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
