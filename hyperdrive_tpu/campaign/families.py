"""The three campaign families: storm, capture, coincidence.

Each engine is a deterministic function ``run(config, registry, obs)
-> summary dict`` over the REAL subsystems — the admission gate and
reputation table (load/backpressure.py), the host ledger executor
(exec/ledger.py), the epoch schedule (epochs.py), the aggregation
topology and contribution scores (overlay/) — never simplified stand-
ins. The summary is the campaign's full observable trajectory; its
canonical-JSON sha256 (record.summary_digest) is the replay-identity
digest, so every number that matters lands in the summary and every
number in the summary is a pure function of the config.

Host-side only: stdlib + numpy via the exec layer; no jax import on
any path here.
"""

from __future__ import annotations

import hashlib

import numpy as np

from hyperdrive_tpu.crypto.keys import KeyRing
from hyperdrive_tpu.epochs import _EPOCH_TAG, EpochSchedule, elect_committee
from hyperdrive_tpu.exec import ExecutionConfig
from hyperdrive_tpu.exec.ledger import (
    KIND_STAKE,
    KIND_TRANSFER,
    KIND_UNSTAKE,
    BlockSource,
    HostLedgerExecutor,
    TxBlock,
)
from hyperdrive_tpu.load.backpressure import (
    AdmissionGate,
    BackpressureController,
    SignerReputation,
    _peer_label,
)
from hyperdrive_tpu.messages import Prevote
from hyperdrive_tpu.obs.recorder import NULL_BOUND
from hyperdrive_tpu.overlay.score import ContributionScores
from hyperdrive_tpu.overlay.topology import Topology
from hyperdrive_tpu.verifier import HostVerifier

from hyperdrive_tpu.campaign import CampaignConfig

__all__ = [
    "run_storm",
    "run_capture",
    "run_coincidence",
    "ENGINES",
]


def _stream(tag: bytes, *parts: int):
    """Keyed deterministic byte stream for campaign draws (the
    epochs.py ``_draw`` idiom, widened to a shake stream)."""
    key = tag + b"".join(int(p).to_bytes(8, "little") for p in parts)
    return hashlib.shake_256(key)


# --------------------------------------------------------------- storm


def _forge(sig: bytes) -> bytes:
    """Well-formed but invalid: correct length, correct structure,
    fails batch verify — the exec layer's bad_sig_every corruption."""
    return bytes([sig[0] ^ 0xFF]) + sig[1:]


def run_storm(
    cfg: CampaignConfig, registry=None, obs=NULL_BOUND
) -> dict:
    """Signed-vote storm against one admission gate.

    Per wave: every honest committee signer emits ``wave_votes``
    properly-signed prevotes; every attacker emits ``wave_votes *
    attack_rate`` forged ones. All frames pass the gate's cheap checks
    (fresh keys, well-formed), admitted rows batch through the real
    Ed25519 verifier, and per-signer verdicts feed back through
    ``note_verify``. With the reputation loop on, attackers demote
    after their first wave and shed at the gate from then on — the
    post-verify cost curve in the summary is the loop's receipt.
    """
    k, a = cfg.committee_size, cfg.attackers
    ring = KeyRing.deterministic(
        k, namespace=b"campaign-storm-%d" % cfg.seed
    )
    honest = [ring[i].public for i in range(a, k)]
    attackers = [ring[j].public for j in range(a)]
    rep = (
        SignerReputation(registry=registry, obs=obs)
        if cfg.reputation
        else None
    )
    honest_rows = (k - a) * cfg.wave_votes
    storm_rows = honest_rows + a * cfg.wave_votes * cfg.attack_rate
    # Depth thresholds scaled to the workload: an honest-only wave sits
    # below SHED_LOW_PRIORITY, a full storm wave sits above it, and
    # CRITICAL_ONLY stays out of reach — the storm must degrade
    # admission, not black out honest prevotes.
    ctrl = BackpressureController(
        depth_low_priority=honest_rows * 2,
        depth_critical=storm_rows * 4,
        hysteresis=2,
        registry=registry,
        obs=obs,
    )
    gate = AdmissionGate(
        ctrl, reputation=rep, registry=registry, obs=obs
    )
    verifier = HostVerifier()
    waves = []
    if obs is not NULL_BOUND:
        obs.emit("campaign.family", -1, -1, "storm")
    for w in range(cfg.waves):
        height = w + 1
        value = _stream(b"campaign-storm-value", cfg.seed, w).digest(32)
        frames = []
        for i in range(a, k):
            for r in range(cfg.wave_votes):
                msg = Prevote(height, r, value, ring[i].public)
                frames.append(
                    (r, i, msg.with_signature(
                        ring[i].sign_digest(msg.digest())
                    ))
                )
        for j in range(a):
            for r in range(cfg.wave_votes * cfg.attack_rate):
                msg = Prevote(height, r, value, ring[j].public)
                frames.append(
                    (r, j, msg.with_signature(
                        _forge(ring[j].sign_digest(msg.digest()))
                    ))
                )
        # Interleave by round so attack traffic rides WITH honest
        # traffic through the gate, not after it.
        frames.sort(key=lambda f: (f[0], f[1]))
        offered0, admitted0 = gate.offered, gate.admitted
        shed_rep0 = gate.shed.get("reputation", 0)
        batch = []
        for _, signer, msg in frames:
            if gate.admit(msg, peer=msg.sender):
                batch.append(
                    (msg.sender, msg.digest(), msg.signature)
                )
        # The admitted window IS the device queue: depth escalates the
        # controller exactly as a DeviceWorkQueue submit burst would.
        ctrl.note_depth(len(batch))
        mask = verifier.verify_signatures(batch)
        per_signer: dict = {}
        for (sender, _, _), ok in zip(batch, mask):
            good, bad = per_signer.get(sender, (0, 0))
            per_signer[sender] = (
                (good + 1, bad) if ok else (good, bad + 1)
            )
        failed = 0
        attacker_rows_verified = 0
        aset = set(attackers)
        for sender, (good, bad) in per_signer.items():
            if sender in aset:
                attacker_rows_verified += good + bad
            if good:
                gate.note_verify(sender, True, good)
            if bad:
                failed += bad
                gate.note_verify(sender, False, bad)
        ctrl.note_drain(len(batch), 0.0)
        if rep is not None:
            # One committed height per wave: the per-commit amnesty.
            rep.rehabilitate(1)
        waves.append({
            "wave": w,
            "offered": gate.offered - offered0,
            "admitted": gate.admitted - admitted0,
            "verified_rows": len(batch),
            "failed_rows": failed,
            "attacker_rows_verified": attacker_rows_verified,
            "shed_reputation": gate.shed.get("reputation", 0)
            - shed_rep0,
            "level": ctrl.level,
        })
        if obs is not NULL_BOUND:
            obs.emit(
                "campaign.wave", height, -1,
                "rows=%d failed=%d level=%d"
                % (len(batch), failed, ctrl.level),
            )
    snap = gate.snapshot()
    return {
        "family": "storm",
        "seed": cfg.seed,
        "reputation": bool(cfg.reputation),
        "honest": sorted(_peer_label(p) for p in honest),
        "attackers": sorted(_peer_label(p) for p in attackers),
        "honest_rows": honest_rows,
        "waves": waves,
        "gate": {
            "offered": snap["offered"],
            "admitted": snap["admitted"],
            "shed": dict(sorted(snap["shed"].items())),
            "level": snap["level"],
            "verify_failed": {
                _peer_label(p): rows
                for p, rows in sorted(
                    snap["verify_failed_by_peer"].items(),
                    key=lambda kv: _peer_label(kv[0]),
                )
            },
            "demoted": (
                sorted(_peer_label(p) for p in rep.demoted)
                if rep is not None
                else []
            ),
            "demotions": rep.demotions if rep is not None else 0,
        },
    }


# -------------------------------------------------------------- capture


class _CampaignSource(BlockSource):
    """BlockSource with adversary plan overlays: boundary heights with
    a registered plan serve the planned block (base columns + appended
    adversary rows); every other height passes through untouched, so
    the honest background workload is bit-identical to a plain run."""

    def __init__(self, config: ExecutionConfig):
        super().__init__(config)
        self.plans: dict[int, TxBlock] = {}

    def block(self, height: int) -> TxBlock:
        planned = self.plans.get(height)
        if planned is not None:
            return planned
        return super().block(height)


def _planned_block(
    base: TxBlock, rows, epoch: int, cand: int
) -> TxBlock:
    """Base block + adversary rows appended, as a fresh TxBlock. The
    digest binds the base content and the plan identity (not used for
    state — sign_txs is off on campaign ledgers — but keeps blocks
    distinguishable in obs detail and cache keys)."""
    kind, sender, recipient, amount = (c.copy() for c in base._np)
    if rows:
        ak = np.array([r[0] for r in rows], dtype=np.int32)
        asnd = np.array([r[1] for r in rows], dtype=np.int32)
        arcp = np.array([r[2] for r in rows], dtype=np.int32)
        aamt = np.array([r[3] for r in rows], dtype=np.int32)
        kind = np.concatenate([kind, ak])
        sender = np.concatenate([sender, asnd])
        recipient = np.concatenate([recipient, arcp])
        amount = np.concatenate([amount, aamt])
    digest = hashlib.sha256(
        b"campaign-plan" + base.digest
        + epoch.to_bytes(8, "little") + cand.to_bytes(8, "little")
    ).digest()
    return TxBlock(base.height, kind, sender, recipient, amount, digest)


def _grind_plan(cfg: CampaignConfig, epoch: int, cand: int) -> list:
    """Candidate ``cand``'s adversary rows for the epoch boundary.

    Candidate 0 is the null plan (the passive baseline the grinder
    must beat). Others are stake-conserving rotations and delegation
    churn among the sybils: each UNSTAKE is paired with an equal STAKE
    on another sybil, so total adversary stake never changes — the
    only degree of freedom being ground is the election seed, exactly
    the attack surface the proportionality bound must absorb."""
    if cand == 0:
        return []
    s = cfg.sybils
    draws = np.frombuffer(
        _stream(
            b"campaign-grind", cfg.seed, epoch, cand
        ).digest(16 * s),
        dtype="<u4",
    ).reshape(s, 4)
    rows = []
    for i in range(s):
        src = int(draws[i, 0]) % s
        dst = int(draws[i, 1]) % s
        if src == dst:
            dst = (dst + 1) % s
        amt = 1 + int(draws[i, 2]) % 16
        if draws[i, 3] & 1:
            # Rotation: move stake weight between sybils.
            rows.append((KIND_UNSTAKE, src, src, amt))
            rows.append((KIND_STAKE, dst, dst, amt))
        else:
            # Delegation churn: shuffle balances (the STAKE headroom)
            # without touching current weight.
            rows.append((KIND_TRANSFER, src, dst, amt))
    return rows


def _genesis_stakes(cfg: CampaignConfig) -> list:
    """Per-account genesis stakes: every honest validator holds
    ``_HONEST_STAKE``; the adversary's total is sized so its share of
    the pool is exactly ``budget_milli`` (integer arithmetic, the
    remainder parked on sybil 0)."""
    n, s = cfg.validators, cfg.sybils
    honest_total = _HONEST_STAKE * (n - s)
    adv_total = honest_total * cfg.budget_milli // (
        1000 - cfg.budget_milli
    )
    per_sybil = adv_total // s
    stakes = [per_sybil] * s + [_HONEST_STAKE] * (n - s)
    stakes[0] += adv_total - per_sybil * s
    return stakes


_HONEST_STAKE = 1000


def run_capture(
    cfg: CampaignConfig, registry=None, obs=NULL_BOUND
) -> dict:
    """Validator-set capture across ``cfg.epochs`` consecutive epochs.

    Each epoch boundary, the adversary probes ``grind_width`` candidate
    boundary blocks through the real executor (snapshot / apply /
    restore — the speculation machinery's own primitives), predicts
    the resulting election with the exact transition_at anchor
    derivation, commits the best candidate through the live
    ``advance_to`` + ``transition_at`` path, and the trajectory records
    realized seats against realized stake share. The monitor's
    proportionality check is the verdict."""
    cfg.validate()
    n, k, s = cfg.validators, cfg.committee_size, cfg.sybils
    exec_cfg = ExecutionConfig(
        accounts=n,
        txs_per_block=32,
        stake_every=3,
        stake_accounts=n,
        seed=cfg.seed,
        amount_cap=32,
        stake_floor=1,
    )
    source = _CampaignSource(exec_cfg)
    ex = HostLedgerExecutor(
        exec_cfg, genesis_stakes=_genesis_stakes(cfg), source=source
    )
    sched = EpochSchedule(
        ex.election_stakes(n), k, cfg.epoch_length, cfg.seed
    )
    seed8 = sched.seed.to_bytes(8, "little")
    if obs is not NULL_BOUND:
        obs.emit("campaign.family", -1, -1, "capture")
    trajectory = []
    for epoch in range(1, cfg.epochs + 1):
        boundary = epoch * cfg.epoch_length
        ex.advance_to(boundary - 1)
        base = BlockSource.block(source, boundary)
        prev_anchor = sched.anchor(epoch - 1)
        epoch8 = epoch.to_bytes(8, "little")
        snap = ex._snapshot()
        best_cand, best_seats, passive_seats = 0, -1, 0
        for cand in range(cfg.grind_width):
            blk = _planned_block(
                base, _grind_plan(cfg, epoch, cand), epoch, cand
            )
            ex._apply_chain(boundary, blk, None)
            # The exact transition_at derivation, run ahead of time:
            # candidate root -> anchor -> election. Any drift here and
            # the grinder would be probing a different lottery than
            # the one the schedule runs.
            anchor = hashlib.sha256(
                _EPOCH_TAG + b"anchor" + seed8 + epoch8
                + prev_anchor + hashlib.sha256(ex.root).digest()
            ).digest()
            members = elect_committee(
                ex.election_stakes(n), k, anchor + b"elect"
            )
            seats = sum(1 for i in members if i < s)
            ex._restore(snap)
            ex.roots.pop(boundary, None)
            if cand == 0:
                passive_seats = seats
            if seats > best_seats:
                best_cand, best_seats = cand, seats
        source.plans[boundary] = _planned_block(
            base, _grind_plan(cfg, epoch, best_cand), epoch, best_cand
        )
        root = ex.advance_to(boundary)
        stakes_now = ex.election_stakes(n)
        tr = sched.transition_at(boundary, root, stakes=stakes_now)
        seats = sum(1 for v in tr.committee if v.index < s)
        adv_stake = sum(stakes_now[:s])
        trajectory.append({
            "epoch": epoch,
            "seats": seats,
            "passive_seats": passive_seats,
            "committee": k,
            "adv_stake": adv_stake,
            "total_stake": sum(stakes_now),
            "candidate": best_cand,
            "root": root[:8].hex(),
        })
        if obs is not NULL_BOUND:
            obs.emit(
                "campaign.grind", boundary, -1,
                "cand=%d seats=%d passive=%d"
                % (best_cand, best_seats, passive_seats),
            )
            obs.emit(
                "campaign.epoch", boundary, -1,
                "e=%d seats=%d/%d" % (epoch, seats, k),
            )
        if registry is not None:
            registry.count("campaign.epochs")
            registry.count("campaign.adv_seats", seats)
    return {
        "family": "capture",
        "seed": cfg.seed,
        "validators": n,
        "sybils": s,
        "budget_milli": cfg.budget_milli,
        "grind_width": cfg.grind_width,
        "trajectory": trajectory,
        "seats_total": sum(t["seats"] for t in trajectory),
        "passive_total": sum(t["passive_seats"] for t in trajectory),
        "final_root": trajectory[-1]["root"],
    }


# ---------------------------------------------------------- coincidence


def run_coincidence(
    cfg: CampaignConfig, registry=None, obs=NULL_BOUND
) -> dict:
    """Everything at once: the capture loop, a per-epoch signature
    storm through a shared admission gate, and a partition slicing the
    epoch's aggregation tree along a level boundary, with overlay
    contribution scores charging the silenced slots exactly as live
    observers would. Safety currency stays the same — proportionality
    over the trajectory, never-starve under the slice, and no honest
    peer left permanently demoted after the heal runway."""
    cfg.validate()
    n, k, s = cfg.validators, cfg.committee_size, cfg.sybils
    exec_cfg = ExecutionConfig(
        accounts=n,
        txs_per_block=32,
        stake_every=3,
        stake_accounts=n,
        seed=cfg.seed,
        amount_cap=32,
        stake_floor=1,
    )
    source = _CampaignSource(exec_cfg)
    ex = HostLedgerExecutor(
        exec_cfg, genesis_stakes=_genesis_stakes(cfg), source=source
    )
    sched = EpochSchedule(
        ex.election_stakes(n), k, cfg.epoch_length, cfg.seed
    )
    seed8 = sched.seed.to_bytes(8, "little")
    ring = KeyRing.deterministic(
        n, namespace=b"campaign-coin-%d" % cfg.seed
    )
    rep = (
        SignerReputation(registry=registry, obs=obs)
        if cfg.reputation
        else None
    )
    honest_rows = (k - s) * cfg.wave_votes
    ctrl = BackpressureController(
        depth_low_priority=honest_rows * 2,
        depth_critical=(honest_rows + k * cfg.wave_votes
                        * cfg.attack_rate) * 4,
        hysteresis=2,
        registry=registry,
        obs=obs,
    )
    gate = AdmissionGate(
        ctrl, reputation=rep, registry=registry, obs=obs
    )
    verifier = HostVerifier()
    scores = ContributionScores(n)
    if obs is not NULL_BOUND:
        obs.emit("campaign.family", -1, -1, "coincidence")
    trajectory = []
    overlay_epochs = []
    storm_epochs = []
    for epoch in range(1, cfg.epochs + 1):
        boundary = epoch * cfg.epoch_length
        ex.advance_to(boundary - 1)
        base = BlockSource.block(source, boundary)
        prev_anchor = sched.anchor(epoch - 1)
        epoch8 = epoch.to_bytes(8, "little")
        snap = ex._snapshot()
        best_cand, best_seats, passive_seats = 0, -1, 0
        for cand in range(cfg.grind_width):
            blk = _planned_block(
                base, _grind_plan(cfg, epoch, cand), epoch, cand
            )
            ex._apply_chain(boundary, blk, None)
            anchor = hashlib.sha256(
                _EPOCH_TAG + b"anchor" + seed8 + epoch8
                + prev_anchor + hashlib.sha256(ex.root).digest()
            ).digest()
            members = elect_committee(
                ex.election_stakes(n), k, anchor + b"elect"
            )
            seats = sum(1 for i in members if i < s)
            ex._restore(snap)
            ex.roots.pop(boundary, None)
            if cand == 0:
                passive_seats = seats
            if seats > best_seats:
                best_cand, best_seats = cand, seats
        source.plans[boundary] = _planned_block(
            base, _grind_plan(cfg, epoch, best_cand), epoch, best_cand
        )
        root = ex.advance_to(boundary)
        stakes_now = ex.election_stakes(n)
        tr = sched.transition_at(boundary, root, stakes=stakes_now)
        committee = tr.committee
        seats = sum(1 for v in committee if v.index < s)
        trajectory.append({
            "epoch": epoch,
            "seats": seats,
            "passive_seats": passive_seats,
            "committee": k,
            "adv_stake": sum(stakes_now[:s]),
            "total_stake": sum(stakes_now),
            "candidate": best_cand,
            "root": root[:8].hex(),
        })
        # ---- signature storm, this epoch's committee as signers.
        value = _stream(
            b"campaign-coin-value", cfg.seed, epoch
        ).digest(32)
        frames = []
        for slot, v in enumerate(committee):
            kp = ring[v.index]
            if v.index < s:
                for r in range(cfg.wave_votes * cfg.attack_rate):
                    msg = Prevote(boundary, r, value, kp.public)
                    frames.append((r, slot, msg.with_signature(
                        _forge(kp.sign_digest(msg.digest()))
                    )))
            else:
                for r in range(cfg.wave_votes):
                    msg = Prevote(boundary, r, value, kp.public)
                    frames.append((r, slot, msg.with_signature(
                        kp.sign_digest(msg.digest())
                    )))
        frames.sort(key=lambda f: (f[0], f[1]))
        batch = []
        for _, _, msg in frames:
            if gate.admit(msg, peer=msg.sender):
                batch.append(
                    (msg.sender, msg.digest(), msg.signature)
                )
        ctrl.note_depth(len(batch))
        mask = verifier.verify_signatures(batch)
        per_signer: dict = {}
        for (sender, _, _), ok in zip(batch, mask):
            good, bad = per_signer.get(sender, (0, 0))
            per_signer[sender] = (
                (good + 1, bad) if ok else (good, bad + 1)
            )
        failed = 0
        for sender, (good, bad) in per_signer.items():
            if good:
                gate.note_verify(sender, True, good)
            if bad:
                failed += bad
                gate.note_verify(sender, False, bad)
        ctrl.note_drain(len(batch), 0.0)
        storm_epochs.append({
            "epoch": epoch,
            "verified_rows": len(batch),
            "failed_rows": failed,
            "shed_reputation": gate.shed.get("reputation", 0),
            "level": ctrl.level,
        })
        # ---- partition: slice the epoch's aggregation tree along a
        # level boundary; the silenced group's slots are charged
        # "withheld" once per in-epoch height, exactly as their
        # observers would under a real slice.
        topo = Topology(
            cfg.seed, sched.anchor(epoch),
            [v.signatory for v in committee],
        )
        level = max(1, topo.levels - 2)
        groups = topo.level_groups(level)
        pick = int.from_bytes(
            _stream(b"campaign-slice", cfg.seed, epoch).digest(8),
            "little",
        ) % len(groups)
        sliced = set(groups[pick])
        windows_exhausted = 0
        fallback_engaged = 0
        for slot in range(len(committee)):
            if slot in sliced:
                continue
            contacts = topo.contacts(slot, 1, 2)
            if contacts and all(c in sliced for c in contacts):
                # Every level-1 contact is dark: the retry windows
                # exhaust and the ranked direct-gossip fallback MUST
                # engage (the never-starve doctrine) — modeled here,
                # asserted by the monitor.
                windows_exhausted += 1
                fallback_engaged += 1
        for _ in range(cfg.epoch_length):
            for slot in range(len(committee)):
                idx = committee[slot].index
                if slot in sliced:
                    scores.charge(idx, "withheld")
                else:
                    scores.credit_coverage(idx, 1)
            scores.rehabilitate(1)
            if rep is not None:
                rep.rehabilitate(1)
        overlay_epochs.append({
            "epoch": epoch,
            "sliced": len(sliced),
            "windows_exhausted": windows_exhausted,
            "fallback_engaged": fallback_engaged,
            "demoted": len(scores.demoted),
        })
        if obs is not NULL_BOUND:
            obs.emit(
                "campaign.partition", boundary, -1,
                "level=%d sliced=%d" % (level, len(sliced)),
            )
            obs.emit(
                "campaign.epoch", boundary, -1,
                "e=%d seats=%d/%d" % (epoch, seats, k),
            )
    # Heal runway: the slice lifts, amnesty plus fresh contribution
    # credit repay any honest debt (O(depth/heal_rate) heights — the
    # score floor is -64, each runway height repays 3).
    runway = (-ContributionScores(1).floor) // 3 + 3
    for _ in range(runway):
        for idx in range(n):
            scores.credit_coverage(idx, 1)
        scores.rehabilitate(1)
    if obs is not NULL_BOUND:
        obs.emit(
            "campaign.heal", -1, -1, "runway=%d" % runway
        )
    honest_demoted = sorted(
        idx for idx in scores.demoted if idx >= s
    )
    return {
        "family": "coincidence",
        "seed": cfg.seed,
        "validators": n,
        "sybils": s,
        "budget_milli": cfg.budget_milli,
        "grind_width": cfg.grind_width,
        "reputation": bool(cfg.reputation),
        "trajectory": trajectory,
        "storm": storm_epochs,
        "overlay": overlay_epochs,
        "honest_demoted_final": honest_demoted,
        "seats_total": sum(t["seats"] for t in trajectory),
        "final_root": trajectory[-1]["root"],
    }


ENGINES = {
    "storm": run_storm,
    "capture": run_capture,
    "coincidence": run_coincidence,
}
