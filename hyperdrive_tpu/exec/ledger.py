"""Deterministic tx blocks, the host reference executor, root chaining.

Everything here is jax-free and bit-deterministic. A height's block is
a pure function of ``(config.seed, height)`` — never of committed
values or delivery order — so a laggard resyncing straight to height H
executes the identical blocks every up-to-date replica executed, and a
replayed dump re-derives the whole ledger trajectory from the config
ints alone (ScenarioRecord v7 stores no state).

State roots chain like the commit chain itself:

  root_0   = sha256("exec-genesis" || pack(balances) || pack(stakes))
  root_h   = fold(root_{h-1}, h,
                  fold_merkle(digest(state_h), merkle(state_h)))

where ``digest`` is the fixed-shape uint32 reduction over the packed
state leaves, ``merkle`` the account hash tree's root (ops/merkle.py,
updated incrementally from the block's own scatter targets — PR 17),
and ``fold`` the per-height chain mix — all defined ONCE in ops/ with
bit-identical numpy (host) and jnp (device) twins, so the device
executor keeps the running root AND the hash tree ON DEVICE between
heights (no per-block host hash hop) and still chains byte-equal to
the host reference. Because ``root_h`` commits the Merkle root,
``prove(account)`` yields an O(log n) inclusion proof any stateless
client can check against the certificate chain
(``verify_inclusion``). ``pack`` stays 8-byte little-endian signed per
account (the word split mirrors it lo/hi), the root stays 32 bytes,
and the genesis root stays sha256. The reduction is linear-algebraic,
not cryptographic: integrity of the running chain is re-derived
host-side at checkpoints (``host_verify``) and in the parity CLIs —
see ROBUSTNESS.md "State-root doctrine".

Speculative pipelining (PR 16): ``speculate(h, guess)`` applies height
``h`` under a guessed admission mask while the real verification is
still in flight; ``resolve(h, true_mask)`` either confirms the height
or ROLLS BACK — restoring state to the pre-speculation snapshot
bit-identically, recording every discarded root (the chaos monitor's
no-leak invariant reads ``discarded_roots``), and re-applying under
the true mask. A rolled-back root can therefore never appear in a
committed value: commits only read roots after resolution.

Apply semantics are ORDER-INDEPENDENT and block-atomic per sender: a
sender whose summed asks (balance asks for TRANSFER/STAKE, stake asks
for UNSTAKE) exceed its pre-block funds has every transaction in that
block rejected. That is what makes the vectorized device form
(ops/ledger.py: segment-sum → solvency gather → scatter-add) exactly
equal to any serial schedule of the same block.
"""

from __future__ import annotations

import hashlib

import numpy as np

from hyperdrive_tpu.devsched.queue import VerifyLauncher
from hyperdrive_tpu.exec import ExecutionConfig
from hyperdrive_tpu.obs.recorder import NULL_BOUND
from hyperdrive_tpu.ops.merkle import (
    MerkleProof,
    build_tree_np,
    fold_merkle_np,
    merkle_bytes,
    merkle_root_np,
    prove_np,
    tree_depth,
    update_tree_np,
    verify_inclusion,
)
from hyperdrive_tpu.ops.rootmix import (
    fold_root_np,
    root_bytes,
    root_words,
    state_digest_np,
)

__all__ = [
    "KIND_TRANSFER",
    "KIND_STAKE",
    "KIND_UNSTAKE",
    "TxBlock",
    "BlockSource",
    "HostLedgerExecutor",
    "ProofBasis",
    "ExecApplyLauncher",
]

#: Transaction kinds — must match ops/ledger.py (the device kernel keeps
#: its own copies so ops/ stays importable without this package;
#: tests/test_exec.py pins the equality).
KIND_TRANSFER = 0
KIND_STAKE = 1
KIND_UNSTAKE = 2

_INT32_MAX = 2**31 - 1

#: "no mask supplied" sentinel for ``_step`` (None is a real mask value:
#: the unsigned everything-admitted semantics).
_UNSET = object()


def pack_state(values) -> bytes:
    """Account vector -> bytes, 8-byte little-endian signed per entry.
    The ONE packing both executors must agree on for root equality."""
    return b"".join(int(v).to_bytes(8, "little", signed=True) for v in values)


class TxBlock:
    """One height's transactions as dense columns. The NUMPY arrays are
    the native layout (the device executor pads them straight into
    tensors); the Python-list views the host executor walks are
    materialized lazily on first access, so a device-executor run never
    pays the array->list conversion at all."""

    __slots__ = (
        "height", "digest", "_np", "_py", "_sig_items", "_cols",
    )

    def __init__(self, height, kind, sender, recipient, amount, digest):
        self.height = height
        #: Content digest: what the exec proposer's value commits to.
        self.digest = digest
        #: (kind, sender, recipient, amount) as int32 numpy columns —
        #: the device kernel's native dtype, so padding is a copy, not
        #: a cast (accounts and amount_cap are int32-bounded by config
        #: validation).
        self._np = tuple(
            np.asarray(c, dtype=np.int32)
            for c in (kind, sender, recipient, amount)
        )
        self._py = None
        self._sig_items = None
        #: Device-padded column cache (DeviceLedgerExecutor): the
        #: array->tensor conversion is block MATERIALIZATION, shared by
        #: every replica on the source like the columns themselves, and
        #: evicted with the block by the source's LRU.
        self._cols = None

    def _lists(self):
        py = self._py
        if py is None:
            py = self._py = tuple(c.tolist() for c in self._np)
        return py

    @property
    def kind(self):
        return self._lists()[0]

    @property
    def sender(self):
        return self._lists()[1]

    @property
    def recipient(self):
        return self._lists()[2]

    @property
    def amount(self):
        return self._lists()[3]

    def __len__(self) -> int:
        return len(self._np[0])


#: STAKE-vs-UNSTAKE split point on the stake lane: a uint32 draw below
#: this threshold (~0.6 * 2^32) stakes, above it unstakes — biased
#: toward STAKE so validator weights drift and elections have
#: something to read.
_STAKE_BIAS = int(0.6 * 2**32)


class BlockSource:
    """Deterministic per-height workload, shared by every replica.

    ``block(h)`` derives height h's transactions from a keyed
    ``shake_256`` stream expanded into dense numpy columns in one pass
    (the per-tx Python RNG loop this replaced was ~87% of pipelined
    e2e wall time at 16k-tx blocks); every ``stake_every``-th tx is a
    STAKE/UNSTAKE on a validator stake account (``stake_accounts``
    wide, biased toward STAKE so validator weights drift and epoch
    elections have something to read). ``value(h)`` is the 32-byte
    proposal value committing to the block. With ``sign_txs`` each tx
    carries a real Ed25519 signature from its sender's deterministic
    account key; ``bad_sig_every`` corrupts every K-th one.

    ``spec_epoch`` tags cache entries with the open speculation window
    (the sim bumps it when a window closes): entries touched in the
    CURRENT epoch are pinned against LRU eviction, so a rollback that
    replays a window height hits the cached block — padded device
    columns included — instead of re-materializing it. ``hits`` /
    ``misses`` / ``evictions`` count the cache's behavior for tests
    and the obs report.
    """

    #: Blocks cached per source; sim runs walk heights forward and
    #: bench blocks are large, so a short LRU covers re-reads (the n
    #: replicas' executors share one source) without pinning 64k-tx
    #: columns for every committed height. Entries of the open
    #: speculation epoch are pinned (rollback replays them), so the
    #: cache can transiently exceed this by the window depth.
    CACHE = 8

    def __init__(self, config: ExecutionConfig):
        self.config = config
        #: height -> [spec_epoch_last_touched, TxBlock]
        self._cache: dict[int, list] = {}
        self._values: dict[int, bytes] = {}
        self._ring = None
        self.spec_epoch = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def _content_digest(self, height: int) -> bytes:
        """The block's content digest WITHOUT materializing the block:
        the columns are a pure function of ``(key, config shape)``, so
        the digest binds the derivation key plus every config field
        that shapes content — identical commitment, none of the
        hash-over-columns cost (which proposal values for not-yet-
        executed heights would otherwise pay in full)."""
        cfg = self.config
        key = hashlib.sha256(
            b"exec-block-%d-%d" % (cfg.seed, height)
        ).digest()
        return hashlib.sha256(
            b"exec-txs" + key
            + b"%d-%d-%d-%d-%d" % (
                cfg.accounts, cfg.txs_per_block, cfg.stake_every,
                cfg.stake_accounts, cfg.amount_cap,
            )
        ).digest()

    def block(self, height: int) -> TxBlock:
        ent = self._cache.get(height)
        if ent is not None:
            ent[0] = self.spec_epoch
            self.hits += 1
            return ent[1]
        self.misses += 1
        cfg = self.config
        key = hashlib.sha256(
            b"exec-block-%d-%d" % (cfg.seed, height)
        ).digest()
        t = cfg.txs_per_block
        w = np.frombuffer(
            hashlib.shake_256(b"exec-cols" + key).digest(16 * t),
            dtype="<u4",
        ).reshape(t, 4)
        stake_lane = cfg.stake_every > 0 and cfg.stake_accounts > 0
        kind = np.zeros(t, dtype=np.int64)
        sender = w[:, 1].astype(np.int64) % cfg.accounts
        recipient = w[:, 2].astype(np.int64) % cfg.accounts
        if stake_lane:
            lane = np.zeros(t, dtype=bool)
            lane[:: cfg.stake_every] = True
            kind[lane] = np.where(
                w[lane, 0] < np.uint32(_STAKE_BIAS),
                KIND_STAKE, KIND_UNSTAKE,
            )
            sender[lane] = w[lane, 1].astype(np.int64) % cfg.stake_accounts
            recipient[lane] = sender[lane]
        amount = 1 + w[:, 3].astype(np.int64) % cfg.amount_cap
        blk = TxBlock(
            height, kind, sender, recipient, amount,
            self._content_digest(height),
        )
        if len(self._cache) >= self.CACHE:
            # Evict oldest-first, but never an entry of the OPEN
            # speculation epoch — a rollback may replay it.
            for k in list(self._cache):
                if self._cache[k][0] != self.spec_epoch:
                    del self._cache[k]
                    self.evictions += 1
                    if len(self._cache) < self.CACHE:
                        break
        self._cache[height] = [self.spec_epoch, blk]
        return blk

    def value(self, height: int) -> bytes:
        """The proposal value for ``height`` — commits to the block
        content via its content digest (round-independent: retries
        re-propose the same block). Derived WITHOUT materializing the
        block: a pipelined proposer asks for values heights ahead of
        execution, and at 64k-tx blocks each materialization is
        milliseconds of column synthesis the value never needed."""
        v = self._values.get(height)
        if v is None:
            v = hashlib.sha256(
                b"exec-value" + self._content_digest(height)
            ).digest()
            while len(self._values) >= 4096:
                self._values.pop(next(iter(self._values)))
            self._values[height] = v
        return v

    def keyring(self):
        """Deterministic per-account Ed25519 keys (sign_txs mode)."""
        if self._ring is None:
            from hyperdrive_tpu.crypto.keys import KeyRing

            self._ring = KeyRing.deterministic(
                self.config.accounts, namespace=b"exec-%d" % self.config.seed
            )
        return self._ring

    def sig_items(self, block: TxBlock) -> list:
        """The block's ``(pub, digest, sig)`` verifier triples, cached
        on the block. Only meaningful with ``sign_txs``."""
        if block._sig_items is not None:
            return block._sig_items
        cfg = self.config
        ring = self.keyring()
        bad = cfg.bad_sig_every
        sender = block.sender
        items = []
        for t in range(len(block)):
            kp = ring[sender[t]]
            digest = hashlib.sha256(
                b"exec-tx" + block.digest
                + t.to_bytes(4, "little")
            ).digest()
            sig = kp.sign_digest(digest)
            if bad and (t + 1) % bad == 0:
                # Deterministically corrupted lane: the mask must
                # reject it on every replica and both executors.
                sig = bytes([sig[0] ^ 0xFF]) + sig[1:]
            items.append((kp.public, digest, sig))
        block._sig_items = items
        return items


class HostLedgerExecutor:
    """The reference executor: one ledger, blocks applied in height
    order with pure-Python two-pass semantics. ``advance_to(h)``
    applies every missing block in ``(height, h]`` (resync gaps catch
    up deterministically) and returns the chained root at ``h``;
    re-asking a settled height returns the cached root (crash-restore
    re-commits).

    ``masks`` is an optional SHARED ``height -> [bool]`` dict the sim's
    devsched launcher path fills (ExecApplyLauncher futures resolve
    into it); absent an entry, sign_txs blocks are verified host-side —
    same signatures, same verdict, so launcher and fallback paths are
    digest-identical (replayed dumps never re-propose, hence never
    re-submit, and still reproduce the live roots).
    """

    device = False

    def __init__(
        self,
        config: ExecutionConfig,
        genesis_stakes=(),
        source: BlockSource | None = None,
        masks: dict | None = None,
        obs=NULL_BOUND,
    ):
        cfg = config
        self.config = cfg
        self.source = source if source is not None else BlockSource(cfg)
        gs = list(genesis_stakes)
        if len(gs) > cfg.accounts:
            raise ValueError(
                f"{len(gs)} genesis stakes exceed {cfg.accounts} accounts"
            )
        gs += [0] * (cfg.accounts - len(gs))
        if any(s < 0 or s > _INT32_MAX for s in gs):
            raise ValueError("genesis stakes must fit int32")
        self._init_state([cfg.initial_balance] * cfg.accounts, gs)
        #: Last applied height; 0 = genesis (heights are 1-based).
        self.height = 0
        self.genesis_root = hashlib.sha256(
            b"exec-genesis" + self._state_bytes()
        ).digest()
        self.root = self.genesis_root
        #: The running root as uint32 words — the chain-fold input form
        #: (``root`` is its byte rendering; the device executor keeps
        #: the live copy on device and mirrors here at sync).
        self._root_words = root_words(self.genesis_root)
        #: height -> chained root, for every applied height.
        self.roots: dict[int, bytes] = {}
        self.applied_total = 0
        self.rejected_total = 0
        self.masks = masks
        self.obs = obs
        self._verifier = None
        # Cumulative int32 headroom: every block can move at most
        # txs_per_block * amount_cap units into one account.
        self._flow = cfg.initial_balance
        #: Open speculative heights: height -> [guess_mask, snapshot].
        #: Insertion order is height order (speculation only stacks).
        self._spec: dict[int, list] = {}
        #: Per-height applied counts for the OPEN window only, so a
        #: rollback can unwind the counters exactly.
        self._applied_at: dict[int, int] = {}
        #: Every root a rollback ever discarded — the chaos monitor's
        #: no-leak invariant asserts none appears in a committed value.
        self.discarded_roots: set[bytes] = set()
        self.spec_confirmed = 0
        self.spec_rolled_back = 0
        #: Deepest single rollback (heights unwound in one mismatch).
        self.spec_rollback_depth = 0

    # ---- state representation (overridden by the device executor)

    def _init_state(self, balances, stakes):
        self.balances = balances
        self.stakes = stakes
        #: The account hash tree (ops/merkle.py numpy twin), updated
        #: in place along the dirty root-paths each block.
        self._tree = build_tree_np(balances, stakes)
        #: Post-block state digest of the last applied height — the
        #: O(1) witness words a proof carries (None until height 1).
        self._last_digest = None

    def _state_bytes(self) -> bytes:
        return pack_state(self.balances) + pack_state(self.stakes)

    def _apply_block(self, blk: TxBlock, ok) -> int:
        bal, stk = self.balances, self.stakes
        kind, sender, recipient, amount = blk._lists()
        out_bal: dict[int, int] = {}
        out_stk: dict[int, int] = {}
        for t in range(len(kind)):
            if ok is not None and not ok[t]:
                continue
            s, a = sender[t], amount[t]
            if kind[t] == KIND_UNSTAKE:
                out_stk[s] = out_stk.get(s, 0) + a
            else:
                out_bal[s] = out_bal.get(s, 0) + a
        # Solvency is a statement about the PRE-block snapshot (the
        # block-atomic rule): freeze the verdict per sender before any
        # mutation, or mid-block balances would re-order-couple the txs.
        sender_ok = {
            s: bal[s] >= out_bal.get(s, 0) and stk[s] >= out_stk.get(s, 0)
            for s in set(out_bal) | set(out_stk)
        }
        applied = 0
        for t in range(len(kind)):
            if ok is not None and not ok[t]:
                continue
            s = sender[t]
            if not sender_ok.get(s, True):
                continue
            k, a = kind[t], amount[t]
            if k == KIND_TRANSFER:
                bal[s] -= a
                bal[recipient[t]] += a
            elif k == KIND_STAKE:
                bal[s] -= a
                stk[s] += a
            else:
                stk[s] -= a
                bal[s] += a
            applied += 1
        return applied

    # ---- speculation hooks (overridden by the device executor)

    def _snapshot(self):
        """Pre-height state capture for rollback. Host: list copies
        (the tree's dirty-set snapshot rides along level by level).
        Device: immutable array refs (free)."""
        return (list(self.balances), list(self.stakes),
                self.root, self._root_words,
                [lvl.copy() for lvl in self._tree], self._last_digest)

    def _restore(self, snap) -> None:
        self.balances = list(snap[0])
        self.stakes = list(snap[1])
        self.root = snap[2]
        self._root_words = snap[3]
        # Copy again: the restored tree mutates in place from here, and
        # the snapshot may be re-read (a re-speculated window can roll
        # back twice against the same capture).
        self._tree = [lvl.copy() for lvl in snap[4]]
        self._last_digest = snap[5]

    def sync(self) -> None:
        """Materialize any device-pending roots/counters host-side.
        No-op on the host executor."""

    def _apply_chain(self, h: int, blk: TxBlock, ok):
        """Apply one block AND fold the new state into the running
        root. Returns the applied count, or None when the count is
        device-pending (materialized at :meth:`sync`)."""
        applied = self._apply_block(blk, ok)
        d = state_digest_np(self.balances, self.stakes)
        # Dirty set = the block's scatter targets verbatim (rejected
        # rows recompute clean leaves idempotently — same rule as the
        # fused device kernel, so the trees stay bit-identical).
        dirty = np.concatenate([blk._np[1], blk._np[2]])
        update_tree_np(self._tree, self.balances, self.stakes, dirty)
        folded = fold_merkle_np(d, merkle_root_np(self._tree))
        self._root_words = fold_root_np(self._root_words, h, folded)
        self.root = root_bytes(self._root_words)
        self.roots[h] = self.root
        self._last_digest = d
        return applied

    # ---- the public surface

    def advance_to(self, height: int) -> bytes:
        """Root at ``height``, applying any missing blocks up to it.

        Crosses an open speculation window only if every window height
        up to ``height`` is exact (unsigned guess): those are confirmed
        in passing, while a still-guessed height raises — commits must
        resolve speculation before they can read its root."""
        if self._spec and height >= min(self._spec):
            self.confirm_to(height)
        if height <= self.height:
            if height == 0:
                return self.genesis_root
            r = self.roots.get(height)
            if r is None:
                self.sync()
                r = self.roots[height]
            return r
        for h in range(self.height + 1, height + 1):
            self._step(h)
        self.sync()
        return self.root

    def _step(self, h: int, ok=_UNSET) -> None:
        cfg = self.config
        self._flow += cfg.txs_per_block * cfg.amount_cap
        if self._flow > _INT32_MAX:
            raise OverflowError(
                "cumulative block flow exceeds int32 headroom — lower "
                "amount_cap/initial_balance or widen the kernel"
            )
        blk = self.source.block(h)
        if ok is _UNSET:
            ok = self._mask_for(h, blk)
        applied = self._apply_chain(h, blk, ok)
        self.height = h
        if applied is None:
            return
        self.applied_total += applied
        self.rejected_total += len(blk) - applied
        if h in self._spec:
            self._applied_at[h] = applied
        if self.obs is not NULL_BOUND:
            self.obs.emit(
                "exec.apply", h, -1,
                "txs=%d applied=%d dev=%d"
                % (len(blk), applied, int(self.device)),
            )
            self.obs.emit("exec.root", h, -1, self.root[:8].hex())
            self.obs.emit(
                "merkle.root", h, -1,
                merkle_bytes(merkle_root_np(self._tree))[:8].hex(),
            )
            self.obs.emit(
                "merkle.update", h, -1,
                "targets=%d depth=%d full=0"
                % (2 * len(blk), tree_depth(self.config.accounts)),
            )

    # ---- speculative pipelining

    def speculate(self, height: int, guess=None) -> None:
        """Apply ``height`` NOW under a guessed admission mask (None =
        exact: every tx admitted, the unsigned semantics), snapshotting
        the pre-height state so :meth:`resolve` can roll back on a
        mismatch. Speculation stacks strictly upward."""
        if height != self.height + 1:
            raise ValueError(
                f"speculate({height}) out of order at height {self.height}"
            )
        snap = self._snapshot()
        self._spec[height] = [guess, snap]
        self._step(height, ok=guess)
        if self.obs is not NULL_BOUND:
            self.obs.emit(
                "exec.spec.speculate", height, -1,
                "signed=%d" % int(guess is not None),
            )

    def resolve(self, height: int, true_mask) -> bool:
        """Settle the LOWEST open speculation against the verified
        mask: confirm if the guess was right, otherwise roll back and
        re-apply (the later window heights re-speculate under their
        original guesses). Returns True on confirm."""
        ent = self._spec.get(height)
        if ent is None:
            raise KeyError(f"height {height} is not speculative")
        if height != min(self._spec):
            raise RuntimeError(
                f"resolve({height}) below open speculation at "
                f"{min(self._spec)}"
            )
        guess = ent[0]
        if guess is None or list(guess) == list(true_mask):
            self._confirm(height)
            return True
        self._rollback(height, true_mask)
        return False

    def confirm_to(self, height: int) -> None:
        """Confirm every exact (unsigned-guess) speculation up to
        ``height``; a still-guessed height in range raises."""
        for h in sorted(self._spec):
            if h > height:
                break
            if self._spec[h][0] is not None:
                raise RuntimeError(
                    f"confirm_to({height}): height {h} still awaits "
                    "signature verification"
                )
            self._confirm(h)

    def _confirm(self, height: int) -> None:
        self._spec.pop(height)
        self._applied_at.pop(height, None)
        self.spec_confirmed += 1
        if self.obs is not NULL_BOUND:
            self.obs.emit("exec.spec.confirm", height, -1, "")

    def _rollback(self, height: int, true_mask) -> None:
        """The mismatch path: unwind state, root, and counters to the
        pre-``height`` snapshot bit-identically, record every discarded
        root, re-apply ``height`` under the TRUE mask (final), then
        re-speculate the rest of the window. A discarded root can never
        reach a committed value: commits only read roots through
        :meth:`advance_to`/:meth:`resolve`, both of which refuse
        unresolved guesses."""
        cfg = self.config
        self.sync()
        top = self.height
        depth = top - height + 1
        later = [
            (h, self._spec[h][0]) for h in sorted(self._spec) if h > height
        ]
        snap = self._spec.pop(height)[1]
        popped = []
        for h in range(height, top + 1):
            rb = self.roots.pop(h, None)
            if rb is not None:
                popped.append((h, rb))
            a = self._applied_at.pop(h, None)
            if a is not None:
                self.applied_total -= a
                self.rejected_total -= cfg.txs_per_block - a
            self._flow -= cfg.txs_per_block * cfg.amount_cap
            self._spec.pop(h, None)
        self._restore(snap)
        self.height = height - 1
        self.spec_rolled_back += 1
        self.spec_rollback_depth = max(self.spec_rollback_depth, depth)
        if self.obs is not NULL_BOUND:
            self.obs.emit(
                "exec.spec.rollback", height, -1, "depth=%d" % depth
            )
        self._step(height, ok=[bool(v) for v in true_mask])
        for h, g in later:
            self.speculate(h, g)
        # A guessed mask can differ from the true one yet settle to the
        # IDENTICAL state (the mis-admitted lane died to block-atomic
        # solvency either way): only a root the re-settled chain
        # actually replaced counts as discarded — those are the bytes
        # the no-leak invariant bans from every committed value.
        self.sync()
        for h, rb in popped:
            if self.roots.get(h) != rb:
                self.discarded_roots.add(rb)

    def host_verify(self) -> bytes:
        """Checkpoint re-derivation (ROBUSTNESS.md state-root
        doctrine): fetch the live state host-side, recompute the last
        chain fold with the numpy twin, and require it to equal the
        running root. Raises on mismatch; returns the verified root."""
        self.sync()
        if self.height == 0:
            want = self.genesis_root
        else:
            prev = (
                self.roots[self.height - 1]
                if self.height > 1 else self.genesis_root
            )
            d = state_digest_np(self.balances, self.stakes)
            # Full O(n) tree rebuild from fetched state: the
            # incrementally-maintained Merkle root must equal it, and
            # the chain fold must re-derive byte-for-byte.
            full = merkle_root_np(build_tree_np(self.balances, self.stakes))
            tree, _ = self._proof_materials()
            if merkle_bytes(full) != merkle_bytes(merkle_root_np(tree)):
                raise AssertionError(
                    f"incremental Merkle root diverged from full rebuild "
                    f"at height {self.height}"
                )
            want = root_bytes(
                fold_root_np(
                    root_words(prev), self.height, fold_merkle_np(d, full)
                )
            )
        if want != self.root:
            raise AssertionError(
                f"state-root checkpoint mismatch at height {self.height}"
            )
        return self.root

    # ---- inclusion proofs (the trustless read path)

    def _proof_materials(self):
        """(tree levels as numpy, last digest as numpy) — the device
        executor overrides to materialize its on-device copies."""
        return self._tree, self._last_digest

    def prove(self, account: int) -> MerkleProof:
        """O(log n) inclusion proof for ``account`` at the current
        settled height: leaf values, sibling path, and the O(1) chain
        witness (previous root + state digest) a stateless client
        needs to check it against a certificate-chain root with
        :meth:`verify_inclusion`. Proofs serve SETTLED chain only —
        an open speculation window refuses (its root could roll
        back)."""
        self.sync()
        if self._spec:
            raise RuntimeError(
                "prove() with an open speculation window — resolve "
                "speculation first (a speculative root may roll back)"
            )
        h = self.height
        if h < 1:
            raise ValueError("no applied height to prove against")
        if not 0 <= account < self.config.accounts:
            raise ValueError(
                f"account {account} outside 0..{self.config.accounts - 1}"
            )
        tree, digest = self._proof_materials()
        prev = self.roots[h - 1] if h > 1 else self.genesis_root
        return MerkleProof(
            height=h,
            account=account,
            balance=int(self.balances[account]),
            stake=int(self.stakes[account]),
            prev_root=prev,
            digest=tuple(int(w) for w in digest),
            siblings=prove_np(tree, account),
        )

    def proof_basis(self) -> "ProofBasis":
        """Freeze the current settled height into a :class:`ProofBasis`
        — an O(n) copy the proof-serving path (parallel/service.py)
        takes ONCE per accepted certificate, so queries never touch the
        live executor (which may be speculated ahead of the last
        certified height by the time a query lands)."""
        self.sync()
        if self._spec:
            raise RuntimeError(
                "proof_basis() with an open speculation window — "
                "resolve speculation first"
            )
        h = self.height
        if h < 1:
            raise ValueError("no applied height to serve proofs from")
        tree, digest = self._proof_materials()
        prev = self.roots[h - 1] if h > 1 else self.genesis_root
        return ProofBasis(
            height=h,
            accounts=self.config.accounts,
            prev_root=prev,
            digest=tuple(int(w) for w in digest),
            tree=[np.array(lvl, copy=True) for lvl in tree],
            balances=[int(v) for v in self.balances],
            stakes=[int(v) for v in self.stakes],
        )

    #: The client-side check, re-exported so light clients and tests
    #: reach it without importing ops/ directly.
    verify_inclusion = staticmethod(verify_inclusion)

    def _mask_for(self, h: int, blk: TxBlock):
        if not self.config.sign_txs:
            return None
        if self.masks is not None:
            m = self.masks.get(h)
            if m is not None:
                return m
        if self._verifier is None:
            from hyperdrive_tpu.verifier import HostVerifier

            self._verifier = HostVerifier()
        mask = self._verifier.verify_signatures(self.source.sig_items(blk))
        return [bool(v) for v in mask]

    def election_stakes(self, n: int) -> tuple:
        """What the epoch election reads at a boundary: the first ``n``
        stake accounts, floored so weight can hit the floor but a pool
        member never leaves candidacy (ROBUSTNESS.md)."""
        floor = self.config.stake_floor
        return tuple(int(self.stakes[i]) + floor for i in range(n))


class ProofBasis:
    """A frozen proof-serving snapshot of ONE settled height: the tree,
    leaf values, and O(1) chain witness (previous root + state digest),
    copied out of an executor by :meth:`HostLedgerExecutor.proof_basis`.
    Serving a proof from a basis is pure numpy indexing — O(log n), no
    executor locks, no interaction with speculation — so the service
    port can answer read storms while the executor runs ahead."""

    __slots__ = ("height", "accounts", "prev_root", "digest", "tree",
                 "balances", "stakes")

    def __init__(self, *, height, accounts, prev_root, digest, tree,
                 balances, stakes):
        self.height = height
        self.accounts = accounts
        self.prev_root = prev_root
        self.digest = digest
        self.tree = tree
        self.balances = balances
        self.stakes = stakes

    def prove(self, account: int) -> MerkleProof:
        """O(log n) inclusion proof for ``account`` at this basis's
        height — same shape :meth:`HostLedgerExecutor.prove` returns,
        minus any dependence on live executor state."""
        if not 0 <= account < self.accounts:
            raise ValueError(
                f"account {account} outside 0..{self.accounts - 1}"
            )
        return MerkleProof(
            height=self.height,
            account=account,
            balance=self.balances[account],
            stake=self.stakes[account],
            prev_root=self.prev_root,
            digest=self.digest,
            siblings=prove_np(self.tree, account),
        )


class ExecApplyLauncher(VerifyLauncher):
    """The ``exec.apply`` device-queue command: a block's tx-signature
    triples, coalesced by the SAME drain that carries vote verifies —
    grouped separately by launcher identity, so one drain cycle issues
    the vote launch and the exec launch back to back, and the block's
    admission mask resolves with the settle futures it shares a slot
    with. Mutation itself doesn't ride the queue: it is one call on the
    executor at commit time, already a single fused kernel."""

    kind = "exec.apply"
