"""Deterministic tx blocks, the host reference executor, root chaining.

Everything here is jax-free and bit-deterministic. A height's block is
a pure function of ``(config.seed, height)`` — never of committed
values or delivery order — so a laggard resyncing straight to height H
executes the identical blocks every up-to-date replica executed, and a
replayed dump re-derives the whole ledger trajectory from the config
ints alone (ScenarioRecord v7 stores no state).

State roots chain like the commit chain itself:

  root_0 = H("exec-genesis" || pack(balances) || pack(stakes))
  root_h = H("exec-root" || root_{h-1} || state_digest_h)

with ``pack`` fixed as 8-byte little-endian signed per account, so the
host executor (Python ints) and the device executor (int32 tensors)
hash identical bytes — the differential-parity contract the
``python -m hyperdrive_tpu.exec parity`` smoke enforces.

Apply semantics are ORDER-INDEPENDENT and block-atomic per sender: a
sender whose summed asks (balance asks for TRANSFER/STAKE, stake asks
for UNSTAKE) exceed its pre-block funds has every transaction in that
block rejected. That is what makes the vectorized device form
(ops/ledger.py: segment-sum → solvency gather → scatter-add) exactly
equal to any serial schedule of the same block.
"""

from __future__ import annotations

import hashlib
import random

from hyperdrive_tpu.devsched.queue import VerifyLauncher
from hyperdrive_tpu.exec import ExecutionConfig
from hyperdrive_tpu.obs.recorder import NULL_BOUND

__all__ = [
    "KIND_TRANSFER",
    "KIND_STAKE",
    "KIND_UNSTAKE",
    "TxBlock",
    "BlockSource",
    "HostLedgerExecutor",
    "ExecApplyLauncher",
]

#: Transaction kinds — must match ops/ledger.py (the device kernel keeps
#: its own copies so ops/ stays importable without this package;
#: tests/test_exec.py pins the equality).
KIND_TRANSFER = 0
KIND_STAKE = 1
KIND_UNSTAKE = 2

_INT32_MAX = 2**31 - 1


def pack_state(values) -> bytes:
    """Account vector -> bytes, 8-byte little-endian signed per entry.
    The ONE packing both executors must agree on for root equality."""
    return b"".join(int(v).to_bytes(8, "little", signed=True) for v in values)


class TxBlock:
    """One height's transactions as dense columns (the device layout is
    the native layout; the host executor just walks the columns)."""

    __slots__ = (
        "height", "kind", "sender", "recipient", "amount", "digest",
        "_sig_items", "_cols",
    )

    def __init__(self, height, kind, sender, recipient, amount, digest):
        self.height = height
        self.kind = kind
        self.sender = sender
        self.recipient = recipient
        self.amount = amount
        #: Content digest: what the exec proposer's value commits to.
        self.digest = digest
        self._sig_items = None
        #: Device-padded column cache (DeviceLedgerExecutor): the
        #: list->tensor conversion is block MATERIALIZATION, shared by
        #: every replica on the source like the columns themselves, and
        #: evicted with the block by the source's LRU.
        self._cols = None

    def __len__(self) -> int:
        return len(self.kind)


class BlockSource:
    """Deterministic per-height workload, shared by every replica.

    ``block(h)`` derives height h's transactions from a seeded RNG
    keyed on ``(config.seed, h)``; every ``stake_every``-th tx is a
    STAKE/UNSTAKE on a validator stake account (``stake_accounts``
    wide, biased toward STAKE so validator weights drift and epoch
    elections have something to read). ``value(h)`` is the 32-byte
    proposal value committing to the block. With ``sign_txs`` each tx
    carries a real Ed25519 signature from its sender's deterministic
    account key; ``bad_sig_every`` corrupts every K-th one.
    """

    #: Blocks cached per source; sim runs walk heights forward and
    #: bench blocks are large, so a short LRU covers re-reads (the n
    #: replicas' executors share one source) without pinning 64k-tx
    #: columns for every committed height.
    CACHE = 8

    def __init__(self, config: ExecutionConfig):
        self.config = config
        self._cache: dict[int, TxBlock] = {}
        self._values: dict[int, bytes] = {}
        self._ring = None

    def block(self, height: int) -> TxBlock:
        blk = self._cache.get(height)
        if blk is not None:
            return blk
        cfg = self.config
        key = hashlib.sha256(
            b"exec-block-%d-%d" % (cfg.seed, height)
        ).digest()
        rnd = random.Random(int.from_bytes(key[:8], "little"))
        kind, sender, recipient, amount = [], [], [], []
        stake_lane = cfg.stake_every > 0 and cfg.stake_accounts > 0
        for t in range(cfg.txs_per_block):
            if stake_lane and t % cfg.stake_every == 0:
                s = rnd.randrange(cfg.stake_accounts)
                kind.append(
                    KIND_STAKE if rnd.random() < 0.6 else KIND_UNSTAKE
                )
                sender.append(s)
                recipient.append(s)
            else:
                kind.append(KIND_TRANSFER)
                sender.append(rnd.randrange(cfg.accounts))
                recipient.append(rnd.randrange(cfg.accounts))
            amount.append(rnd.randint(1, cfg.amount_cap))
        h = hashlib.sha256()
        h.update(b"exec-txs")
        h.update(key)
        for col in (kind, sender, recipient, amount):
            h.update(b"".join(v.to_bytes(4, "little") for v in col))
        blk = TxBlock(height, kind, sender, recipient, amount, h.digest())
        while len(self._cache) >= self.CACHE:
            self._cache.pop(next(iter(self._cache)))
        self._cache[height] = blk
        return blk

    def value(self, height: int) -> bytes:
        """The proposal value for ``height`` — commits to the block
        content (round-independent: retries re-propose the same
        block)."""
        v = self._values.get(height)
        if v is None:
            v = hashlib.sha256(
                b"exec-value" + self.block(height).digest
            ).digest()
            while len(self._values) >= 4096:
                self._values.pop(next(iter(self._values)))
            self._values[height] = v
        return v

    def keyring(self):
        """Deterministic per-account Ed25519 keys (sign_txs mode)."""
        if self._ring is None:
            from hyperdrive_tpu.crypto.keys import KeyRing

            self._ring = KeyRing.deterministic(
                self.config.accounts, namespace=b"exec-%d" % self.config.seed
            )
        return self._ring

    def sig_items(self, block: TxBlock) -> list:
        """The block's ``(pub, digest, sig)`` verifier triples, cached
        on the block. Only meaningful with ``sign_txs``."""
        if block._sig_items is not None:
            return block._sig_items
        cfg = self.config
        ring = self.keyring()
        bad = cfg.bad_sig_every
        items = []
        for t in range(len(block)):
            kp = ring[block.sender[t]]
            digest = hashlib.sha256(
                b"exec-tx" + block.digest
                + t.to_bytes(4, "little")
            ).digest()
            sig = kp.sign_digest(digest)
            if bad and (t + 1) % bad == 0:
                # Deterministically corrupted lane: the mask must
                # reject it on every replica and both executors.
                sig = bytes([sig[0] ^ 0xFF]) + sig[1:]
            items.append((kp.public, digest, sig))
        block._sig_items = items
        return items


class HostLedgerExecutor:
    """The reference executor: one ledger, blocks applied in height
    order with pure-Python two-pass semantics. ``advance_to(h)``
    applies every missing block in ``(height, h]`` (resync gaps catch
    up deterministically) and returns the chained root at ``h``;
    re-asking a settled height returns the cached root (crash-restore
    re-commits).

    ``masks`` is an optional SHARED ``height -> [bool]`` dict the sim's
    devsched launcher path fills (ExecApplyLauncher futures resolve
    into it); absent an entry, sign_txs blocks are verified host-side —
    same signatures, same verdict, so launcher and fallback paths are
    digest-identical (replayed dumps never re-propose, hence never
    re-submit, and still reproduce the live roots).
    """

    device = False

    def __init__(
        self,
        config: ExecutionConfig,
        genesis_stakes=(),
        source: BlockSource | None = None,
        masks: dict | None = None,
        obs=NULL_BOUND,
    ):
        cfg = config
        self.config = cfg
        self.source = source if source is not None else BlockSource(cfg)
        gs = list(genesis_stakes)
        if len(gs) > cfg.accounts:
            raise ValueError(
                f"{len(gs)} genesis stakes exceed {cfg.accounts} accounts"
            )
        gs += [0] * (cfg.accounts - len(gs))
        if any(s < 0 or s > _INT32_MAX for s in gs):
            raise ValueError("genesis stakes must fit int32")
        self._init_state([cfg.initial_balance] * cfg.accounts, gs)
        #: Last applied height; 0 = genesis (heights are 1-based).
        self.height = 0
        self.genesis_root = hashlib.sha256(
            b"exec-genesis" + self._state_bytes()
        ).digest()
        self.root = self.genesis_root
        #: height -> chained root, for every applied height.
        self.roots: dict[int, bytes] = {}
        self.applied_total = 0
        self.rejected_total = 0
        self.masks = masks
        self.obs = obs
        self._verifier = None
        # Cumulative int32 headroom: every block can move at most
        # txs_per_block * amount_cap units into one account.
        self._flow = cfg.initial_balance

    # ---- state representation (overridden by the device executor)

    def _init_state(self, balances, stakes):
        self.balances = balances
        self.stakes = stakes

    def _state_bytes(self) -> bytes:
        return pack_state(self.balances) + pack_state(self.stakes)

    def _apply_block(self, blk: TxBlock, ok) -> int:
        bal, stk = self.balances, self.stakes
        out_bal: dict[int, int] = {}
        out_stk: dict[int, int] = {}
        for t in range(len(blk)):
            if ok is not None and not ok[t]:
                continue
            s, a = blk.sender[t], blk.amount[t]
            if blk.kind[t] == KIND_UNSTAKE:
                out_stk[s] = out_stk.get(s, 0) + a
            else:
                out_bal[s] = out_bal.get(s, 0) + a
        # Solvency is a statement about the PRE-block snapshot (the
        # block-atomic rule): freeze the verdict per sender before any
        # mutation, or mid-block balances would re-order-couple the txs.
        sender_ok = {
            s: bal[s] >= out_bal.get(s, 0) and stk[s] >= out_stk.get(s, 0)
            for s in set(out_bal) | set(out_stk)
        }
        applied = 0
        for t in range(len(blk)):
            if ok is not None and not ok[t]:
                continue
            s = blk.sender[t]
            if not sender_ok.get(s, True):
                continue
            k, a = blk.kind[t], blk.amount[t]
            if k == KIND_TRANSFER:
                bal[s] -= a
                bal[blk.recipient[t]] += a
            elif k == KIND_STAKE:
                bal[s] -= a
                stk[s] += a
            else:
                stk[s] -= a
                bal[s] += a
            applied += 1
        return applied

    # ---- the public surface

    def advance_to(self, height: int) -> bytes:
        """Root at ``height``, applying any missing blocks up to it."""
        if height <= self.height:
            return self.roots[height] if height > 0 else self.genesis_root
        for h in range(self.height + 1, height + 1):
            self._step(h)
        return self.root

    def _step(self, h: int) -> None:
        cfg = self.config
        self._flow += cfg.txs_per_block * cfg.amount_cap
        if self._flow > _INT32_MAX:
            raise OverflowError(
                "cumulative block flow exceeds int32 headroom — lower "
                "amount_cap/initial_balance or widen the kernel"
            )
        blk = self.source.block(h)
        ok = self._mask_for(h, blk)
        applied = self._apply_block(blk, ok)
        self.applied_total += applied
        self.rejected_total += len(blk) - applied
        self.height = h
        d = hashlib.sha256(
            b"exec-state" + h.to_bytes(8, "little") + self._state_bytes()
        ).digest()
        self.root = hashlib.sha256(b"exec-root" + self.root + d).digest()
        self.roots[h] = self.root
        if self.obs is not NULL_BOUND:
            self.obs.emit(
                "exec.apply", h, -1,
                "txs=%d applied=%d dev=%d"
                % (len(blk), applied, int(self.device)),
            )
            self.obs.emit("exec.root", h, -1, self.root[:8].hex())

    def _mask_for(self, h: int, blk: TxBlock):
        if not self.config.sign_txs:
            return None
        if self.masks is not None:
            m = self.masks.get(h)
            if m is not None:
                return m
        if self._verifier is None:
            from hyperdrive_tpu.verifier import HostVerifier

            self._verifier = HostVerifier()
        mask = self._verifier.verify_signatures(self.source.sig_items(blk))
        return [bool(v) for v in mask]

    def election_stakes(self, n: int) -> tuple:
        """What the epoch election reads at a boundary: the first ``n``
        stake accounts, floored so weight can hit the floor but a pool
        member never leaves candidacy (ROBUSTNESS.md)."""
        floor = self.config.stake_floor
        return tuple(int(self.stakes[i]) + floor for i in range(n))


class ExecApplyLauncher(VerifyLauncher):
    """The ``exec.apply`` device-queue command: a block's tx-signature
    triples, coalesced by the SAME drain that carries vote verifies —
    grouped separately by launcher identity, so one drain cycle issues
    the vote launch and the exec launch back to back, and the block's
    admission mask resolves with the settle futures it shares a slot
    with. Mutation itself doesn't ride the queue: it is one call on the
    executor at commit time, already a single fused kernel."""

    kind = "exec.apply"
