"""The device executor: ledger state as int32 tensors, block apply +
state digest + root fold as ONE jitted launch (ops/ledger.py
``apply_block_chain_jax``).

Digest-identical to :class:`~hyperdrive_tpu.exec.ledger
.HostLedgerExecutor` by construction — the device chain fold is the
bit-exact jnp twin of the numpy reduction in ops/rootmix.py — and
enforced by ``python -m hyperdrive_tpu.exec parity`` (CI: exec-parity
smoke on forced CPU devices, HD_SANITIZE=1, including the
``--pipelined`` leg).

Between heights NOTHING leaves the device: the running root rides as a
uint32[8] tensor and per-height applied counts as int32 scalars, queued
on ``_pending`` and materialized in one stacked fetch per pipeline
window (:meth:`sync` — called by ``advance_to`` before a root is read,
and by rollback before counters unwind). Speculation snapshots are
immutable array refs, so snapshotting a height costs nothing.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from hyperdrive_tpu.exec.ledger import HostLedgerExecutor, TxBlock
from hyperdrive_tpu.obs.recorder import NULL_BOUND
from hyperdrive_tpu.ops import ledger as ops_ledger
from hyperdrive_tpu.ops import merkle
from hyperdrive_tpu.ops.rootmix import mix_matrix, root_bytes

__all__ = ["DeviceLedgerExecutor"]


class DeviceLedgerExecutor(HostLedgerExecutor):
    """Ledger state lives on device between blocks; each height is one
    fused padded kernel call (apply + digest + chain fold, pad rows
    inert) whose outputs — new state, new root, applied count — stay on
    device until :meth:`sync`."""

    device = True

    def _init_state(self, balances, stakes):
        self._dbal = jnp.asarray(np.asarray(balances, dtype=np.int32))
        self._dstk = jnp.asarray(np.asarray(stakes, dtype=np.int32))
        #: Device-resident running root (uint32[8]); created lazily at
        #: the first apply (genesis root is a host sha256).
        self._droot = None
        #: Heights applied but not yet materialized host-side:
        #: (height, root_words_tensor, applied_count_scalar,
        #: merkle_root_tensor, full_rebuild_flag).
        self._pending: list = []
        self._dmix = None
        #: Device-resident account hash tree (tuple of uint32 levels,
        #: ops/merkle.py ``build_tree_jax``) and last post-block state
        #: digest — both created lazily like ``_droot`` and updated
        #: inside the same fused launch as the apply.
        self._dtree = None
        self._ddigest = None

    def _state_bytes(self) -> bytes:
        bal = np.asarray(self._dbal, dtype=np.int64)
        stk = np.asarray(self._dstk, dtype=np.int64)
        return (
            bal.astype("<i8").tobytes() + stk.astype("<i8").tobytes()
        )

    @staticmethod
    def _device_cols(blk: TxBlock):
        # The block as ONE packed [5, bucket] int32 device tensor
        # (kind/sender/recipient/amount/sig_ok rows), cached ON the
        # block: the pack+transfer is block materialization (shared by
        # every replica via the shared source, freed with the block by
        # the source's LRU — speculation-epoch entries pinned so
        # rollback replays hit this cache), so the per-apply cost is
        # the kernel launch itself. One contiguous transfer instead of
        # five: device_put dispatch is a fixed per-buffer cost that was
        # a visible slice of the per-height bill. The cached sig_ok row
        # is the no-signature mask (real rows 1, pad rows inert 0);
        # signed runs repack per call.
        cols = blk._cols
        if cols is None:
            cols = blk._cols = jnp.asarray(
                ops_ledger.pack_block_cols(*blk._np)
            )
        return cols

    def _apply_chain(self, h: int, blk: TxBlock, ok):
        if ok is not None:
            cols = jnp.asarray(
                ops_ledger.pack_block_cols(*blk._np, sig_ok=ok)
            )
        else:
            cols = self._device_cols(blk)
        if self._droot is None:
            self._droot = jnp.asarray(self._root_words)
        if self._dmix is None:
            self._dmix = jnp.asarray(mix_matrix(4 * self.config.accounts))
        if self._dtree is None:
            self._dtree = merkle.build_tree_jax(self._dbal, self._dstk)
        full = 2 * cols.shape[1] >= self._dtree[0].shape[0]
        (
            self._dbal, self._dstk, count, self._droot,
            self._ddigest, self._dtree,
        ) = ops_ledger._jitted_chain_merkle_cols()(
            self._dbal, self._dstk, self._droot, self._dtree,
            jnp.uint32(h & 0xFFFFFFFF), cols, self._dmix,
        )
        self._pending.append(
            (h, self._droot, count, self._dtree[-1][0], full)
        )
        return None  # counters/roots materialize at sync()

    # ---- speculation hooks: snapshots are array refs (free)

    def _snapshot(self):
        if self._droot is None:
            self._droot = jnp.asarray(self._root_words)
        if self._dtree is None:
            self._dtree = merkle.build_tree_jax(self._dbal, self._dstk)
        return (self._dbal, self._dstk, self._droot,
                self._dtree, self._ddigest)

    def _restore(self, snap) -> None:
        (self._dbal, self._dstk, self._droot,
         self._dtree, self._ddigest) = snap

    def sync(self) -> None:
        """One fetch materializes every pending height's root and
        applied count host-side — the only inter-height host hop the
        device path pays, once per pipeline window. ``device_get`` on
        the pytree copies leaves without staging an XLA program (a
        ``jnp.stack`` here would compile once per distinct window
        depth, which on a cold cache costs more than the window)."""
        if not self._pending:
            return
        import jax

        fetched = jax.device_get(
            [(p[1], p[2], p[3]) for p in self._pending]
        )
        t = self.config.txs_per_block
        depth = merkle.tree_depth(self.config.accounts)
        for (h, _, _, _, full), (rw, c, mw) in zip(
            self._pending, fetched
        ):
            rb = root_bytes(rw)
            self.roots[h] = rb
            c = int(c)
            self.applied_total += c
            self.rejected_total += t - c
            if h in self._spec:
                self._applied_at[h] = c
            if self.obs is not NULL_BOUND:
                self.obs.emit(
                    "exec.apply", h, -1,
                    "txs=%d applied=%d dev=1" % (t, c),
                )
                self.obs.emit("exec.root", h, -1, rb[:8].hex())
                self.obs.emit(
                    "merkle.root", h, -1,
                    merkle.merkle_bytes(mw)[:8].hex(),
                )
                self.obs.emit(
                    "merkle.update", h, -1,
                    "targets=%d depth=%d full=%d"
                    % (2 * t, depth, int(full)),
                )
        self._pending.clear()
        self._root_words = np.asarray(fetched[-1][0], dtype=np.uint32)
        self.root = root_bytes(self._root_words)

    def _proof_materials(self):
        """Materialize the on-device tree and digest for proof
        serving — a read-path fetch, never on the apply hot path."""
        return (
            [np.asarray(lvl) for lvl in self._dtree],
            np.asarray(self._ddigest),
        )

    # Host views for election_stakes / debugging: materialize on read.
    @property
    def balances(self):
        return np.asarray(self._dbal)

    @property
    def stakes(self):
        return np.asarray(self._dstk)
