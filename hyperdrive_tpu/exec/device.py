"""The device executor: ledger state as int32 tensors, block apply as
one jitted segment-sum/scatter-add launch (ops/ledger.py).

Digest-identical to :class:`~hyperdrive_tpu.exec.ledger
.HostLedgerExecutor` by construction — the root chain hashes the same
8-byte little-endian packing of the same int32 state — and enforced by
``python -m hyperdrive_tpu.exec parity`` (CI: exec-parity smoke on
forced CPU devices, HD_SANITIZE=1).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from hyperdrive_tpu.exec.ledger import HostLedgerExecutor, TxBlock
from hyperdrive_tpu.ops import ledger as ops_ledger

__all__ = ["DeviceLedgerExecutor"]


class DeviceLedgerExecutor(HostLedgerExecutor):
    """Ledger state lives on device between blocks; each applied block
    is one padded kernel call (pad rows inert), and only the root hash
    pulls the state back to host — the per-block transfer both
    executors pay, since the root is a host hash either way."""

    device = True

    def _init_state(self, balances, stakes):
        self._dbal = jnp.asarray(np.asarray(balances, dtype=np.int32))
        self._dstk = jnp.asarray(np.asarray(stakes, dtype=np.int32))

    def _state_bytes(self) -> bytes:
        bal = np.asarray(self._dbal, dtype=np.int64)
        stk = np.asarray(self._dstk, dtype=np.int64)
        return (
            bal.astype("<i8").tobytes() + stk.astype("<i8").tobytes()
        )

    @staticmethod
    def _device_cols(blk: TxBlock):
        # Padded device tensors, cached ON the block: the list->tensor
        # conversion is block materialization (shared by every replica
        # via the shared source, freed with the block by the source's
        # LRU), so the per-apply cost is the kernel launch itself. The
        # cached mask is the no-signature mask (real rows True, pad
        # rows inert False); signed runs overwrite it per call.
        cols = blk._cols
        if cols is None:
            k, s, r, a, m = ops_ledger.pad_block(
                blk.kind, blk.sender, blk.recipient, blk.amount,
                [True] * len(blk),
            )
            cols = blk._cols = (
                jnp.asarray(k), jnp.asarray(s), jnp.asarray(r),
                jnp.asarray(a), jnp.asarray(m),
            )
        return cols

    def _apply_block(self, blk: TxBlock, ok) -> int:
        n = len(blk)
        k, s, r, a, m = self._device_cols(blk)
        if ok is not None:
            padded = np.zeros(len(m), dtype=bool)
            padded[:n] = ok
            m = jnp.asarray(padded)
        self._dbal, self._dstk, applied = ops_ledger._jitted()(
            self._dbal, self._dstk, k, s, r, a, m
        )
        # Pad rows are inert (mask False), so the full-width sum is the
        # true applied count.
        return int(np.asarray(applied).sum())

    # Host views for election_stakes / debugging: materialize on read.
    @property
    def balances(self):
        return np.asarray(self._dbal)

    @property
    def stakes(self):
        return np.asarray(self._dstk)
