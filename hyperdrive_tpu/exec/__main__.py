"""Execution-layer smokes: device apply vs host reference parity.

Usage::

    python -m hyperdrive_tpu.exec parity [--blocks H] [--accounts A]
        [--txs T] [--seed S] [--pipelined]
    python -m hyperdrive_tpu.exec prove [--blocks H] [--accounts A]
        [--txs T] [--seed S]

Runs the SAME deterministic block workload through
:class:`~hyperdrive_tpu.exec.ledger.HostLedgerExecutor` (pure-Python
two-pass reference) and :class:`~hyperdrive_tpu.exec.device
.DeviceLedgerExecutor` (one padded segment-sum/scatter-add launch per
block, ops/ledger.py) and demands byte-equal chained state roots at
EVERY height — three legs:

  1. unsigned transfers + stake churn (the sim/chaos configuration),
  2. signed transactions with a deterministically corrupted lane every
     8th tx (the admission mask must reject identically on both),
  3. an insolvency-heavy leg (tiny balances) hammering the
     block-atomic sender-solvency rule where vectorized and serial
     semantics would first diverge if they could.

``prove`` is the Merkle proof-serving smoke: both executor classes
advance the same chain, every sampled account's inclusion proof must be
bit-identical across host and device, survive the wire codec
byte-for-byte, and verify against the chained root — and all four
forged-proof variants (stale previous root, forged sibling, truncated
path, wrong leaf) must fail verification on both.

``--pipelined`` adds a fourth leg exercising the speculative pipeline
end to end: every leg's config is replayed through speculate/resolve —
including a forced wrong-guess rollback per window — and the resulting
root chain must be byte-equal to the sequential ``advance_to`` chain,
with ``host_verify`` re-deriving the final fold from fetched state on
both executors (the state-root checkpoint doctrine, ROBUSTNESS.md).

Exit 1 on any root mismatch. Shapes are tiny; with the checkout's
``.jax_cache`` warmed the run is seconds. HD_SANITIZE=1 in the CI
environment arms the runtime sanitizer exactly as the devsched parity
smoke does.
"""

from __future__ import annotations

import argparse
import os
import sys

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", ".jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "2.0")


def _leg(name: str, cfg, genesis_stakes, blocks: int) -> int:
    from hyperdrive_tpu.exec.device import DeviceLedgerExecutor
    from hyperdrive_tpu.exec.ledger import BlockSource, HostLedgerExecutor

    src = BlockSource(cfg)
    host = HostLedgerExecutor(cfg, genesis_stakes, source=src)
    dev = DeviceLedgerExecutor(cfg, genesis_stakes, source=src)
    if host.genesis_root != dev.genesis_root:
        print(f"FAIL {name}: genesis roots differ", file=sys.stderr)
        return 1
    for h in range(1, blocks + 1):
        hr = host.advance_to(h)
        dr = dev.advance_to(h)
        if hr != dr:
            print(
                f"FAIL {name}: root mismatch at height {h}: "
                f"host={hr.hex()[:16]} device={dr.hex()[:16]}",
                file=sys.stderr,
            )
            return 1
    if host.applied_total != dev.applied_total:
        print(
            f"FAIL {name}: applied counts differ "
            f"({host.applied_total} != {dev.applied_total})",
            file=sys.stderr,
        )
        return 1
    print(
        f"ok {name}: {blocks} blocks, roots identical, "
        f"applied={host.applied_total} rejected={host.rejected_total}"
    )
    return 0


def _pipelined_leg(name: str, cfg, genesis_stakes, blocks: int) -> int:
    """Speculative-pipeline parity: each executor class speculates a
    window per height — first with a deliberately WRONG guess (forcing
    a rollback) where the block has rows to mis-admit, then resolves
    with the true mask — and the settled chain must equal the
    sequential reference chain byte for byte."""
    from hyperdrive_tpu.exec.device import DeviceLedgerExecutor
    from hyperdrive_tpu.exec.ledger import BlockSource, HostLedgerExecutor

    src = BlockSource(cfg)
    ref = HostLedgerExecutor(cfg, genesis_stakes, source=src)
    seq = [ref.advance_to(h) for h in range(1, blocks + 1)]

    if cfg.sign_txs:
        from hyperdrive_tpu.verifier import HostVerifier

        v = HostVerifier()

        def true_mask(h):
            items = src.sig_items(src.block(h))
            return [bool(b) for b in v.verify_signatures(items)]
    else:
        def true_mask(h):
            return [True] * cfg.txs_per_block

    rollbacks = 0
    for cls in (HostLedgerExecutor, DeviceLedgerExecutor):
        ex = cls(cfg, genesis_stakes, source=src)
        for h in range(1, blocks + 1):
            m = true_mask(h)
            guess = list(m)
            if h % 2 and any(guess):
                # Force a mismatch: flip one admitted lane.
                guess[guess.index(True)] = False
            ex.speculate(h, guess)
            if not ex.resolve(h, m):
                rollbacks += 1
        got = [ex.advance_to(h) for h in range(1, blocks + 1)]
        if got != seq:
            bad = next(h for h in range(blocks) if got[h] != seq[h])
            print(
                f"FAIL {name}: pipelined root diverges from sequential "
                f"at height {bad + 1} ({cls.__name__})",
                file=sys.stderr,
            )
            return 1
        if ex.applied_total != ref.applied_total:
            print(
                f"FAIL {name}: pipelined applied count "
                f"{ex.applied_total} != {ref.applied_total} "
                f"({cls.__name__})",
                file=sys.stderr,
            )
            return 1
        if ex.discarded_roots & set(seq):
            print(
                f"FAIL {name}: a rolled-back root equals a committed "
                f"root ({cls.__name__})",
                file=sys.stderr,
            )
            return 1
        ex.host_verify()
    print(
        f"ok {name}: {blocks} blocks pipelined == sequential, "
        f"{rollbacks} forced rollbacks, checkpoints verified"
    )
    return 0


def parity(args) -> int:
    from hyperdrive_tpu.exec import ExecutionConfig

    rc = 0
    rc |= _leg(
        "exec-apply",
        ExecutionConfig(
            accounts=args.accounts,
            txs_per_block=args.txs,
            stake_every=3,
            stake_accounts=min(4, args.accounts),
            seed=args.seed,
        ),
        (5, 9, 2, 7),
        args.blocks,
    )
    rc |= _leg(
        "exec-signed",
        ExecutionConfig(
            accounts=min(args.accounts, 16),
            txs_per_block=min(args.txs, 24),
            stake_every=4,
            stake_accounts=4,
            seed=args.seed + 1,
            sign_txs=True,
            bad_sig_every=8,
        ),
        (3, 3, 3, 3),
        min(args.blocks, 3),
    )
    rc |= _leg(
        "exec-insolvent",
        ExecutionConfig(
            accounts=args.accounts,
            txs_per_block=args.txs,
            stake_every=2,
            stake_accounts=min(4, args.accounts),
            seed=args.seed + 2,
            amount_cap=64,
            initial_balance=40,
        ),
        (1, 0, 2, 0),
        args.blocks,
    )
    if getattr(args, "pipelined", False):
        rc |= _pipelined_leg(
            "exec-pipelined",
            ExecutionConfig(
                accounts=args.accounts,
                txs_per_block=args.txs,
                stake_every=3,
                stake_accounts=min(4, args.accounts),
                seed=args.seed,
            ),
            (5, 9, 2, 7),
            args.blocks,
        )
        rc |= _pipelined_leg(
            "exec-pipelined-signed",
            ExecutionConfig(
                accounts=min(args.accounts, 16),
                txs_per_block=min(args.txs, 24),
                stake_every=4,
                stake_accounts=4,
                seed=args.seed + 1,
                sign_txs=True,
                bad_sig_every=8,
            ),
            (3, 3, 3, 3),
            min(args.blocks, 3),
        )
    return rc


def prove(args) -> int:
    """Proof-serving smoke: host/device proof parity, codec roundtrip,
    chained-root verification, and the four forged variants all
    rejected — the CI vehicle for the trustless-read surface."""
    import dataclasses

    from hyperdrive_tpu.exec import ExecutionConfig
    from hyperdrive_tpu.exec.device import DeviceLedgerExecutor
    from hyperdrive_tpu.exec.ledger import BlockSource, HostLedgerExecutor
    from hyperdrive_tpu.parallel.service import (
        STATUS_COMMITTED,
        decode_proof,
        encode_proof,
    )

    cfg = ExecutionConfig(
        accounts=args.accounts,
        txs_per_block=args.txs,
        stake_every=3,
        stake_accounts=min(4, args.accounts),
        seed=args.seed,
    )
    src = BlockSource(cfg)
    host = HostLedgerExecutor(cfg, source=src)
    dev = DeviceLedgerExecutor(cfg, source=src)
    for ex in (host, dev):
        ex.advance_to(args.blocks)
    if host.root != dev.root:
        print("FAIL prove: host/device root mismatch", file=sys.stderr)
        return 1
    root = host.roots[args.blocks]
    sample = sorted({0, args.accounts // 2, args.accounts - 1})
    for account in sample:
        hp, dp = host.prove(account), dev.prove(account)
        if hp != dp:
            print(
                f"FAIL prove: host/device proof mismatch for account "
                f"{account}",
                file=sys.stderr,
            )
            return 1
        _, _, wired = decode_proof(
            encode_proof(1, STATUS_COMMITTED, hp)
        )
        if wired != hp:
            print(
                f"FAIL prove: proof frame for account {account} did "
                f"not roundtrip the wire codec",
                file=sys.stderr,
            )
            return 1
        if not host.verify_inclusion(
            root, account, wired.balance, wired.stake, wired
        ):
            print(
                f"FAIL prove: honest proof for account {account} "
                f"failed verification",
                file=sys.stderr,
            )
            return 1
    victim = host.prove(sample[-1])
    forgeries = {
        "stale-root": dataclasses.replace(
            victim, prev_root=b"\x01" * 32
        ),
        "forged-sibling": dataclasses.replace(
            victim, siblings=((1, 2, 3, 4),) + victim.siblings[1:]
        ),
        "truncated-path": dataclasses.replace(
            victim, siblings=victim.siblings[:-1]
        ),
        "wrong-leaf": dataclasses.replace(
            victim, balance=victim.balance + 1
        ),
    }
    for name, bad in forgeries.items():
        if host.verify_inclusion(
            root, bad.account, bad.balance, bad.stake, bad
        ):
            print(
                f"FAIL prove: {name} forgery verified", file=sys.stderr
            )
            return 1
    print(
        f"ok prove: {args.blocks} blocks, {len(sample)} accounts "
        f"host==device, codec roundtrip, root verification, "
        f"{len(forgeries)} forgeries rejected "
        f"(depth={len(victim.siblings)})"
    )
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m hyperdrive_tpu.exec")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p = sub.add_parser(
        "parity",
        help="device batched apply vs host reference executor: chained "
        "state roots must be byte-equal at every height",
    )
    p.add_argument("--blocks", type=int, default=6)
    p.add_argument("--accounts", type=int, default=32)
    p.add_argument("--txs", type=int, default=48)
    p.add_argument("--seed", type=int, default=7)
    p.add_argument(
        "--pipelined", action="store_true",
        help="add the speculative-pipeline legs: forced-rollback "
        "speculate/resolve chains must equal the sequential chains, "
        "host_verify checkpoints included",
    )
    p.set_defaults(fn=parity, label="parity")

    p = sub.add_parser(
        "prove",
        help="Merkle proof-serving smoke: host/device proof parity, "
        "wire-codec roundtrip, chained-root verification, all four "
        "forged variants rejected",
    )
    p.add_argument("--blocks", type=int, default=4)
    p.add_argument("--accounts", type=int, default=32)
    p.add_argument("--txs", type=int, default=24)
    p.add_argument("--seed", type=int, default=7)
    p.set_defaults(fn=prove, label="prove")

    args = ap.parse_args(argv)
    rc = args.fn(args)
    if rc == 0:
        print(f"exec {args.label} ok")
    else:
        print(f"exec {args.label} FAILED", file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
