"""Execution layer: a deterministic replicated account/stake ledger.

Blocks stop being opaque digests (ROADMAP item 4): every committed
height carries a deterministic transaction block, applying it is one
padded device launch (ops/ledger.py — signature checks ride the
existing batch-verify drain via :class:`ExecApplyLauncher`, balance and
stake mutations are one segment-sum/scatter-add kernel), and the
resulting state root is chained into the commit value, so the commit
digest now covers the world state, not just the agreed bytes.

Import discipline mirrors ``parallel/``: this package root and
``ledger.py`` (the host reference executor) are jax-free — the chaos
soak and the serving layer use them without a device runtime —
while ``device.py`` pulls in the jnp kernel.
"""

from __future__ import annotations

from dataclasses import dataclass

__all__ = [
    "ExecutionConfig",
    "BlockSource",
    "HostLedgerExecutor",
    "ExecApplyLauncher",
]


@dataclass(frozen=True)
class ExecutionConfig:
    """One execution-layer deployment, fully determined by these ints
    (ScenarioRecord v7 persists exactly this tuple, so a dump replays
    the identical ledger trajectory with no stored state).

    ``accounts`` is the ledger width; accounts ``0..stake_accounts-1``
    double as the validator stake accounts the epoch elections read
    (the sim pins ``stake_accounts = n``). ``stake_every`` routes every
    K-th transaction to the stake lane (STAKE/UNSTAKE on a validator
    account); 0 disables stake churn. ``sign_txs`` attaches real
    Ed25519 signatures per transaction (checked through the batch
    verifier / devsched drain); ``bad_sig_every`` corrupts every K-th
    signature so the mask visibly rejects lanes. ``stake_floor`` is the
    election-time floor added to every ledger stake — see
    ROBUSTNESS.md "State-root doctrine" — so full unstaking reduces
    weight but never ejects a pool member from candidacy. ``device``
    selects the jnp apply kernel over the host reference executor
    (digest-identical either way; the parity smoke enforces it).
    """

    accounts: int = 64
    txs_per_block: int = 32
    stake_every: int = 4
    stake_accounts: int = 0
    seed: int = 0
    amount_cap: int = 128
    initial_balance: int = 1_000_000
    sign_txs: bool = False
    bad_sig_every: int = 0
    stake_floor: int = 1
    device: bool = False

    def __post_init__(self):
        if self.accounts < 1:
            raise ValueError("accounts must be >= 1")
        if self.txs_per_block < 1:
            raise ValueError("txs_per_block must be >= 1")
        if self.amount_cap < 1:
            raise ValueError("amount_cap must be >= 1")
        if self.stake_accounts < 0 or self.stake_accounts > self.accounts:
            raise ValueError("stake_accounts must be in [0, accounts]")
        if self.stake_floor < 0:
            raise ValueError("stake_floor must be >= 0")
        # int32 kernel headroom: one block's worst-case inflow into a
        # single account on top of the seeded balance must not wrap.
        # The executor re-asserts the cumulative bound as blocks land.
        if (
            self.initial_balance + self.txs_per_block * self.amount_cap
            >= 2**31
        ):
            raise ValueError(
                "initial_balance + txs_per_block * amount_cap must stay "
                "below 2**31 (int32 device kernel)"
            )

    def as_ints(self) -> tuple:
        """The record-trailer encoding (ScenarioRecord v7)."""
        return (
            self.accounts,
            self.txs_per_block,
            self.stake_every,
            self.stake_accounts,
            self.seed,
            self.amount_cap,
            self.initial_balance,
            int(self.sign_txs),
            self.bad_sig_every,
            self.stake_floor,
            int(self.device),
        )

    @classmethod
    def from_ints(cls, vals) -> "ExecutionConfig":
        vals = tuple(int(v) for v in vals)
        if len(vals) != 11:
            raise ValueError(
                f"execution trailer has {len(vals)} fields, expected 11"
            )
        return cls(
            accounts=vals[0],
            txs_per_block=vals[1],
            stake_every=vals[2],
            stake_accounts=vals[3],
            seed=vals[4],
            amount_cap=vals[5],
            initial_balance=vals[6],
            sign_txs=bool(vals[7]),
            bad_sig_every=vals[8],
            stake_floor=vals[9],
            device=bool(vals[10]),
        )


def __getattr__(name):
    # Lazy re-exports keep `import hyperdrive_tpu.exec` jax-free.
    if name in ("BlockSource", "HostLedgerExecutor", "ExecApplyLauncher"):
        from hyperdrive_tpu.exec import ledger

        return getattr(ledger, name)
    if name == "DeviceLedgerExecutor":
        from hyperdrive_tpu.exec import device

        return device.DeviceLedgerExecutor
    raise AttributeError(name)
