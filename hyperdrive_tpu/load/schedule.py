"""Deterministic open-loop arrival schedules.

An arrival schedule is a seeded, reproducible stream of absolute
arrival times (seconds from the schedule's epoch). Open-loop means the
stream is fixed up front: arrivals never wait on service, so offered
load can exceed capacity — the whole point of the overload harness.
Both generators (the sim injector and the wall-clock TCP firehose)
consume the same schedules, so "the same storm" can be replayed
against either path.

Everything draws from ``random.Random(seed)`` only — same seed, same
arrival times, byte for byte.
"""

from __future__ import annotations

import random

__all__ = ["PoissonSchedule", "BurstSchedule"]


class PoissonSchedule:
    """Memoryless arrivals at ``rate`` per second (exponential gaps).

    The classic open-loop model: each inter-arrival gap is an
    independent exponential draw with mean ``1/rate``, so short-term
    bursts well above the mean rate occur naturally — the traffic shape
    that makes fixed-capacity queues interesting.
    """

    def __init__(self, rate: float, seed: int = 0):
        if rate <= 0.0:
            raise ValueError(f"rate must be positive, got {rate}")
        self.rate = float(rate)
        self.seed = int(seed)

    def __iter__(self):
        # String seeds hash through SHA-512 inside random.seed — stable
        # across processes, unlike tuple seeding (deprecated) or hash().
        rng = random.Random(f"poisson:{self.seed}:{self.rate!r}")
        t = 0.0
        while True:
            t += rng.expovariate(self.rate)
            yield t

    def arrivals(self, horizon: float) -> list[float]:
        """Every arrival time in ``[0, horizon)``, ascending."""
        out: list[float] = []
        for t in self:
            if t >= horizon:
                break
            out.append(t)
        return out


class BurstSchedule:
    """``burst`` arrivals at once, every ``burst / rate`` seconds.

    The adversarial complement of Poisson smoothing: the same mean rate
    delivered as periodic spikes (a gossip storm, a reconnecting peer
    flushing its backlog). ``jitter`` perturbs each spike's position by
    up to that fraction of the period, seeded.
    """

    def __init__(self, rate: float, burst: int = 32, seed: int = 0,
                 jitter: float = 0.0):
        if rate <= 0.0:
            raise ValueError(f"rate must be positive, got {rate}")
        if burst < 1:
            raise ValueError(f"burst must be >= 1, got {burst}")
        if not 0.0 <= jitter < 1.0:
            raise ValueError(f"jitter must be in [0, 1), got {jitter}")
        self.rate = float(rate)
        self.burst = int(burst)
        self.seed = int(seed)
        self.jitter = float(jitter)

    def __iter__(self):
        rng = random.Random(f"burst:{self.seed}:{self.burst}")
        period = self.burst / self.rate
        k = 0
        while True:
            base = k * period
            if self.jitter:
                base += period * self.jitter * rng.random()
            for _ in range(self.burst):
                yield base
            k += 1

    def arrivals(self, horizon: float) -> list[float]:
        """Every arrival time in ``[0, horizon)``, ascending."""
        out: list[float] = []
        for t in self:
            if t >= horizon:
                break
            out.append(t)
        return out
