"""Open-loop load generators: the sim injector and the TCP firehose.

:class:`LoadProfile` is data — the sim interprets it
(``Simulation(load=...)``): arrivals from the profile's schedule are
checked against the virtual clock at every delivered vote, and each due
arrival re-delivers the current vote to its recipient as a gossip
duplicate. Injection is *trajectory-neutral by construction*: injected
deliveries consume no virtual time, no delivery steps, and no RNG
draws, so the real message schedule — timeouts, chaos faults, reorder
swaps — is bit-identical to the unloaded run, and because duplicates
are exactly what the Process dedups (and the admission gate sheds),
the committed chain digests equal too. That is the property the chaos
overload family asserts; what overload *costs* is measured on the wall
clock (the overload bench) and on the admission counters.

:class:`TcpLoadGenerator` is the real-socket path: a thread that fires
pre-encoded frames at :class:`~hyperdrive_tpu.transport.TcpNode`
listen ports on the wall clock, at the schedule's arrival times,
whether or not the node keeps up — open-loop by definition. When the
generator falls behind the schedule (the socket blocked), it does not
thin the offered load; the backlog drains as fast as the socket
allows, exactly like a real firehose peer.
"""

from __future__ import annotations

import socket
import threading
import time
from dataclasses import dataclass

from hyperdrive_tpu.load.backpressure import SHED_DUPLICATES
from hyperdrive_tpu.load.schedule import BurstSchedule, PoissonSchedule

__all__ = ["LoadProfile", "LoadRuntime", "TcpLoadGenerator"]


@dataclass(frozen=True)
class LoadProfile:
    """One open-loop overload scenario for the deterministic sim.

    ``rate`` is injected duplicate votes per *virtual* second between
    ``start`` and ``stop``; ``burst > 1`` switches the arrival process
    from Poisson to periodic spikes of that size. ``admission`` wires a
    :class:`~hyperdrive_tpu.load.backpressure.BackpressureController`
    (pinned at ``floor``) and per-replica admission gates onto the run;
    with it off, the same storm hits the raw Process-dedup path — the
    differential the overload bench measures. ``floor`` must stay in
    the behavior-neutral band (<= SHED_DUPLICATES) when the run's chain
    digest is compared against an unloaded baseline; the chaos family
    checks that invariant at construction.

    ``amp_cap`` bounds duplicates injected at one delivery point; when
    a virtual-clock jump makes more arrivals due at once, the excess
    stays due and drains at the next deliveries (offered load is never
    silently discarded).
    """

    rate: float
    burst: int = 1
    start: float = 0.0
    stop: float = float("inf")
    seed: int = 0
    admission: bool = True
    floor: int = SHED_DUPLICATES
    #: pin=True (digest-safe mode) holds the admission level AT the
    #: floor: live pressure signals are not coupled, so the level can
    #: never escalate into the trajectory-changing band mid-run.
    #: pin=False additionally watches the sim's device-work queue —
    #: depth/drain signals escalate freely (the bench's escalation
    #: exercise; digests may then diverge from an unloaded run).
    pin: bool = True
    amp_cap: int = 64

    def validate(self) -> None:
        if self.rate <= 0.0:
            raise ValueError(f"load rate must be positive, got {self.rate}")
        if self.burst < 1:
            raise ValueError(f"load burst must be >= 1, got {self.burst}")
        if not 0.0 <= self.start < self.stop:
            raise ValueError(
                f"load window [{self.start}, {self.stop}) is empty"
            )
        if self.amp_cap < 1:
            raise ValueError(f"amp_cap must be >= 1, got {self.amp_cap}")

    def schedule(self):
        if self.burst > 1:
            return BurstSchedule(self.rate, burst=self.burst, seed=self.seed)
        return PoissonSchedule(self.rate, seed=self.seed)

    @classmethod
    def seeded(cls, seed: int, *, rate: float = 2000.0) -> "LoadProfile":
        """The chaos overload family's profile draw: a deterministic
        storm shape from the scenario seed — Poisson or spiky, full-run
        window — always in the behavior-neutral admission band so the
        loaded run's chain must equal the unloaded baseline's."""
        import random

        rng = random.Random((seed << 1) ^ 0x4C4F4144)
        burst = rng.choice([1, 1, 16, 64])
        return cls(
            rate=rate * rng.uniform(0.5, 2.0),
            burst=burst,
            seed=seed,
            admission=True,
            floor=SHED_DUPLICATES,
        )


class LoadRuntime:
    """The sim-side interpreter state for one :class:`LoadProfile`:
    walks the schedule's arrival stream against the virtual clock."""

    def __init__(self, profile: LoadProfile):
        profile.validate()
        self.profile = profile
        self._arrivals = iter(profile.schedule())
        self._next = next(self._arrivals) + profile.start
        self._due = 0
        #: Total arrivals handed out (the run's offered injection count).
        self.offered = 0
        #: The subset of ``offered`` the admission gate is *expected* to
        #: shed: vote duplicates whose height had not advanced past the
        #: original delivery (the sim tallies this at the injection
        #: point). Duplicated proposals and votes re-delivered after the
        #: commit edge are admitted/height-filtered by doctrine, so a
        #: bursty storm landing only there legitimately sheds nothing.
        self.sheddable = 0

    def due(self, now: float) -> int:
        """Arrivals due at virtual time ``now``, capped at ``amp_cap``
        per call (the excess stays due for the next call)."""
        p = self.profile
        if now >= p.stop:
            self._due = 0
            return 0
        while self._next <= now:
            self._due += 1
            self._next = next(self._arrivals) + p.start
        n = min(self._due, p.amp_cap)
        self._due -= n
        self.offered += n
        return n


class TcpLoadGenerator:
    """Wall-clock open-loop frame firehose at real TcpNode ports.

    ``targets`` is a list of ``(host, port)`` listen addresses;
    ``frames`` a list of pre-encoded wire frames
    (:func:`~hyperdrive_tpu.transport.encode_frame` output) cycled
    round-robin — the caller decides what the storm is made of
    (duplicate prevotes for a behavior-neutral storm, fresh signed
    votes for a verification storm). One socket per target, dialed
    with bounded retries; a target that stays down just accumulates
    ``errors`` (open-loop: the storm does not care).
    """

    def __init__(
        self,
        targets,
        frames,
        schedule,
        *,
        duration: float = 1.0,
        time_fn=time.monotonic,
    ):
        if not frames:
            raise ValueError("frames must be non-empty")
        self.targets = list(targets)
        self.frames = list(frames)
        self.arrivals = schedule.arrivals(duration)
        self._time = time_fn
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        #: Frames written / write+connect failures / max scheduling lag
        #: observed (seconds the generator ran behind its own schedule —
        #: a lag far above 0 means the *sender host* saturated, worth
        #: knowing when reading offered-load numbers).
        self.sent = 0
        self.errors = 0
        self.behind_max = 0.0
        #: Wall time the schedule started at (set when the thread runs);
        #: arrival k was offered at ``t0 + arrivals[k]`` — the reference
        #: point latency probes measure against.
        self.t0 = None

    def start(self) -> "TcpLoadGenerator":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def join(self, timeout=None) -> None:
        self._thread.join(timeout)

    def _run(self) -> None:
        socks: dict = {}
        try:
            t0 = self.t0 = self._time()
            k = 0
            nf = len(self.frames)
            nt = len(self.targets)
            for at in self.arrivals:
                if self._stop.is_set():
                    return
                lag = (self._time() - t0) - at
                if lag < 0.0:
                    time.sleep(-lag)
                elif lag > self.behind_max:
                    self.behind_max = lag
                target = self.targets[k % nt]
                frame = self.frames[k % nf]
                k += 1
                sock = socks.get(target)
                if sock is None:
                    try:
                        sock = socket.create_connection(target, timeout=2.0)
                        sock.setsockopt(
                            socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
                        )
                        socks[target] = sock
                    except OSError:
                        self.errors += 1
                        continue
                try:
                    sock.sendall(frame)
                    self.sent += 1
                except OSError:
                    self.errors += 1
                    try:
                        sock.close()
                    except OSError:
                        pass
                    socks.pop(target, None)
        finally:
            for sock in socks.values():
                try:
                    sock.close()
                except OSError:
                    pass
