"""The overload soak driver (CI's ``overload-soak`` job).

Usage::

    python -m hyperdrive_tpu.load soak [--scenarios N] [--seed S]
        [--n N_REPLICAS] [--target H] [--rate R] [--out DIR]
        [--p99-factor F] [--escalate-every K]

Each scenario pushes an open-loop duplicate storm through the
deterministic harness and asserts the overload doctrine end-to-end
(ROBUSTNESS.md "Overload doctrine"):

``baseline``
    unloaded, certificates on, observed — the reference chain and the
    reference commit-latency anatomy.

``pinned``
    the same run plus the storm, admission spine pinned in the
    behavior-neutral band. Must commit the byte-identical chain (no
    fork, same digests), mint the same certificates (certificates are
    never shed), shed only ``duplicate``/``stale_height``, and keep
    the admission accounting identity exact
    (offered == admitted + shed).

``escalation`` (every ``--escalate-every``-th scenario)
    the same storm with ``pin`` off and the device-work queue watched,
    so live depth/drain signals escalate the level freely. The chain
    may differ from baseline (fresh prevotes become sheddable) but
    safety must hold, the run must still complete, and the
    admitted-work commit p99 must stay within ``--p99-factor`` of the
    baseline's — graceful degradation, not collapse.

Scenarios run unsigned and accelerator-free (no jax import on the hot
path). HD_SANITIZE=1 in the environment arms the runtime sanitizer on
every replica — CI runs the soak that way.
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import sys

from hyperdrive_tpu.harness.sim import Simulation
from hyperdrive_tpu.load.generator import LoadProfile
from hyperdrive_tpu.obs.report import anatomy

#: Spread scenario seeds so adjacent indices explore unrelated storms
#: (same stride as the chaos soak, so seed N here and there relate).
_SEED_STRIDE = 9973

#: Shed classes allowed in the behavior-neutral (digest-safe) band.
_NEUTRAL = {"duplicate", "stale_height"}


class SoakViolation(AssertionError):
    """One overload-doctrine invariant failed."""


def _p99(result_events) -> "float | None":
    vals = sorted(
        r["total_s"] for r in anatomy(result_events)
        if r["total_s"] is not None
    )
    if not vals:
        return None
    return vals[min(len(vals) - 1, int(0.99 * len(vals)))]


def _build(scen_seed: int, n: int, target: int, max_steps: int,
           load=None, escalate: bool = False):
    extra = {"certificates": True}
    if load is not None:
        extra["load"] = load
    if escalate:
        # The escalation leg watches a REAL device-work queue: settles
        # flush through it, depth/drain feed the controller. max_depth
        # sits above the low-priority threshold (64) so pressure can
        # actually cross it before the auto-drain relieves the queue.
        from hyperdrive_tpu.devsched import DeviceWorkQueue, QueueFlusher
        from hyperdrive_tpu.verifier import NullVerifier

        queue = DeviceWorkQueue(max_depth=96)
        extra["devsched"] = queue
        extra["flusher_for"] = lambda i, validators: QueueFlusher(
            NullVerifier(), queue
        )
    sim = Simulation(
        n=n,
        target_height=target,
        seed=scen_seed,
        timeout=1.0,
        delivery_cost=1e-3,
        observe=True,
        **extra,
    )
    return sim


def _check_accounting(snap) -> None:
    shed_total = sum(snap["shed"].values())
    if snap["offered"] != snap["admitted"] + shed_total:
        raise SoakViolation(
            "admission accounting broken: offered "
            f"{snap['offered']} != admitted {snap['admitted']} "
            f"+ shed {shed_total}"
        )


def _check_certs_intact(base_sim, loaded_sim) -> None:
    """Certificates-never-shed, asserted structurally: the loaded run
    minted exactly the certificates the unloaded run did."""
    for i, (bc, lc) in enumerate(
        zip(base_sim.certifiers, loaded_sim.certifiers)
    ):
        if set(bc.certs) != set(lc.certs):
            raise SoakViolation(
                f"replica {i} certificate set diverged under load: "
                f"{sorted(set(bc.certs) ^ set(lc.certs))}"
            )


def _dump_failure(out: str, scen_seed: int, sim, err) -> str:
    os.makedirs(out, exist_ok=True)
    base = os.path.join(out, f"overload_seed_{scen_seed}")
    record = getattr(sim, "record", None)
    if record is not None:
        record.dump(base + ".bin")
    sim.obs.save(base + ".journal.json")
    with open(base + ".txt", "w") as fh:
        fh.write(f"seed={scen_seed}\nviolation={err}\n")
    return base


def soak(args) -> int:
    failures = 0
    for k in range(args.scenarios):
        scen_seed = args.seed + k * _SEED_STRIDE
        profile = LoadProfile.seeded(scen_seed, rate=args.rate)
        base_sim = _build(scen_seed, args.n, args.target, args.max_steps)
        sim = base_sim
        try:
            base = base_sim.run(max_steps=args.max_steps)
            base.assert_safety()
            base_p99 = _p99(base_sim.obs.snapshot())

            # ---- pinned leg: behavior-neutral storm, identical chain
            sim = _build(
                scen_seed, args.n, args.target, args.max_steps,
                load=profile,
            )
            res = sim.run(max_steps=args.max_steps)
            res.assert_safety()
            if res.commit_digest() != base.commit_digest():
                raise SoakViolation(
                    "pinned overload run forked from the unloaded chain"
                )
            _check_certs_intact(base_sim, sim)
            snap = sim.overload_snapshot()
            _check_accounting(snap)
            # Only vote duplicates at un-advanced heights are the
            # gate's guaranteed prey; a storm landing solely on
            # proposal deliveries or behind the commit edge is
            # admitted/height-filtered by doctrine and sheds nothing.
            if snap["injected_sheddable"] and not snap["shed"]:
                raise SoakViolation(
                    "sheddable storm injected but admission shed nothing"
                )
            bad = set(snap["shed"]) - _NEUTRAL
            if bad:
                raise SoakViolation(
                    f"behavior-neutral run shed classes {sorted(bad)}"
                )
            p99 = _p99(sim.obs.snapshot())
            if (
                base_p99 is not None
                and p99 is not None
                and p99 > base_p99 * args.p99_factor
            ):
                raise SoakViolation(
                    f"pinned admitted-work p99 {p99:.4f}s blew past "
                    f"{args.p99_factor}x baseline {base_p99:.4f}s"
                )
            print(
                f"ok seed={scen_seed} injected={snap['injected']} "
                f"shed={snap['shed']} p99={p99 if p99 is None else round(p99, 4)}"
            )

            # ---- escalation leg: live signals, graceful degradation
            if args.escalate_every and k % args.escalate_every == 0:
                esc_profile = dataclasses.replace(profile, pin=False)
                sim = _build(
                    scen_seed, args.n, args.target, args.max_steps,
                    load=esc_profile, escalate=True,
                )
                eres = sim.run(max_steps=args.max_steps)
                eres.assert_safety()
                if not eres.completed:
                    raise SoakViolation(
                        "escalation run collapsed: target height never "
                        "reached under load"
                    )
                esnap = sim.overload_snapshot()
                _check_accounting(esnap)
                ep99 = _p99(sim.obs.snapshot())
                if (
                    base_p99 is not None
                    and ep99 is not None
                    and ep99 > base_p99 * args.p99_factor
                ):
                    raise SoakViolation(
                        f"escalation admitted-work p99 {ep99:.4f}s blew "
                        f"past {args.p99_factor}x baseline "
                        f"{base_p99:.4f}s"
                    )
                print(
                    f"ok escalation seed={scen_seed} "
                    f"level<={esnap['level']} "
                    f"transitions={esnap['transitions']} "
                    f"shed={esnap['shed']} "
                    f"p99={ep99 if ep99 is None else round(ep99, 4)}"
                )
        except AssertionError as err:
            failures += 1
            base_path = _dump_failure(args.out, scen_seed, sim, err)
            print(
                f"FAIL seed={scen_seed} {err}\n"
                f"  dumped {base_path}.journal.json (+ record)",
                file=sys.stderr,
            )
            if not args.keep_going:
                return 1
            continue
    if failures:
        print(f"soak FAILED: {failures}/{args.scenarios}", file=sys.stderr)
        return 1
    print(f"overload soak ok: {args.scenarios} scenarios, 0 violations")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m hyperdrive_tpu.load")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser(
        "soak", help="run N seeded overload scenarios (CI overload-soak)"
    )
    p.add_argument("--scenarios", type=int, default=6)
    p.add_argument("--seed", type=int, default=1)
    p.add_argument("--n", type=int, default=4)
    p.add_argument("--target", type=int, default=6)
    p.add_argument("--rate", type=float, default=3000.0,
                   help="nominal storm rate (duplicates per virtual s)")
    p.add_argument("--max-steps", type=int, default=500_000)
    p.add_argument("--out", default="load_failures")
    p.add_argument(
        "--p99-factor", type=float, default=3.0,
        help="admitted-work commit p99 must stay within this multiple "
        "of the unloaded baseline's",
    )
    p.add_argument(
        "--escalate-every", type=int, default=2,
        help="run every Kth scenario unpinned with the device queue "
        "watched, asserting graceful degradation (0 = off)",
    )
    p.add_argument("--keep-going", action="store_true")
    p.set_defaults(fn=soak)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
