"""Shared frame classification: one vocabulary for every ingress.

Two independent paths judge an arriving consensus frame before any
protocol logic sees it: the :class:`~hyperdrive_tpu.load.backpressure.
AdmissionGate` (overload shedding) and the overlay contribution scorer
(:mod:`hyperdrive_tpu.overlay` — charging peers that relay duplicate or
stale-generation votes). Before this module each re-implemented the
duplicate / stale-height / stale-generation predicates locally, and the
two could drift — a frame the gate called a duplicate could score as
fresh coverage, silently rewarding replay spam. :func:`classify_frame`
is now the single source of truth; both callers map its verdicts onto
their own policies (shed vs. charge), never re-deriving them.

The classes form a closed vocabulary (mirroring ``SHED_CLASSES``):

``FRESH``
    admit / credit — first sighting of a live vote (or a never-shed
    kind: proposals and non-vote frames carry no dedup key at all).
``DUPLICATE``
    this ingress already saw the exact (type, sender, height, round,
    value) key.
``STALE_HEIGHT``
    the consumer's height has moved past the vote (the replica's
    height filter would drop it anyway).
``STALE_GENERATION``
    signed under an identity retired by an epoch rotation at or before
    the frame's height (epochs.py key retirement) — checked FIRST and
    for every message kind, because a retired key is invalid regardless
    of freshness.
``QUERY``
    a read-path proof query (:class:`QueryFrame` — the service port's
    TAG_QUERY ingress). Always sheddable at SHED_LOW_PRIORITY and
    above: reads are idempotent and retryable, so a read storm must
    never displace consensus traffic — certificates and precommits
    outrank queries by doctrine.
"""

from __future__ import annotations

from dataclasses import dataclass

from hyperdrive_tpu.messages import Precommit, Prevote, Propose

__all__ = [
    "FRESH",
    "DUPLICATE",
    "STALE_HEIGHT",
    "STALE_GENERATION",
    "QUERY",
    "FRAME_CLASSES",
    "QueryFrame",
    "MetricsFrame",
    "classify_frame",
]

FRESH = "fresh"
DUPLICATE = "duplicate"
STALE_HEIGHT = "stale_height"
STALE_GENERATION = "stale_generation"
QUERY = "query"

#: The closed classification vocabulary, in check order.
FRAME_CLASSES = (STALE_GENERATION, QUERY, STALE_HEIGHT, DUPLICATE, FRESH)


@dataclass(frozen=True)
class QueryFrame:
    """One proof query at an admission gate: the lightweight stand-in
    the service port classifies before any ledger work happens. Carries
    no sender identity (stateless clients are anonymous to the gate —
    fairness attribution uses the connection's tenant as ``peer``)."""

    account: int
    height: int = -1
    round: int = -1
    sender: bytes | None = None


@dataclass(frozen=True)
class MetricsFrame:
    """One live-metrics scrape at an admission gate: the service
    port's TAG_METRICS ingress. Classified WITH proof queries (QUERY)
    — a scrape is an idempotent, retryable read, and the
    observability plane must be the first thing shed under load,
    never a reason consensus traffic queues."""

    height: int = -1
    round: int = -1
    sender: bytes | None = None


#: Message-type tags for dedup keys (stable across runs, unlike id()).
_TAG = {Propose: 0, Prevote: 1, Precommit: 2}


def classify_frame(msg, *, seen=None, height_fn=None, retired=None):
    """Classify one frame; returns ``(cls, key)``.

    ``seen`` is the caller's dedup memory (any container supporting
    ``in`` over keys), ``height_fn`` supplies the consumer's current
    height, ``retired`` maps retired signatory -> first stale height
    (the sim / TcpNode shared retirement bound). Each is optional —
    an unsupplied signal simply never triggers its class, so callers
    opt into exactly the checks their ingress owns.

    ``key`` is the stable dedup key ``(tag, sender, height, round,
    value)`` for vote frames, or None for never-shed kinds (proposals,
    certificates, unknown types) — those classify FRESH by doctrine
    (aggregates outrank raw votes; there is exactly one legitimate
    proposal per round) and have nothing to remember.
    """
    sender = getattr(msg, "sender", None)
    if retired and sender is not None:
        bad_from = retired.get(sender)
        if bad_from is not None and getattr(msg, "height", -1) >= bad_from:
            return STALE_GENERATION, None
    t = type(msg)
    if t is QueryFrame:
        # Reads carry a key (so the gate treats them as sheddable) but
        # are never deduplicated: an identical re-query after a shed is
        # the client doing exactly what the retry doctrine tells it to.
        return QUERY, ("query", msg.account)
    if t is MetricsFrame:
        # Metrics scrapes are the same read-path class: sheddable
        # first, never deduplicated (a re-scrape after a shed is the
        # scraper's retry loop working as designed).
        return QUERY, ("metrics",)
    tag = _TAG.get(t)
    if tag is None or t is Propose:
        return FRESH, None
    key = (tag, sender, msg.height, msg.round, msg.value)
    if height_fn is not None and msg.height < height_fn():
        return STALE_HEIGHT, key
    if seen is not None and key in seen:
        return DUPLICATE, key
    return FRESH, key
