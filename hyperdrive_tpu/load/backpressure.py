"""The backpressure spine: admission levels, classification, shedding.

Overload handling before this module was three disconnected fragments:
TcpNode shed the oldest frame on per-peer queue overflow, the device
work queue auto-drained at ``max_depth`` with no signal back to
admission, and nothing distinguished a duplicate low-value vote from an
irreplaceable proposal when deciding what to drop. This module is the
connective tissue: a :class:`BackpressureController` turns pipeline
signals (device-queue depth, drain latency, peer send-queue occupancy)
into one admission level, and an :class:`AdmissionGate` applies that
level at every ingress (wire delivery, broadcast, mq insert, replica
buffering) with a fixed shed-class doctrine.

Admission levels (escalating)::

    ACCEPT             everything admitted
    SHED_DUPLICATES    exact duplicates and stale-height votes shed
    SHED_LOW_PRIORITY  + fresh prevotes from over-share peers shed
                         (per-peer fairness: a firehose peer cannot
                         starve the rest)
    CRITICAL_ONLY      + every fresh prevote shed; only proposals,
                         precommits, and certificates flow

Never shed, at any level: proposals (irreplaceable — there is exactly
one legitimate proposal per round), precommits (quorum-forming), and
certificates / unknown message types (aggregates outrank raw votes —
arXiv:1911.04698's shed policy). The first two levels are
*behavior-neutral*: the Process dedups votes and the replica
height-filters stale ones, so a run shedding only those classes commits
a byte-identical chain to the unloaded run — the chaos overload family
asserts exactly that. CRITICAL_ONLY trades prevote liveness for
survival and is the transient panic level; safety is never at stake
(shedding inputs is indistinguishable from message loss, which the
protocol tolerates by design).

De-escalation is hysteretic: the level steps down only after
``hysteresis`` consecutive clean polls, so a queue oscillating around a
threshold does not flap the gate.
"""

from __future__ import annotations

import threading

from hyperdrive_tpu.load.frames import FRESH, QUERY, classify_frame
from hyperdrive_tpu.messages import Prevote
from hyperdrive_tpu.obs.recorder import NULL_BOUND

__all__ = [
    "ACCEPT",
    "SHED_DUPLICATES",
    "SHED_LOW_PRIORITY",
    "CRITICAL_ONLY",
    "LEVEL_NAMES",
    "SHED_CLASSES",
    "REPUTATION_WEIGHTS",
    "BackpressureController",
    "SignerReputation",
    "AdmissionGate",
]

ACCEPT = 0
SHED_DUPLICATES = 1
SHED_LOW_PRIORITY = 2
CRITICAL_ONLY = 3

#: Level index -> stable wire/report name.
LEVEL_NAMES = ("accept", "shed_duplicates", "shed_low_priority",
               "critical_only")

#: The closed shed-class vocabulary (ROBUSTNESS.md "Overload doctrine").
#: ``duplicate`` / ``stale_height`` are behavior-neutral; ``low_priority``
#: / ``panic`` trade prevote liveness for survival; ``query`` is the
#: read path — proof queries shed from SHED_LOW_PRIORITY up, always
#: ahead of any consensus frame (reads are idempotent and retryable, so
#: a read storm must never starve certificates); ``reputation`` is the
#: economic path — prevotes from signers whose signatures keep FAILING
#: device batch verify shed at EVERY level once the signer is demoted
#: (ROBUSTNESS.md "Adversarial economy"). There is deliberately no
#: class for proposals, precommits, or certificates — they are never
#: shed, and the soak asserts the counters for them stay absent.
SHED_CLASSES = ("duplicate", "stale_height", "low_priority", "panic",
                "query", "reputation")

#: Integer reputation deltas, mirroring the overlay's CHARGE_WEIGHTS
#: (overlay/score.py): ``verify_failed`` is the expensive verdict — the
#: frame passed every cheap admission check and died at device batch
#: verify, so it outweighs everything else the gate can observe.
REPUTATION_WEIGHTS = {"verify_failed": 6, "shed_while_demoted": 0}

# Classification (duplicate / stale detection and the dedup key shape)
# is shared with the overlay contribution scorer through
# load/frames.classify_frame — the two ingress paths must never drift
# on what counts as a duplicate or a stale frame.


class BackpressureController:
    """Fuses pipeline pressure signals into one admission level.

    Signals (each optional — unsupplied signals simply never escalate):

    - **device-queue depth** — :class:`~hyperdrive_tpu.devsched.queue.
      DeviceWorkQueue` pushes its depth on every submit and its drain
      wall time on every drain once ``queue.controller`` is set (or
      call :meth:`watch`).
    - **drain latency** — seconds one coalesced drain took; a pipeline
      that still drains fast can absorb a deep queue, so latency gates
      the two upper levels rather than depth alone.
    - **peer send-queue occupancy** — worst per-peer outbound backlog
      as a fraction of capacity (TcpNode reports it on every shed-path
      broadcast).

    The level is the max over the per-signal levels, plus an optional
    ``floor`` (the sim's deterministic overload profiles pin the floor
    instead of modeling device time, keeping fixed-seed digests exact).
    Escalation is immediate; de-escalation needs ``hysteresis``
    consecutive polls that all map below the current level.
    """

    def __init__(
        self,
        queue=None,
        *,
        depth_duplicates: int = 8,
        depth_low_priority: int = 64,
        depth_critical: int = 256,
        drain_latency_s: float = 0.25,
        occupancy_low_priority: float = 0.5,
        occupancy_critical: float = 0.9,
        hysteresis: int = 3,
        registry=None,
        obs=None,
        time_fn=None,
        threadsafe: bool = False,
    ):
        self.depth_duplicates = int(depth_duplicates)
        self.depth_low_priority = int(depth_low_priority)
        self.depth_critical = int(depth_critical)
        self.drain_latency_s = float(drain_latency_s)
        self.occupancy_low_priority = float(occupancy_low_priority)
        self.occupancy_critical = float(occupancy_critical)
        self.hysteresis = max(1, int(hysteresis))
        self.registry = registry
        self.obs = obs if obs is not None else NULL_BOUND
        #: Clock for drain-latency timing in the watched queue (the sim
        #: passes its virtual clock; real deployments time.monotonic).
        #: None keeps drain latency at 0.0 — depth and occupancy still
        #: escalate, and fixed-seed runs stay wall-clock-free.
        self.time_fn = time_fn
        self._lock = threading.Lock() if threadsafe else None
        #: Pinned minimum level (load profiles / tests); raw signals can
        #: escalate above the floor but never de-escalate below it.
        self.floor = ACCEPT
        self.level = ACCEPT
        #: Level transitions (escalations + de-escalations), for tests
        #: and the overload report.
        self.transitions = 0
        self._depth = 0
        self._drain_s = 0.0
        self._occupancy = 0.0
        self._calm = 0
        if queue is not None:
            self.watch(queue)

    def watch(self, queue) -> None:
        """Attach to a DeviceWorkQueue: its submit/drain paths push
        depth and drain-latency signals here from then on."""
        queue.controller = self

    # ------------------------------------------------------------ signals

    def note_depth(self, depth: int) -> None:
        lock = self._lock
        if lock is None:
            self._depth = depth
            self._update()
        else:
            with lock:
                self._depth = depth
                self._update()

    def note_drain(self, resolved: int, latency_s: float) -> None:
        lock = self._lock
        if lock is None:
            self._drain_s = latency_s
            self._depth = 0
            self._update()
        else:
            with lock:
                self._drain_s = latency_s
                self._depth = 0
                self._update()

    def note_peer_occupancy(self, fraction: float) -> None:
        """Worst outbound peer-queue occupancy in [0, 1]."""
        lock = self._lock
        if lock is None:
            self._occupancy = fraction
            self._update()
        else:
            with lock:
                self._occupancy = fraction
                self._update()

    def poll(self) -> int:
        """Recompute (hysteresis advances on clean polls); returns the
        current level."""
        lock = self._lock
        if lock is None:
            self._update()
        else:
            with lock:
                self._update()
        return self.level

    # ------------------------------------------------------------ fusion

    def _raw_level(self) -> int:
        level = self.floor
        d = self._depth
        if d >= self.depth_critical:
            level = max(level, CRITICAL_ONLY)
        elif d >= self.depth_low_priority:
            level = max(level, SHED_LOW_PRIORITY)
        elif d >= self.depth_duplicates:
            level = max(level, SHED_DUPLICATES)
        if self._drain_s >= self.drain_latency_s:
            level = max(level, SHED_LOW_PRIORITY)
        occ = self._occupancy
        if occ >= self.occupancy_critical:
            level = max(level, CRITICAL_ONLY)
        elif occ >= self.occupancy_low_priority:
            level = max(level, SHED_LOW_PRIORITY)
        return level

    def _update(self) -> None:
        raw = self._raw_level()
        if raw > self.level:
            self._set(raw)
            self._calm = 0
        elif raw < self.level:
            self._calm += 1
            if self._calm >= self.hysteresis:
                self._set(raw)
                self._calm = 0
        else:
            self._calm = 0

    def _set(self, level: int) -> None:
        self.level = level
        self.transitions += 1
        if self.registry is not None:
            self.registry.set_gauge("admission.level", level)
            self.registry.count("admission.transitions")
        if self.obs is not NULL_BOUND:
            self.obs.emit("admission.level", -1, -1, LEVEL_NAMES[level])


class SignerReputation:
    """Per-signer verify-failure reputation: the admission gate's
    economic memory (ROBUSTNESS.md "Adversarial economy").

    A forged-but-well-formed signature passes every cheap admission
    check and dies only at device batch verify — the most expensive
    verdict in the pipeline. This table closes the loop: the drain path
    reports each signer's per-row verify outcome back here
    (:meth:`AdmissionGate.note_verify`), repeat offenders cross
    ``demote_at`` and their SUBSEQUENT prevotes shed at the gate — at
    every admission level — under the ``reputation`` class, before the
    verifier ever sees them.

    The mechanism deliberately mirrors the overlay's
    :class:`~hyperdrive_tpu.overlay.score.ContributionScores`: integer
    arithmetic only (scores feed shed decisions, which feed digests),
    demotion at a threshold above a clamping floor so debt stays
    repayable, per-commit amnesty (:meth:`rehabilitate`) so no verdict
    is forever, and recovery credit for verified signatures. The
    doctrine asymmetry carries over too: an attacker re-earns its debt
    6 per failed row while amnesty forgives 1 per committed height.
    Scope is narrower than the overlay's advisory demotion, on purpose:
    only PREVOTES are reputation-shed — proposals, precommits and
    certificates stay never-shed, so a mis-charged honest signer loses
    redundant-vote bandwidth, never safety-critical reach.
    """

    def __init__(
        self,
        *,
        credit: int = 1,
        demote_at: int = -8,
        floor: int = -64,
        registry=None,
        obs=None,
    ):
        if demote_at <= floor:
            raise ValueError("demote_at must sit above the score floor")
        self.credit_per_verify = int(credit)
        self.demote_at = int(demote_at)
        self.floor = int(floor)
        self.registry = registry
        self.obs = obs if obs is not None else NULL_BOUND
        #: peer -> integer score (absent = 0). Peers are whatever the
        #: gate attributes frames to: validator indices in the campaign
        #: engines, signatory bytes at a real transport ingress.
        self.scores: dict = {}
        self.demoted: set = set()
        self.demotions = 0
        self.recoveries = 0
        #: class -> total charges (REPUTATION_WEIGHTS keys only).
        self.charges = {k: 0 for k in REPUTATION_WEIGHTS}
        #: peer -> charge count, the per-peer view metrics export.
        self.charges_by_peer: dict = {}

    def charge(self, peer, cls: str = "verify_failed") -> int:
        """Debit ``peer`` for one failed verify row; clamps at the
        floor so a long storm stays repayable in bounded credit."""
        weight = REPUTATION_WEIGHTS[cls]
        self.charges[cls] += 1
        self.charges_by_peer[peer] = self.charges_by_peer.get(peer, 0) + 1
        s = max(self.floor, self.scores.get(peer, 0) - weight)
        self.scores[peer] = s
        if self.registry is not None:
            self.registry.count("admission.reputation.charges", label=cls)
        if self.obs is not NULL_BOUND:
            self.obs.emit("admission.reputation.charge", -1, -1, cls)
        if s <= self.demote_at and peer not in self.demoted:
            self.demoted.add(peer)
            self.demotions += 1
            if self.registry is not None:
                self.registry.count("admission.reputation.demotions")
                self.registry.set_gauge(
                    "admission.reputation.demoted", len(self.demoted)
                )
            if self.obs is not NULL_BOUND:
                self.obs.emit(
                    "admission.reputation.demote", -1, -1, _peer_label(peer)
                )
        return s

    def credit(self, peer, rows: int = 1) -> int:
        """Reward ``peer`` for ``rows`` signatures that VERIFIED —
        the recovery path out of demotion."""
        if rows <= 0:
            return self.scores.get(peer, 0)
        s = min(0, self.scores.get(peer, 0) + self.credit_per_verify * rows)
        self.scores[peer] = s
        self._maybe_recover(peer, s)
        return s

    def rehabilitate(self, amount: int = 1) -> None:
        """Per-commit amnesty: pull every debt ``amount`` toward zero.
        Bounds how long any verdict stays on the books — an attacker
        that stops forging eventually sheds its demotion, exactly like
        the overlay's per-height rehabilitation."""
        if amount <= 0:
            return
        for peer in list(self.scores):
            s = self.scores[peer]
            if s >= 0:
                continue
            s = min(0, s + amount)
            self.scores[peer] = s
            self._maybe_recover(peer, s)

    def _maybe_recover(self, peer, s: int) -> None:
        if peer in self.demoted and s > self.demote_at:
            self.demoted.discard(peer)
            self.recoveries += 1
            if self.registry is not None:
                self.registry.count("admission.reputation.recoveries")
                self.registry.set_gauge(
                    "admission.reputation.demoted", len(self.demoted)
                )
            if self.obs is not NULL_BOUND:
                self.obs.emit(
                    "admission.reputation.recover", -1, -1, _peer_label(peer)
                )

    def is_demoted(self, peer) -> bool:
        return peer in self.demoted

    def snapshot(self) -> dict:
        return {
            "demoted": sorted(self.demoted, key=_peer_label),
            "demotions": self.demotions,
            "recoveries": self.recoveries,
            "charges": dict(self.charges),
            "min": min(self.scores.values()) if self.scores else 0,
        }


def _peer_label(peer) -> str:
    """Stable short label for a peer key (int index or signatory
    bytes) — the one rendering metrics labels, journal details and
    snapshots share, so the three views join on equal strings."""
    if isinstance(peer, (bytes, bytearray, memoryview)):
        return bytes(peer)[:4].hex()
    return str(peer)


class AdmissionGate:
    """Classify one message against the controller's level and decide
    admit/shed. One gate per ingress point (a TcpNode, a replica);
    gates share a controller, never dedup memory — duplicate detection
    is a local property of what *this* ingress already saw.

    ``height_fn`` supplies the consumer's current height so below-height
    votes classify as stale (they would be dropped by the replica's
    height filter anyway — shedding them earlier is behavior-neutral
    and saves the decode/buffer work). ``peer`` attribution on
    :meth:`admit` feeds per-peer fairness at SHED_LOW_PRIORITY; callers
    without transport-level peer identity fall back to the sender.

    ``reputation`` (optional) attaches a :class:`SignerReputation`:
    the drain path reports per-row verify outcomes via
    :meth:`note_verify`, and prevotes from demoted signers shed under
    the ``reputation`` class at EVERY level — the feedback loop that
    moves repeat forgers from the expensive post-verify shed to the
    cheap pre-verify one.
    """

    def __init__(
        self,
        controller: BackpressureController,
        *,
        height_fn=None,
        dedup_capacity: int = 65536,
        fair_window: int = 1024,
        fair_share: float = 0.5,
        reputation: "SignerReputation | None" = None,
        registry=None,
        obs=None,
        threadsafe: bool = False,
    ):
        self.controller = controller
        self.height_fn = height_fn
        self.dedup_capacity = int(dedup_capacity)
        self.fair_window = max(1, int(fair_window))
        self.fair_share = float(fair_share)
        self.reputation = reputation
        self.registry = registry
        self.obs = obs if obs is not None else NULL_BOUND
        self._lock = threading.Lock() if threadsafe else None
        #: Insertion-ordered dedup memory: vote key -> None, FIFO-evicted
        #: at ``dedup_capacity`` (a bounded bloom-like memory, exact
        #: within the window).
        self._mem: dict = {}
        #: peer -> admitted count inside the current fairness window.
        self._fair: dict = {}
        self._fair_seen = 0
        self.offered = 0
        self.admitted = 0
        #: shed-class name -> count. Only SHED_CLASSES names ever appear.
        self.shed: dict = {}
        #: peer -> total sheds attributed to that peer (any class).
        self.shed_by_peer: dict = {}
        #: peer -> rows of that peer's signatures batch verify REJECTED
        #: (the post-verify shed cost the reputation loop exists to cut).
        self.verify_failed_by_peer: dict = {}

    # ------------------------------------------------------------- admit

    def admit(self, msg, peer=None) -> bool:
        lock = self._lock
        if lock is None:
            return self._admit(msg, peer)
        with lock:
            return self._admit(msg, peer)

    def _admit(self, msg, peer) -> bool:
        self.offered += 1
        cls, key = classify_frame(
            msg, seen=self._mem, height_fn=self.height_fn
        )
        # Never-shed invariant: proposals, and anything that is not one
        # of the three vote types (certificates, resets, future message
        # kinds), classify keyless and pass at every level. Aggregates
        # outrank raw votes.
        if key is None:
            self._admitted()
            return True
        level = self.controller.level
        if cls is QUERY:
            # Read path: queries are the FIRST sheddable class once
            # load crosses SHED_LOW_PRIORITY — before any fresh vote,
            # and always before certificates (which classify keyless
            # above and never reach here). Admitted queries are not
            # remembered: reads dedup to nothing and must not evict
            # vote keys from the bounded memory.
            if level >= SHED_LOW_PRIORITY:
                return self._shed(msg, "query", peer)
            self._admitted()
            return True
        if level >= SHED_DUPLICATES and cls is not FRESH:
            # cls is the shed class verbatim: the classifier's closed
            # vocabulary intersects SHED_CLASSES on exactly the two
            # behavior-neutral classes the gate polices.
            return self._shed(msg, cls, peer)
        if type(msg) is Prevote:
            who = peer if peer is not None else msg.sender
            rep = self.reputation
            if rep is not None and rep.is_demoted(who):
                # The economic shed: level-independent (a demoted
                # forger is expensive at ANY load) and prevote-only
                # (proposals / precommits / certificates stay
                # never-shed, so a mis-charge costs redundant votes,
                # never quorum reach).
                rep.charges["shed_while_demoted"] += 1
                return self._shed(msg, "reputation", who)
            if level >= CRITICAL_ONLY:
                return self._shed(msg, "panic", who)
            if level >= SHED_LOW_PRIORITY:
                budget = max(1, int(self.fair_share * self.fair_window))
                if self._fair.get(who, 0) >= budget:
                    return self._shed(msg, "low_priority", who)
                self._fair_note(who)
        self._remember(key)
        self._admitted()
        return True

    def note_verify(self, peer, ok: bool, rows: int = 1) -> None:
        """Batch-verify feedback for ``rows`` of ``peer``'s signatures:
        the drain loop calls this per (signer, verdict) after the
        device/host verifier resolves a window. Failures charge the
        attached reputation (and count toward the per-peer post-verify
        cost the loop exists to cut); successes repay debt."""
        if not ok:
            self.verify_failed_by_peer[peer] = (
                self.verify_failed_by_peer.get(peer, 0) + rows
            )
            if self.registry is not None:
                self.registry.count(
                    "admission.verify_failed", rows, label=_peer_label(peer)
                )
            if self.reputation is not None:
                for _ in range(rows):
                    self.reputation.charge(peer, "verify_failed")
        elif self.reputation is not None:
            self.reputation.credit(peer, rows)

    # ---------------------------------------------------------- plumbing

    def _remember(self, key) -> None:
        mem = self._mem
        if key not in mem:
            mem[key] = None
            if len(mem) > self.dedup_capacity:
                mem.pop(next(iter(mem)))

    def _fair_note(self, who) -> None:
        self._fair_seen += 1
        if self._fair_seen >= self.fair_window:
            self._fair.clear()
            self._fair_seen = 0
        self._fair[who] = self._fair.get(who, 0) + 1

    def _admitted(self) -> None:
        self.admitted += 1
        if self.registry is not None:
            self.registry.count("admission.offered")
            self.registry.count("admission.admitted")

    def _shed(self, msg, cls: str, peer=None) -> bool:
        self.shed[cls] = self.shed.get(cls, 0) + 1
        if peer is not None:
            self.shed_by_peer[peer] = self.shed_by_peer.get(peer, 0) + 1
        if self.registry is not None:
            self.registry.count("admission.offered")
            self.registry.count("admission.shed", label=cls)
            if peer is not None:
                self.registry.count(
                    "admission.shed_by_peer", label=_peer_label(peer)
                )
        if self.obs is not NULL_BOUND:
            self.obs.emit(
                "admission.shed", msg.height, getattr(msg, "round", -1), cls
            )
        return False

    def snapshot(self) -> dict:
        """Counter view for soak assertions and the overload report."""
        snap = {
            "offered": self.offered,
            "admitted": self.admitted,
            "shed": dict(self.shed),
            "level": self.controller.level,
            "shed_by_peer": dict(self.shed_by_peer),
            "verify_failed_by_peer": dict(self.verify_failed_by_peer),
        }
        if self.reputation is not None:
            snap["reputation"] = self.reputation.snapshot()
        return snap
