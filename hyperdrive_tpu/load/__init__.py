"""Open-loop load generation and the backpressure spine.

Everything before this package drove the system closed-loop: the
harness delivers a message, waits for the replica to digest it, then
delivers the next — offered load can never exceed service rate by
construction. Production traffic is open-loop: arrivals keep coming at
their own rate whether or not the pipeline has caught up (ROADMAP item
5), and the only question is what the system does past saturation.

Three pieces:

- :mod:`~hyperdrive_tpu.load.schedule` — deterministic seeded arrival
  processes (Poisson and bursty), the open-loop clock both the sim
  injector and the real-socket generator draw from.
- :mod:`~hyperdrive_tpu.load.backpressure` — the admission spine: a
  :class:`BackpressureController` watching DeviceWorkQueue depth /
  drain latency / peer send-queue occupancy and exposing an admission
  level (ACCEPT → SHED_DUPLICATES → SHED_LOW_PRIORITY →
  CRITICAL_ONLY), plus the :class:`AdmissionGate` that classifies and
  sheds messages under it. The shed-class doctrine (ROBUSTNESS.md)
  follows arXiv:1911.04698's aggregation-gossip policy: certificates
  and proposals are never shed, duplicates and stale-height votes go
  first — exactly the classes the Process ignores anyway, which is why
  behavior-neutral shedding commits the same chain as an unloaded run.
- :mod:`~hyperdrive_tpu.load.generator` — :class:`LoadProfile` (the
  sim-side open-loop injector, interpreted by ``Simulation(load=...)``)
  and :class:`TcpLoadGenerator` (a wall-clock firehose of pre-encoded
  frames at a real :class:`~hyperdrive_tpu.transport.TcpNode`).

``python -m hyperdrive_tpu.load soak`` is the CI overload soak: a short
open-loop run past saturation under HD_SANITIZE asserting no-fork,
certificates-never-shed, and a bounded admitted-work p99.
"""

from hyperdrive_tpu.load.backpressure import (
    ACCEPT,
    CRITICAL_ONLY,
    LEVEL_NAMES,
    SHED_DUPLICATES,
    SHED_LOW_PRIORITY,
    AdmissionGate,
    BackpressureController,
)
from hyperdrive_tpu.load.generator import LoadProfile, TcpLoadGenerator
from hyperdrive_tpu.load.schedule import BurstSchedule, PoissonSchedule

__all__ = [
    "ACCEPT",
    "SHED_DUPLICATES",
    "SHED_LOW_PRIORITY",
    "CRITICAL_ONLY",
    "LEVEL_NAMES",
    "AdmissionGate",
    "BackpressureController",
    "BurstSchedule",
    "PoissonSchedule",
    "LoadProfile",
    "TcpLoadGenerator",
]
