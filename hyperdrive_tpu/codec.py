"""Deterministic binary codec with strict byte-budget accounting.

Provides the capability the reference gets from ``renproject/surge``
(reference usage: ``process/state.go:168-279``, ``process/message.go``):
fixed-width little-endian integers, 32-byte arrays, length-prefixed
containers, and a *remaining-byte budget* threaded through every operation so
that adversarial input raises :class:`SerdeError` — it never panics and never
allocates unboundedly. The encoding is canonical (map keys are sorted), so a
marshaled structure is a stable fingerprint suitable for hashing and replay.

This codec is host-side plumbing; the device path packs the same messages
into NumPy structured arrays (see :mod:`hyperdrive_tpu.batch`).
"""

from __future__ import annotations

import struct

__all__ = [
    "SerdeError",
    "MAX_BYTES",
    "Writer",
    "Reader",
]

#: Default byte budget, mirroring surge.MaxBytes's DoS-hardening role.
MAX_BYTES = 8 * 1024 * 1024

_U8 = struct.Struct("<B")
_U16 = struct.Struct("<H")
_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")
_I8 = struct.Struct("<b")
_I64 = struct.Struct("<q")
_F64 = struct.Struct("<d")


class SerdeError(Exception):
    """Raised on any malformed input or exhausted byte budget."""


class Writer:
    """Appends fixed-width values while charging them against a byte budget."""

    __slots__ = ("_parts", "rem")

    def __init__(self, rem: int = MAX_BYTES):
        self._parts: list[bytes] = []
        self.rem = rem

    def _take(self, n: int) -> None:
        if self.rem < n:
            raise SerdeError(f"byte budget exhausted: need {n}, have {self.rem}")
        self.rem -= n

    def _pack(self, st: struct.Struct, v) -> None:
        self._take(st.size)
        try:
            self._parts.append(st.pack(v))
        except struct.error as e:
            raise SerdeError(str(e)) from e

    def u8(self, v: int) -> None:
        self._pack(_U8, v)

    def u16(self, v: int) -> None:
        self._pack(_U16, v)

    def u32(self, v: int) -> None:
        self._pack(_U32, v)

    def u64(self, v: int) -> None:
        self._pack(_U64, v)

    def i8(self, v: int) -> None:
        self._pack(_I8, v)

    def i64(self, v: int) -> None:
        self._pack(_I64, v)

    def f64(self, v: float) -> None:
        self._pack(_F64, v)

    def bool(self, v: bool) -> None:
        self._pack(_U8, 1 if v else 0)

    def bytes32(self, v: bytes) -> None:
        if len(v) != 32:
            raise SerdeError(f"expected 32 bytes, got {len(v)}")
        self._take(32)
        self._parts.append(bytes(v))

    def raw(self, v: bytes) -> None:
        """Length-prefixed variable byte string."""
        self.u32(len(v))
        self._take(len(v))
        self._parts.append(bytes(v))

    def data(self) -> bytes:
        return b"".join(self._parts)


class Reader:
    """Consumes fixed-width values, charging both the buffer and the budget.

    Any out-of-bounds read raises :class:`SerdeError`; fuzzed inputs must
    never crash the caller (reference test contract:
    ``process/state_test.go:20-29``).
    """

    __slots__ = ("_buf", "_pos", "rem")

    def __init__(self, data: bytes, rem: int = MAX_BYTES):
        self._buf = memoryview(bytes(data))
        self._pos = 0
        self.rem = rem

    def _take(self, n: int) -> memoryview:
        if self.rem < n:
            raise SerdeError(f"byte budget exhausted: need {n}, have {self.rem}")
        if self._pos + n > len(self._buf):
            raise SerdeError(
                f"buffer underflow: need {n} at {self._pos}, len {len(self._buf)}"
            )
        self.rem -= n
        out = self._buf[self._pos : self._pos + n]
        self._pos += n
        return out

    def _unpack(self, st: struct.Struct):
        return st.unpack(self._take(st.size))[0]

    def u8(self) -> int:
        return self._unpack(_U8)

    def u16(self) -> int:
        return self._unpack(_U16)

    def u32(self) -> int:
        return self._unpack(_U32)

    def u64(self) -> int:
        return self._unpack(_U64)

    def i8(self) -> int:
        return self._unpack(_I8)

    def i64(self) -> int:
        return self._unpack(_I64)

    def f64(self) -> float:
        return self._unpack(_F64)

    def bool(self) -> bool:
        v = self.u8()
        if v not in (0, 1):
            raise SerdeError(f"invalid bool byte: {v}")
        return v == 1

    def bytes32(self) -> bytes:
        return bytes(self._take(32))

    def raw(self) -> bytes:
        n = self.u32()
        return bytes(self._take(n))

    def done(self) -> bool:
        return self._pos == len(self._buf)

    def remaining_bytes(self) -> int:
        return len(self._buf) - self._pos
