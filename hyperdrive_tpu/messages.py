"""Consensus messages: Propose, Prevote, Precommit, and Timeout.

Capability parity with the reference's message layer
(``process/message.go:43-345``, ``timer/timer.go:14-61``): immutable records
with height/round/value/sender fields, canonical binary serialization under a
byte budget, per-message signing digests that cover everything *except* the
sender (the sender is authenticated by the signature itself), and structural
equality.

Unlike the reference, messages here are hashable frozen dataclasses so they
can live directly in log dict/set structures, and they carry an optional
detached Ed25519 signature for the first-class Verifier path (the reference
assumes authentication happens outside the library,
``process/process.go:95-98``).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field, replace

from hyperdrive_tpu.analysis.annotations import wire_codec
from hyperdrive_tpu.codec import Reader, SerdeError, Writer
from hyperdrive_tpu.types import (
    INT64_MIN,
    INT64_MAX,
    MessageType,
    NIL_SIGNATORY,
    NIL_VALUE,
)

__all__ = [
    "Propose",
    "Prevote",
    "Precommit",
    "Timeout",
    "marshal_message",
    "unmarshal_message",
]


def _check_i64(v: int, what: str) -> None:
    if not INT64_MIN <= v <= INT64_MAX:
        raise SerdeError(f"{what} out of int64 range: {v}")


@wire_codec(tag="msg.propose", max_bytes=1 << 20)
@dataclass(frozen=True, slots=True)
class Propose:
    """A proposer's value suggestion for one (height, round).

    Sent at most once per round by the scheduled proposer (reference:
    ``process/message.go:43-50``). ``valid_round`` carries the proposer's
    ValidRound for the L28 re-propose rule.

    ``payload`` is this framework's MPC extension (no reference analogue):
    an optional opaque byte blob riding with the proposal — in the Shamir
    path it carries the k-of-n share bundle the committer reconstructs per
    committed block (BASELINE config 5). It participates in equality (two
    proposals differing only in payload are equivocation) and is committed
    to by the signing digest.
    """

    height: int
    round: int
    valid_round: int
    value: bytes
    sender: bytes
    payload: bytes = b""
    signature: bytes = field(default=b"", compare=False)
    _digest: bytes = field(default=b"", init=False, repr=False, compare=False)

    def digest(self) -> bytes:
        """Signing digest over (height, round, valid_round, value[,
        payload]).

        Mirrors ``NewProposeHash`` (reference: process/message.go:53-78) —
        the sender is deliberately excluded; the signature authenticates it.
        The leading byte is a per-type domain-separation tag (the
        MessageType) so digests of different message types can never
        collide, regardless of field layout. A non-empty payload appends
        its SHA-256 (injective vs the empty case: the preimage lengths
        differ), so the signature also binds the share bundle.

        Memoized: in the harness one broadcast object fans out to every
        replica, so the digest is computed once per broadcast instead of
        once per delivery. (The cache never covers the signature, so
        ``with_signature`` copies need no invalidation.)
        """
        d = self._digest
        if d:
            return d
        w = Writer()
        w.i64(self.height)
        w.i64(self.round)
        w.i64(self.valid_round)
        w.bytes32(self.value)
        if self.payload:
            w.bytes32(hashlib.sha256(self.payload).digest())
        d = hashlib.sha256(b"\x01" + w.data()).digest()
        object.__setattr__(self, "_digest", d)
        return d

    def size_hint(self) -> int:
        return 8 + 8 + 8 + 32 + 32 + 4 + len(self.payload)

    def marshal(self, w: Writer) -> None:
        _check_i64(self.height, "height")
        _check_i64(self.round, "round")
        _check_i64(self.valid_round, "valid_round")
        w.i64(self.height)
        w.i64(self.round)
        w.i64(self.valid_round)
        w.bytes32(self.value)
        w.bytes32(self.sender)
        w.raw(self.payload)

    @classmethod
    def unmarshal(cls, r: Reader) -> "Propose":
        return cls(
            height=r.i64(),
            round=r.i64(),
            valid_round=r.i64(),
            value=r.bytes32(),
            sender=r.bytes32(),
            payload=r.raw(),
        )

    def with_signature(self, signature: bytes) -> "Propose":
        return replace(self, signature=signature)


@wire_codec(tag="msg.prevote", max_bytes=256)
@dataclass(frozen=True, slots=True)
class Prevote:
    """The first voting step (reference: ``process/message.go:156-162``)."""

    height: int
    round: int
    value: bytes
    sender: bytes
    signature: bytes = field(default=b"", compare=False)
    _digest: bytes = field(default=b"", init=False, repr=False, compare=False)

    def digest(self) -> bytes:
        """Mirrors ``NewPrevoteHash`` (reference: process/message.go:165-186).
        Memoized (see :meth:`Propose.digest`)."""
        d = self._digest
        if d:
            return d
        w = Writer()
        w.i64(self.height)
        w.i64(self.round)
        w.bytes32(self.value)
        d = hashlib.sha256(b"\x02" + w.data()).digest()
        object.__setattr__(self, "_digest", d)
        return d

    def size_hint(self) -> int:
        return 8 + 8 + 32 + 32

    def marshal(self, w: Writer) -> None:
        _check_i64(self.height, "height")
        _check_i64(self.round, "round")
        w.i64(self.height)
        w.i64(self.round)
        w.bytes32(self.value)
        w.bytes32(self.sender)

    @classmethod
    def unmarshal(cls, r: Reader) -> "Prevote":
        return cls(
            height=r.i64(),
            round=r.i64(),
            value=r.bytes32(),
            sender=r.bytes32(),
        )

    def with_signature(self, signature: bytes) -> "Prevote":
        return replace(self, signature=signature)


@wire_codec(tag="msg.precommit", max_bytes=256)
@dataclass(frozen=True, slots=True)
class Precommit:
    """The second voting step (reference: ``process/message.go:254-260``)."""

    height: int
    round: int
    value: bytes
    sender: bytes
    signature: bytes = field(default=b"", compare=False)
    _digest: bytes = field(default=b"", init=False, repr=False, compare=False)

    def digest(self) -> bytes:
        """Mirrors ``NewPrecommitHash`` (reference: process/message.go:263-284).

        A distinct domain-separation tag keeps prevote and precommit digests
        for the same (height, round, value) from colliding.
        Memoized (see :meth:`Propose.digest`).
        """
        d = self._digest
        if d:
            return d
        w = Writer()
        w.i64(self.height)
        w.i64(self.round)
        w.bytes32(self.value)
        d = hashlib.sha256(b"\x03" + w.data()).digest()
        object.__setattr__(self, "_digest", d)
        return d

    def size_hint(self) -> int:
        return 8 + 8 + 32 + 32

    def marshal(self, w: Writer) -> None:
        _check_i64(self.height, "height")
        _check_i64(self.round, "round")
        w.i64(self.height)
        w.i64(self.round)
        w.bytes32(self.value)
        w.bytes32(self.sender)

    @classmethod
    def unmarshal(cls, r: Reader) -> "Precommit":
        return cls(
            height=r.i64(),
            round=r.i64(),
            value=r.bytes32(),
            sender=r.bytes32(),
        )

    def with_signature(self, signature: bytes) -> "Precommit":
        return replace(self, signature=signature)


@wire_codec(tag="msg.timeout", max_bytes=32)
@dataclass(frozen=True, slots=True)
class Timeout:
    """A fired timeout event (reference: ``timer/timer.go:14-18``)."""

    message_type: MessageType
    height: int
    round: int

    def marshal(self, w: Writer) -> None:
        _check_i64(self.height, "height")
        _check_i64(self.round, "round")
        w.i8(int(self.message_type))
        w.i64(self.height)
        w.i64(self.round)

    @classmethod
    def unmarshal(cls, r: Reader) -> "Timeout":
        ty = r.i8()
        try:
            mt = MessageType(ty)
        except ValueError as e:
            raise SerdeError(f"invalid message type: {ty}") from e
        return cls(message_type=mt, height=r.i64(), round=r.i64())


_TYPE_TAGS = {
    Propose: MessageType.PROPOSE,
    Prevote: MessageType.PREVOTE,
    Precommit: MessageType.PRECOMMIT,
    Timeout: MessageType.TIMEOUT,
}

_TAG_CLASSES = {
    MessageType.PROPOSE: Propose,
    MessageType.PREVOTE: Prevote,
    MessageType.PRECOMMIT: Precommit,
    MessageType.TIMEOUT: Timeout,
}


#: Widest detached signature the envelope accepts: Ed25519 is 64 bytes,
#: BLS12-381 G2 is 96 — anything longer is a Byzantine frame, not a key
#: format we will ever grow into silently.
_MAX_SIGNATURE = 96


@wire_codec(tag="msg.envelope", max_bytes=1 << 20)
def marshal_message(msg, w: Writer) -> None:
    """Marshal any message with a leading type tag (the wire envelope used
    by scenario records). Unlike the core message serde, the envelope also
    carries the detached signature — on a real wire the signature travels
    with the message."""
    tag = _TYPE_TAGS.get(type(msg))
    if tag is None:
        raise SerdeError(f"unknown message type: {type(msg)!r}")
    w.i8(int(tag))
    msg.marshal(w)
    if not isinstance(msg, Timeout):
        w.raw(msg.signature)


@wire_codec(tag="msg.envelope", max_bytes=1 << 20)
def unmarshal_message(r: Reader):
    """Inverse of :func:`marshal_message`. Unknown tags and oversized
    trailing signatures are typed rejections — the envelope is the first
    decode a Byzantine peer's bytes meet."""
    ty = r.i8()
    try:
        cls = _TAG_CLASSES[MessageType(ty)]
    except (ValueError, KeyError) as e:
        raise SerdeError(f"invalid message tag: {ty}") from e
    msg = cls.unmarshal(r)
    if cls is not Timeout:
        signature = r.raw()
        if len(signature) > _MAX_SIGNATURE:
            raise SerdeError(
                f"detached signature too wide: {len(signature)} > "
                f"{_MAX_SIGNATURE}"
            )
        if signature:
            msg = msg.with_signature(signature)
    return msg
