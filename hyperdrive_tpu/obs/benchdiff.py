"""Perf-regression sentinel: diff two bench JSON artifacts.

``python -m hyperdrive_tpu.obs benchdiff OLD.json NEW.json`` walks the
two artifacts in parallel, pairs up every numeric leaf and numeric
series, and decides — with noise bounds derived from the data itself —
whether NEW regressed relative to OLD. Exit status is nonzero iff a
*gated* metric regressed, so CI can pin a committed baseline and fail
the build on a real slowdown without flaking on runner jitter.

Three design points keep the sentinel honest:

**Medians over means.** A per-block series (``block_wall_s`` etc.)
compares by median, which a single GC pause or cold-start outlier
cannot move. Scalars compare directly but get wider default bounds.

**Noise bounds from the series.** The tolerance for a series is
``max(threshold, NOISE_K * MAD/median)`` — the artifact's own run-to-run
scatter (median absolute deviation) sets the floor, so a naturally
noisy metric doesn't page and a rock-stable one is held tight.

**Machine-portable gates.** Absolute numbers differ across runners, so
hard failure is reserved for paths the artifact itself nominates under
a top-level ``benchdiff_gate`` list (dotted paths, NEW's list wins).
Everything else is reported informationally. Ratio-style metrics
(speedups, relative throughput) make the best gates because they
divide the runner's speed out.

Direction is inferred from the metric name: throughput-like names
(``per_s``, ``speedup``, ``rate``, ``throughput``, ``ops``) are
higher-is-better; time-like names (``wall``, ``latency``, ``_s``,
``seconds``, ``wait``, ``time``) are lower-is-better; anything else is
compared as lower-is-better only when gated, informational otherwise.
"""

from __future__ import annotations

import json

__all__ = ["compare", "render", "main"]

#: Default relative tolerance for an ungated/low-noise metric.
DEFAULT_THRESHOLD = 0.08

#: Scatter multiplier: a series' noise bound is NOISE_K robust
#: coefficient-of-variations (MAD/median), so ~NOISE_K-sigma moves gate.
NOISE_K = 4.0

_HIGHER = ("per_s", "speedup", "rate", "throughput", "ops", "per_sec")
_LOWER = ("wall", "latency", "seconds", "wait", "time", "_s", "_us", "_ms")


def _direction(path):
    """+1 higher-is-better, -1 lower-is-better, 0 unknown."""
    leaf = path.rsplit(".", 1)[-1].lower()
    for pat in _HIGHER:
        if pat in leaf:
            return 1
    for pat in _LOWER:
        if pat in leaf:
            return -1
    return 0


def _is_num(v):
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def _walk(node, prefix=""):
    """Yield (dotted_path, value) for numeric leaves and numeric series."""
    if isinstance(node, dict):
        for k in sorted(node):
            yield from _walk(node[k], f"{prefix}.{k}" if prefix else str(k))
    elif isinstance(node, list):
        if node and all(_is_num(v) for v in node):
            yield prefix, node
        else:
            for i, v in enumerate(node):
                yield from _walk(v, f"{prefix}[{i}]")
    elif _is_num(node):
        yield prefix, node


def _median(vals):
    s = sorted(vals)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


def _series_stats(vals):
    """(median, mad) of a numeric series."""
    med = _median(vals)
    mad = _median([abs(v - med) for v in vals])
    return med, mad


def compare(old, new, threshold=DEFAULT_THRESHOLD, gates=None):
    """Diff two bench artifacts (parsed JSON), return the verdict dict.

    ``gates``: iterable of dotted paths that hard-fail on regression;
    defaults to NEW's top-level ``benchdiff_gate`` list (falling back
    to OLD's). A gate path matches a metric if it equals the metric's
    path or is a prefix of it (so ``consensus`` gates every metric
    under that subtree).
    """
    if gates is None:
        gates = new.get("benchdiff_gate", old.get("benchdiff_gate", []))
    gates = list(gates or [])

    def gated(path):
        return any(
            path == g or path.startswith(g + ".") or path.startswith(g + "[")
            for g in gates
        )

    old_metrics = dict(_walk(old))
    new_metrics = dict(_walk(new))
    regressions, improvements, ok, skipped = [], [], [], []

    for path in sorted(set(old_metrics) & set(new_metrics)):
        if path == "benchdiff_gate" or path.startswith("benchdiff_gate"):
            continue
        ov, nv = old_metrics[path], new_metrics[path]
        is_series = isinstance(ov, list)
        if is_series != isinstance(nv, list):
            skipped.append({"path": path, "reason": "shape-mismatch"})
            continue
        bound = threshold
        if is_series:
            if len(ov) < 3 or len(nv) < 3:
                skipped.append({"path": path, "reason": "short-series"})
                continue
            o_med, o_mad = _series_stats(ov)
            n_med, _ = _series_stats(nv)
            if o_med:
                bound = max(threshold, NOISE_K * o_mad / abs(o_med))
            ov, nv = o_med, n_med
        direction = _direction(path)
        if direction == 0 and not gated(path):
            skipped.append({"path": path, "reason": "direction-unknown"})
            continue
        if direction == 0:
            direction = -1  # gated but nameless: assume lower-is-better
        if ov == 0:
            if nv == 0:
                ok.append({"path": path, "old": ov, "new": nv, "ratio": 1.0})
                continue
            skipped.append({"path": path, "reason": "zero-baseline"})
            continue
        ratio = nv / ov
        # Normalize so delta > 0 always means "got worse".
        delta = (ratio - 1.0) if direction < 0 else (1.0 - ratio)
        entry = {
            "path": path,
            "old": ov,
            "new": nv,
            "ratio": ratio,
            "delta": delta,
            "bound": bound,
            "gated": gated(path),
            "series": is_series,
        }
        if delta > bound:
            regressions.append(entry)
        elif delta < -bound:
            improvements.append(entry)
        else:
            ok.append(entry)

    gated_regressions = [e for e in regressions if e["gated"]]
    return {
        "regressions": regressions,
        "gated_regressions": gated_regressions,
        "improvements": improvements,
        "ok": ok,
        "skipped": skipped,
        "gates": gates,
        "failed": bool(gated_regressions),
    }


def render(verdict):
    """Human-readable sentinel report lines."""
    lines = []

    def fmt(e, tag):
        flag = " [GATED]" if e.get("gated") else ""
        kind = "median" if e.get("series") else "value"
        lines.append(
            f"{tag}{flag} {e['path']}: {kind} {e['old']:.6g} -> "
            f"{e['new']:.6g} (delta {e['delta']:+.1%}, "
            f"bound {e['bound']:.1%})"
        )

    for e in verdict["regressions"]:
        fmt(e, "REGRESSION")
    for e in verdict["improvements"]:
        fmt(e, "improved  ")
    lines.append(
        f"{len(verdict['ok'])} ok, "
        f"{len(verdict['improvements'])} improved, "
        f"{len(verdict['regressions'])} regressed "
        f"({len(verdict['gated_regressions'])} gated), "
        f"{len(verdict['skipped'])} skipped"
    )
    if verdict["failed"]:
        lines.append("FAIL: gated perf regression")
    else:
        lines.append("PASS")
    return "\n".join(lines)


def main(old_path, new_path, threshold=DEFAULT_THRESHOLD, gates=None,
         as_json=False):
    """CLI entry: returns the process exit code."""
    with open(old_path) as fh:
        old = json.load(fh)
    with open(new_path) as fh:
        new = json.load(fh)
    verdict = compare(old, new, threshold=threshold, gates=gates)
    if as_json:
        print(json.dumps(verdict, indent=1))
    else:
        print(render(verdict))
    return 1 if verdict["failed"] else 0
