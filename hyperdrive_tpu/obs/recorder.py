"""Consensus flight recorder: a bounded, deterministic event journal.

The Tracer (utils/trace.py) answers "how many / how long"; this module
answers "in what order, and why". A :class:`Recorder` captures typed
events — step transitions, timeout schedules and fires, commits,
equivocations, flush launches/settles, device fetches — into a fixed
ring buffer, each stamped with (ts, replica, height, round, kind,
detail). The timestamp comes from an injectable ``time_fn`` so a sim
wired to the VirtualClock produces a replay-identical journal: two
fixed-seed runs digest to the same bytes (tests/analysis/
test_digest_stability.py).

Disabled recording follows the NULL_TRACER discipline: hot paths hold a
:data:`NULL_BOUND` handle and guard with an identity check, so the off
state costs one attribute load and one ``is not``. The ``Replica``
constructor seam is named ``obs`` throughout — ``recorder`` was already
taken by the transport-replay FlightRecorder (transport.py), which logs
consumption, not causality.

Event kinds are a closed, documented taxonomy (OBSERVABILITY.md); the
``detail`` slot carries at most one deterministic scalar (an int or a
short string), never wall-clock-, id()- or hash-order-derived values.
"""

from __future__ import annotations

import hashlib
import json
import threading

__all__ = [
    "Event",
    "Recorder",
    "BoundRecorder",
    "NullRecorder",
    "NullBound",
    "NULL_RECORDER",
    "NULL_BOUND",
    "load_journal",
]

# The closed event taxonomy. Kept here (not just in docs) so tooling —
# the report, the exporter, HD005 fixtures — can validate against it.
EVENT_KINDS = frozenset(
    {
        "round.start",
        "round.skip",
        "step.prevoting",
        "step.precommitting",
        "timeout.propose.scheduled",
        "timeout.propose.fired",
        "timeout.prevote.scheduled",
        "timeout.prevote.fired",
        "timeout.precommit.scheduled",
        "timeout.precommit.fired",
        "commit",
        "equivocation",
        "height.resync",
        "ingest.window",
        "mq.drop",
        "settle.pass",
        "settle.speculative",
        "verify.launch",
        "verify.rlc.verdict",
        "verify.rlc.fallbacks",
        "verify.msm.windows",
        "verify.msm.occupancy",
        "verify.msm.depth",
        "cert.emit",
        "cert.verify",
        "tally.launch",
        "sched.submit",
        "sched.coalesce",
        "sched.drain",
        "sched.gated",
        # Device-pipeline telemetry (obs/devtel.py): one launch-probe
        # family per coalesced DeviceWorkQueue drain. submit/cmd carry
        # the per-command sequence number the Perfetto exporter keys
        # its submit->drain flow arrows on; commit closes the loop on
        # the gated replica's track with the covering launch_id.
        "sched.launch.submit",
        "sched.launch.begin",
        "sched.launch.cmd",
        "sched.launch.rows",
        "sched.launch.lanes",
        "sched.launch.occupancy",
        "sched.launch.queue_wait",
        "sched.launch.split",
        "sched.launch.end",
        "sched.launch.commit",
        # Lanes-requested vs bucket-padded economics per verify chunk
        # (ops/ed25519_jax.py).
        "verify.occupancy.rows",
        "verify.occupancy.lanes",
        "verify.occupancy.pct",
        # Metrics registry (obs/metrics.py) lifecycle marks.
        "metrics.snapshot",
        "flush.launch",
        "flush.settle",
        "fetch.sync",
        "wire.frame.malformed",
        "wire.frame.oversize",
        "wire.frame.shed",
        "wire.frame.stale",
        # HDS005 decode-budget breach (analysis/sanitizer.py WireBudget):
        # detail = "<tag>:<bytes needed>".
        "wire.budget.exceeded",
        "transport.peer.dropped",
        "transport.reconnect",
        # Overload harness (load/): offered-load marks from the open-loop
        # injector and the backpressure spine's admission decisions.
        "load.offered",
        "load.burst",
        "admission.level",
        "admission.shed",
        "chaos.partition",
        "chaos.heal",
        "chaos.crash",
        "chaos.restore",
        "epoch.begin",
        "epoch.elect",
        "epoch.switch",
        "epoch.proof",
        "epoch.stale_vote",
        # Aggregation overlay (overlay/runtime.py): frame accounting,
        # contribution-score verdicts, level-window escalation, and the
        # never-starve fallback. Closed family — the --overlay report
        # decoder and OBSERVABILITY.md enumerate exactly these.
        "overlay.frame",
        "overlay.invalid",
        "overlay.stale",
        "overlay.duplicate",
        "overlay.withhold",
        "overlay.level.timeout",
        "overlay.fallback",
        "overlay.demote",
        "overlay.recover",
        "overlay.rekey",
        # BLS aggregate path (certificates.py, overlay/runtime.py):
        # one mark per minted aggregate-signature certificate (detail
        # carries partial count + host|device aggregation route) and
        # one per merge-level partial-aggregate reject (the contributor
        # charged before any batch verify). Closed family — the lint
        # (HD005) and OBSERVABILITY.md enumerate exactly these.
        "bls.cert.agg",
        "bls.partial.reject",
        # Multi-tenant serving (devsched/policy.py, parallel/service.py):
        # drain-policy deferrals/starvation-bound firings on the queue
        # track, and the cross-process submit path's lifecycle — remote
        # windows admitted, certificate frames resolved, overload sheds,
        # retired tenant certificate prunes. Closed families — the lint
        # (HD005) and OBSERVABILITY.md enumerate exactly these.
        "tenant.drain.deferred",
        "tenant.drain.forced",
        "service.remote.submit",
        "service.remote.resolve",
        "service.remote.shed",
        "service.tenant.retire",
        # Execution layer (exec/ledger.py, harness/sim.py): one mark
        # per applied block (detail: tx count, admitted count, host vs
        # device kernel route), one per chained state root, and one per
        # boundary stake snapshot read by an epoch election. Closed
        # family — the lint (HD005), the --exec report decoder, and
        # OBSERVABILITY.md enumerate exactly these.
        "exec.apply",
        "exec.root",
        "exec.stake",
        # Speculative execution pipeline (exec/ledger.py speculate/
        # resolve): one mark per speculative apply (detail: signed
        # guess or exact), one per confirmed height, one per rollback
        # (detail: heights unwound). Closed family — the lint (HD005),
        # the --exec report's speculation-outcome table, and
        # OBSERVABILITY.md enumerate exactly these.
        "exec.spec.speculate",
        "exec.spec.confirm",
        "exec.spec.rollback",
        # Merkleized state (ops/merkle.py via both executors): one mark
        # per applied block's account-tree root and one per incremental
        # update (detail: scatter-target count, tree depth, whether the
        # kernel fell back to a full rebuild). Closed family — the lint
        # (HD005), the --proofs report decoder, and OBSERVABILITY.md
        # enumerate exactly these.
        "merkle.root",
        "merkle.update",
        # Proof serving (parallel/service.py TAG_QUERY path): one mark
        # per proof frame served (detail: account, frame bytes) and one
        # per query shed by the admission gate (detail: tenant). Closed
        # family — same three consumers as merkle.*.
        "proof.serve",
        "proof.shed",
        # Live metrics plane (parallel/service.py TAG_METRICS path):
        # one mark per Prometheus snapshot served on remote_port()
        # (detail: rendered bytes) and one per scrape the admission
        # gate shed ahead of consensus traffic (detail: tenant).
        # Closed family (the `metrics.` prefix already is) — the lint
        # (HD005) and OBSERVABILITY.md enumerate exactly these.
        "metrics.serve",
        "metrics.shed",
        # Cross-process causal tracing (obs/tracectx.py): one mark per
        # stamped frame sent (detail: "origin:seq"), one per stamped
        # frame received (detail: "origin:seq" of the SENDER's stamp —
        # the merge CLI pairs send/recv on it), and one per estimated
        # wall-clock offset from a HELLO echo exchange (detail:
        # "peer_origin:offset_seconds"). Closed family — the lint
        # (HD005), obs merge, and OBSERVABILITY.md enumerate exactly
        # these.
        "trace.send",
        "trace.recv",
        "trace.offset",
        # SLO burn-rate checks (obs/slo.py): one verdict mark per
        # objective evaluated against a registry snapshot or merged
        # journal (detail: "<slo name>:<measured value>"). Closed
        # family — the lint (HD005) and OBSERVABILITY.md enumerate
        # exactly these.
        "slo.ok",
        "slo.breach",
        # Admission-gate reputation loop (load/backpressure.py
        # SignerReputation): one mark per verify-failure charge (detail:
        # charge class), one per signer demotion and one per recovery
        # (detail: peer label). Closed family — the lint (HD005), the
        # --campaign report decoder, and OBSERVABILITY.md enumerate
        # exactly these.
        "admission.reputation.charge",
        "admission.reputation.demote",
        "admission.reputation.recover",
        # Attack-campaign workloads (campaign/): one mark per family
        # launch (detail: family name), per storm wave reaching batch
        # verify (detail: admitted rows), per capture epoch (detail:
        # adversary seats), per grinding pick (detail: candidate
        # index), per overlay slice engaged/healed in a coincidence run
        # (detail: level), per invariant violation (detail: kind), and
        # one closing mark carrying the campaign digest prefix. Closed
        # family — the lint (HD005), the --campaign report decoder, and
        # OBSERVABILITY.md enumerate exactly these.
        "campaign.family",
        "campaign.wave",
        "campaign.epoch",
        "campaign.grind",
        "campaign.partition",
        "campaign.heal",
        "campaign.violation",
        "campaign.done",
    }
)

JOURNAL_VERSION = 1


class Event(tuple):
    """A recorded event: ``(ts, replica, height, round, kind, detail)``.

    A bare tuple subclass (not a dataclass) so ring inserts stay a
    single allocation; the named properties are for report/export code,
    which is off the hot path.

    Merged journals (obs/merge.py) append a seventh slot — the origin
    process id — so one stream can hold events from N processes; a
    plain per-process event reads as pid 0.
    """

    __slots__ = ()

    ts = property(lambda self: self[0])
    replica = property(lambda self: self[1])
    height = property(lambda self: self[2])
    round = property(lambda self: self[3])
    kind = property(lambda self: self[4])
    detail = property(lambda self: self[5])
    pid = property(lambda self: self[6] if len(self) > 6 else 0)


class Recorder:
    """Fixed-capacity ring journal of consensus events.

    Parameters
    ----------
    capacity:
        Maximum retained events; older events are overwritten (the
        ``dropped`` counter in the journal says how many).
    time_fn:
        Zero-arg timestamp source. Inject the sim's VirtualClock
        (``lambda: clock.now``) for deterministic journals; defaults to
        a monotonically increasing sequence number when omitted so the
        recorder is still usable standalone.
    threadsafe:
        Guard inserts with a lock. The sim is single-threaded and
        passes False; TcpNode wiring needs True.
    """

    __slots__ = (
        "capacity", "_ring", "total", "_time_fn", "_lock", "_seq",
        "_dropped",
    )

    def __init__(self, capacity=65536, time_fn=None, threadsafe=False):
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.capacity = capacity
        self._ring = []
        self.total = 0
        self._time_fn = time_fn
        self._lock = threading.Lock() if threadsafe else None
        self._seq = 0
        self._dropped = 0

    # ------------------------------------------------------------ insert

    def emit(self, kind, replica, height, round_, detail=None):
        # The whole emit — timestamp draw (the fallback _tick mutates
        # _seq), ring write, and overwrite accounting — runs under the
        # lock in threadsafe mode: a torn total/_dropped pair would let
        # `dropped` disagree with what snapshot() actually returns.
        lock = self._lock
        if lock is None:
            self._insert(kind, replica, height, round_, detail)
        else:
            with lock:
                self._insert(kind, replica, height, round_, detail)

    def _insert(self, kind, replica, height, round_, detail):
        ts = self._time_fn() if self._time_fn is not None else self._tick()
        ev = Event((ts, replica, height, round_, kind, detail))
        ring = self._ring
        if len(ring) < self.capacity:
            ring.append(ev)
        else:
            ring[self.total % self.capacity] = ev
            self._dropped += 1
        self.total += 1

    def _tick(self):
        self._seq += 1
        return float(self._seq)

    # ------------------------------------------------------------- views

    def scoped(self, replica):
        """A per-replica handle that pre-binds the replica key."""
        return BoundRecorder(self, replica)

    def __len__(self):
        return len(self._ring)

    @property
    def dropped(self):
        """Events the ring overwrote — an explicit counter maintained
        under the same lock as the ring write, so a concurrent reader
        never sees it disagree with the snapshot (the old derived
        ``total - capacity`` could tear against a mid-flight insert)."""
        lock = self._lock
        if lock is None:
            return self._dropped
        with lock:
            return self._dropped

    def snapshot(self):
        """Events oldest-to-newest, as a new list of :class:`Event`."""
        lock = self._lock
        if lock is None:
            return self._snapshot()
        with lock:
            return self._snapshot()

    def _snapshot(self):
        ring = self._ring
        if self.total <= self.capacity:
            return list(ring)
        head = self.total % self.capacity
        return ring[head:] + ring[:head]

    def journal(self, meta=None):
        """A JSON-ready dict of the whole journal.

        ``meta`` (optional dict) rides along verbatim — the serve CLI
        stamps each per-process journal with its trace origin id so
        ``obs merge`` can key offset estimates without guessing from
        filenames. :func:`load_journal` returns it untouched.
        """
        data = {
            "version": JOURNAL_VERSION,
            "capacity": self.capacity,
            "total": self.total,
            "dropped": self.dropped,
            "events": [list(ev) for ev in self.snapshot()],
        }
        if meta:
            data["meta"] = dict(meta)
        return data

    def digest(self):
        """sha256 over the canonical JSON encoding of the events.

        Two fixed-seed sim runs must agree here — any nondeterminism in
        the hook sites (hash-order iteration, wall-clock stamps) shows
        up as a digest mismatch.
        """
        blob = json.dumps(
            [list(ev) for ev in self.snapshot()],
            separators=(",", ":"),
            sort_keys=False,
        ).encode()
        return hashlib.sha256(blob).hexdigest()

    def save(self, path, meta=None):
        with open(path, "w") as fh:
            json.dump(self.journal(meta=meta), fh, separators=(",", ":"))
            fh.write("\n")


class BoundRecorder:
    """A recorder handle with the replica key baked in.

    This is what hot paths hold: ``obs.emit(kind, height, round)`` is
    one bound-method call, and the disabled case is the shared
    :data:`NULL_BOUND` singleton so ``obs is not NULL_BOUND`` gates any
    extra work (building a detail value, say) off entirely.
    """

    __slots__ = ("_rec", "replica")

    def __init__(self, rec, replica):
        self._rec = rec
        self.replica = replica

    def emit(self, kind, height, round_, detail=None):
        self._rec.emit(kind, self.replica, height, round_, detail)


class NullRecorder(Recorder):
    """Recording disabled: every emit is a no-op, scoped() is shared."""

    __slots__ = ()

    def __init__(self):
        super().__init__(capacity=1)

    def emit(self, kind, replica, height, round_, detail=None):
        pass

    def scoped(self, replica):
        return NULL_BOUND


class NullBound(BoundRecorder):
    __slots__ = ()

    def __init__(self):
        super().__init__(None, -1)

    def emit(self, kind, height, round_, detail=None):
        pass


NULL_RECORDER = NullRecorder()
NULL_BOUND = NullBound()


def load_journal(path):
    """Read a journal written by :meth:`Recorder.save`.

    Returns the journal dict with ``events`` rehydrated to
    :class:`Event` instances (tuples with named accessors).
    """
    with open(path) as fh:
        data = json.load(fh)
    if data.get("version") != JOURNAL_VERSION:
        raise ValueError(
            f"unsupported journal version {data.get('version')!r}"
        )
    data["events"] = [Event(tuple(ev)) for ev in data["events"]]
    return data
