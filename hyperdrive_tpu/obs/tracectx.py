"""Cross-process causal trace propagation (the distributed half of obs).

Every per-process journal is causally blind past its own socket: a
``service.remote.submit`` in the parent and the matching
``exec.apply`` in a child tenant share no key, so commit latency on
the real mesh could not be attributed to a hop. This module defines
the compact trace-context stamp that rides in front of wire frames:

    [u8 magic 0x54]["origin" u32]["seq" u64]["parent" u64]   (21 bytes)

``origin`` is the stamping process's trace id (the serve CLI hands
each child a distinct one), ``seq`` a per-process monotone frame
counter, and ``parent`` an optional upstream (origin << 32 | seq)
reference for frames sent in reaction to a received one. The magic
byte cannot collide with any existing first byte on either protocol:
consensus envelopes open with a small non-negative MessageType i8 and
service frames with tags 1..5, while 0x54 is well clear of both — a
reader peeks one byte, strips the stamp when present, and decodes the
remainder exactly as before, so unstamped peers interoperate
unchanged.

Stamping emits ``trace.send`` and stripping emits ``trace.recv``,
both with detail ``"origin:seq"`` — the one shared key ``obs merge``
pairs across journals and the Perfetto exporter draws cross-process
flow arrows on. The codec is registered under ``@wire_codec`` with a
hard 64-byte budget so the HDS005 sanitizer meters it like every
other wire family.
"""

from __future__ import annotations

import threading

from hyperdrive_tpu.analysis.annotations import wire_codec
from hyperdrive_tpu.analysis.sanitizer import maybe_wire_reader
from hyperdrive_tpu.codec import SerdeError, Writer
from hyperdrive_tpu.obs.recorder import NULL_BOUND

__all__ = [
    "TRACE_MAGIC",
    "STAMP_LEN",
    "encode_stamp",
    "decode_stamp",
    "split_frame",
    "span_id",
    "TraceSource",
    "note_recv",
]

#: First byte of every stamp. 0x54 ('T') — distinct from the i8
#: MessageType consensus envelopes open with and the 1..5 service tags.
TRACE_MAGIC = 0x54

#: Fixed stamp width: magic u8 + origin u32 + seq u64 + parent u64.
STAMP_LEN = 1 + 4 + 8 + 8


@wire_codec(tag="trace.ctx", max_bytes=64)
def encode_stamp(origin: int, seq: int, parent: int = 0) -> bytes:
    w = Writer()
    w.u8(TRACE_MAGIC)
    w.u32(origin)
    w.u64(seq)
    w.u64(parent)
    return w.data()


@wire_codec(tag="trace.ctx", max_bytes=64)
def decode_stamp(payload: bytes):
    """Decode one bare stamp → ``(origin, seq, parent)``.

    Rejects a wrong magic byte and trailing garbage with
    :class:`SerdeError` so the wire-audit fuzz harness sees only typed
    failures; the HDS005 budget (64 bytes) is charged through
    :func:`maybe_wire_reader` like every registered family.
    """
    r = maybe_wire_reader("trace.ctx", payload)
    magic = r.u8()
    if magic != TRACE_MAGIC:
        raise SerdeError(f"bad trace stamp magic {magic:#x}")
    origin = r.u32()
    seq = r.u64()
    parent = r.u64()
    if not r.done():
        raise SerdeError("trailing bytes after trace stamp")
    return origin, seq, parent


def split_frame(payload):
    """Strip a leading stamp from a frame payload, if present.

    Returns ``(ctx, rest)`` where ``ctx`` is ``(origin, seq, parent)``
    or ``None`` for an unstamped frame — the back-compat path: peers
    that never learned to stamp keep decoding byte-identically.
    """
    if len(payload) < STAMP_LEN or payload[0] != TRACE_MAGIC:
        return None, payload
    ctx = decode_stamp(bytes(payload[:STAMP_LEN]))
    return ctx, payload[STAMP_LEN:]


def span_id(origin: int, seq: int) -> int:
    """The flow-arrow / parent-ref key: ``origin << 32 | seq``."""
    return (origin << 32) | (seq & 0xFFFFFFFF)


class TraceSource:
    """Per-process stamp mint: one monotone seq, one origin id.

    ``stamp()`` prefixes a payload with a fresh stamp and emits
    ``trace.send``; the counter is lock-guarded by default because
    TcpNode broadcast and the service client both send from multiple
    threads. Origin 0 is reserved for "tracing off" — the transports
    treat a ``None`` source as the no-stamp fast path.
    """

    __slots__ = ("origin", "obs", "_lock", "_seq")

    def __init__(self, origin: int, obs=None, threadsafe: bool = True):
        if origin <= 0:
            raise ValueError("trace origin must be a positive int")
        self.origin = origin
        self.obs = obs if obs is not None else NULL_BOUND
        self._lock = threading.Lock() if threadsafe else None
        self._seq = 0

    def _next(self) -> int:
        lock = self._lock
        if lock is None:
            self._seq += 1
            return self._seq
        with lock:
            self._seq += 1
            return self._seq

    def stamp(self, payload: bytes, parent: int = 0,
              height: int = -1, round_: int = -1) -> bytes:
        seq = self._next()
        if self.obs is not NULL_BOUND:
            self.obs.emit(
                "trace.send", height, round_, f"{self.origin}:{seq}"
            )
        return encode_stamp(self.origin, seq, parent) + payload


def note_recv(obs, ctx, height: int = -1, round_: int = -1) -> None:
    """Emit the receive-side half of a span: ``trace.recv`` keyed on
    the SENDER's ``origin:seq`` so merge can pair it with the matching
    ``trace.send`` in another process's journal."""
    if obs is not NULL_BOUND and ctx is not None:
        obs.emit("trace.recv", height, round_, f"{ctx[0]}:{ctx[1]}")
