"""SLO definitions evaluated as burn-rate checks.

ACE Runtime (PAPERS.md, arXiv:2603.10242) makes sub-second finality
the *product* metric — so the soak legs should fail on the product
metric, not only on invariant violations. This module defines the
three serving objectives and evaluates them against what a run
already produces (a metrics-Registry snapshot and/or a journal event
stream), emitting one closed-taxonomy verdict mark per objective:

``finality_p99``
    99th-percentile certificate-accept latency, from the
    ``tenant.commit.latency`` histogram (worst tenant wins — an SLO
    is a floor for every tenant, not an average).
``shed_rate``
    shed frames / (shed + served) over the journal's admission and
    serve marks. Shedding is doctrine under overload, but a soak
    whose steady state sheds most of its offered load is failing its
    clients while passing its invariants.
``rollback_rate``
    speculative rollbacks / speculations (``exec.spec.*``). The
    speculation doctrine (PR 16) says mispredicts must be rare enough
    that the pipeline wins; this is where "rare enough" gets a number.

``burn`` is the classic burn-rate ratio measured/objective: 1.0 is
exactly on budget, >1.0 is burning error budget. Objectives whose
inputs are absent from the run (no histogram, no speculation) are
skipped, not passed — a missing signal is not evidence of health.
"""

from __future__ import annotations

from dataclasses import dataclass

from hyperdrive_tpu.obs.recorder import NULL_BOUND

__all__ = ["SloResult", "DEFAULT_OBJECTIVES", "evaluate_slos"]

#: Objective ceilings: finality p99 (seconds), shed fraction, rollback
#: fraction. Chaos/load soak legs evaluate against these unless the
#: caller overrides.
DEFAULT_OBJECTIVES = {
    "finality_p99": 0.75,
    "shed_rate": 0.25,
    "rollback_rate": 0.05,
}

#: Journal kinds that count as one shed decision.
_SHED_KINDS = frozenset({
    "admission.shed", "wire.frame.shed", "service.remote.shed",
    "proof.shed", "metrics.shed",
})

#: Journal kinds that count as one served/admitted unit of work — the
#: shed-rate denominator's "what got through" half.
_SERVE_KINDS = frozenset({
    "service.remote.submit", "proof.serve", "metrics.serve",
    "ingest.window",
})


@dataclass(frozen=True)
class SloResult:
    """One objective's verdict: the measured value, the ceiling, and
    the burn-rate ratio (measured / objective)."""

    name: str
    measured: float
    objective: float
    burn: float
    ok: bool


def _finality_p99(snapshot: dict):
    hists = (snapshot or {}).get("histograms", {})
    rows = hists.get("tenant.commit.latency")
    if not rows:
        return None
    if "p99" in rows:  # unlabeled histogram: one stats row
        return float(rows["p99"])
    worst = None
    for stats in rows.values():
        p99 = float(stats.get("p99", 0.0))
        if worst is None or p99 > worst:
            worst = p99
    return worst


def _shed_rate(events):
    sheds = served = 0
    for ev in events:
        kind = ev[4]
        if kind in _SHED_KINDS:
            sheds += 1
        elif kind in _SERVE_KINDS:
            served += 1
    if sheds + served == 0:
        return None
    return sheds / (sheds + served)


def _rollback_rate(events):
    rollbacks = speculations = 0
    for ev in events:
        kind = ev[4]
        if kind == "exec.spec.rollback":
            rollbacks += 1
        elif kind == "exec.spec.speculate":
            speculations += 1
    if speculations == 0:
        return None
    return rollbacks / speculations


def evaluate_slos(snapshot=None, events=None, objectives=None,
                  obs=None) -> list:
    """Evaluate every objective whose inputs are present.

    ``snapshot`` is a :meth:`Registry.snapshot` dict (feeds
    finality_p99); ``events`` a journal event sequence (feeds
    shed_rate and rollback_rate); either may be None. Each evaluated
    objective emits ``slo.ok`` / ``slo.breach`` on ``obs`` with detail
    ``"<name>:<measured>"`` and lands in the returned list.
    """
    objectives = {**DEFAULT_OBJECTIVES, **(objectives or {})}
    obs = obs if obs is not None else NULL_BOUND
    measured = {}
    if snapshot is not None:
        measured["finality_p99"] = _finality_p99(snapshot)
    if events is not None:
        measured["shed_rate"] = _shed_rate(events)
        measured["rollback_rate"] = _rollback_rate(events)
    results = []
    for name in sorted(objectives):
        value = measured.get(name)
        if value is None:
            continue
        ceiling = float(objectives[name])
        burn = value / ceiling if ceiling > 0 else float("inf")
        ok = burn <= 1.0
        if obs is not NULL_BOUND:
            obs.emit(
                "slo.ok" if ok else "slo.breach", -1, -1,
                f"{name}:{value:.6f}",
            )
        results.append(SloResult(name, value, ceiling, burn, ok))
    return results
