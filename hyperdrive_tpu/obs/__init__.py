"""Consensus observability: flight recorder, anatomy report, traces,
device-launch telemetry, metrics registry, and the perf sentinel.

See OBSERVABILITY.md for the event taxonomy and CLI usage. The hot-path
contract is the same as utils/trace.py's NULL_TRACER: components hold a
recorder handle that defaults to the shared no-op singleton, and guard
any non-trivial event construction with an identity check (device
telemetry follows suit with NULL_DEVTEL).
"""

from hyperdrive_tpu.obs.recorder import (
    EVENT_KINDS,
    NULL_BOUND,
    NULL_RECORDER,
    BoundRecorder,
    Event,
    NullBound,
    NullRecorder,
    Recorder,
    load_journal,
)
from hyperdrive_tpu.obs.report import (
    anatomy,
    critical_path_summary,
    phase_summary,
    render_critical_path_table,
    render_table,
    render_tenant_table,
    tenant_summary,
)
from hyperdrive_tpu.obs.perfetto import DEVICE_TID, export, to_trace_events
from hyperdrive_tpu.obs.tracectx import (
    STAMP_LEN,
    TRACE_MAGIC,
    TraceSource,
    decode_stamp,
    encode_stamp,
    note_recv,
    span_id,
    split_frame,
)
from hyperdrive_tpu.obs.merge import (
    estimate_offsets,
    merge_journals,
    merged_digest,
    save_merged,
)
from hyperdrive_tpu.obs.slo import (
    DEFAULT_OBJECTIVES,
    SloResult,
    evaluate_slos,
)
from hyperdrive_tpu.obs.devtel import (
    NULL_DEVTEL,
    DeviceTelemetry,
    LaunchRecord,
    NullDeviceTelemetry,
)
from hyperdrive_tpu.obs.metrics import (
    Gauge,
    Registry,
    histogram_stats,
    merge_histograms,
    to_prometheus,
)
from hyperdrive_tpu.obs.benchdiff import compare as benchdiff_compare

__all__ = [
    "EVENT_KINDS",
    "NULL_BOUND",
    "NULL_RECORDER",
    "BoundRecorder",
    "Event",
    "NullBound",
    "NullRecorder",
    "Recorder",
    "load_journal",
    "anatomy",
    "critical_path_summary",
    "phase_summary",
    "render_critical_path_table",
    "render_table",
    "render_tenant_table",
    "tenant_summary",
    "DEVICE_TID",
    "export",
    "to_trace_events",
    "STAMP_LEN",
    "TRACE_MAGIC",
    "TraceSource",
    "decode_stamp",
    "encode_stamp",
    "note_recv",
    "span_id",
    "split_frame",
    "estimate_offsets",
    "merge_journals",
    "merged_digest",
    "save_merged",
    "DEFAULT_OBJECTIVES",
    "SloResult",
    "evaluate_slos",
    "NULL_DEVTEL",
    "DeviceTelemetry",
    "LaunchRecord",
    "NullDeviceTelemetry",
    "Gauge",
    "Registry",
    "histogram_stats",
    "merge_histograms",
    "to_prometheus",
    "benchdiff_compare",
]
