"""Consensus observability: flight recorder, anatomy report, traces.

See OBSERVABILITY.md for the event taxonomy and CLI usage. The hot-path
contract is the same as utils/trace.py's NULL_TRACER: components hold a
recorder handle that defaults to the shared no-op singleton, and guard
any non-trivial event construction with an identity check.
"""

from hyperdrive_tpu.obs.recorder import (
    EVENT_KINDS,
    NULL_BOUND,
    NULL_RECORDER,
    BoundRecorder,
    Event,
    NullBound,
    NullRecorder,
    Recorder,
    load_journal,
)
from hyperdrive_tpu.obs.report import anatomy, phase_summary, render_table
from hyperdrive_tpu.obs.perfetto import export, to_trace_events

__all__ = [
    "EVENT_KINDS",
    "NULL_BOUND",
    "NULL_RECORDER",
    "BoundRecorder",
    "Event",
    "NullBound",
    "NullRecorder",
    "Recorder",
    "load_journal",
    "anatomy",
    "phase_summary",
    "render_table",
    "export",
    "to_trace_events",
]
