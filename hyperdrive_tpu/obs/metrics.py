"""Uniform metrics registry: counters, gauges, mergeable histograms.

The Tracer (utils/trace.py) grew ad-hoc per-subsystem counters and
histograms; this module is the one registry every surface exports
through — the obs CLI (``python -m hyperdrive_tpu.obs metrics``), the
bench artifacts, and the device-telemetry probe (obs/devtel.py) all
speak :meth:`Registry.snapshot`. Three shapes only:

- **counter** — monotone int (``Counter``, shared with the tracer).
- **gauge** — last-write-wins scalar (queue depth, occupancy).
- **histogram** — the tracer's fixed-bucket :class:`~hyperdrive_tpu.
  utils.trace.Histogram`, extended here with :func:`merge_histograms`
  so per-replica / per-tenant histograms aggregate losslessly at the
  bucket level (sample windows concatenate, recent-biased).

Labels are a single optional dimension (``observe(name, v,
label=...)``): the metric NAME stays a static literal — HD005 polices
that — while the label carries the per-tenant / per-replica key, so
the registry never unbounds on interpolated names.

Determinism contract: a registry timed by the sim's VirtualClock
snapshots to byte-identical JSON across fixed-seed runs
(:meth:`Registry.digest`), exactly like the flight recorder's journal.
Everything here is stdlib-only — no jax import, safe for analysis
tooling and pure-host deployments.
"""

from __future__ import annotations

import hashlib
import json
import time

from hyperdrive_tpu.utils.trace import Counter, Histogram

__all__ = [
    "Gauge",
    "Registry",
    "merge_histograms",
    "histogram_stats",
    "to_prometheus",
]

#: Quantiles every histogram snapshot reports, in snapshot key order.
QUANTILES = ((0.50, "p50"), (0.95, "p95"), (0.99, "p99"))


class Gauge:
    """A last-write-wins scalar (depth, occupancy %, table generation)."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def set(self, v) -> None:
        self.value = v


def merge_histograms(a: Histogram, b: Histogram) -> Histogram:
    """A new histogram holding both inputs' observations.

    Bucket counts, totals and sums add exactly; the bounded sample
    windows concatenate and keep the most recent ``max_samples`` (the
    same recent bias a single histogram's ring overwrite has), so
    quantiles over the merge stay exact within the retained window.
    Bucket ladders must agree — mixing ladders would mis-bin counts.
    """
    if a.buckets != b.buckets:
        raise ValueError("cannot merge histograms with different buckets")
    out = Histogram(buckets=a.buckets, max_samples=a._max_samples)
    out.counts = [x + y for x, y in zip(a.counts, b.counts)]
    out.total = a.total + b.total
    out.sum = a.sum + b.sum
    out._samples = (list(a._samples) + list(b._samples))[-out._max_samples:]
    return out


def histogram_stats(h: Histogram) -> dict:
    """The snapshot row for one histogram: count/sum/mean + quantiles."""
    row = {"count": h.total, "sum": h.sum, "mean": h.mean}
    for q, key in QUANTILES:
        row[key] = h.quantile(q)
    return row


class Registry:
    """Named counters, gauges, and histograms with one label dimension.

    ``time_fn`` feeds :meth:`span` timing; the sim injects its virtual
    clock so spans (and therefore snapshots) are deterministic, while
    standalone deployments default to ``time.perf_counter``.

    The registry is single-writer by design (the sim and the device
    queue are single-threaded); cross-thread aggregation composes via
    :meth:`merge` on thread-local registries instead of a hot-path lock.
    """

    def __init__(self, time_fn=None):
        self._time = time_fn or time.perf_counter
        self.counters: dict = {}      # name -> Counter | {label: Counter}
        self.gauges: dict = {}        # name -> Gauge
        self.histograms: dict = {}    # name -> Histogram | {label: Histogram}
        #: Names whose value dict is keyed by label (one level).
        self._labeled: set = set()

    # ---------------------------------------------------------- recording

    def now(self) -> float:
        return self._time()

    def count(self, name: str, n: int = 1, label=None) -> None:
        table = self.counters
        if label is not None:
            self._labeled.add(name)
            table = table.setdefault(name, {})
            name = label
        c = table.get(name)
        if c is None:
            c = table[name] = Counter()
        c.inc(n)

    def set_gauge(self, name: str, v) -> None:
        g = self.gauges.get(name)
        if g is None:
            g = self.gauges[name] = Gauge()
        g.set(v)

    def observe(self, name: str, v: float, label=None) -> None:
        table = self.histograms
        if label is not None:
            self._labeled.add(name)
            table = table.setdefault(name, {})
            name = label
        h = table.get(name)
        if h is None:
            h = table[name] = Histogram()
        h.observe(v)

    def span(self, name: str, label=None):
        """Context manager timing a block into histogram ``name``."""
        return _Span(self, name, label)

    # -------------------------------------------------------- aggregation

    def absorb_tracer(self, tracer, overwrite: bool = True) -> None:
        """Adopt a Tracer's counters and histograms by reference.

        This is the absorb seam: a sim's ``sim.*`` / ``replica.*``
        tracer series appear in the registry snapshot without copying —
        the registry holds the SAME Counter/Histogram objects, so later
        tracer updates are visible too. Existing registry entries of the
        same name are replaced when ``overwrite`` (the tracer is the
        source of truth for its own names).
        """
        for name, c in tracer.counters.items():
            if overwrite or name not in self.counters:
                self.counters[name] = c
        for name, h in tracer.histograms.items():
            if overwrite or name not in self.histograms:
                self.histograms[name] = h

    def merge(self, other: "Registry") -> None:
        """Fold ``other`` into this registry (cross-replica/tenant
        aggregation): counters add, gauges last-write-win, histograms
        merge at the bucket level."""
        for name, c in other.counters.items():
            if isinstance(c, dict):
                self._labeled.add(name)
                mine = self.counters.setdefault(name, {})
                for label, lc in c.items():
                    got = mine.get(label)
                    if got is None:
                        got = mine[label] = Counter()
                    got.inc(lc.value)
            else:
                got = self.counters.get(name)
                if got is None or isinstance(got, dict):
                    got = self.counters[name] = Counter()
                got.inc(c.value)
        for name, g in other.gauges.items():
            self.set_gauge(name, g.value)
        for name, h in other.histograms.items():
            if isinstance(h, dict):
                self._labeled.add(name)
                mine = self.histograms.setdefault(name, {})
                for label, lh in h.items():
                    mine[label] = (
                        merge_histograms(mine[label], lh)
                        if label in mine else lh
                    )
            else:
                got = self.histograms.get(name)
                self.histograms[name] = (
                    merge_histograms(got, h)
                    if isinstance(got, Histogram) else h
                )

    # ---------------------------------------------------------- reporting

    def snapshot(self) -> dict:
        """JSON-ready view: sorted names, labeled series nested."""
        out: dict = {"counters": {}, "gauges": {}, "histograms": {}}
        for name in sorted(self.counters, key=str):
            c = self.counters[name]
            if isinstance(c, dict):
                out["counters"][name] = {
                    str(k): c[k].value for k in sorted(c, key=str)
                }
            else:
                out["counters"][name] = c.value
        for name in sorted(self.gauges, key=str):
            out["gauges"][name] = self.gauges[name].value
        for name in sorted(self.histograms, key=str):
            h = self.histograms[name]
            if isinstance(h, dict):
                out["histograms"][name] = {
                    str(k): histogram_stats(h[k]) for k in sorted(h, key=str)
                }
            else:
                out["histograms"][name] = histogram_stats(h)
        return out

    def digest(self) -> str:
        """sha256 of the canonical snapshot JSON — the determinism
        check: two fixed-seed sim runs must agree byte-for-byte."""
        blob = json.dumps(
            self.snapshot(), separators=(",", ":"), sort_keys=True
        ).encode()
        return hashlib.sha256(blob).hexdigest()


class _Span:
    __slots__ = ("_reg", "_name", "_label", "_t0")

    def __init__(self, reg, name, label):
        self._reg = reg
        self._name = name
        self._label = label

    def __enter__(self):
        self._t0 = self._reg.now()
        return self

    def __exit__(self, *exc):
        self._reg.observe(
            self._name, self._reg.now() - self._t0, label=self._label
        )
        return False


# ------------------------------------------------------------ prometheus


def _prom_name(name: str) -> str:
    return "hd_" + "".join(
        ch if ch.isalnum() or ch == "_" else "_" for ch in name
    )


def _prom_label(label) -> str:
    return str(label).replace("\\", "\\\\").replace('"', '\\"')


def to_prometheus(snapshot: dict) -> str:
    """Render a :meth:`Registry.snapshot` dict as Prometheus text
    exposition format (counters, gauges, and summary-style histograms
    with quantile labels). Pure function of the snapshot, so a saved
    JSON snapshot re-renders without the live registry."""
    lines: list = []
    for name, v in snapshot.get("counters", {}).items():
        pn = _prom_name(name)
        lines.append(f"# TYPE {pn} counter")
        if isinstance(v, dict):
            for label, lv in v.items():
                lines.append(f'{pn}{{label="{_prom_label(label)}"}} {lv}')
        else:
            lines.append(f"{pn} {v}")
    for name, v in snapshot.get("gauges", {}).items():
        pn = _prom_name(name)
        lines.append(f"# TYPE {pn} gauge")
        lines.append(f"{pn} {v}")
    for name, v in snapshot.get("histograms", {}).items():
        pn = _prom_name(name)
        lines.append(f"# TYPE {pn} summary")
        rows = v.items() if "count" not in v else [(None, v)]
        for label, stats in rows:
            sel = f'label="{_prom_label(label)}",' if label is not None else ""
            for _, qkey in QUANTILES:
                lines.append(
                    f'{pn}{{{sel}quantile="{qkey[1:]}"}} {stats[qkey]}'
                )
            base = f'{{label="{_prom_label(label)}"}}' if label is not None else ""
            lines.append(f"{pn}_sum{base} {stats['sum']}")
            lines.append(f"{pn}_count{base} {stats['count']}")
    return "\n".join(lines) + "\n"
