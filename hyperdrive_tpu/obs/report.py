"""Round-anatomy report: decompose each committed height into phases.

Reads a recorder journal (live snapshot or one loaded from disk) and,
per (replica, height), reconstructs the commit's latency anatomy:

  propose   round.start      -> step.prevoting     (proposal wait+verify)
  prevote   step.prevoting   -> step.precommitting (prevote quorum)
  precommit step.precommitting -> commit           (precommit quorum)

A height that needed several rounds attributes the phases of the round
that actually committed, and the time burned in earlier rounds shows up
as ``stall`` (round.start of round 0 -> round.start of the committing
round). Outlier flags mark the interesting rows: extra rounds,
timeout-driven progress, and totals far above the run median.
"""

from __future__ import annotations

__all__ = [
    "anatomy",
    "phase_summary",
    "render_table",
    "tenant_summary",
    "render_tenant_table",
    "overload_summary",
    "render_overload_table",
    "overlay_summary",
    "render_overlay_table",
    "exec_summary",
    "render_exec_table",
    "proofs_summary",
    "render_proofs_table",
    "critical_path_summary",
    "render_critical_path_table",
]

_TIMEOUT_FIRES = (
    "timeout.propose.fired",
    "timeout.prevote.fired",
    "timeout.precommit.fired",
)


def anatomy(events):
    """Per-(replica, height) commit anatomy rows, sorted.

    ``events`` is an iterable of Event tuples (ts, replica, height,
    round, kind, detail). Returns a list of dict rows; heights that
    never committed in the journal window are omitted.
    """
    # Pass 1: index the marker events per (replica, height).
    marks = {}  # (replica, height) -> state dict

    def st(ev):
        key = (ev[1], ev[2])
        s = marks.get(key)
        if s is None:
            s = {
                "round_start": {},  # round -> ts of first round.start
                "prevoting": {},  # round -> ts
                "precommitting": {},  # round -> ts
                "commit": None,  # (ts, round, detail)
                "timeouts": 0,
                "equivocations": 0,
                "skips": 0,
            }
            marks[key] = s
        return s

    for ev in events:
        kind = ev[4]
        if kind == "round.start":
            s = st(ev)
            s["round_start"].setdefault(ev[3], ev[0])
        elif kind == "step.prevoting":
            s = st(ev)
            s["prevoting"].setdefault(ev[3], ev[0])
        elif kind == "step.precommitting":
            s = st(ev)
            s["precommitting"].setdefault(ev[3], ev[0])
        elif kind == "commit":
            s = st(ev)
            if s["commit"] is None:
                s["commit"] = (ev[0], ev[3], ev[5])
        elif kind in _TIMEOUT_FIRES:
            st(ev)["timeouts"] += 1
        elif kind == "equivocation":
            st(ev)["equivocations"] += 1
        elif kind == "round.skip":
            st(ev)["skips"] += 1

    # Pass 2: committed heights -> anatomy rows.
    rows = []
    for (replica, height), s in marks.items():
        if s["commit"] is None:
            continue
        t_commit, commit_round, detail = s["commit"]
        r0 = s["round_start"].get(0)
        rstart = s["round_start"].get(commit_round)
        tpv = s["prevoting"].get(commit_round)
        tpc = s["precommitting"].get(commit_round)

        def dur(a, b):
            if a is None or b is None:
                return None
            return max(0.0, b - a)

        total = dur(r0 if r0 is not None else rstart, t_commit)
        rows.append(
            {
                "replica": replica,
                "height": height,
                "rounds": commit_round + 1,
                "propose_s": dur(rstart, tpv),
                "prevote_s": dur(tpv, tpc),
                "precommit_s": dur(tpc, t_commit),
                "stall_s": dur(r0, rstart) if commit_round > 0 else 0.0,
                "total_s": total,
                "timeouts": s["timeouts"],
                "equivocations": s["equivocations"],
                "skips": s["skips"],
                "value": detail,
            }
        )
    rows.sort(key=lambda r: (r["height"], r["replica"]))

    # Pass 3: outlier flags need the run median.
    totals = sorted(r["total_s"] for r in rows if r["total_s"] is not None)
    median = totals[len(totals) // 2] if totals else 0.0
    for r in rows:
        flags = []
        if r["rounds"] > 1:
            flags.append("extra-rounds")
        if r["timeouts"] > 0:
            flags.append("timeout-driven")
        if (
            median > 0.0
            and r["total_s"] is not None
            and r["total_s"] > 3.0 * median
        ):
            flags.append("slow")
        if r["equivocations"] > 0:
            flags.append("equivocation")
        r["flags"] = flags
    return rows


def phase_summary(events):
    """Aggregate commit-latency breakdown for bench artifact embedding.

    Means over all committed (replica, height) rows, in journal time
    units (virtual seconds in the sim).
    """
    rows = anatomy(events)
    if not rows:
        return {"commits": 0}

    def mean_of(key):
        vals = [r[key] for r in rows if r[key] is not None]
        return (sum(vals) / len(vals)) if vals else None

    return {
        "commits": len(rows),
        "mean_rounds": sum(r["rounds"] for r in rows) / len(rows),
        "mean_propose_s": mean_of("propose_s"),
        "mean_prevote_s": mean_of("prevote_s"),
        "mean_precommit_s": mean_of("precommit_s"),
        "mean_stall_s": mean_of("stall_s"),
        "mean_total_s": mean_of("total_s"),
        "timeout_driven": sum(1 for r in rows if r["timeouts"] > 0),
        "extra_round_commits": sum(1 for r in rows if r["rounds"] > 1),
    }


def _quantile(sorted_vals, q):
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[idx]


def tenant_summary(events):
    """Per-origin (tenant/replica) device-launch latency rows.

    Reconstructed purely from ``sched.launch.*`` journal events
    (obs/devtel.py), so it works on saved journals with no live
    registry: a command's *verify* latency is submit ts -> the end ts
    of the launch that carried it, and its *commit* latency extends to
    the ``sched.launch.commit`` event naming that launch. Rows are one
    per origin track (tenant id under ShardVerifyService, replica /
    -1 sim under the scheduler), with p50/p95 over the run.
    """
    submits = {}  # seq -> (origin, ts)
    seq_launch = {}  # seq -> launch_id
    launch_end = {}  # launch_id -> end ts
    commit_ts = {}  # launch_id -> [commit ts, ...]
    open_id = None
    for ev in events:
        ts, origin, kind, detail = ev[0], ev[1], ev[4], ev[5]
        if kind == "sched.launch.submit":
            submits[detail] = (origin, ts)
        elif kind == "sched.launch.begin":
            open_id = detail
        elif kind == "sched.launch.cmd":
            if open_id is not None:
                seq_launch[detail] = open_id
        elif kind == "sched.launch.end":
            if open_id is not None:
                launch_end[open_id] = ts
                open_id = None
        elif kind == "sched.launch.commit":
            commit_ts.setdefault(detail, []).append(ts)

    per = {}  # origin -> state

    def row(origin):
        r = per.get(origin)
        if r is None:
            r = {"submits": 0, "launches": set(), "verify": [], "commit": []}
            per[origin] = r
        return r

    for seq, (origin, t0) in submits.items():
        r = row(origin)
        r["submits"] += 1
        lid = seq_launch.get(seq)
        if lid is None:
            continue
        r["launches"].add(lid)
        t_end = launch_end.get(lid)
        if t_end is not None:
            r["verify"].append(max(0.0, t_end - t0))
        for tc in commit_ts.get(lid, ()):
            r["commit"].append(max(0.0, tc - t0))

    rows = []
    for origin in sorted(per):
        r = per[origin]
        v = sorted(r["verify"])
        c = sorted(r["commit"])
        rows.append(
            {
                "tenant": origin,
                "submits": r["submits"],
                "launches": len(r["launches"]),
                "verify_p50_s": _quantile(v, 0.50),
                "verify_p95_s": _quantile(v, 0.95),
                "commit_p50_s": _quantile(c, 0.50),
                "commit_p95_s": _quantile(c, 0.95),
                "commits": len(c),
            }
        )
    return rows


def render_tenant_table(rows):
    """The tenant-summary rows as an aligned text table."""
    cols = [
        ("tenant", "tenant"),
        ("subs", "submits"),
        ("launches", "launches"),
        ("vrfy p50", "verify_p50_s"),
        ("vrfy p95", "verify_p95_s"),
        ("cmt p50", "commit_p50_s"),
        ("cmt p95", "commit_p95_s"),
        ("commits", "commits"),
    ]
    table = [[h for h, _ in cols]]
    for r in rows:
        table.append([_fmt(r[k]) for _, k in cols])
    widths = [max(len(row[i]) for row in table) for i in range(len(cols))]
    lines = []
    for i, row in enumerate(table):
        lines.append("  ".join(c.rjust(w) for c, w in zip(row, widths)))
        if i == 0:
            lines.append("  ".join("-" * w for w in widths))
    return "\n".join(lines)


def overload_summary(events):
    """Overload posture reconstructed from the journal alone.

    Reads the ``load.*`` / ``admission.*`` events the open-loop harness
    emits plus the transport's ``wire.frame.*`` / ``transport.*``
    overload events, so a saved journal from an overloaded run is
    diagnosable with no live registry: how much load was injected, what
    the admission gates shed (by class), how the admission level moved
    over the run (transition timeline + time spent at each level), and
    what the wire path dropped on its own (per-peer backlog eviction,
    stale-generation frames, reconnect storms).
    """
    out = {
        "injected": 0,
        "injection_points": 0,
        "bursts": 0,
        "shed": {},
        "shed_total": 0,
        "level_timeline": [],
        "time_at_level": {},
        "wire_shed": {},
        "stale_frames": 0,
        "reconnects": 0,
        "reconnect_attempts": 0,
    }
    last_level = None  # (ts, name) of the level currently in force
    t_last = None
    for ev in events:
        ts, kind, detail = ev[0], ev[4], ev[5]
        t_last = ts
        if kind == "load.offered":
            out["injected"] += int(detail or 0)
            out["injection_points"] += 1
        elif kind == "load.burst":
            out["bursts"] += 1
        elif kind == "admission.shed":
            cls = detail if isinstance(detail, str) else "?"
            out["shed"][cls] = out["shed"].get(cls, 0) + 1
            out["shed_total"] += 1
        elif kind == "admission.level":
            out["level_timeline"].append((ts, detail))
            if last_level is not None:
                t0, prev = last_level
                out["time_at_level"][prev] = (
                    out["time_at_level"].get(prev, 0.0) + (ts - t0)
                )
            last_level = (ts, detail)
        elif kind == "wire.frame.shed":
            cls = detail if isinstance(detail, str) else "backlog"
            out["wire_shed"][cls] = out["wire_shed"].get(cls, 0) + 1
        elif kind == "wire.frame.stale":
            # The transport emits its cumulative per-node counter.
            out["stale_frames"] = max(out["stale_frames"], int(detail or 0))
        elif kind == "transport.reconnect":
            out["reconnects"] += 1
            out["reconnect_attempts"] += int(detail or 0)
    if last_level is not None and t_last is not None:
        t0, prev = last_level
        out["time_at_level"][prev] = (
            out["time_at_level"].get(prev, 0.0) + (t_last - t0)
        )
    return out


def render_overload_table(summary):
    """The overload summary as aligned text (the CLI's ``--overload``)."""
    lines = [
        f"injected {summary['injected']} "
        f"over {summary['injection_points']} delivery points · "
        f"amp-cap bursts {summary['bursts']}"
    ]
    shed = summary["shed"]
    if shed:
        total = summary["shed_total"]
        rows = [["class", "shed", "share"]]
        for cls in sorted(shed, key=shed.get, reverse=True):
            rows.append([cls, str(shed[cls]), f"{shed[cls] / total:.0%}"])
        widths = [max(len(r[i]) for r in rows) for i in range(3)]
        for i, r in enumerate(rows):
            lines.append("  ".join(c.rjust(w) for c, w in zip(r, widths)))
            if i == 0:
                lines.append("  ".join("-" * w for w in widths))
    else:
        lines.append("admission shed nothing")
    tl = summary["level_timeline"]
    if tl:
        lines.append(
            "level timeline: "
            + " -> ".join(f"{name}@{ts:.3f}" for ts, name in tl[:12])
            + (f" (+{len(tl) - 12} more)" if len(tl) > 12 else "")
        )
        at = summary["time_at_level"]
        lines.append(
            "time at level: "
            + " · ".join(f"{k} {v:.3f}s" for k, v in at.items())
        )
    wire = []
    if summary["wire_shed"]:
        wire.append(
            "peer-queue shed "
            + ", ".join(
                f"{c}={n}" for c, n in sorted(summary["wire_shed"].items())
            )
        )
    if summary["stale_frames"]:
        wire.append(f"stale-generation frames {summary['stale_frames']}")
    if summary["reconnects"]:
        wire.append(
            f"reconnects {summary['reconnects']} "
            f"(total backoff attempts {summary['reconnect_attempts']})"
        )
    if wire:
        lines.append("wire: " + " · ".join(wire))
    return "\n".join(lines)


def overlay_summary(events):
    """Aggregation-overlay posture from the journal alone.

    Decodes the closed ``overlay.*`` family (obs/recorder.py) so a
    saved journal from an overlay run answers the robustness questions
    without a live runtime: how much coverage moved per tree level, who
    got charged for what (the contribution-score verdicts), how often
    level windows escalated or dead-ended into the ranked fallback, and
    which peers finished demoted vs recovered.
    """
    out = {
        "frames": 0,
        "new_coverage": 0,
        "frames_by_level": {},
        "charges": {"invalid": 0, "stale": 0, "duplicate": 0,
                    "withhold": 0},
        "charged_peers": {},
        "level_timeouts": 0,
        "timeouts_by_level": {},
        "fallbacks": 0,
        "demotions": [],
        "recoveries": [],
        "still_demoted": [],
        "rekeys": [],
    }
    _CHARGE_KINDS = {
        "overlay.invalid": "invalid",
        "overlay.stale": "stale",
        "overlay.duplicate": "duplicate",
        "overlay.withhold": "withhold",
    }
    demoted = set()
    for ev in events:
        replica, kind, detail = ev[1], ev[4], ev[5]
        if kind == "overlay.frame":
            out["frames"] += 1
            lvl = new = None
            for part in str(detail or "").split(":"):
                if part.startswith("lvl="):
                    lvl = int(part[4:])
                elif part.startswith("new="):
                    new = int(part[4:])
            if lvl is not None:
                out["frames_by_level"][lvl] = (
                    out["frames_by_level"].get(lvl, 0) + 1
                )
            if new is not None:
                out["new_coverage"] += new
        elif kind in _CHARGE_KINDS:
            cls = _CHARGE_KINDS[kind]
            out["charges"][cls] += 1
            peer = str(detail or "")
            if peer.startswith("peer="):
                key = f"{peer[5:]}:{cls}"
                out["charged_peers"][key] = (
                    out["charged_peers"].get(key, 0) + 1
                )
        elif kind == "overlay.level.timeout":
            out["level_timeouts"] += 1
            for part in str(detail or "").split(":"):
                if part.startswith("lvl="):
                    lvl = int(part[4:])
                    out["timeouts_by_level"][lvl] = (
                        out["timeouts_by_level"].get(lvl, 0) + 1
                    )
        elif kind == "overlay.fallback":
            out["fallbacks"] += 1
        elif kind == "overlay.demote":
            out["demotions"].append((replica, str(detail or "")))
            demoted.add(replica)
        elif kind == "overlay.recover":
            out["recoveries"].append((replica, str(detail or "")))
            demoted.discard(replica)
        elif kind == "overlay.rekey":
            out["rekeys"].append(str(detail or ""))
    out["still_demoted"] = sorted(demoted)
    return out


def render_overlay_table(summary):
    """The overlay summary as aligned text (the CLI's ``--overlay``)."""
    lines = [
        f"frames {summary['frames']} carrying "
        f"{summary['new_coverage']} new signer bits"
    ]
    by_level = summary["frames_by_level"]
    if by_level:
        tmo = summary["timeouts_by_level"]
        rows = [["level", "frames", "timeouts"]]
        for lvl in sorted(by_level):
            rows.append(
                [str(lvl), str(by_level[lvl]), str(tmo.get(lvl, 0))]
            )
        widths = [max(len(r[i]) for r in rows) for i in range(3)]
        for i, r in enumerate(rows):
            lines.append("  ".join(c.rjust(w) for c, w in zip(r, widths)))
            if i == 0:
                lines.append("  ".join("-" * w for w in widths))
    charges = summary["charges"]
    total = sum(charges.values())
    if total:
        lines.append(
            "charges: "
            + " · ".join(
                f"{cls}={n}" for cls, n in sorted(charges.items()) if n
            )
        )
        per_peer = summary["charged_peers"]
        if per_peer:
            worst = sorted(
                per_peer.items(), key=lambda kv: -kv[1]
            )[:8]
            lines.append(
                "worst offenders: "
                + ", ".join(
                    f"peer {k.split(':')[0]} {k.split(':')[1]}x{n}"
                    for k, n in worst
                )
            )
    else:
        lines.append("no contribution charges (clean overlay)")
    lines.append(
        f"level windows: {summary['level_timeouts']} escalations · "
        f"{summary['fallbacks']} ranked fallbacks"
    )
    dem, rec = summary["demotions"], summary["recoveries"]
    if dem or rec:
        lines.append(
            f"demotions {len(dem)} / recoveries {len(rec)} · "
            f"still demoted at journal end: "
            f"{summary['still_demoted'] or 'none'}"
        )
    if summary["rekeys"]:
        lines.append(
            "rekeys: " + " -> ".join(summary["rekeys"][:6])
            + (f" (+{len(summary['rekeys']) - 6} more)"
               if len(summary["rekeys"]) > 6 else "")
        )
    return "\n".join(lines)


def exec_summary(events):
    """Execution-layer posture from the journal alone.

    Decodes the closed ``exec.*`` family (obs/recorder.py) so a saved
    journal from an execution run answers the ledger questions without
    a live sim: how many transactions each replica applied vs rejected,
    whether the per-height state roots agree across every replica that
    reported one, and which stake snapshots fed epoch elections.
    """
    out = {
        "blocks": 0,
        "txs": 0,
        "applied": 0,
        "rejected": 0,
        "device_blocks": 0,
        "host_blocks": 0,
        "per_replica": {},  # replica -> {blocks, txs, applied}
        "roots": {},  # height -> {root8 -> [replicas]}
        "root_forks": [],  # heights where >1 distinct root was reported
        "stake_marks": [],  # (height, detail) epoch stake snapshots
        # Speculation outcomes (exec.spec.* — the pipelined path):
        # replica -> {speculated, signed, confirmed, rolled_back,
        # max_depth}. A replica absent here ran strictly sequential.
        "spec_per_replica": {},
    }

    def spec_rep(replica):
        return out["spec_per_replica"].setdefault(
            replica,
            {"speculated": 0, "signed": 0, "confirmed": 0,
             "rolled_back": 0, "max_depth": 0},
        )

    for ev in events:
        replica, height, kind, detail = ev[1], ev[2], ev[4], ev[5]
        if kind == "exec.apply":
            out["blocks"] += 1
            txs = applied = dev = None
            for part in str(detail or "").split():
                if part.startswith("txs="):
                    txs = int(part[4:])
                elif part.startswith("applied="):
                    applied = int(part[8:])
                elif part.startswith("dev="):
                    dev = int(part[4:])
            rep = out["per_replica"].setdefault(
                replica, {"blocks": 0, "txs": 0, "applied": 0}
            )
            rep["blocks"] += 1
            if txs is not None:
                out["txs"] += txs
                rep["txs"] += txs
            if applied is not None:
                out["applied"] += applied
                rep["applied"] += applied
            if txs is not None and applied is not None:
                out["rejected"] += txs - applied
            if dev:
                out["device_blocks"] += 1
            elif dev is not None:
                out["host_blocks"] += 1
        elif kind == "exec.root":
            root8 = str(detail or "")
            by_root = out["roots"].setdefault(height, {})
            by_root.setdefault(root8, []).append(replica)
        elif kind == "exec.stake":
            out["stake_marks"].append((height, str(detail or "")))
        elif kind == "exec.spec.speculate":
            rep = spec_rep(replica)
            rep["speculated"] += 1
            if str(detail or "") == "signed=1":
                rep["signed"] += 1
        elif kind == "exec.spec.confirm":
            spec_rep(replica)["confirmed"] += 1
        elif kind == "exec.spec.rollback":
            rep = spec_rep(replica)
            rep["rolled_back"] += 1
            d = str(detail or "")
            if d.startswith("depth="):
                rep["max_depth"] = max(rep["max_depth"], int(d[6:]))
    out["root_forks"] = sorted(
        h for h, by_root in out["roots"].items() if len(by_root) > 1
    )
    return out


def render_exec_table(summary):
    """The exec summary as aligned text (the CLI's ``--exec``)."""
    lines = [
        f"{summary['blocks']} applied blocks · "
        f"{summary['txs']} txs ({summary['applied']} applied, "
        f"{summary['rejected']} rejected) · "
        f"route device={summary['device_blocks']} "
        f"host={summary['host_blocks']}"
    ]
    per = summary["per_replica"]
    if per:
        rows = [["replica", "blocks", "txs", "applied"]]
        for rep in sorted(per):
            s = per[rep]
            rows.append(
                [str(rep), str(s["blocks"]), str(s["txs"]),
                 str(s["applied"])]
            )
        widths = [max(len(r[i]) for r in rows) for i in range(4)]
        for i, r in enumerate(rows):
            lines.append("  ".join(c.rjust(w) for c, w in zip(r, widths)))
            if i == 0:
                lines.append("  ".join("-" * w for w in widths))
    spec = summary.get("spec_per_replica") or {}
    if spec:
        lines.append("speculation outcomes:")
        rows = [["replica", "speculated", "signed", "confirmed",
                 "rolled back", "max depth"]]
        for rep in sorted(spec):
            s = spec[rep]
            rows.append(
                [str(rep), str(s["speculated"]), str(s["signed"]),
                 str(s["confirmed"]), str(s["rolled_back"]),
                 str(s["max_depth"])]
            )
        widths = [max(len(r[i]) for r in rows) for i in range(6)]
        for i, r in enumerate(rows):
            lines.append("  ".join(c.rjust(w) for c, w in zip(r, widths)))
            if i == 0:
                lines.append("  ".join("-" * w for w in widths))
    roots = summary["roots"]
    if roots:
        agreed = len(roots) - len(summary["root_forks"])
        lines.append(
            f"state roots: {len(roots)} heights reported · "
            f"{agreed} unanimous"
        )
        if summary["root_forks"]:
            lines.append(
                "ROOT FORKS at heights: "
                + ", ".join(str(h) for h in summary["root_forks"])
            )
    if summary["stake_marks"]:
        lines.append(
            "epoch stake snapshots: "
            + " · ".join(
                f"h{h} {d}" for h, d in summary["stake_marks"][:6]
            )
            + (f" (+{len(summary['stake_marks']) - 6} more)"
               if len(summary["stake_marks"]) > 6 else "")
        )
    return "\n".join(lines)


def proofs_summary(events):
    """Trustless-read posture from the journal alone.

    Decodes the closed ``merkle.*`` / ``proof.*`` families
    (obs/recorder.py): how many proof frames the port served vs shed
    (and at what sizes), how the incremental tree kept up (updates vs
    full-rebuild fallbacks), and whether every replica that reported a
    Merkle root at a height reported the SAME one — a Merkle-root fork
    is state divergence even when the chained exec roots still agree.
    """
    out = {
        "served": 0,
        "shed": 0,
        "bytes_min": None,
        "bytes_max": None,
        "bytes_mean": None,
        "served_heights": {},  # basis height -> frames served
        "shed_tenants": {},  # tenant -> queries shed
        "updates": 0,
        "full_rebuilds": 0,
        "max_targets": 0,
        "depth": None,
        "merkle_roots": {},  # height -> {root8 -> [replicas]}
        "merkle_forks": [],  # heights with >1 distinct Merkle root
    }
    byte_total = 0
    for ev in events:
        replica, height, kind, detail = ev[1], ev[2], ev[4], ev[5]
        if kind == "proof.serve":
            out["served"] += 1
            out["served_heights"][height] = (
                out["served_heights"].get(height, 0) + 1
            )
            for part in str(detail or "").split():
                if part.startswith("bytes="):
                    b = int(part[6:])
                    byte_total += b
                    out["bytes_min"] = (
                        b if out["bytes_min"] is None
                        else min(out["bytes_min"], b)
                    )
                    out["bytes_max"] = (
                        b if out["bytes_max"] is None
                        else max(out["bytes_max"], b)
                    )
        elif kind == "proof.shed":
            out["shed"] += 1
            tenant = str(detail or "")
            out["shed_tenants"][tenant] = (
                out["shed_tenants"].get(tenant, 0) + 1
            )
        elif kind == "merkle.root":
            root8 = str(detail or "")
            by_root = out["merkle_roots"].setdefault(height, {})
            by_root.setdefault(root8, []).append(replica)
        elif kind == "merkle.update":
            out["updates"] += 1
            for part in str(detail or "").split():
                if part.startswith("targets="):
                    out["max_targets"] = max(
                        out["max_targets"], int(part[8:])
                    )
                elif part.startswith("depth="):
                    out["depth"] = int(part[6:])
                elif part.startswith("full=") and int(part[5:]):
                    out["full_rebuilds"] += 1
    if out["served"]:
        out["bytes_mean"] = byte_total / out["served"]
    out["merkle_forks"] = sorted(
        h for h, by_root in out["merkle_roots"].items()
        if len(by_root) > 1
    )
    return out


def render_proofs_table(summary):
    """The proofs summary as aligned text (the CLI's ``--proofs``)."""
    lines = [
        f"{summary['served']} proofs served · "
        f"{summary['shed']} queries shed"
    ]
    if summary["served"]:
        lines.append(
            f"proof frames: {summary['bytes_min']}"
            f"/{summary['bytes_mean']:.0f}/{summary['bytes_max']} "
            "bytes (min/mean/max)"
        )
        rows = [["basis height", "served"]]
        for h in sorted(summary["served_heights"]):
            rows.append([str(h), str(summary["served_heights"][h])])
        widths = [max(len(r[i]) for r in rows) for i in range(2)]
        for i, r in enumerate(rows):
            lines.append("  ".join(c.rjust(w) for c, w in zip(r, widths)))
            if i == 0:
                lines.append("  ".join("-" * w for w in widths))
    if summary["shed_tenants"]:
        lines.append(
            "shed by tenant: "
            + " · ".join(
                f"{t}={n}"
                for t, n in sorted(summary["shed_tenants"].items())
            )
        )
    if summary["updates"]:
        lines.append(
            f"merkle updates: {summary['updates']} "
            f"({summary['full_rebuilds']} full rebuilds) · "
            f"max targets {summary['max_targets']} · "
            f"tree depth {summary['depth']}"
        )
    roots = summary["merkle_roots"]
    if roots:
        agreed = len(roots) - len(summary["merkle_forks"])
        lines.append(
            f"merkle roots: {len(roots)} heights reported · "
            f"{agreed} unanimous"
        )
        if summary["merkle_forks"]:
            lines.append(
                "MERKLE ROOT FORKS at heights: "
                + ", ".join(str(h) for h in summary["merkle_forks"])
            )
    return "\n".join(lines)


#: Finality milestones in doctrine order: the merged event kinds that
#: mark one committed height's journey across the mesh. ``send`` has no
#: kind of its own — it is the ``trace.send`` paired (by "origin:seq")
#: to the height's first ``trace.recv``, usually in ANOTHER process's
#: journal, which is exactly why this report wants merged input.
_CP_MILESTONES = (
    ("send", ()),
    ("recv", ("trace.recv",)),
    ("submit", ("service.remote.submit",)),
    ("verify", ("verify.launch", "sched.launch.begin", "tally.launch")),
    ("cert", ("cert.emit",)),
    ("resolve", ("service.remote.resolve",)),
    ("commit", ("commit",)),
    ("apply", ("exec.apply",)),
)


def critical_path_summary(events):
    """Finality critical-path attribution over a (merged) journal.

    Walks each committed height's event chain — frame send → peer
    receive → coalesced verify launch → cert mint → gated commit →
    apply drain — and names the hop that dominated its wall time.
    Milestones are the FIRST event of each kind at that height; hops
    are the gaps between consecutive milestones in time order, so they
    telescope to exactly the height's first-to-last span (100% of the
    wall time is attributed to named hops by construction).
    """
    kind_to_ms = {}
    for name, kinds in _CP_MILESTONES:
        for kind in kinds:
            kind_to_ms[kind] = name
    order = {name: i for i, (name, _) in enumerate(_CP_MILESTONES)}
    send_ts = {}  # trace span key -> earliest (aligned) send ts
    recv_key = {}  # height -> span key of its first trace.recv
    marks = {}  # height -> {milestone -> ts}
    for ev in events:
        ts, height, kind, detail = ev[0], ev[2], ev[4], ev[5]
        if kind == "trace.send" and detail:
            key = str(detail)
            if key not in send_ts or ts < send_ts[key]:
                send_ts[key] = ts
            continue
        if height < 0:
            continue
        name = kind_to_ms.get(kind)
        if name is None:
            continue
        ms = marks.setdefault(height, {})
        if name not in ms:
            ms[name] = ts
            if kind == "trace.recv" and detail:
                recv_key[height] = str(detail)
    rows = []
    aggregate = {}
    for height in sorted(marks):
        ms = marks[height]
        key = recv_key.get(height)
        if key is not None and key in send_ts:
            ms["send"] = send_ts[key]
        if len(ms) < 2:
            continue
        # Time order (milestone order as tiebreak) keeps the hops
        # telescoping even if clock alignment slightly reordered two
        # milestones — attribution stays exact, never negative.
        chain = sorted(ms.items(), key=lambda kv: (kv[1], order[kv[0]]))
        hops = []
        for (a, ta), (b, tb) in zip(chain, chain[1:]):
            label = f"{a}→{b}"
            hops.append((label, tb - ta))
            aggregate[label] = aggregate.get(label, 0.0) + (tb - ta)
        total = chain[-1][1] - chain[0][1]
        dominant, dominant_s = max(hops, key=lambda h: h[1])
        rows.append({
            "height": height,
            "milestones": dict(chain),
            "hops": hops,
            "total_s": total,
            "dominant": dominant,
            "dominant_s": dominant_s,
            "attributed": 1.0 if total > 0 else 0.0,
        })
    out = {"rows": rows, "aggregate": aggregate}
    if aggregate:
        dom = max(aggregate.items(), key=lambda kv: kv[1])
        out["dominant"] = dom[0]
        out["dominant_s"] = dom[1]
    return out


def render_critical_path_table(summary):
    """The critical-path rows as aligned text (the CLI's
    ``--critical-path``)."""
    rows = summary["rows"]
    if not rows:
        return "no committed heights with ≥2 finality milestones"
    table = [["ht", "total", "dominant hop", "share", "hops"]]
    for r in rows:
        share = r["dominant_s"] / r["total_s"] if r["total_s"] > 0 else 0.0
        table.append([
            str(r["height"]),
            _fmt(r["total_s"]),
            r["dominant"],
            f"{share:.0%}",
            " · ".join(f"{name}={dur:.4f}" for name, dur in r["hops"]),
        ])
    widths = [max(len(row[i]) for row in table) for i in range(5)]
    lines = []
    for i, row in enumerate(table):
        lines.append("  ".join(c.ljust(w) for c, w in zip(row, widths)))
        if i == 0:
            lines.append("  ".join("-" * w for w in widths))
    total = sum(r["total_s"] for r in rows)
    agg = summary["aggregate"]
    if total > 0 and agg:
        shares = " · ".join(
            f"{name}={dur / total:.0%}"
            for name, dur in sorted(
                agg.items(), key=lambda kv: -kv[1]
            )
        )
        lines.append(
            f"aggregate over {len(rows)} heights "
            f"({total:.4f}s attributed 100% to named hops): {shares}"
        )
    return "\n".join(lines)


def _detail_ints(detail):
    """Parse a ``k=v`` event detail string into an int dict.

    Campaign events carry compact ``rows=352 failed=256 level=2``
    payloads; ``seats=4/64`` splits into ``seats`` and ``of``. Tokens
    that don't parse are skipped so the decoder never throws on a
    journal written by a newer engine.
    """
    out = {}
    if not isinstance(detail, str):
        return out
    for tok in detail.split():
        if "=" not in tok:
            continue
        key, _, val = tok.partition("=")
        if "/" in val:
            val, _, denom = val.partition("/")
            try:
                out["of"] = int(denom)
            except ValueError:
                pass
        try:
            out[key] = int(val)
        except ValueError:
            pass
    return out


def campaign_summary(events):
    """Attack-campaign posture from the journal alone.

    Decodes the closed ``campaign.*`` family the campaign engines emit
    (hyperdrive_tpu/campaign/families.py) plus the admission gate's
    ``admission.reputation.*`` feedback loop, so a journal saved by
    ``python -m hyperdrive_tpu.campaign run`` (or a violation dump's
    sidecar journal) is diagnosable offline: which families ran, how
    each storm wave degraded and recovered, the adversary's per-epoch
    committee-seat trajectory vs its passive baseline, partition slices
    and the heal runway, reputation charges/demotions/recoveries, and
    any monitor violations with their final digests.
    """
    out = {
        "families": [],
        "waves": [],
        "epochs": [],
        "grinds": [],
        "partitions": [],
        "heal_runway": None,
        "violations": [],
        "done": [],
        "reputation": {
            "charges": {},
            "charge_total": 0,
            "demotions": 0,
            "recoveries": 0,
        },
    }
    rep = out["reputation"]
    for ev in events:
        height, kind, detail = ev[2], ev[4], ev[5]
        if kind == "campaign.family":
            out["families"].append(str(detail))
        elif kind == "campaign.wave":
            d = _detail_ints(detail)
            d["height"] = height
            out["waves"].append(d)
        elif kind == "campaign.epoch":
            d = _detail_ints(detail)
            d["height"] = height
            out["epochs"].append(d)
        elif kind == "campaign.grind":
            d = _detail_ints(detail)
            d["height"] = height
            out["grinds"].append(d)
        elif kind == "campaign.partition":
            d = _detail_ints(detail)
            d["height"] = height
            out["partitions"].append(d)
        elif kind == "campaign.heal":
            d = _detail_ints(detail)
            out["heal_runway"] = d.get("runway")
        elif kind == "campaign.violation":
            out["violations"].append(str(detail))
        elif kind == "campaign.done":
            out["done"].append(str(detail))
        elif kind == "admission.reputation.charge":
            cls = detail if isinstance(detail, str) else "?"
            rep["charges"][cls] = rep["charges"].get(cls, 0) + 1
            rep["charge_total"] += 1
        elif kind == "admission.reputation.demote":
            rep["demotions"] += 1
        elif kind == "admission.reputation.recover":
            rep["recoveries"] += 1
    return out


def render_campaign_table(summary):
    """The campaign summary as aligned text (the CLI's ``--campaign``)."""
    lines = []
    if summary["families"]:
        lines.append("families: " + " · ".join(summary["families"]))
    waves = summary["waves"]
    if waves:
        table = [["wave", "ht", "verified", "failed", "level"]]
        for i, w in enumerate(waves):
            table.append([
                str(i),
                str(w.get("height", "-")),
                str(w.get("rows", "-")),
                str(w.get("failed", "-")),
                str(w.get("level", "-")),
            ])
        widths = [max(len(r[i]) for r in table) for i in range(5)]
        for i, r in enumerate(table):
            lines.append("  ".join(c.rjust(w) for c, w in zip(r, widths)))
            if i == 0:
                lines.append("  ".join("-" * w for w in widths))
        failed = sum(w.get("failed", 0) for w in waves)
        lines.append(
            f"storm: {len(waves)} waves · "
            f"{failed} forged rows died at batch verify"
        )
    epochs = summary["epochs"]
    if epochs:
        grind_by_h = {g.get("height"): g for g in summary["grinds"]}
        part_by_h = {p.get("height"): p for p in summary["partitions"]}
        table = [["epoch", "ht", "adv seats", "grind", "partition"]]
        for e in epochs:
            h = e.get("height")
            g = grind_by_h.get(h)
            p = part_by_h.get(h)
            table.append([
                str(e.get("e", "-")),
                str(h),
                "%s/%s" % (e.get("seats", "-"), e.get("of", "-")),
                "cand=%s +%s" % (
                    g.get("cand", "-"),
                    g.get("seats", 0) - g.get("passive", 0),
                ) if g else "-",
                "lvl=%s sliced=%s" % (
                    p.get("level", "-"), p.get("sliced", "-"),
                ) if p else "-",
            ])
        widths = [max(len(r[i]) for r in table) for i in range(5)]
        for i, r in enumerate(table):
            lines.append("  ".join(c.rjust(w) for c, w in zip(r, widths)))
            if i == 0:
                lines.append("  ".join("-" * w for w in widths))
        seats = sum(e.get("seats", 0) for e in epochs)
        lines.append(
            f"capture: {len(epochs)} epochs · "
            f"{seats} adversary seats total"
        )
    if summary["heal_runway"] is not None:
        lines.append(f"heal runway: {summary['heal_runway']} heights")
    rep = summary["reputation"]
    if rep["charge_total"] or rep["demotions"] or rep["recoveries"]:
        by_cls = ", ".join(
            f"{c}={n}" for c, n in sorted(rep["charges"].items())
        )
        lines.append(
            f"reputation: {rep['charge_total']} charges ({by_cls}) · "
            f"{rep['demotions']} demotions · "
            f"{rep['recoveries']} recoveries"
        )
    for v in summary["violations"]:
        lines.append(f"VIOLATION: {v}")
    for d in summary["done"]:
        lines.append(f"done: {d}")
    if not lines:
        return "no campaign.* events in journal window"
    return "\n".join(lines)


def _fmt(v):
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.4f}"
    return str(v)


def render_table(rows):
    """The anatomy rows as an aligned text table (the CLI's output)."""
    cols = [
        ("ht", "height"),
        ("rep", "replica"),
        ("rnds", "rounds"),
        ("propose", "propose_s"),
        ("prevote", "prevote_s"),
        ("precommit", "precommit_s"),
        ("stall", "stall_s"),
        ("total", "total_s"),
        ("t/o", "timeouts"),
        ("flags", "flags"),
    ]
    table = [[h for h, _ in cols]]
    for r in rows:
        table.append(
            [
                ",".join(r[k]) if k == "flags" else _fmt(r[k])
                for _, k in cols
            ]
        )
    widths = [max(len(row[i]) for row in table) for i in range(len(cols))]
    lines = []
    for i, row in enumerate(table):
        lines.append("  ".join(c.rjust(w) for c, w in zip(row, widths)))
        if i == 0:
            lines.append("  ".join("-" * w for w in widths))
    return "\n".join(lines)
