"""``python -m hyperdrive_tpu.obs`` — record, report, export, metrics,
benchdiff.

    record     run a short observed sim and save its event journal
    report     render the round-anatomy table from a saved journal
               (``--tenants`` for per-origin device-launch latency,
               ``--overload`` for admission/shed posture,
               ``--overlay`` for aggregation-overlay posture,
               ``--exec`` for execution-layer/state-root posture,
               ``--proofs`` for trustless-read/Merkle posture,
               ``--campaign`` for attack-campaign posture,
               ``--critical-path`` for per-commit finality hop
               attribution — most useful on a merged journal)
    merge      fold N per-process journals into one causally-
               consistent stream (clock-aligned, pid-stamped)
    export     convert a saved journal to Perfetto/Chrome trace JSON
               (merged journals render per-process tracks + cross-
               process flow arrows)
    metrics    run a short observed sim, print its metrics-registry
               snapshot (JSON; ``--prometheus FILE`` for exposition text)
    benchdiff  diff two bench artifacts, exit nonzero on a gated
               perf regression (the CI sentinel)

``record`` exists so CI (and anyone without a saved journal) can go
from nothing to a viewable trace in two commands:

    python -m hyperdrive_tpu.obs record -o journal.json
    python -m hyperdrive_tpu.obs export journal.json -o trace.json
"""

from __future__ import annotations

import argparse
import json
import sys

from hyperdrive_tpu.obs.recorder import load_journal
from hyperdrive_tpu.obs.report import (
    anatomy,
    campaign_summary,
    critical_path_summary,
    exec_summary,
    overlay_summary,
    overload_summary,
    phase_summary,
    proofs_summary,
    render_campaign_table,
    render_critical_path_table,
    render_exec_table,
    render_proofs_table,
    render_overlay_table,
    render_overload_table,
    render_table,
    render_tenant_table,
    tenant_summary,
)
from hyperdrive_tpu.obs.perfetto import export


def _cmd_record(ns):
    # Imported here: the sim pulls in jax; report/export stay stdlib.
    from hyperdrive_tpu.harness import Simulation

    sim = Simulation(
        n=ns.replicas,
        target_height=ns.heights,
        seed=ns.seed,
        timeout=ns.timeout,
        delivery_cost=ns.delivery_cost,
        observe=True,
    )
    res = sim.run()
    sim.obs.save(ns.output)
    print(
        json.dumps(
            {
                "completed": res.completed,
                "events": len(sim.obs),
                "dropped": sim.obs.dropped,
                "digest": sim.obs.digest(),
                "journal": ns.output,
            }
        )
    )
    return 0 if res.completed else 1


def _cmd_report(ns):
    journal = load_journal(ns.journal)
    if ns.campaign:
        summary = campaign_summary(journal["events"])
        if ns.json:
            print(json.dumps({"campaign": summary}, indent=1))
            return 0
        if not (summary["families"] or summary["waves"]
                or summary["epochs"]
                or summary["reputation"]["charge_total"]):
            print("no campaign.* events in journal window "
                  "(record one: python -m hyperdrive_tpu.campaign run "
                  "— violation dumps ship a sidecar journal)")
            return 1
        print(render_campaign_table(summary))
        return 0
    if ns.critical_path:
        summary = critical_path_summary(journal["events"])
        if ns.json:
            print(json.dumps({"critical_path": summary}, indent=1))
            return 0
        if not summary["rows"]:
            print("no committed heights with >=2 finality milestones "
                  "in journal window (merge per-process journals first: "
                  "python -m hyperdrive_tpu.obs merge ...)")
            return 1
        print(render_critical_path_table(summary))
        return 0
    if ns.exec:
        summary = exec_summary(journal["events"])
        if ns.json:
            print(json.dumps({"exec": summary}, indent=1))
            return 0
        if not (summary["blocks"] or summary["roots"]
                or summary["stake_marks"]):
            print("no exec.* events in journal window "
                  "(record an execution run: Simulation(execution=...))")
            return 1
        print(render_exec_table(summary))
        return 0
    if ns.proofs:
        summary = proofs_summary(journal["events"])
        if ns.json:
            print(json.dumps({"proofs": summary}, indent=1))
            return 0
        if not (summary["served"] or summary["shed"]
                or summary["updates"] or summary["merkle_roots"]):
            print("no merkle.*/proof.* events in journal window "
                  "(record an execution run and serve queries through "
                  "the service port)")
            return 1
        print(render_proofs_table(summary))
        return 0
    if ns.overlay:
        summary = overlay_summary(journal["events"])
        if ns.json:
            print(json.dumps({"overlay": summary}, indent=1))
            return 0
        if not (
            summary["frames"]
            or summary["level_timeouts"]
            or summary["fallbacks"]
            or sum(summary["charges"].values())
        ):
            print("no overlay.* events in journal window "
                  "(record an overlay run: Simulation(overlay=...))")
            return 1
        print(render_overlay_table(summary))
        return 0
    if ns.overload:
        summary = overload_summary(journal["events"])
        if ns.json:
            print(json.dumps({"overload": summary}, indent=1))
            return 0
        if not (
            summary["injected"]
            or summary["shed_total"]
            or summary["level_timeline"]
            or summary["wire_shed"]
            or summary["reconnects"]
        ):
            print("no load.*/admission.* events in journal window "
                  "(record an overloaded run: Simulation(load=...))")
            return 1
        print(render_overload_table(summary))
        return 0
    if ns.tenants:
        rows = tenant_summary(journal["events"])
        if ns.json:
            print(json.dumps({"tenants": rows}, indent=1))
            return 0
        if not rows:
            print("no sched.launch.* events in journal window "
                  "(record with device telemetry on)")
            return 1
        print(render_tenant_table(rows))
        return 0
    rows = anatomy(journal["events"])
    if ns.json:
        print(
            json.dumps(
                {"rows": rows, "summary": phase_summary(journal["events"])},
                indent=1,
            )
        )
        return 0
    if not rows:
        print("no committed heights in journal window")
        return 1
    print(render_table(rows))
    summary = phase_summary(journal["events"])
    print()
    print(
        f"{summary['commits']} commits · "
        f"mean rounds {summary['mean_rounds']:.2f} · "
        f"mean total {summary['mean_total_s']:.4f}s · "
        f"timeout-driven {summary['timeout_driven']} · "
        f"extra-round {summary['extra_round_commits']}"
    )
    if journal.get("dropped"):
        print(
            f"(ring dropped {journal['dropped']} oldest events; "
            "raise obs_capacity for full anatomy)"
        )
    return 0


def _cmd_merge(ns):
    from hyperdrive_tpu.obs.merge import (
        merge_journals,
        merged_digest,
        save_merged,
    )

    journals = [load_journal(path) for path in ns.journals]
    merged = merge_journals(journals)
    save_merged(merged, ns.output)
    print(
        json.dumps(
            {
                "merged": ns.output,
                "journals": len(journals),
                "origins": merged["meta"]["origins"],
                "events": len(merged["events"]),
                "orphans": len(merged["meta"]["orphans"]),
                "digest": merged_digest(merged),
            }
        )
    )
    return 0


def _cmd_export(ns):
    journal = load_journal(ns.journal)
    doc = export(journal["events"], ns.output)
    print(
        json.dumps(
            {"trace": ns.output, "events": len(doc["traceEvents"])}
        )
    )
    return 0


def _cmd_metrics(ns):
    # Imported here: the sim pulls in jax; the registry itself is stdlib.
    from hyperdrive_tpu.harness import Simulation
    from hyperdrive_tpu.obs.metrics import to_prometheus

    extra = {}
    if ns.pipeline:
        # pipeline_heights requires burst mode and a batch verifier;
        # sign=True supplies the jax-free HostVerifier default.
        extra = dict(sign=True, burst=True, pipeline_heights=True)
    sim = Simulation(
        n=ns.replicas,
        target_height=ns.heights,
        seed=ns.seed,
        timeout=ns.timeout,
        delivery_cost=ns.delivery_cost,
        observe=True,
        **extra,
    )
    res = sim.run()
    snap = sim.metrics_snapshot()
    if ns.output:
        with open(ns.output, "w") as fh:
            json.dump(snap, fh, indent=1, sort_keys=True)
            fh.write("\n")
    if ns.prometheus:
        with open(ns.prometheus, "w") as fh:
            fh.write(to_prometheus(snap))
    print(
        json.dumps(
            {
                "completed": res.completed,
                "counters": len(snap["counters"]),
                "gauges": len(snap["gauges"]),
                "histograms": len(snap["histograms"]),
                "digest": sim.registry.digest(),
                "snapshot": ns.output,
                "prometheus": ns.prometheus,
            }
        )
    )
    return 0 if res.completed else 1


def _cmd_benchdiff(ns):
    from hyperdrive_tpu.obs.benchdiff import main as benchdiff_main

    return benchdiff_main(
        ns.old,
        ns.new,
        threshold=ns.threshold,
        gates=ns.gate or None,
        as_json=ns.json,
    )


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m hyperdrive_tpu.obs",
        description="consensus flight recorder tooling (OBSERVABILITY.md)",
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    rec = sub.add_parser("record", help="run an observed sim, save journal")
    rec.add_argument("-o", "--output", default="journal.json")
    rec.add_argument("--replicas", type=int, default=4)
    rec.add_argument("--heights", type=int, default=5)
    rec.add_argument("--seed", type=int, default=91)
    rec.add_argument("--timeout", type=float, default=20.0)
    rec.add_argument("--delivery-cost", type=float, default=0.001)
    rec.set_defaults(fn=_cmd_record)

    rep = sub.add_parser("report", help="round-anatomy table from journal")
    rep.add_argument("journal")
    rep.add_argument("--json", action="store_true")
    rep.add_argument(
        "--tenants",
        action="store_true",
        help="per-origin device-launch latency summary instead",
    )
    rep.add_argument(
        "--overload",
        action="store_true",
        help="overload/admission posture summary instead "
             "(load.*, admission.*, wire.frame.* events)",
    )
    rep.add_argument(
        "--overlay",
        action="store_true",
        help="aggregation-overlay posture summary instead "
             "(the closed overlay.* family: frames, charges, "
             "escalations, demotions)",
    )
    rep.add_argument(
        "--exec",
        action="store_true",
        help="execution-layer posture summary instead "
             "(the closed exec.* family: applied blocks, state-root "
             "agreement, epoch stake snapshots)",
    )
    rep.add_argument(
        "--proofs",
        action="store_true",
        help="trustless-read posture summary instead "
             "(the closed merkle.*/proof.* families: proofs served vs "
             "shed, frame sizes, incremental-update posture, per-height "
             "Merkle-root agreement)",
    )
    rep.add_argument(
        "--campaign",
        action="store_true",
        help="attack-campaign posture summary instead "
             "(the closed campaign.*/admission.reputation.* families: "
             "storm waves, per-epoch adversary seat trajectory, grind "
             "candidates, partitions, reputation loop, violations)",
    )
    rep.add_argument(
        "--critical-path",
        dest="critical_path",
        action="store_true",
        help="per-commit finality critical path instead: walk each "
             "committed height's event chain (frame send -> peer recv "
             "-> verify launch -> cert mint -> gated commit -> apply "
             "drain) and name the dominating hop",
    )
    rep.set_defaults(fn=_cmd_report)

    mrg = sub.add_parser(
        "merge",
        help="fold N per-process journals into one aligned stream",
    )
    mrg.add_argument("journals", nargs="+",
                     help="per-process journal files (>=1)")
    mrg.add_argument("-o", "--output", default="merged.json")
    mrg.set_defaults(fn=_cmd_merge)

    exp = sub.add_parser("export", help="journal -> Perfetto trace JSON")
    exp.add_argument("journal")
    exp.add_argument("-o", "--output", default="trace.json")
    exp.set_defaults(fn=_cmd_export)

    met = sub.add_parser(
        "metrics", help="run an observed sim, print registry snapshot"
    )
    met.add_argument("-o", "--output", default=None,
                     help="also write the snapshot JSON here")
    met.add_argument("--prometheus", default=None,
                     help="write Prometheus exposition text here")
    met.add_argument("--replicas", type=int, default=4)
    met.add_argument("--heights", type=int, default=5)
    met.add_argument("--seed", type=int, default=91)
    met.add_argument("--timeout", type=float, default=20.0)
    met.add_argument("--delivery-cost", type=float, default=0.001)
    met.add_argument("--pipeline", action="store_true",
                     help="pipelined heights (exercises device telemetry)")
    met.set_defaults(fn=_cmd_metrics)

    bd = sub.add_parser(
        "benchdiff", help="perf sentinel: diff two bench JSON artifacts"
    )
    bd.add_argument("old")
    bd.add_argument("new")
    bd.add_argument("--threshold", type=float, default=0.08)
    bd.add_argument("--gate", action="append", default=[],
                    help="extra gated metric path (repeatable)")
    bd.add_argument("--json", action="store_true")
    bd.set_defaults(fn=_cmd_benchdiff)

    ns = p.parse_args(argv)
    return ns.fn(ns)


if __name__ == "__main__":
    sys.exit(main())
