"""``python -m hyperdrive_tpu.obs`` — record, report, export.

    record  run a short observed sim and save its event journal
    report  render the round-anatomy table from a saved journal
    export  convert a saved journal to Perfetto/Chrome trace JSON

``record`` exists so CI (and anyone without a saved journal) can go
from nothing to a viewable trace in two commands:

    python -m hyperdrive_tpu.obs record -o journal.json
    python -m hyperdrive_tpu.obs export journal.json -o trace.json
"""

from __future__ import annotations

import argparse
import json
import sys

from hyperdrive_tpu.obs.recorder import load_journal
from hyperdrive_tpu.obs.report import anatomy, phase_summary, render_table
from hyperdrive_tpu.obs.perfetto import export


def _cmd_record(ns):
    # Imported here: the sim pulls in jax; report/export stay stdlib.
    from hyperdrive_tpu.harness import Simulation

    sim = Simulation(
        n=ns.replicas,
        target_height=ns.heights,
        seed=ns.seed,
        timeout=ns.timeout,
        delivery_cost=ns.delivery_cost,
        observe=True,
    )
    res = sim.run()
    sim.obs.save(ns.output)
    print(
        json.dumps(
            {
                "completed": res.completed,
                "events": len(sim.obs),
                "dropped": sim.obs.dropped,
                "digest": sim.obs.digest(),
                "journal": ns.output,
            }
        )
    )
    return 0 if res.completed else 1


def _cmd_report(ns):
    journal = load_journal(ns.journal)
    rows = anatomy(journal["events"])
    if ns.json:
        print(
            json.dumps(
                {"rows": rows, "summary": phase_summary(journal["events"])},
                indent=1,
            )
        )
        return 0
    if not rows:
        print("no committed heights in journal window")
        return 1
    print(render_table(rows))
    summary = phase_summary(journal["events"])
    print()
    print(
        f"{summary['commits']} commits · "
        f"mean rounds {summary['mean_rounds']:.2f} · "
        f"mean total {summary['mean_total_s']:.4f}s · "
        f"timeout-driven {summary['timeout_driven']} · "
        f"extra-round {summary['extra_round_commits']}"
    )
    if journal.get("dropped"):
        print(
            f"(ring dropped {journal['dropped']} oldest events; "
            "raise obs_capacity for full anatomy)"
        )
    return 0


def _cmd_export(ns):
    journal = load_journal(ns.journal)
    doc = export(journal["events"], ns.output)
    print(
        json.dumps(
            {"trace": ns.output, "events": len(doc["traceEvents"])}
        )
    )
    return 0


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m hyperdrive_tpu.obs",
        description="consensus flight recorder tooling (OBSERVABILITY.md)",
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    rec = sub.add_parser("record", help="run an observed sim, save journal")
    rec.add_argument("-o", "--output", default="journal.json")
    rec.add_argument("--replicas", type=int, default=4)
    rec.add_argument("--heights", type=int, default=5)
    rec.add_argument("--seed", type=int, default=91)
    rec.add_argument("--timeout", type=float, default=20.0)
    rec.add_argument("--delivery-cost", type=float, default=0.001)
    rec.set_defaults(fn=_cmd_record)

    rep = sub.add_parser("report", help="round-anatomy table from journal")
    rep.add_argument("journal")
    rep.add_argument("--json", action="store_true")
    rep.set_defaults(fn=_cmd_report)

    exp = sub.add_parser("export", help="journal -> Perfetto trace JSON")
    exp.add_argument("journal")
    exp.add_argument("-o", "--output", default="trace.json")
    exp.set_defaults(fn=_cmd_export)

    ns = p.parse_args(argv)
    return ns.fn(ns)


if __name__ == "__main__":
    sys.exit(main())
