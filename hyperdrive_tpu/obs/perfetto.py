"""Chrome/Perfetto trace JSON exporter for recorder journals.

Emits the Trace Event Format (the JSON flavour ui.perfetto.dev and
chrome://tracing both load): one track (tid) per replica, ``B``/``E``
duration spans for rounds and consensus phases, and ``i`` instant
events for timeout fires, commits, equivocations, and wire anomalies.
Timestamps are the journal's (virtual) seconds scaled to microseconds,
so a sim second reads as a second in the UI.

Device telemetry (``sched.launch.*`` events, obs/devtel.py) renders as
its own **device** track (tid -3): one complete slice per coalesced
launch carrying the probe's args (rows, lanes, occupancy %, queue
wait), plus flow arrows stitching the cross-layer story together —
``cmdflow`` from each submitter's ``submit`` slice to the launch that
carried the command, and ``commitflow`` from the launch back to every
gated commit it finalized, so a commit's wall time decomposes across
the host/device boundary in one trace.

Merged journals (obs/merge.py) render MULTI-process: each event's
seventh slot is its origin pid, so every process gets its own Perfetto
process group (its own replica/device/sim tracks), and the causal
trace spans draw as ``traceflow`` arrows from each ``trace.send``
slice to its matching ``trace.recv`` slice — usually in ANOTHER
process's group. A per-process journal (6-tuples) renders exactly as
before under pid 0.
"""

from __future__ import annotations

import json

__all__ = ["to_trace_events", "export"]

PID = 0

#: The device track's tid: one rung below the devsched queue track
#: (-2), mirroring the journal's replica>=0 / sim -1 / devsched -2
#: layering.
DEVICE_TID = -3

_INSTANTS = {
    "timeout.propose.fired": "timeout propose",
    "timeout.prevote.fired": "timeout prevote",
    "timeout.precommit.fired": "timeout precommit",
    "commit": "commit",
    "equivocation": "equivocation",
    "round.skip": "round skip",
    "height.resync": "height resync",
    "mq.drop": "mq drop",
    "wire.frame.malformed": "frame malformed",
    "wire.frame.oversize": "frame oversize",
    "wire.frame.shed": "frame shed",
    "settle.speculative": "speculative settle",
    "verify.rlc.fallbacks": "rlc fallback",
    "sched.coalesce": "coalesce",
    "sched.drain": "drain",
    "sched.gated": "commit gated",
    "sched.launch.split": "gen split",
    "epoch.begin": "epoch begin",
    "epoch.elect": "epoch elect",
    "epoch.switch": "epoch switch",
    "epoch.proof": "epoch proof",
    "epoch.stale_vote": "stale vote",
    "trace.offset": "clock offset",
    "metrics.serve": "metrics served",
    "metrics.shed": "metrics shed",
    "slo.breach": "SLO BREACH",
}

_PHASE_OPENERS = {
    "round.start": ("propose", "phase"),
    "step.prevoting": ("prevote", "phase"),
    "step.precommitting": ("precommit", "phase"),
}


def _us(ts):
    return max(0.0, ts * 1e6)


def _span_fid(detail):
    """The traceflow arrow id for one "origin:seq" span key, or None
    for a malformed detail (a flow id must be an int)."""
    origin_s, _, seq_s = str(detail).partition(":")
    try:
        return (int(origin_s) << 32) | (int(seq_s) & 0xFFFFFFFF)
    except ValueError:
        return None


def to_trace_events(events):
    """Journal events -> list of Chrome trace event dicts."""
    out = []
    tracks = set()  # (pid, tid) pairs seen
    # Per-(pid, replica) open-span state: rounds nest phases, so the
    # phase span must close before the round span that contains it.
    open_round = {}  # (pid, tid) -> (height, round)
    open_phase = {}  # (pid, tid) -> name

    def begin(pid, tid, ts, name, cat, args=None):
        ev = {
            "ph": "B",
            "ts": _us(ts),
            "pid": pid,
            "tid": tid,
            "name": name,
            "cat": cat,
        }
        if args:
            ev["args"] = args
        out.append(ev)

    def end(pid, tid, ts):
        out.append({"ph": "E", "ts": _us(ts), "pid": pid, "tid": tid})

    def close_phase(key, ts):
        if open_phase.pop(key, None) is not None:
            end(key[0], key[1], ts)

    def close_round(key, ts):
        close_phase(key, ts)
        if open_round.pop(key, None) is not None:
            end(key[0], key[1], ts)

    # Running queue depth for the devsched track's counter series —
    # reconstructed from the journal (submits raise it, a drain zeroes
    # it), so the counter is as deterministic as the journal itself.
    # Per-pid: each process owns its own queue.
    sched_depth = {}

    # Device-track state (sched.launch.* events), per pid: the launch
    # being assembled (begin..end bracket), completed launches' time
    # spans for the commit flows, and a running id for commitflow
    # arrows (each Chrome flow id is one polyline, so N commits off
    # one launch need N distinct ids).
    launch_open = {}  # pid -> open-launch state
    launch_spans = {}  # (pid, launch_id) -> (begin_ts, end_ts)
    commit_flows = 0

    def flow(ph, ts, pid, tid, fid, cat, name):
        ev = {
            "ph": ph,
            "ts": _us(ts),
            "pid": pid,
            "tid": tid,
            "id": fid,
            "cat": cat,
            "name": name,
        }
        if ph == "f":
            ev["bp"] = "e"
        out.append(ev)

    for ev in events:
        ts, replica, height, round_, kind, detail = (
            ev[0], ev[1], ev[2], ev[3], ev[4], ev[5],
        )
        pid = ev[6] if len(ev) > 6 else PID
        tid = replica
        key = (pid, tid)
        tracks.add(key)
        if kind == "sched.submit" or kind == "sched.drain":
            depth = (
                sched_depth.get(pid, 0) + 1
                if kind == "sched.submit" else 0
            )
            sched_depth[pid] = depth
            out.append(
                {
                    "ph": "C",
                    "ts": _us(ts),
                    "pid": pid,
                    "tid": tid,
                    "name": "sched.depth",
                    "args": {"depth": depth},
                }
            )
        if kind.startswith("sched.launch."):
            if kind == "sched.launch.submit":
                # A zero-ish slice anchors the flow start on the
                # submitter's track (flows bind to slices, and the sim
                # track has no spans of its own).
                out.append(
                    {
                        "ph": "X",
                        "ts": _us(ts),
                        "dur": 1.0,
                        "pid": pid,
                        "tid": tid,
                        "name": "submit",
                        "cat": "devtel",
                        "args": {"seq": detail},
                    }
                )
                flow("s", ts, pid, tid, int(detail), "cmdflow", "cmd")
            elif kind == "sched.launch.begin":
                launch_open[pid] = {
                    "id": detail,
                    "ts": ts,
                    "cmds": [],
                    "args": {"launch_id": detail},
                }
            elif kind == "sched.launch.cmd":
                if launch_open.get(pid) is not None:
                    launch_open[pid]["cmds"].append(detail)
            elif kind in (
                "sched.launch.rows",
                "sched.launch.lanes",
                "sched.launch.occupancy",
                "sched.launch.queue_wait",
            ):
                if launch_open.get(pid) is not None:
                    leaf = kind.rsplit(".", 1)[1]
                    launch_open[pid]["args"][leaf] = detail
            elif kind == "sched.launch.end":
                lo = launch_open.get(pid)
                if lo is not None:
                    tracks.add((pid, DEVICE_TID))
                    t0 = lo["ts"]
                    args = lo["args"]
                    args["commands"] = len(lo["cmds"])
                    out.append(
                        {
                            "ph": "X",
                            "ts": _us(t0),
                            "dur": max(_us(ts) - _us(t0), 1.0),
                            "pid": pid,
                            "tid": DEVICE_TID,
                            "name": f"launch {lo['id']}",
                            "cat": "launch",
                            "args": args,
                        }
                    )
                    for seq in lo["cmds"]:
                        flow("f", t0, pid, DEVICE_TID, int(seq),
                             "cmdflow", "cmd")
                    launch_spans[(pid, lo["id"])] = (t0, ts)
                    launch_open[pid] = None
            elif kind == "sched.launch.commit":
                out.append(
                    {
                        "ph": "X",
                        "ts": _us(ts),
                        "dur": 1.0,
                        "pid": pid,
                        "tid": tid,
                        "name": "commit finalize",
                        "cat": "devtel",
                        "args": {"height": height, "launch_id": detail},
                    }
                )
                span = launch_spans.get((pid, detail))
                if span is not None:
                    commit_flows += 1
                    flow("s", span[0], pid, DEVICE_TID, commit_flows,
                         "commitflow", "commit")
                    flow("f", ts, pid, tid, commit_flows,
                         "commitflow", "commit")

        if kind in ("trace.send", "trace.recv") and detail is not None:
            # Cross-process causal spans: an anchoring slice per end
            # (flows bind to slices) and one traceflow arrow per
            # "origin:seq" span key — in a merged journal the two ends
            # usually live in DIFFERENT process groups, which is the
            # arrow the per-process exports could never draw.
            fid = _span_fid(detail)
            if fid is not None:
                sendside = kind == "trace.send"
                out.append(
                    {
                        "ph": "X",
                        "ts": _us(ts),
                        "dur": 1.0,
                        "pid": pid,
                        "tid": tid,
                        "name": "frame send" if sendside else "frame recv",
                        "cat": "trace",
                        "args": {"span": detail, "height": height},
                    }
                )
                flow("s" if sendside else "f", ts, pid, tid, fid,
                     "traceflow", "frame")

        if kind == "round.start":
            close_round(key, ts)
            begin(
                pid,
                tid,
                ts,
                f"h{height} r{round_}",
                "round",
                {"height": height, "round": round_},
            )
            open_round[key] = (height, round_)
            begin(pid, tid, ts, "propose", "phase")
            open_phase[key] = "propose"
        elif kind in ("step.prevoting", "step.precommitting"):
            close_phase(key, ts)
            name = _PHASE_OPENERS[kind][0]
            begin(pid, tid, ts, name, "phase")
            open_phase[key] = name

        if kind in _INSTANTS:
            inst = {
                "ph": "i",
                "ts": _us(ts),
                "pid": pid,
                "tid": tid,
                "name": _INSTANTS[kind],
                "cat": kind.split(".", 1)[0],
                "s": "t",
                "args": {"height": height, "round": round_},
            }
            if detail is not None:
                inst["args"]["detail"] = detail
            out.append(inst)

        if kind == "commit":
            # The commit ends the whole round span for this height.
            close_round(key, ts)

    # Close anything still open at the journal edge.
    if events:
        last_ts = events[-1][0]
        for key in list(open_phase):
            close_phase(key, last_ts)
        for key in list(open_round):
            close_round(key, last_ts)

    # Track naming metadata first, so the UI labels tids as replicas
    # and (for merged journals) each origin as its own process group.
    meta = []
    for pid, tid in sorted(tracks):
        # tid -2 is the devsched work-queue track (sim.py scopes the
        # queue's recorder handle there); -1 is the sim's own track.
        if tid == DEVICE_TID:
            name = "device"
        elif tid == -2:
            name = "devsched"
        elif tid < 0:
            name = "sim"
        else:
            name = f"replica {tid}"
        meta.append(
            {
                "ph": "M",
                "pid": pid,
                "tid": tid,
                "name": "thread_name",
                "args": {"name": name},
            }
        )
    for pid in sorted({pid for pid, _ in tracks}):
        meta.append(
            {
                "ph": "M",
                "pid": pid,
                "name": "process_name",
                "args": {
                    "name": (
                        "hyperdrive consensus" if pid == PID
                        else f"hyperdrive process {pid}"
                    )
                },
            }
        )
    return meta + out


def export(events, path):
    """Write the Perfetto-loadable trace JSON for ``events``."""
    doc = {
        "traceEvents": to_trace_events(events),
        "displayTimeUnit": "ms",
    }
    with open(path, "w") as fh:
        json.dump(doc, fh, separators=(",", ":"))
        fh.write("\n")
    return doc
