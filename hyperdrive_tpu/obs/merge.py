"""Journal merge + clock alignment: N per-process journals, one stream.

Every process on the mesh — the serving host, each remote tenant, a
MULTICHIP dryrun's workers — records its own flight-recorder journal
on its own clock. This module folds them into ONE causally-consistent
stream:

1. **pid stamping** — every event gains a seventh slot, the process's
   trace origin id (:class:`~hyperdrive_tpu.obs.recorder.Event.pid`),
   so one stream can carry all processes without losing attribution.
2. **clock alignment** — per-process wall-clock offsets are estimated
   from the ``trace.offset`` events the HELLO echo handshake produced
   (client-side NTP: ``offset ≈ t1 - (t0 + t3) / 2``). The offset
   graph is walked breadth-first from the lowest origin id, so any
   connected mesh aligns to one reference clock; a process with no
   handshake path keeps its own clock (offset 0). Virtual-clock runs
   have no offset events at all, so fixed-seed sim journals merge
   EXACTLY — two runs' merged journals stay digest-identical.
3. **causal clamp** — after alignment, a ``trace.recv`` is never
   allowed to precede its matching ``trace.send`` (clock estimation
   error cannot invert causality in the merged order); a recv whose
   send appears in NO journal is an **orphaned span** — flagged in the
   merged meta, never dropped (a partition-torn run keeps its
   evidence).

``python -m hyperdrive_tpu.obs merge a.json b.json -o merged.json``
is the CLI face; the merged file round-trips through
:func:`~hyperdrive_tpu.obs.recorder.load_journal` unchanged and feeds
``obs report --critical-path`` and the Perfetto exporter's
per-process tracks.
"""

from __future__ import annotations

import hashlib
import json

from hyperdrive_tpu.obs.recorder import JOURNAL_VERSION, Event

__all__ = [
    "estimate_offsets",
    "merge_journals",
    "merged_digest",
    "save_merged",
]


def _journal_origin(journal: dict, position: int) -> int:
    """The journal's trace origin id: the recorded meta wins, else a
    deterministic 1-based position (stand-alone journals merged by
    hand still get distinct pids)."""
    meta = journal.get("meta") or {}
    origin = meta.get("origin")
    return int(origin) if origin else position + 1


def estimate_offsets(journals_by_origin: dict) -> dict:
    """origin -> seconds to ADD to that process's timestamps so every
    journal reads on one reference clock.

    Each ``trace.offset`` event in origin A's journal (detail
    ``"B:offset"``) asserts ``clock_B ≈ clock_A + offset``. Offsets
    compose along the resulting undirected graph; the reference is the
    lowest origin id in each connected component (deterministic across
    runs — never dict order). Conflicting estimates for one edge
    average; unreachable processes stay at 0.0.
    """
    edges: dict = {}
    for origin, events in journals_by_origin.items():
        for ev in events:
            if ev[4] != "trace.offset" or not ev[5]:
                continue
            peer_s, _, off_s = str(ev[5]).partition(":")
            try:
                peer, off = int(peer_s), float(off_s)
            except ValueError:
                continue
            edges.setdefault(origin, {}).setdefault(peer, []).append(off)
            edges.setdefault(peer, {}).setdefault(origin, []).append(-off)
    deltas = {origin: 0.0 for origin in journals_by_origin}
    seen: set = set()
    for root in sorted(journals_by_origin):
        if root in seen:
            continue
        seen.add(root)
        frontier = [root]
        while frontier:
            nxt = []
            for a in frontier:
                for b, offs in sorted(edges.get(a, {}).items()):
                    if b in seen or b not in deltas:
                        continue
                    seen.add(b)
                    # clock_b = clock_a + off  →  to map b onto the
                    # reference: delta_b = delta_a - off.
                    off = sum(offs) / len(offs)
                    deltas[b] = deltas[a] - off
                    nxt.append(b)
            frontier = nxt
    return deltas


def merge_journals(journals) -> dict:
    """Fold journal dicts (:func:`load_journal` output) into one merged
    journal dict: version 1, 7-slot events ordered on the aligned
    clock, and a meta block recording origins, the offset estimates,
    and any orphaned receive spans."""
    by_origin: dict = {}
    capacity = 0
    total = 0
    dropped = 0
    for i, journal in enumerate(journals):
        origin = _journal_origin(journal, i)
        if origin in by_origin:
            raise ValueError(f"duplicate journal origin {origin}")
        by_origin[origin] = journal["events"]
        capacity += journal.get("capacity", 0)
        total += journal.get("total", len(journal["events"]))
        dropped += journal.get("dropped", 0)
    deltas = estimate_offsets(by_origin)
    # Pair spans FIRST (on raw per-journal streams): span key ->
    # aligned send ts, so the causal clamp below can pin receives.
    send_ts: dict = {}
    for origin, events in by_origin.items():
        delta = deltas[origin]
        for ev in events:
            if ev[4] == "trace.send" and ev[5]:
                key = str(ev[5])
                ts = ev[0] + delta
                if key not in send_ts or ts < send_ts[key]:
                    send_ts[key] = ts
    merged = []
    orphans = []
    for origin in sorted(by_origin):
        delta = deltas[origin]
        for idx, ev in enumerate(by_origin[origin]):
            ts = ev[0] + delta
            if ev[4] == "trace.recv" and ev[5]:
                sent = send_ts.get(str(ev[5]))
                if sent is None:
                    # Partition-torn span: the sender's journal never
                    # made it here. Keep the event, flag the span.
                    orphans.append(f"{origin}<-{ev[5]}")
                elif ts < sent:
                    ts = sent  # causality beats clock estimation
            merged.append(
                (ts, Event((ts, ev[1], ev[2], ev[3], ev[4], ev[5],
                            origin)), origin, idx)
            )
    merged.sort(key=lambda item: (item[0], item[2], item[3]))
    return {
        "version": JOURNAL_VERSION,
        "capacity": capacity,
        "total": total,
        "dropped": dropped,
        "events": [list(item[1]) for item in merged],
        "meta": {
            "merged": True,
            "origins": sorted(by_origin),
            "offsets": {str(o): deltas[o] for o in sorted(deltas)},
            "orphans": sorted(orphans),
        },
    }


def merged_digest(merged: dict) -> str:
    """sha256 over the canonical JSON encoding of the merged events —
    the same shape :meth:`Recorder.digest` hashes, so two fixed-seed
    multi-process runs must agree here."""
    blob = json.dumps(
        [list(ev) for ev in merged["events"]],
        separators=(",", ":"),
        sort_keys=False,
    ).encode()
    return hashlib.sha256(blob).hexdigest()


def save_merged(merged: dict, path) -> None:
    with open(path, "w") as fh:
        json.dump(merged, fh, separators=(",", ":"))
        fh.write("\n")
