"""Device-pipeline telemetry: launch probes for the async work queue.

Every :class:`~hyperdrive_tpu.devsched.DeviceWorkQueue` drain becomes a
:class:`LaunchRecord` with a monotonically-assigned ``launch_id``:
submit→drain queue wait per command, lanes requested vs padded (the
bucket-padding bill), occupancy %, coalescing factor, generation-split
count, and the wall clock split into pack / dispatch / sync / unpack —
the sync share tapped straight from :func:`~hyperdrive_tpu.analysis.
annotations.device_fetch`, the one blessed materialization point.

Two sinks, one probe:

- the flight-recorder journal gets the deterministic integers
  (``sched.launch.*`` events on the devsched track, with per-command
  ``sched.launch.submit``/``sched.launch.cmd`` events carrying the
  submitter's track so the Perfetto exporter can draw flow arrows
  submit → drain → gated commit);
- the metrics :class:`~hyperdrive_tpu.obs.metrics.Registry` gets the
  histograms (queue wait, occupancy, wall splits) and counters.

``time_fn`` is injectable exactly like the recorder's: the sim passes
its VirtualClock so queue waits are virtual seconds and the journal +
registry snapshot stay digest-identical across fixed-seed runs;
standalone deployments default to ``time.perf_counter`` and get real
wall splits.

Off state is the NULL_TRACER discipline: the queue holds
:data:`NULL_DEVTEL` and guards with ``devtel is not NULL_DEVTEL`` — one
pointer compare per submit/drain, nothing else.
"""

from __future__ import annotations

import time

from hyperdrive_tpu.analysis.annotations import set_fetch_probe
from hyperdrive_tpu.obs.metrics import Registry
from hyperdrive_tpu.ops.bucketing import bucket_for

__all__ = [
    "CmdMeta",
    "LaunchRecord",
    "DeviceTelemetry",
    "NullDeviceTelemetry",
    "NULL_DEVTEL",
    "EXEC_ORIGIN",
]

#: The submitting-track id the sim stamps on execution-layer commands
#: (tx-signature rows riding the fused drain). A launch whose metas
#: include this origin is a FUSED drain — votes and exec rows in one
#: coalesced device program — and its stage spans are double-booked
#: under ``devtel.fused.*`` so the fused pipeline's pack/dispatch/
#: sync/unpack economics are separable from pure vote drains.
EXEC_ORIGIN = -3


class CmdMeta:
    """Per-command probe state: submission sequence number (the flow-
    arrow key), submit timestamp, submitting track (replica / tenant /
    sim), and requested rows."""

    __slots__ = ("seq", "ts", "origin", "rows")

    def __init__(self, seq, ts, origin, rows):
        self.seq = seq
        self.ts = ts
        self.origin = origin
        self.rows = rows


class LaunchRecord:
    """One coalesced device launch, fully attributed.

    Deterministic fields (journal-bound): ``launch_id``, ``kind``,
    ``generation``, ``commands``, ``rows``, ``lanes``,
    ``occupancy_pct``, ``queue_wait_max`` / ``queue_wait_sum`` (in the
    probe clock's seconds — virtual under the sim), ``origins``,
    ``syncs``. Clock-derived fields (registry-bound): ``t_pack`` /
    ``t_dispatch`` / ``t_sync`` / ``t_unpack`` / ``wall``.
    """

    __slots__ = (
        "launch_id", "kind", "generation", "commands", "rows", "lanes",
        "occupancy_pct", "queue_wait_max", "queue_wait_sum", "origins",
        "syncs", "exec_rows", "t_pack", "t_dispatch", "t_sync",
        "t_unpack", "wall", "_t_begin", "_t_last",
    )

    def __init__(self, launch_id, kind, generation, metas, now):
        self.launch_id = launch_id
        self.kind = kind
        self.generation = generation
        self.commands = len(metas)
        self.rows = sum(m.rows for m in metas)
        self.lanes = self.rows
        self.occupancy_pct = 100
        waits = [now - m.ts for m in metas]
        self.queue_wait_max = max(waits, default=0.0)
        self.queue_wait_sum = sum(waits)
        self.origins = tuple(m.origin for m in metas)
        self.syncs = 0
        #: Rows submitted by the execution layer (origin EXEC_ORIGIN):
        #: nonzero marks this launch as a fused drain.
        self.exec_rows = sum(
            m.rows for m in metas if m.origin == EXEC_ORIGIN
        )
        self.t_pack = 0.0
        self.t_dispatch = 0.0
        self.t_sync = 0.0
        self.t_unpack = 0.0
        self.wall = 0.0
        self._t_begin = now
        self._t_last = now

    def _mark(self, attr, now) -> None:
        setattr(self, attr, getattr(self, attr) + (now - self._t_last))
        self._t_last = now

    def as_dict(self) -> dict:
        return {
            "launch_id": self.launch_id,
            "kind": self.kind,
            "generation": self.generation,
            "commands": self.commands,
            "rows": self.rows,
            "lanes": self.lanes,
            "occupancy_pct": self.occupancy_pct,
            "queue_wait_max": self.queue_wait_max,
            "queue_wait_sum": self.queue_wait_sum,
            "origins": list(self.origins),
            "syncs": self.syncs,
            "exec_rows": self.exec_rows,
            "t_pack": self.t_pack,
            "t_dispatch": self.t_dispatch,
            "t_sync": self.t_sync,
            "t_unpack": self.t_unpack,
            "wall": self.wall,
        }


class DeviceTelemetry:
    """The live probe: owns launch-id assignment, the registry, and the
    journal emissions. One instance per queue (the sim builds one and
    hands it to its queue; a service can share one across queues only if
    those queues never interleave drains).

    ``recorder``: a :class:`~hyperdrive_tpu.obs.recorder.Recorder` (not
    a bound handle — per-command events carry the submitting track, so
    the probe scopes per emission). None disables journal output while
    keeping the registry live.
    """

    def __init__(self, recorder=None, registry=None, time_fn=None,
                 keep: int = 256):
        self._rec = recorder
        self.registry = (
            registry if registry is not None else Registry(time_fn=time_fn)
        )
        self._time = time_fn or time.perf_counter
        self._next_id = 0
        self._next_seq = 0
        self._open: LaunchRecord | None = None
        #: Ring of the most recent ``keep`` completed LaunchRecords.
        self.records: list = []
        self._keep = keep

    def now(self) -> float:
        return self._time()

    # ----------------------------------------------------------- submit

    def command(self, origin, rows) -> CmdMeta:
        """Stamp one submitted command; emits ``sched.launch.submit``
        on the submitter's track with the sequence number the exporter
        keys the submit→drain flow arrow on."""
        seq = self._next_seq
        self._next_seq += 1
        meta = CmdMeta(seq, self._time(), origin, int(rows))
        if self._rec is not None:
            track = -2 if origin is None else origin
            self._rec.emit("sched.launch.submit", track, -1, -1, seq)
        self.registry.count("devtel.submitted")
        return meta

    # ------------------------------------------------------------ drain

    def splits(self, n: int) -> None:
        """A drain cycle split into ``n`` extra per-generation launches
        (epoch boundaries inside one coalescing window)."""
        if self._rec is not None:
            self._rec.emit("sched.launch.split", -2, -1, -1, n)
        self.registry.count("devtel.launch.gen_splits", n)

    def launch_begin(self, kind, generation, metas) -> LaunchRecord:
        launch_id = self._next_id
        self._next_id += 1
        rec = LaunchRecord(launch_id, kind, generation, metas, self._time())
        self._open = rec
        set_fetch_probe(self)
        if self._rec is not None:
            emit = self._rec.emit
            emit("sched.launch.begin", -2, -1, -1, launch_id)
            for m in metas:
                emit(
                    "sched.launch.cmd",
                    -2 if m.origin is None else m.origin,
                    -1, -1, m.seq,
                )
        return rec

    def mark_pack(self, rec: LaunchRecord) -> None:
        rec._mark("t_pack", self._time())

    def mark_dispatch(self, rec: LaunchRecord) -> None:
        # The dispatch leg brackets the launcher call; fetch-probe time
        # accrued inside it is the sync share, carved out below.
        rec._mark("t_dispatch", self._time())
        rec.t_dispatch = max(0.0, rec.t_dispatch - rec.t_sync)

    def launch_lanes(self, rec: LaunchRecord, launcher) -> None:
        """Resolve lanes-requested vs lanes-padded for this launch from
        the launcher's bucket ladder (TpuBatchVerifier exposes it at
        ``verifier.host.buckets``); ladderless launchers (host / null
        verifiers) pad nothing."""
        verifier = getattr(launcher, "verifier", None)
        buckets = getattr(verifier, "buckets", None)
        if buckets is None:
            buckets = getattr(
                getattr(verifier, "host", None), "buckets", None
            )
        rec.lanes = bucket_for(rec.rows, buckets) if buckets else rec.rows
        rec.occupancy_pct = int(
            round(100 * rec.rows / max(rec.lanes, 1))
        )

    def launch_end(self, rec: LaunchRecord) -> None:
        set_fetch_probe(None)
        self._open = None
        now = self._time()
        rec._mark("t_unpack", now)
        rec.wall = now - rec._t_begin
        self.records.append(rec)
        if len(self.records) > self._keep:
            del self.records[: -self._keep]
        if self._rec is not None:
            emit = self._rec.emit
            emit("sched.launch.rows", -2, -1, -1, rec.rows)
            emit("sched.launch.lanes", -2, -1, -1, rec.lanes)
            emit("sched.launch.occupancy", -2, -1, -1, rec.occupancy_pct)
            emit(
                "sched.launch.queue_wait", -2, -1, -1,
                int(round(rec.queue_wait_max * 1e6)),
            )
            emit("sched.launch.end", -2, -1, -1, rec.launch_id)
        reg = self.registry
        reg.count("devtel.launches")
        reg.count("devtel.launch.commands", rec.commands)
        reg.count("devtel.launch.rows", rec.rows)
        reg.count("devtel.launch.lanes", rec.lanes)
        reg.count("devtel.launch.syncs", rec.syncs)
        reg.set_gauge("devtel.launch.last_id", rec.launch_id)
        reg.observe("devtel.launch.occupancy", rec.occupancy_pct)
        reg.observe("devtel.launch.coalesce", rec.commands)
        reg.observe("devtel.launch.queue_wait.latency", rec.queue_wait_max)
        reg.observe("devtel.launch.pack.latency", rec.t_pack)
        reg.observe("devtel.launch.dispatch.latency", rec.t_dispatch)
        reg.observe("devtel.launch.sync.latency", rec.t_sync)
        reg.observe("devtel.launch.unpack.latency", rec.t_unpack)
        reg.observe("devtel.launch.wall.latency", rec.wall)
        if rec.exec_rows:
            # Fused-drain stage spans (PR 16): this launch carried
            # exec-layer signature rows coalesced with vote verifies,
            # so its per-stage latencies are double-booked under the
            # fused series — `obs report` and the exporter can show
            # what the speculative pipeline's shared launches cost at
            # each stage without disentangling mixed histograms.
            reg.count("devtel.fused.launches")
            reg.count("devtel.fused.exec_rows", rec.exec_rows)
            reg.observe("devtel.fused.pack.latency", rec.t_pack)
            reg.observe("devtel.fused.dispatch.latency", rec.t_dispatch)
            reg.observe("devtel.fused.sync.latency", rec.t_sync)
            reg.observe("devtel.fused.unpack.latency", rec.t_unpack)
            reg.observe("devtel.fused.wall.latency", rec.wall)

    # ------------------------------------------- device_fetch probe taps

    def fetch_begin(self, why: str) -> None:
        rec = self._open
        if rec is not None:
            rec.syncs += 1
            self._sync_t0 = self._time()

    def fetch_end(self, why: str) -> None:
        rec = self._open
        if rec is not None:
            rec.t_sync += self._time() - getattr(self, "_sync_t0", self._time())

    # ----------------------------------------------------- per-tenant

    #: Latency legs a service attributes per tenant. ``commit_rejected``
    #: is deliberately a separate histogram: a failed certificate verify
    #: must not pollute the committed-path p95/p99.
    _TENANT_LEGS = {
        "verify": "tenant.verify.latency",
        "commit": "tenant.commit.latency",
        "commit_rejected": "tenant.commit_rejected.latency",
    }

    def tenant_latency(self, tenant, seconds: float, leg: str = "verify"):
        """Per-tenant latency attribution (ShardVerifyService): labeled
        histograms so cross-tenant aggregation stays mergeable."""
        self.registry.observe(
            self._TENANT_LEGS[leg], seconds, label=tenant
        )


class NullDeviceTelemetry(DeviceTelemetry):
    """Probing disabled: every hook is a no-op; the off-state guard is
    ``devtel is not NULL_DEVTEL`` at the queue's call sites, so none of
    these methods run on hot paths anyway."""

    def __init__(self):
        super().__init__(time_fn=lambda: 0.0)

    def command(self, origin, rows):
        return None

    def splits(self, n):
        pass

    def launch_begin(self, kind, generation, metas):
        return None

    def launch_end(self, rec):
        pass


NULL_DEVTEL = NullDeviceTelemetry()
