"""Proposer election schedulers.

Capability parity with the reference's ``scheduler/scheduler.go``: at every
(height, round) exactly one process must be elected proposer, all correct
processes must agree on the election, and the schedule must be locally
computable (no consensus needed to agree on the schedule itself).
"""

from __future__ import annotations

from hyperdrive_tpu.types import INVALID_ROUND, Height, Round, Signatory

__all__ = ["RoundRobin"]

_U64_MASK = (1 << 64) - 1


class RoundRobin:
    """Rotates through the signatory set by ``(height + round) % n``.

    Simple and easy to audit, but unfair — avoid when proposers are
    rewarded (reference: scheduler/scheduler.go:26-31). Height/round sums
    wrap modulo 2^64 exactly as the reference's uint64 conversion does
    (scheduler/scheduler.go:52), so edge-case heights like MaxInt64 elect
    the same proposer in both implementations.
    """

    __slots__ = ("signatories",)

    def __init__(self, signatories: list[Signatory]):
        self.signatories = list(signatories)

    def schedule(self, height: Height, round: Round) -> Signatory:
        if not self.signatories:
            raise ValueError("no processes to schedule")
        if height <= 0:
            raise ValueError(f"invalid height: {height}")
        if round <= INVALID_ROUND:
            raise ValueError(f"invalid round: {round}")
        idx = (((height & _U64_MASK) + (round & _U64_MASK)) & _U64_MASK) % len(
            self.signatories
        )
        return self.signatories[idx]
