"""BLS signatures over BLS12-381 — host reference implementation.

Pure-Python oracle for the device aggregation path (:mod:`..ops.fp381`,
:mod:`..ops.g1`), mirroring the role :mod:`.ed25519` plays for the
ed25519 kernels: every algebraic object is Python ints, every routine is
independently checkable, and the device kernels are pinned against this
module by differential tests (``tests/test_bls.py``).

Scheme: **minimal-signature-size** BLS (draft-irtf-cfrg-bls-signature) —
signatures in G1 (48 bytes compressed), public keys in G2 (96 bytes),
same-message aggregation:

    sign(sk, m)          = [sk] H(m)           in G1
    aggregate(sigs)      = sum sigma_i         in G1  (the device MSM)
    apk                  = sum pk_i            in G2
    verify_aggregate     : e(sigma, -g2) * e(H(m), apk) == 1

so the per-quorum cost is one product of two Miller loops and ONE final
exponentiation, while the O(n) aggregation work is a bitmask-weighted
G1 sum — exactly the fixed-shape kernel :mod:`..ops.g1` launches.

Construction notes (PARITY.md "BLS" records the conformance status):

- **Hash-to-curve** follows RFC 9380's hash_to_curve skeleton with
  expand_message_xmd(SHA-256) and the *generic Shallue–van de Woestijne
  map* (§6.6.1) with its constants derived at import time by the RFC's
  own ``find_z_svdw`` procedure. The standard BLS ciphersuite instead
  uses the simplified SWU map through an 11-isogeny whose constant
  tables are not re-derivable here, so this module registers its own
  suite under a distinct DST. The map is still uniform, deterministic
  and constant-free to the caller; test vectors are self-generated and
  pinned, with algebraic cross-checks (on-curve, bilinearity,
  e(G1, G2)^r == 1) guarding the construction itself.
- **Pairing** is the optimal ate pairing: affine Miller loop over
  bits of |x| (x = BLS parameter -0xd201000000010000), line functions
  through the untwisted G2 point in Fp12, conjugation for x < 0, and a
  *naive* final exponentiation f^((p^12-1)/r) — a few hundred ms, run
  once per quorum, chosen for checkability over speed (the exponent is
  exact arithmetic; no hard-part decomposition to get subtly wrong).
- **Serialization** is the ZCash format every production BLS12-381
  library interops on: 48/96-byte compressed points, bit 7 compression
  flag, bit 6 infinity, bit 5 lexicographic y sign.
- **KeyGen** is the draft's HKDF construction (salt
  "BLS-SIG-KEYGEN-SALT-" re-hashed per round, I2OSP(L=48, 2), reject
  sk = 0).

The Fp2/Fp6/Fp12 tower is u^2 = -1, v^3 = u + 1, w^2 = v (the standard
BLS12-381 tower); elements are bare tuples to keep the oracle legible.
"""

from __future__ import annotations

import hashlib
import hmac
from dataclasses import dataclass

__all__ = [
    "P",
    "R_ORDER",
    "G1_GEN",
    "G2_GEN",
    "DST",
    "keygen",
    "pk_from_sk",
    "sign",
    "verify",
    "aggregate_signatures",
    "aggregate_pubkeys",
    "verify_aggregate_same_message",
    "hash_to_curve_g1",
    "hash_to_field",
    "expand_message_xmd",
    "pairing",
    "pairing_check",
    "g1_add",
    "g1_double",
    "g1_mul",
    "g1_neg",
    "g1_is_on_curve",
    "g1_in_subgroup",
    "g2_add",
    "g2_mul",
    "g2_neg",
    "g2_is_on_curve",
    "g2_in_subgroup",
    "g1_compress",
    "g1_decompress",
    "g2_compress",
    "g2_decompress",
    "BlsKeyPair",
    "bls_keypair_from_identity",
]

# --------------------------------------------------------------- parameters

#: Base field prime (381 bits).
P = int(
    "1a0111ea397fe69a4b1ba7b6434bacd764774b84f38512bf6730d2a0f6b0f624"
    "1eabfffeb153ffffb9feffffffffaaab",
    16,
)
#: Subgroup order r (255 bits).
R_ORDER = int(
    "73eda753299d7d483339d80809a1d80553bda402fffe5bfeffffffff00000001", 16
)
#: BLS parameter x (negative); |x| drives the Miller loop.
BLS_X = 0xD201000000010000
#: G1 cofactor.
H_G1 = 0x396C8C005555E1568C00AAAB0000AAAB

#: Canonical generators (standard, as published with the curve).
G1_GEN = (
    int(
        "17f1d3a73197d7942695638c4fa9ac0fc3688c4f9774b905a14e3a3f171bac58"
        "6c55e83ff97a1aeffb3af00adb22c6bb",
        16,
    ),
    int(
        "08b3f481e3aaa0f1a09e30ed741d8ae4fcf5e095d5d00af600db18cb2c04b3ed"
        "d03cc744a2888ae40caa232946c5e7e1",
        16,
    ),
)
G2_GEN = (
    (
        int(
            "024aa2b2f08f0a91260805272dc51051c6e47ad4fa403b02b4510b647ae3d177"
            "0bac0326a805bbefd48056c8c121bdb8",
            16,
        ),
        int(
            "13e02b6052719f607dacd3a088274f65596bd0d09920b61ab5da61bbdc7f5049"
            "334cf11213945d57e5ac7d055d042b7e",
            16,
        ),
    ),
    (
        (
            int(
                "0ce5d527727d6e118cc9cdc6da2e351aadfd9baa8cbdd3a76d429a695160d12c"
                "923ac9cc3baca289e193548608b82801",
                16,
            )
        ),
        int(
            "0606c4a02ea734cc32acd2b02bc28b99cb3e287e85a763af267492ab572e99ab"
            "3f370d275cec1da1aaa9075ff05f79be",
            16,
        ),
    ),
)

#: Domain separation tag for this framework's G1 hash-to-curve suite
#: (SvdW generic map — see module docstring; NOT the standard SSWU suite).
DST = b"HYPERDRIVE-V01-CS01-with-BLS12381G1_XMD:SHA-256_SVDW_RO_"

_HALF_P = (P - 1) // 2


# ------------------------------------------------------------------ Fp / Fp2


def _inv(a: int) -> int:
    return pow(a, -1, P)


def _sqrt_fp(a: int) -> "int | None":
    """Square root in Fp (p = 3 mod 4), or None if a is a non-residue."""
    r = pow(a, (P + 1) // 4, P)
    return r if r * r % P == a % P else None


def _is_square_fp(a: int) -> bool:
    return pow(a % P, _HALF_P, P) in (0, 1)


# Fp2 = Fp[u]/(u^2 + 1); elements are (c0, c1) = c0 + c1*u.
F2_ZERO = (0, 0)
F2_ONE = (1, 0)


def f2_add(a, b):
    return ((a[0] + b[0]) % P, (a[1] + b[1]) % P)


def f2_sub(a, b):
    return ((a[0] - b[0]) % P, (a[1] - b[1]) % P)


def f2_neg(a):
    return (-a[0] % P, -a[1] % P)


def f2_mul(a, b):
    return (
        (a[0] * b[0] - a[1] * b[1]) % P,
        (a[0] * b[1] + a[1] * b[0]) % P,
    )


def f2_sqr(a):
    return ((a[0] + a[1]) * (a[0] - a[1]) % P, 2 * a[0] * a[1] % P)


def f2_muls(a, k: int):
    return (a[0] * k % P, a[1] * k % P)


def f2_inv(a):
    d = _inv(a[0] * a[0] + a[1] * a[1])
    return (a[0] * d % P, -a[1] * d % P)


def f2_xi(a):
    """Multiply by the Fp6 non-residue xi = 1 + u."""
    return ((a[0] - a[1]) % P, (a[0] + a[1]) % P)


def f2_pow(a, e: int):
    r = F2_ONE
    while e:
        if e & 1:
            r = f2_mul(r, a)
        a = f2_sqr(a)
        e >>= 1
    return r


def f2_sqrt(a):
    """Square root in Fp2 (complex method for p = 3 mod 4), or None.
    Self-verifying: only returns x with x^2 == a."""
    if a == F2_ZERO:
        return F2_ZERO
    a1 = f2_pow(a, (P - 3) // 4)
    x0 = f2_mul(a1, a)
    alpha = f2_mul(a1, x0)  # a^((p-1)/2)
    if alpha == (P - 1, 0):
        x = f2_mul((0, 1), x0)
    else:
        b = f2_pow(f2_add(F2_ONE, alpha), _HALF_P)
        x = f2_mul(b, x0)
    return x if f2_sqr(x) == (a[0] % P, a[1] % P) else None


# Fp6 = Fp2[v]/(v^3 - xi); elements are (c0, c1, c2).
F6_ZERO = (F2_ZERO, F2_ZERO, F2_ZERO)
F6_ONE = (F2_ONE, F2_ZERO, F2_ZERO)


def f6_add(a, b):
    return (f2_add(a[0], b[0]), f2_add(a[1], b[1]), f2_add(a[2], b[2]))


def f6_sub(a, b):
    return (f2_sub(a[0], b[0]), f2_sub(a[1], b[1]), f2_sub(a[2], b[2]))


def f6_neg(a):
    return (f2_neg(a[0]), f2_neg(a[1]), f2_neg(a[2]))


def f6_mul(a, b):
    t0 = f2_mul(a[0], b[0])
    t1 = f2_mul(a[1], b[1])
    t2 = f2_mul(a[2], b[2])
    c0 = f2_add(
        t0,
        f2_xi(
            f2_sub(
                f2_sub(
                    f2_mul(f2_add(a[1], a[2]), f2_add(b[1], b[2])), t1
                ),
                t2,
            )
        ),
    )
    c1 = f2_add(
        f2_sub(
            f2_sub(f2_mul(f2_add(a[0], a[1]), f2_add(b[0], b[1])), t0), t1
        ),
        f2_xi(t2),
    )
    c2 = f2_add(
        f2_sub(
            f2_sub(f2_mul(f2_add(a[0], a[2]), f2_add(b[0], b[2])), t0), t2
        ),
        t1,
    )
    return (c0, c1, c2)


def f6_mul_v(a):
    """Multiply by v: (c0, c1, c2) -> (xi*c2, c0, c1)."""
    return (f2_xi(a[2]), a[0], a[1])


def f6_inv(a):
    c0 = f2_sub(f2_sqr(a[0]), f2_xi(f2_mul(a[1], a[2])))
    c1 = f2_sub(f2_xi(f2_sqr(a[2])), f2_mul(a[0], a[1]))
    c2 = f2_sub(f2_sqr(a[1]), f2_mul(a[0], a[2]))
    t = f2_inv(
        f2_add(
            f2_mul(a[0], c0),
            f2_xi(f2_add(f2_mul(a[2], c1), f2_mul(a[1], c2))),
        )
    )
    return (f2_mul(c0, t), f2_mul(c1, t), f2_mul(c2, t))


# Fp12 = Fp6[w]/(w^2 - v); elements are (c0, c1).
F12_ZERO = (F6_ZERO, F6_ZERO)
F12_ONE = (F6_ONE, F6_ZERO)


def f12_add(a, b):
    return (f6_add(a[0], b[0]), f6_add(a[1], b[1]))


def f12_mul(a, b):
    t0 = f6_mul(a[0], b[0])
    t1 = f6_mul(a[1], b[1])
    c0 = f6_add(t0, f6_mul_v(t1))
    c1 = f6_sub(
        f6_mul(f6_add(a[0], a[1]), f6_add(b[0], b[1])), f6_add(t0, t1)
    )
    return (c0, c1)


def f12_sqr(a):
    return f12_mul(a, a)


def f12_conj(a):
    return (a[0], f6_neg(a[1]))


def f12_inv(a):
    d = f6_inv(f6_sub(f6_sqr_(a[0]), f6_mul_v(f6_sqr_(a[1]))))
    return (f6_mul(a[0], d), f6_neg(f6_mul(a[1], d)))


def f6_sqr_(a):
    return f6_mul(a, a)


def f12_pow(a, e: int):
    r = F12_ONE
    while e:
        if e & 1:
            r = f12_mul(r, a)
        a = f12_sqr(a)
        e >>= 1
    return r


def _f12_from_fp(x: int):
    return (((x % P, 0), F2_ZERO, F2_ZERO), F6_ZERO)


def _f12_from_fp2(x):
    return ((x, F2_ZERO, F2_ZERO), F6_ZERO)


# w^2 = v and w^3 = v*w as Fp12 elements; their inverses drive the
# untwist E'(Fp2) -> E(Fp12).
_W2 = ((F2_ZERO, F2_ONE, F2_ZERO), F6_ZERO)
_W3 = (F6_ZERO, (F2_ZERO, F2_ONE, F2_ZERO))
_W2_INV = f12_inv(_W2)
_W3_INV = f12_inv(_W3)


# ------------------------------------------------------- G1 (ints, Jacobian)
#
# Affine points are (x, y) int tuples; None is the point at infinity.
# Jacobian triples (X, Y, Z) are internal to the ladders.


def g1_is_on_curve(pt) -> bool:
    if pt is None:
        return True
    x, y = pt
    return y * y % P == (x * x * x + 4) % P


def g1_neg(pt):
    return None if pt is None else (pt[0], -pt[1] % P)


def _jac_dbl(X, Y, Z):
    if Y == 0:
        return (0, 1, 0)
    A = X * X % P
    B = Y * Y % P
    C = B * B % P
    D = 2 * ((X + B) * (X + B) - A - C) % P
    E = 3 * A % P
    F = E * E % P
    X3 = (F - 2 * D) % P
    Y3 = (E * (D - X3) - 8 * C) % P
    Z3 = 2 * Y * Z % P
    return (X3, Y3, Z3)


def _jac_add(p1, p2):
    X1, Y1, Z1 = p1
    X2, Y2, Z2 = p2
    if Z1 == 0:
        return p2
    if Z2 == 0:
        return p1
    Z1Z1 = Z1 * Z1 % P
    Z2Z2 = Z2 * Z2 % P
    U1 = X1 * Z2Z2 % P
    U2 = X2 * Z1Z1 % P
    S1 = Y1 * Z2 * Z2Z2 % P
    S2 = Y2 * Z1 * Z1Z1 % P
    if U1 == U2:
        if S1 != S2:
            return (0, 1, 0)
        return _jac_dbl(X1, Y1, Z1)
    H = (U2 - U1) % P
    Rr = (S2 - S1) % P
    HH = H * H % P
    HHH = H * HH % P
    V = U1 * HH % P
    X3 = (Rr * Rr - HHH - 2 * V) % P
    Y3 = (Rr * (V - X3) - S1 * HHH) % P
    Z3 = Z1 * Z2 * H % P
    return (X3, Y3, Z3)


def _jac_to_affine(p):
    X, Y, Z = p
    if Z == 0:
        return None
    zi = _inv(Z)
    zi2 = zi * zi % P
    return (X * zi2 % P, Y * zi2 * zi % P)


def _affine_to_jac(pt):
    return (0, 1, 0) if pt is None else (pt[0], pt[1], 1)


def g1_add(a, b):
    return _jac_to_affine(_jac_add(_affine_to_jac(a), _affine_to_jac(b)))


def g1_double(a):
    return _jac_to_affine(_jac_dbl(*_affine_to_jac(a)))


def g1_mul(pt, k: int):
    """[k] P for P of order r (reduces k mod r)."""
    return g1_mul_raw(pt, k % R_ORDER)


def g1_mul_raw(pt, k: int):
    """Scalar multiply WITHOUT reducing k mod r — cofactor clearing and
    subgroup checks need the full-width scalar."""
    acc = (0, 1, 0)
    q = _affine_to_jac(pt)
    while k:
        if k & 1:
            acc = _jac_add(acc, q)
        q = _jac_dbl(*q)
        k >>= 1
    return _jac_to_affine(acc)


def g1_in_subgroup(pt) -> bool:
    return g1_is_on_curve(pt) and g1_mul_raw(pt, R_ORDER) is None


# ------------------------------------------------------ G2 (Fp2, Jacobian)

_B2 = f2_xi((4, 0))  # 4 * (1 + u)


def g2_is_on_curve(pt) -> bool:
    if pt is None:
        return True
    x, y = pt
    return f2_sqr(y) == f2_add(f2_mul(f2_sqr(x), x), _B2)


def g2_neg(pt):
    return None if pt is None else (pt[0], f2_neg(pt[1]))


def _jac2_dbl(X, Y, Z):
    if Y == F2_ZERO:
        return (F2_ZERO, F2_ONE, F2_ZERO)
    A = f2_sqr(X)
    B = f2_sqr(Y)
    C = f2_sqr(B)
    D = f2_muls(f2_sub(f2_sub(f2_sqr(f2_add(X, B)), A), C), 2)
    E = f2_muls(A, 3)
    F = f2_sqr(E)
    X3 = f2_sub(F, f2_muls(D, 2))
    Y3 = f2_sub(f2_mul(E, f2_sub(D, X3)), f2_muls(C, 8))
    Z3 = f2_muls(f2_mul(Y, Z), 2)
    return (X3, Y3, Z3)


def _jac2_add(p1, p2):
    X1, Y1, Z1 = p1
    X2, Y2, Z2 = p2
    if Z1 == F2_ZERO:
        return p2
    if Z2 == F2_ZERO:
        return p1
    Z1Z1 = f2_sqr(Z1)
    Z2Z2 = f2_sqr(Z2)
    U1 = f2_mul(X1, Z2Z2)
    U2 = f2_mul(X2, Z1Z1)
    S1 = f2_mul(f2_mul(Y1, Z2), Z2Z2)
    S2 = f2_mul(f2_mul(Y2, Z1), Z1Z1)
    if U1 == U2:
        if S1 != S2:
            return (F2_ZERO, F2_ONE, F2_ZERO)
        return _jac2_dbl(X1, Y1, Z1)
    H = f2_sub(U2, U1)
    Rr = f2_sub(S2, S1)
    HH = f2_sqr(H)
    HHH = f2_mul(H, HH)
    V = f2_mul(U1, HH)
    X3 = f2_sub(f2_sub(f2_sqr(Rr), HHH), f2_muls(V, 2))
    Y3 = f2_sub(f2_mul(Rr, f2_sub(V, X3)), f2_mul(S1, HHH))
    Z3 = f2_mul(f2_mul(Z1, Z2), H)
    return (X3, Y3, Z3)


def _jac2_to_affine(p):
    X, Y, Z = p
    if Z == F2_ZERO:
        return None
    zi = f2_inv(Z)
    zi2 = f2_sqr(zi)
    return (f2_mul(X, zi2), f2_mul(Y, f2_mul(zi2, zi)))


def _affine2_to_jac(pt):
    return (
        (F2_ZERO, F2_ONE, F2_ZERO)
        if pt is None
        else (pt[0], pt[1], F2_ONE)
    )


def g2_add(a, b):
    return _jac2_to_affine(_jac2_add(_affine2_to_jac(a), _affine2_to_jac(b)))


def g2_mul(pt, k: int):
    acc = (F2_ZERO, F2_ONE, F2_ZERO)
    q = _affine2_to_jac(pt)
    while k:
        if k & 1:
            acc = _jac2_add(acc, q)
        q = _jac2_dbl(*q)
        k >>= 1
    return _jac2_to_affine(acc)


def g2_in_subgroup(pt) -> bool:
    return g2_is_on_curve(pt) and g2_mul(pt, R_ORDER) is None


# ------------------------------------------------------------------ pairing


def _untwist(q):
    """E'(Fp2) -> E(Fp12): (x', y') -> (x'/w^2, y'/w^3) (w^6 = xi)."""
    x = f12_mul(_f12_from_fp2(q[0]), _W2_INV)
    y = f12_mul(_f12_from_fp2(q[1]), _W3_INV)
    return (x, y)


def _line(r, lam, px, py):
    """Evaluate the line through r with slope lam at P: (yP - yR) -
    lam*(xP - xR). Constant sign factors vanish in the final
    exponentiation ((p^12-1)/r is even)."""
    xr, yr = r
    t = f12_mul(lam, f12_add(px, (f6_neg(xr[0]), f6_neg(xr[1]))))
    return f12_add(f12_add(py, (f6_neg(yr[0]), f6_neg(yr[1]))), (f6_neg(t[0]), f6_neg(t[1])))


def _miller_loop(p1, q2):
    """f_{|x|, Q}(P) for P in G1, Q in G2 (affine, both non-infinity),
    conjugated for the negative BLS parameter."""
    px = _f12_from_fp(p1[0])
    py = _f12_from_fp(p1[1])
    Q = _untwist(q2)
    R = Q
    f = F12_ONE
    for i in range(BLS_X.bit_length() - 2, -1, -1):
        xr, yr = R
        # Doubling: lam = 3 xR^2 / (2 yR).
        lam = f12_mul(
            f12_mul(f12_sqr(xr), _f12_from_fp(3)),
            f12_inv(f12_mul(yr, _f12_from_fp(2))),
        )
        f = f12_mul(f12_sqr(f), _line(R, lam, px, py))
        x3 = f12_add(
            f12_sqr(lam),
            (f6_neg(f12_mul(xr, _f12_from_fp(2))[0]),
             f6_neg(f12_mul(xr, _f12_from_fp(2))[1])),
        )
        y3 = f12_add(
            f12_mul(lam, f12_add(xr, (f6_neg(x3[0]), f6_neg(x3[1])))),
            (f6_neg(yr[0]), f6_neg(yr[1])),
        )
        R = (x3, y3)
        if (BLS_X >> i) & 1:
            xr, yr = R
            xq, yq = Q
            # Addition: lam = (yQ - yR) / (xQ - xR).
            lam = f12_mul(
                f12_add(yq, (f6_neg(yr[0]), f6_neg(yr[1]))),
                f12_inv(f12_add(xq, (f6_neg(xr[0]), f6_neg(xr[1])))),
            )
            f = f12_mul(f, _line(R, lam, px, py))
            x3 = f12_add(
                f12_add(f12_sqr(lam), (f6_neg(xr[0]), f6_neg(xr[1]))),
                (f6_neg(xq[0]), f6_neg(xq[1])),
            )
            y3 = f12_add(
                f12_mul(lam, f12_add(xr, (f6_neg(x3[0]), f6_neg(x3[1])))),
                (f6_neg(yr[0]), f6_neg(yr[1])),
            )
            R = (x3, y3)
    # x < 0: e(P, Q) = conj(f_{|x|})^exp (conjugation = inversion in the
    # cyclotomic subgroup the final exponentiation lands in).
    return f12_conj(f)


_FINAL_EXP = (P**12 - 1) // R_ORDER


def pairing(p1, q2):
    """Full optimal ate pairing e(P, Q) -> Fp12 (unity for infinity
    inputs)."""
    if p1 is None or q2 is None:
        return F12_ONE
    return f12_pow(_miller_loop(p1, q2), _FINAL_EXP)


def pairing_check(pairs) -> bool:
    """prod e(Pi, Qi) == 1, with a single shared final exponentiation —
    the once-per-quorum check in the verification paths."""
    f = F12_ONE
    for p1, q2 in pairs:
        if p1 is None or q2 is None:
            continue
        f = f12_mul(f, _miller_loop(p1, q2))
    return f12_pow(f, _FINAL_EXP) == F12_ONE


# ------------------------------------------------------------ hash-to-curve


def expand_message_xmd(msg: bytes, dst: bytes, len_in_bytes: int) -> bytes:
    """RFC 9380 §5.3.1 with SHA-256."""
    if len(dst) > 255:
        raise ValueError("DST too long")
    ell = -(-len_in_bytes // 32)
    if ell > 255:
        raise ValueError("len_in_bytes too large")
    dst_prime = dst + bytes([len(dst)])
    z_pad = b"\x00" * 64
    l_i_b = len_in_bytes.to_bytes(2, "big")
    b0 = hashlib.sha256(z_pad + msg + l_i_b + b"\x00" + dst_prime).digest()
    bi = hashlib.sha256(b0 + b"\x01" + dst_prime).digest()
    out = [bi]
    for i in range(2, ell + 1):
        bi = hashlib.sha256(
            bytes(x ^ y for x, y in zip(b0, bi)) + bytes([i]) + dst_prime
        ).digest()
        out.append(bi)
    return b"".join(out)[:len_in_bytes]


#: L = ceil((ceil(log2(p)) + k) / 8) for k = 128-bit security.
_L_FIELD = 64


def hash_to_field(msg: bytes, count: int, dst: bytes = DST):
    """RFC 9380 §5.2 hash_to_field for GF(p), m = 1."""
    uniform = expand_message_xmd(msg, dst, count * _L_FIELD)
    return [
        int.from_bytes(uniform[i * _L_FIELD : (i + 1) * _L_FIELD], "big") % P
        for i in range(count)
    ]


def _find_z_svdw():
    """RFC 9380 §H.1 find_z_svdw for g(x) = x^3 + 4 (A = 0, B = 4)."""

    def g(x):
        return (x * x * x + 4) % P

    def h(z):
        num = -(3 * z * z) % P
        den = 4 * g(z) % P
        return num * _inv(den) % P if den else None

    ctr = 1
    while True:
        for z_cand in (ctr, -ctr % P):
            gz = g(z_cand)
            if gz == 0:
                continue
            hz = h(z_cand)
            if hz is None or hz == 0 or not _is_square_fp(hz):
                continue
            if _is_square_fp(gz) or _is_square_fp(g(-z_cand * pow(2, -1, P) % P)):
                return z_cand
        ctr += 1


_Z_SVDW = _find_z_svdw()
_C1_SVDW = (_Z_SVDW**3 + 4) % P  # g(Z)
_C2_SVDW = -_Z_SVDW * pow(2, -1, P) % P  # -Z / 2
_C3_SVDW = _sqrt_fp(-_C1_SVDW * (3 * _Z_SVDW * _Z_SVDW) % P)
if _C3_SVDW is None:  # pragma: no cover - find_z_svdw guarantees square
    raise AssertionError("svdw c3 not a square")
if _C3_SVDW & 1:  # sgn0(c3) must be 0
    _C3_SVDW = P - _C3_SVDW
_C4_SVDW = -4 * _C1_SVDW * _inv(3 * _Z_SVDW * _Z_SVDW) % P


def _map_to_curve_svdw(u: int):
    """RFC 9380 §6.6.1 Shallue–van de Woestijne map to y^2 = x^3 + 4."""
    tv1 = u * u % P * _C1_SVDW % P
    tv2 = (1 + tv1) % P
    tv1 = (1 - tv1) % P
    tv3 = tv1 * tv2 % P
    tv3 = _inv(tv3) if tv3 else 0  # inv0
    tv4 = u * tv1 % P * tv3 % P * _C3_SVDW % P
    x1 = (_C2_SVDW - tv4) % P
    gx1 = (x1 * x1 * x1 + 4) % P
    e1 = _is_square_fp(gx1)
    x2 = (_C2_SVDW + tv4) % P
    gx2 = (x2 * x2 * x2 + 4) % P
    e2 = _is_square_fp(gx2) and not e1
    x3 = (tv2 * tv2 % P * tv3 % P) ** 2 % P * _C4_SVDW % P
    x3 = (x3 + _Z_SVDW) % P
    x = x1 if e1 else (x2 if e2 else x3)
    gx = (x * x * x + 4) % P
    y = _sqrt_fp(gx)
    assert y is not None, "svdw exceptional case"
    if (u & 1) != (y & 1):  # sgn0 match
        y = P - y
    assert y * y % P == gx
    return (x, y)


def hash_to_curve_g1(msg: bytes, dst: bytes = DST):
    """hash_to_curve: two field elements, two SvdW maps, add, clear
    cofactor. Returns an affine G1 point of order r."""
    u0, u1 = hash_to_field(msg, 2, dst)
    q = g1_add(_map_to_curve_svdw(u0), _map_to_curve_svdw(u1))
    return g1_mul_raw(q, H_G1)


# ------------------------------------------------------------ serialization


def g1_compress(pt) -> bytes:
    """ZCash 48-byte compressed G1."""
    if pt is None:
        return bytes([0xC0]) + b"\x00" * 47
    x, y = pt
    out = bytearray(x.to_bytes(48, "big"))
    out[0] |= 0x80
    if y > _HALF_P:
        out[0] |= 0x20
    return bytes(out)


def g1_decompress(data: bytes):
    """Inverse of :func:`g1_compress`; raises ValueError on malformed or
    off-curve input. Subgroup membership is NOT checked here (callers
    on trust boundaries use :func:`g1_in_subgroup`)."""
    if len(data) != 48:
        raise ValueError("bad G1 length")
    flags = data[0]
    if not flags & 0x80:
        raise ValueError("uncompressed G1 not supported")
    if flags & 0x40:
        if any(data[1:]) or flags != 0xC0:
            raise ValueError("bad G1 infinity encoding")
        return None
    x = int.from_bytes(bytes([flags & 0x1F]) + data[1:], "big")
    if x >= P:
        raise ValueError("G1 x out of range")
    y = _sqrt_fp((x * x * x + 4) % P)
    if y is None:
        raise ValueError("G1 x not on curve")
    if bool(flags & 0x20) != (y > _HALF_P):
        y = P - y
    return (x, y)


def g2_compress(pt) -> bytes:
    """ZCash 96-byte compressed G2 (imaginary limb first)."""
    if pt is None:
        return bytes([0xC0]) + b"\x00" * 95
    (x0, x1), (y0, y1) = pt
    out = bytearray(x1.to_bytes(48, "big") + x0.to_bytes(48, "big"))
    out[0] |= 0x80
    sign = y1 > _HALF_P if y1 != 0 else y0 > _HALF_P
    if sign:
        out[0] |= 0x20
    return bytes(out)


def g2_decompress(data: bytes):
    if len(data) != 96:
        raise ValueError("bad G2 length")
    flags = data[0]
    if not flags & 0x80:
        raise ValueError("uncompressed G2 not supported")
    if flags & 0x40:
        if any(data[1:]) or flags != 0xC0:
            raise ValueError("bad G2 infinity encoding")
        return None
    x1 = int.from_bytes(bytes([flags & 0x1F]) + data[1:48], "big")
    x0 = int.from_bytes(data[48:], "big")
    if x0 >= P or x1 >= P:
        raise ValueError("G2 x out of range")
    x = (x0, x1)
    y = f2_sqrt(f2_add(f2_mul(f2_sqr(x), x), _B2))
    if y is None:
        raise ValueError("G2 x not on curve")
    sign = y[1] > _HALF_P if y[1] != 0 else y[0] > _HALF_P
    if bool(flags & 0x20) != sign:
        y = f2_neg(y)
    return (x, y)


# ------------------------------------------------------------------- scheme


def keygen(ikm: bytes, key_info: bytes = b"") -> int:
    """draft-irtf-cfrg-bls-signature KeyGen (HKDF-SHA-256)."""
    if len(ikm) < 32:
        raise ValueError("IKM must be at least 32 bytes")
    salt = b"BLS-SIG-KEYGEN-SALT-"
    while True:
        salt = hashlib.sha256(salt).digest()
        prk = hmac.new(salt, ikm + b"\x00", hashlib.sha256).digest()
        # HKDF-Expand to L = 48 bytes (two SHA-256 blocks).
        info = key_info + (48).to_bytes(2, "big")
        t1 = hmac.new(prk, info + b"\x01", hashlib.sha256).digest()
        t2 = hmac.new(prk, t1 + info + b"\x02", hashlib.sha256).digest()
        sk = int.from_bytes((t1 + t2)[:48], "big") % R_ORDER
        if sk:
            return sk


def pk_from_sk(sk: int):
    """Public key [sk] g2 (affine Fp2 pair)."""
    return g2_mul(G2_GEN, sk % R_ORDER)


def sign(sk: int, msg: bytes, dst: bytes = DST):
    """sigma = [sk] H(msg) in G1 (affine)."""
    return g1_mul_raw(hash_to_curve_g1(msg, dst), sk % R_ORDER)


def verify(pk, msg: bytes, sig, dst: bytes = DST) -> bool:
    """Single-signature verification: e(sigma, -g2) * e(H(m), pk) == 1."""
    if sig is None or pk is None:
        return False
    if not (g1_in_subgroup(sig) and g2_in_subgroup(pk)):
        return False
    h = hash_to_curve_g1(msg, dst)
    return pairing_check([(sig, g2_neg(G2_GEN)), (h, pk)])


def aggregate_signatures(sigs):
    """Sum in G1 — the operation the device MSM performs."""
    acc = None
    for s in sigs:
        acc = g1_add(acc, s)
    return acc


def aggregate_pubkeys(pks):
    acc = None
    for pk in pks:
        acc = g2_add(acc, pk)
    return acc


def verify_aggregate_same_message(pks, msg: bytes, agg_sig, dst: bytes = DST) -> bool:
    """Same-message aggregate verification (the quorum-certificate
    check): e(sigma_agg, -g2) * e(H(m), sum pk_i) == 1. One pairing
    product, one final exponentiation, regardless of committee size."""
    if agg_sig is None or not pks:
        return False
    if not g1_in_subgroup(agg_sig):
        return False
    apk = aggregate_pubkeys(list(pks))
    if apk is None:
        return False
    h = hash_to_curve_g1(msg, dst)
    return pairing_check([(agg_sig, g2_neg(G2_GEN)), (h, apk)])


# -------------------------------------------------------- deterministic keys


@dataclass(frozen=True)
class BlsKeyPair:
    """A BLS keypair bound to a replica identity (sim/bench plumbing)."""

    sk: int
    pk: tuple  # G2 affine
    pk_bytes: bytes  # 96-byte compressed

    def sign(self, msg: bytes):
        return sign(self.sk, msg)


def bls_keypair_from_identity(identity: bytes) -> BlsKeyPair:
    """Deterministic keypair from a 32-byte replica identity: IKM =
    SHA-256("hd-bls-keygen-v1" || identity). Lets every harness
    component derive the same committee keyring without a trusted
    dealer (mirrors the ed25519 KeyRing's deterministic construction)."""
    ikm = hashlib.sha256(b"hd-bls-keygen-v1" + bytes(identity)).digest()
    sk = keygen(ikm)
    pk = pk_from_sk(sk)
    return BlsKeyPair(sk=sk, pk=pk, pk_bytes=g2_compress(pk))
