"""Shamir secret sharing over GF(2^255 - 19) (host reference path).

The framework's MPC-payload capability (BASELINE.md config 5): committed
values can carry k-of-n secret-shared payloads which replicas reconstruct
per committed block. The field is the same GF(2^255-19) the signature
kernels use, so the device path (:mod:`hyperdrive_tpu.ops.shamir`) reuses
the limb arithmetic; this module is the bignum oracle it is tested against.

Payload blocks are 31 bytes: every 31-byte string is < 2^248 < p, so
packing is injective and padding-free.
"""

from __future__ import annotations

import hashlib

from hyperdrive_tpu.analysis.annotations import wire_codec
from hyperdrive_tpu.crypto.ed25519 import P

__all__ = [
    "BLOCK_BYTES",
    "split_block",
    "reconstruct_block",
    "lagrange_coeffs_at_zero",
    "split_payload",
    "reconstruct_payload",
    "encode_share_bundle",
    "decode_share_bundle",
]

BLOCK_BYTES = 31


def _poly_eval(coeffs: list[int], x: int) -> int:
    acc = 0
    for c in reversed(coeffs):
        acc = (acc * x + c) % P
    return acc


def _det_coeff(tag: bytes, i: int) -> int:
    """Deterministic coefficient derivation (keeps the harness seedable)."""
    return int.from_bytes(hashlib.sha512(tag + i.to_bytes(4, "little")).digest(), "little") % P


def split_block(secret: int, k: int, n: int, tag: bytes = b"") -> list[tuple[int, int]]:
    """Split ``secret`` (< p) into n shares, any k of which reconstruct.

    Shares are (x, y) with x = 1..n. Coefficients derive deterministically
    from ``tag`` so tests and scenario replays are reproducible; pass a
    random tag for real secrecy.
    """
    if not 0 <= secret < P:
        raise ValueError("secret out of field range")
    if not 1 <= k <= n:
        raise ValueError("need 1 <= k <= n")
    coeffs = [secret] + [_det_coeff(tag, i) for i in range(1, k)]
    return [(x, _poly_eval(coeffs, x)) for x in range(1, n + 1)]


def lagrange_coeffs_at_zero(xs: list[int]) -> list[int]:
    """lambda_i = prod_{j != i} x_j / (x_j - x_i) mod p — the interpolation
    weights at 0 for the given share x-coordinates. Host-computed once per
    share-set; the device kernel applies them across many blocks."""
    lams = []
    for i, xi in enumerate(xs):
        num, den = 1, 1
        for j, xj in enumerate(xs):
            if i == j:
                continue
            num = (num * xj) % P
            den = (den * (xj - xi)) % P
        lams.append((num * pow(den, P - 2, P)) % P)
    return lams


def reconstruct_block(shares: list[tuple[int, int]]) -> int:
    """Interpolate the secret from k (x, y) shares."""
    xs = [x for x, _ in shares]
    lams = lagrange_coeffs_at_zero(xs)
    return sum(lam * y for lam, (_, y) in zip(lams, shares)) % P


# ------------------------------------------------------- byte-payload API


def split_payload(payload: bytes, k: int, n: int, tag: bytes = b"") -> list[list[tuple[int, int]]]:
    """Split an arbitrary byte payload into per-block share lists.

    The payload is chunked into 31-byte blocks (the final block keeps its
    true length via a standard 0x80 pad)."""
    padded = payload + b"\x80"
    padded += b"\x00" * ((-len(padded)) % BLOCK_BYTES)
    blocks = [
        int.from_bytes(padded[i : i + BLOCK_BYTES], "little")
        for i in range(0, len(padded), BLOCK_BYTES)
    ]
    return [
        split_block(b, k, n, tag=tag + i.to_bytes(4, "little"))
        for i, b in enumerate(blocks)
    ]


def unpad_payload(out: bytes) -> bytes:
    """Strip the 0x80 padding — shared by the host and device paths so the
    two can never desynchronize."""
    end = out.rstrip(b"\x00")
    if not end.endswith(b"\x80"):
        raise ValueError("invalid payload padding")
    return end[:-1]


def reconstruct_payload(block_shares: list[list[tuple[int, int]]]) -> bytes:
    """Inverse of :func:`split_payload` given >= k shares per block."""
    out = b"".join(
        reconstruct_block(shares).to_bytes(BLOCK_BYTES, "little")
        for shares in block_shares
    )
    return unpad_payload(out)


# ----------------------------------------------------- wire bundle format
#
# The byte encoding a Propose's ``payload`` field carries: every replica
# receives the full n-share bundle and any k shares reconstruct at commit
# (BASELINE config 5). x-coordinates are implicit (split_payload always
# emits x = 1..n in order), so the bundle is just the y-value matrix.


@wire_codec(tag="shamir.bundle", max_bytes=1 << 20)
def encode_share_bundle(block_shares: list[list[tuple[int, int]]]) -> bytes:
    """[blocks][n] (x, y) shares -> bytes: u32 blocks, u32 n, then y values
    as 32-byte little-endian rows, block-major."""
    blocks = len(block_shares)
    n = len(block_shares[0]) if blocks else 0
    parts = [blocks.to_bytes(4, "little"), n.to_bytes(4, "little")]
    for shares in block_shares:
        if len(shares) != n or [x for x, _ in shares] != list(range(1, n + 1)):
            raise ValueError("bundle blocks must carry shares x = 1..n in order")
        parts.extend(y.to_bytes(32, "little") for _, y in shares)
    return b"".join(parts)


@wire_codec(tag="shamir.bundle", max_bytes=1 << 20)
def decode_share_bundle(data: bytes) -> list[list[tuple[int, int]]]:
    """Inverse of :func:`encode_share_bundle`; raises ValueError on any
    malformed input (never crashes — proposal payloads are attacker-
    controlled bytes)."""
    if len(data) < 8:
        raise ValueError("bundle too short")
    blocks = int.from_bytes(data[0:4], "little")
    n = int.from_bytes(data[4:8], "little")
    if blocks > 1 << 20 or n > 1 << 20 or len(data) != 8 + 32 * blocks * n:
        raise ValueError("bundle size mismatch")
    out = []
    off = 8
    for _ in range(blocks):
        shares = []
        for x in range(1, n + 1):
            y = int.from_bytes(data[off : off + 32], "little")
            if y >= P:
                raise ValueError("share value out of field range")
            shares.append((x, y))
            off += 32
        out.append(shares)
    return out
