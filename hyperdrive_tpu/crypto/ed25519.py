"""Pure-Python Ed25519 (RFC 8032), the framework's host verification path.

This is the correctness oracle: the TPU batch verifier
(:mod:`hyperdrive_tpu.ops.ed25519_jax`) must agree with this implementation
bit-for-bit on accept/reject, which is enforced by differential tests.

Implementation notes:
- Extended homogeneous coordinates (X, Y, Z, T) on the twisted Edwards
  curve -x^2 + y^2 = 1 + d x^2 y^2 over GF(2^255 - 19).
- Scalar multiplication is plain double-and-add on Python ints — this is a
  host correctness path, not the throughput path (that is the TPU's job).
- All helpers needed by the device path (decompression, scalar reduction,
  the challenge hash) are exported so the host<->device packing shares one
  definition of every quantity.
"""

from __future__ import annotations

import hashlib

__all__ = [
    "P",
    "L",
    "D",
    "BASE",
    "sha512",
    "secret_expand",
    "public_key_from_seed",
    "sign",
    "verify",
    "point_compress",
    "point_decompress",
    "challenge_scalar",
    "scalar_from_bytes",
    "point_add",
    "point_double",
    "scalar_mult",
    "point_equal",
    "IDENTITY",
]

# Field prime and group order.
P = 2**255 - 19
L = 2**252 + 27742317777372353535851937790883648493

# Curve constant d = -121665/121666 mod p.
D = (-121665 * pow(121666, P - 2, P)) % P

# sqrt(-1) mod p, used in decompression.
SQRT_M1 = pow(2, (P - 1) // 4, P)


def sha512(data: bytes) -> bytes:
    return hashlib.sha512(data).digest()


# ------------------------------------------------------------ point algebra
# Points are (X, Y, Z, T) with x = X/Z, y = Y/Z, T = XY/Z.

IDENTITY = (0, 1, 1, 0)


def point_add(p, q):
    """Unified addition (complete for a = -1 twisted Edwards)."""
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = ((y1 - x1) * (y2 - x2)) % P
    b = ((y1 + x1) * (y2 + x2)) % P
    c = (2 * t1 * t2 * D) % P
    dd = (2 * z1 * z2) % P
    e = b - a
    f = dd - c
    g = dd + c
    h = b + a
    return ((e * f) % P, (g * h) % P, (f * g) % P, (e * h) % P)


def point_double(p):
    return point_add(p, p)


def scalar_mult(s: int, p):
    q = IDENTITY
    while s > 0:
        if s & 1:
            q = point_add(q, p)
        p = point_add(p, p)
        s >>= 1
    return q


def point_equal(p, q) -> bool:
    """Projective equality: X1 Z2 == X2 Z1 and Y1 Z2 == Y2 Z1."""
    x1, y1, z1, _ = p
    x2, y2, z2, _ = q
    return (x1 * z2 - x2 * z1) % P == 0 and (y1 * z2 - y2 * z1) % P == 0


# Base point: y = 4/5 mod p, x recovered with even parity.
def _base_point():
    y = (4 * pow(5, P - 2, P)) % P
    x = _recover_x(y, 0)
    return (x, y, 1, (x * y) % P)


def _recover_x(y: int, sign: int):
    """Solve x^2 = (y^2 - 1) / (d y^2 + 1); None if no root exists."""
    if y >= P:
        return None
    x2 = ((y * y - 1) * pow(D * y * y + 1, P - 2, P)) % P
    if x2 == 0:
        if sign:
            return None
        return 0
    x = pow(x2, (P + 3) // 8, P)
    if (x * x - x2) % P != 0:
        x = (x * SQRT_M1) % P
    if (x * x - x2) % P != 0:
        return None
    if (x & 1) != sign:
        x = P - x
    return x


BASE = _base_point()


# ------------------------------------------------------------- wire formats


def point_compress(p) -> bytes:
    x, y, z, _ = p
    zinv = pow(z, P - 2, P)
    x = (x * zinv) % P
    y = (y * zinv) % P
    return int.to_bytes(y | ((x & 1) << 255), 32, "little")


def point_decompress(data: bytes):
    """Decompress 32 bytes to an extended point, or None if invalid."""
    if len(data) != 32:
        return None
    enc = int.from_bytes(data, "little")
    y = enc & ((1 << 255) - 1)
    sign = enc >> 255
    x = _recover_x(y, sign)
    if x is None:
        return None
    return (x, y, 1, (x * y) % P)


def scalar_from_bytes(data: bytes) -> int:
    return int.from_bytes(data, "little")


def challenge_scalar(r_bytes: bytes, pub: bytes, msg: bytes) -> int:
    """k = SHA-512(R || A || M) mod L — shared by sign, host verify, and the
    device packing path."""
    return scalar_from_bytes(sha512(r_bytes + pub + msg)) % L


# ------------------------------------------------------------------ keypath


def secret_expand(seed: bytes) -> tuple[int, bytes]:
    """Expand a 32-byte seed into the clamped scalar and the prefix."""
    if len(seed) != 32:
        raise ValueError("seed must be 32 bytes")
    h = sha512(seed)
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a, h[32:]


def public_key_from_seed(seed: bytes) -> bytes:
    a, _ = secret_expand(seed)
    return point_compress(scalar_mult(a, BASE))


def sign(seed: bytes, msg: bytes) -> bytes:
    """RFC 8032 Ed25519 signature: R (32B) || s (32B little-endian)."""
    a, prefix = secret_expand(seed)
    pub = point_compress(scalar_mult(a, BASE))
    r = scalar_from_bytes(sha512(prefix + msg)) % L
    r_point = scalar_mult(r, BASE)
    r_bytes = point_compress(r_point)
    k = challenge_scalar(r_bytes, pub, msg)
    s = (r + k * a) % L
    return r_bytes + int.to_bytes(s, 32, "little")


def verify(pub: bytes, msg: bytes, sig: bytes) -> bool:
    """Check [s]B == R + [k]A. Returns False on any malformed input."""
    if len(pub) != 32 or len(sig) != 64:
        return False
    a_point = point_decompress(pub)
    if a_point is None:
        return False
    r_bytes = sig[:32]
    r_point = point_decompress(r_bytes)
    if r_point is None:
        return False
    s = scalar_from_bytes(sig[32:])
    if s >= L:
        return False
    k = challenge_scalar(r_bytes, pub, msg)
    sb = scalar_mult(s, BASE)
    rka = point_add(r_point, scalar_mult(k, a_point))
    return point_equal(sb, rka)
