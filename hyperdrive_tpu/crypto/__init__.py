"""Host-side cryptography: Ed25519 identity/signing and Shamir sharing.

The reference delegates all signing/verification to ``renproject/id``
(secp256k1 ECDSA + Keccak) and *assumes* messages are authenticated before
they reach the library (reference: process/process.go:95-98). This
framework makes authentication first-class and chooses Ed25519: the curve
arithmetic batches cleanly onto TPU int32 lanes
(:mod:`hyperdrive_tpu.ops.ed25519_jax`), and this module provides the
bit-exact host implementation that the device kernels are differentially
tested against.
"""

from hyperdrive_tpu.crypto.ed25519 import (
    public_key_from_seed,
    sign,
    verify,
)
from hyperdrive_tpu.crypto.keys import KeyPair, KeyRing

__all__ = [
    "KeyPair",
    "KeyRing",
    "public_key_from_seed",
    "sign",
    "verify",
]
