"""Replica identity and signing keys.

The capability analogue of ``renproject/id`` in the reference (Signatory
pubkey-hash identities, PrivKey signing — reference usage:
process/process.go:105, process/message_test.go:145-158), with a deliberate
design change: a Signatory here *is* the 32-byte Ed25519 public key, which
is exactly the array layout the TPU batch verifier consumes.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

from hyperdrive_tpu.crypto import ed25519
from hyperdrive_tpu.types import Signatory

__all__ = ["KeyPair", "KeyRing"]


def _backend():
    """The shared C++ signer/verifier when buildable, else None (Python
    oracle path). Resolved lazily so importing crypto never forces a
    compile."""
    from hyperdrive_tpu import native

    return native.instance()


@dataclass(frozen=True)
class KeyPair:
    """A replica's Ed25519 seed and derived public identity."""

    seed: bytes
    public: Signatory

    @classmethod
    def from_seed(cls, seed: bytes) -> "KeyPair":
        n = _backend()
        if n is not None:
            return cls(seed=seed, public=n.public_from_seed(seed))
        return cls(seed=seed, public=ed25519.public_key_from_seed(seed))

    @classmethod
    def deterministic(cls, tag: bytes) -> "KeyPair":
        """Derive a keypair from an arbitrary tag (test/harness use)."""
        return cls.from_seed(hashlib.sha256(tag).digest())

    @property
    def signatory(self) -> Signatory:
        return self.public

    def sign_digest(self, digest: bytes) -> bytes:
        n = _backend()
        if n is not None:
            return n.sign(self.seed, digest, pub=self.public)
        return ed25519.sign(self.seed, digest)

    def sign_message(self, msg):
        """Attach a detached signature over the message's signing digest."""
        return msg.with_signature(self.sign_digest(msg.digest()))


class KeyRing:
    """An ordered set of keypairs — the signatory set of one network."""

    def __init__(self, pairs: list[KeyPair]):
        self.pairs = list(pairs)
        self.by_signatory = {kp.public: kp for kp in pairs}

    @classmethod
    def deterministic(cls, n: int, namespace: bytes = b"hyperdrive") -> "KeyRing":
        return cls(
            [KeyPair.deterministic(namespace + b"-%d" % i) for i in range(n)]
        )

    @property
    def signatories(self) -> list[Signatory]:
        return [kp.public for kp in self.pairs]

    def __len__(self) -> int:
        return len(self.pairs)

    def __getitem__(self, i: int) -> KeyPair:
        return self.pairs[i]
