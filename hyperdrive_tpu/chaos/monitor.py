"""Safety/liveness invariant monitor for chaos runs.

The monitor wraps a :class:`~hyperdrive_tpu.harness.sim.Simulation`'s
commit callback — the same seam the HD_SANITIZE runtime sanitizer
interposes (utils/sanitize.py) — and receives lifecycle notifications
(crash/restore/heal) from the chaos engine. It checks, *while the run is
still live* so the ScenarioRecord is intact at raise time:

- **no-fork-across-restarts** — one committed value per height,
  network-wide, forever: a restored replica re-committing a height must
  agree with what the network committed, and no two replicas may ever
  commit different values at the same height (safety under ≤ f faults,
  paper Lemma: agreement).
- **bounded rounds to commit after every heal** — after a partition
  heals, each live replica's next commit must land within
  ``max_rounds_after_heal`` rounds (liveness once synchrony resumes,
  paper round-synchronization argument).

and post-run via :meth:`check_final`:

- **commit-digest equality among honest replicas** — byte-equality of
  every overlapping commit, cross-checked against the obs journal's
  commit events when the sim runs with ``observe=True``.
- **completeness** — the run actually reached its target height.

A violation raises :class:`InvariantViolation` (an ``AssertionError``
subclass so plain pytest/soak harnesses catch it naturally); the soak
CLI reacts by dumping the ScenarioRecord and obs journal for
message-for-message replay.
"""

from __future__ import annotations

from typing import TYPE_CHECKING

if TYPE_CHECKING:  # pragma: no cover
    from hyperdrive_tpu.harness.sim import Simulation, SimulationResult

__all__ = ["InvariantMonitor", "InvariantViolation"]


class InvariantViolation(AssertionError):
    """A chaos invariant failed; ``kind`` names which one."""

    def __init__(self, kind: str, detail: str) -> None:
        super().__init__(f"{kind}: {detail}")
        self.kind = kind


class InvariantMonitor:
    """Attach to a Simulation *before* ``run()``; it hooks the commit
    callback and registers itself as ``sim._chaos_monitor`` so the chaos
    engine reports crashes, restores, and heals as they happen."""

    def __init__(
        self,
        sim: "Simulation",
        *,
        max_rounds_after_heal: int = 12,
        honest: "set[int] | None" = None,
    ) -> None:
        self.sim = sim
        self.max_rounds_after_heal = max_rounds_after_heal
        self.honest = set(range(sim.n)) if honest is None else set(honest)
        #: height -> committed value: the network-wide chain. Survives
        #: crashes and restores by construction — it is never reset.
        self.chain: dict[int, bytes] = {}
        self.heals: list[float] = []
        self.crashes: list[tuple[int, float]] = []
        self.restores: list[tuple[int, int]] = []
        self.commit_rounds_after_heal: list[int] = []
        self._await_heal_commit: "set[int] | None" = None
        #: (epoch, boundary height) per observed epoch switch.
        self.epoch_switches: list[tuple[int, int]] = []
        self.commit_rounds_after_epoch: list[int] = []
        self._last_epoch = 0
        self._epoch_boundary: "int | None" = None
        self._await_epoch_commit: "set[int] | None" = None
        self._orig_commit = sim._on_commit
        sim._on_commit = self._commit
        sim._chaos_monitor = self

    # -- live hooks --------------------------------------------------

    def _commit(self, i: int, height: int, value: bytes):
        prev = self.chain.get(height)
        if prev is not None and prev != value:
            raise InvariantViolation(
                "fork",
                f"replica {i} committed {value.hex()[:16]} at height "
                f"{height}; the network committed {prev.hex()[:16]}",
            )
        self.chain[height] = value
        awaiting = self._await_heal_commit
        if awaiting is not None and i in awaiting:
            awaiting.discard(i)
            rounds = self.sim.replicas[i].proc.current_round + 1
            self.commit_rounds_after_heal.append(rounds)
            if rounds > self.max_rounds_after_heal:
                raise InvariantViolation(
                    "liveness",
                    f"replica {i} needed {rounds} rounds to commit "
                    f"height {height} after heal "
                    f"(bound {self.max_rounds_after_heal})",
                )
        # Bounded rounds-to-commit after churn: armed at each epoch
        # switch below; checked BEFORE the commit callback runs (the
        # sim's boundary handler rotates the round machinery, so
        # current_round must be read pre-rotation).
        eawait = self._await_epoch_commit
        if (
            eawait is not None
            and i in eawait
            and self._epoch_boundary is not None
            and height > self._epoch_boundary
        ):
            eawait.discard(i)
            rounds = self.sim.replicas[i].proc.current_round + 1
            self.commit_rounds_after_epoch.append(rounds)
            if rounds > self.max_rounds_after_heal:
                raise InvariantViolation(
                    "epoch-liveness",
                    f"replica {i} needed {rounds} rounds to commit "
                    f"height {height} after the epoch "
                    f"{self._last_epoch} switch "
                    f"(bound {self.max_rounds_after_heal})",
                )
        ret = self._orig_commit(i, height, value)
        sim = self.sim
        if (
            getattr(sim, "epoch_schedule", None) is not None
            and sim.epoch > self._last_epoch
        ):
            self._last_epoch = sim.epoch
            self.epoch_switches.append((sim.epoch, height))
            self._epoch_boundary = height
            self._await_epoch_commit = {
                j
                for j in range(sim.n)
                if sim.alive[j] and j in self.honest
            }
        return ret

    def note_crash(self, victim: int, now: float) -> None:
        self.crashes.append((victim, now))

    def note_restore(self, victim: int, resync_height: int) -> None:
        self.restores.append((victim, resync_height))

    def note_heal(self, now: float) -> None:
        self.heals.append(now)
        sim = self.sim
        self._await_heal_commit = {
            i for i in range(sim.n) if sim.alive[i] and i in self.honest
        }

    # -- post-run ----------------------------------------------------

    def check_final(self, result: "SimulationResult") -> "InvariantMonitor":
        """Assert the post-run invariants; returns self for chaining."""
        result.assert_safety()
        has_exec = bool(getattr(self.sim, "executors", None))
        for i in sorted(self.honest):
            for height, value in result.commits[i].items():
                want = self.chain.get(height)
                if want is None:
                    continue
                if has_exec:
                    # Execution runs store root-extended commits
                    # (value + state root); the monitor's chain holds
                    # the raw consensus value from the callback seam.
                    # Root agreement is _check_exec's dedicated job.
                    value = value[: len(want)]
                if value != want:
                    raise InvariantViolation(
                        "digest",
                        f"replica {i} holds {value.hex()[:16]} at height "
                        f"{height}; chain has {want.hex()[:16]}",
                    )
        self._check_journal()
        # Post-heal liveness: a completed run IS the liveness proof —
        # completion means every replica individually committed the
        # target height, and the harness stops delivering the moment
        # that happens, so a replica can legitimately end mid-height
        # with its commit quorum still in flight. Only when the run
        # STALLED (queue drained or max_steps without completing) does
        # an unemptied awaiting set witness a real post-heal deadlock.
        if (
            not result.completed
            and self.heals
            and self._await_heal_commit
        ):
            raise InvariantViolation(
                "liveness",
                f"replicas {sorted(self._await_heal_commit)} never "
                "committed after the last heal",
            )
        if not result.completed:
            raise InvariantViolation(
                "liveness",
                f"run stalled below target; heights={result.heights}",
            )
        self._check_epochs()
        self._check_overlay()
        self._check_exec()
        return self

    def _check_epochs(self) -> None:
        """Dynamic-validator-set invariants (epoch runs only):

        - **no retired key in a caught-up whitelist** — once a rotation
          retires a key at its bound height, no replica at or past that
          height may still whitelist it (so no commit can count it);
        - **epoch-proof chain continuity** — the UNION of per-replica
          proof chains covers every epoch 1..current and verifies
          end-to-end from genesis. Per-replica chains legitimately have
          gaps (a resync jumps a laggard OVER boundary commits, so it
          never mints those proofs); the network-wide claim is that
          SOMEONE certified every hop, and the hops link up.
        """
        sim = self.sim
        sched = getattr(sim, "epoch_schedule", None)
        if sched is None:
            return
        for sig, bad_from in sim._retired.items():
            for j in sorted(self.honest):
                if not sim.alive[j]:
                    continue
                r = sim.replicas[j]
                if (
                    r.proc.current_height >= bad_from
                    and sig in r.procs_allowed
                ):
                    raise InvariantViolation(
                        "retired-key",
                        f"replica {j} at height {r.proc.current_height} "
                        f"still whitelists a key retired from height "
                        f"{bad_from}",
                    )
        certifiers = [
            c for c in getattr(sim, "certifiers", []) if c is not None
        ]
        if not certifiers or sim.epoch == 0:
            return
        covered: dict = {}
        for c in certifiers:
            for e, pr in getattr(c, "proofs", {}).items():
                covered.setdefault(e, pr)
        missing = [
            e for e in range(1, sim.epoch + 1) if e not in covered
        ]
        if missing:
            raise InvariantViolation(
                "epoch-chain",
                f"no replica holds a transition proof for epochs "
                f"{missing} (current epoch {sim.epoch})",
            )
        from hyperdrive_tpu.epochs import EpochChainError, verify_epoch_chain

        try:
            verify_epoch_chain(
                sched.signatories(0),
                [covered[e] for e in range(1, sim.epoch + 1)],
            )
        except EpochChainError as exc:
            raise InvariantViolation("epoch-chain", str(exc)) from exc

    def _check_overlay(self) -> None:
        """Aggregation-overlay invariants (overlay runs only):

        - **no honest peer permanently demoted** — contribution scoring
          may transiently demote an honest peer caught behind a
          partition or mid-restore (its frames look withheld from the
          far side), and per-commit rehabilitation restores it once the
          charges stop. PERMANENT means the commit floor advanced far
          enough past the peer's last charge that rehabilitation must
          have lifted it back over ``demote_at`` — and it didn't. A
          still-demoted honest peer whose last charge was too recent
          for the available runway is tolerated: the scenario ended,
          not the recovery mechanism.
        - **never-starve** — if any level window ever expired with
          coverage still missing, the ranked direct-gossip fallback
          must have engaged: timeouts without fallback means the
          escalation ladder dead-ends and slow peers starve silently.
        """
        ov = getattr(self.sim, "_overlay", None)
        if ov is None:
            return
        heal = ov.config.heal_rate
        permanent = []
        for p in ov.honest_demoted():
            if p not in self.honest:
                continue
            deficit = ov.scores.demote_at - ov.scores.scores[p] + 1
            runway = ov._floor - ov._last_charge_floor.get(p, ov._floor)
            if heal and runway * heal < deficit:
                continue
            permanent.append(p)
        if permanent:
            raise InvariantViolation(
                "overlay-demotion",
                f"honest peers {permanent} permanently demoted (scores "
                f"{[ov.scores.scores[p] for p in permanent]}, floor "
                f"{ov._floor}, byzantine={sorted(ov._byz)}) — "
                f"rehabilitation had the runway and did not recover them",
            )
        exhausted = getattr(ov, "windows_exhausted", 0)
        if exhausted and not ov.fallback_engaged:
            raise InvariantViolation(
                "overlay-starvation",
                f"{exhausted} level windows exhausted all "
                f"{ov.config.max_waves} waves with coverage missing but "
                "the ranked fallback never engaged",
            )

    def _check_exec(self) -> None:
        """Replicated-ledger invariants (execution runs only):

        - **state-root agreement** — every honest replica that applied
          a block at a committed height derived the SAME chained state
          root: the deterministic-execution analogue of no-fork. The
          commit values already carry the root (the sim chains it into
          the commit digest), so a divergence would eventually surface
          as a value fork too — checking the executors directly
          localizes blame to the apply path and catches a replica whose
          ledger ran ahead of or behind its own commits.
        - **commit/ledger binding** — each replica's stored commit at a
          height must end with that replica's own root for the height,
          so the root the certificate chain vouches for is the root the
          ledger actually computed.
        - **no rolled-back root committed** — the speculative pipeline's
          hard promise (``--exec-pipeline-every`` soak legs): a root
          computed under a wrong signature guess and then unwound
          (``discarded_roots``) must never appear inside ANY honest
          replica's committed value. A leak means a commit record was
          minted from pre-rollback state — the one failure mode
          speculation must not have.
        """
        executors = getattr(self.sim, "executors", None)
        if not executors:
            return
        # Device executors queue applied heights on-device; materialize
        # every pending root before auditing (host sync is a no-op).
        for ex in {id(e): e for e in executors}.values():
            ex.sync()
        by_height: dict[int, dict[bytes, list[int]]] = {}
        for i, ex in enumerate(executors):
            if i not in self.honest:
                continue
            for height, root in ex.roots.items():
                by_height.setdefault(height, {}).setdefault(
                    root, []
                ).append(i)
        for height in sorted(by_height):
            by_root = by_height[height]
            if len(by_root) > 1:
                raise InvariantViolation(
                    "exec-root",
                    f"state-root fork at height {height}: "
                    + "; ".join(
                        f"{root[:8].hex()} from replicas {reps}"
                        for root, reps in sorted(by_root.items())
                    ),
                )
        for i in sorted(self.honest):
            ex = executors[i]
            for height, value in self.sim.commits[i].items():
                root = ex.roots.get(height)
                if root is not None and not value.endswith(root):
                    raise InvariantViolation(
                        "exec-root",
                        f"replica {i}'s commit at height {height} does "
                        f"not end with its own state root "
                        f"{root[:8].hex()}",
                    )
        discarded: set[bytes] = set()
        for i, ex in enumerate(executors):
            if i in self.honest:
                discarded |= getattr(ex, "discarded_roots", set())
        if discarded:
            for i in sorted(self.honest):
                for height, value in self.sim.commits[i].items():
                    for root in discarded:
                        if root in value:
                            raise InvariantViolation(
                                "exec-rollback",
                                f"rolled-back root {root[:8].hex()} "
                                f"appears in replica {i}'s committed "
                                f"value at height {height} — a commit "
                                "was minted from speculative state that "
                                "the pipeline later unwound",
                            )

    @staticmethod
    def check_tenant_fairness(policy) -> None:
        """Multi-tenant drain-policy invariant (the ``--tenants-every``
        soak leg): the starvation bound is a hard promise, not a
        heuristic. A :class:`~hyperdrive_tpu.devsched.DeficitRoundRobin`
        forces a command into the next launch once it has been deferred
        ``starve_after`` times, so no command ever observes a deferral
        count beyond the bound — however hard one tenant firehoses the
        shared queue. ``max_deferrals`` is the policy's own high-water
        mark; exceeding the bound means the forced lane failed."""
        bound = getattr(policy, "starve_after", 0)
        seen = getattr(policy, "max_deferrals", 0)
        if bound and seen > bound:
            raise InvariantViolation(
                "tenant-fairness",
                f"a tenant command was deferred {seen} times "
                f"(starvation bound {bound}) — the forced lane never "
                f"fired for it",
            )

    # --------------------------------------------------- campaign checks
    # Sim-free staticmethods (the check_tenant_fairness precedent): the
    # campaign runner hands them the campaign summary, and they speak
    # InvariantViolation like every other probe, so the chaos soak, the
    # campaign CLI and the tests all share one failure currency.

    @staticmethod
    def check_campaign_proportionality(
        trajectory, *, grind_width: int = 1
    ) -> None:
        """The arXiv:2004.12990 proportionality bound over a WHOLE
        capture trajectory: cumulative adversary committee seats must
        not exceed cumulative proportional expectation plus a
        concentration allowance plus the grinding uplift.

        Per epoch ``e`` with realized adversary stake share ``p_e`` and
        committee size ``k``, a proportional election seats the
        adversary ``k * p_e`` in expectation with per-epoch deviation
        ``sigma_e = sqrt(k * p_e * (1 - p_e))``. A grinder choosing the
        best of ``W`` candidate boundary blocks takes the max of ``W``
        roughly-independent draws — worth at most
        ``sigma_e * sqrt(2 ln W)`` extra per epoch (the Gaussian
        max bound; LOGARITHMIC in grinding effort, which is the whole
        point of the anchor chain). On top, a 3-sigma allowance over
        the campaign's summed variance covers ordinary luck. Exceeding
        the total means the election machinery leaks more than
        grinding theory permits — a real disproportionality bug, not
        adversary luck."""
        import math

        seats = 0.0
        expected = 0.0
        var = 0.0
        grind_slack = 0.0
        uplift = math.sqrt(2.0 * math.log(max(grind_width, 2)))
        for row in trajectory:
            k = row["committee"]
            p = row["adv_stake"] / row["total_stake"]
            sigma = math.sqrt(k * p * (1.0 - p))
            seats += row["seats"]
            expected += k * p
            var += k * p * (1.0 - p)
            grind_slack += sigma * uplift
        bound = expected + grind_slack + 3.0 * math.sqrt(var)
        if seats > bound:
            raise InvariantViolation(
                "capture-proportionality",
                f"adversary took {seats:.0f} committee seats over "
                f"{len(list(trajectory))} epochs; proportional "
                f"expectation {expected:.1f} + grinding allowance "
                f"{grind_slack:.1f} (width {grind_width}) + 3-sigma "
                f"{3.0 * math.sqrt(var):.1f} bounds it at {bound:.1f}",
            )

    @staticmethod
    def check_storm_hygiene(summary: dict) -> None:
        """Signed-vote-storm invariants over a storm (or coincidence)
        gate summary: verify failures and demotions attribute ONLY to
        attackers (an honest signer must never fail batch verify or be
        reputation-shed), shed classes stay inside the closed
        vocabulary, and — when the reputation loop is on — repeat
        forgers actually demote and stop reaching the verifier by the
        final wave (the loop's entire reason to exist)."""
        from hyperdrive_tpu.load.backpressure import SHED_CLASSES

        gate = summary["gate"]
        honest = set(summary["honest"])
        attackers = set(summary["attackers"])
        for cls in gate["shed"]:
            if cls not in SHED_CLASSES:
                raise InvariantViolation(
                    "storm-shed-class",
                    f"gate shed under unknown class {cls!r}",
                )
        leaked = sorted(set(gate["verify_failed"]) & honest)
        if leaked:
            raise InvariantViolation(
                "storm-attribution",
                f"honest signers {leaked} charged with verify "
                "failures — misattribution would let a storm demote "
                "bystanders",
            )
        bad_demotions = sorted(set(gate["demoted"]) - attackers)
        if bad_demotions:
            raise InvariantViolation(
                "storm-attribution",
                f"non-attackers {bad_demotions} reputation-demoted",
            )
        if summary.get("reputation"):
            if gate["demotions"] < 1 or not gate["shed"].get(
                "reputation"
            ):
                raise InvariantViolation(
                    "storm-reputation",
                    "reputation loop on, yet no forger was demoted "
                    "or reputation-shed across the storm",
                )
            last = summary["waves"][-1]
            if last["attacker_rows_verified"]:
                raise InvariantViolation(
                    "storm-reputation",
                    f"{last['attacker_rows_verified']} forged rows "
                    "still reached batch verify in the final wave — "
                    "the reputation loop failed to move the shed "
                    "ahead of the verifier",
                )
            if last["admitted"] < summary["honest_rows"]:
                raise InvariantViolation(
                    "storm-liveness",
                    f"final wave admitted {last['admitted']} rows but "
                    f"the honest workload alone is "
                    f"{summary['honest_rows']} — the storm starved "
                    "honest prevotes instead of shedding forgers",
                )

    @staticmethod
    def check_campaign_economy(summary: dict) -> None:
        """Coincidence-family overlay invariants: the never-starve
        doctrine holds under the slice (every epoch that exhausted
        retry windows engaged the ranked fallback), and after the heal
        runway no HONEST validator is still contribution-demoted —
        partitions are forgiven, only persistent misbehavior is not."""
        for row in summary.get("overlay", ()):
            if row["windows_exhausted"] and not row["fallback_engaged"]:
                raise InvariantViolation(
                    "campaign-starvation",
                    f"epoch {row['epoch']}: {row['windows_exhausted']} "
                    "slots exhausted their retry windows with no "
                    "fallback engagement",
                )
        stuck = summary.get("honest_demoted_final", [])
        if stuck:
            raise InvariantViolation(
                "campaign-demotion",
                f"honest validators {stuck} still demoted after the "
                "heal runway — amnesty plus contribution credit must "
                "always repay a partition's debt",
            )

    def _check_journal(self) -> None:
        """Cross-check the obs flight recorder against the chain: every
        journalled commit event's value prefix must match what the
        monitor saw at the callback seam (observe=True runs only)."""
        snapshot = getattr(self.sim.obs, "snapshot", None)
        if snapshot is None:
            return
        for ev in snapshot():
            if ev.kind != "commit":
                continue
            want = self.chain.get(ev.height)
            if want is None or ev.detail is None:
                continue
            if not want.hex().startswith(str(ev.detail)):
                raise InvariantViolation(
                    "journal",
                    f"obs journal commit at height {ev.height} carries "
                    f"{ev.detail}; chain has {want.hex()[:16]}",
                )
