"""ChaosProxy: a frame-aware fault-injecting TCP forwarder.

The deterministic harness interprets FaultPlans in virtual time; this is
the same fault vocabulary applied to the REAL transport
(:class:`~hyperdrive_tpu.transport.TcpNode`): a proxy listens on its own
port, peers dial it instead of the target node, and every length-framed
consensus envelope flowing through it can be dropped, duplicated,
delayed, or black-holed by an in-flight :meth:`partition` /
:meth:`heal` toggle.

The proxy parses the transport's 4-byte little-endian framing rather
than splicing raw bytes, so faults land on whole messages — dropping
half a frame would just desynchronize the stream and close the
connection, which is a different (and less interesting) failure than
losing a vote. Faults draw from a seeded RNG; counters
(``forwarded``/``dropped``) make tests assertable.

One proxy covers one direction (peer -> target inbound). Symmetric
partitions place one in front of each side — exactly how toxiproxy-style
tools are deployed.
"""

from __future__ import annotations

import random
import socket
import struct
import threading
import time

__all__ = ["ChaosProxy"]

_LEN = struct.Struct("<I")
_MAX_FRAME = 1 << 20  # match transport.py: beyond this is a framing attack


class ChaosProxy:
    """Listen on ``self.port``; forward framed traffic to
    ``(target_host, target_port)`` with seeded faults.

    Parameters mirror :class:`~hyperdrive_tpu.chaos.plan.LinkFault`:
    ``drop``/``duplicate``/``delay`` are per-frame probabilities, and a
    delayed frame sleeps a uniform draw from ``delay_s`` before being
    written (the link stays FIFO — real TCP links are). While
    partitioned, inbound frames are read and discarded, keeping the
    peer's connection alive so heal resumes without a redial.

    ``bandwidth_bps > 0`` models a slow link rather than a lossy one:
    every forwarded frame pays a serialization delay of ``frame bits /
    bandwidth_bps`` seconds before the write (FIFO, so the slow-peer
    backlog accumulates exactly as a saturated pipe would). This is the
    overload family's slow-peer fault — the reader-side complement of
    the sender's backpressure: the target node's peer queue toward a
    throttled peer fills and sheds while healthy peers stay fast.

    ``fuzz_every = K > 0`` is the Byzantine-bytes fault: every Kth
    forwarded frame has its PAYLOAD mutated (seeded truncate / extend /
    bitflip / tag-swap, same vocabulary as tests/test_wire_audit.py)
    and its length header recomputed, so the stream stays parseable and
    the corruption lands in the target's DECODE path, not its framing
    layer. The target must count the frame (``malformed_frames``) or
    deliver a still-valid decode — never crash a read thread. ``fuzzed``
    counts mutations for assertions.
    """

    def __init__(
        self,
        target_host: str,
        target_port: int,
        *,
        listen_host: str = "127.0.0.1",
        seed: int = 0,
        drop: float = 0.0,
        duplicate: float = 0.0,
        delay: float = 0.0,
        delay_s: tuple[float, float] = (0.005, 0.05),
        bandwidth_bps: float = 0.0,
        fuzz_every: int = 0,
    ) -> None:
        self._target = (target_host, target_port)
        self._rng = random.Random(seed)
        self._rng_lock = threading.Lock()
        self.drop = drop
        self.duplicate = duplicate
        self.delay = delay
        self.delay_s = delay_s
        if bandwidth_bps < 0.0:
            raise ValueError(
                f"bandwidth_bps must be >= 0, got {bandwidth_bps}"
            )
        self.bandwidth_bps = bandwidth_bps
        #: Cumulative seconds of serialization delay paid (tests assert
        #: the throttle actually bit).
        self.throttled_s = 0.0
        if fuzz_every < 0:
            raise ValueError(f"fuzz_every must be >= 0, got {fuzz_every}")
        self.fuzz_every = fuzz_every
        #: Frames mutated by the fuzz fault (tests assert the mutation
        #: cadence actually bit).
        self.fuzzed = 0
        self._fuzz_ctr = 0
        self._partitioned = threading.Event()
        self._stop = threading.Event()
        self.forwarded = 0
        self.dropped = 0
        self._count_lock = threading.Lock()
        self._conns: list[socket.socket] = []
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((listen_host, 0))
        self._srv.listen(16)
        self.port = self._srv.getsockname()[1]
        self._threads = [
            threading.Thread(target=self._accept_loop, daemon=True)
        ]

    # ---------------------------------------------------------- lifecycle

    def start(self) -> "ChaosProxy":
        for t in self._threads:
            if not t.is_alive():
                t.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass
        with self._count_lock:
            conns, self._conns = self._conns, []
        for c in conns:
            try:
                c.close()
            except OSError:
                pass

    def __enter__(self) -> "ChaosProxy":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------------- faults

    def partition(self) -> None:
        """Black-hole traffic (frames read and discarded) until heal."""
        self._partitioned.set()

    def heal(self) -> None:
        self._partitioned.clear()

    @property
    def partitioned(self) -> bool:
        return self._partitioned.is_set()

    # ------------------------------------------------------------ plumbing

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            with self._count_lock:
                self._conns.append(conn)
            threading.Thread(
                target=self._pipe, args=(conn,), daemon=True
            ).start()

    def _pipe(self, conn: socket.socket) -> None:
        """One inbound connection: parse frames, apply faults, forward
        over a dedicated upstream connection (dialed lazily so the proxy
        can accept before the target listens)."""
        upstream: socket.socket | None = None
        try:
            with conn:
                while not self._stop.is_set():
                    frame = self._read_frame(conn)
                    if frame is None:
                        return
                    if self._partitioned.is_set():
                        self._note(dropped=1)
                        continue
                    with self._rng_lock:
                        r_drop = self._rng.random()
                        r_dup = self._rng.random()
                        r_delay = self._rng.random()
                        pause = self._rng.uniform(*self.delay_s)
                    if self.drop and r_drop < self.drop:
                        self._note(dropped=1)
                        continue
                    if self.delay and r_delay < self.delay:
                        time.sleep(pause)
                    if self.bandwidth_bps:
                        # Slow link: serialization time proportional to
                        # frame size, paid on every frame (deterministic
                        # in size, not seeded — a pipe's width is not a
                        # coin flip).
                        pay = len(frame) * 8.0 / self.bandwidth_bps
                        with self._count_lock:
                            self.throttled_s += pay
                        time.sleep(pay)
                    if self.fuzz_every:
                        with self._count_lock:
                            self._fuzz_ctr += 1
                            hit = self._fuzz_ctr % self.fuzz_every == 0
                        if hit:
                            frame = self._fuzz(frame)
                            with self._count_lock:
                                self.fuzzed += 1
                    copies = (
                        2 if self.duplicate and r_dup < self.duplicate else 1
                    )
                    for _ in range(copies):
                        if upstream is None:
                            upstream = self._dial()
                            if upstream is None:
                                return
                        try:
                            upstream.sendall(frame)
                        except OSError:
                            return
                        self._note(forwarded=1)
        finally:
            if upstream is not None:
                try:
                    upstream.close()
                except OSError:
                    pass

    def _fuzz(self, frame: bytes) -> bytes:
        """Mutate a frame's payload and recompute its length header.

        The framing layer stays intact on purpose: a bad length prefix
        only exercises the target's ``_read_frame`` guard, while a
        well-framed garbage payload reaches ``unmarshal_message`` — the
        decode path HD007/HDS005 exist to defend. Mutations mirror the
        wire-audit corpus: truncate, extend with junk, flip one bit,
        smash the leading tag byte."""
        payload = frame[_LEN.size:]
        with self._rng_lock:
            kind = self._rng.randrange(4)
            if kind == 0 and len(payload) > 1:
                payload = payload[: self._rng.randrange(1, len(payload))]
            elif kind == 1:
                payload = payload + bytes(
                    self._rng.randrange(256)
                    for _ in range(self._rng.randrange(1, 17))
                )
            elif kind == 2 and payload:
                i = self._rng.randrange(len(payload))
                b = bytearray(payload)
                b[i] ^= 1 << self._rng.randrange(8)
                payload = bytes(b)
            elif payload:
                b = bytearray(payload)
                b[0] = self._rng.randrange(256)
                payload = bytes(b)
        return _LEN.pack(len(payload)) + payload

    def _dial(self) -> "socket.socket | None":
        deadline = time.monotonic() + 5.0
        while not self._stop.is_set() and time.monotonic() < deadline:
            try:
                s = socket.create_connection(self._target, timeout=5.0)
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                with self._count_lock:
                    self._conns.append(s)
                return s
            except OSError:
                time.sleep(0.05)
        return None

    def _read_frame(self, conn: socket.socket) -> "bytes | None":
        head = self._recv_exact(conn, _LEN.size)
        if head is None:
            return None
        (length,) = _LEN.unpack(head)
        if length > _MAX_FRAME:
            return None  # mirror the transport: framing attack, hang up
        payload = self._recv_exact(conn, length)
        if payload is None:
            return None
        return head + payload

    @staticmethod
    def _recv_exact(conn: socket.socket, n: int) -> "bytes | None":
        buf = b""
        while len(buf) < n:
            try:
                chunk = conn.recv(n - len(buf))
            except OSError:
                return None
            if not chunk:
                return None
            buf += chunk
        return buf

    def _note(self, forwarded: int = 0, dropped: int = 0) -> None:
        with self._count_lock:
            self.forwarded += forwarded
            self.dropped += dropped
