"""The FaultPlan DSL: seeded, declarative adversarial conditions.

Tendermint-BFT's guarantees (PAPER.md; arXiv:1807.04938) are claims about
behavior under message loss, duplication, delay, network partitions, and
crash-restarts. A :class:`FaultPlan` states one such adversarial scenario
as data — per-link fault distributions, scheduled partitions with heal
times, crash-at-step followed by restart-from-checkpoint — and the
deterministic harness interprets it per delivery
(:class:`hyperdrive_tpu.harness.sim.Simulation` with ``chaos=``), while
:class:`hyperdrive_tpu.chaos.proxy.ChaosProxy` applies the same fault
vocabulary to real-socket :class:`~hyperdrive_tpu.transport.TcpNode`
traffic.

Everything is seeded: the same (plan, sim seed) pair produces the same
run, and because the harness records only *post-fault* deliveries, a
failing chaos run replays message-for-message from its
:class:`~hyperdrive_tpu.harness.sim.ScenarioRecord` with no knowledge of
the plan at all (crash/restore/resync lifecycle ops ride a record
trailer; see ROBUSTNESS.md).
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field

__all__ = ["LinkFault", "Partition", "CrashRestart", "FaultPlan"]


@dataclass(frozen=True)
class LinkFault:
    """Per-link fault distribution on the directed link ``src -> dst``.

    Each probability is evaluated once per delivery from the chaos
    engine's dedicated seeded stream. A dropped delivery is silently
    lost (the protocol has no retransmission — exactly the reference's
    trust model, process/process.go:47-60). A duplicated delivery
    arrives once now and once more at the back of the queue. A delayed
    delivery is deferred on the virtual clock by a uniform draw from
    ``[delay_min, delay_max)`` virtual seconds. Faulted copies are never
    re-faulted (no infinite delay/duplication chains); partitions still
    apply to them at their eventual delivery time.
    """

    src: int
    dst: int
    drop: float = 0.0
    duplicate: float = 0.0
    delay: float = 0.0
    delay_min: float = 0.05
    delay_max: float = 0.5


@dataclass(frozen=True)
class Partition:
    """A scheduled network partition on the virtual clock.

    From virtual time ``at`` until ``heal``, deliveries between replicas
    in different groups are blocked (local Timeout events are never
    blocked — a partitioned replica's own timers keep firing). Replicas
    not named in any group form one implicit remainder group, so
    ``groups=((5, 6),)`` isolates replicas 5 and 6 from everyone else.

    On heal, when ``resync_on_heal`` is set (default), every live
    replica whose height lags the network's best commit is jumped
    forward via :class:`~hyperdrive_tpu.replica.ResetHeight` — the
    protocol has no retransmission, so a minority partition can never
    recover the missed heights by itself; resync is the reference's own
    catch-up mechanism (replica/replica.go:222-235).
    """

    at: float
    heal: float
    groups: tuple[tuple[int, ...], ...]
    resync_on_heal: bool = True


@dataclass(frozen=True)
class CrashRestart:
    """Crash ``replica`` at delivery step ``crash_at_step``; restart it
    from its latest checkpoint ``restart_after_steps`` later.

    The crash loses every volatile buffer (sorted queue, burst lane,
    reentrant backlog); only the checkpoint envelope — taken through
    :func:`hyperdrive_tpu.utils.checkpoint.checkpoint_bytes` after every
    delivery the victim handles, the reference's "save after every
    method call" contract (process/state.go:18-20) — survives. On
    restart the Process state is restored (locked/valid values, vote
    logs, once-flags included) and the replica rejoins: in place when
    its height is still live (mid-height, re-arming the current step's
    timeout via :meth:`~hyperdrive_tpu.process.Process.resume`), or via
    ResetHeight when the network committed past it. A replica that
    crashes before handling anything restarts from the default genesis
    state.
    """

    replica: int
    crash_at_step: int
    restart_after_steps: int = 500


@dataclass(frozen=True)
class FaultPlan:
    """One scenario's complete adversarial schedule."""

    links: tuple[LinkFault, ...] = ()
    partitions: tuple[Partition, ...] = ()
    crashes: tuple[CrashRestart, ...] = field(default_factory=tuple)

    def validate(self, n: int) -> None:
        """Reject structurally impossible plans with a clear error
        instead of a mid-run surprise."""
        for lf in self.links:
            if not (0 <= lf.src < n and 0 <= lf.dst < n):
                raise ValueError(
                    f"link fault {lf.src}->{lf.dst} outside 0..{n - 1}"
                )
            for p in (lf.drop, lf.duplicate, lf.delay):
                if not 0.0 <= p <= 1.0:
                    raise ValueError(
                        f"link fault probability {p} outside [0, 1]"
                    )
            if not 0.0 <= lf.delay_min <= lf.delay_max:
                raise ValueError(
                    "link delay bounds must satisfy "
                    f"0 <= min <= max, got [{lf.delay_min}, {lf.delay_max}]"
                )
        for part in self.partitions:
            if not 0.0 <= part.at < part.heal:
                raise ValueError(
                    f"partition window [{part.at}, {part.heal}) is empty"
                )
            seen: set[int] = set()
            for group in part.groups:
                for m in group:
                    if not 0 <= m < n:
                        raise ValueError(
                            f"partition member {m} outside 0..{n - 1}"
                        )
                    if m in seen:
                        raise ValueError(
                            f"replica {m} appears in two partition groups"
                        )
                    seen.add(m)
        crashed: set[int] = set()
        for c in self.crashes:
            if not 0 <= c.replica < n:
                raise ValueError(
                    f"crash victim {c.replica} outside 0..{n - 1}"
                )
            if c.replica in crashed:
                raise ValueError(
                    f"replica {c.replica} has two crash schedules"
                )
            crashed.add(c.replica)
            if c.crash_at_step < 1 or c.restart_after_steps < 1:
                raise ValueError("crash/restart steps must be >= 1")

    @classmethod
    def seeded(
        cls,
        seed: int,
        n: int,
        *,
        partition: bool = True,
        crash: bool = True,
        links: bool = True,
    ) -> "FaultPlan":
        """Draw one randomized-but-reproducible scenario: a partition
        isolating up to f replicas with a heal time, one crash-restart
        (inside the isolated group when there is one, so the majority
        keeps its 2f+1 quorum), and a couple of lossy/dup/laggy links.
        The soak CLI (``python -m hyperdrive_tpu.chaos soak``) iterates
        this over scenario seeds."""
        rng = random.Random(seed)
        f = n // 3
        parts: tuple[Partition, ...] = ()
        isolated: list[int] = []
        if partition and f:
            isolated = rng.sample(range(n), rng.randint(1, f))
            at = rng.uniform(0.2, 0.8)
            parts = (
                Partition(
                    at=at,
                    heal=at + rng.uniform(1.0, 3.0),
                    groups=(tuple(isolated),),
                ),
            )
        crashes: tuple[CrashRestart, ...] = ()
        if crash and f:
            victim = rng.choice(isolated) if isolated else rng.randrange(n)
            crashes = (
                CrashRestart(
                    replica=victim,
                    crash_at_step=rng.randint(250, 700),
                    restart_after_steps=rng.randint(200, 600),
                ),
            )
        link_faults: list[LinkFault] = []
        if links:
            for _ in range(rng.randint(0, 3)):
                src, dst = rng.randrange(n), rng.randrange(n)
                link_faults.append(
                    LinkFault(
                        src=src,
                        dst=dst,
                        drop=rng.choice([0.0, 0.05, 0.1]),
                        duplicate=rng.choice([0.0, 0.05]),
                        delay=rng.choice([0.0, 0.1]),
                        delay_min=0.01,
                        delay_max=rng.uniform(0.05, 0.3),
                    )
                )
        plan = cls(
            links=tuple(link_faults), partitions=parts, crashes=crashes
        )
        plan.validate(n)
        return plan

    @classmethod
    def overload(
        cls,
        seed: int,
        n: int,
        *,
        rate: float = 2000.0,
    ) -> "tuple[FaultPlan, object]":
        """The overload fault family (ISSUE 11): open-loop load
        COINCIDING with partitions and heals. Returns ``(plan,
        profile)`` — the same :meth:`seeded` plan the unloaded baseline
        runs, paired with a digest-safe
        :class:`~hyperdrive_tpu.load.generator.LoadProfile` drawn from
        the same seed. The acceptance contract: a run with both applied
        commits the SAME chain digests as the plan alone, because the
        profile stays pinned in the behavior-neutral admission band
        (floor <= SHED_DUPLICATES) and the injector consumes no steps,
        clock, or rng. The soak CLI's ``--overload-every`` leg asserts
        exactly that equality."""
        from hyperdrive_tpu.load.generator import LoadProfile

        plan = cls.seeded(seed, n)
        profile = LoadProfile.seeded(seed, rate=rate)
        if profile.floor > 1:  # SHED_DUPLICATES
            raise ValueError(
                "overload family profiles must stay behavior-neutral "
                f"(floor <= SHED_DUPLICATES), got floor={profile.floor}"
            )
        return plan, profile

    @classmethod
    def churn(
        cls,
        seed: int,
        n: int,
        *,
        est_virtual_time: float = 4.0,
        crash: bool = True,
        links: bool = True,
    ) -> "FaultPlan":
        """The epoch-churn scenario family (ISSUE: dynamic validator
        sets). Three stressors composed so faults LAND ON epoch
        machinery rather than around it:

        - churn during an active partition — the partition window is
          drawn wide (``est_virtual_time`` fractions) so with short
          epochs at least one boundary election + key rotation commits
          while up to f//2 replicas are isolated;
        - crash-restore across an epoch boundary — the victim is chosen
          from the isolated group when there is one, and its restart
          window is long enough that the network usually crosses a
          boundary while it is down, forcing the restore path to
          re-apply epoch state (rotated whoami, new committee) before
          rejoining;
        - laggard rejoining under a rotated key — heal-time resync of
          the isolated group exercises exactly the stale-generation
          reject + retired-key bound in replica.py.

        The caller supplies the epoch schedule on the Simulation side
        (``epochs=EpochConfig(...)``); this plan only shapes WHEN the
        network is hostile. ``est_virtual_time``: rough expected virtual
        duration of the run, used to place the partition window."""
        rng = random.Random((seed << 1) ^ 0x45504F43)
        f = n // 3
        isolated: list[int] = []
        parts: tuple[Partition, ...] = ()
        if f:
            isolated = rng.sample(range(n), rng.randint(1, max(1, f // 2)))
            at = est_virtual_time * rng.uniform(0.25, 0.4)
            heal = at + est_virtual_time * rng.uniform(0.3, 0.45)
            parts = (
                Partition(at=at, heal=heal, groups=(tuple(isolated),)),
            )
        crashes: tuple[CrashRestart, ...] = ()
        if crash and f:
            victim = rng.choice(isolated) if isolated else rng.randrange(n)
            crashes = (
                CrashRestart(
                    replica=victim,
                    crash_at_step=rng.randint(300, 900),
                    restart_after_steps=rng.randint(300, 800),
                ),
            )
        link_faults: list[LinkFault] = []
        if links:
            for _ in range(rng.randint(0, 2)):
                src, dst = rng.randrange(n), rng.randrange(n)
                link_faults.append(
                    LinkFault(
                        src=src,
                        dst=dst,
                        drop=rng.choice([0.0, 0.05]),
                        duplicate=rng.choice([0.0, 0.05]),
                        delay=rng.choice([0.0, 0.1]),
                        delay_min=0.01,
                        delay_max=rng.uniform(0.05, 0.2),
                    )
                )
        plan = cls(
            links=tuple(link_faults), partitions=parts, crashes=crashes
        )
        plan.validate(n)
        return plan

    @classmethod
    def overlay(
        cls,
        seed: int,
        n: int,
        *,
        est_virtual_time: float = 4.0,
        crash: bool = True,
    ) -> "tuple[FaultPlan, object]":
        """The overlay fault family (ISSUE 12): Byzantine contributors
        composed with faults aimed at the aggregation TREE rather than
        at random replicas. Returns ``(plan, OverlayFaults)`` for
        ``Simulation(chaos=plan, overlay=OverlayConfig(faults=...))``.

        The tree-slicing partition is the novel piece: the epoch-0
        topology is a pure function of (seed, genesis anchor, default
        identities), so the plan reconstructs it here — before any sim
        exists — and cuts the network along a level boundary, isolating
        one full 2**level rank block. Inside the partition window every
        member of that block loses its entire sibling half at the level
        above, forcing wave escalation + withhold charging + ranked
        fallback on one side and reciprocal-push starvation handling on
        the other; the monitor then requires honest scores to recover
        after heal.

        Byzantine contributors (up to f//2, disjoint from the sliced
        block so the two stressors compose rather than shadow each
        other) withhold at a seeded level and garbage the rest of their
        frames. Crash-restore rotates an interior (odd-rank, relay-heavy)
        node mid-height, exercising tick disarm/re-arm."""
        import hashlib

        from hyperdrive_tpu.epochs import genesis_anchor
        from hyperdrive_tpu.overlay import OverlayFaults, Topology

        rng = random.Random((seed << 1) ^ 0x4F564C59)
        f = n // 3
        identities = [
            hashlib.sha256(b"sim-replica-%d-%d" % (seed, i)).digest()
            for i in range(n)
        ]
        topo = Topology(seed, genesis_anchor(seed), identities)
        parts: tuple[Partition, ...] = ()
        sliced: tuple = ()
        if topo.levels >= 1 and f:
            level = rng.randint(1, max(1, topo.levels - 1))
            groups = topo.level_groups(level)
            # Cut off one block, capped at f members so quorum survives.
            block = list(rng.choice(groups))
            if len(block) > f:
                block = sorted(rng.sample(block, f))
            sliced = tuple(block)
            at = est_virtual_time * rng.uniform(0.2, 0.35)
            heal = at + est_virtual_time * rng.uniform(0.25, 0.4)
            parts = (Partition(at=at, heal=heal, groups=(sliced,)),)
        byz_pool = [i for i in range(n) if i not in set(sliced)]
        byz_count = min(max(1, f // 2), len(byz_pool)) if f else 0
        byz = tuple(sorted(rng.sample(byz_pool, byz_count))) if byz_count else ()
        faults = OverlayFaults(
            byzantine=byz,
            withhold_levels=(rng.randint(1, max(1, topo.levels)),),
            garbage_rate=rng.uniform(0.2, 0.5),
            stale_rate=rng.uniform(0.0, 0.4),
        )
        crashes: tuple[CrashRestart, ...] = ()
        if crash and f:
            candidates = [i for i in range(n) if i not in byz]
            victim = max(
                candidates, key=lambda i: (topo.rank[i] & 1, -i)
            )
            crashes = (
                CrashRestart(
                    replica=victim,
                    crash_at_step=rng.randint(300, 900),
                    restart_after_steps=rng.randint(300, 800),
                ),
            )
        plan = cls(partitions=parts, crashes=crashes)
        plan.validate(n)
        faults.validate(n)
        return plan, faults
