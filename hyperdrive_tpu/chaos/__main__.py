"""The chaos soak driver.

Usage::

    python -m hyperdrive_tpu.chaos soak [--scenarios N] [--seed S]
        [--n N_REPLICAS] [--target H] [--out DIR] [--replay-every K]
        [--pipelined-every K] [--certs-every K] [--bls-certs-every K]
        [--churn-every K] [--overload-every K] [--overlay-every K]
        [--tenants-every K] [--exec-every K] [--exec-pipeline-every K]
        [--proofs-every K] [--fuzz-frames-every K] [--metrics-every K]
        [--campaign-every K] [--dump-ok DIR]
    python -m hyperdrive_tpu.chaos replay DUMP.bin

``soak`` runs N seeded scenarios — each a fresh
:meth:`~hyperdrive_tpu.chaos.plan.FaultPlan.seeded` draw (partition of
up to f replicas with a heal, one crash-restart, a few lossy links) —
under the :class:`~hyperdrive_tpu.chaos.monitor.InvariantMonitor`. Any
violation dumps the ScenarioRecord, the obs journal, and the victims'
checkpoints into ``--out`` and exits 1; the printed ``replay`` command
reproduces the failure message-for-message. Every ``--replay-every``-th
passing scenario is also replayed from its own record as a determinism
self-check.

Scenarios run unsigned (values are opaque digests; signature checking is
orthogonal to fault handling), so the soak needs no accelerator and no
jax import — the ``--bls-certs-every`` leg included, which exercises the
BLS aggregate paths on the pure-Python host reference (:mod:`..crypto.bls`)
rather than the device kernels. HD_SANITIZE=1 in the environment arms the
runtime sanitizer on every replica — CI runs the soak that way.
"""

from __future__ import annotations

import argparse
import hashlib
import os
import random
import sys

from hyperdrive_tpu.chaos.monitor import InvariantMonitor, InvariantViolation
from hyperdrive_tpu.chaos.plan import FaultPlan
from hyperdrive_tpu.harness.sim import ScenarioRecord, Simulation

#: Spread scenario seeds so adjacent indices explore unrelated plans.
_SEED_STRIDE = 9973


def _build(scen_seed: int, n: int, target: int, pipelined: bool = False,
           certificates: bool = False, bls_certificates: bool = False,
           load=None):
    plan = FaultPlan.seeded(scen_seed, n)
    extra = {}
    if certificates:
        extra["certificates"] = True
    if bls_certificates:
        extra["bls_certificates"] = True
    if load is not None:
        extra["load"] = load
    if pipelined:
        # Queue-backed settle path: every replica flushes through one
        # shared async device-work queue (jax-free QueueFlusher), so
        # faults land with coalesced settles in flight. Still unsigned
        # and accelerator-free — only the schedule moves.
        from hyperdrive_tpu.devsched import DeviceWorkQueue, QueueFlusher
        from hyperdrive_tpu.verifier import NullVerifier

        queue = DeviceWorkQueue(max_depth=8)
        extra = dict(
            devsched=queue,
            flusher_for=lambda i, validators: QueueFlusher(
                NullVerifier(), queue
            ),
        )
    sim = Simulation(
        n=n,
        target_height=target,
        seed=scen_seed,
        timeout=1.0,
        # The reference harness paces deliveries at 1 ms
        # (replica_test.go:291); partitions need the pacing to engage.
        delivery_cost=1e-3,
        chaos=plan,
        observe=True,
        **extra,
    )
    return plan, sim


def _build_churn(scen_seed: int, n: int, target: int):
    """An epoch-churn scenario: short epochs with a ~25% membership
    swap + one key rotation per boundary, under a churn-shaped fault
    plan (partition spanning a boundary, crash-restore inside it,
    laggards rejoining under rotated keys). Certificates are on so the
    epoch-proof chain is minted and the monitor can verify it
    end-to-end; the target guarantees >= 3 boundary crossings."""
    from hyperdrive_tpu.epochs import EpochConfig

    plan = FaultPlan.churn(scen_seed, n)
    epoch_length = 2
    committee = max(3, (3 * n) // 4)
    target = max(target, 3 * epoch_length + 1)
    sim = Simulation(
        n=n,
        target_height=target,
        seed=scen_seed,
        timeout=1.0,
        delivery_cost=1e-3,
        chaos=plan,
        observe=True,
        certificates=True,
        epochs=EpochConfig(
            epoch_length=epoch_length,
            committee_size=committee,
            rekey_per_epoch=1,
        ),
    )
    return plan, sim


def _build_exec_churn(scen_seed: int, n: int, target: int):
    """An execution-churn scenario: stake-churn transactions mutate the
    replicated ledger while short epochs elect committees FROM that
    stake, under the churn fault plan (partition spanning a boundary,
    crash-restore inside it, laggards rejoining under rotated keys).
    The monitor's exec invariants are armed — state-root agreement at
    every committed height plus commit/ledger binding — and the leg
    adds a record-replay determinism self-check that must reproduce the
    identical root-extended chain from the dump alone. Host executors
    keep the soak jax-free; kernel parity has its own CI smoke."""
    from hyperdrive_tpu.epochs import EpochConfig
    from hyperdrive_tpu.exec import ExecutionConfig

    plan = FaultPlan.churn(scen_seed, n)
    epoch_length = 2
    committee = max(3, (3 * n) // 4)
    target = max(target, 3 * epoch_length + 1)
    sim = Simulation(
        n=n,
        target_height=target,
        seed=scen_seed,
        timeout=1.0,
        delivery_cost=1e-3,
        chaos=plan,
        observe=True,
        certificates=True,
        epochs=EpochConfig(
            epoch_length=epoch_length,
            committee_size=committee,
            rekey_per_epoch=1,
        ),
        execution=ExecutionConfig(
            accounts=max(2 * n, 16),
            txs_per_block=64,
            stake_every=2,
            seed=scen_seed,
        ),
    )
    return plan, sim


def _build_exec_pipeline(scen_seed: int, n: int, target: int,
                         speculate: bool):
    """A speculative-execution-pipeline scenario (PR 16): signed
    transaction blocks with forged-but-well-formed signatures (every
    K-th sig byte-flipped, still 64 bytes — so the well-formedness
    guess ADMITS the lane and verification then rejects it) applied
    speculatively through a shared devsched queue, under the churn
    fault plan's partition + crash-restore — faults land inside open
    speculation windows. Every resolved window mismatches, so the
    rollback path runs constantly; the monitor's no-rolled-back-root-
    committed invariant is armed with real discarded roots to audit.
    ``speculate=False`` builds the sequential settle-then-execute twin
    (same config, no queue) the digest cross-check holds the pipelined
    chain to. Host executors + the jax-free QueueFlusher keep the soak
    accelerator-free; the signature checks run on the host verifier."""
    from hyperdrive_tpu.exec import ExecutionConfig

    plan = FaultPlan.churn(scen_seed, n)
    extra = {}
    if speculate:
        from hyperdrive_tpu.devsched import DeviceWorkQueue, QueueFlusher
        from hyperdrive_tpu.verifier import NullVerifier

        queue = DeviceWorkQueue(max_depth=8)
        extra = dict(
            devsched=queue,
            flusher_for=lambda i, validators: QueueFlusher(
                NullVerifier(), queue
            ),
            exec_speculate=True,
        )
    sim = Simulation(
        n=n,
        target_height=target,
        seed=scen_seed,
        timeout=1.0,
        delivery_cost=1e-3,
        chaos=plan,
        observe=True,
        execution=ExecutionConfig(
            accounts=max(2 * n, 16),
            txs_per_block=12,
            stake_every=4,
            seed=scen_seed,
            sign_txs=True,
            bad_sig_every=5,
        ),
        **extra,
    )
    return plan, sim


def _build_overlay(scen_seed: int, n: int, target: int):
    """An aggregation-overlay scenario: the full tree-slicing fault
    family (partition cutting a level block, Byzantine contributors
    withholding/garbling frames, an interior-node crash-restore) on top
    of the overlay dissemination path. The monitor's overlay invariants
    are armed: commit safety, no honest peer still demoted at run end,
    and never-starve (exhausted windows must have engaged the ranked
    fallback)."""
    from hyperdrive_tpu.overlay import OverlayConfig

    plan, faults = FaultPlan.overlay(scen_seed, n)
    sim = Simulation(
        n=n,
        target_height=target,
        seed=scen_seed,
        timeout=1.0,
        delivery_cost=1e-3,
        chaos=plan,
        observe=True,
        overlay=OverlayConfig(faults=faults),
    )
    return plan, faults, sim


def _bls_overlay_probe(scen_seed: int, args) -> int:
    """The overlay leg of the BLS spot-check: the tree-slicing fault
    family with real BLS partial aggregates riding every frame (host
    fold — the soak stays jax-free), held to the armed monitor plus a
    digest-neutrality cross-check, then a DETERMINISTIC merge-level
    probe — replay a real frame with its aggregate corrupted and
    require the runtime to charge the contributor and refuse the merge
    before any coverage (or batch verify) happens. Returns the count of
    organically-rejected Byzantine aggregates."""
    from hyperdrive_tpu.overlay import OverlayConfig, OverlayFrame

    on = args.n if args.n else 8
    plan, faults = FaultPlan.overlay(scen_seed, on)
    fsim = Simulation(
        n=on, target_height=args.target, seed=scen_seed, timeout=1.0,
        delivery_cost=1e-3, chaos=plan, observe=True,
        overlay=OverlayConfig(faults=faults, bls_partials=True),
    )
    fmon = InvariantMonitor(fsim)
    fresult = fsim.run(max_steps=args.max_steps)
    fmon.check_final(fresult)
    bsim = Simulation(
        n=on, target_height=args.target, seed=scen_seed, timeout=1.0,
        delivery_cost=1e-3,
    )
    bresult = bsim.run(max_steps=args.max_steps)
    csim = Simulation(
        n=on, target_height=args.target, seed=scen_seed, timeout=1.0,
        delivery_cost=1e-3, overlay=OverlayConfig(bls_partials=True),
    )
    cresult = csim.run(max_steps=args.max_steps)
    if (cresult.commit_digest(up_to=args.target)
            != bresult.commit_digest(up_to=args.target)):
        raise InvariantViolation(
            "bls-overlay",
            "BLS-partial overlay chain diverges from all-to-all baseline",
        )
    rt = fsim._overlay
    src, slot, st, to = 0, None, None, None
    for sl, s in rt._slots.items():
        if not s.bls:
            continue
        r = next(
            (i for i in range(on)
             if (s.all_mask & ~s.cov[i]) and i != src), None,
        )
        if r is not None:
            slot, st, to = sl, s, r
            break
        if slot is None:
            # Fallback target if every slot fully propagated: the
            # reject/charge half of the probe still runs; only the
            # coverage-unchanged half becomes vacuous.
            slot, st, to = sl, s, 1
    if slot is None:
        raise InvariantViolation(
            "bls-overlay", "faulted run produced no BLS partials"
        )
    mask = st.all_mask
    good = rt._bls_masked_sum(st, mask, 0, 0)
    bad = bytes([good[0] ^ 0x01]) + good[1:]
    rejects = rt.bls_partial_rejects
    invalid = rt.scores.charges["invalid"]
    cov = st.cov[to]
    rt.on_frame(to, OverlayFrame(src, slot, 0, mask, agg=bad))
    if rt.bls_partial_rejects != rejects + 1:
        raise InvariantViolation(
            "bls-overlay", "corrupted aggregate survived the merge check"
        )
    if rt.scores.charges["invalid"] != invalid + 1:
        raise InvariantViolation(
            "bls-overlay", "merge-level reject did not charge the sender"
        )
    if st.cov[to] != cov:
        raise InvariantViolation(
            "bls-overlay", "coverage merged despite a corrupted aggregate"
        )
    if mask & ~cov:
        rt.on_frame(to, OverlayFrame(src, slot, 0, mask, agg=good))
        if st.cov[to] == cov:
            raise InvariantViolation(
                "bls-overlay", "honest aggregate failed to merge after probe"
            )
    return rejects


def _tenant_service_probe(scen_seed: int) -> dict:
    """The multi-tenant serving fault family (jax-free): three tenants
    share one continuously-batching ShardVerifyService under a
    DeficitRoundRobin drain policy while (a) one tenant firehoses the
    queue with wide windows and deep inflight, and (b) another drops
    off the drive loop for a seeded partition window. Invariants:

    - the WITNESS tenant (neither overloaded nor partitioned) and the
      healed partitioned tenant commit chains byte-identical to clean
      solo runs on dedicated services — a neighbor's overload or outage
      must never move a third tenant's digests;
    - the fairness starvation bound holds
      (:meth:`InvariantMonitor.check_tenant_fairness`) AND was actually
      exercised — a leg whose firehose never forced a deferral proves
      nothing.
    """
    from hyperdrive_tpu.devsched import DeficitRoundRobin
    from hyperdrive_tpu.parallel.service import (
        ShardVerifyService,
        TenantShard,
    )
    from hyperdrive_tpu.verifier import NullVerifier

    rng = random.Random(scen_seed * _SEED_STRIDE + 7)
    heights = 12
    policy = DeficitRoundRobin(
        capacity_rows=16, quantum_rows=4, starve_after=3
    )
    svc = ShardVerifyService(NullVerifier(), max_depth=0, policy=policy)
    fire = TenantShard(
        "firehose", n_validators=16, target_height=heights, sign=False
    ).attach_local(svc)
    part = TenantShard(
        "partitioned", n_validators=4, target_height=heights, sign=False
    ).attach_local(svc)
    wit = TenantShard(
        "witness", n_validators=4, target_height=heights, sign=False
    ).attach_local(svc)
    p0 = rng.randrange(1, 5)
    p1 = p0 + rng.randrange(3, 9)
    step = 0
    while not (fire.done and part.done and wit.done):
        fire.pump(max_inflight=8)
        if not (p0 <= step < p1):
            part.pump(max_inflight=1)
        wit.pump(max_inflight=1)
        svc.drain()
        step += 1
        if step > 10_000:
            raise InvariantViolation(
                "tenant-liveness",
                f"tenants stalled: firehose={len(fire.commits)} "
                f"partitioned={len(part.commits)} "
                f"witness={len(wit.commits)} of {heights}",
            )
    InvariantMonitor.check_tenant_fairness(policy)
    if not policy.deferred_total:
        raise InvariantViolation(
            "tenant-fairness",
            "firehose never forced a deferral — the leg did not "
            "exercise the drain policy",
        )
    for shard, nv in ((part, 4), (wit, 4)):
        solo_svc = ShardVerifyService(NullVerifier(), max_depth=0)
        solo = TenantShard(
            shard.name, n_validators=nv, target_height=heights,
            sign=False,
        ).attach_local(solo_svc)
        while not solo.done:
            solo.pump(max_inflight=1)
            solo_svc.drain()
        if shard.commit_digest() != solo.commit_digest():
            raise InvariantViolation(
                "tenant-digest",
                f"tenant {shard.name} diverged from its clean solo run "
                f"under a neighbor's overload/partition",
            )
        if shard.rejected:
            raise InvariantViolation(
                "tenant-digest",
                f"tenant {shard.name} had {shard.rejected} rejected "
                f"commits under a neighbor's faults",
            )
    return {
        "deferred": policy.deferred_total,
        "forced": policy.forced_total,
        "max_deferrals": policy.max_deferrals,
        "launches": svc.queue.launches,
        "partition": (p0, p1),
    }


def _proof_probe(scen_seed: int) -> dict:
    """The proof-serving fault family (jax-free): a seeded
    HostLedgerExecutor advances a short chain, then

    - a handful of inclusion proofs must survive the wire codec
      byte-for-byte AND verify against the chained root a light client
      already trusts (an honest proof that fails to verify is a
      liveness violation for every reader);
    - all four adversarial mutations — stale previous root, forged
      sibling, truncated path, wrong leaf value — must FAIL
      verification. A forgery that verifies is the one violation the
      trustless-read doctrine can never absorb.
    """
    import dataclasses

    from hyperdrive_tpu.exec import (
        BlockSource,
        ExecutionConfig,
        HostLedgerExecutor,
    )
    from hyperdrive_tpu.parallel.service import (
        STATUS_COMMITTED,
        decode_proof,
        encode_proof,
    )

    rng = random.Random(scen_seed * _SEED_STRIDE + 13)
    accounts = rng.choice((16, 32, 64))
    target = rng.randrange(3, 7)
    cfg = ExecutionConfig(
        accounts=accounts, txs_per_block=16, stake_every=3,
        stake_accounts=accounts // 4, seed=scen_seed % 10_000,
        amount_cap=16, initial_balance=500,
    )
    ex = HostLedgerExecutor(cfg, source=BlockSource(cfg))
    ex.advance_to(target)
    basis = ex.proof_basis()
    root = ex.roots[target]
    served = 0
    for account in sorted(rng.sample(range(accounts), 5)):
        proof = basis.prove(account)
        rid, status, wired = decode_proof(
            encode_proof(served + 1, STATUS_COMMITTED, proof)
        )
        if wired != proof or rid != served + 1:
            raise InvariantViolation(
                "proof-codec",
                f"proof frame for account {account} did not roundtrip "
                f"the wire codec losslessly",
            )
        if not ex.verify_inclusion(
            root, account, wired.balance, wired.stake, wired
        ):
            raise InvariantViolation(
                "proof-serve",
                f"honest proof for account {account} failed "
                f"verification at height {target}",
            )
        served += 1
    victim = basis.prove(rng.randrange(accounts))
    forgeries = {
        "stale-root": dataclasses.replace(
            victim, prev_root=b"\x01" * 32
        ),
        "forged-sibling": dataclasses.replace(
            victim, siblings=((1, 2, 3, 4),) + victim.siblings[1:]
        ),
        "truncated-path": dataclasses.replace(
            victim, siblings=victim.siblings[:-1]
        ),
        "wrong-leaf": dataclasses.replace(
            victim, balance=victim.balance + 1
        ),
    }
    for name, bad in forgeries.items():
        if ex.verify_inclusion(
            root, bad.account, bad.balance, bad.stake, bad
        ):
            raise InvariantViolation(
                "proof-forgery",
                f"{name} forgery VERIFIED at height {target} "
                f"(account {bad.account}, {accounts} accounts)",
            )
    return {
        "height": target,
        "accounts": accounts,
        "served": served,
        "depth": len(victim.siblings),
        "forgeries": len(forgeries),
    }


def _wire_fuzz_probe(scen_seed: int) -> dict:
    """The Byzantine-bytes fault family (ISSUE 18, jax-free): a real
    :class:`~hyperdrive_tpu.transport.TcpNode` behind a
    :class:`~hyperdrive_tpu.chaos.ChaosProxy` with ``fuzz_every`` armed,
    fed a burst of signed prevote frames where every 3rd payload arrives
    mutated (seeded truncate / extend / bitflip / tag-smash, length
    header recomputed so the corruption lands in the DECODE path).
    Invariants:

    - every CLEAN frame still delivers — a garbage frame must never
      take honest traffic down with it (FIFO link, so clean deliveries
      can only be missing if a mutant killed the read loop);
    - a final clean frame sent after the burst delivers on the SAME
      connection — the read loop survived every mutant without
      desyncing or crashing its thread;
    - every frame the target counted as malformed was one the proxy
      fuzzed (honest frames never misparse), and the fuzzer never broke
      framing (``oversize_frames`` stays zero: the corruption is the
      payload's, not the length prefix's).

    Runs with whatever ``HD_SANITIZE`` the environment sets — CI arms
    it, so mutants also cross the HDS005 budget accounting.
    """
    import socket
    import time

    from hyperdrive_tpu.chaos.proxy import ChaosProxy
    from hyperdrive_tpu.crypto.keys import KeyRing
    from hyperdrive_tpu.messages import Prevote
    from hyperdrive_tpu.transport import TcpNode, encode_frame

    received: list = []

    class _Sink:
        def propose(self, m, stop=None):
            received.append(m)

        prevote = precommit = timeout = propose

    def _await(pred, deadline_s=10.0):
        deadline = time.monotonic() + deadline_s
        while time.monotonic() < deadline:
            if pred():
                return True
            time.sleep(0.01)
        return pred()

    frames, fuzz_every = 60, 3
    ring = KeyRing.deterministic(1, namespace=b"wirefuzz")

    def _frame(height: int) -> bytes:
        return encode_frame(ring[0].sign_message(
            Prevote(height=height, round=0, value=b"\x07" * 32,
                    sender=ring[0].public)
        ))

    node = TcpNode()
    node.add_replica(_Sink())
    node.start()
    proxy = ChaosProxy(
        "127.0.0.1", node.port, seed=scen_seed, fuzz_every=fuzz_every
    ).start()
    try:
        with socket.create_connection(("127.0.0.1", proxy.port)) as s:
            for h in range(1, frames + 1):
                s.sendall(_frame(h))
            if not _await(lambda: proxy.forwarded >= frames):
                raise InvariantViolation(
                    "wire-fuzz",
                    f"proxy forwarded {proxy.forwarded}/{frames} frames",
                )
            if proxy.fuzzed != frames // fuzz_every:
                raise InvariantViolation(
                    "wire-fuzz",
                    f"fuzz cadence missed: {proxy.fuzzed} mutations for "
                    f"{frames} frames at every {fuzz_every}",
                )
            clean = frames - proxy.fuzzed
            if not _await(lambda: len(received) >= clean):
                raise InvariantViolation(
                    "wire-fuzz",
                    f"only {len(received)} of {clean} clean frames "
                    "delivered — a garbage frame took honest traffic "
                    "down with it",
                )
            # frames+1 is not a multiple of fuzz_every, so the survivor
            # frame crosses the proxy unmutated.
            before = len(received)
            s.sendall(_frame(frames + 1))
            if not _await(lambda: len(received) > before):
                raise InvariantViolation(
                    "wire-fuzz",
                    "read loop dead after the fuzz burst: a clean "
                    "frame no longer delivers",
                )
        if node.oversize_frames:
            raise InvariantViolation(
                "wire-fuzz",
                f"fuzzer broke framing: target counted "
                f"{node.oversize_frames} oversize frames",
            )
        if node.malformed_frames > proxy.fuzzed:
            raise InvariantViolation(
                "wire-fuzz",
                f"{node.malformed_frames} malformed frames exceed the "
                f"{proxy.fuzzed} mutations — an honest frame misparsed",
            )
        return {
            "frames": frames + 1,
            "fuzzed": proxy.fuzzed,
            "malformed": node.malformed_frames,
            "delivered": len(received),
        }
    finally:
        proxy.stop()
        node.stop()


def _metrics_probe(scen_seed: int) -> dict:
    """The live-metrics fault family (ISSUE 19, jax-free): a real
    :class:`~hyperdrive_tpu.parallel.service.ServicePort` serving
    remote tenants over real sockets, scraped over TAG_METRICS.
    Invariants:

    - a scrape after real traffic answers STATUS_COMMITTED with valid
      Prometheus exposition text (every non-comment line parses as
      ``name{labels} value``) that already carries the commit-latency
      histogram the tenant's own submits fed;
    - shed ORDERING (the metrics-plane doctrine): with the admission
      floor forced to SHED_LOW_PRIORITY, the scrape answers
      STATUS_SHED while a second tenant's consensus submits — run
      under the SAME floor — all still commit. The observability
      plane sheds strictly before any consensus class, and no submit
      row is shed while the scrape is;
    - pressure released, the retried scrape serves again (scrapes are
      flow-controlled reads, never lost), and the SLO burn-rate
      checks (obs/slo.py) evaluate over the run's registry snapshot
      and journal: finality_p99 and shed_rate must both be MEASURED
      (a missing signal is not evidence of health) and finality must
      hold its ceiling on an unloaded local run.
    """
    import re
    import threading
    import time

    from hyperdrive_tpu.load.backpressure import SHED_LOW_PRIORITY
    from hyperdrive_tpu.obs.metrics import Registry
    from hyperdrive_tpu.obs.recorder import Recorder
    from hyperdrive_tpu.obs.slo import evaluate_slos
    from hyperdrive_tpu.parallel.service import (
        RemoteServiceClient,
        STATUS_COMMITTED,
        STATUS_SHED,
        ShardVerifyService,
        TenantShard,
    )
    from hyperdrive_tpu.verifier import NullVerifier

    rng = random.Random(scen_seed * _SEED_STRIDE + 29)
    target = rng.randrange(3, 6)
    rec = Recorder(threadsafe=True)
    obs = rec.scoped(-1)
    svc = ShardVerifyService(
        NullVerifier(), max_depth=0, registry=Registry(), obs=obs
    )
    port = svc.remote_port(obs=obs)

    def _run_tenant(name: str, heights: int):
        client = RemoteServiceClient(*port.address)
        shard = TenantShard(name, target_height=heights, sign=False)
        shard.attach_remote(client)
        t = threading.Thread(target=shard.run_remote, daemon=True)
        t.start()
        deadline = time.monotonic() + 10.0
        while not shard.done and time.monotonic() < deadline:
            port.pump()
            svc.drain()
            time.sleep(0.001)
        t.join(timeout=5.0)
        if not shard.done or shard.rejected:
            raise InvariantViolation(
                "metrics-liveness",
                f"tenant {name} stalled (done={shard.done} "
                f"rejected={shard.rejected}) — consensus traffic did "
                f"not survive the probe's load profile",
            )
        return client

    def _scrape(client):
        fut = client.metrics()
        deadline = time.monotonic() + 5.0
        while not fut.done() and time.monotonic() < deadline:
            port.pump()
            svc.drain()
            time.sleep(0.001)
        return fut.metrics_result(timeout=1.0)

    prom_line = re.compile(
        r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? \S+$"
    )
    clients = []
    try:
        clients.append(_run_tenant(f"mx-{scen_seed % 977}", target))
        status, text = _scrape(clients[0])
        if status != STATUS_COMMITTED or not text:
            raise InvariantViolation(
                "metrics-serve",
                f"unloaded scrape refused (status={status}) — the "
                f"metrics plane failed with zero pressure on the gate",
            )
        for line in text.splitlines():
            if line and not line.startswith("#") and \
                    not prom_line.match(line):
                raise InvariantViolation(
                    "metrics-format",
                    f"scrape line is not Prometheus exposition "
                    f"text: {line!r}",
                )
        if "hd_tenant_commit_latency" not in text:
            raise InvariantViolation(
                "metrics-serve",
                "scrape is missing the commit-latency histogram the "
                "tenant's own traffic fed",
            )
        port.controller.floor = SHED_LOW_PRIORITY
        port.controller.poll()
        status2, text2 = _scrape(clients[0])
        if status2 != STATUS_SHED or text2 is not None:
            raise InvariantViolation(
                "metrics-shed-order",
                f"scrape under SHED_LOW_PRIORITY answered "
                f"status={status2} — metrics must be the FIRST class "
                f"shed, before any consensus frame queues behind them",
            )
        # The ordering half: under the SAME floor that just shed the
        # scrape, a fresh tenant's consensus submits must all commit.
        clients.append(_run_tenant(f"my-{scen_seed % 977}", 2))
        if port.remote_sheds:
            raise InvariantViolation(
                "metrics-shed-order",
                f"{port.remote_sheds} consensus submits shed at the "
                f"floor that sheds metrics — the shed order inverted",
            )
        port.controller.floor = 0
        for _ in range(port.controller.hysteresis):
            port.controller.poll()
        status3, text3 = _scrape(clients[0])
        if status3 != STATUS_COMMITTED or not text3:
            raise InvariantViolation(
                "metrics-serve",
                f"scrape after pressure release refused "
                f"(status={status3}) — sheds must be retryable, "
                f"never a lost read",
            )
        slos = evaluate_slos(
            snapshot=svc.registry.snapshot(),
            events=rec.snapshot(), obs=obs,
        )
        by_name = {r.name: r for r in slos}
        for needed in ("finality_p99", "shed_rate"):
            if needed not in by_name:
                raise InvariantViolation(
                    "metrics-slo",
                    f"{needed} was not measured — its input signal "
                    f"went missing from a run that produced it",
                )
        if not by_name["finality_p99"].ok:
            raise InvariantViolation(
                "metrics-slo",
                f"finality_p99 burned "
                f"{by_name['finality_p99'].burn:.2f}x its budget on "
                f"an unloaded local run",
            )
        return {
            "target": target,
            "serves": port.metrics_serves,
            "sheds": port.metrics_sheds,
            "bytes": len(text3),
            "slos": len(slos),
            "breaches": sum(1 for r in slos if not r.ok),
        }
    finally:
        for client in clients:
            client.close()
        port.close()
        svc.close()


def _dump_failure(out: str, scen_seed: int, sim, err) -> str:
    os.makedirs(out, exist_ok=True)
    base = os.path.join(out, f"chaos_seed_{scen_seed}")
    sim.record.dump(base + ".bin")
    sim.obs.save(base + ".journal.json")
    sim._ckpt_store.dump(base + ".ckpt")
    with open(base + ".txt", "w") as fh:
        fh.write(f"seed={scen_seed}\nviolation={err}\n")
    return base


def soak(args) -> int:
    rng = random.Random(args.seed)
    failures = 0
    churn_dumped = False
    overlay_dumped = False
    for k in range(args.scenarios):
        scen_seed = args.seed + k * _SEED_STRIDE
        n = args.n if args.n else rng.choice([4, 7])
        plan, sim = _build(scen_seed, n, args.target)
        monitor = InvariantMonitor(sim)
        try:
            result = sim.run(max_steps=args.max_steps)
            monitor.check_final(result)
            if args.replay_every and k % args.replay_every == 0:
                replayed = Simulation.replay(sim.record)
                if replayed.commits != result.commits:
                    raise InvariantViolation(
                        "replay", "replayed commits diverge from live run"
                    )
            if args.certs_every and k % args.certs_every == 0:
                # Re-run the same plan with quorum certificates minted
                # at every commit: partitions, crashes, and heals must
                # not bend the chain (digest-identical to the baseline
                # run), every surviving certificate must match the
                # committed value it proves, and each must still pass
                # its O(1) re-verification.
                _, csim = _build(
                    scen_seed, n, args.target, certificates=True
                )
                cmon = InvariantMonitor(csim)
                cresult = csim.run(max_steps=args.max_steps)
                cmon.check_final(cresult)
                if cresult.commit_digest() != result.commit_digest():
                    raise InvariantViolation(
                        "certificates",
                        "certificate-carrying chain diverges from baseline",
                    )
                for i, certifier in enumerate(csim.certifiers):
                    for ch, cert in certifier.certs.items():
                        v = cresult.commits[i].get(ch)
                        if (
                            v is not None
                            and cert.value_digest
                            != hashlib.sha256(v).digest()
                        ):
                            raise InvariantViolation(
                                "certificates",
                                f"certificate digest mismatch at "
                                f"height {ch}",
                            )
                        if not certifier.verify(cert):
                            raise InvariantViolation(
                                "certificates",
                                f"certificate failed O(1) re-verify at "
                                f"height {ch}",
                            )
            if args.bls_certs_every and k % args.bls_certs_every == 0:
                # BLS-bound certificates (ISSUE 13): re-run the same
                # plan with aggregate-signature minting on. The chain
                # must stay digest-identical to the baseline (the
                # aggregate changes the certificate, never the
                # agreement), every surviving certificate must carry
                # the 48-byte aggregate and re-verify its binding, and
                # one sampled certificate per run must pass the full
                # LIGHT-CLIENT pairing check — committee pubkeys only,
                # zero transcript trust. A second, faulted overlay run
                # rides along with real BLS partials on every frame: a
                # deterministic merge-level probe corrupts a real
                # frame's aggregate and the runtime must charge the
                # contributor and refuse the merge BEFORE any batch
                # verify.
                from hyperdrive_tpu.certificates import (
                    verify_bls_certificate,
                )

                _, bcsim = _build(
                    scen_seed, n, args.target, bls_certificates=True
                )
                bcmon = InvariantMonitor(bcsim)
                bcresult = bcsim.run(max_steps=args.max_steps)
                bcmon.check_final(bcresult)
                if bcresult.commit_digest() != result.commit_digest():
                    raise InvariantViolation(
                        "bls-certs",
                        "BLS-certificate chain diverges from baseline",
                    )
                sampled = 0
                for certifier in bcsim.certifiers:
                    pks = certifier.bls_pubkeys()
                    for ch, cert in certifier.certs.items():
                        if len(cert.agg_sig) != 48:
                            raise InvariantViolation(
                                "bls-certs",
                                f"certificate at height {ch} carries no "
                                f"aggregate signature",
                            )
                        if not certifier.verify(cert):
                            raise InvariantViolation(
                                "bls-certs",
                                f"BLS certificate failed binding "
                                f"re-verify at height {ch}",
                            )
                    if certifier.certs and not sampled:
                        # One pairing per run: the light-client path is
                        # O(seconds) on the host reference, so the soak
                        # samples the newest certificate rather than
                        # paying n * heights pairings per scenario.
                        ch = max(certifier.certs)
                        if not verify_bls_certificate(
                            certifier.certs[ch], pks,
                            quorum=2 * ((n - 1) // 3) + 1,
                        ):
                            raise InvariantViolation(
                                "bls-certs",
                                f"light-client verify rejected the "
                                f"certificate at height {ch}",
                            )
                        sampled += 1
                if not sampled:
                    raise InvariantViolation(
                        "bls-certs", "run minted no BLS certificates"
                    )
                rejects = _bls_overlay_probe(scen_seed, args)
                print(
                    f"ok bls seed={scen_seed} n={n} "
                    f"certs=48B-agg light-client=ok "
                    f"overlay-rejects={rejects} merge-probe=ok"
                )
            if args.pipelined_every and k % args.pipelined_every == 0:
                # Re-run the same plan with settles pipelined through
                # the shared device-work queue: the monitor must stay
                # clean and the agreed chain byte-identical.
                _, psim = _build(scen_seed, n, args.target, pipelined=True)
                pmon = InvariantMonitor(psim)
                presult = psim.run(max_steps=args.max_steps)
                pmon.check_final(presult)
                if presult.commit_digest() != result.commit_digest():
                    raise InvariantViolation(
                        "pipelined",
                        "pipelined chain diverges from sequential",
                    )
            if args.overload_every and k % args.overload_every == 0:
                # The overload fault family (ISSUE 11): re-run the SAME
                # plan with an open-loop duplicate storm + the admission
                # spine pinned in the behavior-neutral band. The loaded
                # run must commit the identical chain — injected
                # duplicates consume no steps/clock/rng, and the gate
                # sheds only classes the Process ignores anyway — and
                # must actually have shed something (the storm is not
                # allowed to be a no-op) while never shedding outside
                # the admission vocabulary.
                _, profile = FaultPlan.overload(scen_seed, n)
                _, osim = _build(scen_seed, n, args.target, load=profile)
                omon = InvariantMonitor(osim)
                oresult = osim.run(max_steps=args.max_steps)
                omon.check_final(oresult)
                if oresult.commit_digest() != result.commit_digest():
                    raise InvariantViolation(
                        "overload",
                        "overloaded chain diverges from unloaded run",
                    )
                osnap = osim.overload_snapshot()
                # Guaranteed-shed prey only: vote duplicates at
                # un-advanced heights (proposal dups and behind-the-
                # commit-edge votes are admitted/filtered by doctrine).
                if osnap["injected_sheddable"] and not osnap["shed"]:
                    raise InvariantViolation(
                        "overload",
                        "sheddable storm injected but admission shed nothing",
                    )
                bad = set(osnap["shed"]) - {"duplicate", "stale_height"}
                if bad:
                    raise InvariantViolation(
                        "overload",
                        f"behavior-neutral run shed classes {sorted(bad)}",
                    )
                shed_str = ",".join(
                    f"{c}:{n_}" for c, n_ in sorted(osnap["shed"].items())
                ) or "-"
                print(
                    f"ok overload seed={scen_seed} n={n} "
                    f"injected={osnap['injected']} shed={shed_str}"
                )
            if args.tenants_every and k % args.tenants_every == 0:
                # The multi-tenant serving fault family (ISSUE 14):
                # overload on one tenant + a partition on another must
                # not move a third tenant's digests, and the DRR
                # starvation bound must hold while being exercised.
                tstats = _tenant_service_probe(scen_seed)
                print(
                    f"ok tenants seed={scen_seed} "
                    f"deferred={tstats['deferred']} "
                    f"forced={tstats['forced']} "
                    f"max_deferrals={tstats['max_deferrals']} "
                    f"launches={tstats['launches']} "
                    f"partition={tstats['partition'][0]}.."
                    f"{tstats['partition'][1]}"
                )
            if args.proofs_every and k % args.proofs_every == 0:
                # The proof-serving fault family (ISSUE 17): honest
                # proofs must roundtrip the wire codec and verify
                # against the chained root; the four forged-proof
                # variants must all fail verification.
                pstats = _proof_probe(scen_seed)
                print(
                    f"ok proofs seed={scen_seed} "
                    f"height={pstats['height']} "
                    f"accounts={pstats['accounts']} "
                    f"served={pstats['served']} "
                    f"depth={pstats['depth']} "
                    f"forgeries-rejected={pstats['forgeries']}"
                )
        except (InvariantViolation, AssertionError) as err:
            failures += 1
            base = _dump_failure(args.out, scen_seed, sim, err)
            print(
                f"FAIL seed={scen_seed} n={n} {err}\n"
                f"  dumped {base}.bin (+ journal, checkpoints)\n"
                f"  reproduce: python -m hyperdrive_tpu.chaos replay "
                f"{base}.bin",
                file=sys.stderr,
            )
            if not args.keep_going:
                return 1
            continue
        print(
            f"ok seed={scen_seed} n={n} heights<= {max(result.heights)} "
            f"steps={result.steps} crashes={len(monitor.crashes)} "
            f"heals={len(monitor.heals)}"
        )
        if args.churn_every and k % args.churn_every == 0:
            # Every Kth scenario additionally runs the epoch-churn
            # family: dynamic validator sets under the same seed's
            # hostility, with the monitor's epoch invariants armed
            # (no fork across switches, retired keys out of every
            # whitelist, union proof chain verifying end-to-end) and a
            # record-replay determinism self-check.
            cn = args.n if args.n else 8
            zplan, zsim = _build_churn(scen_seed, cn, args.target)
            zmon = InvariantMonitor(zsim)
            try:
                zresult = zsim.run(max_steps=args.max_steps)
                zmon.check_final(zresult)
                if not zmon.epoch_switches:
                    raise InvariantViolation(
                        "epoch-liveness",
                        "churn run never crossed an epoch boundary",
                    )
                zreplayed = Simulation.replay(zsim.record)
                if zreplayed.commits != zresult.commits:
                    raise InvariantViolation(
                        "replay",
                        "churn replay diverges from live run",
                    )
            except (InvariantViolation, AssertionError) as err:
                failures += 1
                base = _dump_failure(args.out, scen_seed, zsim, err)
                print(
                    f"FAIL churn seed={scen_seed} n={cn} {err}\n"
                    f"  dumped {base}.bin (+ journal, checkpoints)\n"
                    f"  reproduce: python -m hyperdrive_tpu.chaos "
                    f"replay {base}.bin",
                    file=sys.stderr,
                )
                if not args.keep_going:
                    return 1
                continue
            print(
                f"ok churn seed={scen_seed} n={cn} "
                f"epoch={zsim.epoch} switches={len(zmon.epoch_switches)} "
                f"stale_votes={sum(r.stale_votes for r in zsim.replicas)}"
            )
            if args.dump_ok and not churn_dumped:
                os.makedirs(args.dump_ok, exist_ok=True)
                okbase = os.path.join(
                    args.dump_ok, f"churn_seed_{scen_seed}.bin"
                )
                zsim.record.dump(okbase)
                churn_dumped = True
                print(f"  dumped passing churn record: {okbase}")
        if args.overlay_every and k % args.overlay_every == 0:
            # Every Kth scenario additionally runs the aggregation-
            # overlay fault family (ISSUE 12): tree-slicing partition +
            # Byzantine contributors + interior crash on the overlay
            # dissemination path, with the monitor's overlay invariants
            # armed and a record-replay determinism self-check (overlay
            # records hold plain per-message deliveries, so they replay
            # with no overlay wiring at all). A second, fault-free pair
            # checks DIGEST NEUTRALITY: the same seed through a clean
            # overlay must commit the byte-identical chain the
            # all-to-all baseline commits — aggregation changes the
            # transport, never the agreed values.
            on = args.n if args.n else 8
            yplan, yfaults, ysim = _build_overlay(
                scen_seed, on, args.target
            )
            ymon = InvariantMonitor(ysim)
            try:
                yresult = ysim.run(max_steps=args.max_steps)
                ymon.check_final(yresult)
                yreplayed = Simulation.replay(ysim.record)
                if yreplayed.commits != yresult.commits:
                    raise InvariantViolation(
                        "replay",
                        "overlay replay diverges from live run",
                    )
                from hyperdrive_tpu.overlay import OverlayConfig

                bsim = Simulation(
                    n=on, target_height=args.target, seed=scen_seed,
                    timeout=1.0, delivery_cost=1e-3,
                )
                bresult = bsim.run(max_steps=args.max_steps)
                # Clean overlay, no faults: Byzantine withholding can
                # legitimately push a height into an extra round (the
                # fallback costs virtual time), so chain equality is
                # only an invariant of the aggregation mechanism
                # itself, not of adversarial timing. The faulted leg
                # above is held to the monitor's fork/digest checks.
                vsim = Simulation(
                    n=on, target_height=args.target, seed=scen_seed,
                    timeout=1.0, delivery_cost=1e-3,
                    overlay=OverlayConfig(),
                )
                vresult = vsim.run(max_steps=args.max_steps)
                if (vresult.commit_digest(up_to=args.target)
                        != bresult.commit_digest(up_to=args.target)):
                    raise InvariantViolation(
                        "overlay",
                        "overlay chain diverges from all-to-all baseline",
                    )
            except (InvariantViolation, AssertionError) as err:
                failures += 1
                base = _dump_failure(args.out, scen_seed, ysim, err)
                print(
                    f"FAIL overlay seed={scen_seed} n={on} {err}\n"
                    f"  dumped {base}.bin (+ journal, checkpoints)\n"
                    f"  reproduce: python -m hyperdrive_tpu.chaos "
                    f"replay {base}.bin",
                    file=sys.stderr,
                )
                if not args.keep_going:
                    return 1
                continue
            ysnap = ysim.overlay_snapshot()
            print(
                f"ok overlay seed={scen_seed} n={on} "
                f"frames={ysnap['frames']} "
                f"fallbacks={ysnap['fallback_engaged']} "
                f"demoted={ysnap['scores']['demoted']} "
                f"byz={ysnap['byzantine']} neutrality=ok"
            )
            if args.dump_ok and not overlay_dumped:
                os.makedirs(args.dump_ok, exist_ok=True)
                okbase = os.path.join(
                    args.dump_ok, f"overlay_seed_{scen_seed}.bin"
                )
                ysim.record.dump(okbase)
                overlay_dumped = True
                print(f"  dumped passing overlay record: {okbase}")
        if args.exec_every and k % args.exec_every == 0:
            # Every Kth scenario additionally runs the execution-churn
            # family (ISSUE 15): stake-churn transactions feeding
            # stake-driven elections across epoch boundaries under
            # partition + crash-restore, with the monitor's exec
            # invariants armed (state-root agreement network-wide,
            # commit/ledger binding) and a record-replay determinism
            # self-check on the root-extended chain.
            en = args.n if args.n else 8
            xplan, xsim = _build_exec_churn(scen_seed, en, args.target)
            xmon = InvariantMonitor(xsim)
            try:
                xresult = xsim.run(max_steps=args.max_steps)
                xmon.check_final(xresult)
                if not xmon.epoch_switches:
                    raise InvariantViolation(
                        "epoch-liveness",
                        "exec-churn run never crossed an epoch boundary",
                    )
                if not sum(e.applied_total for e in xsim.executors):
                    raise InvariantViolation(
                        "exec-root",
                        "exec-churn run applied no transactions — the "
                        "leg did not exercise the ledger",
                    )
                xreplayed = Simulation.replay(xsim.record)
                if xreplayed.commits != xresult.commits:
                    raise InvariantViolation(
                        "replay",
                        "exec-churn replay diverges from live run "
                        "(root-extended commits)",
                    )
            except (InvariantViolation, AssertionError) as err:
                failures += 1
                base = _dump_failure(args.out, scen_seed, xsim, err)
                print(
                    f"FAIL exec seed={scen_seed} n={en} {err}\n"
                    f"  dumped {base}.bin (+ journal, checkpoints)\n"
                    f"  reproduce: python -m hyperdrive_tpu.chaos "
                    f"replay {base}.bin",
                    file=sys.stderr,
                )
                if not args.keep_going:
                    return 1
                continue
            print(
                f"ok exec seed={scen_seed} n={en} epoch={xsim.epoch} "
                f"applied={sum(e.applied_total for e in xsim.executors)} "
                f"rejected={sum(e.rejected_total for e in xsim.executors)} "
                f"roots={len(xsim.executors[0].roots)} root-agreement=ok"
            )
        if args.fuzz_frames_every and k % args.fuzz_frames_every == 0:
            # Every Kth scenario additionally runs the Byzantine-bytes
            # probe (ISSUE 18): a real TcpNode behind a frame-fuzzing
            # proxy — every 3rd payload mutated, length header intact —
            # must deliver all clean traffic, survive every mutant
            # without a read-loop crash, and never misparse an honest
            # frame.
            try:
                wstats = _wire_fuzz_probe(scen_seed)
            except (InvariantViolation, AssertionError) as err:
                failures += 1
                print(
                    f"FAIL wire-fuzz seed={scen_seed} {err}",
                    file=sys.stderr,
                )
                if not args.keep_going:
                    return 1
                continue
            print(
                f"ok wire-fuzz seed={scen_seed} "
                f"frames={wstats['frames']} fuzzed={wstats['fuzzed']} "
                f"malformed={wstats['malformed']} "
                f"delivered={wstats['delivered']}"
            )
        if args.metrics_every and k % args.metrics_every == 0:
            # Every Kth scenario additionally runs the live-metrics
            # probe (ISSUE 19): a real ServicePort scraped over
            # TAG_METRICS mid-soak — the scrape must serve valid
            # Prometheus text carrying the tenant-fed commit-latency
            # histogram, shed FIRST under a forced admission floor
            # while consensus submits run under the same floor all
            # still commit, serve again once pressure releases, and
            # the SLO burn-rate checks must both measure and hold.
            try:
                mstats = _metrics_probe(scen_seed)
            except (InvariantViolation, AssertionError) as err:
                failures += 1
                print(
                    f"FAIL metrics seed={scen_seed} {err}",
                    file=sys.stderr,
                )
                if not args.keep_going:
                    return 1
                continue
            print(
                f"ok metrics seed={scen_seed} "
                f"heights={mstats['target']} "
                f"serves={mstats['serves']} sheds={mstats['sheds']} "
                f"bytes={mstats['bytes']} slos={mstats['slos']} "
                f"breaches={mstats['breaches']}"
            )
        if args.exec_pipeline_every and k % args.exec_pipeline_every == 0:
            # Every Kth scenario additionally runs the speculative-
            # pipeline family (PR 16): forged-but-well-formed tx
            # signatures force a rollback on every resolved window
            # while churn faults (partition + crash-restore) land
            # inside open windows. Armed invariants: no rolled-back
            # root in any committed value (monitor), digest equality
            # with the sequential settle-then-execute twin, and a
            # record-replay self-check on the root-extended chain.
            pn = args.n if args.n else 7
            _, ssim = _build_exec_pipeline(
                scen_seed, pn, args.target, speculate=True
            )
            smon = InvariantMonitor(ssim)
            try:
                sres = ssim.run(max_steps=args.max_steps)
                smon.check_final(sres)
                rolled = sum(
                    e.spec_rolled_back for e in ssim._exec_unique
                )
                discarded: set = set()
                for e in ssim._exec_unique:
                    discarded |= e.discarded_roots
                if not rolled or not discarded:
                    raise InvariantViolation(
                        "exec-rollback",
                        "speculative leg resolved no rollbacks — the "
                        "forged signatures did not exercise the unwind "
                        "path",
                    )
                _, qsim = _build_exec_pipeline(
                    scen_seed, pn, args.target, speculate=False
                )
                qmon = InvariantMonitor(qsim)
                qres = qsim.run(max_steps=args.max_steps)
                qmon.check_final(qres)
                if sres.commit_digest() != qres.commit_digest():
                    raise InvariantViolation(
                        "exec-rollback",
                        "speculative pipeline chain diverges from the "
                        "sequential settle-then-execute run",
                    )
                sreplayed = Simulation.replay(ssim.record)
                if sreplayed.commits != sres.commits:
                    raise InvariantViolation(
                        "replay",
                        "speculative-pipeline replay diverges from "
                        "live run (root-extended commits)",
                    )
            except (InvariantViolation, AssertionError) as err:
                failures += 1
                base = _dump_failure(args.out, scen_seed, ssim, err)
                print(
                    f"FAIL exec-pipeline seed={scen_seed} n={pn} {err}\n"
                    f"  dumped {base}.bin (+ journal, checkpoints)\n"
                    f"  reproduce: python -m hyperdrive_tpu.chaos "
                    f"replay {base}.bin",
                    file=sys.stderr,
                )
                if not args.keep_going:
                    return 1
                continue
            print(
                f"ok exec-pipeline seed={scen_seed} n={pn} "
                f"rollbacks={rolled} discarded={len(discarded)} "
                f"max_depth="
                f"{max(e.spec_rollback_depth for e in ssim._exec_unique)} "
                f"seq-digest=ok replay=ok"
            )
        if args.campaign_every and k % args.campaign_every == 0:
            # Every Kth scenario additionally runs the attack-campaign
            # family (campaign/): a budgeted validator-set-capture
            # attempt ground through the real ledger + epoch schedule,
            # judged by the monitor's trajectory proportionality bound,
            # then round-tripped through its CampaignRecord dump — the
            # replay-from-dump must re-derive the identical trajectory
            # digest with zero stored state beyond the config.
            import tempfile

            from hyperdrive_tpu.campaign import CampaignConfig
            from hyperdrive_tpu.campaign.record import CampaignRecord
            from hyperdrive_tpu.campaign.runner import (
                replay_campaign,
                run_campaign,
            )

            ccfg = CampaignConfig(
                family="capture", seed=scen_seed, validators=128,
                committee_size=16, attackers=4, sybils=8,
            )
            clive = None
            try:
                clive = run_campaign(ccfg)
                if clive.violations:
                    kind, detail = clive.violations[0]
                    raise InvariantViolation(kind, detail)
                with tempfile.TemporaryDirectory() as td:
                    cpath = os.path.join(td, "campaign.bin")
                    clive.record.dump(cpath)
                    loaded = CampaignRecord.load_file(cpath)
                    same, cfresh = replay_campaign(loaded)
                if not same:
                    raise InvariantViolation(
                        "replay",
                        "campaign replay-from-dump diverges from the "
                        f"live run ({cfresh.digest[:8].hex()} vs "
                        f"{clive.digest[:8].hex()})",
                    )
            except (InvariantViolation, AssertionError) as err:
                failures += 1
                os.makedirs(args.out, exist_ok=True)
                cbase = os.path.join(
                    args.out, f"campaign_seed_{scen_seed}"
                )
                if clive is not None:
                    clive.record.dump(cbase + ".bin")
                with open(cbase + ".txt", "w") as fh:
                    fh.write(f"seed={scen_seed}\nviolation={err}\n")
                print(
                    f"FAIL campaign seed={scen_seed} {err}\n"
                    f"  dumped {cbase}.bin\n"
                    f"  reproduce: python -m hyperdrive_tpu.campaign "
                    f"replay {cbase}.bin",
                    file=sys.stderr,
                )
                if not args.keep_going:
                    return 1
                continue
            print(
                f"ok campaign seed={scen_seed} family=capture "
                f"epochs={ccfg.epochs} "
                f"seats={clive.summary['seats_total']} "
                f"passive={clive.summary['passive_total']} "
                f"digest={clive.digest[:8].hex()} replay=ok"
            )
    if failures:
        print(f"soak FAILED: {failures}/{args.scenarios}", file=sys.stderr)
        return 1
    print(f"soak ok: {args.scenarios} scenarios, 0 violations")
    return 0


def replay(args) -> int:
    record = ScenarioRecord.load(args.dump)
    extra = {}
    if record.epochs is not None:
        # Epoch records replay with certificates on so the transition
        # proofs are re-minted from the recorded deliveries and the
        # light-client chain walk can run from the dump alone.
        extra["certificates"] = True
    result = Simulation.replay(record, **extra)
    result.assert_safety()
    print(
        f"replayed seed={record.seed} n={record.n} "
        f"target={record.target_height}: completed={result.completed} "
        f"steps={result.steps} lifecycle_ops={len(record.lifecycle)} "
        f"digest={result.commit_digest()[:16]}"
    )
    if record.epochs is not None:
        from hyperdrive_tpu.epochs import verify_epoch_chain

        sim = result.sim
        covered: dict = {}
        for c in sim.certifiers:
            for e, pr in getattr(c, "proofs", {}).items():
                covered.setdefault(e, pr)
        missing = sorted(set(range(1, sim.epoch + 1)) - set(covered))
        if missing:
            print(f"epoch chain BROKEN: no proof for epochs {missing}",
                  file=sys.stderr)
            return 1
        proofs = [covered[e] for e in sorted(covered)]
        hops = verify_epoch_chain(
            sim.epoch_schedule.signatories(0), proofs
        )
        print(f"epoch chain ok: {hops} transitions verified from genesis")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m hyperdrive_tpu.chaos")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("soak", help="run N seeded chaos scenarios")
    p.add_argument("--scenarios", type=int, default=20)
    p.add_argument("--seed", type=int, default=1)
    p.add_argument(
        "--n", type=int, default=0, help="replica count (0 = mix of 4 and 7)"
    )
    p.add_argument("--target", type=int, default=8)
    p.add_argument("--max-steps", type=int, default=500_000)
    p.add_argument("--out", default="chaos_failures")
    p.add_argument(
        "--replay-every",
        type=int,
        default=5,
        help="determinism self-check cadence (0 = off)",
    )
    p.add_argument(
        "--pipelined-every",
        type=int,
        default=4,
        help="re-run every Kth plan with devsched-pipelined settles and "
        "cross-check the commit digest (0 = off)",
    )
    p.add_argument(
        "--certs-every",
        type=int,
        default=4,
        help="re-run every Kth plan with quorum certificates enabled and "
        "cross-check chain digests + certificate integrity (0 = off)",
    )
    p.add_argument(
        "--bls-certs-every",
        type=int,
        default=0,
        help="re-run every Kth plan with BLS aggregate-signature "
        "certificates (digest parity, binding re-verify, one sampled "
        "light-client pairing check) plus a faulted BLS-partial overlay "
        "run with a deterministic merge-level corruption probe (0 = off)",
    )
    p.add_argument(
        "--overload-every",
        type=int,
        default=4,
        help="re-run every Kth plan under an open-loop duplicate storm "
        "with behavior-neutral admission and cross-check the commit "
        "digest against the unloaded run (0 = off)",
    )
    p.add_argument(
        "--tenants-every",
        type=int,
        default=0,
        help="additionally run every Kth seed as a multi-tenant serving "
        "scenario (a firehose tenant + a partitioned tenant sharing one "
        "continuously-batching verify service with a third, unfaulted "
        "tenant; digest isolation + the DRR starvation bound; 0 = off)",
    )
    p.add_argument(
        "--churn-every",
        type=int,
        default=0,
        help="additionally run every Kth seed as an epoch-churn scenario "
        "(dynamic validator set + key rotation under chaos; 0 = off)",
    )
    p.add_argument(
        "--overlay-every",
        type=int,
        default=0,
        help="additionally run every Kth seed as an aggregation-overlay "
        "scenario (tree-slicing partition + Byzantine contributors on "
        "the overlay path, plus a digest-neutrality cross-check against "
        "the all-to-all baseline; 0 = off)",
    )
    p.add_argument(
        "--exec-every",
        type=int,
        default=0,
        help="additionally run every Kth seed as an execution-churn "
        "scenario (stake-churn transactions driving stake-elected "
        "epochs under partition + crash-restore, with state-root "
        "agreement armed and a root-extended replay self-check; "
        "0 = off)",
    )
    p.add_argument(
        "--exec-pipeline-every",
        type=int,
        default=0,
        help="additionally run every Kth seed as a speculative-"
        "execution-pipeline scenario (forged-but-well-formed tx "
        "signatures forcing rollbacks inside churn faults, the "
        "no-rolled-back-root-committed invariant armed, digest parity "
        "with the sequential twin, and a record-replay self-check; "
        "0 = off)",
    )
    p.add_argument(
        "--proofs-every",
        type=int,
        default=0,
        help="additionally run every Kth seed as a proof-serving "
        "probe (jax-free host executor: honest inclusion proofs must "
        "roundtrip the wire codec and verify against the chained "
        "root, and all four forged-proof variants must fail "
        "verification; 0 = off)",
    )
    p.add_argument(
        "--fuzz-frames-every",
        type=int,
        default=0,
        help="additionally run every Kth seed as a Byzantine-bytes "
        "probe (real TcpNode behind a frame-fuzzing proxy mutating "
        "every 3rd payload; clean traffic must all deliver, the read "
        "loop must survive every mutant, and honest frames must never "
        "misparse; 0 = off)",
    )
    p.add_argument(
        "--metrics-every",
        type=int,
        default=0,
        help="additionally run every Kth seed as a live-metrics probe "
        "(jax-free ServicePort scraped over TAG_METRICS: valid "
        "Prometheus exposition text, metrics shed FIRST under a "
        "forced admission floor while consensus submits under the "
        "same floor all commit, and the SLO burn-rate checks measure "
        "and hold; 0 = off)",
    )
    p.add_argument(
        "--campaign-every",
        type=int,
        default=0,
        help="additionally run every Kth seed as an attack-campaign "
        "scenario (jax-free: a budgeted validator-set-capture attempt "
        "through the real ledger and epoch schedule, the trajectory "
        "proportionality bound armed, and a replay-from-dump digest "
        "identity self-check through the CampaignRecord codec; "
        "0 = off)",
    )
    p.add_argument(
        "--dump-ok",
        default="",
        help="dump the first PASSING churn scenario's record here (the "
        "CI epoch-proof-chain replay smoke consumes it)",
    )
    p.add_argument("--keep-going", action="store_true")
    p.set_defaults(fn=soak)

    p = sub.add_parser("replay", help="replay a dumped ScenarioRecord")
    p.add_argument("dump")
    p.set_defaults(fn=replay)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
