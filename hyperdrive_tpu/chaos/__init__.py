"""Chaos engineering for the consensus stack.

Seeded, replayable adversarial conditions as a first-class subsystem:

- :mod:`~hyperdrive_tpu.chaos.plan` — the FaultPlan DSL (link faults,
  scheduled partitions, crash-restarts) interpreted by the deterministic
  harness (``Simulation(chaos=...)``).
- :mod:`~hyperdrive_tpu.chaos.monitor` — the InvariantMonitor asserting
  no-fork-across-restarts, commit-digest equality, and bounded rounds to
  commit after every heal.
- :mod:`~hyperdrive_tpu.chaos.proxy` — a fault-injecting TCP proxy for
  real-socket partition/heal tests against TcpNode.
- ``python -m hyperdrive_tpu.chaos soak`` — N seeded scenarios; any
  violation dumps its ScenarioRecord + obs journal + checkpoints for
  message-for-message replay.

See ROBUSTNESS.md for the taxonomy, examples, and walkthrough.
"""

from hyperdrive_tpu.chaos.monitor import InvariantMonitor, InvariantViolation
from hyperdrive_tpu.chaos.plan import (
    CrashRestart,
    FaultPlan,
    LinkFault,
    Partition,
)
from hyperdrive_tpu.chaos.proxy import ChaosProxy

__all__ = [
    "LinkFault",
    "Partition",
    "CrashRestart",
    "FaultPlan",
    "InvariantMonitor",
    "InvariantViolation",
    "ChaosProxy",
]
