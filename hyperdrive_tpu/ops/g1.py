"""BLS12-381 G1 point arithmetic and aggregation kernels on TPU.

The device side of the BLS aggregate-verification path: complete
projective point arithmetic over :mod:`.fp381` limb vectors, plugged
into the curve-parameterized Pippenger engine of :mod:`.msm`, plus the
committee-bitmask aggregation kernel the quorum-certificate and overlay
paths launch.

Representation: a point batch is three [..., 30] int32 Montgomery-domain
limb tensors (X, Y, Z) — **complete projective** coordinates with the
Renes–Costello–Batina a = 0 formulas (b3 = 3*4 = 12). Complete formulas
are the whole trick for SIMD consensus workloads: identity, doubling
and generic addition all take the SAME branch-free instruction
sequence, so identity-padded lanes, bitmask-deselected committee slots
and bucket trash need no special cases anywhere in the kernel. The
identity is (0 : 1 : 0) (Montgomery-encoded 1).

Two kernels:

- :func:`aggregate_kernel` — sigma = sum_{i in mask} P_i, the O(n)
  half of BLS aggregate verification (aggregate signature or aggregate
  public-key-shadow sums). A select against the identity plus a
  halving tree of [n/2]-wide complete adds: log2(n) fixed-shape levels,
  one launch per committee regardless of the bitmask.
- :func:`g1_msm_kernel` — general scalar MSM over the shared Pippenger
  engine (:func:`.msm.msm_engine` with :func:`g1_curve_ops`), used by
  the parity CLI and anywhere weighted sums appear.

Host-side pack/unpack helpers convert between the affine Python-int
points of :mod:`hyperdrive_tpu.crypto.bls` and the device layout; the
differential contract is exact agreement with that oracle
(``tests/test_bls.py``).

Value-bound note (the fp381 invariant walk): the formulas chain at most
three adds or one mul_small(12) between Montgomery multiplies, so every
mul operand stays below 2^388.2 against the CIOS accumulator's 2^403
capacity — see the bound analysis in :mod:`.fp381`.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from hyperdrive_tpu.ops import fp381 as fp
from hyperdrive_tpu.ops import msm

__all__ = [
    "padd",
    "pdbl",
    "identity_rows",
    "g1_curve_ops",
    "g1_msm_kernel",
    "aggregate_kernel",
    "make_aggregate_fn",
    "make_batched_aggregate_fn",
    "aggregate_points",
    "G1SumLauncher",
    "G1_WINDOWS",
    "recode_scalars",
    "pack_points",
    "unpack_points",
]

#: b3 = 3 * b for y^2 = x^3 + 4.
B3 = 12

#: Signed 4-bit windows covering the 255-bit BLS12-381 scalar field
#: (one extra bit of headroom for the recode carry).
G1_WINDOWS = msm.windows_for_bits(256)  # 64


# ----------------------------------------------------------- point formulas


def padd(p, q):
    """Complete projective addition (Renes–Costello–Batina, a = 0).
    Branch-free: correct for identity, equal and opposite inputs alike."""
    x1, y1, z1 = p
    x2, y2, z2 = q
    t0 = fp.mul(x1, x2)
    t1 = fp.mul(y1, y2)
    t2 = fp.mul(z1, z2)
    t3 = fp.mul(fp.add(x1, y1), fp.add(x2, y2))
    t3 = fp.sub(t3, fp.add(t0, t1))
    t4 = fp.mul(fp.add(y1, z1), fp.add(y2, z2))
    t4 = fp.sub(t4, fp.add(t1, t2))
    x3 = fp.mul(fp.add(x1, z1), fp.add(x2, z2))
    y3 = fp.sub(x3, fp.add(t0, t2))
    t0 = fp.add(fp.add(t0, t0), t0)  # 3*X1X2
    t2 = fp.mul_small(t2, B3)
    z3 = fp.add(t1, t2)
    t1 = fp.sub(t1, t2)
    y3 = fp.mul_small(y3, B3)
    x3 = fp.sub(fp.mul(t3, t1), fp.mul(t4, y3))
    y3 = fp.add(fp.mul(t1, z3), fp.mul(y3, t0))
    z3 = fp.add(fp.mul(z3, t4), fp.mul(t0, t3))
    return (x3, y3, z3)


def pdbl(p):
    """Complete projective doubling (a = 0)."""
    x, y, z = p
    t0 = fp.mul(y, y)
    z3 = fp.add(fp.add(fp.add(t0, t0), fp.add(t0, t0)), fp.add(fp.add(t0, t0), fp.add(t0, t0)))  # 8*Y^2
    t1 = fp.mul(y, z)
    t2 = fp.mul_small(fp.mul(z, z), B3)
    x3 = fp.mul(t2, z3)
    y3 = fp.add(t0, t2)
    z3 = fp.mul(t1, z3)
    t1 = fp.add(t2, t2)
    t2 = fp.add(t1, t2)
    t0 = fp.sub(t0, t2)
    y3 = fp.add(x3, fp.mul(t0, y3))
    x3 = fp.mul_small(fp.mul(t0, fp.mul(x, y)), 2)
    return (x3, y3, z3)


def identity_rows(n: int):
    """n identity points (0 : 1 : 0), Montgomery domain: [n, 30] x3."""
    zero = jnp.zeros((n, fp.N_LIMBS), dtype=jnp.int32)
    one = jnp.broadcast_to(jnp.asarray(fp.ONE, dtype=jnp.int32), (n, fp.N_LIMBS))
    return (zero, one, zero)


# -------------------------------------------------------------- curve bundle


def _g1_ops() -> msm.CurveOps:
    def bucket_identity(G: int):
        zero = jnp.zeros((G, msm.N_BUCKETS + 1, fp.N_LIMBS), dtype=jnp.int32)
        one = jnp.broadcast_to(
            jnp.asarray(fp.ONE, dtype=jnp.int32),
            (G, msm.N_BUCKETS + 1, fp.N_LIMBS),
        )
        return (zero, one, zero)

    def entry_select(sign, entry):
        x, y, z = entry
        return (x, fp.select(sign, fp.neg(y), y), z)

    def window_shift(acc):
        for _ in range(msm.WINDOW_BITS):
            acc = pdbl(acc)
        return acc

    return msm.CurveOps(
        n_limbs=fp.N_LIMBS,
        acc_identity=identity_rows,
        bucket_identity=bucket_identity,
        entry_select=entry_select,
        add_entry=padd,
        add=padd,
        window_shift=window_shift,
    )


_G1_OPS = None


def g1_curve_ops() -> msm.CurveOps:
    global _G1_OPS
    if _G1_OPS is None:
        _G1_OPS = _g1_ops()
    return _G1_OPS


def g1_msm_kernel(px, py, pz, digits):
    """sum_i [s_i]P_i over projective G1 points via the shared Pippenger
    engine. ``digits``: [W, N] signed 4-bit windows (see
    :func:`recode_scalars`). Returns a projective point, [1, 30] x3.

    Padding lanes are free (zero digits land in the trash bucket), and
    identity *points* are also free — complete formulas again."""
    return msm.msm_engine((px, py, pz), digits, g1_curve_ops())


def aggregate_kernel(px, py, pz, mask):
    """Committee-bitmask aggregation: sum of P_i where mask_i != 0.

    Args (int32): px, py, pz [N, 30] projective Montgomery coords;
    mask [N] (0/1). N need not be a power of two — odd tails fold in
    with one extra width-1 add per level. Returns [1, 30] x3.

    One fixed-shape launch per committee: deselected lanes become the
    identity (free under complete addition), then a halving tree of
    batched adds reduces log2(N) levels — the device replacement for
    the host's O(N) serial Jacobian walk."""
    m = mask != 0
    ident = identity_rows(px.shape[0])
    pt = (
        fp.select(m, px, ident[0]),
        fp.select(m, py, ident[1]),
        fp.select(m, pz, ident[2]),
    )
    n = px.shape[0]
    while n > 1:
        h = n // 2
        lo = tuple(c[:h] for c in pt)
        hi = tuple(c[h : 2 * h] for c in pt)
        merged = padd(lo, hi)
        if n % 2:
            tail = tuple(c[n - 1 : n] for c in pt)
            merged = tuple(
                jnp.concatenate([c[: h - 1], d], axis=0)
                for c, d in zip(
                    merged, padd(tuple(c[h - 1 : h] for c in merged), tail)
                )
            )
        pt = merged
        n = h
    return pt


@functools.lru_cache(maxsize=32)
def make_aggregate_fn(jit: bool = True):
    return jax.jit(aggregate_kernel) if jit else aggregate_kernel


def aggregate_points(points, width: "int | None" = None):
    """Host convenience around :func:`aggregate_kernel`: aggregate a
    list of affine host points (``(x, y)`` tuples / None) on device and
    return the affine sum (or None).

    ``width`` pads the launch to a fixed lane count (identity rows) so
    callers with a varying live set — a certifier seeing different
    quorum sizes per commit — reuse ONE compiled kernel per committee
    width instead of recompiling per count."""
    n = len(points)
    if width is None:
        width = max(n, 1)
    if n > width:
        raise ValueError(f"{n} points exceed launch width {width}")
    px, py, pz = pack_points(list(points) + [None] * (width - n))
    mask = np.zeros(width, dtype=np.int32)
    mask[:n] = 1
    rx, ry, rz = make_aggregate_fn()(px, py, pz, mask)
    return unpack_points(rx, ry, rz)[0]


@functools.lru_cache(maxsize=8)
def make_batched_aggregate_fn():
    """jit(vmap(aggregate_kernel)): B independent masked sums in one
    launch — [B, N, 30] x3 + [B, N] mask -> [B, 1, 30] x3."""
    return jax.jit(jax.vmap(aggregate_kernel))


class G1SumLauncher:
    """DeviceWorkQueue launcher for masked G1 sums (the overlay's
    per-level partial-aggregate merges and any other bitmask-weighted
    point sums).

    A payload is a list of affine host points; the drain stacks every
    pending payload into ONE batched (vmapped) aggregation launch at a
    fixed lane width — submitted with ``generation=level``, so one
    aggregation level's merges coalesce into a single launch exactly
    like the verify path's windows do. Results come back as affine host
    points (None = identity)."""

    kind = "bls.g1sum"

    def __init__(self, width: int):
        self.width = int(width)
        #: Lifetime lane accounting (tests / obs report rows).
        self.launched = 0
        self.rows = 0

    def launch(self, payloads: list) -> list:
        width = self.width
        stacks = []
        masks = np.zeros((len(payloads), width), dtype=np.int32)
        for b, pts in enumerate(payloads):
            pts = list(pts)
            if len(pts) > width:
                raise ValueError(
                    f"{len(pts)} points exceed launch width {width}"
                )
            masks[b, : len(pts)] = 1
            stacks.append(pack_points(pts + [None] * (width - len(pts))))
        px = np.stack([s[0] for s in stacks])
        py = np.stack([s[1] for s in stacks])
        pz = np.stack([s[2] for s in stacks])
        rx, ry, rz = make_batched_aggregate_fn()(px, py, pz, masks)
        self.launched += 1
        self.rows += len(payloads)
        return unpack_points(
            np.asarray(rx)[:, 0], np.asarray(ry)[:, 0], np.asarray(rz)[:, 0]
        )


# ------------------------------------------------------------- host packing


def recode_scalars(vals) -> np.ndarray:
    """Python ints (< 2^255) -> [64, N] signed window digits in [-8, 8],
    window 0 least significant (numpy mirror of the device recoder in
    :mod:`.ed25519_jax`, host-side because BLS scalars originate on the
    host)."""
    vals = [int(v) for v in vals]
    if any(v < 0 or v >= 1 << 255 for v in vals):
        raise ValueError("scalar out of range")
    nib = np.array(
        [[(v >> (4 * i)) & 0xF for i in range(G1_WINDOWS)] for v in vals],
        dtype=np.int32,
    )  # [N, W]
    digits = np.zeros((G1_WINDOWS, len(vals)), dtype=np.int32)
    carry = np.zeros(len(vals), dtype=np.int32)
    for i in range(G1_WINDOWS):
        d = nib[:, i] + carry
        carry = (d > 8).astype(np.int32)
        digits[i] = d - 16 * carry
    if carry.any():
        raise ValueError("scalar recode overflow")
    return digits


def pack_points(points) -> tuple:
    """Affine host points (list of (x, y) int tuples or None) -> device
    projective Montgomery limb arrays ([N, 30] x3). None packs as the
    identity."""
    xs = [0 if p is None else p[0] for p in points]
    ys = [1 if p is None else p[1] for p in points]
    zs = [0 if p is None else 1 for p in points]
    return (
        np.asarray(fp.to_mont(xs)),
        np.asarray(fp.to_mont(ys)),
        np.asarray(fp.to_mont(zs)),
    )


def unpack_points(px, py, pz):
    """Device projective points -> affine host points ((x, y) or None).
    Accepts [N, 30] x3 (returns a list) or [30] x3 (returns one)."""
    X = fp.from_mont(np.asarray(px))
    Y = fp.from_mont(np.asarray(py))
    Z = fp.from_mont(np.asarray(pz))
    single = not isinstance(X, list)
    if single:
        X, Y, Z = [X], [Y], [Z]
    out = []
    p = fp.P_INT
    for x, y, z in zip(X, Y, Z):
        if z == 0:
            out.append(None)
            continue
        zi = pow(z, -1, p)
        out.append((x * zi % p, y * zi % p))
    return out[0] if single else out
