"""GF(p) arithmetic for the BLS12-381 base field on int32 limb vectors.

Same device dialect as :mod:`.fe25519` — a field element is a vector of
13-bit limbs in int32 (here **30 limbs**, capacity 390 bits), every
function is shape-static and jit/vmap/shard_map-transparent — but the
reduction strategy differs, because p_381 has no pseudo-Mersenne
structure: 2^390 mod p is a full-width 381-bit constant, so the fe25519
fold (multiply the carry-out by a small factor) never converges.

Instead, values live in the **Montgomery domain** (x̄ = x·R mod p,
R = 2^390) and multiplication is CIOS with the reduction interleaved
into the schoolbook product (:class:`hyperdrive_tpu.ops.limbs.Montgomery`).
Two consequences shape the API:

- **Signed redundancy, no subtraction bias.** fe25519 needs a
  limb-dominating multiple of p so subtraction stays non-negative
  before its fold. Montgomery reduction is indifferent to sign
  (arithmetic shifts are floor divisions; the quotient digit is
  computed from a masked — hence canonical — low limb), so ``sub`` is a
  plain limb subtraction plus one carry pass, and intermediate values
  are signed with the invariant **|value| < 2^389.5** (top limb below
  2^12.5, safely inside the CIOS bounds below). Each ``mul`` contracts
  the magnitude back below |a·b|/R + p < 2^389.5·2/R·|b| ~ 2^388.6, so
  chains of up to 8x-scaling add/sub between muls stay inside the
  invariant — the G1 complete-addition formulas (:mod:`.g1`) peak at
  8·Y^2 ~ 2^389.2.

- **Domain conversion is host-side.** ``encode``/``decode`` are Python
  int multiplies at pack/unpack time; the device never materializes
  R^2. ``canonical`` drops to the standard domain on device via a
  Montgomery multiply by 1 (x̄·1/R = x), which also squeezes the value
  into [0, p] for the conditional subtract.

Int32 safety (the bound walk the CIOS pass depends on): operand limbs
after a pass have magnitude <= 2^13 + eps; each CIOS step adds one
a_i*b_j product and one m*p_j product per column (<= 2 * 8193^2 ~=
1.35e8) onto an accumulator limb whose steady state is <= 8192 +
1.35e8/2^13 ~= 2.5e4 — columns stay < 1.4e8 << 2^31. The (n+1)-limb
accumulator holds intermediate values < 2^13 * 2^389 = 2^402 < 2^403,
its 403-bit capacity.

The Python-int reference for every operation is the host crypto module
(:mod:`hyperdrive_tpu.crypto.bls`); differential tests in
``tests/test_bls.py`` enforce exact agreement.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from hyperdrive_tpu.ops import limbs as _limbs

__all__ = [
    "N_LIMBS",
    "LIMB_BITS",
    "LIMB_MASK",
    "P_INT",
    "MONT",
    "to_mont",
    "from_mont",
    "to_limbs",
    "from_limbs",
    "zeros_like_batch",
    "add",
    "sub",
    "neg",
    "mul",
    "sqr",
    "mul_small",
    "canonical",
    "eq",
    "is_zero",
    "select",
    "ZERO",
    "ONE",
]

N_LIMBS = 30
LIMB_BITS = _limbs.LIMB_BITS
LIMB_MASK = _limbs.LIMB_MASK

#: The BLS12-381 base field prime (381 bits).
P_INT = int(
    "1a0111ea397fe69a4b1ba7b6434bacd764774b84f38512bf6730d2a0f6b0f624"
    "1eabfffeb153ffffb9feffffffffaaab",
    16,
)

#: Montgomery context: R = 2^390, n0' = -p^{-1} mod 2^13, CIOS kernel.
MONT = _limbs.make_montgomery(P_INT, N_LIMBS)


def to_limbs(x) -> np.ndarray:
    """Python int(s) in [0, 2^390) -> int32 limb array [..., 30]. Raw
    limb packing — no domain conversion (see :func:`to_mont`)."""
    return _limbs.to_limbs(x, N_LIMBS)


def from_limbs(limbs) -> "int | list":
    """Inverse of :func:`to_limbs` (host-side, signed-safe)."""
    return _limbs.from_limbs(limbs)


def to_mont(x) -> np.ndarray:
    """Host pack: Python int(s) -> Montgomery-domain limb array. Accepts
    a single int or any nested sequence (mirrors :func:`to_limbs`)."""
    if isinstance(x, int):
        return to_limbs(MONT.encode(x))
    x = list(x)
    if x and isinstance(x[0], int):
        return to_limbs([MONT.encode(v) for v in x])
    return np.stack([to_mont(v) for v in x])


def from_mont(limbs) -> "int | list":
    """Host unpack: Montgomery-domain limbs (any redundant signed
    representation) -> canonical Python int(s) in [0, p)."""
    v = from_limbs(limbs)
    if isinstance(v, int):
        return MONT.decode(v)

    def walk(t):
        return MONT.decode(t) if isinstance(t, int) else [walk(u) for u in t]

    return walk(v)


ZERO = to_limbs(0)
#: 1 in the Montgomery domain (R mod p).
ONE = to_mont(1)
_ONE_STD = to_limbs(1)
_P_LIMBS = to_limbs(P_INT)


def zeros_like_batch(batch_shape) -> jnp.ndarray:
    return jnp.zeros((*batch_shape, N_LIMBS), dtype=jnp.int32)


# ---------------------------------------------------------------- operators


def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a + b (domain-agnostic). One carry pass; the top limb absorbs the
    carry-out unmasked (value bound keeps it tiny)."""
    return _limbs.carry_pass_keep_top(a + b)


def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a - b, signed — no bias needed (see module docstring)."""
    return _limbs.carry_pass_keep_top(a - b)


def neg(a: jnp.ndarray) -> jnp.ndarray:
    return _limbs.carry_pass_keep_top(-a)


def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Montgomery product ā·b̄/R (= the Montgomery form of a·b)."""
    return MONT.mul(a, b)


def sqr(a: jnp.ndarray) -> jnp.ndarray:
    """Montgomery squaring. CIOS gains nothing from symmetry (the
    reduction interleave dominates), so this is :func:`mul`."""
    return MONT.mul(a, a)


def mul_small(a: jnp.ndarray, k: int) -> jnp.ndarray:
    """Multiply by a small non-negative constant. Scalars act directly
    in the Montgomery domain (k·x̄ = Montgomery form of k·x). k < 2^17
    keeps limb products inside int32; two passes restore the limb
    bound."""
    if not 0 <= k < (1 << 17):
        raise ValueError("constant too large for int32 limb products")
    x = _limbs.carry_pass_keep_top(a * jnp.int32(k))
    return _limbs.carry_pass_keep_top(x)


# ------------------------------------------------------------- canonical


def _cond_sub_p(x: jnp.ndarray) -> jnp.ndarray:
    """Subtract p if x >= p (constant-time select; x in [0, p] after
    :func:`canonical`'s squeeze, so one round suffices)."""
    p = jnp.asarray(_P_LIMBS, dtype=jnp.int32)
    t = x - p
    t, borrow = _limbs.carry_scan(t)  # borrow < 0 iff x < p
    keep = borrow < 0
    return jnp.where(keep[..., None], x, t)


def canonical(x: jnp.ndarray) -> jnp.ndarray:
    """Montgomery-domain x̄ -> the unique standard-domain representative
    of x in [0, p). A Montgomery multiply by 1 computes x̄/R = x while
    squeezing the value into [0, p] (|x̄|/R < 1 for invariant inputs, and
    the quotient additions keep the result non-negative); a scan carry
    then a single conditional subtract finish."""
    one = jnp.asarray(_ONE_STD, dtype=jnp.int32)
    std = MONT.mul(x, one)
    std, _ = _limbs.carry_scan(std)
    return _cond_sub_p(std)


def eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Field equality across redundant signed representations."""
    return jnp.all(canonical(a) == canonical(b), axis=-1)


def is_zero(a: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(canonical(a) == 0, axis=-1)


def select(mask: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Elementwise field-element select: mask ? a : b (mask shaped [...])."""
    return jnp.where(mask[..., None], a, b)
