"""TPU device kernels (JAX/XLA, Pallas where beneficial).

The batchable numeric work of the consensus framework lives here:

- :mod:`hyperdrive_tpu.ops.fe25519` — GF(2^255-19) arithmetic on int32
  limb vectors, the foundation of everything below.
- :mod:`hyperdrive_tpu.ops.ed25519_jax` — batched Ed25519 signature
  verification as fused XLA ops (the portable device backend).
- :mod:`hyperdrive_tpu.ops.ed25519_pallas` — the same verification as one
  Mosaic kernel in limb-major layout (7.5x the XLA kernel on v5e;
  auto-selected on TPU backends).
- :mod:`hyperdrive_tpu.ops.ed25519_wire` — verification straight from
  wire bytes: point decompression (and, via the challenge path, the
  whole signature hash) on device.
- :mod:`hyperdrive_tpu.ops.sha512_jax` — batched single-block SHA-512
  and canonical mod-L scalar reduction as lax.scans, for deriving
  Ed25519 challenge scalars in-launch (68 B/lane wire format).
- :mod:`hyperdrive_tpu.ops.tally` — masked quorum-tally reductions over
  vote tensors.
- :mod:`hyperdrive_tpu.ops.votegrid` — device-resident vote grids: the
  quorum tally state as sharded tensors feeding the rule cascade.
- :mod:`hyperdrive_tpu.ops.shamir` — batched Shamir share reconstruction.
- :mod:`hyperdrive_tpu.ops.bucketing` — static-shape batch bucketing so
  jitted kernels see a handful of shapes.

TPU design notes: there is no 64-bit integer multiply on the VPU, so field
elements are 20 limbs x 13 bits in int32 — limb products are < 2^26 and a
full 20-term column sum stays < 2^31 (no overflow), giving schoolbook
multiplication entirely in int32 lanes. All functions are shaped
``[..., 20]`` and are jit/vmap/shard_map-transparent.
"""
