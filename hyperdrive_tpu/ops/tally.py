"""Quorum tallies as masked reductions over vote tensors.

The reference's four hot loops scan Go maps per received message —
O(n) per vote, O(n^2) per round per replica
(reference: process/process.go:487-491, 574-579, 626-631, 696-701). Here a
round's votes live in a dense tensor ``[rounds, validators, words]`` and
every rule's count is one masked equality + sum reduction, batched over all
in-flight rounds at once and fused behind the signature-verification mask.

Sharding: the validator axis is the natural SPMD axis — under ``shard_map``
each device tallies its validator shard and the counts combine with a
``psum`` (see :mod:`hyperdrive_tpu.parallel.mesh`).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

__all__ = [
    "VALUE_WORDS",
    "pack_value",
    "pack_values",
    "tally_counts",
    "quorum_flags",
]

#: A 32-byte value packs into eight int32 words.
VALUE_WORDS = 8


def pack_value(value: bytes) -> np.ndarray:
    """32-byte value -> [8] int32 (little-endian words)."""
    if len(value) != 32:
        raise ValueError("value must be 32 bytes")
    return np.frombuffer(value, dtype="<i4").astype(np.int32)


def pack_values(values) -> np.ndarray:
    """Iterable of 32-byte values -> [n, 8] int32."""
    return np.stack([pack_value(v) for v in values])


def tally_counts(
    vote_values: jnp.ndarray,  # [R, V, 8] int32 — per-round per-validator vote
    present: jnp.ndarray,  # [R, V] bool — vote exists AND signature verified
    target_values: jnp.ndarray,  # [R, 8] int32 — the proposal value per round
):
    """All per-round counts the consensus rules need, in one fused pass.

    Returns a dict of [R] int32 arrays:
      - ``matching``:  votes equal to the round's target value   (L36/L28/L49)
      - ``nil``:       votes for the nil value                   (L44)
      - ``total``:     votes present at all                      (L34/L47)
    """
    present_i = present.astype(jnp.int32)
    eq_target = jnp.all(vote_values == target_values[:, None, :], axis=-1)
    eq_nil = jnp.all(vote_values == 0, axis=-1)
    return {
        "matching": jnp.sum(eq_target.astype(jnp.int32) * present_i, axis=-1),
        "nil": jnp.sum(eq_nil.astype(jnp.int32) * present_i, axis=-1),
        "total": jnp.sum(present_i, axis=-1),
    }


def quorum_flags(counts: dict, f: jnp.ndarray):
    """Threshold the counts: 2f+1 quorums and skip-target eligibility.

    ``f`` is a scalar (or [R]) int32. Returns a dict of [R] bool arrays
    keyed by the paper rules they open. ``skip_eligible`` is only the
    *count* half of rule L55 (>= f+1 unique participants in the round);
    the consumer must additionally require ``round > current_round`` —
    flagging the current round itself would break liveness.
    """
    q = 2 * f + 1
    return {
        "quorum_matching": counts["matching"] >= q,  # L36 / L28 / L49
        "quorum_nil": counts["nil"] >= q,  # L44
        "quorum_any": counts["total"] >= q,  # L34 / L47
        "skip_eligible": counts["total"] >= f + 1,  # L55, count half only
    }
