"""Device-side Ed25519 challenge scalars: SHA-512 + mod-L reduction in XLA.

Why this exists: the sustained unique-signature pipeline is TRANSFER-bound
(BENCH.md config 7 — 100 B/lane over a ~4-13 MB/s tunnel link sets the
rate, not the kernel and not the host). Of those 100 bytes, 32 are the
challenge scalar k = SHA-512(R || A || M) mod L, which the host packer
computes per lane. But every input of that hash is already available on
device: R ships anyway (32 B), A's compressed encoding lives in the
resident :class:`~hyperdrive_tpu.ops.ed25519_wire.ValidatorTable`, and
consensus digests M are shared by every validator voting for the same
(round, value) — the sender is deliberately excluded from the signing
digest (reference: /root/reference/process/message.go:165-186), so M is
per-ROUND data, not per-lane data. Deriving k on device drops the wire to
R (32) + s (32) + idx (4) = 68 B/lane and removes the SHA-512 from the
host packing leg entirely.

Contents:

- a batched single-block SHA-512 (messages <= 111 bytes; the challenge
  preimage R||A||M is exactly 96) over uint32 half-word pairs — TPUs have
  no 64-bit integer units, so every 64-bit add/rotate is expressed as two
  32-bit ops with explicit carries, which XLA fuses into the surrounding
  elementwise work;
- a base-2^13 limb reduction of the 512-bit digest to the CANONICAL
  scalar k < L (the fe25519 limb discipline, applied mod L): two
  delta-folds using 2^252 === -delta (mod L), then three conditional
  subtracts. Canonical — not merely partially reduced — so the device
  scalar is bit-identical to the host packer's
  (:func:`hyperdrive_tpu.crypto.ed25519.challenge_scalar`), which the
  differential tests assert, and the ladder's documented scalar < 2^253
  precondition (ops/ed25519_jax.py::verify_kernel) holds by construction.

All functions are jit-traceable and shape-polymorphic over the batch axis.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import lax

from hyperdrive_tpu.crypto import ed25519 as host_ed

__all__ = [
    "sha512_cat",
    "sc_reduce_limbs",
    "challenge_scalar_device",
    "limbs13_from_bytes",
    "bytes_from_limbs13",
]

L = host_ed.L
_LIMB_BITS = 13
_LIMB_MASK = (1 << _LIMB_BITS) - 1
#: delta = L - 2^252: the fold constant (2^252 === -delta mod L). 125 bits
#: -> 10 limbs of 13.
_DELTA = L - (1 << 252)


def _to_limbs13(x: int, n: int) -> list[int]:
    return [(x >> (_LIMB_BITS * i)) & _LIMB_MASK for i in range(n)]


_DELTA_LIMBS = _to_limbs13(_DELTA, 10)
_L_LIMBS = np.asarray(_to_limbs13(L, 20), dtype=np.int32)
_2L_LIMBS = np.asarray(_to_limbs13(2 * L, 20), dtype=np.int32)


# ------------------------------------------------------------- SHA-512

# FIPS 180-4 round constants (first 64 bits of the fractional parts of the
# cube roots of the first 80 primes) and initial hash value, as
# (hi, lo) uint32 pairs.
_K = [
    0x428A2F98D728AE22, 0x7137449123EF65CD, 0xB5C0FBCFEC4D3B2F,
    0xE9B5DBA58189DBBC, 0x3956C25BF348B538, 0x59F111F1B605D019,
    0x923F82A4AF194F9B, 0xAB1C5ED5DA6D8118, 0xD807AA98A3030242,
    0x12835B0145706FBE, 0x243185BE4EE4B28C, 0x550C7DC3D5FFB4E2,
    0x72BE5D74F27B896F, 0x80DEB1FE3B1696B1, 0x9BDC06A725C71235,
    0xC19BF174CF692694, 0xE49B69C19EF14AD2, 0xEFBE4786384F25E3,
    0x0FC19DC68B8CD5B5, 0x240CA1CC77AC9C65, 0x2DE92C6F592B0275,
    0x4A7484AA6EA6E483, 0x5CB0A9DCBD41FBD4, 0x76F988DA831153B5,
    0x983E5152EE66DFAB, 0xA831C66D2DB43210, 0xB00327C898FB213F,
    0xBF597FC7BEEF0EE4, 0xC6E00BF33DA88FC2, 0xD5A79147930AA725,
    0x06CA6351E003826F, 0x142929670A0E6E70, 0x27B70A8546D22FFC,
    0x2E1B21385C26C926, 0x4D2C6DFC5AC42AED, 0x53380D139D95B3DF,
    0x650A73548BAF63DE, 0x766A0ABB3C77B2A8, 0x81C2C92E47EDAEE6,
    0x92722C851482353B, 0xA2BFE8A14CF10364, 0xA81A664BBC423001,
    0xC24B8B70D0F89791, 0xC76C51A30654BE30, 0xD192E819D6EF5218,
    0xD69906245565A910, 0xF40E35855771202A, 0x106AA07032BBD1B8,
    0x19A4C116B8D2D0C8, 0x1E376C085141AB53, 0x2748774CDF8EEB99,
    0x34B0BCB5E19B48A8, 0x391C0CB3C5C95A63, 0x4ED8AA4AE3418ACB,
    0x5B9CCA4F7763E373, 0x682E6FF3D6B2B8A3, 0x748F82EE5DEFB2FC,
    0x78A5636F43172F60, 0x84C87814A1F0AB72, 0x8CC702081A6439EC,
    0x90BEFFFA23631E28, 0xA4506CEBDE82BDE9, 0xBEF9A3F7B2C67915,
    0xC67178F2E372532B, 0xCA273ECEEA26619C, 0xD186B8C721C0C207,
    0xEADA7DD6CDE0EB1E, 0xF57D4F7FEE6ED178, 0x06F067AA72176FBA,
    0x0A637DC5A2C898A6, 0x113F9804BEF90DAE, 0x1B710B35131C471B,
    0x28DB77F523047D84, 0x32CAAB7B40C72493, 0x3C9EBE0A15C9BEBC,
    0x431D67C49C100D4C, 0x4CC5D4BECB3E42B6, 0x597F299CFC657E2A,
    0x5FCB6FAB3AD6FAEC, 0x6C44198C4A475817,
]
_H0 = [
    0x6A09E667F3BCC908, 0xBB67AE8584CAA73B, 0x3C6EF372FE94F82B,
    0xA54FF53A5F1D36F1, 0x510E527FADE682D1, 0x9B05688C2B3E6C1F,
    0x1F83D9ABFB41BD6B, 0x5BE0CD19137E2179,
]


def _split64(x: int):
    return np.uint32(x >> 32), np.uint32(x & 0xFFFFFFFF)


def _add64(ah, al, bh, bl):
    lo = al + bl  # uint32 wraps
    carry = (lo < al).astype(jnp.uint32)
    return ah + bh + carry, lo


def _add64c(ah, al, k: int):
    kh, kl = _split64(k)
    lo = al + kl
    carry = (lo < al).astype(jnp.uint32)
    return ah + kh + carry, lo


def _rotr64(h, l, n: int):  # noqa: E741 - (h, l) mirrors the 64-bit halves
    if n == 32:
        return l, h
    if n < 32:
        return ((h >> n) | (l << (32 - n)), (l >> n) | (h << (32 - n)))
    m = n - 32
    return ((l >> m) | (h << (32 - m)), (h >> m) | (l << (32 - m)))


def _shr64(h, l, n: int):  # noqa: E741
    # n < 32 everywhere below (7 and 6)
    return h >> n, (l >> n) | (h << (32 - n))


def _xor3(a, b, c):
    return (a[0] ^ b[0] ^ c[0], a[1] ^ b[1] ^ c[1])


def sha512_cat(parts) -> jnp.ndarray:
    """Batched SHA-512 over the concatenation of ``parts`` (each
    [B, w_i] uint8); total width <= 111 bytes so the padded message is a
    single 1024-bit block. Returns the digest as [B, 64] uint8."""
    data = jnp.concatenate([p.astype(jnp.uint32) for p in parts], axis=1)
    nbytes = data.shape[1]
    if nbytes > 111:
        raise ValueError("single-block SHA-512 requires <= 111 bytes")

    # Message words W[0..15]: data big-endian, then 0x80 padding byte,
    # zeros, and the 128-bit bit-length field (all static for a fixed
    # width, so padding costs nothing at runtime).
    def byte(i):
        if i < nbytes:
            return data[:, i]
        if i == nbytes:
            return jnp.full(data.shape[:1], 0x80, dtype=jnp.uint32)
        if i >= 120:  # length field, big-endian 128-bit = 8 * nbytes
            shift = (127 - i) * 8
            return jnp.full(
                data.shape[:1], (nbytes * 8 >> shift) & 0xFF,
                dtype=jnp.uint32,
            )
        return jnp.zeros(data.shape[:1], dtype=jnp.uint32)

    w16_hi = []
    w16_lo = []
    for t in range(16):
        b = [byte(8 * t + j) for j in range(8)]
        w16_hi.append((b[0] << 24) | (b[1] << 16) | (b[2] << 8) | b[3])
        w16_lo.append((b[4] << 24) | (b[5] << 16) | (b[6] << 8) | b[7])
    win = (jnp.stack(w16_hi), jnp.stack(w16_lo))  # each [16, B]

    # Both the message schedule and the compression are lax.scans, NOT
    # unrolled Python loops: the unrolled 80-round graph sends an
    # XLA:CPU optimizer pass superlinear (minutes-long compiles for a
    # graph whose scanned form compiles in seconds), and the scan is the
    # compiler-friendly shape on TPU regardless — 80 cheap elementwise
    # steps with a 16-entry rolling window, fused by Mosaic/XLA.
    def sched_step(win, _):
        whi, wlo = win
        s0 = _xor3(_rotr64(whi[1], wlo[1], 1), _rotr64(whi[1], wlo[1], 8),
                   _shr64(whi[1], wlo[1], 7))
        s1 = _xor3(_rotr64(whi[14], wlo[14], 19),
                   _rotr64(whi[14], wlo[14], 61),
                   _shr64(whi[14], wlo[14], 6))
        acc = _add64(whi[0], wlo[0], *s0)
        acc = _add64(*acc, whi[9], wlo[9])
        nh, nl = _add64(*acc, *s1)
        new_win = (
            jnp.concatenate([whi[1:], nh[None]], axis=0),
            jnp.concatenate([wlo[1:], nl[None]], axis=0),
        )
        return new_win, (nh, nl)

    _, (ext_hi, ext_lo) = lax.scan(sched_step, win, None, length=64)
    w_hi = jnp.concatenate([win[0], ext_hi], axis=0)  # [80, B]
    w_lo = jnp.concatenate([win[1], ext_lo], axis=0)

    k_hi = jnp.asarray([k >> 32 for k in _K], dtype=jnp.uint32)
    k_lo = jnp.asarray([k & 0xFFFFFFFF for k in _K], dtype=jnp.uint32)

    def comp_step(state, xs):
        (a, b, c, d, e, f, g, h) = state
        khi, klo, whi, wlo = xs
        S1 = _xor3(_rotr64(*e, 14), _rotr64(*e, 18), _rotr64(*e, 41))
        ch = ((e[0] & f[0]) ^ (~e[0] & g[0]),
              (e[1] & f[1]) ^ (~e[1] & g[1]))
        t1 = _add64(*h, *S1)
        t1 = _add64(*t1, *ch)
        t1 = _add64(*t1, khi, klo)
        t1 = _add64(*t1, whi, wlo)
        S0 = _xor3(_rotr64(*a, 28), _rotr64(*a, 34), _rotr64(*a, 39))
        maj = ((a[0] & b[0]) ^ (a[0] & c[0]) ^ (b[0] & c[0]),
               (a[1] & b[1]) ^ (a[1] & c[1]) ^ (b[1] & c[1]))
        t2 = _add64(*S0, *maj)
        return ((_add64(*t1, *t2), a, b, c, _add64(*d, *t1), e, f, g),
                None)

    init = tuple(
        (jnp.full(data.shape[:1], v >> 32, dtype=jnp.uint32),
         jnp.full(data.shape[:1], v & 0xFFFFFFFF, dtype=jnp.uint32))
        for v in _H0
    )
    state, _ = lax.scan(comp_step, init, (k_hi, k_lo, w_hi, w_lo))

    out = []
    for init_v, word in zip(_H0, state):
        hi, lo = _add64c(*word, init_v)
        for half in (hi, lo):
            out.extend(
                ((half >> s) & 0xFF) for s in (24, 16, 8, 0)
            )
    return jnp.stack(out, axis=1).astype(jnp.uint8)


# ------------------------------------------------- base-2^13 scalar limbs


def limbs13_from_bytes(rows: jnp.ndarray, n_limbs: int) -> jnp.ndarray:
    """[B, W] uint8 little-endian -> [B, n_limbs] int32 13-bit limbs.
    The generalization of ed25519_wire.limbs_from_rows to any width, with
    no bit-255 masking (callers reduce, they don't interpret mod p)."""
    b = rows.astype(jnp.int32)
    width = rows.shape[1]
    limbs = []
    for i in range(n_limbs):
        bit = _LIMB_BITS * i
        byte, off = bit >> 3, bit & 7
        v = b[:, byte]
        if byte + 1 < width:
            v = v | (b[:, byte + 1] << 8)
        if byte + 2 < width:
            v = v | (b[:, byte + 2] << 16)
        limbs.append((v >> off) & _LIMB_MASK)
    return jnp.stack(limbs, axis=-1)


def bytes_from_limbs13(limbs: jnp.ndarray, n_bytes: int = 32) -> jnp.ndarray:
    """[B, n] int32 13-bit limbs -> [B, n_bytes] uint8 little-endian."""
    n = limbs.shape[-1]
    out = []
    for i in range(n_bytes):
        bit = 8 * i
        li, off = bit // _LIMB_BITS, bit % _LIMB_BITS
        v = limbs[:, li] >> off
        if off > _LIMB_BITS - 8 and li + 1 < n:
            v = v | (limbs[:, li + 1] << (_LIMB_BITS - off))
        out.append(v & 0xFF)
    return jnp.stack(out, axis=1).astype(jnp.uint8)


def _mul_const(x: jnp.ndarray, const: list[int]) -> jnp.ndarray:
    """Schoolbook [B, n] limbs x m-limb constant -> [B, n+m-1] raw column
    sums (no carries). Bound: each product < 2^26, <= min(n, m) <= 10
    terms per column -> columns < 2^30, comfortably int32."""
    n, m = x.shape[-1], len(const)
    cols = [None] * (n + m - 1)
    for j, cj in enumerate(const):
        if cj == 0:
            continue
        for i in range(n):
            t = x[:, i] * cj
            k = i + j
            cols[k] = t if cols[k] is None else cols[k] + t
    zero = jnp.zeros_like(x[:, 0])
    return jnp.stack([zero if c is None else c for c in cols], axis=-1)


def _carry(cols: jnp.ndarray, n_out: int) -> jnp.ndarray:
    """Sequential signed carry propagation into ``n_out`` 13-bit limbs.
    Arithmetic >> floor-divides, so negative columns borrow correctly;
    the caller guarantees the total value fits n_out limbs and is
    nonnegative, making the final carry-out zero."""
    out = []
    carry = jnp.zeros_like(cols[:, 0])
    n = cols.shape[-1]
    for i in range(n_out):
        v = (cols[:, i] if i < n else jnp.zeros_like(carry)) + carry
        out.append(v & _LIMB_MASK)
        carry = v >> _LIMB_BITS
    return jnp.stack(out, axis=-1)


def _split252(limbs: jnp.ndarray, n_high: int):
    """Split value = low + 2^252 * high on limb tensors. Bit 252 sits at
    limb 19, offset 5 (19*13 = 247), so the split is elementwise shifts.
    Returns (low [B, 20] < 2^252, high [B, n_high])."""
    n = limbs.shape[-1]
    zero = jnp.zeros_like(limbs[:, 0])

    def limb(i):
        return limbs[:, i] if i < n else zero

    low = jnp.concatenate(
        [limbs[:, :19], (limb(19) & 0x1F)[:, None]], axis=-1
    )
    high = [
        ((limb(19 + j) >> 5) | ((limb(20 + j) & 0x1F) << 8)) & _LIMB_MASK
        for j in range(n_high)
    ]
    return low, jnp.stack(high, axis=-1)


def _cond_sub(limbs: jnp.ndarray, const: np.ndarray) -> jnp.ndarray:
    """One vectorized conditional subtract: limbs - const if that does not
    underflow, else limbs unchanged."""
    c = jnp.asarray(const, dtype=jnp.int32)
    out = []
    borrow = jnp.zeros_like(limbs[:, 0])
    for i in range(limbs.shape[-1]):
        v = limbs[:, i] - c[i] - borrow
        out.append(v & _LIMB_MASK)
        borrow = -(v >> _LIMB_BITS)  # v >= -2^13, so >>13 is -1 or 0
    sub = jnp.stack(out, axis=-1)
    keep = (borrow == 1)[:, None]
    return jnp.where(keep, limbs, sub)


def sc_reduce_limbs(h_limbs: jnp.ndarray) -> jnp.ndarray:
    """[B, 40] 13-bit limbs of a 512-bit value -> [B, 20] limbs of the
    CANONICAL residue mod L.

    Two folds of 2^252 === -delta (each fold shrinks the value:
    2^512 -> delta*2^260 < 2^385 -> delta*2^133 < 2^258 -> delta*2^6 <
    2^131), recombined as a - c_low + d_low - e + 2L (nonnegative: the
    subtracted terms total < 2^252 + 2^131 < 2L; below 2^254.1: the added
    terms total < 2^252 + 2^252 + 2L), then conditional subtracts of
    [2L, L, L] (value < 4.2 L) land in [0, L)."""
    a, b = _split252(h_limbs, 21)  # h = a + 2^252 b,  b < 2^260
    c = _carry(_mul_const(b, _DELTA_LIMBS), 31)  # delta*b < 2^385
    c_low, c_high = _split252(c, 12)  # c_high < 2^133
    d = _carry(_mul_const(c_high, _DELTA_LIMBS), 22)  # delta*c_high < 2^258
    d_low, d_high = _split252(d, 3)  # d_high < 2^6
    e = _carry(_mul_const(d_high, _DELTA_LIMBS), 20)  # delta*d_high < 2^131

    two_l = jnp.asarray(_2L_LIMBS, dtype=jnp.int32)
    k = _carry(a - c_low + d_low - e + two_l[None, :], 20)
    k = _cond_sub(k, _2L_LIMBS)
    k = _cond_sub(k, _L_LIMBS)
    k = _cond_sub(k, _L_LIMBS)
    return k


def challenge_scalar_device(r_rows, a_rows, m_rows) -> jnp.ndarray:
    """k = SHA-512(R || A || M) mod L, entirely on device. Inputs are
    [B, 32] uint8 wire encodings; returns [B, 32] uint8 little-endian
    canonical k — bit-identical to the host packer's
    (crypto/ed25519.py::challenge_scalar), by the differential tests."""
    digest = sha512_cat((r_rows, a_rows, m_rows))
    return bytes_from_limbs13(sc_reduce_limbs(limbs13_from_bytes(digest, 40)))
