"""Batched Ed25519 signature verification on TPU.

The device backend of the Verifier seam (SURVEY.md section 7.1): checks
``[s]B == R + [k]A`` for a whole batch of votes in one launch, vectorized
over signatures x limbs in int32 lanes on top of
:mod:`hyperdrive_tpu.ops.fe25519`.

Work split (host does the bit-twiddly, device does the wide math):

- **Host** (:class:`Ed25519BatchHost`): parse signatures, SHA-512 challenge
  scalars (hashlib releases the GIL and is C-speed), decompress A and R
  (one ~255-bit modexp each via Python pow — microseconds), range-check s,
  negate A, pack everything into int32 limb tensors padded to a bucketed
  batch size (static shapes -> no recompiles).
- **Device** (:func:`verify_kernel`): compute P = [s]B + [k](-A) with one
  joint Horner loop — 63 iterations of 4 doublings + two table additions —
  then accept iff P projectively equals the decompressed R. The B window
  table is a compile-time constant; the (-A) table (16 multiples) is built
  on device per signature.

Verification semantics match the host oracle
(:func:`hyperdrive_tpu.crypto.ed25519.verify`) bit-for-bit: malformed
points, out-of-range s, and wrong signatures all reject; differential tests
enforce agreement.
"""

from __future__ import annotations

import functools
import os

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from hyperdrive_tpu.crypto import ed25519 as host_ed
from hyperdrive_tpu.ops import fe25519 as fe

__all__ = [
    "verify_kernel",
    "make_verify_fn",
    "Ed25519BatchHost",
    "TpuBatchVerifier",
]

P = host_ed.P

# 2d mod p — the constant in the unified addition law.
K2D = (2 * host_ed.D) % P
_K2D_LIMBS = fe.to_limbs(K2D)


# ----------------------------------------------------------- point algebra
# A point batch is a tuple (X, Y, Z, T) of [..., 20] int32 arrays.


def _identity_like(batch_shape):
    zero = jnp.zeros((*batch_shape, fe.N_LIMBS), dtype=jnp.int32)
    one = jnp.broadcast_to(
        jnp.asarray(fe.ONE, dtype=jnp.int32), (*batch_shape, fe.N_LIMBS)
    )
    return (zero, one, one, zero)


def _point_select(onehot, table):
    """Table lookup as multiply-accumulate: ``onehot`` [B, 16] x ``table``
    components each [B, 16, 20] (or [16, 20] shared) -> component [B, 20].

    One-hot matmul instead of gather: gathers scatter badly on TPU; a
    [B,16] x [16,*] contraction rides the vector units.
    """
    oh = onehot.astype(jnp.int32)
    out = []
    for comp in table:
        if comp.ndim == 2:  # shared table [16, 20]
            out.append(jnp.einsum("bv,vl->bl", oh, comp))
        else:  # per-signature table [B, 16, 20]
            out.append(jnp.einsum("bv,bvl->bl", oh, comp))
    return tuple(out)


# ------------------------------------------- niels-form additions/doublings
#
# Table entries are stored pre-transformed ("niels" coordinates): an entry
# (y+x, y-x, 2d*t [, z]) folds the additions and the 2d multiply of the
# unified formula into the table once, instead of recomputing them on
# every window (64x per signature).


def _madd(p, n, need_t: bool):
    """Extended point + niels entry with z2 = 1 (affine table): 7 muls,
    6 without the T output."""
    x1, y1, z1, t1 = p
    yp2, ym2, t2d2 = n
    a = fe.mul(fe.sub(y1, x1), ym2)
    b = fe.mul(fe.add(y1, x1), yp2)
    c = fe.mul(t1, t2d2)
    d = fe.mul_small(z1, 2)
    e = fe.sub(b, a)
    f = fe.sub(d, c)
    g = fe.add(d, c)
    h = fe.add(b, a)
    out = (fe.mul(e, f), fe.mul(g, h), fe.mul(f, g))
    return (*out, fe.mul(e, h)) if need_t else out


def _padd(p, n, need_t: bool):
    """Extended point + projective niels entry (z2 != 1): 8 muls."""
    x1, y1, z1, t1 = p
    yp2, ym2, t2d2, z2 = n
    a = fe.mul(fe.sub(y1, x1), ym2)
    b = fe.mul(fe.add(y1, x1), yp2)
    c = fe.mul(t1, t2d2)
    d = fe.mul_small(fe.mul(z1, z2), 2)
    e = fe.sub(b, a)
    f = fe.sub(d, c)
    g = fe.add(d, c)
    h = fe.add(b, a)
    out = (fe.mul(e, f), fe.mul(g, h), fe.mul(f, g))
    return (*out, fe.mul(e, h)) if need_t else out


def _dbl(p3, need_t: bool):
    """Doubling on (x, y, z) only — the extended T input is never needed
    to double, and computing the T *output* (one mul) is skipped for the
    three inner doublings of each window."""
    x1, y1, z1 = p3
    a = fe.sqr(x1)
    b = fe.sqr(y1)
    c = fe.mul_small(fe.sqr(z1), 2)
    d = fe.neg(a)
    e = fe.sub(fe.sub(fe.sqr(fe.add(x1, y1)), a), b)
    g = fe.add(d, b)
    f = fe.sub(g, c)
    h = fe.sub(d, b)
    out = (fe.mul(e, f), fe.mul(g, h), fe.mul(f, g))
    return (*out, fe.mul(e, h)) if need_t else out


# --------------------------------------------------------- B window table

_WINDOW = 4
_N_WINDOWS = 64  # 256 bits / 4


@functools.lru_cache(maxsize=None)
def _b_niels_np():
    """[v]B for v in 0..15 as affine niels limbs (y+x, y-x, 2d*x*y)."""
    yp, ym, t2 = [], [], []
    pt = host_ed.IDENTITY
    for v in range(16):
        x, y, z, _ = pt
        zinv = pow(z, P - 2, P)
        xa, ya = (x * zinv) % P, (y * zinv) % P
        yp.append((ya + xa) % P)
        ym.append((ya - xa) % P)
        t2.append((K2D * xa * ya) % P)
        pt = host_ed.point_add(pt, host_ed.BASE)
    return (fe.to_limbs(yp), fe.to_limbs(ym), fe.to_limbs(t2))


# ------------------------------------------------------------------ kernel


def verify_kernel(ax, ay, at, rx, ry, s_nibbles, k_nibbles):
    """Batched check of [s]B + [k]A' == R (A' = -A, all inputs packed).

    Args (all int32):
      ax, ay, at: [B, 20] affine extended coords of -A (t = x*y mod p)
      rx, ry:     [B, 20] affine coords of R
      s_nibbles:  [B, 64] little-endian base-16 digits of s
      k_nibbles:  [B, 64] little-endian base-16 digits of k
    Returns: bool [B] acceptance mask.
    """
    bsz = ax.shape[0]
    one = jnp.broadcast_to(
        jnp.asarray(fe.ONE, dtype=jnp.int32), (bsz, fe.N_LIMBS)
    )
    zero = jnp.zeros_like(one)
    k2d = jnp.asarray(_K2D_LIMBS, dtype=jnp.int32)

    # Per-signature table of the 16 multiples of A' (affine, z = 1), built
    # with a scan so the traced graph holds a single addition (15
    # executed), then converted to niels form in one batched shot.
    a_niels = (fe.add(ay, ax), fe.sub(ay, ax), fe.mul(at, k2d))

    def table_step(pt, _):
        return _madd(pt, a_niels, need_t=True), pt

    _, stacked = lax.scan(table_step, _identity_like((bsz,)), None, length=16)
    sx, sy, sz, st = (jnp.moveaxis(c, 0, 1) for c in stacked)  # [B, 16, 20]
    ta = (fe.add(sy, sx), fe.sub(sy, sx), fe.mul(st, k2d), sz)

    tb = tuple(
        jnp.asarray(comp, dtype=jnp.int32) for comp in _b_niels_np()
    )  # each [16, 20]

    lanes = jnp.arange(16, dtype=jnp.int32)

    def body(i, acc3):
        w = _N_WINDOWS - 1 - i
        acc3 = lax.fori_loop(
            0, _WINDOW - 1, lambda _, p: _dbl(p, need_t=False), acc3
        )
        acc4 = _dbl(acc3, need_t=True)
        k_digit = lax.dynamic_slice_in_dim(k_nibbles, w, 1, axis=1)  # [B,1]
        s_digit = lax.dynamic_slice_in_dim(s_nibbles, w, 1, axis=1)
        acc4 = _padd(acc4, _point_select(lanes[None, :] == k_digit, ta), need_t=True)
        return _madd(acc4, _point_select(lanes[None, :] == s_digit, tb), need_t=False)

    px, py, pz = lax.fori_loop(0, _N_WINDOWS, body, (zero, one, one))

    ok_x = fe.eq(px, fe.mul(rx, pz))
    ok_y = fe.eq(py, fe.mul(ry, pz))
    return ok_x & ok_y


@functools.lru_cache(maxsize=None)
def make_verify_fn(jit: bool = True):
    """Cached so every Verifier instance shares one jitted kernel (one XLA
    compile per batch shape process-wide, not per replica)."""
    return jax.jit(verify_kernel) if jit else verify_kernel


# ------------------------------------------------------------- host packer


def _nibbles(x: int) -> np.ndarray:
    return np.array([(x >> (4 * i)) & 0xF for i in range(64)], dtype=np.int32)


class Ed25519BatchHost:
    """Parses/packs (pubkey, digest, signature) triples for the kernel.

    Bucketed padding: batches are padded up to the next size in ``buckets``
    so the jitted kernel sees only a handful of static shapes.

    Packing runs through the native C++ runtime
    (:mod:`hyperdrive_tpu.native`) when available — point decompression is
    one field exponentiation per point and dominates the host cost — with
    the pure-Python loop as the always-available fallback (``HD_NO_NATIVE=1``
    forces it). Both paths are differentially tested to produce identical
    tensors and masks.
    """

    def __init__(self, buckets=(64, 256, 1024, 4096), use_native: bool = True):
        self.buckets = tuple(sorted(buckets))
        self._native = None
        if use_native and not os.environ.get("HD_NO_NATIVE"):
            try:
                from hyperdrive_tpu.native import NativePacker

                self._native = NativePacker()
            except RuntimeError as e:
                # Toolchain missing / build failed: fall back to the pure-
                # Python loop, but say so — it is ~100x slower and would
                # otherwise silently eat the throughput target.
                import warnings

                warnings.warn(
                    f"native packer unavailable ({e}); falling back to the "
                    "pure-Python packing path (expect ~100x slower host "
                    "packing). Set HD_NO_NATIVE=1 to silence this.",
                    RuntimeWarning,
                    stacklevel=2,
                )

    def bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        return int(np.ceil(n / self.buckets[-1])) * self.buckets[-1]

    def pack(self, items):
        """items: iterable of (pub32, digest, sig64).

        Returns (arrays, prevalid, n) where arrays feed verify_kernel,
        prevalid marks host-side rejections (bad point/range), and n is the
        true batch size before padding.
        """
        items = list(items)
        n = len(items)
        bsz = self.bucket_for(max(n, 1))

        ax = np.zeros((bsz, fe.N_LIMBS), dtype=np.int32)
        ay = np.zeros_like(ax)
        at = np.zeros_like(ax)
        rx = np.zeros_like(ax)
        ry = np.zeros_like(ax)
        s_nib = np.zeros((bsz, 64), dtype=np.int32)
        k_nib = np.zeros((bsz, 64), dtype=np.int32)
        prevalid = np.zeros(bsz, dtype=bool)

        if self._native is not None:
            prevalid[:n] = self._native.pack_into(
                items, ax, ay, at, rx, ry, s_nib, k_nib
            )
            return (ax, ay, at, rx, ry, s_nib, k_nib), prevalid, n

        for i, (pub, digest, sig) in enumerate(items):
            if len(pub) != 32 or len(sig) != 64:
                continue
            a_pt = host_ed.point_decompress(pub)
            if a_pt is None:
                continue
            r_pt = host_ed.point_decompress(sig[:32])
            if r_pt is None:
                continue
            s = int.from_bytes(sig[32:], "little")
            if s >= host_ed.L:
                continue
            k = host_ed.challenge_scalar(sig[:32], pub, digest)
            # Negate A (x -> p - x): the kernel computes [s]B + [k](-A).
            nax = (P - a_pt[0]) % P
            nay = a_pt[1]
            ax[i] = fe.to_limbs(nax)
            ay[i] = fe.to_limbs(nay)
            at[i] = fe.to_limbs((nax * nay) % P)
            rx[i] = fe.to_limbs(r_pt[0])
            ry[i] = fe.to_limbs(r_pt[1])
            s_nib[i] = _nibbles(s)
            k_nib[i] = _nibbles(k)
            prevalid[i] = True

        return (ax, ay, at, rx, ry, s_nib, k_nib), prevalid, n


class TpuBatchVerifier:
    """Drop-in Verifier (see :mod:`hyperdrive_tpu.verifier`) that batches a
    whole mq drain window into one device launch."""

    def __init__(self, buckets=(64, 256, 1024, 4096)):
        self.host = Ed25519BatchHost(buckets=buckets)
        self._fn = make_verify_fn(jit=True)

    def verify_signatures(self, items) -> np.ndarray:
        """items: list of (pub, digest, sig); returns bool[n]."""
        arrays, prevalid, n = self.host.pack(items)
        if not prevalid.any():
            return np.zeros(n, dtype=bool)
        mask = np.asarray(self._fn(*[jnp.asarray(a) for a in arrays]))
        return (mask & prevalid)[:n]

    def verify_batch(self, window):
        """Verifier-protocol entry: messages with detached signatures."""
        # Signatures pass through unchanged: the packer (native or Python)
        # length-checks and leaves wrong-length lanes prevalid=False, so
        # rejection is deterministic — never substitute zeros, which could
        # verify under an adversarial small-order pubkey.
        items = [(msg.sender, msg.digest(), msg.signature) for msg in window]
        # Messages with no signature at all fail immediately (parity with
        # HostVerifier), but still occupy a lane for shape stability.
        unsigned = np.array([not msg.signature for msg in window], dtype=bool)
        ok = self.verify_signatures(items)
        return list(ok & ~unsigned)
