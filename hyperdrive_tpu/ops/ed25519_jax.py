"""Batched Ed25519 signature verification on TPU.

The device backend of the Verifier seam (SURVEY.md section 7.1): checks
``[s]B == R + [k]A`` for a whole batch of votes in one launch, vectorized
over signatures x limbs in int32 lanes on top of
:mod:`hyperdrive_tpu.ops.fe25519`.

Work split (host does the bit-twiddly, device does the wide math):

- **Host** (:class:`Ed25519BatchHost`): parse signatures, SHA-512 challenge
  scalars (hashlib releases the GIL and is C-speed), decompress A and R
  (one ~255-bit modexp each via Python pow — microseconds), range-check s,
  negate A, pack everything into int32 limb tensors padded to a bucketed
  batch size (static shapes -> no recompiles).
- **Device** (:func:`verify_kernel`): compute P = [s]B + [k](-A) with one
  joint Horner loop — 64 iterations of 4 doublings + two signed-window
  table additions — then accept iff P projectively equals the decompressed
  R. The B window table is a compile-time constant; the (-A) table (9
  multiples, signed digits select +/-) is built on device per signature.

Verification semantics match the host oracle
(:func:`hyperdrive_tpu.crypto.ed25519.verify`) bit-for-bit: malformed
points, out-of-range s, and wrong signatures all reject; differential tests
enforce agreement.
"""

from __future__ import annotations

import functools
import os

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from hyperdrive_tpu.analysis.annotations import device_fetch
from hyperdrive_tpu.crypto import ed25519 as host_ed
from hyperdrive_tpu.obs.recorder import NULL_BOUND as _OBS_NULL_BOUND
from hyperdrive_tpu.ops import bucketing
from hyperdrive_tpu.ops import fe25519 as fe

__all__ = [
    "verify_kernel",
    "rlc_kernel",
    "make_verify_fn",
    "make_rlc_fn",
    "Ed25519BatchHost",
    "TpuBatchVerifier",
]

P = host_ed.P

# 2d mod p — the constant in the unified addition law.
K2D = (2 * host_ed.D) % P
_K2D_LIMBS = fe.to_limbs(K2D)


# ----------------------------------------------------------- point algebra
# A point batch is a tuple (X, Y, Z, T) of [..., 20] int32 arrays.


def _identity_like(batch_shape):
    zero = jnp.zeros((*batch_shape, fe.N_LIMBS), dtype=jnp.int32)
    one = jnp.broadcast_to(
        jnp.asarray(fe.ONE, dtype=jnp.int32), (*batch_shape, fe.N_LIMBS)
    )
    return (zero, one, one, zero)


def _point_select(onehot, table):
    """Table lookup as multiply-accumulate: ``onehot`` [B, V] x ``table``
    components each [B, V, 20] (or [V, 20] shared) -> component [B, 20],
    for any table width V (9 signed-window entries in verify_kernel, 16
    unsigned in rlc_kernel).

    One-hot matmul instead of gather: gathers scatter badly on TPU; a
    [B,16] x [16,*] contraction rides the vector units.
    """
    oh = onehot.astype(jnp.int32)
    out = []
    for comp in table:
        if comp.ndim == 2:  # shared table [16, 20]
            out.append(jnp.einsum("bv,vl->bl", oh, comp))
        else:  # per-signature table [B, 16, 20]
            out.append(jnp.einsum("bv,bvl->bl", oh, comp))
    return tuple(out)


# ------------------------------------------- niels-form additions/doublings
#
# Table entries are stored pre-transformed ("niels" coordinates): an entry
# (y+x, y-x, 2d*t [, z]) folds the additions and the 2d multiply of the
# unified formula into the table once, instead of recomputing them on
# every window (64x per signature).


def _madd(p, n, need_t: bool):
    """Extended point + niels entry with z2 = 1 (affine table): 7 muls,
    6 without the T output."""
    x1, y1, z1, t1 = p
    yp2, ym2, t2d2 = n
    a = fe.mul(fe.sub(y1, x1), ym2)
    b = fe.mul(fe.add(y1, x1), yp2)
    c = fe.mul(t1, t2d2)
    d = fe.mul_small(z1, 2)
    e = fe.sub(b, a)
    f = fe.sub(d, c)
    g = fe.add(d, c)
    h = fe.add(b, a)
    out = (fe.mul(e, f), fe.mul(g, h), fe.mul(f, g))
    return (*out, fe.mul(e, h)) if need_t else out


def _padd(p, n, need_t: bool):
    """Extended point + projective niels entry (z2 != 1): 8 muls."""
    x1, y1, z1, t1 = p
    yp2, ym2, t2d2, z2 = n
    a = fe.mul(fe.sub(y1, x1), ym2)
    b = fe.mul(fe.add(y1, x1), yp2)
    c = fe.mul(t1, t2d2)
    d = fe.mul_small(fe.mul(z1, z2), 2)
    e = fe.sub(b, a)
    f = fe.sub(d, c)
    g = fe.add(d, c)
    h = fe.add(b, a)
    out = (fe.mul(e, f), fe.mul(g, h), fe.mul(f, g))
    return (*out, fe.mul(e, h)) if need_t else out


def _dbl(p3, need_t: bool):
    """Doubling on (x, y, z) only — the extended T input is never needed
    to double, and computing the T *output* (one mul) is skipped for the
    three inner doublings of each window."""
    x1, y1, z1 = p3
    a = fe.sqr(x1)
    b = fe.sqr(y1)
    c = fe.mul_small(fe.sqr(z1), 2)
    d = fe.neg(a)
    e = fe.sub(fe.sub(fe.sqr(fe.add(x1, y1)), a), b)
    g = fe.add(d, b)
    f = fe.sub(g, c)
    h = fe.sub(d, b)
    out = (fe.mul(e, f), fe.mul(g, h), fe.mul(f, g))
    return (*out, fe.mul(e, h)) if need_t else out


# --------------------------------------------------------- B window table

_WINDOW = 4
_N_WINDOWS = 64  # 256 bits / 4


@functools.lru_cache(maxsize=None)
def _b_niels_np(entries: int = 16):
    """[v]B for v in 0..entries-1 as affine niels limbs (y+x, y-x, 2d*x*y).

    The per-signature kernel selects over 9 entries (signed digits, |d| <=
    8); the RLC kernel keeps the unsigned 16-entry table."""
    yp, ym, t2 = [], [], []
    pt = host_ed.IDENTITY
    for _v in range(entries):
        x, y, z, _ = pt
        zinv = pow(z, P - 2, P)
        xa, ya = (x * zinv) % P, (y * zinv) % P
        yp.append((ya + xa) % P)
        ym.append((ya - xa) % P)
        t2.append((K2D * xa * ya) % P)
        pt = host_ed.point_add(pt, host_ed.BASE)
    return (fe.to_limbs(yp), fe.to_limbs(ym), fe.to_limbs(t2))


def _recode_signed(nibbles):
    """[B, 64] unsigned base-16 digits -> [64, B] signed digits in [-8, 7].

    Standard signed-window recoding: digits >= 8 borrow 16 and carry 1
    into the next position. Both verified scalars are < 2^253 (s is
    range-checked against L, k is reduced mod L), so the top digit is at
    most 1 + carry = 2 and the carry never overflows. Halving the digit
    magnitude halves the table the per-window selects read (9 entries
    instead of 16) — negation of a niels entry is a swap + one field
    negation, far cheaper than the wider select."""
    xs = jnp.moveaxis(nibbles, -1, 0)

    def step(carry, col):
        d = col + carry
        ge = (d >= 8).astype(jnp.int32)
        return ge, d - 16 * ge

    _, out = lax.scan(step, jnp.zeros_like(xs[0]), xs)
    return out


def _select_signed(digit, table, shared: bool):
    """Select entry [|digit|] from a 9-entry niels table and negate it when
    the digit is negative: a niels negation swaps (y+x, y-x) and negates
    the 2d*t component; any z passes through.

    ``digit``: [B] signed; ``table``: niels components each [B, 9, 20]
    (per-signature) or [9, 20] (``shared``); returns the selected entry."""
    lanes9 = jnp.arange(9, dtype=jnp.int32)
    sign = digit < 0
    oh = lanes9[None, :] == jnp.abs(digit)[:, None]
    sel = _point_select(oh, table)
    yp, ym, t2 = sel[0], sel[1], sel[2]
    out = (
        fe.select(sign, ym, yp),
        fe.select(sign, yp, ym),
        fe.select(sign, fe.neg(t2), t2),
    )
    return out if shared else (*out, sel[3])


# ------------------------------------------------------------------ kernel


def verify_kernel(ax, ay, at, rx, ry, s_nibbles, k_nibbles):
    """Batched check of [s]B + [k]A' == R (A' = -A, all inputs packed).

    Args (all int32):
      ax, ay, at: [B, 20] affine extended coords of -A (t = x*y mod p)
      rx, ry:     [B, 20] affine coords of R
      s_nibbles:  [B, 64] little-endian base-16 digits of s
      k_nibbles:  [B, 64] little-endian base-16 digits of k
    Returns: bool [B] acceptance mask.

    PRECONDITION: every scalar's nibbles must encode a value < 2^253
    (both s and k). The signed-digit recode discards the final carry,
    so a raw scalar >= 2^253 would silently verify as (scalar - 2^256)
    instead of being rejected. The packer guarantees this — s is
    range-checked against L and k is reduced mod L, with invalid lanes
    zeroed and masked via ``prevalid`` — so only call this kernel on
    packer output (or inputs honoring the same bound).
    """
    bsz = ax.shape[0]
    one = jnp.broadcast_to(
        jnp.asarray(fe.ONE, dtype=jnp.int32), (bsz, fe.N_LIMBS)
    )
    zero = jnp.zeros_like(one)
    k2d = jnp.asarray(_K2D_LIMBS, dtype=jnp.int32)

    # Signed-digit recoding: the window selects then read a 9-entry table
    # (|d| <= 8) instead of 16, and negation is a cheap swap+neg.
    k_signed = _recode_signed(k_nibbles)  # [64, B]
    s_signed = _recode_signed(s_nibbles)

    # Per-signature table of the multiples [0..8]A' (affine, z = 1), built
    # with a scan so the traced graph holds a single addition (8
    # executed), then converted to niels form in one batched shot.
    a_niels = (fe.add(ay, ax), fe.sub(ay, ax), fe.mul(at, k2d))

    def table_step(pt, _):
        return _madd(pt, a_niels, need_t=True), pt

    _, stacked = lax.scan(table_step, _identity_like((bsz,)), None, length=9)
    sx, sy, sz, st = (jnp.moveaxis(c, 0, 1) for c in stacked)  # [B, 9, 20]
    ta = (fe.add(sy, sx), fe.sub(sy, sx), fe.mul(st, k2d), sz)

    tb = tuple(
        jnp.asarray(comp, dtype=jnp.int32) for comp in _b_niels_np(9)
    )  # each [9, 20]

    def body(i, acc3):
        w = _N_WINDOWS - 1 - i
        # The three T-less doublings are unrolled statically: a nested
        # lax.fori_loop would put a while-loop fusion barrier inside every
        # window, and the whole window body fuses better as straight line.
        for _ in range(_WINDOW - 1):
            acc3 = _dbl(acc3, need_t=False)
        acc4 = _dbl(acc3, need_t=True)
        kd = lax.dynamic_slice_in_dim(k_signed, w, 1, axis=0)[0]  # [B]
        sd = lax.dynamic_slice_in_dim(s_signed, w, 1, axis=0)[0]
        acc4 = _padd(acc4, _select_signed(kd, ta, shared=False), need_t=True)
        return _madd(acc4, _select_signed(sd, tb, shared=True), need_t=False)

    # Two windows per traced iteration: halving the loop-carried barrier
    # count buys ~0.7% on v5e (69.2 -> 69.7k sigs/s); a 4-window unroll
    # measured no better and doubles the traced body, so stop at 2.
    def body2(j, acc3):
        acc4 = body(2 * j, acc3)
        return body(2 * j + 1, acc4)

    px, py, pz = lax.fori_loop(0, _N_WINDOWS // 2, body2, (zero, one, one))

    ok_x = fe.eq(px, fe.mul(rx, pz))
    ok_y = fe.eq(py, fe.mul(ry, pz))
    return ok_x & ok_y


@functools.lru_cache(maxsize=None)
def make_verify_fn(jit: bool = True):
    """Cached so every Verifier instance shares one jitted kernel (one XLA
    compile per batch shape process-wide, not per replica)."""
    return jax.jit(verify_kernel) if jit else verify_kernel


# ------------------------------------------------- RLC batch verification
#
# The random-linear-combination equation (SURVEY.md §7.1(1)): with
# per-signature random 128-bit z_i and m_i = z_i·k_i mod L,
# c = Σ z_i·s_i mod L, every signature in the batch is valid iff
#
#     [c]B == Σ_i ( [z_i]R_i + [m_i]A_i )         (w.h.p. over z)
#
# The win is structural: the per-signature Horner loops of
# `verify_kernel` each carry their own accumulator (64 windows × 4
# doublings per signature), while the batch sum above reduces the whole
# batch through ONE Pippenger multi-scalar multiplication
# (:mod:`hyperdrive_tpu.ops.msm`): windowed signed-digit decomposition,
# bucket accumulation as fixed-shape batched niels additions, bucket
# suffix-sums, and a single shared window-Horner accumulator. Per lane
# per window that is ~7 field muls against the ladder's ~22, and the
# doubling work collapses from per-signature to per-window.
#
# HISTORY: the first cut of this kernel was a shared Straus walk whose
# per-window tree-sum concatenates broke XLA fusion — measured ~40k
# votes/s vs ~59k for the per-signature kernel on v5e at B=16384, and it
# shipped off by default as the honest record of that experiment. The
# Pippenger rewrite removes every concatenate from the hot loop (one-hot
# bucket blends over a static [G, 9] layout instead); BENCH_r07.json
# carries the paired ladder-vs-MSM medians that flipped the default (see
# TpuBatchVerifier: rlc="auto" resolves per backend + bucket ladder).
#
# A batch mismatch falls back to `verify_kernel` to identify culprits.
# Acceptance semantics: the kernel cofactor-clears the combined sum with
# three final doublings, so the batch equation is the COFACTORED relation
# [8]([Σz·s]B − Σ[z]R − Σ[z·k]A) == O — torsion components from R *and* A
# are annihilated deterministically rather than surviving under grindable
# weights. A batch-accept certifies every lane under cofactored
# verification (false accept of a main-subgroup forgery ~2^-128); a
# crafted signature that is valid cofactored but invalid under the strict
# cofactorless check (honest signers never produce one — it requires
# adding a small-order torsion point) IS accepted by the fast path where
# `verify_kernel`/the host oracle would reject. That divergence class is
# exactly the one the EdDSA batch-verification literature accepts
# ("Taming the many EdDSAs": batch verify ≡ cofactored single verify);
# rlc=False remains the default and keeps strict per-signature semantics.


def _add_ext(p, q, need_t: bool):
    """Unified addition of two extended projective points (add-2008-hwcd,
    as in _padd but with the niels transform of ``q`` inlined): 9 muls."""
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    k2d = jnp.asarray(_K2D_LIMBS, dtype=jnp.int32)
    a = fe.mul(fe.sub(y1, x1), fe.sub(y2, x2))
    b = fe.mul(fe.add(y1, x1), fe.add(y2, x2))
    c = fe.mul(t1, fe.mul(t2, k2d))
    d = fe.mul_small(fe.mul(z1, z2), 2)
    e = fe.sub(b, a)
    f = fe.sub(d, c)
    g = fe.add(d, c)
    h = fe.add(b, a)
    out = (fe.mul(e, f), fe.mul(g, h), fe.mul(f, g))
    return (*out, fe.mul(e, h)) if need_t else out


def _dbl4_ext(p4):
    """Four doublings of an extended point batch, T produced on the last
    only (the Straus accumulator shift by one 4-bit window)."""
    p3 = p4[:3]
    for _ in range(3):
        p3 = _dbl(p3, need_t=False)
    return _dbl(p3, need_t=True)


def _identity_rows(m):
    zero = jnp.zeros((m, fe.N_LIMBS), dtype=jnp.int32)
    one = jnp.broadcast_to(jnp.asarray(fe.ONE, dtype=jnp.int32), (m, fe.N_LIMBS))
    return (zero, one, one, zero)


def rlc_kernel(ax, ay, at, rx, ry, m_nib, z_nib, c_nib):
    """Batched RLC check: does [c]B + Σ([z_i](-R_i) + [m_i](-A_i)) vanish?

    Args (all int32):
      ax, ay, at: [B, 20] affine extended coords of -A (as verify_kernel)
      rx, ry:     [B, 20] affine coords of R (negated here)
      m_nib:      [B, 64] nibbles of m_i = z_i*k_i mod L (zero for invalid
                  lanes, which then contribute the identity)
      z_nib:      [B, 64] nibbles of z_i (only the low 32 are nonzero)
      c_nib:      [1, 64] nibbles of c = sum z_i*s_i mod L
    Returns: bool [] — True iff the whole batch verifies.

    The batch sum is TWO Pippenger MSMs sharing one engine
    (:func:`hyperdrive_tpu.ops.msm.msm_kernel`): Σ[m_i](-A_i) over 64
    signed windows and Σ[z_i](-R_i) over 33 (z is 128-bit; one extra
    window absorbs the recode carry), instead of the per-lane table
    walk + tree-sum of the original Straus formulation.
    """
    from hyperdrive_tpu.ops.msm import (
        ED25519_FULL_WINDOWS,
        ED25519_HALF_WINDOWS,
        msm_kernel,
    )

    lanes = jnp.arange(16, dtype=jnp.int32)

    # Signed-window decomposition. Both scalars satisfy the < 2^253
    # recode precondition: m and c are reduced mod L, z is 128-bit. The
    # window geometry is the planner's (64 full / 33 half), derived from
    # the scalar bit widths rather than hardcoded.
    m_digits = _recode_signed(m_nib)  # [64, B]
    z_digits = _recode_signed(z_nib)[:ED25519_HALF_WINDOWS]  # [33, B]

    t_a = msm_kernel(ax, ay, at, m_digits)
    # -R: negate x and t of the affine point.
    nrx = fe.neg(rx)
    t_r = msm_kernel(nrx, ry, fe.mul(nrx, ry), z_digits)
    t_point = _add_ext(t_a, t_r, need_t=True)  # [1, 20] x4

    # [c]B on the shared fixed-base niels table.
    tb = tuple(jnp.asarray(comp, dtype=jnp.int32) for comp in _b_niels_np())

    def cb_body(i, acc3):
        w = 63 - i
        acc4 = _dbl4_ext((acc3[0], acc3[1], acc3[2]))
        digit = lax.dynamic_slice_in_dim(c_nib, w, 1, axis=1)
        return _madd(acc4, _point_select(lanes[None, :] == digit, tb), need_t=True)

    one1 = jnp.broadcast_to(jnp.asarray(fe.ONE, dtype=jnp.int32), (1, fe.N_LIMBS))
    zero1 = jnp.zeros_like(one1)
    cb = lax.fori_loop(0, 64, cb_body, (zero1, one1, one1, zero1))

    total = _add_ext(t_point, cb, need_t=True)
    # Cofactor-clear the COMBINED sum: three doublings annihilate every
    # 8-torsion component — from R *and* A alike — so acceptance is the
    # deterministic cofactored relation [8]([c]B + Σ[z]R' + Σ[m]A') == O
    # regardless of how the weights reduced mod L.
    p3 = total[:3]
    for _ in range(3):
        p3 = _dbl(p3, need_t=False)
    sx, sy, sz = p3
    # Projective identity: X == 0 and Y == Z.
    return (fe.is_zero(sx) & fe.eq(sy, sz))[0]


@functools.lru_cache(maxsize=None)
def make_rlc_fn(jit: bool = True):
    return jax.jit(rlc_kernel) if jit else rlc_kernel


# ------------------------------------------------------------- host packer


def _nibbles(x: int) -> np.ndarray:
    return np.array([(x >> (4 * i)) & 0xF for i in range(64)], dtype=np.int32)


class Ed25519BatchHost:
    """Parses/packs (pubkey, digest, signature) triples for the kernel.

    Bucketed padding: batches are padded up to the next size in ``buckets``
    so the jitted kernel sees only a handful of static shapes.

    Packing runs through the native C++ runtime
    (:mod:`hyperdrive_tpu.native`) when available — point decompression is
    one field exponentiation per point and dominates the host cost — with
    the pure-Python loop as the always-available fallback (``HD_NO_NATIVE=1``
    forces it). Both paths are differentially tested to produce identical
    tensors and masks.
    """

    def __init__(self, buckets=(64, 256, 1024, 4096), use_native: bool = True):
        self.buckets = tuple(sorted(buckets))
        self._native = None
        if use_native and not os.environ.get("HD_NO_NATIVE"):
            try:
                from hyperdrive_tpu.native import NativePacker

                self._native = NativePacker()
            except RuntimeError as e:
                # Toolchain missing / build failed: fall back to the pure-
                # Python loop, but say so — it is ~100x slower and would
                # otherwise silently eat the throughput target.
                import warnings

                warnings.warn(
                    f"native packer unavailable ({e}); falling back to the "
                    "pure-Python packing path (expect ~100x slower host "
                    "packing). Set HD_NO_NATIVE=1 to silence this.",
                    RuntimeWarning,
                    stacklevel=2,
                )

    def bucket_for(self, n: int) -> int:
        return bucketing.bucket_for(n, self.buckets)

    def pack(self, items, _scan=None):
        """items: iterable of (pub32, digest, sig64).

        Returns (arrays, prevalid, n) where arrays feed verify_kernel,
        prevalid marks host-side rejections (bad point/range), and n is the
        true batch size before padding.
        """
        items = list(items)
        n = len(items)

        # Duplicate-HEAVY batches — e.g. one simulated chip carrying every
        # receiver's redundant verification load, where each broadcast's
        # triple repeats once per receiver — pack each DISTINCT triple
        # once and fan the packed rows out by index. Point decompression
        # dominates host packing cost (~45us/triple through the native
        # runtime), while a row copy is ~1us; identical inputs pack
        # identically, so verdicts are unchanged. Majority-duplicate
        # threshold: for lightly-duplicated batches the extra bucket-sized
        # allocation + full-row copies cost more than the few repacks they
        # save. ``_scan``: a precomputed (uniq, inv) from the caller's own
        # :func:`_dedup_scan`, so the verify path scans each chunk once.
        uniq, inv = _scan if _scan is not None else _dedup_scan(items)
        if n and 2 * len(uniq) <= n:
            arrays_u, prevalid_u, nu = self.pack(uniq)
            bsz = self.bucket_for(max(n, 1))
            out = []
            for a in arrays_u:
                o = np.zeros((bsz,) + a.shape[1:], dtype=a.dtype)
                o[:n] = a[:nu][inv]
                out.append(o)
            prevalid = np.zeros(bsz, dtype=bool)
            prevalid[:n] = prevalid_u[:nu][inv]
            return tuple(out), prevalid, n

        bsz = self.bucket_for(max(n, 1))

        ax = np.zeros((bsz, fe.N_LIMBS), dtype=np.int32)
        ay = np.zeros_like(ax)
        at = np.zeros_like(ax)
        rx = np.zeros_like(ax)
        ry = np.zeros_like(ax)
        s_nib = np.zeros((bsz, 64), dtype=np.int32)
        k_nib = np.zeros((bsz, 64), dtype=np.int32)
        prevalid = np.zeros(bsz, dtype=bool)

        if self._native is not None:
            prevalid[:n] = self._native.pack_into(
                items, ax, ay, at, rx, ry, s_nib, k_nib
            )
            return (ax, ay, at, rx, ry, s_nib, k_nib), prevalid, n

        for i, (pub, digest, sig) in enumerate(items):
            if len(pub) != 32 or len(sig) != 64:
                continue
            a_pt = host_ed.point_decompress(pub)
            if a_pt is None:
                continue
            r_pt = host_ed.point_decompress(sig[:32])
            if r_pt is None:
                continue
            s = int.from_bytes(sig[32:], "little")
            if s >= host_ed.L:
                continue
            k = host_ed.challenge_scalar(sig[:32], pub, digest)
            # Negate A (x -> p - x): the kernel computes [s]B + [k](-A).
            nax = (P - a_pt[0]) % P
            nay = a_pt[1]
            ax[i] = fe.to_limbs(nax)
            ay[i] = fe.to_limbs(nay)
            at[i] = fe.to_limbs((nax * nay) % P)
            rx[i] = fe.to_limbs(r_pt[0])
            ry[i] = fe.to_limbs(r_pt[1])
            s_nib[i] = _nibbles(s)
            k_nib[i] = _nibbles(k)
            prevalid[i] = True

        return (ax, ay, at, rx, ry, s_nib, k_nib), prevalid, n


def _nibbles_from_rows(rows: np.ndarray) -> np.ndarray:
    """[B, 32] uint8 little-endian scalars -> [B, 64] int32 base-16 digits."""
    out = np.empty((rows.shape[0], 64), dtype=np.int32)
    out[:, 0::2] = rows & 0xF
    out[:, 1::2] = rows >> 4
    return out


def _ints_from_nibbles(nib: np.ndarray) -> list[int]:
    """[B, 64] int32 nibbles -> per-row little-endian integers."""
    rows = (nib[:, 0::2] | (nib[:, 1::2] << 4)).astype(np.uint8).tobytes()
    return [
        int.from_bytes(rows[i * 32 : (i + 1) * 32], "little")
        for i in range(nib.shape[0])
    ]


def rlc_scalars(s_nib, k_nib, prevalid, binder: bytes):
    """Host half of the RLC equation: derive the per-lane random weights
    and the combined scalars the kernel consumes.

    ``binder`` must commit to the whole batch content (pubs, digests,
    signatures) BEFORE the weights are derived — Fiat-Shamir style — so a
    signer cannot craft signatures that cancel under known weights.
    Returns (m_nib [B,64], z_nib [B,64], c_nib [1,64]); invalid lanes get
    zero digits and contribute the identity on device.
    """
    import hashlib as _hl

    bsz = prevalid.shape[0]
    seed = _hl.sha256(b"hd-rlc-v3" + binder).digest()
    s_ints = _ints_from_nibbles(s_nib)
    k_ints = _ints_from_nibbles(k_nib)
    L = host_ed.L
    m_rows = np.zeros((bsz, 32), dtype=np.uint8)
    z_rows = np.zeros((bsz, 32), dtype=np.uint8)
    c = 0
    for i in range(bsz):
        if not prevalid[i]:
            continue
        # Plain 128-bit weights: torsion is cleared deterministically by
        # the kernel's final cofactor doublings (see rlc_kernel), not by
        # weight structure — (z*k) mod L wouldn't stay a multiple of 8
        # anyway, so weight-side clearing could only ever cover R.
        zi = int.from_bytes(
            _hl.sha512(seed + i.to_bytes(4, "little")).digest()[:16],
            "little",
        )
        m_rows[i] = np.frombuffer(
            ((zi * k_ints[i]) % L).to_bytes(32, "little"), dtype=np.uint8
        )
        z_rows[i] = np.frombuffer(zi.to_bytes(32, "little"), dtype=np.uint8)
        c = (c + zi * s_ints[i]) % L
    c_rows = np.frombuffer(c.to_bytes(32, "little"), dtype=np.uint8)
    return (
        _nibbles_from_rows(m_rows),
        _nibbles_from_rows(z_rows),
        _nibbles_from_rows(c_rows[None, :]),
    )


def _dedup_scan(items):
    """One pass over (pub, digest, sig) triples: returns (uniq, inv)
    with items[i] == uniq[inv[i]]. Shared by the packer and the
    verifier's device-expansion path so a chunk is hash-scanned once."""
    index: dict = {}
    uniq: list = []
    inv = np.empty(len(items), dtype=np.int32)
    for i, it in enumerate(items):
        j = index.get(it)
        if j is None:
            j = index[it] = len(uniq)
            uniq.append(it)
        inv[i] = j
    return uniq, inv


@functools.lru_cache(maxsize=None)
def _expand_verify_jit(inner):
    """Jitted gather-then-verify: the kernel receives each DISTINCT
    signature's packed rows once plus an expansion index, gathers the
    full redundant batch on device, and runs the complete ladder on every
    lane. Duplicate-heavy batches (one chip carrying every receiver's
    redundant load) then transfer ~1% of the bytes — packed limb rows are
    ~930 B/lane and the tunnel's bandwidth, not the ladder, was the
    bottleneck — while the device still performs the full per-lane
    verification work."""

    @jax.jit
    def run(ax, ay, at, rx, ry, s_nib, k_nib, inv):
        return inner(*(a[inv] for a in (ax, ay, at, rx, ry, s_nib, k_nib)))

    return run


@functools.lru_cache(maxsize=None)
def _pallas_padded_verify(block: int):
    """Identity-stable (cached) padding wrapper around ``verify_pallas``
    for one block size — consumers embed it in larger jits (the fused
    vote-grid kernel), whose compile caches key on callable identity."""
    from hyperdrive_tpu.ops.ed25519_pallas import verify_pallas

    return functools.partial(verify_pallas, block=block)


class TpuBatchVerifier:
    """Drop-in Verifier (see :mod:`hyperdrive_tpu.verifier`) that batches a
    whole mq drain window into one device launch.

    ``rlc=True`` verifies each window through the random-linear-combination
    kernel first — ONE Pippenger MSM over the whole chunk
    (:mod:`hyperdrive_tpu.ops.msm`) — falling back to the per-signature
    kernel when the combined check fails, to identify the culprit lanes
    (and for strict cofactorless semantics; see PARITY.md). The default
    ``rlc="auto"`` flips the fast path on exactly where the paired
    medians justify it (BENCH_r07.json): the XLA backend with a
    production-size bucket ladder (top bucket >= 4096 lanes, where the
    MSM's per-lane op-count collapse dominates its fixed reduction
    cost). The Pallas ladder backend keeps rlc off — its per-signature
    kernel is already past 500k sigs/s on v5e and the MSM is not ported
    to Mosaic. ``HD_RLC=0``/``HD_RLC=1`` force-overrides the resolution
    either way.
    """

    def __init__(self, buckets=(64, 256, 1024, 4096), rlc="auto",
                 backend: str = "auto", obs=None):
        from hyperdrive_tpu.ops.ed25519_pallas import resolve_backend

        self.host = Ed25519BatchHost(buckets=buckets)
        self._fn = make_verify_fn(jit=True)
        self.backend = resolve_backend(backend)
        if rlc == "auto":
            env = os.environ.get("HD_RLC")
            if env is not None:
                rlc = env not in ("0", "")
            else:
                rlc = (
                    self.backend != "pallas"
                    and bucketing.launch_target(self.host.buckets) >= 4096
                )
        self.rlc = bool(rlc)
        self._rlc_fn = make_rlc_fn(jit=True) if self.rlc else None
        #: Digest of the last verified chunk's length-framed transcript
        #: (the RLC binder) — the batch-verify binding that
        #: :mod:`hyperdrive_tpu.certificates` folds into emitted quorum
        #: certificates. b"" until the first RLC chunk verifies.
        self.last_transcript = b""
        #: Epoch-keyed pubkey-table generation (epochs.py). When nonzero
        #: it is framed into the RLC binder — and therefore into
        #: :attr:`last_transcript` — so a certificate minted off a queued
        #: launch commits to WHICH validator-set generation verified its
        #: quorum. The DeviceWorkQueue's drain calls
        #: :meth:`set_generation` before each coalesced launch; windows
        #: from different generations never share a batch (queue.py
        #: groups by (launcher, generation)).
        self.generation = 0
        #: How many windows fell back to the per-signature kernel.
        self.rlc_fallbacks = 0
        #: Flight-recorder handle (obs/recorder.py; NULL_BOUND = off).
        #: The documented-slower ``rlc=True`` path reports per-chunk
        #: verdicts and the running fallback count through this seam
        #: instead of a silent counter — an observed run shows WHERE the
        #: second launches went, not just that some happened. The sim
        #: binds it when ``observe=True``; deployments pass a scoped
        #: handle.
        self.obs = obs if obs is not None else _OBS_NULL_BOUND
        # Kernel backend (resolved above, before the rlc="auto" decision
        # that depends on it): the Pallas ladder (7.5x the XLA kernel on
        # v5e — 535.1k vs 70.9k sigs/s in bench.py) on real TPU backends,
        # the XLA kernel elsewhere (the Mosaic interpreter is far too
        # slow for production windows; CPU tests run the XLA kernel).

    def _device_verify(self, arrays):
        dev_in = [jnp.asarray(a) for a in arrays]
        if self.backend == "pallas":
            return self._pallas_verify(dev_in[0].shape[0])(*dev_in)
        return self._fn(*dev_in)

    @staticmethod
    def _pallas_block(batch: int) -> int:
        """Small buckets keep a matching block so a 64-signature window is
        not padded to 256 lanes (4x the ladder work on the latency-
        sensitive windows) — but never below 128: sub-128-lane blocks are
        under the TPU tile width and outside the measured sweep, so a
        64-lane bucket runs one 128-lane block with verify_pallas's
        padding absorbing the tail."""
        from hyperdrive_tpu.ops.ed25519_pallas import _BLOCK

        return min(_BLOCK, max(batch, 128))

    def _pallas_verify(self, batch: int):
        return _pallas_padded_verify(self._pallas_block(batch))

    def fused_inner(self, batch: int):
        """The traceable batch-verify callable ((ax..k_nib) -> bool[B]) for
        composition inside a larger jit — the vote grid's fused
        verify+scatter+tally launch embeds it so a settle pass pays one
        device round trip for signatures AND quorum counts."""
        if self.backend == "pallas":
            return self._pallas_verify(batch)
        return verify_kernel

    def set_generation(self, generation: int) -> None:
        """Install the epoch table generation for subsequent launches.

        Called by the async queue's drain right before a coalesced
        launch whose commands carry a nonzero generation tag; blocking
        callers may set :attr:`generation` directly at rotation time.
        The ladder itself is table-free (pubkeys ride in each lane), so
        the swap is pure transcript binding — O(1), no device traffic."""
        self.generation = int(generation)

    def warmup(self) -> None:
        """Compile the kernel for every bucket shape up front (XLA compiles
        once per static shape; ~20-40s each on a cold TPU, far less for
        the Pallas backend) so steady-state runs and benchmarks never bill
        a compile mid-flight."""
        for b in self.host.buckets:
            z = jnp.zeros((b, fe.N_LIMBS), dtype=jnp.int32)
            zn = jnp.zeros((b, 64), dtype=jnp.int32)
            device_fetch(self._device_verify((z, z, z, z, z, zn, zn)),
                         why="warmup: block until the compile lands")
            if self._rlc_fn is not None:
                zn1 = jnp.zeros((1, 64), dtype=jnp.int32)
                device_fetch(self._rlc_fn(z, z, z, z, z, zn, zn, zn1),
                             why="warmup: block until the compile lands")


    def verify_signatures(self, items) -> np.ndarray:
        """items: list of (pub, digest, sig); returns bool[n].

        Windows beyond the largest bucket are chunked at that size: every
        launch reuses one of the precompiled static shapes (no fresh XLA
        compile for e.g. a 65k aggregated burst window), and the chunks are
        all enqueued before the first result is materialized so the device
        pipeline stays full. With RLC enabled, chunks whose combined check
        fails get a second, per-signature launch to localize the forgeries.
        """
        items = list(items)
        if not items:
            return np.zeros(0, dtype=bool)
        cap = bucketing.launch_target(self.host.buckets)
        pending = []
        for lo in range(0, len(items), cap):
            chunk = items[lo : lo + cap]
            scan = None
            if self._rlc_fn is None:
                scan = _dedup_scan(chunk)
                if 2 * len(scan[0]) <= len(chunk):
                    pending.append(self._verify_chunk_deduped(chunk, scan))
                    continue
            arrays, prevalid, n = self.host.pack(chunk, _scan=scan)
            if self.obs is not _OBS_NULL_BOUND:
                # Bucket-padding economics per launch: lanes requested
                # vs the static shape actually compiled — what the
                # padding bill costs this chunk (devtel aggregates the
                # same ratio across queue drains).
                lanes = int(arrays[0].shape[0])
                self.obs.emit("verify.occupancy.rows", -1, -1, n)
                self.obs.emit("verify.occupancy.lanes", -1, -1, lanes)
                self.obs.emit(
                    "verify.occupancy.pct", -1, -1,
                    int(round(100 * n / max(lanes, 1))),
                )
            if not prevalid.any():
                pending.append((None, None, prevalid, n))
                continue
            if self._rlc_fn is not None:
                # Length-framed so the byte stream parses uniquely: without
                # framing, batches with different (pub, digest, sig) splits
                # of the same bytes would share z weights, letting a signer
                # precompute weights for a colliding batch.
                binder = b"".join(
                    len(p).to_bytes(2, "little")
                    + p
                    + len(d).to_bytes(4, "little")
                    + d
                    + len(s).to_bytes(2, "little")
                    + s
                    for p, d, s in chunk
                )
                if self.generation:
                    # Generation frame first: the z weights and the
                    # bound transcript both commit to the pubkey-table
                    # generation the launch verified under, so an
                    # epoch-N certificate can never replay an
                    # epoch-N+1 launch's transcript (or vice versa).
                    binder = (
                        b"hd-gen"
                        + int(self.generation).to_bytes(8, "little")
                        + binder
                    )
                m_nib, z_nib, c_nib = rlc_scalars(
                    arrays[5], arrays[6], prevalid, binder
                )
                import hashlib as _hl

                self.last_transcript = _hl.sha256(binder).digest()
                if self.obs is not _OBS_NULL_BOUND:
                    from hyperdrive_tpu.ops.msm import (
                        ED25519_FULL_WINDOWS,
                        ED25519_HALF_WINDOWS,
                        msm_plan,
                    )

                    plan = msm_plan(
                        arrays[0].shape[0],
                        ED25519_FULL_WINDOWS + ED25519_HALF_WINDOWS,
                    )
                    occ = (
                        np.count_nonzero(m_nib) + np.count_nonzero(z_nib)
                    ) / max(m_nib.size + z_nib.size, 1)
                    self.obs.emit(
                        "verify.msm.windows", -1, -1, plan["windows"]
                    )
                    self.obs.emit(
                        "verify.msm.occupancy", -1, -1, round(occ, 4)
                    )
                    self.obs.emit(
                        "verify.msm.depth", -1, -1,
                        plan["reduction_depth"],
                    )
                dev = self._rlc_fn(
                    *(jnp.asarray(a) for a in arrays[:5]),
                    jnp.asarray(m_nib),
                    jnp.asarray(z_nib),
                    jnp.asarray(c_nib),
                )
            else:
                dev = self._device_verify(arrays)
            pending.append((dev, arrays, prevalid, n))

        # Multi-chunk batches fetch ONE concatenated mask: each separate
        # np.asarray is its own ~100ms round trip over a tunnel-attached
        # chip, so a 131k redundant batch (8 chunks) would pay 8 RTTs for
        # what one transfer carries. (The RLC path keeps per-chunk fetches
        # — its combined-check scalar decides whether a second launch is
        # even needed.)
        if self._rlc_fn is None:
            devs = [d for d, _, _, _ in pending if d is not None]
            if len(devs) > 1:
                big = device_fetch(jnp.concatenate(devs),
                                   why="one RTT for the whole batch mask")
                off = 0
                out = []
                for dev, _, prevalid, n in pending:
                    if dev is None:
                        out.append(prevalid[:n].copy())
                        continue
                    width = dev.shape[0]
                    out.append(
                        (big[off : off + width] & prevalid)[:n]
                    )
                    off += width
                return np.concatenate(out)
        out = []
        for dev, arrays, prevalid, n in pending:
            if dev is None:
                out.append(prevalid[:n].copy())  # all lanes malformed
            elif self._rlc_fn is not None:
                obs_on = self.obs is not _OBS_NULL_BOUND
                if bool(device_fetch(dev, why="RLC verdict gates the "
                                              "fallback launch")):
                    if obs_on:
                        self.obs.emit("verify.rlc.verdict", -1, -1, "ok")
                    out.append(prevalid[:n].copy())
                else:
                    self.rlc_fallbacks += 1
                    if obs_on:
                        self.obs.emit(
                            "verify.rlc.verdict", -1, -1, "fallback"
                        )
                        self.obs.emit(
                            "verify.rlc.fallbacks", -1, -1,
                            self.rlc_fallbacks,
                        )
                    mask = device_fetch(self._device_verify(arrays),
                                        why="per-signature fallback mask")
                    out.append((mask & prevalid)[:n])
            else:
                out.append((device_fetch(dev, why="chunk verify mask")
                            & prevalid)[:n])
        return out[0] if len(out) == 1 else np.concatenate(out)

    def _verify_chunk_deduped(self, chunk, scan):
        """Duplicate-heavy chunk path: pack each distinct triple once,
        ship the unique rows plus an expansion index, gather+verify on
        device (see :func:`_expand_verify_jit`). ``scan``: the caller's
        (uniq, inv) from :func:`_dedup_scan`. Returns a ``pending``
        entry."""
        uniq, inv = scan
        arrays_u, prevalid_u, nu = self.host.pack(uniq)
        prevalid = np.zeros(
            self.host.bucket_for(len(chunk)), dtype=bool
        )
        prevalid[: len(chunk)] = prevalid_u[inv]
        if not prevalid.any():
            # Every lane malformed (e.g. a flood of one unparseable
            # triple): rejection is already decided host-side — skip the
            # launch and its ~100ms mask round trip.
            return (None, None, prevalid, len(chunk))
        bn = prevalid.shape[0]
        inv_p = np.zeros(bn, dtype=np.int32)
        inv_p[: len(chunk)] = inv
        dev = _expand_verify_jit(self.fused_inner(bn))(
            *(jnp.asarray(a) for a in arrays_u), jnp.asarray(inv_p)
        )
        return (dev, None, prevalid, len(chunk))

    def verify_batch(self, window):
        """Verifier-protocol entry: messages with detached signatures.

        Stays on the object path deliberately: one broadcast object fans
        out to every replica's window, so ``m.digest()`` memoization makes
        the digest a once-per-broadcast cost — columnarizing here
        (``MessageBlock``) would recompute it per delivery.
        """
        # Signatures pass through unchanged: the packer (native or Python)
        # length-checks and leaves wrong-length lanes prevalid=False, so
        # rejection is deterministic — never substitute zeros, which could
        # verify under an adversarial small-order pubkey.
        items = [(msg.sender, msg.digest(), msg.signature) for msg in window]
        # Messages with no signature at all fail immediately (parity with
        # HostVerifier), but still occupy a lane for shape stability.
        unsigned = np.array([not msg.signature for msg in window], dtype=bool)
        ok = self.verify_signatures(items)
        return list(ok & ~unsigned)
