"""Ops kernel smokes: differential parity for the device crypto path.

Usage::

    python -m hyperdrive_tpu.ops msm-parity [--n N] [--windows W]
        [--seed S] [--rlc]
    python -m hyperdrive_tpu.ops bls-parity [--n N] [--seed S]

``msm-parity`` drives :func:`hyperdrive_tpu.ops.msm.msm_kernel` against
the host curve reference (``crypto/ed25519.py`` scalar_mult/point_add)
on random points and scalars — the Pippenger bucketing, group combine,
and window Horner must land on the exact affine point the serial
reference computes, or exit 1. ``--rlc`` adds the end-to-end leg: real
signatures through ``TpuBatchVerifier(rlc=True)`` (whose rlc_kernel
drives two MSMs) versus the per-signature ladder, including a forged
lane to prove the culprit-isolation fallback masks identically.

``bls-parity`` is the same differential discipline for the BLS12-381
path (ISSUE 13): fp381 Montgomery products vs Python bigints, the
curve-parameterized G1 Pippenger MSM and the masked aggregation tree vs
the host reference in ``crypto/bls.py`` (identity rows, zero scalars,
and masked-out lanes included), and one end-to-end k-of-k aggregate
through the host pairing with a forged-message rejection.

Shapes stay tiny (the fori-loop kernels compile once regardless of
window count, so the compile bill is flat and the .jax_cache-warmed CI
run is seconds); HD_SANITIZE=1 in the environment arms the runtime
sanitizer exactly as the devsched parity smoke does.
"""

from __future__ import annotations

import argparse
import os
import random
import sys

# Standalone-CLI compile cache: tests get this from conftest.py; the CI
# smoke reuses the same .jax_cache checkout path so warmed runs skip the
# XLA compile entirely.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", ".jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "2.0")


def _host_affine(p):
    """Extended homogeneous (X, Y, Z, T) -> affine (x, y). The host
    curve ops keep Z != 1, so anything packed for the kernel (which
    assumes z = 1) or compared against it must normalize first."""
    from hyperdrive_tpu.crypto import ed25519 as hed

    x, y, z, _ = p
    zinv = pow(z, hed.P - 2, hed.P)
    return (x * zinv) % hed.P, (y * zinv) % hed.P


def _host_msm(points, scalars):
    """Serial reference: sum [s_i]P_i (affine inputs) via the host curve
    arithmetic; returns the affine sum."""
    from hyperdrive_tpu.crypto import ed25519 as hed

    acc = hed.IDENTITY
    for (x, y), s in zip(points, scalars):
        ext = (x, y, 1, x * y % hed.P)
        acc = hed.point_add(acc, hed.scalar_mult(s, ext))
    return _host_affine(acc)


def msm_parity(args) -> int:
    import numpy as np

    from hyperdrive_tpu.crypto import ed25519 as hed
    from hyperdrive_tpu.ops import fe25519 as fe
    from hyperdrive_tpu.ops.ed25519_jax import _recode_signed
    from hyperdrive_tpu.ops.msm import msm_kernel, msm_plan

    rng = random.Random(args.seed)
    n, windows = args.n, args.windows
    bits = 4 * windows

    points, scalars = [], []
    for _ in range(n):
        k = rng.randrange(1, hed.L)
        points.append(_host_affine(hed.scalar_mult(k, hed.BASE)))
        scalars.append(rng.randrange(0, min(1 << bits, 2**252)))

    px = np.stack([fe.to_limbs(p[0]) for p in points])
    py = np.stack([fe.to_limbs(p[1]) for p in points])
    pt = np.stack([fe.to_limbs(p[0] * p[1] % hed.P) for p in points])
    # One extra zero nibble absorbs the signed-recode carry out of the
    # top window (same reason rlc_kernel runs 33 windows for 128-bit z).
    nibs = np.array(
        [
            [(s >> (4 * w)) & 0xF for w in range(windows + 1)]
            for s in scalars
        ],
        dtype=np.int32,
    )
    digits = np.asarray(_recode_signed(nibs))

    sx, sy, sz, _ = msm_kernel(px, py, pt, digits)
    zi = pow(int(fe.from_limbs(np.asarray(sz))[0]), hed.P - 2, hed.P)
    got = (
        int(fe.from_limbs(np.asarray(sx))[0]) * zi % hed.P,
        int(fe.from_limbs(np.asarray(sy))[0]) * zi % hed.P,
    )
    want = _host_msm(points, scalars)
    plan = msm_plan(n, windows)
    ok = got == want
    print(
        f"{'ok' if ok else 'FAIL'} msm-kernel: n={n} windows={windows} "
        f"groups={plan['groups']}x{plan['group_size']} "
        f"depth={plan['reduction_depth']} "
        f"{'matches host reference' if ok else f'{got} != {want}'}"
    )
    return 0 if ok else 1


def rlc_parity(args) -> int:
    import hashlib

    import numpy as np

    from hyperdrive_tpu.crypto.keys import KeyPair
    from hyperdrive_tpu.ops.ed25519_jax import TpuBatchVerifier

    items = []
    for i in range(args.n):
        kp = KeyPair.deterministic(b"msm-parity-%d" % i)
        digest = hashlib.sha256(f"msg-{i}".encode()).digest()
        items.append((kp.public, digest, kp.sign_digest(digest)))
    # One forged lane — a WELL-FORMED signature over the wrong digest
    # (a mangled encoding would be caught by host prevalidation and
    # never reach the batch equation): the RLC combined check must fail
    # the chunk and the per-signature fallback must isolate exactly
    # this culprit.
    kp = KeyPair.deterministic(b"msm-parity-%d" % (args.n - 1))
    wrong = hashlib.sha256(b"msm-parity-forged").digest()
    items[-1] = (items[-1][0], items[-1][1], kp.sign_digest(wrong))

    buckets = (64,)
    ladder = TpuBatchVerifier(buckets=buckets, rlc=False)
    rlc = TpuBatchVerifier(buckets=buckets, rlc=True)
    m_ladder = np.asarray(ladder.verify_signatures(items))
    m_rlc = np.asarray(rlc.verify_signatures(items))
    ok = bool(
        (m_ladder == m_rlc).all()
        and m_ladder[:-1].all()
        and not m_ladder[-1]
        and rlc.rlc_fallbacks >= 1
        and len(rlc.last_transcript) == 32
    )
    print(
        f"{'ok' if ok else 'FAIL'} rlc-msm: n={len(items)} "
        f"masks {'==' if (m_ladder == m_rlc).all() else '!='} "
        f"fallbacks={rlc.rlc_fallbacks} "
        f"transcript={rlc.last_transcript.hex()[:16]}"
    )
    return 0 if ok else 1


def bls_parity(args) -> int:
    """Differential smoke for the BLS12-381 device path: fp381 field
    arithmetic vs Python ints, the curve-parameterized G1 MSM and the
    masked aggregation tree vs the host reference (crypto/bls.py), and
    one end-to-end aggregate certificate check through the pairing."""
    import numpy as np

    from hyperdrive_tpu.crypto import bls
    from hyperdrive_tpu.ops import fp381 as fp
    from hyperdrive_tpu.ops import g1 as g1k

    rng = random.Random(args.seed)
    n = args.n
    rc = 0

    # 1. Field: Montgomery mul against Python bigints, batched.
    xs = [rng.randrange(bls.P) for _ in range(n)]
    ys = [rng.randrange(bls.P) for _ in range(n)]
    got = fp.from_mont(
        fp.mul(np.stack([fp.to_mont(x) for x in xs]),
               np.stack([fp.to_mont(y) for y in ys]))
    )
    want = [x * y % bls.P for x, y in zip(xs, ys)]
    ok = list(got) == want
    print(f"{'ok' if ok else 'FAIL'} fp381-mul: {n} random products "
          f"{'match' if ok else 'MISMATCH'} Python ints")
    rc |= 0 if ok else 1

    # 2. Curve: Pippenger MSM over G1 vs serial host scalar-mults, with
    # an identity point and a zero scalar in the mix.
    scalars = [rng.randrange(bls.R_ORDER) for _ in range(n)]
    scalars[1] = 0
    points = [bls.g1_mul(bls.G1_GEN, rng.randrange(1, bls.R_ORDER))
              for _ in range(n)]
    points[0] = None  # identity row
    px, py, pz = g1k.pack_points(points)
    digits = g1k.recode_scalars(scalars)
    got = g1k.unpack_points(*g1k.g1_msm_kernel(px, py, pz, digits))
    if isinstance(got, list):  # kernel keeps a leading batch dim of 1
        got = got[0]
    want = None
    for pt, s in zip(points, scalars):
        want = bls.g1_add(want, bls.g1_mul(pt, s))
    ok = got == want
    print(f"{'ok' if ok else 'FAIL'} g1-msm: n={n} windows={g1k.G1_WINDOWS} "
          f"{'matches host reference' if ok else f'{got} != {want}'}")
    rc |= 0 if ok else 1

    # 3. Aggregation tree: masked fixed-width sum vs the host fold.
    mask = [rng.random() < 0.8 for _ in range(n)]
    got = g1k.aggregate_points(
        [p if m else None for p, m in zip(points, mask)]
    )
    want = bls.aggregate_signatures(
        [p for p, m in zip(points, mask) if m and p is not None]
    )
    ok = got == want
    print(f"{'ok' if ok else 'FAIL'} g1-aggregate: width={n} "
          f"mask={sum(mask)}/{n} "
          f"{'matches host fold' if ok else f'{got} != {want}'}")
    rc |= 0 if ok else 1

    # 4. End to end: sign one commit digest under k keys, aggregate on
    # device, verify through the host pairing (the one O(pairing) step
    # a light client pays per certificate).
    k = min(n, 5)
    kps = [bls.bls_keypair_from_identity(b"bls-parity-%d" % i)
           for i in range(k)]
    msg = b"bls-parity-commit"
    agg = g1k.aggregate_points([kp.sign(msg) for kp in kps])
    ok = bls.verify_aggregate_same_message([kp.pk for kp in kps], msg, agg)
    forged = bls.verify_aggregate_same_message(
        [kp.pk for kp in kps], b"bls-parity-forged", agg
    )
    ok = ok and not forged
    print(f"{'ok' if ok else 'FAIL'} bls-e2e: {k}-of-{k} device aggregate "
          f"{'verifies, forgery rejected' if ok else 'FAILED pairing check'}")
    rc |= 0 if ok else 1
    return rc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m hyperdrive_tpu.ops")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p = sub.add_parser(
        "msm-parity",
        help="Pippenger MSM vs host curve reference differential smoke",
    )
    p.add_argument("--n", type=int, default=37)
    p.add_argument(
        "--windows", type=int, default=16,
        help="4-bit scalar windows (scalar width = 4*windows bits)",
    )
    p.add_argument("--seed", type=int, default=7)
    p.add_argument(
        "--rlc", action="store_true",
        help="also run real signatures through the RLC-MSM verifier vs "
        "the per-signature ladder (adds the verify-kernel compile)",
    )
    p.set_defaults(fn=msm_parity, banner="msm")

    p = sub.add_parser(
        "bls-parity",
        help="BLS12-381 device path (fp381, G1 MSM, aggregation tree) "
        "vs the host reference, plus one end-to-end pairing check",
    )
    p.add_argument("--n", type=int, default=16)
    p.add_argument("--seed", type=int, default=7)
    p.set_defaults(fn=bls_parity, banner="bls")

    args = ap.parse_args(argv)
    rc = args.fn(args)
    if args.banner == "msm" and args.rlc:
        rc = rlc_parity(args) or rc
    if rc == 0:
        print(f"{args.banner} parity ok")
    else:
        print(f"{args.banner} parity FAILED", file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
