"""Device-resident vote grids: the consensus tally state lives on device.

This is the integration the north star describes — "quorum tallies become
masked reductions fused behind the verification mask". The reference scans
Go maps per received vote (reference: process/process.go:487-491, 574-579,
626-631, 696-701); :mod:`hyperdrive_tpu.ops.tally` already expresses one
window's counts as masked reductions; this module makes the *accumulated*
per-replica vote state a persistent device tensor so every settle pass is
one scatter + one fused reduction for the whole network:

- ``values [n, 2, R, V, 8]`` int32 — per replica, per vote plane
  (0=prevote, 1=precommit), per round slot, per validator, the 32-byte
  vote value as eight little-endian words;
- ``present [n, 2, R, V]`` bool — vote exists, passed signature
  verification, and survived the host automaton's duplicate/equivocation
  filters (only *accepted* inserts are scattered, so the grid is exactly
  the device image of ``State.prevote_logs``/``precommit_logs``).

Each :meth:`VoteGrid.update_and_tally` call scatters one superstep's
accepted votes for ALL replicas and returns every per-round count the
rule cascade needs (L28/L34/L36/L44/L47/L49) — the Process then consumes
these counts instead of rescanning its logs (see ``Process.ingest``'s
tally source). Buffers are donated, so the grids update in place on
device; the host only ever sees the small ``[n, 2, R]`` count tensors.

Capacity: round slots cover rounds ``0..R-1`` of each replica's current
height. Rounds beyond the window (rare — they require R consecutive
failed rounds) simply aren't covered; the cascade falls back to the host
counters for those rounds, which remain authoritative and are what the
differential tests compare against.
"""

from __future__ import annotations

from collections.abc import Mapping

import numpy as np

import jax
import jax.numpy as jnp

from hyperdrive_tpu.ops import bucketing
from hyperdrive_tpu.types import NIL_VALUE

__all__ = [
    "PREVOTE_PLANE",
    "PRECOMMIT_PLANE",
    "VoteGrid",
    "TallyView",
    "CheckedTallyView",
]

PREVOTE_PLANE = 0
PRECOMMIT_PLANE = 1


class TallyView:
    """One replica's slice of a :class:`VoteGrid` launch result — the
    object ``Process.ingest_cascade`` consults for quorum thresholds.

    The view answers a count query ONLY when the launch provably tallied
    that exact query; otherwise it returns None and the Process falls back
    to its host counters. Declines happen for: rounds outside the slot
    window, rounds marked dirty (a vote couldn't be scattered — unknown
    sender), target values the launch didn't compare against, and any
    query after the replica's height moved past :attr:`height`.
    """

    __slots__ = ("rep", "height", "counts", "R", "targets",
                 "l28_round", "l28_value", "dirty")

    def __init__(self, rep: int, height: int, counts: Mapping, r_slots: int,
                 targets: dict, l28_round: int, l28_value: bytes,
                 dirty=frozenset()):
        self.rep = rep
        self.height = height
        self.counts = counts
        self.R = r_slots
        #: round -> the 32-byte proposal value the launch used as that
        #: round's matching target.
        self.targets = targets
        self.l28_round = l28_round
        self.l28_value = l28_value
        self.dirty = dirty

    def _covered(self, plane: int, rnd: int) -> bool:
        return 0 <= rnd < self.R and (plane, rnd) not in self.dirty

    def _matching(self, plane: int, rnd: int, value: bytes):
        if not self._covered(plane, rnd):
            return None
        if self.targets.get(rnd) == value:
            return int(self.counts["matching"][self.rep, plane, rnd])
        if value == NIL_VALUE:
            return int(self.counts["nil"][self.rep, plane, rnd])
        return None

    def prevotes_for(self, rnd: int, value: bytes):
        c = self._matching(PREVOTE_PLANE, rnd, value)
        if c is not None:
            return c
        # The L28 cross-round lane: prevotes at the current proposal's
        # valid_round compared against the current proposal's value.
        if (
            rnd == self.l28_round
            and value == self.l28_value
            and self._covered(PREVOTE_PLANE, rnd)
        ):
            return int(self.counts["l28"][self.rep])
        return None

    def precommits_for(self, rnd: int, value: bytes):
        return self._matching(PRECOMMIT_PLANE, rnd, value)

    def prevote_total(self, rnd: int):
        if not self._covered(PREVOTE_PLANE, rnd):
            return None
        return int(self.counts["total"][self.rep, PREVOTE_PLANE, rnd])

    def precommit_total(self, rnd: int):
        if not self._covered(PRECOMMIT_PLANE, rnd):
            return None
        return int(self.counts["total"][self.rep, PRECOMMIT_PLANE, rnd])


def _kernel(values, present, reset, idx, words, valid,
            targets, target_valid, l28_slot, l28_target, f,
            axis_name=None):
    """One fused scatter + tally step.

    values [n,2,R,V,8] i32 (donated), present [n,2,R,V] bool (donated),
    reset [n] bool — zero a replica's planes before scattering (height
    advanced), idx [k,4] i32 rows (replica, plane, slot, validator),
    words [k,8] i32 vote values, valid [k] bool (padding mask),
    targets [n,R,8] i32 per-round proposal values, target_valid [n,R],
    l28_slot [n] i32 (valid-round slot for the L28 cross-round count, or
    -1), l28_target [n,8] i32 (the *current* round's proposal value),
    f [n] i32.

    Sharded mode (``axis_name`` set, running under ``shard_map``): the
    validator axis V is the local shard; scatter rows carry GLOBAL
    validator indices, each shard claims only its own range, and the
    partial counts combine with one ``psum`` over the axis — the
    vote-exchange collective rides the ICI ring, the host never sees
    per-validator state.
    """
    n, _, R, V, _ = values.shape

    if axis_name is not None:
        offset = jax.lax.axis_index(axis_name).astype(jnp.int32) * V
        vloc = idx[:, 3] - offset
        valid = valid & (vloc >= 0) & (vloc < V)
        idx = jnp.concatenate([idx[:, :3], vloc[:, None]], axis=1)

    keep = ~reset[:, None, None, None]
    present = present & keep

    flat_vals = values.reshape(-1, 8)
    flat_pres = present.reshape(-1)
    lane = ((idx[:, 0] * 2 + idx[:, 1]) * R + idx[:, 2]) * V + idx[:, 3]
    lane = jnp.where(valid, lane, flat_pres.shape[0])  # OOB lanes drop
    flat_vals = flat_vals.at[lane].set(words, mode="drop")
    flat_pres = flat_pres.at[lane].set(True, mode="drop")
    values = flat_vals.reshape(n, 2, R, V, 8)
    present = flat_pres.reshape(n, 2, R, V)

    pres_i = present.astype(jnp.int32)
    eq_target = (
        jnp.all(values == targets[:, None, :, None, :], axis=-1)
        & target_valid[:, None, :, None]
    )
    eq_nil = jnp.all(values == 0, axis=-1)  # NIL_VALUE is 32 zero bytes
    matching = jnp.sum(eq_target & present, axis=-1, dtype=jnp.int32)
    nil = jnp.sum(eq_nil & present, axis=-1, dtype=jnp.int32)
    total = jnp.sum(pres_i, axis=-1, dtype=jnp.int32)

    # L28 cross-round count: prevotes at the CURRENT proposal's valid_round
    # matching the CURRENT proposal's value (the per-round targets above
    # compare round r's votes against round r's own proposal).
    slot_ok = jnp.arange(R)[None, :] == l28_slot[:, None]  # [n, R]
    eq28 = (
        jnp.all(values[:, PREVOTE_PLANE] == l28_target[:, None, None, :],
                axis=-1)
        & present[:, PREVOTE_PLANE]
        & slot_ok[:, :, None]
    )
    l28 = jnp.sum(eq28, axis=(1, 2), dtype=jnp.int32)  # [n]

    if axis_name is not None:
        matching = jax.lax.psum(matching, axis_name)
        nil = jax.lax.psum(nil, axis_name)
        total = jax.lax.psum(total, axis_name)
        l28 = jax.lax.psum(l28, axis_name)

    q = (2 * f + 1)[:, None, None]
    n_ = matching.shape[0]
    # ONE packed int32 output instead of eight arrays: over a tunnel-
    # attached device every host fetch is a full round trip, and eight
    # per-launch fetches dominated the launch cost (~0.1s each). Layout:
    # [n, 2, R, 6] = (matching, nil, total, quorum_matching, quorum_nil,
    # quorum_any) flattened, then the two L28 lanes appended per replica.
    six = jnp.stack(
        [
            matching,
            nil,
            total,
            (matching >= q).astype(jnp.int32),
            (nil >= q).astype(jnp.int32),
            (total >= q).astype(jnp.int32),
        ],
        axis=-1,
    )  # [n, 2, R, 6]
    l28_pair = jnp.stack(
        [l28, (l28 >= 2 * f + 1).astype(jnp.int32)], axis=-1
    )  # [n, 2]
    packed = jnp.concatenate(
        [six.reshape(n_, -1), l28_pair], axis=1
    )  # [n, 2*R*6 + 2]
    return values, present, packed


class CheckedTallyView:
    """Differential instrumentation: wraps a :class:`TallyView` and
    cross-checks every device-sourced count against the host counters
    before returning it — a mismatch raises. Tests and the verify drive
    install it (``Simulation(tally_check=CheckedTallyView)``) to certify
    that device-tally runs are count-for-count identical to host runs.
    ``hits`` counts answered queries so a test can assert the device path
    was actually exercised rather than silently falling back."""

    __slots__ = ("view", "proc", "height", "hits")

    def __init__(self, view: TallyView, proc):
        self.view = view
        self.proc = proc
        self.height = view.height
        self.hits = 0

    def _check(self, device, host, what):
        if device is None:
            return None
        self.hits += 1
        if device != host:
            raise AssertionError(
                f"device {what} count {device} != host {host} "
                f"(replica {self.view.rep}, height {self.height})"
            )
        return device

    def prevotes_for(self, rnd, value):
        return self._check(
            self.view.prevotes_for(rnd, value),
            self.proc.state.count_prevotes_for(rnd, value),
            f"prevote[r={rnd}]",
        )

    def precommits_for(self, rnd, value):
        return self._check(
            self.view.precommits_for(rnd, value),
            self.proc.state.count_precommits_for(rnd, value),
            f"precommit[r={rnd}]",
        )

    def prevote_total(self, rnd):
        return self._check(
            self.view.prevote_total(rnd),
            len(self.proc.state.prevote_logs.get(rnd, {})),
            f"prevote_total[r={rnd}]",
        )

    def precommit_total(self, rnd):
        return self._check(
            self.view.precommit_total(rnd),
            len(self.proc.state.precommit_logs.get(rnd, {})),
            f"precommit_total[r={rnd}]",
        )


class VoteGrid:
    """Persistent device grids for ``n`` replicas × ``validators`` senders.

    One instance serves a whole simulated network (or, in a deployment,
    one chip's replica set). Call :meth:`update_and_tally` once per settle
    pass; it returns a :class:`LazyCounts` mapping of per-(replica, plane,
    slot) counts whose single host fetch is deferred to first value access.
    """

    def __init__(self, n_replicas: int, n_validators: int, r_slots: int = 8,
                 buckets: tuple = (256, 1024, 4096, 16384),
                 mesh=None, val_axis: str = "val"):
        self.n = n_replicas
        self.V = n_validators
        self.R = r_slots
        self.buckets = tuple(sorted(buckets))
        shape_v = (n_replicas, 2, r_slots, n_validators, 8)
        shape_p = (n_replicas, 2, r_slots, n_validators)
        if mesh is None:
            self._values = jnp.zeros(shape_v, dtype=jnp.int32)
            self._present = jnp.zeros(shape_p, dtype=bool)
            self._fn = jax.jit(_kernel, donate_argnums=(0, 1))
        else:
            # Multi-chip: the validator axis shards over `val_axis`; each
            # chip owns its validators' grid lanes, scatter rows route by
            # global index, counts psum over the ICI ring. Everything else
            # (reset masks, targets, counts) is replicated — it is tiny.
            from functools import partial

            from jax.sharding import NamedSharding, PartitionSpec as P

            d = mesh.shape[val_axis]
            if n_validators % d:
                raise ValueError(
                    f"validators ({n_validators}) must divide evenly over "
                    f"the '{val_axis}' axis ({d} devices)"
                )
            spec_v = P(None, None, None, val_axis, None)
            spec_p = P(None, None, None, val_axis)
            self._values = jax.device_put(
                jnp.zeros(shape_v, dtype=jnp.int32),
                NamedSharding(mesh, spec_v),
            )
            self._present = jax.device_put(
                jnp.zeros(shape_p, dtype=bool), NamedSharding(mesh, spec_p)
            )
            rep = P()
            sharded = jax.shard_map(
                partial(_kernel, axis_name=val_axis),
                mesh=mesh,
                in_specs=(spec_v, spec_p, rep, rep, rep, rep, rep, rep,
                          rep, rep, rep),
                out_specs=(spec_v, spec_p, rep),
                check_vma=False,
            )
            self._fn = jax.jit(sharded, donate_argnums=(0, 1))

    def bucket_for(self, k: int) -> int:
        return bucketing.bucket_for(k, self.buckets)

    def update_and_tally(self, idx, words, reset, targets, target_valid,
                         l28_slot, l28_target, f):
        """Scatter accepted votes, reduce, return counts as numpy.

        idx [k,4] int32 (replica, plane, slot, validator) — the host
        automaton guarantees at most one row per lane per call (duplicate
        and equivocating votes are rejected before scatter); words [k,8]
        int32; remaining args as in :func:`_kernel` (numpy, host-built
        per settle). Returns a :class:`LazyCounts` (dict-like; the device
        fetch happens on first key access).
        """
        k = len(idx)
        b = self.bucket_for(max(k, 1))
        pad_idx = np.zeros((b, 4), dtype=np.int32)
        pad_words = np.zeros((b, 8), dtype=np.int32)
        valid = np.zeros(b, dtype=bool)
        if k:
            pad_idx[:k] = idx
            pad_words[:k] = words
            valid[:k] = True
        self._values, self._present, packed = self._fn(
            self._values,
            self._present,
            jnp.asarray(reset),
            jnp.asarray(pad_idx),
            jnp.asarray(pad_words),
            jnp.asarray(valid),
            jnp.asarray(targets),
            jnp.asarray(target_valid),
            jnp.asarray(l28_slot),
            jnp.asarray(l28_target),
            jnp.asarray(f),
        )
        # One DEFERRED host fetch for everything (see the packing note in
        # _kernel): the counts stay on device until a rule actually reads
        # one. The fetch is skipped only when EVERY view over this launch
        # stays unconsulted (once-flags and step guards short-circuited in
        # all cascades) — common for small networks' quiet settles,
        # measured neutral at n=256 where some replica nearly always
        # queries. The packed array is an independent output, so the next
        # launch's donation of the grid buffers never invalidates it.
        return LazyCounts(packed, self.n, self.R)


class LazyCounts(Mapping):
    """Mapping over one packed count tensor, fetched on first VALUE access.
    The key set is static, so shape probes (iteration, membership, len)
    never trigger the device round trip."""

    __slots__ = ("_packed", "_n", "_R", "_dict")

    _KEYS = (
        "matching",
        "nil",
        "total",
        "quorum_matching",
        "quorum_nil",
        "quorum_any",
        "l28",
        "l28_quorum",
    )

    def __init__(self, packed, n: int, r_slots: int):
        self._packed = packed
        self._n = n
        self._R = r_slots
        self._dict = None

    def _materialize(self) -> dict:
        d = self._dict
        if d is None:
            flat = np.asarray(self._packed)
            n, R = self._n, self._R
            six = flat[:, : 2 * R * 6].reshape(n, 2, R, 6)
            d = self._dict = {
                "matching": six[..., 0],
                "nil": six[..., 1],
                "total": six[..., 2],
                "quorum_matching": six[..., 3].astype(bool),
                "quorum_nil": six[..., 4].astype(bool),
                "quorum_any": six[..., 5].astype(bool),
                "l28": flat[:, 2 * R * 6],
                "l28_quorum": flat[:, 2 * R * 6 + 1].astype(bool),
            }
            self._packed = None
        return d

    def __getitem__(self, key):
        if key not in self._KEYS:
            raise KeyError(key)
        return self._materialize()[key]

    def __iter__(self):
        return iter(self._KEYS)

    def __len__(self) -> int:
        return len(self._KEYS)

    def __contains__(self, key) -> bool:
        return key in self._KEYS
