"""Device-resident vote grids: the consensus tally state lives on device.

This is the integration the north star describes — "quorum tallies become
masked reductions fused behind the verification mask". The reference scans
Go maps per received vote (reference: process/process.go:487-491, 574-579,
626-631, 696-701); :mod:`hyperdrive_tpu.ops.tally` already expresses one
window's counts as masked reductions; this module makes the *accumulated*
per-replica vote state a persistent device tensor so every settle pass is
one scatter + one fused reduction for the whole network:

- ``values [n, 2, R, V, 8]`` int32 — per replica, per vote plane
  (0=prevote, 1=precommit), per round slot, per validator, the 32-byte
  vote value as eight little-endian words;
- ``present [n, 2, R, V]`` bool — vote exists, passed signature
  verification, and survived the host automaton's duplicate/equivocation
  filters (only *accepted* inserts are scattered, so the grid is exactly
  the device image of ``State.prevote_logs``/``precommit_logs``).

Each :meth:`VoteGrid.update_and_tally` call scatters one superstep's
accepted votes for ALL replicas and returns every per-round count the
rule cascade needs (L28/L34/L36/L44/L47/L49) — the Process then consumes
these counts instead of rescanning its logs (see ``Process.ingest``'s
tally source). Buffers are donated, so the grids update in place on
device; the host only ever sees the small ``[n, 2, R]`` count tensors.

Capacity: round slots cover rounds ``0..R-1`` of each replica's current
height. Rounds beyond the window (rare — they require R consecutive
failed rounds) simply aren't covered; the cascade falls back to the host
counters for those rounds, which remain authoritative and are what the
differential tests compare against.
"""

from __future__ import annotations

from collections.abc import Mapping

import numpy as np

import jax
import jax.numpy as jnp

from hyperdrive_tpu.analysis.annotations import device_fetch
from hyperdrive_tpu.ops import bucketing
from hyperdrive_tpu.types import NIL_VALUE

__all__ = [
    "PREVOTE_PLANE",
    "PRECOMMIT_PLANE",
    "VoteGrid",
    "TallyView",
    "CheckedTallyView",
]

PREVOTE_PLANE = 0
PRECOMMIT_PLANE = 1


class TallyView:
    """One replica's slice of a :class:`VoteGrid` launch result — the
    object ``Process.ingest_cascade`` consults for quorum thresholds.

    The view answers a count query ONLY when the launch provably tallied
    that exact query; otherwise it returns None and the Process falls back
    to its host counters. Declines happen for: rounds outside the slot
    window, rounds marked dirty (a vote couldn't be scattered — unknown
    sender), target values the launch didn't compare against, and any
    query after the replica's height moved past :attr:`height`.
    """

    __slots__ = ("rep", "height", "counts", "R", "targets",
                 "l28_round", "l28_value", "dirty")

    def __init__(self, rep: int, height: int, counts: Mapping, r_slots: int,
                 targets: dict, l28_round: int, l28_value: bytes,
                 dirty=frozenset()):
        self.rep = rep
        self.height = height
        self.counts = counts
        self.R = r_slots
        #: round -> the 32-byte proposal value the launch used as that
        #: round's matching target.
        self.targets = targets
        self.l28_round = l28_round
        self.l28_value = l28_value
        self.dirty = dirty

    def _covered(self, plane: int, rnd: int) -> bool:
        return 0 <= rnd < self.R and (plane, rnd) not in self.dirty

    def _matching(self, plane: int, rnd: int, value: bytes):
        if not self._covered(plane, rnd):
            return None
        if self.targets.get(rnd) == value:
            return int(self.counts["matching"][self.rep, plane, rnd])
        if value == NIL_VALUE:
            return int(self.counts["nil"][self.rep, plane, rnd])
        return None

    def prevotes_for(self, rnd: int, value: bytes):
        c = self._matching(PREVOTE_PLANE, rnd, value)
        if c is not None:
            return c
        # The L28 cross-round lane: prevotes at the current proposal's
        # valid_round compared against the current proposal's value.
        if (
            rnd == self.l28_round
            and value == self.l28_value
            and self._covered(PREVOTE_PLANE, rnd)
        ):
            return int(self.counts["l28"][self.rep])
        return None

    def precommits_for(self, rnd: int, value: bytes):
        return self._matching(PRECOMMIT_PLANE, rnd, value)

    def prevote_total(self, rnd: int):
        if not self._covered(PREVOTE_PLANE, rnd):
            return None
        return int(self.counts["total"][self.rep, PREVOTE_PLANE, rnd])

    def precommit_total(self, rnd: int):
        if not self._covered(PRECOMMIT_PLANE, rnd):
            return None
        return int(self.counts["total"][self.rep, PRECOMMIT_PLANE, rnd])


def _kernel(values, present, reset, idx, words, valid,
            targets, target_valid, l28_slot, l28_target, f,
            axis_name=None):
    """One fused scatter + tally step.

    values [n,2,R,V,8] i32 (donated), present [n,2,R,V] bool (donated),
    reset [n] bool — zero a replica's planes before scattering (height
    advanced), idx [k,4] i32 rows (replica, plane, slot, validator),
    words [k,8] i32 vote values, valid [k] bool (padding mask),
    targets [n,R,8] i32 per-round proposal values, target_valid [n,R],
    l28_slot [n] i32 (valid-round slot for the L28 cross-round count, or
    -1), l28_target [n,8] i32 (the *current* round's proposal value),
    f [n] i32.

    Sharded mode (``axis_name`` set, running under ``shard_map``): the
    validator axis V is the local shard; scatter rows carry GLOBAL
    validator indices, each shard claims only its own range, and the
    partial counts combine with one ``psum`` over the axis — the
    vote-exchange collective rides the ICI ring, the host never sees
    per-validator state.
    """
    n, _, R, V, _ = values.shape

    if axis_name is not None:
        offset = jax.lax.axis_index(axis_name).astype(jnp.int32) * V
        vloc = idx[:, 3] - offset
        valid = valid & (vloc >= 0) & (vloc < V)
        idx = jnp.concatenate([idx[:, :3], vloc[:, None]], axis=1)

    keep = ~reset[:, None, None, None]
    present = present & keep

    flat_vals = values.reshape(-1, 8)
    flat_pres = present.reshape(-1)
    lane = ((idx[:, 0] * 2 + idx[:, 1]) * R + idx[:, 2]) * V + idx[:, 3]
    lane = jnp.where(valid, lane, flat_pres.shape[0])  # OOB lanes drop
    flat_vals = flat_vals.at[lane].set(words, mode="drop")
    flat_pres = flat_pres.at[lane].set(True, mode="drop")
    values = flat_vals.reshape(n, 2, R, V, 8)
    present = flat_pres.reshape(n, 2, R, V)

    packed = _tally(
        values, present, targets, target_valid, l28_slot, l28_target, f,
        axis_name=axis_name,
    )
    return values, present, packed


def _tally(values, present, targets, target_valid, l28_slot, l28_target, f,
           axis_name=None):
    """The fused reduction shared by both grid kernels: per-(replica,
    plane, round) matching/nil/total counts + quorum flags + the L28
    cross-round lane, packed into ONE int32 output (over a tunnel-attached
    device every host fetch is a full round trip, and eight per-launch
    fetches dominated the launch cost at ~0.1s each). Layout:
    [n, 2, R, 6] = (matching, nil, total, quorum_matching, quorum_nil,
    quorum_any) flattened, then the two L28 lanes appended per replica."""
    R = values.shape[2]
    pres_i = present.astype(jnp.int32)
    eq_target = (
        jnp.all(values == targets[:, None, :, None, :], axis=-1)
        & target_valid[:, None, :, None]
    )
    eq_nil = jnp.all(values == 0, axis=-1)  # NIL_VALUE is 32 zero bytes
    matching = jnp.sum(eq_target & present, axis=-1, dtype=jnp.int32)
    nil = jnp.sum(eq_nil & present, axis=-1, dtype=jnp.int32)
    total = jnp.sum(pres_i, axis=-1, dtype=jnp.int32)

    # L28 cross-round count: prevotes at the CURRENT proposal's valid_round
    # matching the CURRENT proposal's value (the per-round targets above
    # compare round r's votes against round r's own proposal).
    slot_ok = jnp.arange(R)[None, :] == l28_slot[:, None]  # [n, R]
    eq28 = (
        jnp.all(values[:, PREVOTE_PLANE] == l28_target[:, None, None, :],
                axis=-1)
        & present[:, PREVOTE_PLANE]
        & slot_ok[:, :, None]
    )
    l28 = jnp.sum(eq28, axis=(1, 2), dtype=jnp.int32)  # [n]

    if axis_name is not None:
        matching = jax.lax.psum(matching, axis_name)
        nil = jax.lax.psum(nil, axis_name)
        total = jax.lax.psum(total, axis_name)
        l28 = jax.lax.psum(l28, axis_name)

    n_ = matching.shape[0]
    # Counts only — quorum flags are derived host-side from (counts, f)
    # at materialize time (LazyCounts), halving the per-launch transfer.
    three = jnp.stack([matching, nil, total], axis=-1)  # [n, 2, R, 3]
    return jnp.concatenate(
        [three.reshape(n_, -1), l28[:, None]], axis=1
    )  # [n, 2*R*3 + 1]


def _fused_kernel(verify_inner, values, present,
                  ax, ay, at, rx, ry, s_nib, k_nib, side):
    """Verification + scatter + tally as ONE launch (the north-star
    fusion: tallies are masked reductions fused behind the verification
    mask, and the settle pass pays a single device round trip — the same
    one the verify-only path already pays).

    ``verify_inner``: the traceable Ed25519 batch kernel
    ((ax..k_nib) -> bool[B]). The update is a DENSE one-superstep image of
    the shared window (every lockstep replica receives the same
    broadcasts), not a scatter — XLA scatters serialize badly on TPU
    (measured ~10 ms per settle at 256 replicas), while this merge is
    three elementwise passes over the grid.

    All host-built side inputs arrive as ONE flat int32 array (``side``)
    — every separate ``jnp.asarray`` is its own host->device transfer
    with per-call latency over a tunnel. Layout (sizes static from the
    grid shape): upd_lane [2*R*V] | upd_vals [2*R*V*8] | rep_meta [n*4]
    | tpack [n*(R*8+R+8)], where

    - ``upd_lane [2, R, V]``: the verify lane whose verdict gates the
      lane's update, -1 where the window has no vote for that lane
      (duplicate/conflicting claims are resolved host-side; conflicts
      poison the round via the dirty set).
    - ``upd_vals [2, R, V, 8]``: the vote value per updated lane.
    - ``rep_meta [n, 4]``: reset, participate, l28_slot, f.
    - ``tpack [n, R*8 + R + 8]``: per-round target words | target-valid |
      the L28 target words.

    Writes are presence-guarded — an existing vote in a lane always wins,
    reproducing the host logs' first-wins rule — so per-replica grids
    stay exactly equal to the host automaton's accepted inserts without
    per-replica update tensors.
    """
    n, _, R, V, _ = values.shape
    mask = verify_inner(ax, ay, at, rx, ry, s_nib, k_nib)  # [B] bool
    lanes = 2 * R * V
    o1 = lanes
    o2 = o1 + lanes * 8
    o3 = o2 + n * 4
    upd_lane = side[:o1].reshape(2, R, V)
    upd_vals = side[o1:o2].reshape(2, R, V, 8)
    rep_meta = side[o2:o3].reshape(n, 4)
    tpack = side[o3:].reshape(n, R * 8 + R + 8)
    reset = rep_meta[:, 0].astype(bool)
    participate = rep_meta[:, 1].astype(bool)
    l28_slot = rep_meta[:, 2]
    f = rep_meta[:, 3]
    targets = tpack[:, : R * 8].reshape(n, R, 8)
    target_valid = tpack[:, R * 8 : R * 8 + R].astype(bool)
    l28_target = tpack[:, R * 8 + R :]

    has = upd_lane >= 0
    upd_ok = has & mask[jnp.where(has, upd_lane, 0)]  # [2, R, V]
    present = present & ~reset[:, None, None, None]
    write = (
        upd_ok[None]
        & participate[:, None, None, None]
        & ~present  # presence guard: existing votes win
    )  # [n, 2, R, V]
    values = jnp.where(write[..., None], upd_vals[None], values)
    present = present | write
    packed = _tally(
        values, present, targets, target_valid, l28_slot, l28_target, f
    )
    # ONE flat output = ONE device->host transfer: over the tunnel every
    # array fetch is its own ~100ms round trip, so returning mask and
    # counts separately would double the settle's sync cost.
    out = jnp.concatenate(
        [mask.astype(jnp.int32), packed.reshape(-1)]
    )
    return values, present, out


def _fused_jit(verify_inner):
    """Process-wide cache of the jitted fused kernel, keyed on the verify
    callable's identity: every VoteGrid (one per Simulation) shares one
    compiled executable per (kernel, shape) instead of recompiling."""
    from functools import partial

    fn = _FUSED_JITS.get(verify_inner)
    if fn is None:
        fn = _FUSED_JITS[verify_inner] = jax.jit(
            partial(_fused_kernel, verify_inner), donate_argnums=(0, 1)
        )
    return fn


_FUSED_JITS: dict = {}


class CheckedTallyView:
    """Differential instrumentation: wraps a :class:`TallyView` and
    cross-checks every device-sourced count against the host counters
    before returning it — a mismatch raises. Tests and the verify drive
    install it (``Simulation(tally_check=CheckedTallyView)``) to certify
    that device-tally runs are count-for-count identical to host runs.
    ``hits`` counts answered queries so a test can assert the device path
    was actually exercised rather than silently falling back."""

    __slots__ = ("view", "proc", "height", "hits")

    def __init__(self, view: TallyView, proc):
        self.view = view
        self.proc = proc
        self.height = view.height
        self.hits = 0

    def _check(self, device, host, what):
        if device is None:
            return None
        self.hits += 1
        if device != host:
            raise AssertionError(
                f"device {what} count {device} != host {host} "
                f"(replica {self.view.rep}, height {self.height})"
            )
        return device

    def prevotes_for(self, rnd, value):
        return self._check(
            self.view.prevotes_for(rnd, value),
            self.proc.state.count_prevotes_for(rnd, value),
            f"prevote[r={rnd}]",
        )

    def precommits_for(self, rnd, value):
        return self._check(
            self.view.precommits_for(rnd, value),
            self.proc.state.count_precommits_for(rnd, value),
            f"precommit[r={rnd}]",
        )

    def prevote_total(self, rnd):
        return self._check(
            self.view.prevote_total(rnd),
            len(self.proc.state.prevote_logs.get(rnd, {})),
            f"prevote_total[r={rnd}]",
        )

    def precommit_total(self, rnd):
        return self._check(
            self.view.precommit_total(rnd),
            len(self.proc.state.precommit_logs.get(rnd, {})),
            f"precommit_total[r={rnd}]",
        )


class VoteGrid:
    """Persistent device grids for ``n`` replicas × ``validators`` senders.

    One instance serves a whole simulated network (or, in a deployment,
    one chip's replica set). Call :meth:`update_and_tally` once per settle
    pass; it returns a :class:`LazyCounts` mapping of per-(replica, plane,
    slot) counts whose single host fetch is deferred to first value access.

    Memory budget. The grid holds ``values [n, 2, R, V, 8] int32`` +
    ``present [n, 2, R, V] bool`` = ``n * 2 * R * V * 33`` bytes. The
    n × V product is a SIMULATION artifact — one process carrying every
    replica's grid; a deployed chip hosts one replica (n = 1). At R = 4:

    ====================  ==========  ============  =================
    configuration          n = V       total bytes   per device (d=8,
                                                     validator-sharded)
    ====================  ==========  ============  =================
    sim, 256 validators   256          17.3 MB       2.2 MB
    sim, 512 validators   512          69.2 MB       8.7 MB
    sim, 1024 validators  1024         276.8 MB      34.6 MB
    deployment (n = 1)    V = 1024     270 KB        34 KB
    ====================  ==========  ============  =================

    Past one chip's HBM, ``mesh=`` shards the VALIDATOR axis (SURVEY §5's
    scaling story — scatter rows route by global index, counts psum over
    the mesh); SIGNED sharded consensus at 512 and 1024 validators is
    exercised on the 8-device CPU mesh in tests
    (test_device_tally_sharded_at_scale) and benchmarked in BENCH.md
    config 7. Compacting round slots (R) scales the budget linearly when
    deep round-skipping windows are not needed.
    """

    def __init__(self, n_replicas: int, n_validators: int, r_slots: int = 8,
                 buckets: tuple = (256, 1024, 4096, 16384),
                 mesh=None, val_axis: str = "val"):
        self.n = n_replicas
        self.V = n_validators
        self.R = r_slots
        self._all_slots = frozenset(
            (p, r) for p in (0, 1) for r in range(r_slots)
        )
        self.buckets = tuple(sorted(buckets))
        self._mesh = mesh
        self._fused = None
        shape_v = (n_replicas, 2, r_slots, n_validators, 8)
        shape_p = (n_replicas, 2, r_slots, n_validators)
        if mesh is None:
            self._values = jnp.zeros(shape_v, dtype=jnp.int32)
            self._present = jnp.zeros(shape_p, dtype=bool)
            self._fn = jax.jit(_kernel, donate_argnums=(0, 1))
        else:
            # Multi-chip: the validator axis shards over `val_axis`; each
            # chip owns its validators' grid lanes, scatter rows route by
            # global index, counts psum over the ICI ring. Everything else
            # (reset masks, targets, counts) is replicated — it is tiny.
            from functools import partial

            from jax.sharding import NamedSharding, PartitionSpec as P

            d = mesh.shape[val_axis]
            if n_validators % d:
                raise ValueError(
                    f"validators ({n_validators}) must divide evenly over "
                    f"the '{val_axis}' axis ({d} devices)"
                )
            spec_v = P(None, None, None, val_axis, None)
            spec_p = P(None, None, None, val_axis)
            # Multi-process mesh (a real jax.distributed pod): host numpy
            # inputs cannot be committed to non-addressable devices by
            # plain device_put/jnp.asarray — every input is assembled as
            # a GLOBAL array from each process's (identical) local copy.
            # Each process runs the same deterministic automaton, so the
            # replicated values agree by construction.
            self._multiproc = (
                len({d.process_index for d in mesh.devices.flat}) > 1
            )
            self._rep_sharding = NamedSharding(mesh, P())

            def _global_zeros(shape, dtype, spec):
                if not self._multiproc:
                    return jax.device_put(
                        jnp.zeros(shape, dtype=dtype),
                        NamedSharding(mesh, spec),
                    )
                # Allocate only each shard (zeros are position-
                # independent; materializing the full global array once
                # per local device would cost n_local x full-grid host
                # RAM).
                return jax.make_array_from_callback(
                    shape,
                    NamedSharding(mesh, spec),
                    lambda idx: np.zeros(
                        tuple(
                            len(range(*s.indices(dim)))
                            for s, dim in zip(idx, shape)
                        ),
                        dtype=dtype,
                    ),
                )

            self._values = _global_zeros(shape_v, jnp.int32, spec_v)
            self._present = _global_zeros(shape_p, bool, spec_p)
            rep = P()
            sharded = jax.shard_map(
                partial(_kernel, axis_name=val_axis),
                mesh=mesh,
                in_specs=(spec_v, spec_p, rep, rep, rep, rep, rep, rep,
                          rep, rep, rep),
                out_specs=(spec_v, spec_p, rep),
                check_vma=False,
            )
            self._fn = jax.jit(sharded, donate_argnums=(0, 1))

    def bucket_for(self, k: int) -> int:
        return bucketing.bucket_for(k, self.buckets)

    def all_slots(self) -> frozenset:
        """Every (plane, round-slot) pair this grid serves — the full
        poison set. A host-routed settle that cannot say which slots it
        bypassed (a whole-height claim, or the hysteresis rebuild after a
        disengaged stretch) marks all of them dirty: TallyView then
        declines every query for the claimed height and the cascade reads
        its always-complete host fallback, while the next height's reset
        starts the grid clean."""
        return self._all_slots

    def _rep(self, x):
        """A replicated device input: plain ``jnp.asarray`` single-process,
        a process-local-fed global array on a multi-process mesh.

        DELIBERATE deviation from
        :func:`hyperdrive_tpu.parallel.replicate_to_all_hosts` (which
        broadcasts process 0's bytes precisely because local assembly is
        undefined if hosts disagree): these inputs arrive once per settle
        on the hot path — a broadcast collective per input per settle
        would devour the budget — and divergence between the processes'
        deterministic automata is not silently absorbed here but CAUGHT
        downstream: device counts are cross-checked against each
        process's own host counters (CheckedTallyView) and the harness
        all-gathers commit-map hashes across processes. A deployment
        feeding non-deterministic inputs must use the broadcast helper.
        """
        if self._mesh is None or not self._multiproc:
            return jnp.asarray(x)
        x = np.asarray(x)
        return jax.make_array_from_callback(
            x.shape, self._rep_sharding, lambda idx: x[idx]
        )

    # ------------------------------------------------------------ fused path

    def attach_fused(self, inner_factory) -> None:
        """Install the Ed25519 batch-kernel factory (``batch -> traceable
        verify fn``, e.g. ``TpuBatchVerifier.fused_inner``) and enable the
        fused verify+scatter+tally launcher (single-chip grids only — the
        sharded grid keeps the two-launch path, where the fetch is local
        and cheap). The factory MUST return identity-stable callables per
        batch size: the jitted fused kernel is cached process-wide on that
        identity (see :func:`_fused_jit`), so an unstable factory would
        recompile per grid instance — a silent multi-second stall on every
        new Simulation."""
        if self._mesh is not None:
            raise ValueError("fused path is single-chip; sharded grids "
                             "use update_and_tally")
        self._fused_factory = inner_factory
        self._fused = {}

    def _fused_for(self, b: int):
        fn = self._fused.get(b)
        if fn is None:
            fn = self._fused[b] = _fused_jit(self._fused_factory(b))
        return fn

    def fused_update_and_tally(self, verify_arrays, upd_lane, upd_vals,
                               reset, participate,
                               targets, target_valid, l28_slot, l28_target,
                               f):
        """One launch: verify the packed signature batch, merge the shared
        window's vote lanes (gated by the verification mask) into every
        participating replica's grid, tally. Returns a :class:`_FusedOut`
        whose ``mask()`` is the settle's one blocking sync and whose
        ``counts()`` ride the same transfer.

        ``verify_arrays``: the packer's (ax, ay, at, rx, ry, s_nib, k_nib),
        already padded to a bucket size B — the fused kernel compiles once
        per verify bucket. ``upd_lane [2, R, V]`` / ``upd_vals
        [2, R, V, 8]``: the dense one-superstep update image (see
        :func:`_fused_kernel`)."""
        b = verify_arrays[0].shape[0]
        n, R, V = self.n, self.R, self.V
        lanes = 2 * R * V
        tw = R * 8 + R + 8
        side = np.empty(lanes * 9 + n * (4 + tw), dtype=np.int32)
        o1 = lanes
        o2 = o1 + lanes * 8
        o3 = o2 + n * 4
        side[:o1] = upd_lane.reshape(-1)
        side[o1:o2] = upd_vals.reshape(-1)
        rep_meta = side[o2:o3].reshape(n, 4)
        rep_meta[:, 0] = reset
        rep_meta[:, 1] = participate
        rep_meta[:, 2] = l28_slot
        rep_meta[:, 3] = f
        tpack = side[o3:].reshape(n, tw)
        tpack[:, : R * 8] = targets.reshape(n, R * 8)
        tpack[:, R * 8 : R * 8 + R] = target_valid
        tpack[:, R * 8 + R :] = l28_target
        self._values, self._present, out = self._fused_for(b)(
            self._values,
            self._present,
            *(jnp.asarray(a) for a in verify_arrays),
            jnp.asarray(side),
        )
        # Start the device->host copy immediately so the transfer overlaps
        # whatever host work precedes the first access.
        try:
            out.copy_to_host_async()
        except (AttributeError, NotImplementedError):
            pass
        return _FusedOut(out, b, self.n, self.R, f)

    def update_and_tally(self, idx, words, reset, targets, target_valid,
                         l28_slot, l28_target, f):
        """Scatter accepted votes, reduce, return counts as numpy.

        idx [k,4] int32 (replica, plane, slot, validator) — the host
        automaton guarantees at most one row per lane per call (duplicate
        and equivocating votes are rejected before scatter); words [k,8]
        int32; remaining args as in :func:`_kernel` (numpy, host-built
        per settle). Returns a :class:`LazyCounts` (dict-like; the device
        fetch happens on first key access).
        """
        k = len(idx)
        b = self.bucket_for(max(k, 1))
        pad_idx = np.zeros((b, 4), dtype=np.int32)
        pad_words = np.zeros((b, 8), dtype=np.int32)
        valid = np.zeros(b, dtype=bool)
        if k:
            pad_idx[:k] = idx
            pad_words[:k] = words
            valid[:k] = True
        self._values, self._present, packed = self._fn(
            self._values,
            self._present,
            self._rep(reset),
            self._rep(pad_idx),
            self._rep(pad_words),
            self._rep(valid),
            self._rep(targets),
            self._rep(target_valid),
            self._rep(l28_slot),
            self._rep(l28_target),
            self._rep(f),
        )
        # One DEFERRED host fetch for everything (see the packing note in
        # _kernel): the counts stay on device until a rule actually reads
        # one. The fetch is skipped only when EVERY view over this launch
        # stays unconsulted (once-flags and step guards short-circuited in
        # all cascades) — common for small networks' quiet settles,
        # measured neutral at n=256 where some replica nearly always
        # queries. The packed array is an independent output, so the next
        # launch's donation of the grid buffers never invalidates it.
        return LazyCounts(packed, self.n, self.R, f)


class _FusedOut:
    """One fused launch's flat output: ``mask()`` materializes it (the
    settle's single blocking sync) and returns the verification mask;
    ``counts()`` wraps the already-fetched tail as the TallyView mapping
    for free."""

    __slots__ = ("_out", "_b", "_n", "_R", "_f", "_np")

    def __init__(self, out, b: int, n: int, r_slots: int, f):
        self._out = out
        self._b = b
        self._n = n
        self._R = r_slots
        self._f = f
        self._np = None

    def mask(self) -> np.ndarray:
        if self._np is None:
            self._np = device_fetch(
                self._out, why="single deferred fetch of mask+counts"
            )
            self._out = None
        return self._np[: self._b].astype(bool)

    def counts(self) -> "LazyCounts":
        self.mask()
        return LazyCounts(
            self._np[self._b :].reshape(self._n, -1), self._n, self._R,
            self._f,
        )


class LazyCounts(Mapping):
    """Mapping over one packed count tensor, fetched on first VALUE access.
    The key set is static, so shape probes (iteration, membership, len)
    never trigger the device round trip."""

    __slots__ = ("_packed", "_n", "_R", "_f", "_dict")

    _KEYS = (
        "matching",
        "nil",
        "total",
        "quorum_matching",
        "quorum_nil",
        "quorum_any",
        "l28",
        "l28_quorum",
    )

    def __init__(self, packed, n: int, r_slots: int, f):
        self._packed = packed
        self._n = n
        self._R = r_slots
        self._f = f
        self._dict = None

    def _materialize(self) -> dict:
        d = self._dict
        if d is None:
            flat = device_fetch(
                self._packed, why="deferred count fetch on first access"
            )
            n, R = self._n, self._R
            three = flat[:, : 2 * R * 3].reshape(n, 2, R, 3)
            l28 = flat[:, 2 * R * 3]
            # Quorum flags are host-derived (counts and f travel; flags
            # don't — half the transfer for a handful of comparisons).
            q = (2 * device_fetch(self._f, why="f rides the count fetch")
                 .reshape(n) + 1)[:, None, None]
            d = self._dict = {
                "matching": three[..., 0],
                "nil": three[..., 1],
                "total": three[..., 2],
                "quorum_matching": three[..., 0] >= q,
                "quorum_nil": three[..., 1] >= q,
                "quorum_any": three[..., 2] >= q,
                "l28": l28,
                "l28_quorum": l28 >= q[:, 0, 0],
            }
            self._packed = None
        return d

    def __getitem__(self, key):
        if key not in self._KEYS:
            raise KeyError(key)
        return self._materialize()[key]

    def __iter__(self):
        return iter(self._KEYS)

    def __len__(self) -> int:
        return len(self._KEYS)

    def __contains__(self, key) -> bool:
        return key in self._KEYS
