"""Wire-format batched Ed25519 verification: point decompression ON DEVICE.

The packed path (:class:`hyperdrive_tpu.ops.ed25519_jax.Ed25519BatchHost`)
decompresses A and R on the host — one ~255-bit field exponentiation per
point — which caps a 1-core host at ~30k unique signatures/s while the
device kernel verifies 500k+/s (BENCH.md round 3): the sustained
unique-signature pipeline was pack-bound. This module moves BOTH
decompressions into the device launch. The host ships raw wire bytes —
pub (32 B), R (32 B), s (32 B), k (32 B) = 128 B/lane instead of ~930 B
of packed limbs — and keeps only the cheap bit-twiddly steps: SHA-512
challenge scalars (C-speed), s < L and canonical-y range checks, byte
copies. Packing becomes hash-bound; the pipeline becomes device-bound.

Semantics are bit-identical to the host oracle
(:func:`hyperdrive_tpu.crypto.ed25519.verify`): the device decompression
implements the same RFC 8032 x-recovery rules (the x2 == 0 edge cases and
sign handling of ``_recover_x``, crypto/ed25519.py:106-122; reference
trust-model seam: /root/reference/process/process.go:95-98). The combined
square-root/division trick x = u*v^3*(u*v^7)^((p-5)/8) equals the
oracle's x2 = u * inv(v) path on EVERY input because v = d*y^2 + 1 never
vanishes mod p — -1/d is a quadratic non-residue (asserted in tests).
Differential tests enforce exact agreement, including the adversarial
decompression edge cases (non-canonical y, non-residue x2, sign bit on
x == 0, s >= L).
"""

from __future__ import annotations

import functools
import os
import threading

import numpy as np

import jax
import jax.numpy as jnp

from hyperdrive_tpu.analysis.annotations import device_fetch
from hyperdrive_tpu.crypto import ed25519 as host_ed
from hyperdrive_tpu.ops import bucketing
from hyperdrive_tpu.ops import fe25519 as fe
from hyperdrive_tpu.ops.ed25519_jax import verify_kernel

__all__ = [
    "limbs_from_rows",
    "nibbles_from_rows",
    "decompress_device",
    "wire_verify_kernel",
    "make_wire_verify_fn",
    "semiwire_verify_kernel",
    "make_semiwire_verify_fn",
    "chalwire_verify_kernel",
    "make_chalwire_verify_fn",
    "make_challenge_grouped_fn",
    "ValidatorTable",
    "Ed25519WireHost",
    "TpuWireVerifier",
]

P = host_ed.P
_D_LIMBS = fe.to_limbs(host_ed.D)
_SQRTM1_LIMBS = fe.to_limbs(host_ed.SQRT_M1)
_MASK255 = (1 << 255) - 1


# ------------------------------------------------------ device byte unpack


def limbs_from_rows(rows: jnp.ndarray):
    """[B, 32] uint8 little-endian field encodings -> ([B, 20] 13-bit
    limbs with bit 255 cleared, [B] sign bits). Pure elementwise
    shifts/masks — runs on device so the transfer stays 32 B/point."""
    b = rows.astype(jnp.int32)
    sign = b[:, 31] >> 7
    b31 = b[:, 31] & 0x7F
    limbs = []
    for i in range(fe.N_LIMBS):
        bit = 13 * i
        byte, off = bit >> 3, bit & 7
        v = b31 if byte == 31 else b[:, byte]
        if byte + 1 < 32:
            v = v | ((b31 if byte + 1 == 31 else b[:, byte + 1]) << 8)
        if byte + 2 < 32:
            v = v | ((b31 if byte + 2 == 31 else b[:, byte + 2]) << 16)
        limbs.append((v >> off) & fe.LIMB_MASK)
    return jnp.stack(limbs, axis=-1), sign


def nibbles_from_rows(rows: jnp.ndarray) -> jnp.ndarray:
    """[B, 32] uint8 little-endian scalars -> [B, 64] int32 base-16
    digits (device-side mirror of ed25519_jax._nibbles_from_rows)."""
    b = rows.astype(jnp.int32)
    return jnp.stack([b & 0xF, b >> 4], axis=-1).reshape(b.shape[0], 64)


# --------------------------------------------------- device decompression


def decompress_device(y: jnp.ndarray, sign: jnp.ndarray):
    """RFC 8032 x-recovery on limb tensors: solve x^2 = (y^2-1)/(d y^2+1).

    ``y``: [B, 20] limbs (bit 255 cleared; caller guarantees y < p — the
    wire packer range-checks), ``sign``: [B] int32. Returns (x [B, 20],
    ok [B] bool). Matches crypto.ed25519._recover_x case-for-case:
    x2 == 0 (possible only via u == 0, since v never vanishes) yields
    x = 0 accepted iff sign == 0; a non-residue x2 rejects; otherwise the
    root's parity is flipped to the sign bit."""
    batch = y.shape[:-1]
    one = jnp.broadcast_to(
        jnp.asarray(fe.ONE, dtype=jnp.int32), (*batch, fe.N_LIMBS)
    )
    d = jnp.asarray(_D_LIMBS, dtype=jnp.int32)
    y2 = fe.sqr(y)
    u = fe.sub(y2, one)
    v = fe.add(fe.mul(d, y2), one)
    v2 = fe.sqr(v)
    uv3 = fe.mul(u, fe.mul(v2, v))
    uv7 = fe.mul(uv3, fe.sqr(v2))
    x = fe.mul(uv3, fe.pow22523(uv7))
    vx2 = fe.mul(v, fe.sqr(x))
    ok_direct = fe.eq(vx2, u)
    ok_flip = fe.eq(vx2, fe.neg(u))
    sm1 = jnp.asarray(_SQRTM1_LIMBS, dtype=jnp.int32)
    x = fe.select(ok_flip & ~ok_direct, fe.mul(x, sm1), x)
    ok = ok_direct | ok_flip
    x_zero = fe.is_zero(x)
    ok = ok & ~(x_zero & (sign == 1))
    parity = fe.canonical(x)[..., 0] & 1
    x = fe.select(parity != sign, fe.neg(x), x)
    return x, ok


# ------------------------------------------------------------- the kernel


def wire_verify_kernel(a_rows, r_rows, s_rows, k_rows):
    """Batched verify straight from wire bytes (all [B, 32] uint8):
    unpack, decompress A and R, negate A, then run the packed-path ladder
    (:func:`~hyperdrive_tpu.ops.ed25519_jax.verify_kernel`). Returns
    bool [B]. Lanes the packer marked invalid carry zero rows and must be
    masked by the caller's ``prevalid`` (zero rows happen to reject, but
    prevalid is the contract)."""
    ay, a_sign = limbs_from_rows(a_rows)
    ry, r_sign = limbs_from_rows(r_rows)
    ax, ok_a = decompress_device(ay, a_sign)
    rx, ok_r = decompress_device(ry, r_sign)
    # The ladder computes [s]B + [k](-A): negate A here (x -> p - x,
    # t = x' * y), exactly what the packed-path host packer pre-computes.
    nax = fe.neg(ax)
    nat = fe.mul(nax, ay)
    s_nib = nibbles_from_rows(s_rows)
    k_nib = nibbles_from_rows(k_rows)
    ok = verify_kernel(nax, ay, nat, rx, ry, s_nib, k_nib)
    return ok & ok_a & ok_r


@functools.lru_cache(maxsize=None)
def make_wire_verify_fn(jit: bool = True):
    """Cached (one XLA compile per batch shape process-wide)."""
    return jax.jit(wire_verify_kernel) if jit else wire_verify_kernel


# ------------------------------------------- validator-resident (indexed)


class ValidatorTable:
    """Device-resident decompressed validator pubkeys.

    Consensus verifies signatures from a KNOWN validator set (the
    whitelist the replica installs — reference:
    /root/reference/replica/replica.go:69-72); decompressing each pubkey
    per signature is pure waste, and on a bandwidth-starved link even
    SHIPPING the 32-byte encoding per lane is waste. This table
    decompresses and negates each pubkey once on the host, uploads the
    [V, 20] coordinate tensors once, and the indexed verify path then
    ships a 4-byte validator index per lane (100 B/lane total vs the
    full wire path's 128). Pubkeys that fail decompression occupy an
    invalid slot — their signatures reject, matching the oracle.

    Padding caution: ``bytes(32)`` is NOT an invalid encoding — y = 0
    decompresses to a real curve point, so zero-padded slots are live
    table entries registered under the all-zero pubkey. Pad with a
    non-canonical encoding instead (e.g. ``P.to_bytes(32, "little")``,
    which always fails decompression)."""

    def __init__(self, pubkeys):
        pubkeys = list(pubkeys)
        v = len(pubkeys)
        nax = np.zeros((max(v, 1), fe.N_LIMBS), dtype=np.int32)
        ay = np.zeros_like(nax)
        nat = np.zeros_like(nax)
        valid = np.zeros(max(v, 1), dtype=bool)
        rows = np.zeros((max(v, 1), 32), dtype=np.uint8)
        self.index: dict = {}
        for i, pub in enumerate(pubkeys):
            self.index.setdefault(pub, i)  # first wins on duplicates
            if len(pub) == 32:
                # Compressed encoding, resident for the device-side
                # challenge hash k = SHA-512(R||A||M) — kept even for
                # pubkeys that fail decompression (their lanes reject via
                # ``valid`` regardless of what they hash to).
                rows[i] = np.frombuffer(pub, dtype=np.uint8)
            pt = host_ed.point_decompress(pub)
            if pt is None:
                continue
            x, y = pt[0], pt[1]
            nx = (P - x) % P
            nax[i] = fe.to_limbs(nx)
            ay[i] = fe.to_limbs(y)
            nat[i] = fe.to_limbs((nx * y) % P)
            valid[i] = True
        self.n = v
        self.nax = jnp.asarray(nax)
        self.ay = jnp.asarray(ay)
        self.nat = jnp.asarray(nat)
        self.valid = jnp.asarray(valid)
        self.rows = jnp.asarray(rows)

    def arrays(self):
        return self.nax, self.ay, self.nat, self.valid

    def arrays_chal(self):
        """The :func:`chalwire_verify_kernel` argument pack: coordinate
        tensors plus the resident compressed encodings."""
        return self.nax, self.ay, self.nat, self.valid, self.rows


def semiwire_verify_kernel(idx, r_rows, s_rows, k_rows,
                           tnax, tay, tnat, tvalid, *,
                           kernel=verify_kernel):
    """Indexed-A wire verify: gather the pre-decompressed, pre-negated A
    coordinates from the resident validator table ([V, 20] each), then
    decompress R on device and run the ladder. ``idx``: [B] int32 into
    the table (prevalid lanes only — the packer rejects unknown pubs).
    ``kernel``: the ladder implementation (the XLA verify_kernel by
    default; the sharded mesh step passes its mesh-resolved pick) — one
    definition of the gather/decompress/mask rule for every path."""
    nax = jnp.take(tnax, idx, axis=0)
    ay = jnp.take(tay, idx, axis=0)
    nat = jnp.take(tnat, idx, axis=0)
    ok_t = jnp.take(tvalid, idx, axis=0)
    ry, r_sign = limbs_from_rows(r_rows)
    rx, ok_r = decompress_device(ry, r_sign)
    s_nib = nibbles_from_rows(s_rows)
    k_nib = nibbles_from_rows(k_rows)
    ok = kernel(nax, ay, nat, rx, ry, s_nib, k_nib)
    return ok & ok_r & ok_t


@functools.lru_cache(maxsize=None)
def make_semiwire_verify_fn(jit: bool = True):
    return jax.jit(semiwire_verify_kernel) if jit else semiwire_verify_kernel


# ------------------------------------- challenge-on-device (68 B per lane)


def chalwire_verify_kernel(idx, r_rows, s_rows, m_rows,
                           tnax, tay, tnat, tvalid, trows):
    """Indexed-A wire verify with the CHALLENGE derived on device:
    k = SHA-512(R || A || M) mod L computed in-launch
    (:mod:`hyperdrive_tpu.ops.sha512_jax`), so the wire carries only
    R (32 B) + s (32 B) + idx (4 B) = 68 B/lane — A's compressed encoding
    is gathered from the resident table (``trows``, [V, 32] uint8) and
    ``m_rows`` ([B, 32] uint8 signing digests) is per-round consensus
    data the caller broadcasts INSIDE its jit (validators voting for the
    same (round, value) share the digest; the sender is excluded from it
    — reference: /root/reference/process/message.go:165-186), costing no
    per-lane transfer. The derived k is canonical, so verdicts are
    bit-identical to the host-packed semiwire path."""
    from hyperdrive_tpu.ops.sha512_jax import challenge_scalar_device

    a_rows = jnp.take(trows, idx, axis=0)
    k_rows = challenge_scalar_device(r_rows, a_rows, m_rows)
    return semiwire_verify_kernel(
        idx, r_rows, s_rows, k_rows, tnax, tay, tnat, tvalid
    )


@functools.lru_cache(maxsize=None)
def make_challenge_fn():
    """The challenge leg as its own executable: k rows from (idx, R, M)
    and the resident compressed-pubkey table."""
    from hyperdrive_tpu.ops.sha512_jax import challenge_scalar_device

    @jax.jit
    def chal(idx, r_rows, m_rows, trows):
        return challenge_scalar_device(
            r_rows, jnp.take(trows, idx, axis=0), m_rows
        )

    return chal


def challenge_from_round(idx, r_rows, m_round, trows, lanes_per_round: int):
    """Traceable core of the 68 B/lane challenge leg: per-ROUND digests
    broadcast to round-major lanes (lane = round * lanes_per_round +
    validator — the dense consensus grid order) on device, A gathered by
    index, k derived in-launch. Lanes beyond rounds*lanes_per_round
    (bucket padding) hash a zero digest and are masked by the caller's
    prevalid. The ONE definition of the round->lane rule: the jitted
    single-chip wrapper below and the sharded mesh step
    (parallel/mesh.py::sharded_chalwire_tally) both call it."""
    from hyperdrive_tpu.ops.sha512_jax import challenge_scalar_device

    m = jnp.repeat(m_round, lanes_per_round, axis=0)
    pad = idx.shape[0] - m.shape[0]
    if pad:
        m = jnp.concatenate([m, jnp.zeros((pad, 32), dtype=jnp.uint8)])
    return challenge_scalar_device(
        r_rows, jnp.take(trows, idx, axis=0), m
    )


@functools.lru_cache(maxsize=None)
def make_challenge_round_fn(validators: int):
    """Cached jitted :func:`challenge_from_round` at a fixed validator
    count — bench.py's sustained headline and the tests share it."""

    @jax.jit
    def chal(idx, r_rows, m_round, trows):
        return challenge_from_round(idx, r_rows, m_round, trows, validators)

    return chal


@functools.lru_cache(maxsize=None)
def make_challenge_grouped_fn():
    """Chal leg for the GROUPED engine wire format: digests arrive as a
    deduped table plus a one-byte per-lane index, and M is gathered on
    device. The wire then carries R (32) + s (32) + validator idx (4) +
    digest idx (1) = 69 B/lane, plus U * 32 B of unique digests amortized
    over the chunk. Consensus windows hold only a handful of distinct
    digests — one per (type, height, round, value) claim, value + nil per
    round, because the sender is excluded from the signing digest
    (reference: /root/reference/process/message.go:165-186) — so U stays
    single-digit while lanes number thousands. This is the round-4
    68 B/lane bench format generalized from round-major lanes to an
    arbitrary lane->digest index, which is what the ENGINE's verify path
    (TpuWireVerifier.verify_signatures) can actually ship."""
    from hyperdrive_tpu.ops.sha512_jax import challenge_scalar_device

    @jax.jit
    def chal(idx, r_rows, m_idx, m_uniq, trows):
        m_rows = jnp.take(m_uniq, m_idx.astype(jnp.int32), axis=0)
        return challenge_scalar_device(
            r_rows, jnp.take(trows, idx, axis=0), m_rows
        )

    return chal


@functools.lru_cache(maxsize=None)
def make_chalwire_verify_fn(jit: bool = True):
    """TWO dispatches, not one: the unrolled SHA-512 fused into the
    ladder graph sends XLA:CPU's optimizer superlinear (>12 min for a
    batch-64 compile whose two halves compile in ~1 s + ~45 s; TPU
    compiles the fused form fine, but the CPU test platform must stay
    usable and two enqueued launches cost no extra sync — k never leaves
    the device between them)."""
    if not jit:
        return chalwire_verify_kernel
    chal = make_challenge_fn()
    semi = make_semiwire_verify_fn(jit=True)

    def fn(idx, r_rows, s_rows, m_rows, tnax, tay, tnat, tvalid, trows):
        k_rows = chal(idx, r_rows, m_rows, trows)
        return semi(idx, r_rows, s_rows, k_rows, tnax, tay, tnat, tvalid)

    return fn


def chalwire_verify_pallas(idx, r_rows, s_rows, m_rows,
                           tnax, tay, tnat, tvalid, trows, **kw):
    """Pallas-backed challenge path: the jitted XLA challenge leg, then
    the Mosaic ladder (same two-dispatch split as the XLA path)."""
    from hyperdrive_tpu.ops.ed25519_pallas import semiwire_verify_pallas

    k_rows = make_challenge_fn()(idx, r_rows, m_rows, trows)
    return semiwire_verify_pallas(
        idx, r_rows, s_rows, k_rows, tnax, tay, tnat, tvalid, **kw
    )


# ------------------------------------------------------------- host packer


class Ed25519WireHost:
    """Range-checks and marshals (pub, digest, sig) triples into the wire
    tensors the device kernels consume: four [bucket, 32] uint8 arrays
    (A, R, s, k rows) plus the prevalid mask.

    Host work per item: length checks, canonical-y checks for A and R
    (y < p — the oracle's ``_recover_x`` rejection), the s < L
    malleability check, and k = SHA-512(R||A||M) mod L. No field
    exponentiations — that is the point. The native C++ path
    (``hd_pack_wire``) and the pure-Python loop produce identical rows
    and masks (differentially tested); ``HD_NO_NATIVE=1`` forces Python.
    """

    def __init__(self, buckets=(64, 256, 1024, 4096), use_native: bool = True):
        self.buckets = tuple(sorted(buckets))
        self._native = None
        if use_native and not os.environ.get("HD_NO_NATIVE"):
            from hyperdrive_tpu import native

            packer = native.instance()
            if packer is not None and hasattr(packer, "pack_wire_into"):
                self._native = packer

    def bucket_for(self, n: int) -> int:
        return bucketing.bucket_for(n, self.buckets)

    def pack_wire(self, items):
        """items: iterable of (pub32, digest, sig64). Returns
        ((a_rows, r_rows, s_rows, k_rows), prevalid, n) — rows are
        [bucket, 32] uint8, prevalid is bool[bucket], n the true count."""
        items = list(items)
        n = len(items)
        bsz = self.bucket_for(max(n, 1))
        a_rows = np.zeros((bsz, 32), dtype=np.uint8)
        r_rows = np.zeros_like(a_rows)
        s_rows = np.zeros_like(a_rows)
        k_rows = np.zeros_like(a_rows)
        prevalid = np.zeros(bsz, dtype=bool)

        if self._native is not None:
            prevalid[:n] = self._native.pack_wire_into(
                items, a_rows, r_rows, s_rows, k_rows
            )
            return (a_rows, r_rows, s_rows, k_rows), prevalid, n

        for i, (pub, digest, sig) in enumerate(items):
            if len(pub) != 32 or len(sig) != 64:
                continue
            if (int.from_bytes(pub, "little") & _MASK255) >= P:
                continue
            if (int.from_bytes(sig[:32], "little") & _MASK255) >= P:
                continue
            if int.from_bytes(sig[32:], "little") >= host_ed.L:
                continue
            k = host_ed.challenge_scalar(sig[:32], pub, digest)
            a_rows[i] = np.frombuffer(pub, dtype=np.uint8)
            r_rows[i] = np.frombuffer(sig[:32], dtype=np.uint8)
            s_rows[i] = np.frombuffer(sig[32:], dtype=np.uint8)
            k_rows[i] = np.frombuffer(
                k.to_bytes(32, "little"), dtype=np.uint8
            )
            prevalid[i] = True
        return (a_rows, r_rows, s_rows, k_rows), prevalid, n

    def index_lanes(self, items, table: ValidatorTable):
        """Map each item's pubkey to its table slot. Returns (idx int32
        [bucket], all_known) — unknown pubkeys leave idx 0 and flip
        all_known, telling the caller to use the full wire path for the
        chunk (verdicts must never depend on table contents)."""
        idx = np.zeros(self.bucket_for(max(len(items), 1)), dtype=np.int32)
        lookup = table.index.get
        lanes = np.fromiter(
            (lookup(pub, -1) for pub, _, _ in items),
            dtype=np.int32,
            count=len(items),
        )
        all_known = bool((lanes >= 0).all()) if len(items) else True
        idx[: len(items)] = np.maximum(lanes, 0)
        return idx, all_known

    @staticmethod
    def _rows_lt(rows: np.ndarray, bound: int, mask255: bool = False):
        """Vectorized little-endian 256-bit compare: rows < bound, as
        four uint64 words most-significant first. ``mask255`` clears bit
        255 first (the field-encoding convention: the sign bit is not part
        of y). The word view is byte-order-explicit ('<u8'): on a
        big-endian host a native-endian view would invert the comparison
        and let a malleable s >= L signature through prevalid."""
        w = np.ascontiguousarray(rows).view(np.dtype("<u8"))
        if mask255:
            w = w.copy()
            w[:, 3] &= 0x7FFFFFFFFFFFFFFF
        b = [(bound >> (64 * i)) & 0xFFFFFFFFFFFFFFFF for i in range(4)]
        lt = np.zeros(len(rows), dtype=bool)
        eq = np.ones(len(rows), dtype=bool)
        for i in (3, 2, 1, 0):
            lt |= eq & (w[:, i] < b[i])
            eq &= w[:, i] == b[i]
        return lt

    def pack_wire_challenge(self, items, table: ValidatorTable,
                            with_m: bool = True, _idx=None):
        """Challenge-on-device packing: NO hashing on host — the packer
        only range-checks and marshals, so the host leg of the sustained
        pipeline is pure byte movement. Returns ((idx, r_rows, s_rows,
        m_rows), prevalid, n) for :func:`chalwire_verify_kernel`; with
        ``with_m=False`` the m slot is None (callers whose digests are
        per-round data ship those separately — 68 B/lane on the wire).

        Host work per item: length checks, canonical-y on R, s < L, and
        the table lookup. A's canonicity is a TABLE property (invalid
        entries reject on device via ``tvalid``). Requires every pubkey in
        the table, like :meth:`pack_wire_indexed` — and every digest to be
        exactly 32 bytes (the device hash has a fixed 96-byte preimage;
        consensus digests always are — messages.py::digest — but
        arbitrary-length digests must ride the host-hashed paths)."""
        items = list(items)
        n = len(items)
        if any(len(d) != 32 for _, d, _ in items):
            raise ValueError(
                "pack_wire_challenge requires 32-byte digests"
            )
        bsz = self.bucket_for(max(n, 1))
        r_rows = np.zeros((bsz, 32), dtype=np.uint8)
        s_rows = np.zeros_like(r_rows)
        m_rows = np.zeros_like(r_rows) if with_m else None
        prevalid = np.zeros(bsz, dtype=bool)
        if _idx is not None:
            # Caller already ran index_lanes for routing (verify_signatures
            # does) — don't sweep the lookup dict a second time.
            idx = _idx
        else:
            idx, all_known = self.index_lanes(items, table)
            if not all_known:
                raise ValueError(
                    "pack_wire_challenge requires every pubkey in the table"
                )
        if n == 0:
            return (idx, r_rows, s_rows, m_rows), prevalid, n

        wellformed = np.fromiter(
            (len(sig) == 64 for _, _, sig in items), dtype=bool, count=n
        )
        if wellformed.all():
            flat = np.frombuffer(
                b"".join(sig for _, _, sig in items), dtype=np.uint8
            ).reshape(n, 64)
            r_rows[:n] = flat[:, :32]
            s_rows[:n] = flat[:, 32:]
        else:
            for i, (_, _, sig) in enumerate(items):
                if len(sig) != 64:
                    continue
                r_rows[i] = np.frombuffer(sig[:32], dtype=np.uint8)
                s_rows[i] = np.frombuffer(sig[32:], dtype=np.uint8)
        if with_m:
            m_rows[:n] = np.frombuffer(
                b"".join(d for _, d, _ in items), dtype=np.uint8
            ).reshape(n, 32)
        prevalid[:n] = (
            wellformed
            & self._rows_lt(r_rows[:n], P, mask255=True)
            & self._rows_lt(s_rows[:n], host_ed.L)
        )
        # Malformed lanes carry zero rows; zero R/s happens to reject on
        # device, but prevalid is the contract (same as pack_wire).
        return (idx, r_rows, s_rows, m_rows), prevalid, n

    #: Unique-digest capacity of the grouped challenge format — the
    #: per-lane digest index is one byte. Chunks exceeding it (only
    #: adversarial or benchmark-synthetic: a consensus window has a
    #: handful of distinct claims) fall back to per-lane digest rows.
    M_GROUP_CAP = 256
    #: Bucket ladder for the unique-digest table (its own jit shapes).
    M_BUCKETS = (16, 256)

    def group_digests(self, items, bucket: int):
        """Dedup the items' digests for the grouped challenge format.

        Returns ``(m_idx, m_uniq, u)`` — ``m_idx`` [bucket] uint8 lane ->
        digest-slot indices, ``m_uniq`` [m_bucket, 32] uint8 unique digest
        rows (first ``u`` live), — or None when the chunk has more than
        :data:`M_GROUP_CAP` distinct digests and must ride the per-lane
        path. First-seen order assigns slots, so packing is deterministic.
        """
        cap = min(self.M_GROUP_CAP, 256)  # m_idx is uint8: hard ceiling
        slots: dict = {}
        m_idx = np.zeros(bucket, dtype=np.uint8)
        for i, (_, d, _) in enumerate(items):
            s = slots.get(d)
            if s is None:
                s = len(slots)
                if s >= cap:
                    return None
                slots[d] = s
            m_idx[i] = s
        u = len(slots)
        mb = bucketing.bucket_for(max(u, 1), self.M_BUCKETS)
        m_uniq = np.zeros((mb, 32), dtype=np.uint8)
        if u:
            m_uniq[:u] = np.frombuffer(
                b"".join(slots), dtype=np.uint8
            ).reshape(u, 32)
        return m_idx, m_uniq, u

    def pack_wire_indexed(self, items, table: ValidatorTable):
        """Indexed-A packing: like :meth:`pack_wire`, but A ships as an
        int32 index into ``table`` (4 B/lane instead of 32). Requires
        every pubkey to be in the table (callers route mixed chunks
        through the full wire path). Returns ((idx, r_rows, s_rows,
        k_rows), prevalid, n)."""
        items = list(items)
        # (pack_wire also fills A rows — one 32-byte memcpy per lane,
        # noise next to the SHA-512 — which this path simply drops.)
        (_, r_rows, s_rows, k_rows), prevalid, n = self.pack_wire(items)
        idx, all_known = self.index_lanes(items, table)
        if not all_known:
            raise ValueError(
                "pack_wire_indexed requires every pubkey in the table"
            )
        return (idx, r_rows, s_rows, k_rows), prevalid, n


# --------------------------------------------------------------- verifier


class PendingVerify:
    """Verification launches enqueued but not yet materialized — the
    handle :meth:`TpuWireVerifier.verify_signatures_begin` returns.

    Holding one of these costs nothing on the host; the device is already
    working. :meth:`mask` performs the launches' ONE concatenated fetch
    (separate fetches would each pay a full tunnel round trip) and is
    idempotent — the resolved mask is cached.
    """

    __slots__ = ("_pending", "_mask")

    def __init__(self, pending):
        #: (device_result | None, prevalid, n) per enqueued chunk, in
        #: output order; None results are fully host-rejected chunks.
        self._pending = pending
        self._mask = None

    def mask(self) -> np.ndarray:
        """Block until every enqueued launch lands; bool verdicts in item
        order (``repeats`` consecutive copies when tiled)."""
        if self._mask is not None:
            return self._mask
        pending = self._pending
        devs = [d for d, _, _ in pending if d is not None]
        big = (
            device_fetch(jnp.concatenate(devs),
                         why="THE double-buffer sync point: one RTT for "
                             "every enqueued launch's verdicts")
            if devs else None
        )
        off = 0
        out = []
        for dev, prevalid, n in pending:
            if dev is None:
                out.append(prevalid[:n].copy())
                continue
            width = dev.shape[0]
            out.append((big[off : off + width] & prevalid)[:n])
            off += width
        if not out:
            self._mask = np.zeros(0, dtype=bool)
        elif len(out) == 1:
            self._mask = out[0]
        else:
            self._mask = np.concatenate(out)
        self._pending = ()
        return self._mask


class TpuWireVerifier:
    """Batch verifier over the wire path: 128 B/lane host->device, both
    decompressions on device. Drop-in for
    :class:`~hyperdrive_tpu.ops.ed25519_jax.TpuBatchVerifier` where raw
    throughput on unique signatures matters (the sustained pipeline);
    the packed path remains better when pubkey/decompression reuse is
    high and host CPU is idle."""

    def __init__(self, buckets=(64, 256, 1024, 4096), backend: str = "auto",
                 table: "ValidatorTable | None" = None):
        from hyperdrive_tpu.ops.ed25519_pallas import resolve_backend

        self.host = Ed25519WireHost(buckets=buckets)
        self.backend = resolve_backend(backend)
        self._fn = make_wire_verify_fn(jit=True)
        #: Optional resident validator table: chunks whose senders are all
        #: in the table ride the CHALLENGE path — 4-byte A index per lane
        #: and k = SHA-512(R||A||M) derived on device, so the host does no
        #: hashing at all. When the chunk's digests dedup to <=256 unique
        #: values (every consensus window: digests are per-(type, h, r,
        #: value) claims, sender excluded — reference:
        #: /root/reference/process/message.go:165-186) the GROUPED format
        #: ships a one-byte digest index per lane + the unique digest
        #: table: 69 B/lane, the round-4 bench format as the product
        #: format. Chunks with more distinct digests ride per-lane digest
        #: rows (100 B/lane). Any unknown pubkey routes the whole chunk
        #: through the full 128 B/lane wire path so verdicts never depend
        #: on table contents. Unconditional by measurement: the chal
        #: leg's extra dispatch costs +9 ms p50 at window 64 and is
        #: paired-noise by 1024 (vs a ~120-130 ms per-call sync floor
        #: either way, 2026-07-31 tunnel session) — and windows that
        #: small are the ones the engine's small_window_host /
        #: AdaptiveVerifier routing keeps on host to begin with, so a
        #: size gate here would duplicate routing that already exists a
        #: layer up.
        self.table = table
        #: Epoch table generations (epochs.py), double-buffered: the
        #: current AND previous generation's ValidatorTable stay device-
        #: resident so a drain straddling an epoch boundary can launch
        #: the old generation's windows and the new generation's windows
        #: back-to-back without re-uploading either table.
        self.generation = 0
        self._tables: dict = {0: table} if table is not None else {}
        self._chal_fn = make_chalwire_verify_fn(jit=True)
        self._chal_grouped = make_challenge_grouped_fn()
        self._semi_fn = make_semiwire_verify_fn(jit=True)
        #: Wire-format accounting, reset with :meth:`reset_stats`:
        #: ``lanes`` = real (unpadded) signatures routed per path,
        #: ``format_bytes`` = the per-lane field bytes those lanes cost
        #: on the wire (grouped: 69*n + 32*U; chal per-lane: 100*n;
        #: full wire: 128*n) — the engine bytes/lane BENCH.md reports.
        #: Lock-guarded: deployments share one verifier across replica
        #: threads (tallyflush), and unguarded += would lose counts.
        self.stats = {
            "lanes_grouped": 0,
            "lanes_chal": 0,
            "lanes_wire": 0,
            "format_bytes": 0,
        }
        self._stats_lock = threading.Lock()

    def install_table(self, table, generation=None) -> None:
        """Hot-swap the resident validator table at an epoch boundary.

        The new generation's coordinate tensors upload here (off the
        verify path); the PREVIOUS generation's table is retained so
        in-flight windows tagged with the old generation still verify
        against the keys they were signed under. Older generations are
        evicted — two live tables bound device memory at 2x one epoch's
        committee, and anything older is stale by the retired-key rule
        (replica.py rejects those votes before they reach a verifier)."""
        if generation is None:
            generation = self.generation + 1
        generation = int(generation)
        prev = self.generation
        self._tables = {
            g: t for g, t in self._tables.items() if g == prev
        }
        self._tables[generation] = table
        self.table = table
        self.generation = generation

    def set_generation(self, generation: int) -> None:
        """Select which resident table generation the next launch uses
        (the DeviceWorkQueue drain hook). Only the double-buffered
        current/previous generations are addressable."""
        generation = int(generation)
        got = self._tables.get(generation)
        if got is None:
            raise KeyError(
                f"table generation {generation} is not resident "
                f"(have {sorted(self._tables)})"
            )
        self.table = got
        self.generation = generation

    def reset_stats(self) -> None:
        with self._stats_lock:
            self.stats = {k: 0 for k in self.stats}

    def _count(self, lane_key: str, lanes: int, fbytes: int) -> None:
        with self._stats_lock:
            self.stats[lane_key] += lanes
            self.stats["format_bytes"] += fbytes

    def bytes_per_lane(self) -> float:
        """Mean engine wire-format bytes per real lane since the last
        reset (0.0 when nothing was verified)."""
        lanes = (
            self.stats["lanes_grouped"]
            + self.stats["lanes_chal"]
            + self.stats["lanes_wire"]
        )
        return self.stats["format_bytes"] / lanes if lanes else 0.0

    def _device_verify(self, rows):
        dev_in = [jnp.asarray(a) for a in rows]
        if self.backend == "pallas":
            from hyperdrive_tpu.ops.ed25519_pallas import wire_verify_pallas

            return wire_verify_pallas(*dev_in)
        return self._fn(*dev_in)

    def _device_verify_chal(self, rows):
        dev_in = [jnp.asarray(a) for a in rows]
        tbl = self.table.arrays_chal()
        if self.backend == "pallas":
            return chalwire_verify_pallas(*dev_in, *tbl)
        return self._chal_fn(*dev_in, *tbl)

    def _device_verify_chal_grouped(self, rows):
        """Grouped challenge launch: derive k from the deduped digest
        table (69 B/lane on the wire), then the ladder — the same
        two-dispatch split as the per-lane chal path."""
        idx, r_rows, s_rows, m_idx, m_uniq = (jnp.asarray(a) for a in rows)
        k_rows = self._chal_grouped(
            idx, r_rows, m_idx, m_uniq, self.table.rows
        )
        if self.backend == "pallas":
            from hyperdrive_tpu.ops.ed25519_pallas import (
                semiwire_verify_pallas,
            )

            return semiwire_verify_pallas(
                idx, r_rows, s_rows, k_rows, *self.table.arrays()
            )
        return self._semi_fn(idx, r_rows, s_rows, k_rows,
                             *self.table.arrays())

    def warmup(self) -> None:
        for b in self.host.buckets:
            z = jnp.zeros((b, 32), dtype=jnp.uint8)
            device_fetch(self._device_verify((z, z, z, z)),
                         why="warmup: block until the compile lands")
            if self.table is not None:
                zi = jnp.zeros(b, dtype=jnp.int32)
                device_fetch(self._device_verify_chal((zi, z, z, z)),
                             why="warmup: block until the compile lands")
                zm = jnp.zeros(b, dtype=jnp.uint8)
                for mb in self.host.M_BUCKETS:
                    zu = jnp.zeros((mb, 32), dtype=jnp.uint8)
                    device_fetch(
                        self._device_verify_chal_grouped(
                            (zi, z, z, zm, zu)
                        ),
                        why="warmup: block until the compile lands",
                    )

    def verify_signatures_begin(
        self, items, repeats: int = 1
    ) -> "PendingVerify":
        """Enqueue the verification launches for ``items`` WITHOUT
        materializing the mask — the async half of the double-buffered
        settle. The returned :class:`PendingVerify` resolves everything
        in one concatenated fetch (``.mask()``); until then the device
        crunches while the host runs the previous window's cascade.

        ``repeats > 1`` verifies that many logical copies of ``items``
        (the simulator's redundant per-receiver mode) with the host pack
        paid ONCE: the packed device arrays are re-launched per copy, so
        every copy is real device verification work, but no lane is
        re-packed or re-shipped — pack reuse across buffered windows.
        Accounting follows the physics: ``lanes_*`` count every verified
        lane (n per copy), ``format_bytes`` count each packed lane once.
        The mask holds ``repeats`` consecutive copies of the per-item
        verdicts (verification is deterministic, so copies agree — they
        are separate launches, not a host-side tile).
        """
        items = list(items)
        cap = self.host.buckets[-1]
        pending: list = []
        packed: list = []  # (stats_key, launch, rows, prevalid, n)
        for lo in range(0, len(items), cap):
            chunk = items[lo : lo + cap]
            if self.table is not None and all(
                len(d) == 32 for _, d, _ in chunk
            ):
                idx, all_known = self.host.index_lanes(chunk, self.table)
                if all_known:
                    grouped = self.host.group_digests(chunk, len(idx))
                    rows, prevalid, n = self.host.pack_wire_challenge(
                        chunk, self.table, with_m=grouped is None,
                        _idx=idx,
                    )
                    idx, r_rows, s_rows, m_rows = rows
                    if grouped is not None:
                        m_idx, m_uniq, u = grouped
                        self._count("lanes_grouped", n, 69 * n + 32 * u)
                        packed.append((
                            "lanes_grouped",
                            self._device_verify_chal_grouped,
                            (idx, r_rows, s_rows, m_idx, m_uniq),
                            prevalid, n,
                        ))
                    else:
                        # > M_GROUP_CAP distinct digests: per-lane rows.
                        self._count("lanes_chal", n, 100 * n)
                        packed.append((
                            "lanes_chal", self._device_verify_chal,
                            (idx, r_rows, s_rows, m_rows), prevalid, n,
                        ))
                    continue
            rows, prevalid, n = self.host.pack_wire(chunk)
            self._count("lanes_wire", n, 128 * n)
            packed.append(
                ("lanes_wire", self._device_verify, rows, prevalid, n)
            )
        for rep in range(repeats):
            for j, (key, launch, rows, prevalid, n) in enumerate(packed):
                if not prevalid.any():
                    pending.append((None, prevalid, n))
                    continue
                if rep == 0:
                    # Ship the packed rows to the device once; re-launches
                    # reuse the device-resident arrays (jnp.asarray is a
                    # no-op on them).
                    rows = tuple(jnp.asarray(a) for a in rows)
                    packed[j] = (key, launch, rows, prevalid, n)
                else:
                    self._count(key, n, 0)
                pending.append((launch(rows), prevalid, n))
        return PendingVerify(pending)

    def verify_signatures(self, items) -> np.ndarray:
        """items: list of (pub, digest, sig); returns bool[n]. Chunks at
        the largest bucket; all launches are enqueued before the first
        mask is materialized (one concatenated fetch — separate fetches
        each cost a full tunnel round trip)."""
        return self.verify_signatures_begin(items).mask()

    def verify_batch(self, window):
        """Verifier-protocol entry (messages with detached signatures)."""
        items = [(m.sender, m.digest(), m.signature) for m in window]
        unsigned = np.array([not m.signature for m in window], dtype=bool)
        ok = self.verify_signatures(items)
        return list(ok & ~unsigned)
