"""Batched Shamir reconstruction on device.

Reconstructs many payload blocks at once: the Lagrange weights depend only
on *which* k shares answered (host-computed once per share-set,
:func:`hyperdrive_tpu.crypto.shamir.lagrange_coeffs_at_zero`); the device
then computes ``secret_b = sum_i lambda_i * y_{i,b}`` for every block b —
k field multiplies + adds over the whole block batch, on the same
GF(2^255-19) limb kernels as signature verification (SURVEY.md 7.1(3)).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from hyperdrive_tpu.crypto import shamir as host_shamir
from hyperdrive_tpu.ops import fe25519 as fe

__all__ = ["reconstruct_kernel", "BatchReconstructor"]


@functools.lru_cache(maxsize=None)
def _jitted_reconstruct():
    """One shared jit across all BatchReconstructor instances — one XLA
    compile per (k, B) shape process-wide, not per instance."""
    return jax.jit(reconstruct_kernel)


def reconstruct_kernel(y_shares: jnp.ndarray, lams: jnp.ndarray) -> jnp.ndarray:
    """secrets[b] = sum_i lams[i] * y_shares[i, b]  (canonical form).

    Args:
      y_shares: [k, B, 20] int32 — share values per contributing share i
        and block b.
      lams:     [k, 20] int32 — Lagrange weights at zero.
    Returns: [B, 20] canonical field elements.

    One broadcast field multiply + one RAW limb sum over the share axis:
    normalized limbs are <= SLACK_MAX, so k summands stay below
    k * 9,400 < 2^31 for any k < 228,000 — no per-share normalization
    needed, and the whole reduction is one fused op instead of the k
    sequential add/mul pairs an unrolled loop costs (171 of them at
    k=2f+1, n=256; measured 166 -> ~30ms per launch)."""
    if y_shares.shape[0] * fe.SLACK_MAX >= 1 << 31:
        raise ValueError("k too large for the raw-sum reduction")
    prods = fe.mul(y_shares, lams[:, None, :])  # [k, B, 20]
    acc = jnp.sum(prods, axis=0, dtype=jnp.int32)
    return fe.canonical(acc)


class BatchReconstructor:
    """Host wrapper: packs shares, runs the jitted kernel, unpacks bytes."""

    def __init__(self):
        self._fn = _jitted_reconstruct()
        # Lagrange weights depend only on the contributor set, which is
        # stable across commits in steady state (the same 2f+1 answer
        # first); caching saves ~70ms of host modular arithmetic per
        # launch at k=171.
        self._lam_cache: dict[tuple, jnp.ndarray] = {}

    def warmup(self, k: int, blocks: int) -> None:
        """Compile the kernel for a (k, blocks) shape up front so timed
        runs never bill XLA compilation."""
        self.reconstruct_blocks(list(range(1, k + 1)), [[0] * blocks for _ in range(k)])

    def reconstruct_blocks(self, xs: list[int], y_blocks: list[list[int]]) -> list[int]:
        """xs: the k share x-coordinates; y_blocks: [k][B] share values.

        Returns the B reconstructed block secrets as ints.
        """
        key = tuple(xs)
        lams = self._lam_cache.get(key)
        if lams is None:
            lams = jnp.asarray(
                fe.to_limbs(host_shamir.lagrange_coeffs_at_zero(xs))
            )
            if len(self._lam_cache) >= 64:  # bound: churning contributor
                # sets must not pin device buffers forever (FIFO evict)
                self._lam_cache.pop(next(iter(self._lam_cache)))
            self._lam_cache[key] = lams
        y = jnp.asarray(fe.to_limbs(y_blocks))  # [k, B, 20]
        out = np.asarray(self._fn(y, lams))
        return [fe.from_limbs(row) for row in out]

    def reconstruct_payload_shares(self, per_block_shares) -> bytes:
        """per_block_shares: list over blocks of k (x, y) tuples from the
        same k contributors per block. Device-batched equivalent of
        :func:`hyperdrive_tpu.crypto.shamir.reconstruct_payload`.

        Shares are sorted by x per block, and every block must come from
        the same contributor set (one set of Lagrange weights covers the
        whole batch) — mismatched sets raise instead of corrupting.
        """
        if not per_block_shares:
            return b""
        sorted_blocks = [sorted(shares) for shares in per_block_shares]
        xs = [x for x, _ in sorted_blocks[0]]
        for i, shares in enumerate(sorted_blocks):
            if [x for x, _ in shares] != xs:
                raise ValueError(
                    f"block {i} has share x-coordinates "
                    f"{[x for x, _ in shares]} != {xs}; all blocks must "
                    "come from the same contributor set"
                )
        y_blocks = [
            [shares[i][1] for shares in sorted_blocks]
            for i in range(len(xs))
        ]
        secrets = self.reconstruct_blocks(xs, y_blocks)
        out = b"".join(
            s.to_bytes(host_shamir.BLOCK_BYTES, "little") for s in secrets
        )
        return host_shamir.unpad_payload(out)
