"""Batched Shamir reconstruction on device.

Reconstructs many payload blocks at once: the Lagrange weights depend only
on *which* k shares answered (host-computed once per share-set,
:func:`hyperdrive_tpu.crypto.shamir.lagrange_coeffs_at_zero`); the device
then computes ``secret_b = sum_i lambda_i * y_{i,b}`` for every block b —
k field multiplies + adds over the whole block batch, on the same
GF(2^255-19) limb kernels as signature verification (SURVEY.md 7.1(3)).
"""

from __future__ import annotations

import functools
import time

import numpy as np

import jax
import jax.numpy as jnp

from hyperdrive_tpu.analysis.annotations import device_fetch
from hyperdrive_tpu.crypto import shamir as host_shamir
from hyperdrive_tpu.ops import fe25519 as fe

__all__ = [
    "reconstruct_kernel",
    "BatchReconstructor",
    "AdaptiveReconstructor",
]


@functools.lru_cache(maxsize=None)
def _jitted_reconstruct():
    """One shared jit across all BatchReconstructor instances — one XLA
    compile per (k, B) shape process-wide, not per instance."""
    return jax.jit(reconstruct_kernel)


def reconstruct_kernel(y_shares: jnp.ndarray, lams: jnp.ndarray) -> jnp.ndarray:
    """secrets[b] = sum_i lams[i] * y_shares[i, b]  (canonical form).

    Args:
      y_shares: [k, B, 20] int32 — share values per contributing share i
        and block b.
      lams:     [k, 20] int32 — Lagrange weights at zero.
    Returns: [B, 20] canonical field elements.

    One broadcast field multiply + one RAW limb sum over the share axis:
    normalized limbs are <= SLACK_MAX, so k summands stay below
    k * 9,400 < 2^31 for any k < 228,000 — no per-share normalization
    needed, and the whole reduction is one fused op instead of the k
    sequential add/mul pairs an unrolled loop costs (171 of them at
    k=2f+1, n=256; measured 166 -> ~30ms per launch)."""
    if y_shares.shape[0] * fe.SLACK_MAX >= 1 << 31:
        raise ValueError("k too large for the raw-sum reduction")
    prods = fe.mul(y_shares, lams[:, None, :])  # [k, B, 20]
    acc = jnp.sum(prods, axis=0, dtype=jnp.int32)
    return fe.canonical(acc)


def _sorted_validated(per_block_shares):
    """Sort each block's shares by x and demand ONE contributor set across
    all blocks (one set of Lagrange weights covers the whole batch —
    mismatched sets raise instead of corrupting). Returns
    (sorted_blocks, xs tuple). Shared by the device and host legs so the
    validation can never diverge."""
    sorted_blocks = [sorted(shares) for shares in per_block_shares]
    xs = tuple(x for x, _ in sorted_blocks[0])
    for i, shares in enumerate(sorted_blocks):
        if tuple(x for x, _ in shares) != xs:
            raise ValueError(
                f"block {i} has share x-coordinates "
                f"{[x for x, _ in shares]} != {list(xs)}; all blocks "
                "must come from the same contributor set"
            )
    return sorted_blocks, xs


def _cache_put(cache: dict, key, value, bound: int = 64):
    """Bounded FIFO insert (churning contributor sets must not pin
    weights forever)."""
    if len(cache) >= bound:
        cache.pop(next(iter(cache)))
    cache[key] = value
    return value


class BatchReconstructor:
    """Host wrapper: packs shares, runs the jitted kernel, unpacks bytes."""

    def __init__(self):
        self._fn = _jitted_reconstruct()
        # Lagrange weights depend only on the contributor set, which is
        # stable across commits in steady state (the same 2f+1 answer
        # first); caching saves ~70ms of host modular arithmetic per
        # launch at k=171.
        self._lam_cache: dict[tuple, jnp.ndarray] = {}

    def warmup(self, k: int, blocks: int) -> None:
        """Compile the kernel for a (k, blocks) shape up front so timed
        runs never bill XLA compilation."""
        self.reconstruct_blocks(list(range(1, k + 1)), [[0] * blocks for _ in range(k)])

    def reconstruct_blocks(self, xs: list[int], y_blocks: list[list[int]]) -> list[int]:
        """xs: the k share x-coordinates; y_blocks: [k][B] share values.

        Returns the B reconstructed block secrets as ints.
        """
        key = tuple(xs)
        lams = self._lam_cache.get(key)
        if lams is None:
            lams = _cache_put(
                self._lam_cache,
                key,
                jnp.asarray(
                    fe.to_limbs(host_shamir.lagrange_coeffs_at_zero(xs))
                ),
            )
        y = jnp.asarray(fe.to_limbs(y_blocks))  # [k, B, 20]
        out = device_fetch(self._fn(y, lams),
                           why="reconstructed limbs feed host re-encoding")
        return [fe.from_limbs(row) for row in out]

    def reconstruct_payload_shares(self, per_block_shares) -> bytes:
        """per_block_shares: list over blocks of k (x, y) tuples from the
        same k contributors per block. Device-batched equivalent of
        :func:`hyperdrive_tpu.crypto.shamir.reconstruct_payload`.

        Shares are sorted by x per block, and every block must come from
        the same contributor set (one set of Lagrange weights covers the
        whole batch) — mismatched sets raise instead of corrupting.
        """
        if not per_block_shares:
            return b""
        sorted_blocks, xs = _sorted_validated(per_block_shares)
        y_blocks = [
            [shares[i][1] for shares in sorted_blocks]
            for i in range(len(xs))
        ]
        secrets = self.reconstruct_blocks(list(xs), y_blocks)
        out = b"".join(
            s.to_bytes(host_shamir.BLOCK_BYTES, "little") for s in secrets
        )
        return host_shamir.unpad_payload(out)


class AdaptiveReconstructor:
    """Routes each reconstruction to the host or the device by block
    count — :class:`hyperdrive_tpu.verifier.AdaptiveVerifier`'s
    measured-crossover insight applied to the commit path.

    A commit-sized payload (BASELINE config 5: 16 blocks, 496 bytes) is
    a few hundred host modular multiplies — microseconds — while any
    device launch pays the dispatch+transfer floor (~100 ms on a
    tunnel-attached chip). Wide batches (bulk re-reconstruction, state
    sync) belong on the device. The break-even is measured, not guessed:
    the first batch at least ``calibrate_at`` blocks wide is timed
    through BOTH paths (outputs also cross-checked), and the solved
    crossover routes everything after. Until calibration, the
    provisional ``crossover_blocks`` routes.

    Both paths implement ``reconstruct_payload_shares`` with identical
    outputs (the device path is differentially tested against the host
    oracle), so routing is a pure performance decision.
    """

    def __init__(self, device: "BatchReconstructor | None" = None,
                 crossover_blocks: int = 512, calibrate_at: int = 512):
        self.device = device if device is not None else BatchReconstructor()
        self.crossover_blocks = int(crossover_blocks)
        self.calibrate_at = int(calibrate_at)
        self.calibrated = False
        #: Self-describing calibration record once measured — keys
        #: ``host_blocks_per_s``, ``device_blocks_per_s``,
        #: ``device_overhead_s`` (single-launch time in seconds).
        self.rates = None
        # Host-side Lagrange weight cache, mirroring the device's: the
        # naive per-block reconstruct_payload recomputes the weights — k
        # modular INVERSES — for every block, which at k = 171 costs
        # ~30 ms/block and inverts the whole host-vs-device comparison
        # (measured: naive host 0.49 s vs device 0.12 s on a 16-block
        # commit; cached host ~1 ms). Weights depend only on the
        # contributor set, stable across commits in steady state.
        self._host_lams: dict[tuple, list] = {}

    def warmup(self, k: int, blocks: int) -> None:
        self.device.warmup(k, blocks)

    def host_reconstruct(self, per_block_shares) -> bytes:
        """The cached-weight host leg (public: benchmarks time it)."""
        sorted_blocks, xs = _sorted_validated(per_block_shares)
        lams = self._host_lams.get(xs)
        if lams is None:
            lams = _cache_put(
                self._host_lams,
                xs,
                host_shamir.lagrange_coeffs_at_zero(list(xs)),
            )
        p = host_shamir.P
        out = b"".join(
            (
                sum(lam * y for lam, (_, y) in zip(lams, shares)) % p
            ).to_bytes(host_shamir.BLOCK_BYTES, "little")
            for shares in sorted_blocks
        )
        return host_shamir.unpad_payload(out)

    @staticmethod
    def _median_time(fn, reps: int = 3):
        out = None
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            out = fn()
            ts.append(time.perf_counter() - t0)
        ts.sort()
        return ts[len(ts) // 2], out

    def recalibrate(self) -> None:
        self.calibrated = False

    def _calibrate(self, per_block_shares) -> bytes:
        # The single-block overhead probe must be a decodable payload on
        # its own: only the LAST block carries the 0x80 padding.
        one = per_block_shares[-1:]
        self.device.reconstruct_payload_shares(per_block_shares)  # compile
        self.device.reconstruct_payload_shares(one)
        t_dev_full, out_dev = self._median_time(
            lambda: self.device.reconstruct_payload_shares(per_block_shares)
        )
        t_dev_one, _ = self._median_time(
            lambda: self.device.reconstruct_payload_shares(one)
        )
        t_host, out_host = self._median_time(
            lambda: self.host_reconstruct(per_block_shares)
        )
        if out_dev != out_host:
            raise RuntimeError(
                "host and device reconstruction disagree during "
                "calibration — refusing to route on performance while "
                "correctness differs"
            )
        b = len(per_block_shares)
        host_rate = b / t_host if t_host > 0 else float("inf")
        dev_per_block = max(t_dev_full - t_dev_one, 0.0) / max(b - 1, 1)
        dev_rate = b / t_dev_full if t_dev_full > 0 else float("inf")
        denom = 1.0 / host_rate - dev_per_block
        self.crossover_blocks = (
            int(t_dev_one / denom) + 1 if denom > 0 else 1 << 30
        )
        self.rates = {
            "host_blocks_per_s": host_rate,
            "device_blocks_per_s": dev_rate,
            "device_overhead_s": t_dev_one,
        }
        self.calibrated = True
        return out_dev

    def reconstruct_payload_shares(self, per_block_shares) -> bytes:
        per_block_shares = list(per_block_shares)
        if not per_block_shares:
            return b""
        if (
            not self.calibrated
            and len(per_block_shares) >= self.calibrate_at
        ):
            return self._calibrate(per_block_shares)
        if len(per_block_shares) >= self.crossover_blocks:
            return self.device.reconstruct_payload_shares(per_block_shares)
        return self.host_reconstruct(per_block_shares)
