"""Pippenger multi-scalar multiplication on TPU.

Computes Q = sum_i [s_i]P_i for a whole batch of points in ONE kernel —
the reduction engine behind the RLC batch-verify fast path
(:func:`hyperdrive_tpu.ops.ed25519_jax.rlc_kernel`): instead of walking a
shared Straus ladder whose per-window tree-sum concatenates break XLA
fusion, the batch is bucketed the classic Pippenger way and every stage
is a fixed-shape batched point operation.

Shape of the algorithm (c = 4-bit signed windows, digits in [-8, 8]):

1. **Windowed decomposition** (host or caller): each scalar becomes one
   signed digit per window (:func:`~hyperdrive_tpu.ops.ed25519_jax.
   _recode_signed`); the kernel takes the [W, N] digit tensor.
2. **Bucket accumulation**: lanes are folded into G independent groups
   of g lanes; each group owns 8 buckets (|digit| = 1..8, digit 0 and
   padding fall into a write-only trash slot) and serially folds its g
   lanes in — every fold is one [G]-wide niels addition plus a one-hot
   select/blend, so all groups advance in lock step on the vector units
   and no gather/scatter ever materializes (gathers scatter badly on
   TPU; a [G, 9] one-hot contraction rides the MXU/VPU like the
   verify kernel's table selects).
3. **Group combine**: the G per-group bucket arrays reduce to one by a
   halving tree of [G/2, 8]-wide additions — log2(G) full-width levels,
   no concatenates (identity padding happens once, at layout time).
4. **Bucket-sum + window Horner**: the 8 buckets collapse with the
   suffix-sum identity sum_v v*S_v = sum_v (S_8 + ... + S_v), then the
   per-window sums fold high-to-low through the standard 4-doublings
   Horner accumulator.

Cost per lane per window is ~7 field muls (one niels add) plus the
amortized group combine (72/g muls), against the per-signature ladder's
4 doublings + 2 table adds — the op-count collapse the EdDSA batch-
verification literature banks on (PAPERS.md: "Performance of EdDSA and
BLS Signatures in Committee-Based Consensus").

Points are affine extended (z = 1, t = x*y) int32 limb tensors from the
:mod:`~hyperdrive_tpu.ops.fe25519` layout; the kernel is backend-neutral
XLA (same dialect as verify_kernel) and is exercised on CPU and TPU
alike. See /opt guides' Pallas notes for why the inner loop avoids
data-dependent addressing entirely.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from hyperdrive_tpu.ops import fe25519 as fe
from hyperdrive_tpu.ops.ed25519_jax import (
    _add_ext,
    _dbl,
    _identity_rows,
    _madd,
)

__all__ = ["msm_kernel", "plan_groups", "msm_plan"]

#: Signed 4-bit windows: |digit| <= 8, bucket values 1..8 plus the
#: write-only trash slot at index 0 (digit 0 / padding lanes land there).
N_BUCKETS = 8


def plan_groups(n: int) -> tuple[int, int]:
    """(G, g): group count and per-group serial depth for an n-lane MSM.

    G is a power of two so the combine tree halves cleanly; g ~ 64 keeps
    the per-window combine overhead (~72/g muls per lane) near 1 mul
    while G stays wide enough to fill the vector units. Small batches
    floor at G = 8 — narrower groups would serialize the whole kernel.
    """
    g_target = max(1, n // 64)
    G = 8
    while G * 2 <= min(1024, g_target):
        G *= 2
    if n < 8:
        G = 1
    g = -(-n // G)  # ceil
    return G, g


def msm_plan(n: int, windows: int) -> dict:
    """Static launch geometry for observability (`verify.msm.*` events)
    and benchmarks: window count, bucket occupancy denominator, and the
    reduction depth (combine-tree levels + bucket suffix chain)."""
    G, g = plan_groups(n)
    depth = (G - 1).bit_length() + (N_BUCKETS - 1)
    padded = G * g
    return {
        "windows": windows,
        "groups": G,
        "group_size": g,
        "buckets": N_BUCKETS,
        "reduction_depth": depth,
        # Group-layout padding economics (the devtel occupancy probes'
        # kernel-side counterpart): lanes the [G, g] fold actually
        # walks vs the n requested.
        "padded_lanes": padded,
        "lane_occupancy_pct": int(round(100 * n / max(padded, 1))),
    }


def _niels_affine(px, py, pt):
    """Affine point batch -> niels components (y+x, y-x, 2d*t)."""
    from hyperdrive_tpu.ops.ed25519_jax import _K2D_LIMBS

    k2d = jnp.asarray(_K2D_LIMBS, dtype=jnp.int32)
    return (fe.add(py, px), fe.sub(py, px), fe.mul(pt, k2d))


def _accumulate_window(digits_w, niels_r, G: int, g: int):
    """One window's bucket accumulation: fold g lanes into each of G
    groups' 9-slot bucket arrays (slot 0 = trash). ``digits_w``: [G, g]
    signed; ``niels_r``: niels components reshaped [G, g, 20]. Returns
    extended bucket components, each [G, 9, 20]."""
    yp_r, ym_r, t2_r = niels_r
    lanes9 = jnp.arange(N_BUCKETS + 1, dtype=jnp.int32)

    zero = jnp.zeros((G, N_BUCKETS + 1, fe.N_LIMBS), dtype=jnp.int32)
    one = jnp.broadcast_to(
        jnp.asarray(fe.ONE, dtype=jnp.int32),
        (G, N_BUCKETS + 1, fe.N_LIMBS),
    )
    buckets = (zero, one, one, zero)

    def lane_step(j, buckets):
        d = lax.dynamic_slice_in_dim(digits_w, j, 1, axis=1)[:, 0]  # [G]
        sign = d < 0
        oh = (lanes9[None, :] == jnp.abs(d)[:, None]).astype(jnp.int32)
        # Read: one-hot contraction picks each group's target bucket.
        cur = tuple(
            jnp.einsum("gv,gvl->gl", oh, comp) for comp in buckets
        )
        # This lane's niels entry, negated when the digit is (swap the
        # y+-x pair, negate the 2d*t component — as _select_signed).
        yp = lax.dynamic_slice_in_dim(yp_r, j, 1, axis=1)[:, 0]
        ym = lax.dynamic_slice_in_dim(ym_r, j, 1, axis=1)[:, 0]
        t2 = lax.dynamic_slice_in_dim(t2_r, j, 1, axis=1)[:, 0]
        entry = (
            fe.select(sign, ym, yp),
            fe.select(sign, yp, ym),
            fe.select(sign, fe.neg(t2), t2),
        )
        new = _madd(cur, entry, need_t=True)  # [G, 20] x4
        # Write back: blend the updated bucket into its slot only.
        mask = oh[:, :, None] == 1
        return tuple(
            jnp.where(mask, comp_new[:, None, :], comp)
            for comp, comp_new in zip(buckets, new)
        )

    return lax.fori_loop(0, g, lane_step, buckets)


def _combine_groups(buckets, G: int):
    """Halving tree over the group axis: [G, 9, 20] components -> [8, 20]
    (the trash slot is dropped before the first level)."""
    comps = tuple(comp[:, 1:] for comp in buckets)  # [G, 8, 20]
    m = G
    while m > 1:
        h = m // 2
        comps = _add_ext(
            tuple(c[:h] for c in comps),
            tuple(c[h:m] for c in comps),
            need_t=True,
        )
        m = h
    return tuple(c[0] for c in comps)  # [8, 20] x4


def _bucket_reduce(buckets8):
    """sum_v v*S_v via suffix sums: runtot = S_8 + ... + S_v accumulates
    into the window sum with 2*(buckets-1) width-1 additions."""
    def slot(v):
        return tuple(c[v - 1 : v] for c in buckets8)  # [1, 20] x4

    runtot = slot(N_BUCKETS)
    wsum = runtot
    for v in range(N_BUCKETS - 1, 0, -1):
        runtot = _add_ext(runtot, slot(v), need_t=True)
        wsum = _add_ext(wsum, runtot, need_t=True)
    return wsum


def msm_kernel(px, py, pt, digits):
    """sum_i [s_i]P_i over affine extended points, scalars pre-decomposed
    to signed 4-bit windows.

    Args (all int32):
      px, py, pt: [N, 20] affine extended coords (z = 1, t = x*y mod p)
      digits:     [W, N] signed window digits in [-8, 8], window 0 least
                  significant (the caller recodes nibbles; see
                  ``_recode_signed``)
    Returns: the sum as an extended projective point, [1, 20] x4.

    Padding lanes are free: a zero digit routes its (arbitrary) point to
    the trash bucket, so callers pad with anything shape-compatible.
    """
    n = px.shape[0]
    windows = digits.shape[0]
    G, g = plan_groups(n)
    pad = G * g - n

    niels = _niels_affine(px, py, pt)
    if pad:
        zrow = jnp.zeros((pad, fe.N_LIMBS), dtype=jnp.int32)
        niels = tuple(jnp.concatenate([c, zrow]) for c in niels)
        digits = jnp.concatenate(
            [digits, jnp.zeros((windows, pad), dtype=digits.dtype)], axis=1
        )
    niels_r = tuple(c.reshape(G, g, fe.N_LIMBS) for c in niels)
    digits_r = digits.reshape(windows, G, g)

    def window_body(i, acc):
        w = windows - 1 - i
        # Horner shift: one 4-bit window = four doublings (T on the last).
        acc3 = acc[:3]
        for _ in range(3):
            acc3 = _dbl(acc3, need_t=False)
        acc = _dbl(acc3, need_t=True)
        dw = lax.dynamic_slice_in_dim(digits_r, w, 1, axis=0)[0]  # [G, g]
        buckets = _accumulate_window(dw, niels_r, G, g)
        wsum = _bucket_reduce(_combine_groups(buckets, G))
        return _add_ext(acc, wsum, need_t=True)

    return lax.fori_loop(0, windows, window_body, _identity_rows(1))
