"""Pippenger multi-scalar multiplication on TPU.

Computes Q = sum_i [s_i]P_i for a whole batch of points in ONE kernel —
the reduction engine behind the RLC batch-verify fast path
(:func:`hyperdrive_tpu.ops.ed25519_jax.rlc_kernel`) and the BLS
aggregate path (:mod:`hyperdrive_tpu.ops.g1`): instead of walking a
shared Straus ladder whose per-window tree-sum concatenates break XLA
fusion, the batch is bucketed the classic Pippenger way and every stage
is a fixed-shape batched point operation.

Shape of the algorithm (c = 4-bit signed windows, digits in [-8, 8]):

1. **Windowed decomposition** (host or caller): each scalar becomes one
   signed digit per window (:func:`~hyperdrive_tpu.ops.ed25519_jax.
   _recode_signed`); the kernel takes the [W, N] digit tensor.
2. **Bucket accumulation**: lanes are folded into G independent groups
   of g lanes; each group owns 8 buckets (|digit| = 1..8, digit 0 and
   padding fall into a write-only trash slot) and serially folds its g
   lanes in — every fold is one [G]-wide point addition plus a one-hot
   select/blend, so all groups advance in lock step on the vector units
   and no gather/scatter ever materializes (gathers scatter badly on
   TPU; a [G, 9] one-hot contraction rides the MXU/VPU like the
   verify kernel's table selects).
3. **Group combine**: the G per-group bucket arrays reduce to one by a
   halving tree of [G/2, 8]-wide additions — log2(G) full-width levels,
   no concatenates (identity padding happens once, at layout time).
4. **Bucket-sum + window Horner**: the 8 buckets collapse with the
   suffix-sum identity sum_v v*S_v = sum_v (S_8 + ... + S_v), then the
   per-window sums fold high-to-low through the standard 4-doublings
   Horner accumulator.

The planner and engine are **curve-parameterized**: all bucket/group/
window geometry lives here, while the point representation and its
add/double/select arithmetic arrive as a :class:`CurveOps` bundle. Two
instantiations exist — ed25519 (niels entries over extended accumulators
on the :mod:`.fe25519` layout; built here, used by the RLC kernel) and
BLS12-381 G1 (complete projective points over :mod:`.fp381`; built in
:mod:`.g1`). Window counts are derived with :func:`windows_for_bits`
instead of the historic hardcoded 64/33 split.

Cost per lane per window is ~7 field muls (one mixed add) plus the
amortized group combine (72/g muls), against the per-signature ladder's
4 doublings + 2 table adds — the op-count collapse the EdDSA batch-
verification literature banks on (PAPERS.md: "Performance of EdDSA and
BLS Signatures in Committee-Based Consensus").

The kernel is backend-neutral XLA (same dialect as verify_kernel) and is
exercised on CPU and TPU alike. See /opt guides' Pallas notes for why
the inner loop avoids data-dependent addressing entirely.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

import jax.numpy as jnp
from jax import lax

from hyperdrive_tpu.ops import fe25519 as fe

__all__ = [
    "msm_kernel",
    "msm_engine",
    "plan_groups",
    "msm_plan",
    "windows_for_bits",
    "CurveOps",
    "WINDOW_BITS",
    "ED25519_FULL_WINDOWS",
    "ED25519_HALF_WINDOWS",
]

#: Signed window width in bits; digits live in [-8, 8].
WINDOW_BITS = 4

#: Signed 4-bit windows: |digit| <= 8, bucket values 1..8 plus the
#: write-only trash slot at index 0 (digit 0 / padding lanes land there).
N_BUCKETS = 1 << (WINDOW_BITS - 1)


def windows_for_bits(bits: int, window_bits: int = WINDOW_BITS) -> int:
    """Window count covering a ``bits``-wide scalar with signed digits.

    Signed recoding needs the top digit's carry headroom, so callers
    quote the scalar bound's bit width (e.g. 253 for clamped ed25519
    scalars, 255 for the BLS12-381 group order, 129 for half-width RLC
    coefficients including their carry bit)."""
    return -(-bits // window_bits)


#: The ed25519 RLC geometry, formerly hardcoded as 64/33: full-width
#: scalars are < 2^253 (recode precondition), half-width Fiat-Shamir
#: coefficients are < 2^128 plus one carry bit.
ED25519_FULL_WINDOWS = windows_for_bits(253)  # 64
ED25519_HALF_WINDOWS = windows_for_bits(129)  # 33


def plan_groups(n: int) -> tuple[int, int]:
    """(G, g): group count and per-group serial depth for an n-lane MSM.

    G is a power of two so the combine tree halves cleanly; g ~ 64 keeps
    the per-window combine overhead (~72/g muls per lane) near 1 mul
    while G stays wide enough to fill the vector units. Small batches
    floor at G = 8 — narrower groups would serialize the whole kernel.
    """
    g_target = max(1, n // 64)
    G = 8
    while G * 2 <= min(1024, g_target):
        G *= 2
    if n < 8:
        G = 1
    g = -(-n // G)  # ceil
    return G, g


def msm_plan(n: int, windows: int, curve: str = "ed25519") -> dict:
    """Static launch geometry for observability (`verify.msm.*` /
    `bls.aggregate.*` events) and benchmarks: window count, bucket
    occupancy denominator, and the reduction depth (combine-tree levels
    + bucket suffix chain)."""
    G, g = plan_groups(n)
    depth = (G - 1).bit_length() + (N_BUCKETS - 1)
    padded = G * g
    return {
        "curve": curve,
        "windows": windows,
        "groups": G,
        "group_size": g,
        "buckets": N_BUCKETS,
        "reduction_depth": depth,
        # Group-layout padding economics (the devtel occupancy probes'
        # kernel-side counterpart): lanes the [G, g] fold actually
        # walks vs the n requested.
        "padded_lanes": padded,
        "lane_occupancy_pct": int(round(100 * n / max(padded, 1))),
    }


# ------------------------------------------------------------- curve bundle


@dataclass(frozen=True)
class CurveOps:
    """The arithmetic a curve plugs into the Pippenger engine.

    Accumulators and entries are tuples of [..., n_limbs] int32 arrays;
    the engine never inspects their arity, so mixed representations
    (ed25519: niels entries into extended accumulators) cost nothing.

    Attributes:
      n_limbs:        limbs per field element (20 for fe25519, 30 for
                      fp381)
      acc_identity:   batch-prefix -> identity accumulator tuple
      bucket_identity: G -> [G, N_BUCKETS+1, L] identity bucket tuple
      entry_select:   (sign_mask, entry_tuple) -> entry or its negation
      add_entry:      (acc_tuple, entry_tuple) -> acc_tuple  (mixed add)
      add:            (acc_tuple, acc_tuple) -> acc_tuple    (full add)
      window_shift:   acc_tuple -> acc_tuple  (WINDOW_BITS doublings)
    """

    n_limbs: int
    acc_identity: Callable
    bucket_identity: Callable
    entry_select: Callable
    add_entry: Callable
    add: Callable
    window_shift: Callable


def _ed25519_ops() -> CurveOps:
    from hyperdrive_tpu.ops.ed25519_jax import (
        _add_ext,
        _dbl,
        _identity_rows,
        _madd,
    )

    def bucket_identity(G: int):
        zero = jnp.zeros((G, N_BUCKETS + 1, fe.N_LIMBS), dtype=jnp.int32)
        one = jnp.broadcast_to(
            jnp.asarray(fe.ONE, dtype=jnp.int32),
            (G, N_BUCKETS + 1, fe.N_LIMBS),
        )
        return (zero, one, one, zero)

    def entry_select(sign, entry):
        # Negate a niels point: swap the (y+x, y-x) pair, negate 2d*t.
        yp, ym, t2 = entry
        return (
            fe.select(sign, ym, yp),
            fe.select(sign, yp, ym),
            fe.select(sign, fe.neg(t2), t2),
        )

    def window_shift(acc):
        acc3 = acc[:3]
        for _ in range(3):
            acc3 = _dbl(acc3, need_t=False)
        return _dbl(acc3, need_t=True)

    return CurveOps(
        n_limbs=fe.N_LIMBS,
        acc_identity=_identity_rows,
        bucket_identity=bucket_identity,
        entry_select=entry_select,
        add_entry=lambda acc, entry: _madd(acc, entry, need_t=True),
        add=lambda a, b: _add_ext(a, b, need_t=True),
        window_shift=window_shift,
    )


_ED25519_OPS = None


def ed25519_curve_ops() -> CurveOps:
    global _ED25519_OPS
    if _ED25519_OPS is None:
        _ED25519_OPS = _ed25519_ops()
    return _ED25519_OPS


def _niels_affine(px, py, pt):
    """Affine point batch -> niels components (y+x, y-x, 2d*t)."""
    from hyperdrive_tpu.ops.ed25519_jax import _K2D_LIMBS

    k2d = jnp.asarray(_K2D_LIMBS, dtype=jnp.int32)
    return (fe.add(py, px), fe.sub(py, px), fe.mul(pt, k2d))


# ------------------------------------------------------------------ engine


def _accumulate_window(digits_w, entries_r, G: int, g: int, ops: CurveOps):
    """One window's bucket accumulation: fold g lanes into each of G
    groups' 9-slot bucket arrays (slot 0 = trash). ``digits_w``: [G, g]
    signed; ``entries_r``: entry components reshaped [G, g, L]. Returns
    accumulator-representation buckets, each component [G, 9, L]."""
    lanes9 = jnp.arange(N_BUCKETS + 1, dtype=jnp.int32)
    buckets = ops.bucket_identity(G)

    def lane_step(j, buckets):
        d = lax.dynamic_slice_in_dim(digits_w, j, 1, axis=1)[:, 0]  # [G]
        sign = d < 0
        oh = (lanes9[None, :] == jnp.abs(d)[:, None]).astype(jnp.int32)
        # Read: one-hot contraction picks each group's target bucket.
        cur = tuple(
            jnp.einsum("gv,gvl->gl", oh, comp) for comp in buckets
        )
        entry = ops.entry_select(
            sign,
            tuple(
                lax.dynamic_slice_in_dim(c, j, 1, axis=1)[:, 0]
                for c in entries_r
            ),
        )
        new = ops.add_entry(cur, entry)  # [G, L] per component
        # Write back: blend the updated bucket into its slot only.
        mask = oh[:, :, None] == 1
        return tuple(
            jnp.where(mask, comp_new[:, None, :], comp)
            for comp, comp_new in zip(buckets, new)
        )

    return lax.fori_loop(0, g, lane_step, buckets)


def _combine_groups(buckets, G: int, ops: CurveOps):
    """Halving tree over the group axis: [G, 9, L] components -> [8, L]
    (the trash slot is dropped before the first level)."""
    comps = tuple(comp[:, 1:] for comp in buckets)  # [G, 8, L]
    m = G
    while m > 1:
        h = m // 2
        comps = ops.add(
            tuple(c[:h] for c in comps),
            tuple(c[h:m] for c in comps),
        )
        m = h
    return tuple(c[0] for c in comps)  # [8, L] per component


def _bucket_reduce(buckets8, ops: CurveOps):
    """sum_v v*S_v via suffix sums: runtot = S_8 + ... + S_v accumulates
    into the window sum with 2*(buckets-1) width-1 additions."""

    def slot(v):
        return tuple(c[v - 1 : v] for c in buckets8)  # [1, L] each

    runtot = slot(N_BUCKETS)
    wsum = runtot
    for v in range(N_BUCKETS - 1, 0, -1):
        runtot = ops.add(runtot, slot(v))
        wsum = ops.add(wsum, runtot)
    return wsum


def msm_engine(entries, digits, ops: CurveOps):
    """sum_i [s_i]P_i for any curve: the geometry/bucketing engine.

    Args:
      entries: tuple of [N, L] int32 entry components (curve-specific
               representation; see :class:`CurveOps`)
      digits:  [W, N] signed window digits in [-WINDOW_BITS^2/2 ..], via
               the caller's recoder; window 0 least significant
      ops:     the curve's arithmetic bundle
    Returns: the sum in the curve's accumulator representation, batch 1.

    Padding lanes are free: a zero digit routes its (arbitrary) point to
    the trash bucket, so callers pad with anything shape-compatible.
    """
    n = entries[0].shape[0]
    windows = digits.shape[0]
    G, g = plan_groups(n)
    pad = G * g - n

    if pad:
        zrow = jnp.zeros((pad, ops.n_limbs), dtype=jnp.int32)
        entries = tuple(jnp.concatenate([c, zrow]) for c in entries)
        digits = jnp.concatenate(
            [digits, jnp.zeros((windows, pad), dtype=digits.dtype)], axis=1
        )
    entries_r = tuple(c.reshape(G, g, ops.n_limbs) for c in entries)
    digits_r = digits.reshape(windows, G, g)

    def window_body(i, acc):
        w = windows - 1 - i
        # Horner shift: one window = WINDOW_BITS doublings.
        acc = ops.window_shift(acc)
        dw = lax.dynamic_slice_in_dim(digits_r, w, 1, axis=0)[0]  # [G, g]
        buckets = _accumulate_window(dw, entries_r, G, g, ops)
        wsum = _bucket_reduce(_combine_groups(buckets, G, ops), ops)
        return ops.add(acc, wsum)

    return lax.fori_loop(0, windows, window_body, ops.acc_identity(1))


def msm_kernel(px, py, pt, digits):
    """sum_i [s_i]P_i over affine extended ed25519 points, scalars
    pre-decomposed to signed 4-bit windows.

    Args (all int32):
      px, py, pt: [N, 20] affine extended coords (z = 1, t = x*y mod p)
      digits:     [W, N] signed window digits in [-8, 8], window 0 least
                  significant (the caller recodes nibbles; see
                  ``_recode_signed``)
    Returns: the sum as an extended projective point, [1, 20] x4.
    """
    return msm_engine(_niels_affine(px, py, pt), digits, ed25519_curve_ops())
